"""Training callbacks (parity: python/mxnet/callback.py): Speedometer,
do_checkpoint, LogValidationMetricsCallback, ProgressBar — the classic
Module.fit hooks."""
from __future__ import annotations

import logging
import sys
import time

__all__ = ["Speedometer", "do_checkpoint", "module_checkpoint",
           "log_train_metric", "ProgressBar", "LogValidationMetricsCallback"]


class Speedometer:
    """Logs throughput (samples/sec) and metrics every `frequent` batches
    (parity: mx.callback.Speedometer)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0.0
        self.last_count = 0

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if not self.init:
            self.init = True
            self.tic = time.time()
            return
        if count % self.frequent != 0:
            return
        speed = self.frequent * self.batch_size / (time.time() - self.tic)
        if param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            if self.auto_reset:
                param.eval_metric.reset()
            msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s"
            metrics = "\t".join(f"{n}={v:.6f}" for n, v in name_value)
            logging.info(msg, param.epoch, count, speed, metrics)
        else:
            logging.info("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                         param.epoch, count, speed)
        self.tic = time.time()


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving `prefix-NNNN.params` + symbol json every
    `period` epochs (parity: mx.callback.do_checkpoint)."""
    from .module import save_checkpoint
    period = max(1, int(period))

    def _callback(epoch, sym, arg_params, aux_params):
        if (epoch + 1) % period == 0:
            save_checkpoint(prefix, epoch + 1, sym, arg_params, aux_params)

    return _callback


module_checkpoint = do_checkpoint


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the metric every `period` batches."""

    def _callback(param):
        if param.nbatch % max(1, period) == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            metrics = "\t".join(f"{n}={v:.6f}" for n, v in name_value)
            logging.info("Iter[%d] Batch[%d] Train-%s",
                         param.epoch, param.nbatch, metrics)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class ProgressBar:
    """Text progress bar over batches (parity: mx.callback.ProgressBar)."""

    def __init__(self, total, length=80):
        self.total = max(1, total)
        self.bar_len = length

    def __call__(self, param):
        count = param.nbatch
        filled = int(round(self.bar_len * count / float(self.total)))
        pct = round(100.0 * count / float(self.total), 1)
        bar = "=" * filled + "-" * (self.bar_len - filled)
        sys.stdout.write(f"[{bar}] {pct}%\r")
        sys.stdout.flush()


class LogValidationMetricsCallback:
    """Epoch-end eval callback logging each validation metric."""

    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                         value)
