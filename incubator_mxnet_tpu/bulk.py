"""Bulk-fused eager dispatch: deferred op segments compiled to one XLA call.

Parity target: the reference engine's bulk mode (`Engine::StartBulk` /
`MXEngineSetBulkSize`), which batches `size` consecutive async ops into one
scheduling unit to amortize per-op engine overhead. Rebuilt TPU-native in
the LazyTensor lineage (PyTorch/XLA): inside an `engine.bulk(size)` scope
(or the opt-in auto-bulk mode) every eager NDArray dispatch appends to a
deferred *segment* instead of launching its own XLA computation. The
segment is flushed — replayed as a single `jax.jit`-compiled executable —
when

* it reaches `size` ops                                  (reason ``size``),
* the scope exits                                        (reason ``exit``),
* a value is read: ``asnumpy``/``wait_to_read``/``item``/
  control flow on a deferred array                       (reason ``read``),
* ``autograd.backward``/``grad`` starts a tape walk      (reason ``backward``),
* ``Trainer.step`` begins an optimizer update            (reason ``step``).

Compiled segments are cached by an *op/shape signature* so steady-state
loops hit the compile cache: per op the signature is either the function
object itself (module-level kernels like ``jnp.add``) or, for the closure
lambdas the op layer builds around Python scalars/axes, the pair
``(code object, closure values)`` — two segments share an executable only
when every op's code AND captured constants match, which makes the cache
sound (an `x + 2` segment can never answer for `x + 3`). Ops whose
closures capture unhashable values mark the segment uncacheable; it still
runs fused, it just recompiles (counted as a miss).

Profiler counters (always-live registry, see profiler.counters):
``mxtpu/bulk.segments``, ``mxtpu/bulk.ops``, ``mxtpu/bulk.segment_size``
(gauge, last flush), ``mxtpu/bulk.flush.<reason>``, and
``bulk/jit.cache_hit`` / ``bulk/jit.cache_miss`` for the segment compile
cache.

This module must not import `ndarray` (ndarray imports it); the NDArray
wrapper factory is injected via `_WRAP` at ndarray import time.
"""
from __future__ import annotations

import os
import threading

import jax
import numpy as np

from . import profiler as _prof
from .diagnostics import flight as _flight

__all__ = ["DeferredArray", "defer", "flush", "materialize", "is_deferred",
           "push_scope", "pop_scope", "set_auto_bulk", "auto_bulk_size",
           "pending_ops"]

# fast-path flag checked by ndarray._apply: True iff ANY thread has an open
# bulk scope or auto-bulk is enabled. Per-thread truth lives in _tls.
_ON = False
_AUTO_SIZE = 0
_scope_count = 0
_lock = threading.Lock()
_tls = threading.local()

# installed by ndarray/__init__: raw-like -> NDArray (bypasses coercion)
_WRAP = None

# segment signature -> jitted replay fn. Bounded: cleared wholesale when it
# outgrows _CACHE_MAX (steady-state loops use a handful of signatures).
_COMPILE_CACHE: dict = {}
_CACHE_MAX = 1024


def _recompute_on():
    global _ON
    _ON = _scope_count > 0 or _AUTO_SIZE > 0


def _st():
    if not hasattr(_tls, "stack"):
        _tls.stack = []      # open bulk-scope sizes, innermost last
        _tls.seg = None      # current open segment
    return _tls


def _active_size() -> int:
    st = _st()
    if st.stack:
        return st.stack[-1]
    return _AUTO_SIZE


# ---------------------------------------------------------------------------
# scopes / auto-bulk
# ---------------------------------------------------------------------------

def push_scope(size: int):
    """Enter a bulk scope (engine.bulk.__enter__)."""
    global _scope_count
    st = _st()
    st.stack.append(max(1, int(size)))
    with _lock:
        _scope_count += 1
        _recompute_on()


def pop_scope():
    """Leave a bulk scope: flush the pending segment (imperative semantics
    — values escaping the scope are concrete)."""
    global _scope_count
    st = _st()
    flush("exit")
    if st.stack:
        st.stack.pop()
    with _lock:
        _scope_count = max(0, _scope_count - 1)
        _recompute_on()


def set_auto_bulk(size: int) -> int:
    """Opt-in ambient bulking: every eager dispatch on every thread defers
    into segments of up to `size` ops without an explicit scope (parity:
    MXEngineSetBulkSize). `size<=0` disables and flushes the CALLING
    thread's pending segment; other threads' pending segments flush at
    their next read/backward/waitall/step barrier (those flush points run
    unconditionally). Returns the previous size. Env default:
    MXTPU_AUTO_BULK."""
    global _AUTO_SIZE
    prev = _AUTO_SIZE
    _AUTO_SIZE = max(0, int(size))
    with _lock:
        _recompute_on()
    if _AUTO_SIZE == 0:
        flush("exit")
    return prev


def auto_bulk_size() -> int:
    return _AUTO_SIZE


def pending_ops() -> int:
    """Ops queued in the calling thread's open segment (tests/debug)."""
    st = _st()
    return 0 if st.seg is None or st.seg.done else len(st.seg.ops)


# ---------------------------------------------------------------------------
# deferred values
# ---------------------------------------------------------------------------

class DeferredArray:
    """Placeholder for one output of a deferred op. Duck-types the shape/
    dtype surface of jax.Array; ANY other attribute access materializes the
    owning segment first (that is the flush-on-read contract)."""

    __slots__ = ("_seg", "_slot", "_aval", "_concrete", "__weakref__")

    def __init__(self, seg, slot, aval):
        self._seg = seg
        self._slot = slot
        self._aval = aval
        self._concrete = None

    @property
    def shape(self):
        return tuple(self._aval.shape)

    @property
    def dtype(self):
        return self._aval.dtype

    @property
    def ndim(self):
        return len(self._aval.shape)

    @property
    def size(self):
        return int(np.prod(self._aval.shape)) if self._aval.shape else 1

    def _force(self):
        if self._concrete is None:
            _flush_segment(self._seg, "read")
        return self._concrete

    def __getattr__(self, name):
        # only reached for names not defined above — a concrete-array API
        # access (block_until_ready, reshape, astype, devices, ...)
        return getattr(self._force(), name)

    def __array__(self, dtype=None):
        a = np.asarray(self._force())
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._force()

    def __repr__(self):
        state = "pending" if self._concrete is None else "done"
        return (f"<DeferredArray {self.shape} {self.dtype} {state}>")

    # arithmetic straight on the raw wrapper (grad accumulation et al.)
    # materializes and delegates
    def __add__(self, o): return self._force() + o
    def __radd__(self, o): return o + self._force()
    def __sub__(self, o): return self._force() - o
    def __rsub__(self, o): return o - self._force()
    def __mul__(self, o): return self._force() * o
    def __rmul__(self, o): return o * self._force()
    def __truediv__(self, o): return self._force() / o
    def __rtruediv__(self, o): return o / self._force()
    def __neg__(self): return -self._force()
    def __getitem__(self, k): return self._force()[k]


def is_deferred(x) -> bool:
    return type(x) is DeferredArray


def materialize_one(x):
    """Concrete value of a possibly-deferred raw."""
    if type(x) is DeferredArray:
        return x._force()
    return x


def materialize(raws):
    return [materialize_one(r) for r in raws]


# ---------------------------------------------------------------------------
# segments
# ---------------------------------------------------------------------------

class _Segment:
    __slots__ = ("max_size", "ops", "sig_parts", "consts", "_const_idx",
                 "deferred", "targets", "cacheable", "done")

    def __init__(self, max_size):
        self.max_size = max_size
        self.ops = []          # (fn, in_refs, n_out, out_slots)
        self.sig_parts = []    # per-op signature parts (while cacheable)
        self.consts = []       # concrete segment inputs, deduped by id
        self._const_idx = {}   # id(raw) -> index into consts
        self.deferred = []     # slot -> DeferredArray
        self.targets = []      # (DeferredArray, NDArray) write-back pairs
        self.cacheable = True
        self.done = False

    def _const(self, raw):
        i = self._const_idx.get(id(raw))
        if i is None:
            i = len(self.consts)
            self.consts.append(raw)
            self._const_idx[id(raw)] = i
        return i


def _val_key(v):
    """Hashable identity of a closure-captured value, or None (unhashable
    → the op poisons its segment's cache eligibility). Scalars key with
    their type so `2` and `2.0` (equal, same hash) never collide — jnp
    promotion treats them differently."""
    if callable(v):
        return _fn_key(v)
    if isinstance(v, dict):
        items = []
        for k in sorted(v, key=repr):
            kk = _val_key(v[k])
            if kk is None:
                return None
            items.append((k, kk))
        return ("d",) + tuple(items)
    if isinstance(v, (list, tuple)):
        out = []
        for item in v:
            ik = _val_key(item)
            if ik is None:
                return None
            out.append(ik)
        return ("t",) + tuple(out)
    try:
        hash(v)
    except TypeError:
        return None
    return (type(v).__name__, v)


def _fn_key(fn):
    """Signature of an op function: the function object itself when it has
    no closure (module-level kernels), else (code, closure values) — the
    op layer recreates identical lambdas every loop iteration, and this
    keys them by semantics instead of identity."""
    try:
        hash(fn)
    except TypeError:
        return None
    closure = getattr(fn, "__closure__", None)
    defaults = getattr(fn, "__defaults__", None)
    if not closure and not defaults:
        return fn
    vals = []
    for cell in closure or ():
        k = _val_key(cell.cell_contents)
        if k is None:
            return None
        vals.append(k)
    dk = _val_key(tuple(defaults)) if defaults else None
    code = getattr(fn, "__code__", None)
    if code is None:
        return None
    return (code, tuple(vals), dk)


def defer(fn, raws, n_out, name):
    """Append one op to the calling thread's segment. Returns the output
    NDArrays (already wrapped + registered for write-back), or None when
    bulking does not apply (no active scope, tracer inputs, profiler op
    hook installed by the caller, abstract eval failure)."""
    size = _active_size()
    if size <= 0 or _WRAP is None:
        return None
    for r in raws:
        if isinstance(r, jax.core.Tracer):
            return None          # inside a jit trace: no dispatch to save
    st = _st()
    seg = st.seg
    if seg is None or seg.done:
        seg = st.seg = _Segment(size)
    else:
        seg.max_size = size      # innermost scope's size wins

    in_refs = []
    aval_args = []
    for r in raws:
        if type(r) is DeferredArray:
            if r._seg is seg and r._concrete is None:
                in_refs.append(("s", r._slot))
                aval_args.append(r._aval)
                continue
            r = r._force()       # cross-segment / already-flushed input
        in_refs.append(("c", seg._const(r)))
        aval_args.append(r)
    try:
        out_aval = jax.eval_shape(fn, *aval_args)
    except Exception:
        if not seg.ops:
            st.seg = None
        return None              # data-dependent op: caller runs it eagerly
    out_avals = (out_aval,) if n_out == 1 else tuple(out_aval)
    if len(out_avals) != n_out:
        return None

    fk = _fn_key(fn) if seg.cacheable else None
    if fk is None:
        seg.cacheable = False
        seg.sig_parts = None
    else:
        seg.sig_parts.append((fk, tuple(in_refs), n_out))

    out_nds = []
    out_slots = []
    for av in out_avals:
        slot = len(seg.deferred)
        d = DeferredArray(seg, slot, av)
        seg.deferred.append(d)
        out_slots.append(slot)
        ndarr = _WRAP(d)
        seg.targets.append((d, ndarr))
        out_nds.append(ndarr)
    seg.ops.append((fn, tuple(in_refs), n_out, tuple(out_slots)))

    if len(seg.ops) >= seg.max_size:
        _flush_segment(seg, "size")
        if st.seg is seg:
            st.seg = None
    return out_nds


def _build_seg_fn(ops, n_slots):
    def seg_fn(consts):
        env = [None] * n_slots
        for fn, in_refs, n_out, out_slots in ops:
            args = [consts[i] if kind == "c" else env[i]
                    for kind, i in in_refs]
            o = fn(*args)
            o = (o,) if n_out == 1 else tuple(o)
            for s, v in zip(out_slots, o):
                env[s] = v
        return env
    return seg_fn


def _flush_segment(seg, reason):
    if seg.done:
        return
    seg.done = True
    n = len(seg.ops)
    if n == 0:
        return
    sig = None
    jitted = None
    if seg.cacheable:
        sig = (tuple(seg.sig_parts),
               tuple((tuple(np.shape(c)), str(getattr(c, "dtype", type(c))))
                     for c in seg.consts))
        jitted = _COMPILE_CACHE.get(sig)
    if jitted is None:
        _prof.counter("jit.cache_miss", "bulk").increment()
        jitted = jax.jit(_build_seg_fn(seg.ops, len(seg.deferred)))
        if sig is not None:
            if len(_COMPILE_CACHE) >= _CACHE_MAX:
                _COMPILE_CACHE.clear()
            _COMPILE_CACHE[sig] = jitted
    else:
        _prof.counter("jit.cache_hit", "bulk").increment()
    outs = jitted(list(seg.consts))
    for d, o in zip(seg.deferred, outs):
        d._concrete = o
        d._seg = None     # aliased wrappers (__setitem__/detach) may hold
                          # the DeferredArray long-term: drop the segment
                          # ref so it can't pin consts/ops/targets
    for d, ndarr in seg.targets:
        if ndarr._data is d:
            ndarr._data = d._concrete
    seg.ops = seg.sig_parts = seg.consts = None
    seg.targets = seg.deferred = None
    seg._const_idx = None
    _prof.counter("bulk.segments").increment()
    _prof.counter("bulk.ops").increment(n)
    _prof.set_gauge("bulk.segment_size", n)
    _prof.counter("bulk.flush.%s" % reason).increment()
    if _prof._ACTIVE:
        _prof._instant("bulk.flush(%s)" % reason, "engine",
                       args={"ops": n, "reason": reason})
    if _flight._REC is not None:
        _flight.record("engine", "bulk.flush",
                       {"ops": n, "reason": reason})


def flush(reason="read"):
    """Flush the calling thread's pending segment, if any."""
    st = _st()
    seg = st.seg
    if seg is not None:
        st.seg = None
        _flush_segment(seg, reason)


_env_auto = os.environ.get("MXTPU_AUTO_BULK")
if _env_auto:
    try:
        set_auto_bulk(int(_env_auto))
    except ValueError:
        pass
