#!/usr/bin/env python
"""Headline benchmark: ResNet-50 ImageNet-shape training throughput on one
TPU chip (BASELINE.json: images/sec/chip vs MXNet-on-V100 reference).

Prints exactly one JSON line:
  {"metric": "...", "value": N, "unit": "images/sec", "vs_baseline": N}

Baseline: published MXNet ResNet-50 fp32 V100 throughput ~390 img/s
(BASELINE.json north star: target >=70% of that on one v5e chip).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

# Persistent compilation cache: the axon remote-compile path is slow; cache
# makes repeat bench runs start fast.
try:
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:
    pass

import numpy as np  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon, nd  # noqa: E402
from incubator_mxnet_tpu.models import get_model  # noqa: E402
from incubator_mxnet_tpu.parallel import FusedTrainStep  # noqa: E402

V100_BASELINE_IMG_S = 390.0  # MXNet ResNet-50 fp32, single V100 (published)

# updated once the model is resolved; all error paths report through this
_CURRENT_METRIC = "resnet50_imagenet_images_per_sec_per_chip"


class _PhaseTimeout(Exception):
    pass


def _arm_hard_watchdog(seconds, what="bench"):
    """SIGALRM can't interrupt a hang INSIDE a blocking C call (Python only
    runs signal handlers between bytecodes), and backend-init hangs live in
    C. A daemon thread with os._exit is the hard deadline: it emits the
    parseable error JSON line first so the driver records a diagnosis
    instead of rc=124 with empty output."""
    import threading

    def fire():
        print(json.dumps({
            "metric": _CURRENT_METRIC,
            "value": 0.0,
            "unit": "images/sec",
            "vs_baseline": 0.0,
            "error": f"hard watchdog: {what} exceeded {seconds}s (hang "
                     "inside a C call; SIGALRM deadlines could not fire)",
        }), flush=True)
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


class _phase_deadline:
    """SIGALRM watchdog: the axon tunnel can HANG (not error) on init, and
    a silent hang eats the driver's whole bench budget with no JSON line.
    Convert hangs into exceptions the retry/error paths can handle."""

    def __init__(self, seconds, what):
        self.seconds = int(seconds)
        self.what = what

    def __enter__(self):
        import signal

        def handler(signum, frame):
            raise _PhaseTimeout(f"{self.what} exceeded {self.seconds}s")

        self._old = signal.signal(signal.SIGALRM, handler)
        signal.alarm(self.seconds)
        return self

    def __exit__(self, *exc):
        import signal
        signal.alarm(0)
        signal.signal(signal.SIGALRM, self._old)
        return False


def _log(msg):
    print(f"bench[{time.strftime('%H:%M:%S')}]: {msg}", file=sys.stderr,
          flush=True)


def acquire_backend(attempts=6, first_delay=3.0,
                    per_attempt_timeout=180):
    """Backend init through the axon relay is occasionally UNAVAILABLE or
    simply unresponsive (transient tunnel/contention); retry with backoff —
    and a per-attempt watchdog — before giving up, so one flake doesn't
    forfeit the round's perf number."""
    delay = first_delay
    last = None
    for i in range(attempts):
        try:
            with _phase_deadline(per_attempt_timeout, "backend init"):
                _log(f"backend attempt {i + 1}/{attempts}")
                devs = jax.devices()
                # force a real device computation with a HOST FETCH:
                # through the axon relay block_until_ready() returns at
                # enqueue, so only a value fetch proves the chip answers
                # (a wedged tunnel would otherwise pass this probe and
                # then burn the whole compile watchdog)
                import jax.numpy as jnp
                probe = float(jnp.ones((8, 8)).sum())
                if probe != 64.0:
                    raise RuntimeError(f"device probe returned {probe}")
                _log(f"backend ready: {devs[0]}")
                return devs
        except Exception as e:  # noqa: BLE001
            last = e
            _log(f"backend attempt {i + 1}/{attempts} failed: "
                 f"{type(e).__name__}: {e}")
            if i < attempts - 1:
                time.sleep(delay)
                delay = min(delay * 2, 60.0)
    raise RuntimeError(f"backend unavailable after {attempts} attempts: {last}")


def _build_resnet(batch, dtype):
    net = get_model("resnet50_v1", classes=1000, layout="NHWC")
    net.initialize(init=mx.init.Xavier())
    if dtype == "bfloat16":
        net.cast("bfloat16")
    x = nd.array(np.random.randn(batch, 224, 224, 3).astype(np.float32))
    if dtype == "bfloat16":
        x = x.astype("bfloat16")
    y = nd.array(np.random.randint(0, 1000, batch))
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    flops_per_sample = 3 * 4.09e9                   # fwd+bwd, 224x224
    return net, L, x, y, flops_per_sample, "resnet50_imagenet"


def _build_bert(batch, dtype):
    """Secondary benchmark (BASELINE §6): BERT-base pretraining-shape step
    (seq 128, cls head as the loss surface)."""
    from incubator_mxnet_tpu.models.bert import BERTModel
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    layers = int(os.environ.get("BENCH_LAYERS", "12"))
    bert = BERTModel(num_layers=layers, units=768, hidden_size=3072,
                     num_heads=12, max_length=seq, vocab_size=30522,
                     dropout=0.1, use_pooler=False)
    net = gluon.nn.HybridSequential()
    net.add(bert, gluon.nn.Dense(2, flatten=False, in_units=768))
    net.initialize(init=mx.init.Normal(0.02))
    if dtype == "bfloat16":
        net.cast("bfloat16")
    x = nd.array(np.random.randint(0, 30522, (batch, seq)))
    y = nd.array(np.random.randint(0, 2, (batch, seq)))
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    flops_per_sample = 6 * 110e6 * seq * layers / 12  # ~6*N*T per token pass
    return net, L, x, y, flops_per_sample, f"bert_base_seq{seq}"


_BENCH_MODELS = {"resnet50": _build_resnet, "bert": _build_bert}


def main():
    global _CURRENT_METRIC
    model = os.environ.get("BENCH_MODEL", "resnet50")
    if model not in _BENCH_MODELS:
        raise ValueError(f"unknown BENCH_MODEL {model!r}; choose from "
                         f"{sorted(_BENCH_MODELS)}")
    default_batch = {"resnet50": "128", "bert": "32"}[model]
    batch = int(os.environ.get("BENCH_BATCH", default_batch))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")

    watchdog = _arm_hard_watchdog(
        int(os.environ.get("BENCH_HARD_TIMEOUT", "3300")))
    # a wedged relay hangs INSIDE the first device call (C code — the
    # SIGALRM per-attempt deadline never fires), so a shorter thread-based
    # watchdog covers init specifically; cancelled once the chip answers.
    # Default rides just above acquire_backend's worst legitimate span
    # (attempts * per-attempt timeout + backoff), so it only fires when
    # the retry loop itself is frozen in C.
    _init_attempts, _init_per = 6, 180
    _init_default = _init_attempts * _init_per + 200
    init_watchdog = _arm_hard_watchdog(
        int(os.environ.get("BENCH_INIT_TIMEOUT", str(_init_default))),
        "backend init")
    acquire_backend(attempts=_init_attempts,
                    per_attempt_timeout=_init_per)
    init_watchdog.cancel()
    np.random.seed(0)
    mx.random.seed(0)

    _CURRENT_METRIC = ("resnet50_imagenet_images_per_sec_per_chip"
                       if model == "resnet50"
                       else f"bench_{model}_samples_per_sec_per_chip")
    net, L, x, y, flops_per_sample, tag = _BENCH_MODELS[model](batch, dtype)
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9, wd=1e-4,
                              multi_precision=(dtype == "bfloat16"))
    step = FusedTrainStep(net, L, opt,
                          remat=os.environ.get("BENCH_REMAT") == "1")

    # compile + warmup. NOTE: through the axon relay block_until_ready() does
    # not synchronize; a host value fetch is the only true barrier. Steps
    # chain through updated params, so fetching the final loss times them all.
    _log("compiling fused train step (first call)")
    with _phase_deadline(int(os.environ.get("BENCH_COMPILE_TIMEOUT", "2400")),
                         "train step compile"):
        float(step(x, y))
    _log("compile done; warmup")
    float(step(x, y))
    _log(f"timing {steps} steps @ batch {batch} {dtype}")

    t0 = time.time()
    for _ in range(steps):
        loss = step(x, y)
    loss_val = float(loss)
    dt = time.time() - t0

    img_s = batch * steps / dt
    peak = 197e12 if dtype == "bfloat16" else 99e12  # v5e chip
    mfu = img_s * flops_per_sample / peak

    watchdog.cancel()
    # keep the headline metric name stable across rounds for the driver
    metric = ("resnet50_imagenet_images_per_sec_per_chip"
              if model == "resnet50" else f"{tag}_samples_per_sec_per_chip")
    _CURRENT_METRIC = metric
    print(json.dumps({
        "metric": metric,
        "value": round(img_s, 2),
        "unit": "images/sec" if model == "resnet50" else "samples/sec",
        # the V100 390 img/s baseline is a ResNet-50 number; other models
        # report MFU instead of a cross-model ratio
        "vs_baseline": (round(img_s / V100_BASELINE_IMG_S, 3)
                        if model == "resnet50" else None),
        "extra": {"model": tag, "batch": batch, "dtype": dtype,
                  "steps": steps, "mfu": round(mfu, 4),
                  "final_loss": round(loss_val, 4),
                  "device": str(jax.devices()[0])},
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001
        import traceback
        traceback.print_exc()
        # Emit a parseable JSON line even on failure so the driver records
        # a diagnostic instead of a bare rc=1.
        print(json.dumps({
            "metric": _CURRENT_METRIC,
            "value": 0.0,
            "unit": "images/sec",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:500],
        }))
        sys.exit(1)
