#!/usr/bin/env python
"""Headline benchmark: ResNet-50 ImageNet-shape training throughput on one
TPU chip (BASELINE.json: images/sec/chip vs MXNet-on-V100 reference).

Prints exactly one JSON line:
  {"metric": "...", "value": N, "unit": "images/sec", "vs_baseline": N}

Baseline: published MXNet ResNet-50 fp32 V100 throughput ~390 img/s
(BASELINE.json north star: target >=70% of that on one v5e chip).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

# Persistent compilation cache: the axon remote-compile path is slow; cache
# makes repeat bench runs start fast.
try:
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:
    pass

import numpy as np  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon, nd  # noqa: E402
from incubator_mxnet_tpu.models import get_model  # noqa: E402
from incubator_mxnet_tpu.parallel import FusedTrainStep  # noqa: E402

V100_BASELINE_IMG_S = 390.0  # MXNet ResNet-50 fp32, single V100 (published)
RESNET50_FLOPS_PER_SAMPLE = 3 * 4.09e9   # fwd+bwd, 224x224 (both benches)

# updated once the model is resolved; all error paths report through this
_CURRENT_METRIC = "resnet50_imagenet_images_per_sec_per_chip"

# process start, for fitting the autotune search inside the hard
# watchdog (armed against the same clock in main())
_BENCH_T0 = time.time()


class _PhaseTimeout(Exception):
    pass


def _env_failure_result(msg):
    """The self-describing environment-failure artifact: value 0 plus
    `"status": "env_failure"`, so tools/perf_regress.py (and any future
    baseline builder) can SKIP the artifact instead of reading 0 img/s
    as a real 100% regression — the BENCH_r02–r05 lesson."""
    return {
        "metric": _CURRENT_METRIC,
        "value": 0.0,
        "unit": "images/sec",
        "vs_baseline": 0.0,
        "status": "env_failure",
        "error": str(msg)[:500],
    }


def _arm_hard_watchdog(seconds, what="bench"):
    """SIGALRM can't interrupt a hang INSIDE a blocking C call (Python only
    runs signal handlers between bytecodes), and backend-init hangs live in
    C. A daemon thread with os._exit is the hard deadline: it emits the
    parseable error JSON line first so the driver records a diagnosis
    instead of rc=124 with empty output. A hang is an environment verdict
    (the axon tunnel wedges; PERF.md), so the artifact is marked
    env_failure rather than reported as a 0 img/s perf number."""
    import threading

    def fire():
        print(json.dumps(_env_failure_result(
            f"hard watchdog: {what} exceeded {seconds}s (hang inside a C "
            "call; SIGALRM deadlines could not fire)")), flush=True)
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def _preflight_probe():
    """PERF.md's tunnel-probe protocol as a bench preflight: one small
    matmul + HOST VALUE FETCH (the only true barrier through the relay)
    in a daemon thread with a hard deadline. A backend that hangs — the
    failure mode BENCH_r02–r05 recorded, unreachable by SIGALRM because
    it lives inside a C call — produces a `{"status": "env_failure"}`
    artifact within BENCH_PREFLIGHT_TIMEOUT seconds instead of eating
    the whole bench budget. A probe that ERRORS quickly is left to
    acquire_backend's retry loop (transients recover; hangs don't).
    BENCH_PREFLIGHT=0 skips."""
    if os.environ.get("BENCH_PREFLIGHT", "1") != "1":
        return
    import threading
    timeout_s = int(os.environ.get("BENCH_PREFLIGHT_TIMEOUT", "240"))
    result = []

    def probe():
        try:
            import jax.numpy as jnp
            x = jnp.ones((128, 128), jnp.float32)
            result.append(float((x @ x).sum()))
        except Exception as e:  # noqa: BLE001 — retried by acquire_backend
            result.append(e)

    _log(f"preflight: tunnel probe (deadline {timeout_s}s)")
    t0 = time.time()
    th = threading.Thread(target=probe, daemon=True, name="bench-preflight")
    th.start()
    th.join(timeout_s)
    if th.is_alive():
        print(json.dumps(_env_failure_result(
            f"preflight: backend probe (matmul+fetch) hung for "
            f"{timeout_s}s — wedged tunnel/backend; skipping the run")),
            flush=True)
        os._exit(2)
    if result and not isinstance(result[0], Exception) \
            and result[0] != 128.0 ** 3:
        print(json.dumps(_env_failure_result(
            f"preflight: probe returned {result[0]} != {128.0 ** 3} — "
            "backend answered with garbage")), flush=True)
        os._exit(2)
    verdict = ("error (deferring to backend retry)"
               if result and isinstance(result[0], Exception) else "ok")
    _log(f"preflight: {verdict} in {time.time() - t0:.1f}s")


class _phase_deadline:
    """SIGALRM watchdog: the axon tunnel can HANG (not error) on init, and
    a silent hang eats the driver's whole bench budget with no JSON line.
    Convert hangs into exceptions the retry/error paths can handle."""

    def __init__(self, seconds, what):
        self.seconds = int(seconds)
        self.what = what

    def __enter__(self):
        import signal

        def handler(signum, frame):
            raise _PhaseTimeout(f"{self.what} exceeded {self.seconds}s")

        self._old = signal.signal(signal.SIGALRM, handler)
        signal.alarm(self.seconds)
        return self

    def __exit__(self, *exc):
        import signal
        signal.alarm(0)
        signal.signal(signal.SIGALRM, self._old)
        return False


def _log(msg):
    print(f"bench[{time.strftime('%H:%M:%S')}]: {msg}", file=sys.stderr,
          flush=True)


def _bench_profile_start():
    """Arm the profiler for phase scopes around the bench run. Imperative
    op timing stays OFF (it syncs per op and would distort the measured
    rate); only layer/phase scopes are recorded. Returns the trace path,
    or None when BENCH_TRACE=0."""
    if os.environ.get("BENCH_TRACE", "1") != "1":
        return None
    from incubator_mxnet_tpu import profiler as prof
    path = os.environ.get("BENCH_TRACE_FILE", "/tmp/mxtpu_bench_trace.json")
    prof.reset()
    prof.set_config(filename=path, profile_imperative=False)
    prof.start()
    return path


def _bench_diag_start():
    """Arm the always-on diagnostics layer for the bench run. The memory
    ledger is on unconditionally (its peaks land in BENCH_*.json so
    memory regressions show up in the perf trajectory); BENCH_DIAG=1
    additionally runs the metrics sampler (BENCH_DIAG_INTERVAL_MS,
    default 100) and the flight recorder, whose outputs are validated by
    tools/trace_check at the end of the run."""
    from incubator_mxnet_tpu import diagnostics as diag
    diag.enable_memory()
    if os.environ.get("BENCH_DIAG", "0") != "1":
        return None
    diag_dir = os.environ.get("MXTPU_DIAG_DIR", "/tmp/mxtpu_bench_diag")
    os.makedirs(diag_dir, exist_ok=True)
    diag.enable_flight_recorder(dump_dir=diag_dir)
    jsonl = os.path.join(diag_dir, "metrics.jsonl")  # sampler truncates it
    diag.start_sampler(
        interval_ms=int(os.environ.get("BENCH_DIAG_INTERVAL_MS", "100")),
        jsonl_path=jsonl, prom_path=os.path.join(diag_dir, "metrics.prom"))
    return diag_dir


def _bench_healthmon_start():
    """BENCH_HEALTHMON=1: arm the cross-rank health layer for the bench
    run — the structured event log + watchdogs (stall deadline widened to
    cover the compile phase, BENCH_HEALTHMON_STALL_S). The bench loop
    feeds it one mark per step, so the emitted BENCH json carries the
    healthmon counters and the events file — and the run doubles as the
    measured-overhead harness tools/health_smoke.sh compares against a
    healthmon-off run."""
    if os.environ.get("BENCH_HEALTHMON", "0") != "1":
        return None
    from incubator_mxnet_tpu import healthmon as hm
    diag_dir = os.environ.get("MXTPU_DIAG_DIR", "/tmp/mxtpu_bench_diag")
    os.makedirs(diag_dir, exist_ok=True)
    return hm.enable(
        hm_dir=diag_dir,
        stall_timeout_s=float(os.environ.get("BENCH_HEALTHMON_STALL_S",
                                             "1200")))


def _healthmon_mark_step():
    """One completed bench step (no-op when healthmon is off)."""
    from incubator_mxnet_tpu import healthmon as hm
    if hm._HM is not None:
        hm._HM.step_end()


# the run's CheckpointManager when BENCH_RESILIENCE=1 (closed and
# reported as extra.resilience by _finish_profile)
_RES_MGR = None


def _bench_resilience_start(step):
    """BENCH_RESILIENCE=1: arm async checkpointing (mxtpu.resilience)
    over the steady phase — cadence BENCH_RESILIENCE_EVERY (default 20)
    into BENCH_RESILIENCE_DIR (default a fresh temp dir) — so the BENCH
    json carries extra.resilience: checkpoint cadence, save-cost
    p50/p95, and any recovery accounting. The measured loop pays only
    the boundary device→host copies; serialization stays on the
    manager's worker thread (docs/resilience.md's cost model)."""
    global _RES_MGR
    if os.environ.get("BENCH_RESILIENCE", "0") != "1":
        return None
    import tempfile
    from incubator_mxnet_tpu.resilience import CheckpointManager
    d = os.environ.get("BENCH_RESILIENCE_DIR") or \
        tempfile.mkdtemp(prefix="mxtpu_bench_ckpt_")
    every = int(os.environ.get("BENCH_RESILIENCE_EVERY", "20"))
    keep = int(os.environ.get("BENCH_RESILIENCE_KEEP", "3"))
    _log(f"resilience armed: async checkpoints every {every} steps "
         f"(keep {keep}) -> {d}")
    _RES_MGR = CheckpointManager(d, step, every=every, keep=keep)
    return _RES_MGR


def _resilience_mark_step():
    """One completed bench step/chunk boundary (no-op when resilience
    is off — one predicate, the disabled-cost contract)."""
    if _RES_MGR is not None:
        _RES_MGR.maybe_save()


def _bench_perfscope_start():
    """Arm roofline-aware cost capture (mxtpu.perfscope) for the run:
    every compile site (fused step, loop chunk, jit cache, serving
    buckets) records XLA FLOPs/bytes + a roofline verdict, and the
    steady phase gets a step-time decomposition into
    `extra.perfscope`. BENCH_PERFSCOPE=0 disables."""
    if os.environ.get("BENCH_PERFSCOPE", "1") != "1":
        return None
    from incubator_mxnet_tpu import perfscope as ps
    return ps.enable()


def _bench_commscope_start():
    """Arm collective/resharding extraction (mxtpu.commscope) for the
    run: every compile site's optimized HLO is walked for its collective
    inventory (kind / count / payload bytes / mesh axis / analytic ICI
    estimate), the resharding detector flags accidental all-gathers, and
    the result lands in `extra.commscope` + the step budget's estimated
    `collective` component. Zero cost without a mesh (no collectives to
    find, nothing compiled); under BENCH_MESH it pays one extra XLA
    compile per captured program. BENCH_COMMSCOPE=0 disables; commscope
    rides perfscope's capture hooks (enable() arms perfscope), so a
    default-on commscope DECLINES when BENCH_PERFSCOPE=0 was set —
    the perfscope opt-out must not be silently undone. An explicit
    BENCH_COMMSCOPE=1 wins the conflict (and says so)."""
    if os.environ.get("BENCH_COMMSCOPE", "1") != "1":
        return None
    if os.environ.get("BENCH_PERFSCOPE", "1") != "1":
        if os.environ.get("BENCH_COMMSCOPE") != "1":
            return None
        _log("BENCH_COMMSCOPE=1 overrides BENCH_PERFSCOPE=0: commscope "
             "rides perfscope's capture hooks, arming both")
    from incubator_mxnet_tpu import commscope as cs
    return cs.enable()


def _bench_devicescope_start():
    """BENCH_DEVICESCOPE=1: arm measured device-timeline capture
    (mxtpu.devicescope) — one bounded window (BENCH_DEVICESCOPE_STEPS,
    default 10) of the steady phase runs under jax.profiler.trace; the
    artifact is ingested into measured busy fraction / top-K device ops
    / idle-gap taxonomy, the step budget's provenance upgrades to
    measured(profile), and `extra.devicescope` carries the
    analytic-vs-measured reconciliation. OFF by default: the traced
    steps pay profiler overhead, so the window must be asked for.
    Artifact dirs rotate (MXTPU_DEVICESCOPE_KEEP, default 3)."""
    if os.environ.get("BENCH_DEVICESCOPE", "0") != "1":
        return None
    from incubator_mxnet_tpu import devicescope as ds
    return ds.enable()


def _bench_memscope_start():
    """BENCH_MEMSCOPE=1: arm memory observability (mxtpu.memscope) —
    every captured program additionally reads
    `compiled.memory_analysis()` into a static footprint table joined
    to the roofline verdicts, the steady loops feed a bounded
    watermark ring of allocator samples (+ host RSS), an escaping
    RESOURCE_EXHAUSTED assembles an attributed post-mortem, and
    `extra.memscope` carries it all (validated by trace_check's
    check_memscope_extra). OFF by default: a capture site holding only
    a lowered program pays one extra host-side XLA compile per program
    (the commscope acquisition cost), so the footprints must be asked
    for. Rides perfscope's capture hooks (enable() arms perfscope)."""
    if os.environ.get("BENCH_MEMSCOPE", "0") != "1":
        return None
    from incubator_mxnet_tpu import memscope as ms
    return ms.enable()


def _memscope_mark(step_no):
    """One watermark-ring allocator sample at a steady-loop step
    boundary when memscope is armed (mxtpu.trainloop marks its own
    chunks, so loop mode needs no bench-side mark). One predicate when
    off; sampling never raises."""
    from incubator_mxnet_tpu import memscope as ms
    if ms._MS is not None:
        ms.sample(step=step_no, workload="train")


def _bench_strict_start():
    """MXTPU_STRICT=1 (or BENCH_STRICT=1): arm the mxlint strict-mode
    jit-program auditor (mxtpu.mxlint.runtime) — every steady-loop
    dispatch runs under transfer-guard + NDArray-sentinel host-sync
    detection, perfscope compile captures feed the recompile-storm
    detector, and `extra.mxlint` carries the verdicts (validated by
    trace_check's check_mxlint_extra). On CPU the sentinel counts and
    the run completes; an accelerator jax-guard trip is a counted,
    LOUD failure (no side-effect-safe re-run of a dispatched step
    exists) — a smoke/CI mode, not a production default."""
    from incubator_mxnet_tpu.mxlint import runtime as mxa
    if mxa.enabled():              # armed at import via MXTPU_STRICT=1
        return mxa.auditor()
    if os.environ.get("BENCH_STRICT", "0") == "1":
        return mxa.enable()
    return None


def _strict_guarded(aud, thunk):
    """One steady-loop dispatch through the strict guard (or plainly —
    the loops call this with aud=None when strict is off). The guard
    SEMANTICS live in one home (StrictAuditor.guarded); this wrapper
    only spares the off path an attribute lookup per dispatch."""
    if aud is None:
        return thunk()
    return aud.guarded(thunk)


def _devicescope_window(total_steps, steps_per_dispatch=1):
    """A started capture window over the first N steady steps when
    devicescope is armed, else None (zero overhead: the loops guard
    every mark with `if win is not None`)."""
    from incubator_mxnet_tpu import devicescope as ds
    if ds._DS is None:
        return None
    n = int(os.environ.get("BENCH_DEVICESCOPE_STEPS", "10"))
    n = max(int(steps_per_dispatch), min(n, int(total_steps)))
    win = ds.capture(steps=n).start()
    if win.active:
        _log(f"devicescope: capture window armed ({n} steps) -> "
             f"{win.logdir}")
    else:
        _log("devicescope: capture window DECLINED (profiler busy or "
             "unavailable)")
    return win


def _bench_mesh():
    """BENCH_MESH=dp4|dp2mp2|fsdp4|…: register a process-global device
    mesh (mxtpu.sharding) so the steady phase runs through the SHARDED
    executor — one jit whose in/out shardings carry the resolved
    per-param NamedShardings, XLA inserting the collectives. The token
    grammar (concatenated <axis><size> pairs, the `fsdp` pseudo-axis,
    the model-axis → mode='auto' rule) lives in autotune.knobs.
    parse_mesh — ONE home, shared with the trial runner — and the spec
    itself resolves through the knob table (BENCH_MESH > MXTPU_MESH >
    cached tuning winner). Returns the sharding mode, or None when no
    mesh is configured. On CPU pair with
    XLA_FLAGS=--xla_force_host_platform_device_count=N
    (tools/shard_smoke.sh does)."""
    from incubator_mxnet_tpu.autotune import knobs as _knobs
    from incubator_mxnet_tpu.parallel import make_mesh
    from incubator_mxnet_tpu.parallel import sharding as _shmod
    spec = _knobs.resolve("mesh")[0]
    if not spec:
        return None
    mode, axes = _knobs.parse_mesh(spec)
    mesh = make_mesh(axes)
    _shmod.set_mesh(mesh)
    _log(f"sharding: mesh {dict(mesh.shape)} mode={mode} over "
         f"{mesh.size} of {len(jax.devices())} devices")
    return mode


def _bench_autotune(model, batch, dtype):
    """MXTPU_AUTOTUNE=1: resolve the tuning cache for this
    (model, mesh, device-kind) key — hit: the stored winner's knobs
    install as the below-env defaults with ZERO trials; miss: a bounded
    search runs first (each trial a short bench.py SUBPROCESS —
    docs/autotune.md's cost model), the winner installs and persists.
    Explicit BENCH_*/MXTPU_* overrides still beat the winner (the knob
    precedence), so the tuner can never reinterpret a human A/B run.
    Returns the `extra.autotune` payload; the disabled shape
    ({"enabled": false}) when unarmed, so every training BENCH json
    carries a validatable section either way."""
    from incubator_mxnet_tpu import autotune as at
    if not at.enabled():
        return at.bench_extra(None)
    data_mode = os.environ.get("BENCH_DATA", "synthetic")
    if data_mode not in ("", "synthetic"):
        # the trial runner pins BENCH_* per trial (BENCH_DATA included),
        # so every search trial would measure the SYNTHETIC input path
        # while this run is the JPEG-decode path — input starvation is
        # exactly what data mode changes — and the cache key carries no
        # data-mode leg, so the wrong winner would then poison the
        # synthetic key too. Run untuned rather than tune the wrong
        # workload; the record says why.
        _log(f"autotune: BENCH_DATA={data_mode} runs the record input "
             f"path but search trials measure the synthetic path — "
             f"running UNTUNED (data-path trials not supported yet)")
        return {"enabled": True, "cache_hit": False, "trials": 0,
                "trials_failed": 0, "trials_pruned": 0,
                "winner": None, "score": None,
                "error": f"BENCH_DATA={data_mode}: data-path trials "
                         f"not supported"}
    mesh = at.knobs.resolve("mesh")[0]
    # a cache-miss search must FIT inside the bench's hard watchdog:
    # budget x per-trial timeout can exceed the horizon (6 x 900 s >
    # the default 3300 s), and the watchdog os._exit()s mid-search with
    # nothing cached. Clamp the per-trial timeout so the worst-case
    # search leaves ~600 s for the measured run itself; an explicit
    # MXTPU_AUTOTUNE_TRIAL_TIMEOUT is clamped too (and says so) — a
    # finished cheap search beats a killed thorough one.
    budget = int(os.environ.get("MXTPU_AUTOTUNE_BUDGET", "6"))
    want_timeout = int(os.environ.get("MXTPU_AUTOTUNE_TRIAL_TIMEOUT",
                                      "900"))
    hard = int(os.environ.get("BENCH_HARD_TIMEOUT", "3300"))
    elapsed = time.time() - _BENCH_T0
    fit_timeout = max(60, int((hard - elapsed - 600) / max(1, budget)))
    trial_timeout = min(want_timeout, fit_timeout)
    if trial_timeout < want_timeout * 0.9:
        _log(f"autotune: per-trial timeout clamped {want_timeout}s -> "
             f"{trial_timeout}s so {budget} trials fit inside the "
             f"BENCH_HARD_TIMEOUT={hard}s watchdog (raise it, or lower "
             f"MXTPU_AUTOTUNE_BUDGET, for longer trials)")
    _log(f"autotune armed: model={model} batch={batch} dtype={dtype} "
         f"mesh={mesh}")
    try:
        result = at.ensure_tuned(model=model, batch=batch, dtype=dtype,
                                 mesh=mesh, budget=budget,
                                 trial_timeout=trial_timeout, log=_log)
    except Exception as e:  # noqa: BLE001 — tuning is advisory: a
        _log(f"autotune failed ({type(e).__name__}: {e}); "  # broken
             "running untuned")                # tuner must not cost the
        return {"enabled": True, "cache_hit": False,   # measured run
                "trials": 0, "trials_failed": 0, "trials_pruned": 0,
                "winner": None, "score": None,
                "error": f"{type(e).__name__}: {e}"[:200]}
    return at.bench_extra(result)


def _perfscope_budget(steps_per_dispatch=1):
    """A primed StepBudget when perfscope is armed, else None."""
    from incubator_mxnet_tpu import perfscope as ps
    if ps._PS is None:
        return None
    return ps.StepBudget(steps_per_dispatch=steps_per_dispatch).begin()


def _perfscope_settle(result, budget, steps, steady_s, probe_fn,
                      steps_per_call, flops_per_step, dtype):
    """Close the steady-phase budget: device-time probe (a few extra
    synchronized steps — each ends in a host fetch, the one true barrier
    through the relay), settle the decomposition, and attach
    `extra.perfscope` (decomposition + per-program roofline verdicts +
    the peak table) to the result JSON."""
    from incubator_mxnet_tpu import perfscope as ps
    if budget is None:
        return
    # the whole settle path is best-effort: the headline number is
    # already measured, and attribution must NEVER destroy it (the same
    # contract as the k=1 control) — a wedged relay during the probe
    # costs the decomposition, not the result
    try:
        budget.end(steps=steps, steady_s=steady_s)
        n_probe = int(os.environ.get("BENCH_PERFSCOPE_PROBE", "5"))
        if n_probe > 0 and probe_fn is not None:
            with _phase_deadline(int(os.environ.get("BENCH_PROBE_TIMEOUT",
                                                    "600")),
                                 "perfscope device-time probe"):
                p = budget.probe(probe_fn, iters=n_probe,
                                 steps_per_call=steps_per_call)
            _log(f"perfscope probe: {p['median_ms']:.3f} ms/step sync "
                 f"({p['iters']} iters)")
        decomp = budget.finish(model_flops_per_step=flops_per_step,
                               dtype=dtype)
        result.setdefault("extra", {})["perfscope"] = ps.bench_extra(decomp)
    except Exception as e:  # noqa: BLE001
        _log(f"perfscope settle failed ({type(e).__name__}: {e}); "
             "reporting the measured result without a decomposition")
        try:
            result.setdefault("extra", {})["perfscope"] = ps.bench_extra()
        except Exception:  # noqa: BLE001
            pass
    # the collective inventory rides along whenever commscope is armed
    # (BENCH_MESH runs carry the real payload; unsharded runs an empty
    # one, so the schema is uniform) — attached OUTSIDE the settle try
    # so a failed probe can't cost the comms table too
    try:
        from incubator_mxnet_tpu import commscope as cs
        if cs._CS is not None:
            result.setdefault("extra", {})["commscope"] = cs.bench_extra()
    except Exception as e:  # noqa: BLE001
        _log(f"commscope attach failed ({type(e).__name__}: {e})")
    # the measured device-timeline summary rides along whenever
    # devicescope is armed (window summary + reconciliation; the
    # armed-but-declined shape is `{"window": null}` so the schema is
    # uniform) — also outside the settle try, for the same reason
    try:
        from incubator_mxnet_tpu import devicescope as dsc
        if dsc._DS is not None:
            result.setdefault("extra", {})["devicescope"] = \
                dsc.bench_extra()
    except Exception as e:  # noqa: BLE001
        _log(f"devicescope attach failed ({type(e).__name__}: {e})")
    # the memory footprints / watermarks / headroom / reconciliation
    # ride along whenever memscope is armed — also outside the settle
    # try, so a failed probe can't cost the memory evidence either
    try:
        from incubator_mxnet_tpu import memscope as msc
        if msc._MS is not None:
            result.setdefault("extra", {})["memscope"] = msc.bench_extra()
    except Exception as e:  # noqa: BLE001
        _log(f"memscope attach failed ({type(e).__name__}: {e})")


def _profiled_compile_warmup(run_compile, run_warmup):
    """Shared compile+warmup phase instrumentation for both bench paths:
    arms the profiler, runs the compile under a bench.compile scope and
    the usual phase deadline, times both phases. Returns
    (trace_path, compile_s, warmup_s)."""
    from incubator_mxnet_tpu import profiler as prof
    trace_path = _bench_profile_start()
    t_c = time.time()
    with prof.record_function("bench.compile", "bench", sync=False), \
            _phase_deadline(int(os.environ.get("BENCH_COMPILE_TIMEOUT",
                                               "2400")),
                            "train step compile"):
        run_compile()
    compile_s = time.time() - t_c
    _log(f"compile done in {compile_s:.1f}s; warmup")
    t_w = time.time()
    run_warmup()
    warmup_s = time.time() - t_w
    return trace_path, compile_s, warmup_s


def _load_trace_check():
    import importlib.util
    tc_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tools", "trace_check.py")
    spec = importlib.util.spec_from_file_location("trace_check", tc_path)
    tc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tc)
    return tc


def _finish_profile(result, trace_path, **phase_s):
    """Publish per-phase wall times as profiler gauges, attach them to the
    result JSON (-> BENCH_*.json), then dump the Chrome trace (and any
    diagnostics artifacts) and schema-check everything with
    tools/trace_check — malformed telemetry fails the bench run loudly
    instead of shipping garbage."""
    from incubator_mxnet_tpu import diagnostics as diag
    from incubator_mxnet_tpu import profiler as prof
    phases = {k: round(float(v), 4) for k, v in phase_s.items()}
    for k, v in phases.items():
        prof.set_gauge("bench/" + k, v)
    result.setdefault("extra", {})["phases"] = phases
    # dispatch-overhead regression canary: host dispatches per train step
    # (always-live counter gauge — FusedTrainStep reports 1, 1/k under
    # run_k; the eager Trainer reports #params unfused / #(rule,dtype)
    # groups with fused_update). Visible in BENCH_*.json without a TPU.
    result["extra"]["dispatches_per_step"] = prof.counters().get(
        "mxtpu/trainer.dispatches_per_step")
    # memory-regression canary: the allocation ledger's peaks + the final
    # counters snapshot ride along in BENCH_*.json so drift shows up in
    # the perf trajectory next to step times
    mem = diag.memory_summary(include_reconcile=False)
    result["extra"]["memory"] = {
        "peak_bytes": mem["peak_bytes"],
        "current_bytes": mem["current_bytes"],
        "live_arrays": mem["live_arrays"],
        "by_context": mem["by_context"],
    }
    result["extra"]["counters"] = prof.counters()
    tc = _load_trace_check()
    errors = []
    if trace_path is not None:
        prof.stop()
        prof.dump(filename=trace_path)
        errors += tc.check_trace(trace_path)
        result["extra"]["trace_file"] = trace_path
    if diag.flight_enabled() or diag.sampler_running():
        diag.stop_sampler()
        flight_path = diag.dump_flight(reason="bench_end")
        if flight_path:
            errors += tc.check_flight(flight_path)
            result["extra"]["flight_file"] = flight_path
        diag_dir = os.environ.get("MXTPU_DIAG_DIR", "/tmp/mxtpu_bench_diag")
        for name, checker in (("metrics.jsonl", tc.check_metrics_jsonl),
                              ("metrics.prom", tc.check_prom)):
            p = os.path.join(diag_dir, name)
            if os.path.exists(p):
                errors += checker(p)
                result["extra"]["diag_" + name.split(".")[1]] = p
    global _RES_MGR
    if _RES_MGR is not None:
        # drain the worker so the save histograms cover every enqueued
        # checkpoint, then report cadence + cost + recovery accounting
        from incubator_mxnet_tpu import resilience as _rs
        _RES_MGR.close()
        result["extra"]["resilience"] = _rs.bench_extra(_RES_MGR)
        _RES_MGR = None
    from incubator_mxnet_tpu import healthmon as hm
    if hm.enabled():
        mon = hm.current()
        events_path = mon.events.path
        result["extra"]["healthmon"] = {
            "events_file": events_path,
            "steps": mon.step,
            "counters": {k: v for k, v in prof.counters().items()
                         if k.startswith("healthmon/")},
        }
        hm.disable()               # closes the event log before validation
        errors += tc.check_events_jsonl(events_path)
    if errors:
        raise RuntimeError("bench telemetry failed schema check: "
                           + "; ".join(errors[:5]))
    if trace_path is not None:
        _log(f"trace OK: {trace_path} ({len(phases)} phases)")


def acquire_backend(attempts=6, first_delay=3.0,
                    per_attempt_timeout=180):
    """Backend init through the axon relay is occasionally UNAVAILABLE or
    simply unresponsive (transient tunnel/contention); retry with backoff —
    and a per-attempt watchdog — before giving up, so one flake doesn't
    forfeit the round's perf number."""
    delay = first_delay
    last = None
    for i in range(attempts):
        try:
            with _phase_deadline(per_attempt_timeout, "backend init"):
                _log(f"backend attempt {i + 1}/{attempts}")
                devs = jax.devices()
                # force a real device computation with a HOST FETCH:
                # through the axon relay block_until_ready() returns at
                # enqueue, so only a value fetch proves the chip answers
                # (a wedged tunnel would otherwise pass this probe and
                # then burn the whole compile watchdog)
                import jax.numpy as jnp
                probe = float(jnp.ones((8, 8)).sum())
                if probe != 64.0:
                    raise RuntimeError(f"device probe returned {probe}")
                _log(f"backend ready: {devs[0]}")
                return devs
        except Exception as e:  # noqa: BLE001
            last = e
            _log(f"backend attempt {i + 1}/{attempts} failed: "
                 f"{type(e).__name__}: {e}")
            if i < attempts - 1:
                time.sleep(delay)
                delay = min(delay * 2, 60.0)
    raise RuntimeError(f"backend unavailable after {attempts} attempts: {last}")


def _build_resnet(batch, dtype):
    # BENCH_S2D=1: MLPerf-style space-to-depth stem — exact-equivalent
    # 4x4/s1 conv on a (112,112,12) image instead of 7x7/s2 on (224,224,3),
    # quadrupling MXU input-lane utilization in the stem
    net = get_model("resnet50_v1", classes=1000, layout="NHWC",
                    stem_s2d=os.environ.get("BENCH_S2D") == "1")
    net.initialize(init=mx.init.Xavier())
    if dtype == "bfloat16":
        net.cast("bfloat16")
    x = nd.array(np.random.randn(batch, 224, 224, 3).astype(np.float32))
    if dtype == "bfloat16":
        x = x.astype("bfloat16")
    y = nd.array(np.random.randint(0, 1000, batch))
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    flops_per_sample = RESNET50_FLOPS_PER_SAMPLE
    return net, L, x, y, flops_per_sample, "resnet50_imagenet"


def _build_bert(batch, dtype):
    """Secondary benchmark (BASELINE §6): BERT-base pretraining-shape step
    (seq 128, cls head as the loss surface)."""
    from incubator_mxnet_tpu.models.bert import BERTModel
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    layers = int(os.environ.get("BENCH_LAYERS", "12"))
    bert = BERTModel(num_layers=layers, units=768, hidden_size=3072,
                     num_heads=12, max_length=seq, vocab_size=30522,
                     dropout=0.1, use_pooler=False)
    net = gluon.nn.HybridSequential()
    net.add(bert, gluon.nn.Dense(2, flatten=False, in_units=768))
    net.initialize(init=mx.init.Normal(0.02))
    if dtype == "bfloat16":
        net.cast("bfloat16")
    x = nd.array(np.random.randint(0, 30522, (batch, seq)))
    y = nd.array(np.random.randint(0, 2, (batch, seq)))
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    flops_per_sample = 6 * 110e6 * seq * layers / 12  # ~6*N*T per token pass
    return net, L, x, y, flops_per_sample, f"bert_base_seq{seq}"


def _build_lenet(batch, dtype):
    """BASELINE config 1: LeNet on MNIST shapes
    (example/image-classification/train_mnist.py)."""
    net = get_model("lenet", classes=10)
    net.initialize(init=mx.init.Xavier())
    if dtype == "bfloat16":
        net.cast("bfloat16")
    x = nd.array(np.random.rand(batch, 1, 28, 28).astype(np.float32))
    if dtype == "bfloat16":
        x = x.astype("bfloat16")
    y = nd.array(np.random.randint(0, 10, batch))
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    return net, L, x, y, 3 * 4.3e6, "lenet_mnist"


def _build_ssd(batch, dtype):
    """BASELINE config 4: SSD-512 VOC-shape training step (example/ssd).
    Synthetic boxes; hard negatives are re-mined against the CURRENT
    predictions every step, inside the compiled step (MultiBoxTarget is
    pure lax, so the mining compiles into the same XLA program — the
    reference's per-iteration MultiBoxTarget, minus its CPU round trip).
    Mining inputs are stop-gradiented: targets are labels, not a
    differentiable path."""
    from incubator_mxnet_tpu.models.ssd import ssd_512_resnet50_v1, SSDLoss
    classes = 20
    net = ssd_512_resnet50_v1(classes=classes, layout="NHWC")
    net.initialize(init=mx.init.Xavier())
    if dtype == "bfloat16":
        net.cast("bfloat16")
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(batch, 512, 512, 3).astype(np.float32))
    if dtype == "bfloat16":
        x = x.astype("bfloat16")
    label = np.zeros((batch, 2, 5), np.float32)
    for b in range(batch):
        for j in range(2):
            x0, y0 = rng.rand(2) * 0.5
            label[b, j] = [rng.randint(0, classes), x0, y0,
                           x0 + 0.3, y0 + 0.3]
    label_nd = nd.array(label)
    ssd_l = SSDLoss()

    def loss_fn(out, _y):
        anchor, cls_pred, box_pred = out
        bt, bm, ct = net.targets(nd.stop_gradient(anchor),
                                 nd.stop_gradient(cls_pred), label_nd)
        return ssd_l(cls_pred, box_pred, ct, bt, bm)

    y = nd.array(np.zeros(batch, np.float32))     # unused placeholder
    return net, loss_fn, x, y, 3 * 30e9, "ssd512_voc"


def _build_transformer_lm(batch, dtype):
    """Causal-LM step (GPT-2-base scale by default): fused-QKV causal
    flash attention, tied head, shifted-CE loss."""
    from incubator_mxnet_tpu.models import TransformerLM
    from incubator_mxnet_tpu.models.transformer_lm import lm_loss
    seq = int(os.environ.get("BENCH_SEQ", "512"))
    layers = int(os.environ.get("BENCH_LAYERS", "12"))
    units = int(os.environ.get("BENCH_UNITS", "768"))
    if units < 64 or units % 64:
        raise ValueError(f"BENCH_UNITS={units} must be a multiple of 64 "
                         "(64 dims per attention head)")
    vocab = 50257
    # dropout 0 by default: attention-weight dropout forces the dense
    # O(L^2) softmax path (ops/_raw.py) and the throughput bench should
    # measure the flash kernel; BENCH_DROPOUT restores training realism
    net = TransformerLM(vocab, num_layers=layers, units=units,
                        hidden_size=4 * units, num_heads=units // 64,
                        max_length=seq,
                        dropout=float(os.environ.get("BENCH_DROPOUT", "0")))
    net.initialize(init=mx.init.Normal(0.02))
    if dtype == "bfloat16":
        net.cast("bfloat16")
    x = nd.array(np.random.randint(0, vocab, (batch, seq)))

    def loss_fn(logits, y):
        return lm_loss(logits, y).mean()

    # fwd+bwd = 3x fwd. Per layer per sample: 6*params (block params
    # ~= 12*units^2 GEMMs) + the attention score/value matmuls
    # (QK^T + AV: 2 * 2*L^2*units). Plus the tied-head logits GEMM
    # (units x vocab per token — dense, ~30% of total at base config).
    # Only the input-embedding gather is excluded.
    flops_per_sample = (3 * (2 * 12 * units * units * seq
                             + 4 * seq * seq * units) * layers
                        + 3 * 2 * seq * units * vocab)
    return net, loss_fn, x, x, flops_per_sample, f"gpt_{units}_seq{seq}"


def _recsys_config():
    """The recsys family's shape knobs (bench.py is the env-exempt
    root; the package itself reads nothing raw)."""
    return {
        "tables": int(os.environ.get("BENCH_RECSYS_TABLES", "8")),
        "vocab": int(os.environ.get("BENCH_RECSYS_VOCAB", "512")),
        "dim": int(os.environ.get("BENCH_RECSYS_DIM", "32")),
        "dense": int(os.environ.get("BENCH_RECSYS_DENSE", "13")),
        "bag": int(os.environ.get("BENCH_RECSYS_BAG", "4")),
    }


def _recsys_row(rng, cfg):
    """One synthetic record: dense features + zipf-distributed ids
    (float-encoded; exact for vocab < 2^24) + a learnable click label
    (parity of the first table's first id — the tables, not the dense
    features, carry the signal, so a decreasing loss proves the
    embedding path trains)."""
    dense = rng.randn(cfg["dense"]).astype(np.float32)
    n_ids = cfg["tables"] * cfg["bag"]
    ids = np.minimum(rng.zipf(1.5, (n_ids,)) - 1,
                     cfg["vocab"] - 1).astype(np.float32)
    label = np.float32(int(ids[0]) % 2)
    return np.concatenate([dense, ids, [label]])


def _build_recsys(batch, dtype):
    """DLRM (models/dlrm.py): embedding bags on the model axis + MLPs +
    pairwise interaction — the memory/comms-bound family
    (docs/embedding.md). Ids ride float32 regardless of `dtype` (the
    id-normalization path rounds them back to int32 exactly); a
    bfloat16 run casts the MLPs and tables only."""
    from incubator_mxnet_tpu.models.dlrm import (dlrm_small, dlrm_loss,
                                                 dlrm_flops_per_sample)
    cfg = _recsys_config()
    net = dlrm_small(num_tables=cfg["tables"], vocab_size=cfg["vocab"],
                     embed_dim=cfg["dim"], dense_dim=cfg["dense"],
                     bag_size=cfg["bag"])
    net.initialize(init=mx.init.Normal(0.05))
    if dtype == "bfloat16":
        net.cast("bfloat16")
    rng = np.random.RandomState(0)
    rows = np.stack([_recsys_row(rng, cfg) for _ in range(batch)])
    x = nd.array(rows[:, :-1])
    y = nd.array(rows[:, -1])

    def loss_fn(logits, yb):
        return dlrm_loss(logits, yb).mean()

    flops_per_sample = dlrm_flops_per_sample(net)
    return net, loss_fn, x, y, flops_per_sample, "dlrm_recsys"


_BENCH_MODELS = {"resnet50": _build_resnet, "bert": _build_bert,
                 "lenet": _build_lenet, "ssd": _build_ssd,
                 "transformer_lm": _build_transformer_lm,
                 "recsys": _build_recsys}

# per-model default global batch — the ONE home (tools/perf_sweep.py
# imports it for cache-key fingerprints: a row without an explicit
# BENCH_BATCH ran at THIS batch, and the tuning-cache key must say so)
DEFAULT_BATCH = {"resnet50": 128, "bert": 32, "lenet": 512, "ssd": 16,
                 "transformer_lm": 16, "recsys": 256, "serving": 1}


def _mfu(samples_per_s, flops_per_sample, dtype):
    """Model FLOPs utilization: achieved model FLOP/s over the device's
    peak — ROADMAP item 1's regression metric, emitted into every
    training BENCH json. Peaks come from perfscope's shared table
    (v5e/v4/v5p + CPU fallback, MXTPU_PEAK_FLOPS override), so this
    number and extra.perfscope's MFU decomposition agree by
    construction."""
    from incubator_mxnet_tpu.perfscope.cost import (device_peaks,
                                                    peak_flops_for)
    return samples_per_s * flops_per_sample / peak_flops_for(dtype,
                                                             device_peaks())

# per-sample input shapes for the serving bench (BENCH_MODEL=serving)
_SERVING_SHAPES = {"lenet": (1, 28, 28), "resnet50_v1": (224, 224, 3)}


def _serving_bench():
    """BENCH_MODEL=serving: the inference-path benchmark. Freezes a
    model_zoo network (AOT per-bucket compile + warmup), starts the
    ModelServer, fires BENCH_SERVING_CLIENTS concurrent HTTP clients
    each sending BENCH_SERVING_REQS single-sample requests, and reports
    QPS + latency percentiles + batch-fill. Hard-fails (so the smoke
    and the driver see it) on any dropped request or any response that
    is not bit-exact against direct eager `net(x)`."""
    import threading
    import urllib.request

    from incubator_mxnet_tpu import devicescope
    from incubator_mxnet_tpu import profiler as prof
    from incubator_mxnet_tpu import servescope, serving

    # request-lifecycle tracing + tail-latency attribution rides every
    # serving bench by default (BENCH_SERVESCOPE=0 opts out) —
    # extra.servescope in the BENCH json. Sampled at a stride of 4
    # unless MXTPU_SERVESCOPE_SAMPLE says otherwise: the bench's
    # p50/p95/p99/QPS are the perf_regress-gated headline numbers, and
    # tracing EVERY sub-ms predict would measure the instrumentation,
    # not the server, against pre-servescope baselines
    if os.environ.get("BENCH_SERVESCOPE", "1") != "0":
        servescope.enable(
            sample=os.environ.get("MXTPU_SERVESCOPE_SAMPLE", 4))

    name = os.environ.get("BENCH_SERVING_MODEL", "lenet")
    if name not in _SERVING_SHAPES:
        raise ValueError(f"BENCH_SERVING_MODEL={name!r} has no serving "
                         f"shape; choose from {sorted(_SERVING_SHAPES)}")
    shape = _SERVING_SHAPES[name]
    clients = int(os.environ.get("BENCH_SERVING_CLIENTS", "64"))
    per_client = int(os.environ.get("BENCH_SERVING_REQS", "4"))
    max_delay_ms = float(os.environ.get("BENCH_SERVING_MAX_DELAY_MS", "25"))

    kwargs = {"layout": "NHWC"} if name.startswith("resnet") else {}
    net = get_model(name, classes=10 if name == "lenet" else 1000, **kwargs)
    net.initialize(init=mx.init.Xavier())

    frozen = [None]
    trace_path, compile_s, warmup_s = _profiled_compile_warmup(
        lambda: frozen.__setitem__(0, net.freeze(input_shape=shape)),
        lambda: None)           # freeze() warms every bucket itself
    srv = serving.ModelServer(frozen[0], max_delay_ms=max_delay_ms,
                              queue_limit=max(256, clients * per_client))
    host, port = srv.start()
    _log(f"serving {name} at {srv.address} buckets={frozen[0].buckets}")

    n_req = clients * per_client
    rng = np.random.RandomState(0)
    X = rng.rand(n_req, *shape).astype(np.float32)
    outputs = [None] * n_req
    failures = []

    def client(c):
        for j in range(per_client):
            i = c * per_client + j
            body = json.dumps({"data": X[i].tolist(),
                               "timeout_ms": 60000}).encode()
            try:
                r = urllib.request.urlopen(urllib.request.Request(
                    f"http://{host}:{port}/predict", data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=120)
                outputs[i] = json.loads(r.read())
            except Exception as e:  # noqa: BLE001
                failures.append((i, f"{type(e).__name__}: {e}"))

    _log(f"firing {clients} clients x {per_client} requests")
    # BENCH_DEVICESCOPE=1: one measured device window over the serving
    # dispatches (the batcher marks each executed batch), upgrading the
    # attribution's device_exec provenance to measured(profile)
    ds_win = None
    if os.environ.get("BENCH_DEVICESCOPE", "") == "1":
        ds_win = devicescope.capture(
            steps=int(os.environ.get("BENCH_DEVICESCOPE_STEPS", "10"))
        ).start()
    t0 = time.time()
    with prof.record_function("bench.steady", "bench", sync=False):
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    serve_s = time.time() - t0
    if ds_win is not None:
        ds_win.stop()
    stats = srv.stats()             # ONE registry snapshot: every
    srv.stop()                      # derived number below reads it
    #                                 (graceful drain)

    if failures:
        raise RuntimeError(f"{len(failures)}/{n_req} requests failed; "
                           f"first: {failures[0]}")
    # bit-exactness: reconstruct each dispatched batch (batch_id /
    # batch_index from the responses) and run net() — HYBRIDIZED, i.e.
    # the compiled CachedOp forward, the only path any compiled serving
    # stack can promise bit-identity with (per-op eager may differ by
    # ~1 ULP from any fused program; docs/serving.md) — on the SAME
    # padded batch: every served row must be bit-identical. The eager
    # per-request diff is reported as a number, not asserted.
    by_batch = {}
    for i in range(n_req):
        by_batch.setdefault(outputs[i]["batch_id"], []).append(i)
    eager_diff = 0.0
    for i in range(0, n_req, max(1, n_req // 16)):
        got = np.asarray(outputs[i]["output"], np.float32)
        ref1 = net(nd.array(X[i:i + 1])).asnumpy()[0]
        eager_diff = max(eager_diff, float(np.abs(got - ref1).max()))
    net.hybridize()
    for bid, idxs in by_batch.items():
        rows = sorted(idxs, key=lambda i: outputs[i]["batch_index"])
        bsz = outputs[rows[0]]["batch_size"]
        if len(rows) != bsz:
            raise RuntimeError(f"batch {bid}: {len(rows)} responses but "
                               f"batch_size={bsz}")
        xb = X[rows]
        bucket = frozen[0].bucket_for(bsz)
        if bucket != bsz:
            xb = np.concatenate(
                [xb, np.zeros((bucket - bsz,) + xb.shape[1:], xb.dtype)])
        ref = net(nd.array(xb)).asnumpy()
        for row_pos, i in enumerate(rows):
            got = np.asarray(outputs[i]["output"], np.float32)
            if not np.array_equal(got, ref[row_pos]):
                raise RuntimeError(
                    f"batch {bid} row {row_pos} (request {i}) diverges "
                    f"from the compiled net() forward on the same batch: "
                    f"max abs diff {np.abs(got - ref[row_pos]).max()}")
    dropped = n_req - int(stats.get("serving.responses", 0))
    if dropped:
        raise RuntimeError(f"{dropped} requests dropped "
                           f"(responses != submitted)")

    qps = n_req / serve_s
    # the histogram comes from the SAME snapshot as the percentiles —
    # a second counters() read here could see a later epoch than the
    # stats-derived numbers and trip the validator's lost-observations
    # check under concurrent traffic
    hist = stats.get("serving.latency_ms") or {}
    extra_serving = {
        "model": name, "clients": clients, "per_client": per_client,
        "requests": n_req,
        "responses": int(stats.get("serving.responses", 0)),
        "batches": int(stats.get("serving.batches", 0)),
        "batch_fill": round(stats.get("batch_fill", 0.0), 3),
        "rejected_queue_full": int(stats.get("serving.rejected_queue_full",
                                             0)),
        "rejected_deadline": int(stats.get("serving.rejected_deadline", 0)),
        "rejected_deadline_post_batch": int(stats.get(
            "serving.rejected_deadline_post_batch", 0)),
        "rejected_invalid": int(stats.get("serving.rejected_invalid", 0)),
        "qps": round(qps, 2),
        "p50_ms": stats.get("p50_ms"),
        "p95_ms": stats.get("p95_ms"),
        "p99_ms": stats.get("p99_ms"),
        "latency_ms": hist,
        "max_delay_ms": max_delay_ms,
        "buckets": list(frozen[0].buckets),
        "bit_exact": True,        # vs compiled net() on the same batch
        "max_abs_diff_vs_single_eager": eager_diff,
        "n_dispatch_batches": len(by_batch),
    }
    result = {
        "metric": f"serving_{name}_requests_per_sec",
        "value": round(qps, 2),
        "unit": "requests/sec",
        "vs_baseline": None,
        "extra": {"model": f"serving_{name}", "batch": None,
                  "dtype": "float32", "steps": n_req,
                  "serving": extra_serving,
                  "device": str(jax.devices()[0])},
    }
    from incubator_mxnet_tpu import perfscope as _psmod
    if _psmod._PS is not None:
        # serving has no train-step budget, but the per-bucket roofline
        # verdicts still ride along
        result["extra"]["perfscope"] = _psmod.bench_extra(None)
    if servescope._SS is not None:
        # the tail-latency attribution (per-bucket components + the
        # roofline/resharding verdict join — docs/servescope.md)
        result["extra"]["servescope"] = servescope.bench_extra()
    if ds_win is not None:
        result["extra"]["devicescope"] = devicescope.bench_extra()
    _finish_profile(result, trace_path, compile_s=compile_s,
                    warmup_s=warmup_s, steady_s=serve_s)
    return result


class _CastNorm(gluon.nn.HybridBlock):
    """Device-side input finishing: cast to the compute dtype and, for raw
    uint8 input, apply (x/1 - mean)/std INSIDE the compiled step. The host
    then ships raw decoded bytes — 4x less relay/PCIe traffic than float32
    — and normalization fuses into the step (reference contrast:
    iter_image_recordio_2.cc normalizes on CPU threads)."""

    def __init__(self, dtype, normalize=False,
                 mean=(123.68, 116.28, 103.53), std=(58.40, 57.12, 57.38)):
        super().__init__()
        self._dtype = dtype
        self._normalize = normalize
        self._mean = np.asarray(mean, np.float32)
        self._std = np.asarray(std, np.float32)

    def forward(self, x):
        from incubator_mxnet_tpu.ndarray import _apply
        import jax.numpy as jnp
        dt, norm = self._dtype, self._normalize
        mean, std = self._mean, self._std

        def fn(a):
            a = a.astype(jnp.float32)
            if norm:
                a = (a - mean) / std          # NHWC: broadcasts over C
            return a.astype(dt)

        return _apply(fn, [x], name="cast_norm")


def _ensure_bench_rec(n, size):
    """Synthetic indexed .rec of n JPEGs at size x size (cached on disk:
    encoding hundreds of JPEGs on the 1-core box is slow)."""
    from incubator_mxnet_tpu import recordio
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_rec")
    os.makedirs(d, exist_ok=True)
    rec = os.path.join(d, f"train_{size}_{n}.rec")
    idx = os.path.join(d, f"train_{size}_{n}.idx")
    if os.path.exists(rec) and os.path.exists(idx):
        return rec
    _log(f"building synthetic record file: {n} JPEGs @ {size}px")
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        img = rng.randint(0, 256, (size, size, 3), np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 1000), i, 0), img, quality=90))
    w.close()
    return rec


def _io_slow_transform():
    """BENCH_IO_SLOW_MS: injected per-batch decode latency (a sleep in
    the decode pool's transform hook) — the smoke's stand-in for an
    expensive augment/parse, so a CPU box can demonstrate that the pool
    hides decode wall behind compute. Returns (transform|None, ms)."""
    ms = float(os.environ.get("BENCH_IO_SLOW_MS", "0") or 0)
    if ms <= 0:
        return None, 0.0

    def slow(x, y, _s=ms / 1e3):
        time.sleep(_s)
        return x, y
    return slow, ms


def _io_extra(workers, depth, slow_ms=0.0):
    """extra.io: the ingest pipeline's geometry + per-stage walls, read
    from the io.* counter family (trace_check's check_io_extra
    validates the shape; docs/io.md explains reading the split)."""
    from incubator_mxnet_tpu import profiler as prof
    c = prof.counters()

    def ms(k):
        return round(float(c.get(f"io/io.{k}", 0.0)), 3)

    io = {"workers": int(workers), "depth": int(depth),
          "batches_prefetched": int(c.get("io/io.batches_prefetched", 0)),
          "wait_ms": ms("wait_ms"), "read_ms": ms("read_ms"),
          "decode_ms": ms("decode_ms"), "stage_ms": ms("stage_ms"),
          "put_ms": ms("put_ms")}
    if c.get("io/io.batches_skipped"):
        io["batches_skipped"] = int(c["io/io.batches_skipped"])
    if c.get("io/io.records_read"):
        io["records_read"] = int(c["io/io.records_read"])
    if slow_ms:
        io["slow_ms"] = float(slow_ms)
    return io


def _record_data_bench(mode, batch, steps, dtype):
    """BENCH_DATA=record | record_cached: ResNet-50 trained from the real
    JPEG input path instead of synthetic tensors.

    record        — ImageRecordIter decodes+augments on native engine
                    threads with a bounded prefetch queue; the queue runs
                    ahead of the chip, so host decode overlaps device
                    compute.
    record_cached — decode ONCE into a host uint8 cache (the reference's
                    im2rec pre-resize moves work offline the same way),
                    then ship raw uint8 slices; normalize on device.
    Reports the data-path rate and end-to-end rate, and names the
    bottleneck."""
    import incubator_mxnet_tpu.io as mio
    size = int(os.environ.get("BENCH_IMG_SIZE", "224"))
    n_img = int(os.environ.get("BENCH_REC_IMAGES", str(max(4 * batch, 512))))
    rec = _ensure_bench_rec(n_img, size)

    core = get_model("resnet50_v1", classes=1000, layout="NHWC")
    net = gluon.nn.HybridSequential()
    net.add(_CastNorm(dtype, normalize=(mode == "record_cached")))
    net.add(core)
    net.initialize(init=mx.init.Xavier())
    if dtype == "bfloat16":
        core.cast("bfloat16")
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9,
                              wd=1e-4, multi_precision=(dtype == "bfloat16"))
    from incubator_mxnet_tpu.autotune import knobs as _knobs
    _kc = _knobs.KnobConfig.from_env()
    step = FusedTrainStep(net, L, opt, remat=_kc.remat,
                          remat_policy=_kc.remat_policy)

    threads = int(os.environ.get("BENCH_DECODE_THREADS", "4"))
    def make_iter():
        return mio.ImageRecordIter(
            path_imgrec=rec, data_shape=(3, size, size), batch_size=batch,
            shuffle=True, rand_mirror=True, layout="NHWC",
            preprocess_threads=threads, prefetch_buffer=8,
            mean_r=123.68, mean_g=116.28, mean_b=103.53,
            std_r=58.40, std_g=57.12, std_b=57.38)

    if mode == "record_cached":
        # one decode pass builds the uint8 cache; augment = mirror flip on
        # the cached tensor (cheap), normalization happens on device
        _log("building uint8 cache (one decode pass)")
        from incubator_mxnet_tpu.image import imdecode
        from incubator_mxnet_tpu.recordio import MXIndexedRecordIO, unpack
        r = MXIndexedRecordIO(rec[:-4] + ".idx", rec, "r")
        cache = np.empty((len(r.keys), size, size, 3), np.uint8)
        labels = np.empty((len(r.keys),), np.float32)
        for j, k in enumerate(r.keys):
            h, img = unpack(r.read_idx(k))
            cache[j] = imdecode(img, to_rgb=True).asnumpy()
            labels[j] = h.label if np.isscalar(h.label) else h.label[0]
        rng = np.random.RandomState(0)

        def batches():
            while True:
                sel = rng.randint(0, len(cache), batch)
                xb = cache[sel]
                if rng.rand() < 0.5:
                    xb = xb[:, :, ::-1]        # mirror augment on cache
                yield nd.array(np.ascontiguousarray(xb)), nd.array(labels[sel])
        gen = batches()
        next_batch = lambda: next(gen)           # noqa: E731
    else:
        it = [make_iter()]

        def next_batch():
            try:
                b = it[0].next()
            except StopIteration:
                it[0].reset()
                b = it[0].next()
            return b.data[0], b.label[0]

    # data-path-only rate (no chip work): how fast can the host feed?
    probe_steps = max(4, min(steps, 8))
    next_batch()                                  # spin up threads
    t0 = time.time()
    for _ in range(probe_steps):
        xb, yb = next_batch()
    np.asarray(xb.asnumpy()[:1])                  # materialize
    data_rate = batch * probe_steps / (time.time() - t0)

    _log("compiling fused train step (record path)")
    xb, yb = next_batch()
    from incubator_mxnet_tpu import profiler as prof
    trace_path, compile_s, warmup_s = _profiled_compile_warmup(
        lambda: float(step(xb, yb)),
        lambda: float(step(*next_batch())))

    _log(f"timing {steps} end-to-end steps @ batch {batch} ({mode})")
    # strict mode audits THIS steady loop too (extra.mxlint must never
    # claim a clean audit for dispatches that were not guarded)
    from incubator_mxnet_tpu.mxlint import runtime as _mxa_mod
    strict_aud = _mxa_mod.auditor()
    if strict_aud is not None:
        strict_aud.mark_warmup_done()
    budget = _perfscope_budget()
    ds_win = _devicescope_window(steps)
    t0 = time.time()
    with prof.record_function("bench.steady", "bench", sync=False):
        for _i in range(steps):
            td = time.perf_counter()
            nb = next_batch()
            loss = _strict_guarded(strict_aud, lambda: step(*nb))
            disp_s = time.perf_counter() - td
            if budget is not None:
                budget.add_dispatch(disp_s)
            if ds_win is not None:
                ds_win.step(1, dispatch_ms=disp_s * 1e3,
                            sync=lambda: float(loss), workload="train")
            _memscope_mark(_i + 1)
        loss_val = float(loss)                    # host fetch = barrier
    dt = time.time() - t0
    if ds_win is not None:
        ds_win.stop()
    e2e = batch * steps / dt
    bottleneck = ("input-bound (decode/host)" if data_rate < 1.2 * e2e
                  else "chip-bound")
    result = {
        "metric": "resnet50_imagenet_images_per_sec_per_chip",
        "value": round(e2e, 2),
        "unit": "images/sec",
        "vs_baseline": round(e2e / V100_BASELINE_IMG_S, 3),
        "extra": {"model": f"resnet50_{mode}", "batch": batch,
                  "dtype": dtype, "steps": steps,
                  "mfu": round(_mfu(e2e, RESNET50_FLOPS_PER_SAMPLE,
                                    dtype), 6),
                  "data_path_img_s": round(data_rate, 2),
                  "bottleneck": bottleneck,
                  "decode_threads": threads,
                  "final_loss": round(loss_val, 4),
                  "device": str(jax.devices()[0])},
    }
    from incubator_mxnet_tpu.mxlint import runtime as _mxa_mod
    result["extra"]["mxlint"] = _mxa_mod.bench_extra()
    # record-path probe includes next_batch(): the synchronized step is
    # the end-to-end unit here (decode overlap is what the mode measures)
    _perfscope_settle(result, budget, steps, dt,
                      lambda: float(step(*next_batch())), steps_per_call=1,
                      flops_per_step=RESNET50_FLOPS_PER_SAMPLE * batch,
                      dtype=dtype)
    _finish_profile(result, trace_path, compile_s=compile_s,
                    warmup_s=warmup_s, steady_s=dt,
                    step_ms=dt / steps * 1e3)
    return result


def _ensure_token_rec(n, seq, vocab):
    """Synthetic indexed .rec of n int32 token sequences (cached on
    disk beside the JPEG benches' records). Each record is one packed
    (seq,) int32 row — the LM analogue of the JPEG file."""
    from incubator_mxnet_tpu import recordio
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_rec")
    os.makedirs(d, exist_ok=True)
    rec = os.path.join(d, f"tokens_{seq}_{n}.rec")
    idx = os.path.join(d, f"tokens_{seq}_{n}.idx")
    if os.path.exists(rec) and os.path.exists(idx):
        return rec
    _log(f"building synthetic token record file: {n} rows @ seq {seq}")
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        toks = rng.randint(0, vocab, (seq,)).astype(np.int32)
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, 0.0, i, 0), toks.tobytes()))
    w.close()
    return rec


def _token_record_bench(batch, steps, dtype):
    """BENCH_DATA=record x BENCH_MODEL=transformer_lm: causal-LM
    training fed from the indexed record path through the staged ingest
    pipeline (ShardedRecordReader → DevicePrefetcher) instead of
    synthetic tensors — token rows unpack on the reader thread, batches
    assemble and run the optional transform in the decode pool, and the
    transfer stage lands them on device. The LM twin of
    _record_data_bench; reports the same data-path vs end-to-end split
    plus extra.io stage walls."""
    from incubator_mxnet_tpu.io.pipeline import ShardedRecordReader
    from incubator_mxnet_tpu.io.prefetch import DevicePrefetcher
    from incubator_mxnet_tpu.recordio import unpack
    net, L, x, _y, flops_per_sample, tag = _build_transformer_lm(batch,
                                                                 dtype)
    seq = int(x.shape[1])
    vocab = 50257
    n_rec = int(os.environ.get("BENCH_REC_IMAGES", str(max(4 * batch,
                                                           256))))
    rec = _ensure_token_rec(n_rec, seq, vocab)
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9,
                              wd=1e-4,
                              multi_precision=(dtype == "bfloat16"))
    from incubator_mxnet_tpu.autotune import knobs as _knobs
    _kc = _knobs.KnobConfig.from_env()
    step = FusedTrainStep(net, L, opt, remat=_kc.remat,
                          remat_policy=_kc.remat_policy)

    def decode_row(payload):
        _h, s = unpack(payload)
        return np.frombuffer(s, np.int32).reshape(seq)

    reader = ShardedRecordReader(rec[:-4] + ".idx", rec,
                                 decode_fn=decode_row)

    def batches():
        it = iter(reader)
        while True:
            rows = []
            while len(rows) < batch:
                try:
                    rows.append(next(it))
                except StopIteration:
                    reader.reset()
                    it = iter(reader)
            xb = np.stack(rows)
            yield xb, xb       # causal LM: the loss shifts internally

    io_tf, io_slow_ms = _io_slow_transform()
    pf = DevicePrefetcher(batches(), depth=_kc.prefetch_depth,
                          workers=_kc.io_workers, transform=io_tf)

    # data-path-only rate: how fast can the sharded reader + pool feed?
    probe_steps = max(4, min(steps, 8))
    next(pf)                                      # spin up the stages
    t0 = time.time()
    for _ in range(probe_steps):
        xb, yb = next(pf)
    np.asarray(xb)[:1]                            # materialize
    data_rate = batch * probe_steps / (time.time() - t0)

    _log("compiling fused train step (token record path)")
    xb, yb = next(pf)
    from incubator_mxnet_tpu import profiler as prof
    trace_path, compile_s, warmup_s = _profiled_compile_warmup(
        lambda: float(step(nd.NDArray(xb), nd.NDArray(yb))),
        lambda: float(step(*map(nd.NDArray, next(pf)))))

    _log(f"timing {steps} end-to-end steps @ batch {batch} "
         f"(token record)")
    from incubator_mxnet_tpu.mxlint import runtime as _mxa_mod
    strict_aud = _mxa_mod.auditor()
    if strict_aud is not None:
        strict_aud.mark_warmup_done()
    budget = _perfscope_budget()
    ds_win = _devicescope_window(steps)
    t0 = time.time()
    with prof.record_function("bench.steady", "bench", sync=False):
        for _i in range(steps):
            td = time.perf_counter()
            nb = tuple(map(nd.NDArray, next(pf)))
            loss = _strict_guarded(strict_aud, lambda: step(*nb))
            disp_s = time.perf_counter() - td
            if budget is not None:
                budget.add_dispatch(disp_s)
            if ds_win is not None:
                ds_win.step(1, dispatch_ms=disp_s * 1e3,
                            sync=lambda: float(loss), workload="train")
            _memscope_mark(_i + 1)
        loss_val = float(loss)                    # host fetch = barrier
    dt = time.time() - t0
    if ds_win is not None:
        ds_win.stop()
    e2e = batch * steps / dt
    bottleneck = ("input-bound (read/decode host path)"
                  if data_rate < 1.2 * e2e else "chip-bound")
    result = {
        "metric": f"{tag}_samples_per_sec_per_chip",
        "value": round(e2e, 2),
        "unit": "samples/sec",
        "vs_baseline": None,
        "extra": {"model": f"{tag}_record", "batch": batch,
                  "dtype": dtype, "steps": steps,
                  "mfu": round(_mfu(e2e, flops_per_sample, dtype), 6),
                  "data_path_samples_s": round(data_rate, 2),
                  "bottleneck": bottleneck,
                  "final_loss": round(loss_val, 4),
                  "device": str(jax.devices()[0])},
    }
    result["extra"]["io"] = _io_extra(pf._workers, _kc.prefetch_depth,
                                      slow_ms=io_slow_ms)
    result["extra"]["mxlint"] = _mxa_mod.bench_extra()
    _perfscope_settle(result, budget, steps, dt,
                      lambda: float(step(*map(nd.NDArray, next(pf)))),
                      steps_per_call=1,
                      flops_per_step=flops_per_sample * batch,
                      dtype=dtype)
    _finish_profile(result, trace_path, compile_s=compile_s,
                    warmup_s=warmup_s, steady_s=dt,
                    step_ms=dt / steps * 1e3)
    pf.close()
    return result


def _ensure_recsys_rec(n, cfg):
    """Synthetic indexed .rec of n recsys rows (cached beside the other
    benches' records). Each record is one packed float32 row:
    dense features + float-encoded zipf ids + label."""
    from incubator_mxnet_tpu import recordio
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_rec")
    os.makedirs(d, exist_ok=True)
    stem = (f"recsys_{cfg['dense']}_{cfg['tables']}x{cfg['bag']}"
            f"_{cfg['vocab']}_{n}")
    rec = os.path.join(d, stem + ".rec")
    idx = os.path.join(d, stem + ".idx")
    if os.path.exists(rec) and os.path.exists(idx):
        return rec
    _log(f"building synthetic recsys record file: {n} rows")
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        row = _recsys_row(rng, cfg).astype(np.float32)
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, 0.0, i, 0), row.tobytes()))
    w.close()
    return rec


def _recsys_bench(batch, steps, dtype, shard_mode):
    """BENCH_MODEL=recsys: DLRM training fed from the indexed record
    path through the staged ingest pipeline (ShardedRecordReader →
    DevicePrefetcher) — the categorical stream the embedding subsystem
    exists for. Reports extra.embedding (table census: per-device vs
    replicated bytes, dedup rate, rows touched/step — schema:
    tools/trace_check.py check_embedding_extra) on top of the io/
    sharding/perfscope sections the other record benches carry."""
    from incubator_mxnet_tpu.io.pipeline import ShardedRecordReader
    from incubator_mxnet_tpu.io.prefetch import DevicePrefetcher
    from incubator_mxnet_tpu.recordio import unpack
    from incubator_mxnet_tpu import embedding as _embmod
    from incubator_mxnet_tpu.models.dlrm import dlrm_bytes_per_sample
    cfg = _recsys_config()
    net, L, x, _y, flops_per_sample, tag = _build_recsys(batch, dtype)
    row_len = cfg["dense"] + cfg["tables"] * cfg["bag"] + 1
    n_rec = int(os.environ.get("BENCH_REC_IMAGES", str(max(4 * batch,
                                                           256))))
    rec = _ensure_recsys_rec(n_rec, cfg)
    opt = mx.optimizer.create(
        os.environ.get("BENCH_RECSYS_OPT", "rowsparseadagrad"),
        learning_rate=float(os.environ.get("BENCH_LR", "0.05")))
    from incubator_mxnet_tpu.autotune import knobs as _knobs
    _kc = _knobs.KnobConfig.from_env()
    step = FusedTrainStep(net, L, opt, remat=_kc.remat,
                          remat_policy=_kc.remat_policy,
                          sharding=shard_mode)

    def decode_row(payload):
        _h, s = unpack(payload)
        return np.frombuffer(s, np.float32).reshape(row_len)

    reader = ShardedRecordReader(rec[:-4] + ".idx", rec,
                                 decode_fn=decode_row)

    def batches():
        it = iter(reader)
        while True:
            rows = []
            while len(rows) < batch:
                try:
                    rows.append(next(it))
                except StopIteration:
                    reader.reset()
                    it = iter(reader)
            m = np.stack(rows)
            yield m[:, :-1], m[:, -1]

    io_tf, io_slow_ms = _io_slow_transform()
    pf = DevicePrefetcher(batches(), depth=_kc.prefetch_depth,
                          workers=_kc.io_workers, transform=io_tf)

    # data-path-only rate: how fast can the sharded reader + pool feed?
    probe_steps = max(4, min(steps, 8))
    next(pf)                                      # spin up the stages
    t0 = time.time()
    for _ in range(probe_steps):
        xb, yb = next(pf)
    np.asarray(xb)[:1]                            # materialize
    data_rate = batch * probe_steps / (time.time() - t0)

    _log("compiling fused train step (recsys record path)")
    xb, yb = next(pf)
    from incubator_mxnet_tpu import profiler as prof
    first_loss = []
    trace_path, compile_s, warmup_s = _profiled_compile_warmup(
        lambda: (first_loss.append(float(step(nd.NDArray(xb),
                                              nd.NDArray(yb))))
                 or first_loss[0]),
        lambda: float(step(*map(nd.NDArray, next(pf)))))

    _log(f"timing {steps} end-to-end steps @ batch {batch} (recsys)")
    from incubator_mxnet_tpu.mxlint import runtime as _mxa_mod
    strict_aud = _mxa_mod.auditor()
    if strict_aud is not None:
        strict_aud.mark_warmup_done()
    budget = _perfscope_budget()
    ds_win = _devicescope_window(steps)
    t0 = time.time()
    with prof.record_function("bench.steady", "bench", sync=False):
        for _i in range(steps):
            td = time.perf_counter()
            raw_x, raw_y = next(pf)
            # host-side id accounting: the concrete batch is already in
            # hand, so the dedup-rate gauges cost one np.unique
            _embmod.observe_batch(
                np.asarray(raw_x)[:, cfg["dense"]:], cfg["vocab"])
            nb = (nd.NDArray(raw_x), nd.NDArray(raw_y))
            loss = _strict_guarded(strict_aud, lambda: step(*nb))
            disp_s = time.perf_counter() - td
            if budget is not None:
                budget.add_dispatch(disp_s)
            if ds_win is not None:
                ds_win.step(1, dispatch_ms=disp_s * 1e3,
                            sync=lambda: float(loss), workload="train")
            _memscope_mark(_i + 1)
        loss_val = float(loss)                    # host fetch = barrier
    dt = time.time() - t0
    if ds_win is not None:
        ds_win.stop()
    e2e = batch * steps / dt
    bottleneck = ("input-bound (read/decode host path)"
                  if data_rate < 1.2 * e2e else "chip-bound")
    result = {
        "metric": f"{tag}_samples_per_sec_per_chip",
        "value": round(e2e, 2),
        "unit": "samples/sec",
        "vs_baseline": None,
        "extra": {"model": f"{tag}_record", "batch": batch,
                  "dtype": dtype, "steps": steps,
                  "mfu": round(_mfu(e2e, flops_per_sample, dtype), 6),
                  "data_path_samples_s": round(data_rate, 2),
                  "bottleneck": bottleneck,
                  "first_loss": round(first_loss[0], 4),
                  "final_loss": round(loss_val, 4),
                  "device": str(jax.devices()[0])},
    }
    emb_extra = _embmod.bench_extra()
    emb_extra["bytes_per_sample"] = round(dlrm_bytes_per_sample(
        net, emb_extra.get("dedup_rate") or 0.0), 3)
    result["extra"]["embedding"] = emb_extra
    if shard_mode is not None:
        from incubator_mxnet_tpu.parallel import sharding as _shmod
        result["extra"]["sharding"] = _shmod.summary()
    result["extra"]["io"] = _io_extra(pf._workers, _kc.prefetch_depth,
                                      slow_ms=io_slow_ms)
    result["extra"]["mxlint"] = _mxa_mod.bench_extra()
    _perfscope_settle(result, budget, steps, dt,
                      lambda: float(step(*map(nd.NDArray, next(pf)))),
                      steps_per_call=1,
                      flops_per_step=flops_per_sample * batch,
                      dtype=dtype)
    _finish_profile(result, trace_path, compile_s=compile_s,
                    warmup_s=warmup_s, steady_s=dt,
                    step_ms=dt / steps * 1e3)
    pf.close()
    return result


def main():
    global _CURRENT_METRIC
    _main_t0 = time.time()
    model = os.environ.get("BENCH_MODEL", "resnet50")
    if model not in _BENCH_MODELS and model != "serving":
        raise ValueError(f"unknown BENCH_MODEL {model!r}; choose from "
                         f"{sorted(_BENCH_MODELS) + ['serving']}")
    try:
        default_batch = DEFAULT_BATCH[model]
    except KeyError:
        raise ValueError(f"BENCH_MODEL {model!r} has no default batch; "
                         f"set BENCH_BATCH explicitly")
    from incubator_mxnet_tpu.autotune import knobs as _knobs
    batch = int(_knobs.resolve("batch")[0] or default_batch)
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")

    watchdog = _arm_hard_watchdog(
        int(os.environ.get("BENCH_HARD_TIMEOUT", "3300")))
    # a wedged relay hangs INSIDE the first device call (C code — the
    # SIGALRM per-attempt deadline never fires), so a shorter thread-based
    # watchdog covers init specifically; cancelled once the chip answers.
    # Default rides just above acquire_backend's worst legitimate span
    # (attempts * per-attempt timeout + backoff), so it only fires when
    # the retry loop itself is frozen in C.
    _init_attempts, _init_per = 6, 180
    _init_default = _init_attempts * _init_per + 200
    init_watchdog = _arm_hard_watchdog(
        int(os.environ.get("BENCH_INIT_TIMEOUT", str(_init_default))),
        "backend init")
    _preflight_probe()
    try:
        acquire_backend(attempts=_init_attempts,
                        per_attempt_timeout=_init_per)
    except RuntimeError as e:
        # exhausted retries: an unusable backend is an environment
        # verdict, not a 0 img/s perf number
        print(json.dumps(_env_failure_result(e)), flush=True)
        sys.exit(2)
    init_watchdog.cancel()
    # persistent-cache integrity canary (runtime/cache_guard): validate
    # the cache READ path now — before the big compile — so a corrupt
    # cache recompiles fresh instead of training on garbage executables
    from incubator_mxnet_tpu.runtime import cache_guard as _cg
    _log(f"compile-cache canary ok={_cg.check()}")
    # Front-load the one-time pallas on-device self-test (tiny compiles)
    # under its own deadline, so a Mosaic failure surfaces HERE as a logged
    # fallback to the XLA path — not mid-way through the big model compile.
    from incubator_mxnet_tpu.ops import pallas as _pallas
    _pallas.register_selftest_passthrough(_PhaseTimeout)
    try:
        with _phase_deadline(int(os.environ.get("BENCH_PALLAS_TIMEOUT",
                                                "600")),
                             "pallas self-test"):
            _log(f"pallas kernels enabled={_pallas.enabled()} "
                 f"(on-device self-test verdict={_pallas._KERNELS_OK})")
    except _PhaseTimeout as e:
        # treat a hung self-test as a failed one: XLA path from here on
        _pallas._KERNELS_OK = False
        os.environ["MXTPU_NO_PALLAS"] = "1"
        _log(f"pallas self-test timed out ({e}); using the XLA path")
    # before model build so parameter allocations land in the ledger
    diag_dir = _bench_diag_start()
    if diag_dir:
        _log(f"diagnostics armed (sampler + flight recorder) -> {diag_dir}")
    if _bench_healthmon_start() is not None:
        _log("healthmon armed (watchdogs + structured event log)")
    if _bench_perfscope_start() is not None:
        _log("perfscope armed (roofline cost capture + step decomposition)")
    if _bench_commscope_start() is not None:
        _log("commscope armed (collective inventory + resharding detector)")
    if _bench_devicescope_start() is not None:
        _log("devicescope armed (windowed device-timeline capture)")
    if _bench_memscope_start() is not None:
        _log("memscope armed (program footprints + watermark ring + "
             "OOM forensics)")
    strict_aud = _bench_strict_start()
    if strict_aud is not None:
        _log("mxlint strict mode armed (host-sync + recompile + "
             "donation auditing)")
    # MXTPU_AUTOTUNE=1: resolve the tuning cache / run the bounded
    # search BEFORE the mesh registers and the knobs resolve below —
    # the winner installs as the below-env default layer, so everything
    # from loop_chunk to the mesh spec starts tuned on a cache hit
    autotune_extra = None
    if model != "serving":
        autotune_extra = _bench_autotune(model, batch, dtype)
    # BENCH_MESH: register the global mesh BEFORE model build so param
    # init and the executor resolve against it
    shard_mode = _bench_mesh()
    np.random.seed(0)
    mx.random.seed(0)

    _CURRENT_METRIC = ("resnet50_imagenet_images_per_sec_per_chip"
                       if model == "resnet50"
                       else f"bench_{model}_samples_per_sec_per_chip")
    if model == "serving":
        _CURRENT_METRIC = (
            f"serving_{os.environ.get('BENCH_SERVING_MODEL', 'lenet')}"
            f"_requests_per_sec")
        result = _serving_bench()
        watchdog.cancel()
        print(json.dumps(result))
        return
    if model == "recsys":
        # the recsys family ALWAYS trains from the record stream (the
        # categorical input path is the workload); BENCH_DATA does not
        # apply
        result = _recsys_bench(batch, steps, dtype, shard_mode)
        if autotune_extra is not None:
            autotune_extra["resolved"] = \
                _knobs.KnobConfig.from_env().to_dict()
            result.setdefault("extra", {})["autotune"] = autotune_extra
        watchdog.cancel()
        print(json.dumps(result))
        return
    data_mode = os.environ.get("BENCH_DATA", "synthetic")
    if data_mode in ("record", "record_cached"):
        if model == "transformer_lm":
            if data_mode != "record":
                raise ValueError(
                    "BENCH_DATA=record_cached is a JPEG-path mode; "
                    "transformer_lm's token path supports "
                    "BENCH_DATA=record only")
            result = _token_record_bench(batch, steps, dtype)
        elif model == "resnet50":
            result = _record_data_bench(data_mode, batch, steps, dtype)
        else:
            raise ValueError(
                f"BENCH_DATA={data_mode} supports BENCH_MODEL=resnet50 "
                f"(the JPEG input path) or transformer_lm (the token "
                f"record path), got {model!r}")
        if autotune_extra is not None:
            autotune_extra["resolved"] = \
                _knobs.KnobConfig.from_env().to_dict()
            result.setdefault("extra", {})["autotune"] = autotune_extra
        watchdog.cancel()
        print(json.dumps(result))
        return

    # builders can do real device work (SSD runs a full forward to
    # precompute matching targets) — deadline it like every device phase
    with _phase_deadline(int(os.environ.get("BENCH_BUILD_TIMEOUT", "1200")),
                         "model build"):
        net, L, x, y, flops_per_sample, tag = _BENCH_MODELS[model](batch,
                                                                   dtype)
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9, wd=1e-4,
                              multi_precision=(dtype == "bfloat16"))
    # knob resolution through the ONE table (autotune.knobs): call-site
    # > BENCH_* > MXTPU_* > cached tuning winner > default. loop_chunk
    # > 1 runs the steady phase through the whole-loop executor
    # (mxtpu.trainloop) — N micro-steps per dispatch, device-side
    # double-buffered prefetch, per-micro-step lr; the io.*/trainloop.*
    # counter families land in extra.counters.
    knob_cfg = _knobs.KnobConfig.from_env()
    if autotune_extra is not None:
        # what the run ACTUALLY resolved to (env overrides beat the
        # tuner) — the config perf_regress compares across artifacts
        autotune_extra["resolved"] = knob_cfg.to_dict()
    loop_k = knob_cfg.loop_chunk
    loop = None
    io_tf, io_slow_ms = _io_slow_transform()
    if loop_k > 1:
        from incubator_mxnet_tpu.trainloop import TrainLoop
        loop = TrainLoop(net, L, opt, chunk=loop_k,
                         remat=knob_cfg.remat,
                         remat_policy=knob_cfg.remat_policy,
                         sharding=shard_mode,
                         io_workers=knob_cfg.io_workers,
                         io_transform=io_tf)
        step = loop.step
    else:
        step = FusedTrainStep(net, L, opt,
                              remat=knob_cfg.remat,
                              remat_policy=knob_cfg.remat_policy,
                              sharding=shard_mode)
    if shard_mode is not None:
        from incubator_mxnet_tpu.parallel import sharding as _shmod
        dp_ax = _shmod.data_axis(step.mesh) or "dp"
        dp_n = int(step.mesh.shape.get(dp_ax, 1))
        if batch % dp_n:
            raise ValueError(
                f"BENCH_BATCH={batch} does not divide the {dp_ax}={dp_n} "
                f"mesh axis (BENCH_MESH={os.environ['BENCH_MESH']}); "
                f"pick a divisible global batch")

    _bench_resilience_start(step)

    # compile + warmup. NOTE: through the axon relay block_until_ready() does
    # not synchronize; a host value fetch is the only true barrier. Steps
    # chain through updated params, so fetching the final loss times them all.
    # In loop mode the CHUNK program is the only one the steady phase runs,
    # so it is the one compiled/warmed (the single-step program is never
    # built — jax.jit is lazy).
    from incubator_mxnet_tpu import profiler as prof
    if loop is not None:
        import jax.numpy as jnp
        loop_xs = jnp.broadcast_to(x._data, (loop_k,) + x._data.shape)
        loop_ys = jnp.broadcast_to(y._data, (loop_k,) + y._data.shape)
        _log(f"compiling whole-loop chunk (k={loop_k})")
        trace_path, compile_s, warmup_s = _profiled_compile_warmup(
            lambda: float(loop.run_chunk(loop_xs, loop_ys)[loop_k - 1]),
            lambda: float(loop.run_chunk(loop_xs, loop_ys)[loop_k - 1]))
    else:
        _log("compiling fused train step (first call)")
        trace_path, compile_s, warmup_s = _profiled_compile_warmup(
            lambda: float(step(x, y)),
            lambda: float(step(x, y)))
    if strict_aud is not None:
        # everything compiled so far was warmup; from here a re-capture
        # of a known program is a steady-state recompile finding
        strict_aud.mark_warmup_done()

    # BENCH_K > 1: dispatch k micro-steps as ONE XLA program (lax.scan in
    # FusedTrainStep.run_k) — amortizes per-step relay/host dispatch
    # latency. Default 1 since the r05 on-chip sweep MEASURED the k
    # hypothesis and refuted it: k=1 2064 img/s vs k=8 2015 img/s at the
    # same config (PERF.md) — the 62 ms step is device-bound, not
    # dispatch-bound, so the scan only adds compile surface.
    k = int(os.environ.get("BENCH_K", "1"))
    if loop is not None:
        chunks = max(1, steps // loop_k)
        _log(f"timing {chunks} chunks x {loop_k} micro-steps through the "
             f"whole-loop executor @ batch {batch} {dtype} "
             f"(in_program_lr={loop.in_program_lr})")

        def batches():
            while True:
                yield x, y

        budget = _perfscope_budget(steps_per_dispatch=loop_k)
        # loop mode: run_chunk marks the active devicescope window itself
        # (it knows one dispatch was loop_k steps), so no per-step marks
        ds_win = _devicescope_window(chunks * loop_k,
                                     steps_per_dispatch=loop_k)
        with loop._prefetcher(batches(), cycle=False) as pf:
            t0 = time.time()
            with prof.record_function("bench.steady", "bench", sync=False):
                for _ in range(chunks):
                    xb, yb = next(pf)
                    losses = _strict_guarded(
                        strict_aud, lambda: loop.run_chunk(xb, yb))
                    _healthmon_mark_step()   # one mark per dispatched chunk
                    _resilience_mark_step()
                loss_val = float(losses[loop_k - 1])    # host fetch = barrier
            dt = time.time() - t0
        if ds_win is not None:
            ds_win.stop()
        steps = chunks * loop_k
        k = loop_k
        # loop-mode host_gap rides trainloop.dispatch_ms (run_chunk's own
        # counter), so no per-dispatch timing is needed here
        probe_fn = lambda: float(loop.run_chunk(loop_xs,        # noqa: E731
                                                loop_ys)[loop_k - 1])
    elif k > 1:
        import jax.numpy as jnp
        xs = jnp.broadcast_to(x._data, (k,) + x._data.shape)
        ys = jnp.broadcast_to(y._data, (k,) + y._data.shape)
        _log(f"compiling k-step scan (k={k})")
        with _phase_deadline(int(os.environ.get("BENCH_COMPILE_TIMEOUT",
                                                "2400")),
                             "k-step compile"):
            float(step.run_k(xs, ys)[k - 1])        # compile + warmup
        chunks = max(1, steps // k)
        _log(f"timing {chunks} chunks x {k} micro-steps @ batch {batch} "
             f"{dtype}")
        budget = _perfscope_budget(steps_per_dispatch=k)
        ds_win = _devicescope_window(chunks * k, steps_per_dispatch=k)
        t0 = time.time()
        with prof.record_function("bench.steady", "bench", sync=False):
            for _i in range(chunks):
                td = time.perf_counter()
                losses = _strict_guarded(strict_aud,
                                         lambda: step.run_k(xs, ys))
                disp_s = time.perf_counter() - td
                if budget is not None:
                    budget.add_dispatch(disp_s)
                if ds_win is not None:
                    # sync thunk = loss fetch, the one true barrier: a
                    # window closing at this mark must not close with
                    # its own steps still in flight (async dispatch)
                    ds_win.step(k, dispatch_ms=disp_s * 1e3,
                                sync=lambda: float(losses[k - 1]),
                                workload="train")
                _memscope_mark((_i + 1) * k)
                _healthmon_mark_step()     # one mark per dispatched chunk
                _resilience_mark_step()
            loss_val = float(losses[k - 1])         # host fetch = barrier
        dt = time.time() - t0
        if ds_win is not None:
            ds_win.stop()
        steps = chunks * k
        probe_fn = lambda: float(step.run_k(xs, ys)[k - 1])  # noqa: E731
    else:
        _log(f"timing {steps} steps @ batch {batch} {dtype}")
        budget = _perfscope_budget()
        ds_win = _devicescope_window(steps)
        t0 = time.time()
        with prof.record_function("bench.steady", "bench", sync=False):
            for _i in range(steps):
                td = time.perf_counter()
                loss = _strict_guarded(strict_aud, lambda: step(x, y))
                disp_s = time.perf_counter() - td
                if budget is not None:
                    budget.add_dispatch(disp_s)
                if ds_win is not None:
                    # see run_k path: the sync fetch only runs at the
                    # window boundary, so the other steps stay async
                    ds_win.step(1, dispatch_ms=disp_s * 1e3,
                                sync=lambda: float(loss),
                                workload="train")
                _memscope_mark(_i + 1)
                _healthmon_mark_step()
                _resilience_mark_step()
            loss_val = float(loss)
        dt = time.time() - t0
        if ds_win is not None:
            ds_win.stop()
        probe_fn = lambda: float(step(x, y))         # noqa: E731
    from incubator_mxnet_tpu import healthmon as _hm_mod
    if _hm_mod._HM is not None:
        # final-loss NaN sentinel: the one host value the bench fetched
        _hm_mod.observe_loss(loss_val)

    img_s = batch * steps / dt
    mfu = _mfu(img_s, flops_per_sample, dtype)

    watchdog.cancel()
    # keep the headline metric name stable across rounds for the driver
    metric = ("resnet50_imagenet_images_per_sec_per_chip"
              if model == "resnet50" else f"{tag}_samples_per_sec_per_chip")
    _CURRENT_METRIC = metric
    result = {
        "metric": metric,
        "value": round(img_s, 2),
        "unit": "images/sec" if model == "resnet50" else "samples/sec",
        # the V100 390 img/s baseline is a ResNet-50 number; other models
        # report MFU instead of a cross-model ratio
        "vs_baseline": (round(img_s / V100_BASELINE_IMG_S, 3)
                        if model == "resnet50" else None),
        "extra": {"model": tag, "batch": batch, "dtype": dtype,
                  "steps": steps, "k_per_dispatch": k,
                  "mfu": round(mfu, 6),
                  "loop_chunk": loop_k if loop is not None else None,
                  "in_program_lr": (loop.in_program_lr
                                    if loop is not None else None),
                  "k1_control_img_s": None,
                  "final_loss": round(loss_val, 4),
                  "device": str(jax.devices()[0])},
    }
    if loop is not None:
        # the ingest pipeline ran the steady phase (loop mode is the
        # only synthetic path with a prefetcher) — its stage walls are
        # the starvation-attribution record the smoke compares
        result["extra"]["io"] = _io_extra(loop.io_workers,
                                          loop.prefetch_depth,
                                          slow_ms=io_slow_ms)
    if shard_mode is not None:
        # the resolved layout the executor actually compiled: mesh shape,
        # per-param spec counts, fsdp on/off, per-device bytes
        from incubator_mxnet_tpu.parallel import sharding as _shmod
        result["extra"]["sharding"] = _shmod.summary()
    if autotune_extra is not None:
        # the tuning outcome (cache hit/miss, trials, winner, pruning
        # reasons, score provenance) — validated by trace_check's
        # check_autotune_extra in every training BENCH json
        result["extra"]["autotune"] = autotune_extra
    # strict-mode verdicts (or the {"strict": false} shape — uniform
    # schema, like extra.autotune); check_mxlint_extra validates it
    from incubator_mxnet_tpu.mxlint import runtime as _mxa_mod
    result["extra"]["mxlint"] = _mxa_mod.bench_extra()
    _perfscope_settle(result, budget, steps, dt, probe_fn,
                      steps_per_call=k,
                      flops_per_step=flops_per_sample * batch, dtype=dtype)
    _finish_profile(result, trace_path, compile_s=compile_s,
                    warmup_s=warmup_s, steady_s=dt,
                    step_ms=dt / steps * 1e3)
    # Self-check of the dispatch-latency hypothesis behind the K default:
    # time the ALREADY-COMPILED per-step path alongside, so every K>1
    # report carries its own k=1 control (the blind bet must measure
    # itself). Runs AFTER the headline is fully built, behind a hard
    # thread watchdog that emits the MAIN result and exits cleanly —
    # SIGALRM can't interrupt a C-level relay hang, and the control must
    # never destroy an already-measured number. BENCH_K1_CONTROL=0 skips.
    # (loop mode skips the control: its single-step program was never
    # compiled, so the control would time a fresh compile, not dispatch)
    if k > 1 and loop is None \
            and os.environ.get("BENCH_K1_CONTROL", "1") == "1":
        import threading

        # single-emit: Timer.cancel() can't stop an in-flight callback, so
        # both emit paths take this lock — never two (or half-written)
        # result lines on stdout
        _emit_lock = threading.Lock()
        _emitted = [False]

        def _emit_result():
            with _emit_lock:
                if _emitted[0]:
                    return
                _emitted[0] = True
                print(json.dumps(result), flush=True)

        def _emit_and_exit():
            _log("k=1 control hung; emitting main result without it")
            _emit_result()
            os._exit(0)

        # the guard must fit inside whatever outer budget sized the hard
        # watchdog (perf_sweep kills the subprocess at 3600 s) — never let
        # startup + main run + control exceed the hard-watchdog horizon
        elapsed = time.time() - _main_t0
        hard = int(os.environ.get("BENCH_HARD_TIMEOUT", "3300"))
        guard_s = min(int(os.environ.get("BENCH_K1_TIMEOUT", "300")),
                      max(15, int(hard - elapsed)))
        guard = threading.Timer(guard_s, _emit_and_exit)
        guard.daemon = True
        guard.start()
        try:
            n1 = max(4, min(10, steps // 2))
            t1 = time.time()
            for _ in range(n1):
                loss1 = step(x, y)
            float(loss1)
            k1_img_s = batch * n1 / (time.time() - t1)
            result["extra"]["k1_control_img_s"] = round(k1_img_s, 2)
            _log(f"k=1 control: {k1_img_s:.1f} img/s over {n1} steps "
                 f"(k={k} main run: {img_s:.1f})")
        except Exception as e:  # noqa: BLE001
            # an erroring control must not destroy the measured headline
            _log(f"k=1 control failed ({type(e).__name__}: {e}); "
                 "reporting main result without it")
        finally:
            guard.cancel()
        _emit_result()
    else:
        print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001
        import traceback
        traceback.print_exc()
        # Emit a parseable JSON line even on failure so the driver records
        # a diagnostic instead of a bare rc=1.
        print(json.dumps({
            "metric": _CURRENT_METRIC,
            "value": 0.0,
            "unit": "images/sec",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:500],
        }))
        sys.exit(1)
