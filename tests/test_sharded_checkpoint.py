"""Sharded checkpoint/resume of the fused trainer (parallel/checkpoint.py).

The resume gold standard: save mid-training, restore into a freshly
built step in another object, continue — losses must match the
uninterrupted run exactly. Sharded (ZeRO-1 over dp) state restores to
the same shardings without a gather.
"""
import numpy as np
import pytest

import jax

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.parallel import (FusedTrainStep, latest_step,
                                          restore_train_step,
                                          save_train_step)


@pytest.fixture(autouse=True)
def _no_persistent_compile_cache():
    """This jaxlib's CPU backend mis-deserializes persistent-cache
    entries for the fused (donated, sometimes sharded) train step: a
    run that RE-READS executables written by a previous run gets
    garbage numerics (1e19 -> nan losses on the second post-restore
    step; reproducible by running this file twice with
    tests/.jax_test_cache present). Compile fresh in this module."""
    from jax._src import compilation_cache as cc
    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    cc.reset_cache()           # drop the already-initialized cache object
    yield                      # (the config flip alone is not re-read)
    jax.config.update("jax_enable_compilation_cache", old)
    cc.reset_cache()


def _net():
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(8),
            nn.BatchNorm(), nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    return net


def _step(mesh=None, **kw):
    return FusedTrainStep(_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                          mx.optimizer.create("adam", learning_rate=1e-2),
                          mesh=mesh, **kw)


def _data(seed=0, batch=8):
    rng = np.random.RandomState(seed)
    return (nd.array(rng.randn(batch, 8).astype(np.float32)),
            nd.array(rng.randint(0, 4, batch)))


def _losses(step, n, seed0=10):
    out = []
    for i in range(n):
        x, y = _data(seed=seed0 + i)
        out.append(float(step(x, y)))
    return out


def test_save_restore_resume_matches_uninterrupted(tmp_path):
    gold = _step()
    pre = _losses(gold, 3)
    resumed_ref = _losses(gold, 4)

    run = _step()
    assert _losses(run, 3) == pre
    save_train_step(str(tmp_path), run)
    # poison: keep training past the save point
    _losses(run, 2, seed0=99)

    fresh = _step()
    x, y = _data(seed=0)
    fresh(x, y)                            # build/compile (junk update)
    n = restore_train_step(str(tmp_path), fresh)
    assert n == 3
    np.testing.assert_allclose(_losses(fresh, 4), resumed_ref, rtol=1e-6)


def test_sharded_fsdp_roundtrip_preserves_shardings_cpu(tmp_path):
    """The MIGRATED zero1 coverage (ISSUE 8 satellite): the seed-era
    test ran the ZeRO-1 sharded adam step in-process and SEGFAULTED
    XLA:CPU on this jaxlib's 8-virtual-device host platform — a crash
    that killed the runner and ~130 downstream tests, so it was
    skip-listed. The scenario now runs on the FSDP path (parallel/fsdp
    — params AND adam state sharded over dp, superset of zero1) in a
    SUBPROCESS with its own 4-fake-device backend: the segfault is no
    longer reproducible there (verified repeatedly while building PR 8;
    docs/sharding.md records the investigation), and if it ever
    recurs it fails THIS test instead of truncating the tier-1 run.

    Asserts, from the worker's JSON: sharded save/restore round trip
    restores the update counter, preserves every optimizer-state
    leaf's NamedSharding (no gather onto one host), and resumes
    BIT-exactly with the uninterrupted run."""
    import json
    import os
    import subprocess
    import sys

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "shard_matrix_worker.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)   # the worker pins its own 4-device config
    proc = subprocess.run([sys.executable, worker, "fsdp4", "--ckpt"],
                          capture_output=True, text=True, timeout=300,
                          env=env)
    assert proc.returncode == 0, \
        (f"fsdp checkpoint worker rc={proc.returncode} (a negative rc "
         f"would be the zero1 segfault resurfacing):\n"
         f"{proc.stderr[-2000:]}")
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    assert doc["summary"]["fsdp"] and doc["summary"]["params_data_sharded"]
    ck = doc["ckpt"]
    assert ck["restored_step"] == 6
    assert ck["shardings_preserved"], \
        "optimizer-state shardings changed across save/restore"
    assert ck["resume_exact"], \
        f"resumed tail {ck['resumed_tail']} != gold {ck['gold_tail']}"


def test_latest_step_and_multiple_checkpoints(tmp_path):
    step = _step()
    _losses(step, 1)
    save_train_step(str(tmp_path), step)
    _losses(step, 2)
    save_train_step(str(tmp_path), step)
    assert latest_step(str(tmp_path)) == 3
    fresh = _step()
    x, y = _data(seed=0)
    fresh(x, y)
    assert restore_train_step(str(tmp_path), fresh, step_num=1) == 1
    assert restore_train_step(str(tmp_path), fresh) == 3


def test_unbuilt_step_raises(tmp_path):
    step = _step()
    with pytest.raises(ValueError, match="not built"):
        save_train_step(str(tmp_path), step)
    assert latest_step(str(tmp_path)) is None
    built = _step()
    x, y = _data()
    built(x, y)
    with pytest.raises(FileNotFoundError):
        restore_train_step(str(tmp_path / "empty"), built)


def test_stochastic_net_resumes_exactly(tmp_path):
    """Dropout masks come from the framework RNG key — the checkpoint
    carries it, so resumed losses match the uninterrupted run even for
    stochastic nets."""
    def dropnet():
        mx.random.seed(7)
        np.random.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dropout(0.5),
                nn.Dense(4))
        net.initialize(init=mx.init.Xavier())
        return net

    def mkstep():
        return FusedTrainStep(dropnet(),
                              gluon.loss.SoftmaxCrossEntropyLoss(),
                              mx.optimizer.create("sgd",
                                                  learning_rate=1e-2))

    mx.random.seed(123)
    gold = mkstep()
    _losses(gold, 3)
    ref = _losses(gold, 4)

    mx.random.seed(123)
    run = mkstep()
    _losses(run, 3)
    save_train_step(str(tmp_path), run)

    mx.random.seed(999)  # a fresh process would have a different key
    fresh = mkstep()
    x, y = _data(seed=0)
    fresh(x, y)
    restore_train_step(str(tmp_path), fresh)
    np.testing.assert_allclose(_losses(fresh, 4), ref, rtol=1e-6)
