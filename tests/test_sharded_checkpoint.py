"""Sharded checkpoint/resume of the fused trainer (parallel/checkpoint.py).

The resume gold standard: save mid-training, restore into a freshly
built step in another object, continue — losses must match the
uninterrupted run exactly. Sharded (ZeRO-1 over dp) state restores to
the same shardings without a gather.
"""
import numpy as np
import pytest

import jax

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.parallel import (FusedTrainStep, latest_step,
                                          make_mesh, restore_train_step,
                                          save_train_step)


@pytest.fixture(autouse=True)
def _no_persistent_compile_cache():
    """This jaxlib's CPU backend mis-deserializes persistent-cache
    entries for the fused (donated, sometimes sharded) train step: a
    run that RE-READS executables written by a previous run gets
    garbage numerics (1e19 -> nan losses on the second post-restore
    step; reproducible by running this file twice with
    tests/.jax_test_cache present). Compile fresh in this module."""
    from jax._src import compilation_cache as cc
    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    cc.reset_cache()           # drop the already-initialized cache object
    yield                      # (the config flip alone is not re-read)
    jax.config.update("jax_enable_compilation_cache", old)
    cc.reset_cache()


def _net():
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(8),
            nn.BatchNorm(), nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    return net


def _step(mesh=None, **kw):
    return FusedTrainStep(_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                          mx.optimizer.create("adam", learning_rate=1e-2),
                          mesh=mesh, **kw)


def _data(seed=0, batch=8):
    rng = np.random.RandomState(seed)
    return (nd.array(rng.randn(batch, 8).astype(np.float32)),
            nd.array(rng.randint(0, 4, batch)))


def _losses(step, n, seed0=10):
    out = []
    for i in range(n):
        x, y = _data(seed=seed0 + i)
        out.append(float(step(x, y)))
    return out


def test_save_restore_resume_matches_uninterrupted(tmp_path):
    gold = _step()
    pre = _losses(gold, 3)
    resumed_ref = _losses(gold, 4)

    run = _step()
    assert _losses(run, 3) == pre
    save_train_step(str(tmp_path), run)
    # poison: keep training past the save point
    _losses(run, 2, seed0=99)

    fresh = _step()
    x, y = _data(seed=0)
    fresh(x, y)                            # build/compile (junk update)
    n = restore_train_step(str(tmp_path), fresh)
    assert n == 3
    np.testing.assert_allclose(_losses(fresh, 4), resumed_ref, rtol=1e-6)


@pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="XLA:CPU SEGFAULTS (not fails — kills the interpreter, and "
           "with it the rest of the tier-1 run, ~130 downstream tests) "
           "while executing the ZeRO-1 sharded optimizer step on this "
           "jaxlib's 8-virtual-device host platform; the coverage runs "
           "on real TPU meshes")
def test_sharded_zero1_roundtrip_preserves_shardings(tmp_path):
    mesh = make_mesh({"dp": 8})
    step = _step(mesh=mesh, shard_optimizer_states=True)
    _losses(step, 2)
    live_shardings = [getattr(s, "sharding", None)
                      for s in jax.tree_util.tree_leaves(step._states)]
    save_train_step(str(tmp_path), step)

    fresh = _step(mesh=mesh, shard_optimizer_states=True)
    x, y = _data(seed=0)
    fresh(x, y)
    restore_train_step(str(tmp_path), fresh)
    for live, back in zip(live_shardings,
                          jax.tree_util.tree_leaves(fresh._states)):
        if live is not None:
            assert back.sharding == live
    # resumed losses equal the unsharded gold run (dp math is exact)
    gold = _step()
    _losses(gold, 2)
    np.testing.assert_allclose(_losses(fresh, 3), _losses(gold, 3),
                               rtol=1e-5, atol=1e-6)


def test_latest_step_and_multiple_checkpoints(tmp_path):
    step = _step()
    _losses(step, 1)
    save_train_step(str(tmp_path), step)
    _losses(step, 2)
    save_train_step(str(tmp_path), step)
    assert latest_step(str(tmp_path)) == 3
    fresh = _step()
    x, y = _data(seed=0)
    fresh(x, y)
    assert restore_train_step(str(tmp_path), fresh, step_num=1) == 1
    assert restore_train_step(str(tmp_path), fresh) == 3


def test_unbuilt_step_raises(tmp_path):
    step = _step()
    with pytest.raises(ValueError, match="not built"):
        save_train_step(str(tmp_path), step)
    assert latest_step(str(tmp_path)) is None
    built = _step()
    x, y = _data()
    built(x, y)
    with pytest.raises(FileNotFoundError):
        restore_train_step(str(tmp_path / "empty"), built)


def test_stochastic_net_resumes_exactly(tmp_path):
    """Dropout masks come from the framework RNG key — the checkpoint
    carries it, so resumed losses match the uninterrupted run even for
    stochastic nets."""
    def dropnet():
        mx.random.seed(7)
        np.random.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dropout(0.5),
                nn.Dense(4))
        net.initialize(init=mx.init.Xavier())
        return net

    def mkstep():
        return FusedTrainStep(dropnet(),
                              gluon.loss.SoftmaxCrossEntropyLoss(),
                              mx.optimizer.create("sgd",
                                                  learning_rate=1e-2))

    mx.random.seed(123)
    gold = mkstep()
    _losses(gold, 3)
    ref = _losses(gold, 4)

    mx.random.seed(123)
    run = mkstep()
    _losses(run, 3)
    save_train_step(str(tmp_path), run)

    mx.random.seed(999)  # a fresh process would have a different key
    fresh = mkstep()
    x, y = _data(seed=0)
    fresh(x, y)
    restore_train_step(str(tmp_path), fresh)
    np.testing.assert_allclose(_losses(fresh, 4), ref, rtol=1e-6)
