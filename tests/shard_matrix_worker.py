"""Subprocess worker for the CPU-mesh tier-1 matrix (tests/test_sharding.py).

Runs a small fixed-seed MLP train run under one (dp, mp) layout on 4
FAKE host devices (--xla_force_host_platform_device_count=4 — set HERE,
before jax import, so the test process's own 8-device config can't
leak in) and prints one JSON line with bit-exact losses (float.hex),
the resolved per-param specs/shard shapes, per-device byte accounting
and the diagnostics ledger census. The parent compares layouts against
the single-device run — pod-scale layouts verified on every CPU CI run.

Usage: python shard_matrix_worker.py single|dp4|dp2mp2|fsdp4 [--ckpt]

--ckpt additionally exercises the sharded checkpoint path (the
migration target of the skip-listed zero1 XLA:CPU segfault test):
save mid-run, restore into a fresh step, and verify the resumed losses
and restored state shardings in-process.
"""
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
# isolate from the suite's persistent compile cache (the PR 4 lesson:
# donated/sharded executables re-read from cache can deserialize wrong)
os.environ.setdefault("JAX_ENABLE_COMPILATION_CACHE", "false")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon, nd  # noqa: E402
from incubator_mxnet_tpu.gluon import nn  # noqa: E402
from incubator_mxnet_tpu.parallel import (FusedTrainStep, fsdp, make_mesh,  # noqa: E402
                                          set_mesh, sharding)

STEPS = 6
BATCH = 16


def _net():
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"),
            nn.Dense(16, activation="relu"),
            nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    return net


def _data(seed):
    rng = np.random.RandomState(seed)
    return (nd.array(rng.randn(BATCH, 8).astype(np.float32)),
            nd.array(rng.randint(0, 4, BATCH)))


def _build_step(layout, opt="sgd"):
    if layout == "single":
        mode = None
    elif layout == "dp4":
        set_mesh(make_mesh({"dp": 4}))
        mode = "dp"
    elif layout == "dp2mp2":
        set_mesh(make_mesh({"dp": 2, "mp": 2}))
        mode = "auto"
    elif layout == "fsdp4":
        set_mesh(make_mesh({"dp": -1}))
        mode = "fsdp"
    else:
        raise SystemExit(f"unknown layout {layout!r}")
    return FusedTrainStep(_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                          mx.optimizer.create(opt, learning_rate=1e-2
                                              if opt == "adam" else 0.1),
                          sharding=mode)


def _losses(step, n, seed0=100):
    out = []
    for i in range(n):
        x, y = _data(seed0 + i)
        out.append(float(step(x, y)))
    return out


def main():
    layout = sys.argv[1]
    ckpt = "--ckpt" in sys.argv[2:]
    # ckpt mode trains with adam so SHARDED optimizer state (the zero1
    # scenario that segfaulted XLA:CPU at seed) rides through orbax
    step = _build_step(layout, opt="adam" if ckpt else "sgd")
    losses = _losses(step, STEPS)

    result = {
        "layout": layout,
        "devices": len(jax.devices()),
        # float.hex round-trips exactly — the parent's parity check is
        # BIT-level, not a tolerance
        "losses_hex": [float(v).hex() for v in losses],
        "losses": losses,
        "specs": {p.name: str(getattr(p.data()._data.sharding, "spec",
                                      "single_device"))
                  for p in step.params},
        "shard0_shapes": {
            p.name: list(next(iter(p.data()._data.addressable_shards))
                         .data.shape)
            for p in step.params},
        "report": fsdp.memory_report(step),
        "summary": sharding.summary(),
    }
    from incubator_mxnet_tpu.diagnostics import memory as dmem
    rec = dmem.reconcile()
    result["per_device_live_bytes"] = rec.get("per_device_live_bytes")

    if ckpt:
        import tempfile
        from incubator_mxnet_tpu.parallel import (restore_train_step,
                                                  save_train_step)
        with tempfile.TemporaryDirectory() as tmp:
            live_sh = [str(getattr(s, "sharding", None))
                       for s in jax.tree_util.tree_leaves(step._states)]
            save_train_step(tmp, step)
            gold_tail = _losses(step, 3, seed0=200)   # uninterrupted
            fresh = _build_step(layout, opt="adam")
            x, y = _data(0)
            fresh(x, y)                               # build (junk update)
            n = restore_train_step(tmp, fresh)
            back_sh = [str(getattr(s, "sharding", None))
                       for s in jax.tree_util.tree_leaves(fresh._states)]
            resumed_tail = _losses(fresh, 3, seed0=200)
            result["ckpt"] = {
                "restored_step": n,
                "shardings_preserved": live_sh == back_sh,
                "resume_exact": [float(v).hex() for v in resumed_tail]
                                == [float(v).hex() for v in gold_tail],
                "gold_tail": gold_tail,
                "resumed_tail": resumed_tail,
            }

    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
