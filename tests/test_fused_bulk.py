"""Bulk-fused dispatch (ISSUE 2): multi-tensor optimizer apply parity and
real engine.bulk deferred segments.

Fused apply contract: Trainer groups params by (rule, dtype) and runs each
group's updates in ONE jitted call — bit-identical to per-param update(),
including multi_precision and AMP skip. engine.bulk contract: deferred
segments flush on size/exit/read/backward/step with imperative semantics
preserved, and steady-state segments hit the compile cache.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import bulk, engine, gluon, nd
from incubator_mxnet_tpu import optimizer as opt
from incubator_mxnet_tpu import profiler as prof


def _ctr(name):
    return prof.counters().get(name, 0)


# ---------------------------------------------------------------------------
# fused multi-tensor apply: bit-exact parity vs per-param update()
# ---------------------------------------------------------------------------

DENSE_RULES = [
    ("sgd", dict(learning_rate=0.1)),
    ("sgd", dict(learning_rate=0.1, momentum=0.9, wd=0.01)),
    ("nag", dict(learning_rate=0.1, momentum=0.9)),
    ("signum", dict(learning_rate=0.05, momentum=0.9, wd_lh=0.01)),
    ("adam", dict(learning_rate=0.01, wd=0.01)),
    ("adamw", dict(learning_rate=0.01, wd=0.1)),
    ("adagrad", dict(learning_rate=0.1)),
    ("adadelta", dict(rho=0.9)),
    ("rmsprop", dict(learning_rate=0.01)),
    ("rmsprop", dict(learning_rate=0.01, centered=True)),
    ("ftrl", dict(learning_rate=0.1, lamda1=0.001)),
    ("lamb", dict(learning_rate=0.01, wd=0.01)),
    ("lars", dict(learning_rate=0.01, wd=0.001)),
    ("adamax", dict(learning_rate=0.002)),
    ("nadam", dict(learning_rate=0.001)),
    ("ftml", dict(learning_rate=0.0025)),
    ("dcasgd", dict(learning_rate=0.01, momentum=0.9)),
]

_SHAPES = [(3, 2), (5,), (2, 2, 2), (4, 3)]


def _tensors(seed=0, dtype="float32"):
    rng = np.random.RandomState(seed)
    ws = [rng.randn(*s).astype(np.float32) for s in _SHAPES]
    gsteps = [[rng.randn(*s).astype(np.float32) for s in _SHAPES]
              for _ in range(3)]
    if dtype != "float32":
        ws = [nd.array(w).astype(dtype).asnumpy() for w in ws]
    return ws, gsteps


def _mk(name, kwargs, ws, dtype="float32", **extra):
    o = opt.create(name, **dict(kwargs, **extra))
    W = [nd.array(w).astype(dtype) for w in ws]
    S = [o.create_state_multi_precision(i, W[i]._data)
         for i in range(len(W))]
    return o, W, S


def _assert_same(Wa, Sa, Wb, Sb):
    for a, b in zip(Wa, Wb):
        np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())
    la = jax.tree_util.tree_leaves(Sa)
    lb = jax.tree_util.tree_leaves(Sb)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("name,kwargs", DENSE_RULES,
                         ids=[f"{n}-{i}" for i, (n, _) in
                              enumerate(DENSE_RULES)])
def test_fused_update_bit_exact(name, kwargs):
    ws, gsteps = _tensors()
    o_u, W_u, S_u = _mk(name, kwargs, ws)
    o_f, W_f, S_f = _mk(name, kwargs, ws)
    assert o_f.supports_fused()
    idxs = list(range(len(ws)))
    for gs in gsteps:
        for i in idxs:
            S_u[i] = o_u.update(i, W_u[i], nd.array(gs[i]), S_u[i])
        S_f = o_f.fused_update(idxs, W_f, [nd.array(g) for g in gs], S_f)
    _assert_same(W_u, S_u, W_f, S_f)
    # per-param bookkeeping advanced identically
    assert o_u._index_update_count == o_f._index_update_count
    assert o_u.num_update == o_f.num_update


@pytest.mark.parametrize("name", ["sgd", "adam"])
def test_fused_update_clip_rescale_parity(name):
    kw = dict(learning_rate=0.1, rescale_grad=0.5, clip_gradient=0.4)
    ws, gsteps = _tensors(seed=7)
    o_u, W_u, S_u = _mk(name, kw, ws)
    o_f, W_f, S_f = _mk(name, kw, ws)
    idxs = list(range(len(ws)))
    for gs in gsteps:
        for i in idxs:
            S_u[i] = o_u.update(i, W_u[i], nd.array(gs[i]), S_u[i])
        S_f = o_f.fused_update(idxs, W_f, [nd.array(g) for g in gs], S_f)
    _assert_same(W_u, S_u, W_f, S_f)


@pytest.mark.parametrize("name", ["sgd", "adam", "lamb"])
def test_fused_update_multi_precision_parity(name):
    """bf16 weights + float32 master copies through the fused path."""
    ws, gsteps = _tensors(seed=3, dtype="bfloat16")
    kw = dict(learning_rate=0.01, multi_precision=True)
    o_u, W_u, S_u = _mk(name, kw, ws, dtype="bfloat16")
    o_f, W_f, S_f = _mk(name, kw, ws, dtype="bfloat16")
    assert S_u[0][0].dtype == jnp.float32   # master weights exist
    idxs = list(range(len(ws)))
    for gs in gsteps:
        gnds_u = [nd.array(g).astype("bfloat16") for g in gs]
        gnds_f = [nd.array(g).astype("bfloat16") for g in gs]
        for i in idxs:
            S_u[i] = o_u.update(i, W_u[i], gnds_u[i], S_u[i])
        S_f = o_f.fused_update(idxs, W_f, gnds_f, S_f)
    _assert_same(W_u, S_u, W_f, S_f)


@pytest.mark.parametrize("skip_val", [False, True])
def test_fused_update_amp_skip_parity(skip_val):
    """AMP found-inf `skip` select: both paths keep/skip identically; with
    skip=True the weights and states are untouched."""
    ws, gsteps = _tensors(seed=5)
    skip = jnp.asarray(skip_val)
    o_u, W_u, S_u = _mk("adam", dict(learning_rate=0.01), ws)
    o_f, W_f, S_f = _mk("adam", dict(learning_rate=0.01), ws)
    idxs = list(range(len(ws)))
    for gs in gsteps:
        for i in idxs:
            S_u[i] = o_u.update(i, W_u[i], nd.array(gs[i]), S_u[i],
                                skip=skip)
        S_f = o_f.fused_update(idxs, W_f, [nd.array(g) for g in gs], S_f,
                               skip=skip)
    _assert_same(W_u, S_u, W_f, S_f)
    if skip_val:
        for w0, w in zip(ws, W_f):
            np.testing.assert_array_equal(w.asnumpy(), w0)


def test_sgld_does_not_support_fused():
    # SGLD overrides the eager entry (host RNG per call) -> per-param path
    assert not opt.create("sgld").supports_fused()
    assert opt.create("sgd").supports_fused()


def test_fused_group_compile_cached():
    """Same (shapes, dtypes) group on later steps reuses the jitted fused
    step (hit/miss counters from PR 1)."""
    ws, gsteps = _tensors(seed=11)
    o, W, S = _mk("sgd", dict(learning_rate=0.1), ws)
    idxs = list(range(len(ws)))
    miss0 = _ctr("optimizer/jit.cache_miss")
    hit0 = _ctr("optimizer/jit.cache_hit")
    for gs in gsteps:
        S = o.fused_update(idxs, W, [nd.array(g) for g in gs], S)
    assert _ctr("optimizer/jit.cache_miss") - miss0 == 1
    assert _ctr("optimizer/jit.cache_hit") - hit0 == len(gsteps) - 1


# ---------------------------------------------------------------------------
# Trainer integration: grouping, dispatch counts, fallbacks
# ---------------------------------------------------------------------------

def _mlp(n_layers, width=4, seed=0):
    net = gluon.nn.HybridSequential()
    for _ in range(n_layers):
        net.add(gluon.nn.Dense(width, in_units=width))
    net.initialize(init=mx.init.Xavier())
    rng = np.random.RandomState(seed)
    for p in net.collect_params().values():
        p.set_data(nd.array(rng.randn(*p.shape).astype(np.float32)))
    return net


def _backward(net, width=4, seed=1):
    x = nd.array(np.random.RandomState(seed).randn(2, width)
                 .astype(np.float32))
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()


def test_trainer_fused_matches_unfused():
    net_a, net_b = _mlp(4, seed=2), _mlp(4, seed=2)
    tr_a = gluon.Trainer(net_a.collect_params(), "adam",
                         {"learning_rate": 0.01}, fused_update=False)
    tr_b = gluon.Trainer(net_b.collect_params(), "adam",
                         {"learning_rate": 0.01}, fused_update=True)
    for step in range(3):
        _backward(net_a, seed=step)
        _backward(net_b, seed=step)
        tr_a.step(2)
        tr_b.step(2)
    for pa, pb in zip(net_a.collect_params().values(),
                      net_b.collect_params().values()):
        np.testing.assert_array_equal(pa.data().asnumpy(),
                                      pb.data().asnumpy())


def test_dispatches_per_step_50_params():
    """Acceptance: a 50-param model goes from >=50 optimizer dispatches
    per step to <= #(rule,dtype) groups (here 1) with fused_update."""
    net = _mlp(25)   # 25 x (weight, bias) = 50 params
    params = net.collect_params()
    assert len([p for p in params.values() if p.grad_req != "null"]) == 50

    tr_u = gluon.Trainer(params, "sgd", {"learning_rate": 0.0},
                         fused_update=False)
    _backward(net)
    tr_u.step(1)
    assert _ctr("mxtpu/trainer.dispatches_per_step") == 50
    assert _ctr("mxtpu/optimizer.fused_groups") == 0

    tr_f = gluon.Trainer(params, "sgd", {"learning_rate": 0.0},
                         fused_update=True)
    _backward(net)
    tr_f.step(1)
    assert _ctr("mxtpu/trainer.dispatches_per_step") == 1
    assert _ctr("mxtpu/optimizer.fused_groups") == 1


def test_trainer_groups_by_dtype():
    """Mixed f32/bf16 params fuse into one group per dtype."""
    net32, net16 = _mlp(2, seed=4), _mlp(2, seed=5)
    net16.cast("bfloat16")
    _backward(net32, seed=0)
    x16 = nd.array(np.random.RandomState(0).randn(2, 4)
                   .astype(np.float32)).astype("bfloat16")
    with mx.autograd.record():
        loss = (net16(x16) ** 2).sum()
    loss.backward()
    params = (list(net32.collect_params().values())
              + list(net16.collect_params().values()))
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.01},
                       fused_update=True)
    tr.step(1)
    assert _ctr("mxtpu/optimizer.fused_groups") == 2
    assert _ctr("mxtpu/trainer.dispatches_per_step") == 2


def test_trainer_sgld_falls_back_per_param():
    net = _mlp(3)
    tr = gluon.Trainer(net.collect_params(), "sgld",
                       {"learning_rate": 0.01}, fused_update=True)
    _backward(net)
    tr.step(1)   # supports_fused() False -> per-param path
    assert _ctr("mxtpu/trainer.dispatches_per_step") == 6
    assert _ctr("mxtpu/optimizer.fused_groups") == 0


def test_trainer_sparse_grad_falls_back_per_param():
    """RowSparse grads keep the lazy-row per-param path next to a fused
    dense group."""
    emb = gluon.nn.Embedding(10, 4, sparse_grad=True)
    dense = gluon.nn.Dense(2, in_units=4)
    emb.initialize()
    dense.initialize()
    x = nd.array(np.array([[1, 2], [3, 4]], np.int32))
    with mx.autograd.record():
        loss = (dense(emb(x).reshape((2, -1))[:, :4]) ** 2).sum()
    loss.backward()
    from incubator_mxnet_tpu.ndarray import sparse as _sparse
    params = (list(emb.collect_params().values())
              + list(dense.collect_params().values()))
    assert isinstance(params[0].grad(), _sparse.RowSparseNDArray)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       fused_update=True)
    tr.step(1)
    # 1 sparse per-param dispatch + 1 fused dense group
    assert _ctr("mxtpu/trainer.dispatches_per_step") == 2
    assert _ctr("mxtpu/optimizer.fused_groups") == 1


def test_fused_update_env_override(monkeypatch):
    monkeypatch.setenv("MXTPU_FUSED_UPDATE", "0")
    net = _mlp(2)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    assert tr._fused_update is False
    monkeypatch.setenv("MXTPU_FUSED_UPDATE", "1")
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    assert tr._fused_update is True


# ---------------------------------------------------------------------------
# engine.bulk deferred segments
# ---------------------------------------------------------------------------

def test_bulk_defers_and_is_bit_exact():
    x = nd.array(np.random.RandomState(0).randn(4, 3).astype(np.float32))
    eager = ((x + 1.5) * 2.0 - x).asnumpy()
    with engine.bulk(10):
        r = (x + 1.5) * 2.0 - x
        assert bulk.pending_ops() == 3
        assert bulk.is_deferred(r._data)
    assert bulk.pending_ops() == 0       # scope exit flushed
    assert not bulk.is_deferred(r._data)
    np.testing.assert_array_equal(r.asnumpy(), eager)


def test_bulk_flush_on_read_midscope():
    x = nd.array(np.arange(6.0, dtype=np.float32))
    with engine.bulk(10):
        y = x * 3.0
        assert bulk.pending_ops() == 1
        reads0 = _ctr("mxtpu/bulk.flush.read")
        got = y.asnumpy()                # read forces the flush
        assert bulk.pending_ops() == 0
        assert _ctr("mxtpu/bulk.flush.read") - reads0 == 1
        np.testing.assert_array_equal(got, np.arange(6.0) * 3)
        z = y + 1.0                      # new segment after the flush
        assert bulk.pending_ops() == 1
    np.testing.assert_array_equal(z.asnumpy(), np.arange(6.0) * 3 + 1)


def test_bulk_flush_on_size():
    x = nd.array(np.ones(3, np.float32))
    size0 = _ctr("mxtpu/bulk.flush.size")
    with engine.bulk(2):
        a = x + 1.0
        b = a + 1.0                      # hits size=2 -> auto flush
        assert bulk.pending_ops() == 0
        assert _ctr("mxtpu/bulk.flush.size") - size0 == 1
        c = b + 1.0
        assert bulk.pending_ops() == 1
    np.testing.assert_array_equal(c.asnumpy(), np.full(3, 4.0))


def test_bulk_flush_on_backward():
    x = nd.array(np.ones((2, 2), np.float32))
    w = nd.array(np.random.RandomState(1).randn(2, 2).astype(np.float32))
    w.attach_grad()
    with engine.bulk(10):
        t = x + 2.0                      # deferred, pending
        assert bulk.pending_ops() == 1
        bwd0 = _ctr("mxtpu/bulk.flush.backward")
        with mx.autograd.record():       # recording ops run eagerly
            loss = (w * w).sum()
        loss.backward()
        assert bulk.pending_ops() == 0
        assert _ctr("mxtpu/bulk.flush.backward") - bwd0 == 1
        np.testing.assert_allclose(w.grad.asnumpy(), 2 * w.asnumpy(),
                                   rtol=1e-6)
    np.testing.assert_array_equal(t.asnumpy(), np.full((2, 2), 3.0))


def test_bulk_segment_compile_cache_reuse():
    """Acceptance: identical segments compile once, then cache-hit."""
    x = nd.array(np.random.RandomState(2).randn(7, 11).astype(np.float32))
    miss0 = _ctr("bulk/jit.cache_miss")
    hit0 = _ctr("bulk/jit.cache_hit")
    for _ in range(4):
        with engine.bulk(10):
            r = (x + 0.25) * 1.5
        r.wait_to_read()
    assert _ctr("bulk/jit.cache_miss") - miss0 == 1
    assert _ctr("bulk/jit.cache_hit") - hit0 == 3
    np.testing.assert_allclose(r.asnumpy(), (x.asnumpy() + 0.25) * 1.5,
                               rtol=1e-6)


def test_bulk_cache_distinguishes_captured_scalars():
    """x+2 and x+3 recreate the same lambda code; captured constants are
    part of the signature so the cache can never serve the wrong one."""
    x = nd.array(np.ones(5, np.float32))
    with engine.bulk(10):
        a = x + 2.0
    with engine.bulk(10):
        b = x + 3.0
    np.testing.assert_array_equal(a.asnumpy(), np.full(5, 3.0))
    np.testing.assert_array_equal(b.asnumpy(), np.full(5, 4.0))


def test_bulk_waitall_flushes():
    x = nd.array(np.ones(4, np.float32))
    with engine.bulk(10):
        y = x * 2.0
        assert bulk.pending_ops() == 1
        nd.waitall()
        assert bulk.pending_ops() == 0
    np.testing.assert_array_equal(y.asnumpy(), np.full(4, 2.0))


def test_bulk_trainer_step_flushes():
    net = _mlp(2)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    x = nd.array(np.ones(3, np.float32))
    step0 = _ctr("mxtpu/bulk.flush.step")
    with engine.bulk(10):
        y = x + 1.0                      # pending segment
        _backward(net)                   # flushes via backward first
        z = y * 2.0                      # re-defer after backward flush
        assert bulk.pending_ops() >= 1
        tr.step(1)
        assert bulk.pending_ops() == 0
        assert _ctr("mxtpu/bulk.flush.step") - step0 == 1
    np.testing.assert_array_equal(z.asnumpy(), np.full(3, 4.0))


def test_auto_bulk_mode():
    prev = engine.set_bulk_size(8)
    try:
        assert engine.bulk_size() == 8
        x = nd.array(np.arange(4.0, dtype=np.float32))
        y = x + 4.0                      # defers without an explicit scope
        assert bulk.pending_ops() == 1
        np.testing.assert_array_equal(y.asnumpy(), np.arange(4.0) + 4)
    finally:
        assert engine.set_bulk_size(prev) == 8
    assert engine.bulk_size() == prev
    z = x + 5.0                          # disabled again: eager
    assert not bulk.is_deferred(z._data)


def test_bulk_nested_scopes():
    x = nd.array(np.ones(2, np.float32))
    with engine.bulk(10):
        a = x + 1.0
        with engine.bulk(5):
            b = a + 1.0
            assert bulk.pending_ops() == 2
        # inner exit flushed everything
        assert bulk.pending_ops() == 0
        c = b + 1.0
        assert bulk.pending_ops() == 1
    np.testing.assert_array_equal(c.asnumpy(), np.full(2, 4.0))


def test_bulk_recording_ops_stay_eager():
    """Ops on the autograd tape need concrete values; inside record() the
    dispatch funnel must not defer."""
    x = nd.array(np.ones((2, 2), np.float32))
    x.attach_grad()
    with engine.bulk(10):
        with mx.autograd.record():
            y = (x * 3.0).sum()
            assert not bulk.is_deferred(y._data)
        y.backward()
    np.testing.assert_array_equal(x.grad.asnumpy(), np.full((2, 2), 3.0))
