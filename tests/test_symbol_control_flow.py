"""Symbolic control flow (sym.contrib.foreach/while_loop/cond — parity:
reference tests/python/unittest/test_contrib_control_flow.py). Lowered to
lax.scan / lax.cond inside the executor's jitted program."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import symbol as sym


def test_foreach_cumsum_with_captured_weight():
    data = sym.Variable("data")
    w = sym.Variable("w")
    init = sym.Variable("s0")

    def body(x, states):
        s = states[0] + x * w
        return s, [s]

    outs, states = sym.contrib.foreach(body, data, [init])
    ex = sym.Group([outs, states[0]]).bind(
        args={"data": np.arange(6, dtype=np.float32).reshape(3, 2),
              "w": np.array([1.0, 2.0], np.float32),
              "s0": np.zeros(2, np.float32)}, grad_req="null")
    res, final = (o.asnumpy() for o in ex.forward())
    ref = np.cumsum(np.arange(6).reshape(3, 2) * [1.0, 2.0], axis=0)
    np.testing.assert_allclose(res, ref)
    np.testing.assert_allclose(final, ref[-1])


def test_foreach_backward_through_scan():
    """Gradient w.r.t. a captured weight flows through the scan."""
    data = sym.Variable("data")
    w = sym.Variable("w")

    def body(x, states):
        s = states[0] + x * w
        return s, [s]

    outs, _ = sym.contrib.foreach(body, data, [sym.Variable("s0")])
    loss = sym.sum(outs)
    ex = loss.bind(args={"data": np.ones((4, 3), np.float32),
                         "w": np.full(3, 2.0, np.float32),
                         "s0": np.zeros(3, np.float32)},
                   args_grad={"w": np.zeros(3, np.float32)},
                   grad_req={"w": "write"})
    ex.forward(is_train=True)
    ex.backward()
    # d/dw sum_t cumsum(x*w): each x_t*w appears in (T-t) partial sums;
    # with x=1, grad per element = sum_{t=1..T} t = 10
    np.testing.assert_allclose(ex.grad_dict["w"].asnumpy(), [10.0] * 3)


def test_while_loop_doubling():
    def cond(lv):
        return sym.broadcast_lesser(lv[0], sym.ones(shape=(1,)) * 100)

    def func(lv):
        nv = lv[0] * 2
        return nv, [nv]

    outs, final = sym.contrib.while_loop(cond, func, [sym.Variable("x0")],
                                         max_iterations=10)
    ex = sym.Group([outs, final[0]]).bind(
        args={"x0": np.array([3.0], np.float32)}, grad_req="null")
    o, f = (t.asnumpy() for t in ex.forward())
    np.testing.assert_allclose(f, [192.0])            # 3 * 2^6
    np.testing.assert_allclose(o.ravel()[:6], [6, 12, 24, 48, 96, 192])
    assert (o.ravel()[6:] == 0).all()                 # padded past stop


def test_cond_branches():
    p = sym.Variable("p")
    a = sym.Variable("a")
    out = sym.contrib.cond(p, lambda: a * 2, lambda: a - 1)
    for pv, want in ((1.0, [10.0]), (0.0, [4.0])):
        ex = out.bind(args={"p": np.array(pv, np.float32),
                            "a": np.array([5.0], np.float32)},
                      grad_req="null")
        np.testing.assert_allclose(ex.forward()[0].asnumpy(), want)


def test_control_flow_tojson_embeds_subgraph_spec():
    """Control-flow graphs serialize: the body is nested as a subgraph
    spec in the node attrs (reference nnvm subgraph-in-json layout), and
    the runner callable itself is dropped from the json."""
    import json
    data = sym.Variable("data")

    def body(x, states):
        return x, [states[0]]

    outs, _ = sym.contrib.foreach(body, data, [sym.Variable("s")])
    d = json.loads(outs.tojson())
    fe = [n for n in d["nodes"] if n["op"] == "_foreach"]
    assert len(fe) == 1
    assert "__subgraph_spec__" in fe[0]["attrs"]
    assert "__subgraph__" not in fe[0]["attrs"]


def test_foreach_multiple_outputs_and_states():
    data = sym.Variable("data")

    def body(x, states):
        s1 = states[0] + x
        s2 = states[1] * 2
        return [x * 2, x + 1], [s1, s2]

    outs, states = sym.contrib.foreach(
        body, data, [sym.Variable("a0"), sym.Variable("b0")])
    ex = sym.Group(outs + states).bind(
        args={"data": np.arange(4, dtype=np.float32).reshape(2, 2),
              "a0": np.zeros(2, np.float32),
              "b0": np.ones(2, np.float32)}, grad_req="null")
    o1, o2, s1, s2 = (t.asnumpy() for t in ex.forward())
    np.testing.assert_allclose(o1, np.arange(4).reshape(2, 2) * 2)
    np.testing.assert_allclose(o2, np.arange(4).reshape(2, 2) + 1)
    np.testing.assert_allclose(s1, [2.0, 4.0])
    np.testing.assert_allclose(s2, [4.0, 4.0])


def test_foreach_single_state_and_multi_data():
    """Reference calling styles: single (non-list) state round-trips as a
    single Symbol; multiple data sequences scan in lockstep."""
    data = sym.Variable("data")

    def body(x, s):                       # s is a Symbol, not a list
        ns = s + x
        return ns, ns

    out, final = sym.contrib.foreach(body, data, sym.Variable("s0"))
    assert isinstance(final, sym.Symbol)
    ex = final.bind(args={"data": np.ones((4, 2), np.float32),
                          "s0": np.zeros(2, np.float32)}, grad_req="null")
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [4.0, 4.0])

    a, b = sym.Variable("a"), sym.Variable("b")

    def body2(xs, s):
        return xs[0] + xs[1], s

    outs2, _ = sym.contrib.foreach(body2, [a, b], sym.Variable("z"))
    ex2 = outs2.bind(args={"a": np.ones((3, 2), np.float32),
                           "b": np.full((3, 2), 2.0, np.float32),
                           "z": np.zeros(2, np.float32)}, grad_req="null")
    np.testing.assert_allclose(ex2.forward()[0].asnumpy(),
                               np.full((3, 2), 3.0))


def test_control_flow_auto_names_unique():
    data = sym.Variable("d")

    def body(x, s):
        return x, s

    o1, _ = sym.contrib.foreach(body, data, sym.Variable("s1"))
    o2, _ = sym.contrib.foreach(body, data, sym.Variable("s2"))
    names = sym.Group([o1, o2]).list_outputs()
    assert names[0] != names[1]


def test_foreach_closed_over_symbol_evaluated_once():
    """A computed outer symbol the body closes over (here a Dropout
    output) is lifted as a loop input: ONE realization, consumed by every
    step — reference subgraph-input semantics."""
    w = sym.Variable("w")
    outer = sym.Dropout(w, p=0.5)
    data = sym.Variable("data")

    def body(x, states):
        return x * outer, states

    outs, _ = sym.contrib.foreach(body, data, [sym.Variable("z")])
    ex = outs.bind(args={"w": np.ones(8, np.float32),
                         "data": np.ones((4, 8), np.float32),
                         "z": np.zeros(8, np.float32)}, grad_req="null")
    r = ex.forward(is_train=True)[0].asnumpy()
    for t in range(1, 4):
        np.testing.assert_array_equal(r[t], r[0])


def test_while_loop_dead_iterations_cannot_nan_gradients():
    """Past termination the body must not execute: sqrt leaves its domain
    at the stopping value, yet value and gradient stay finite (lax.cond
    guards the body instead of masking its outputs)."""
    x0 = sym.Variable("x0")

    def cond(lv):
        return sym.broadcast_lesser(lv[0], sym.ones(shape=(1,)) * 10)

    def func(lv):
        nv = sym.sqrt(sym.ones(shape=(1,)) * 10 - lv[0]) + lv[0] + 3
        return nv, [nv]

    _, final = sym.contrib.while_loop(cond, func, [x0], max_iterations=8)
    loss = sym.sum(final[0])
    ex = loss.bind(args={"x0": np.array([5.0], np.float32)},
                   args_grad={"x0": np.zeros(1, np.float32)},
                   grad_req={"x0": "write"})
    v = ex.forward(is_train=True)[0].asnumpy()
    ex.backward()
    g = ex.grad_dict["x0"].asnumpy()
    assert np.isfinite(v).all() and np.isfinite(g).all()


def test_foreach_variable_declared_inside_body():
    """A sym.Variable created INSIDE the body is lifted as a subgraph
    input (reference lifts body-declared variables too), not executed as
    an op per iteration."""
    data = sym.Variable("data")
    init = sym.Variable("s0")

    def body(x, states):
        w = sym.Variable("w_inner")          # declared inside the body
        s = states[0] + x * w
        return s, [s]

    outs, states = sym.contrib.foreach(body, data, [init])
    ex = sym.Group([outs, states[0]]).bind(
        args={"data": np.arange(6, dtype=np.float32).reshape(3, 2),
              "w_inner": np.array([1.0, 2.0], np.float32),
              "s0": np.zeros(2, np.float32)}, grad_req="null")
    res, final = (o.asnumpy() for o in ex.forward())
    ref = np.cumsum(np.arange(6).reshape(3, 2) * [1.0, 2.0], axis=0)
    np.testing.assert_allclose(res, ref)
    np.testing.assert_allclose(final, ref[-1])


def test_while_loop_reference_calling_convention():
    """cond/func written upstream-style — def f(a, b), called as
    f(*loop_vars) — work alongside this repo's list convention."""
    i0 = sym.Variable("i0")
    acc0 = sym.Variable("acc0")

    def cond(i, acc):
        return sym.broadcast_lesser(i, sym.ones(shape=(1,)) * 4)

    def func(i, acc):
        return i * 10.0, [i + 1.0, acc + i]

    outs, final = sym.contrib.while_loop(cond, func, [i0, acc0],
                                         max_iterations=6)
    ex = sym.Group([outs] + final).bind(
        args={"i0": np.zeros(1, np.float32),
              "acc0": np.zeros(1, np.float32)}, grad_req="null")
    o, fi, facc = (t.asnumpy() for t in ex.forward())
    np.testing.assert_allclose(fi, [4.0])
    np.testing.assert_allclose(facc, [6.0])     # 0+1+2+3
    np.testing.assert_allclose(o.ravel()[:4], [0.0, 10.0, 20.0, 30.0])
    assert (o.ravel()[4:] == 0).all()


# ---------------------------------------------------------------------------
# serialization: control-flow graphs roundtrip through json (reference:
# nnvm nests subgraph json in node attrs, src/operator/subgraph_op_common.cc)
# ---------------------------------------------------------------------------

def test_foreach_json_roundtrip_outputs_and_grads():
    data, w, s0 = sym.Variable("data"), sym.Variable("w"), sym.Variable("s0")

    def body(x, st):
        s = sym.tanh(st[0] + x * w)
        return s, [s]

    outs, states = sym.contrib.foreach(body, data, [s0])
    loss = sym.sum(outs) + sym.sum(states[0])
    loss2 = sym.load_json(loss.tojson())

    args = {"data": np.random.RandomState(0).randn(4, 3).astype(np.float32),
            "w": np.array([0.5, -1.0, 2.0], np.float32),
            "s0": np.zeros(3, np.float32)}

    def run(s):
        ex = s.bind(args=dict(args),
                    args_grad={"w": np.zeros(3, np.float32)},
                    grad_req={"w": "write", "data": "null", "s0": "null"})
        v = ex.forward(is_train=True)[0].asnumpy()
        ex.backward()
        return v, ex.grad_dict["w"].asnumpy()

    v1, g1 = run(loss)
    v2, g2 = run(loss2)
    np.testing.assert_allclose(v2, v1, rtol=1e-6)
    np.testing.assert_allclose(g2, g1, rtol=1e-6)


def test_while_loop_and_cond_json_roundtrip():
    i0 = sym.Variable("i0")
    o, fin = sym.contrib.while_loop(
        lambda v: sym.broadcast_lesser(v, sym.ones(shape=(1,)) * 5),
        lambda v: (v * 2.0, v + 1.0), i0, max_iterations=8)
    g = sym.Group([o, fin])
    g2 = sym.load_json(g.tojson())
    a = {"i0": np.array([0.0], np.float32)}
    r1 = [t.asnumpy() for t in g.bind(args=dict(a),
                                      grad_req="null").forward()]
    r2 = [t.asnumpy() for t in g2.bind(args=dict(a),
                                       grad_req="null").forward()]
    for x, y in zip(r1, r2):
        np.testing.assert_array_equal(x, y)

    p, aa = sym.Variable("p"), sym.Variable("a")
    out = sym.contrib.cond(p, lambda: aa * 2, lambda: aa - 1)
    out2 = sym.load_json(out.tojson())
    for pv in (1.0, 0.0):
        ar = {"p": np.array(pv, np.float32), "a": np.array([3.0],
                                                           np.float32)}
        x = out.bind(args=dict(ar), grad_req="null").forward()[0].asnumpy()
        y = out2.bind(args=dict(ar), grad_req="null").forward()[0].asnumpy()
        np.testing.assert_array_equal(x, y)


def test_nested_foreach_json_roundtrip():
    """Nested control flow serializes recursively (spec inside spec)."""
    data, s0 = sym.Variable("data"), sym.Variable("s0")

    def outer_body(row, st):
        def inner_body(x, ist):
            s = ist[0] + x
            return s, [s]

        inner_outs, _ = sym.contrib.foreach(inner_body, row,
                                            [sym.zeros(shape=(1,))])
        tot = st[0] + sym.sum(inner_outs)
        return tot, [tot]

    outs, states = sym.contrib.foreach(outer_body, data, [s0])
    g = sym.Group([outs, states[0]])
    g2 = sym.load_json(g.tojson())
    a = {"data": np.arange(12, dtype=np.float32).reshape(3, 4, 1),
         "s0": np.zeros(1, np.float32)}
    r1 = [t.asnumpy() for t in g.bind(args=dict(a),
                                      grad_req="null").forward()]
    r2 = [t.asnumpy() for t in g2.bind(args=dict(a),
                                       grad_req="null").forward()]
    for x, y in zip(r1, r2):
        np.testing.assert_array_equal(x, y)
    np.testing.assert_allclose(r1[1].ravel(), [150.0])
