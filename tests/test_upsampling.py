"""UpSampling: nearest + bilinear (parity: src/operator/nn/upsampling.cc —
bilinear = fixed-weight Deconvolution with the mx.init.Bilinear kernel,
kernel 2s-s%2, stride s, pad ceil((s-1)/2))."""
import math

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def _bilinear_kernel(k):
    f = math.ceil(k / 2.0)
    c = (2 * f - 1 - f % 2) / (2.0 * f)
    w1 = 1 - np.abs(np.arange(k) / f - c)
    return np.outer(w1, w1).astype(np.float32)


def _ref_bilinear_deconv(x, s):
    """Independent NumPy transposed-conv reference: kernel 2s-s%2,
    stride s, pad ceil((s-1)/2), per channel."""
    n, ch, h, w = x.shape
    k = 2 * s - s % 2
    p = int(math.ceil((s - 1) / 2.0))
    ker = _bilinear_kernel(k)
    full_h = (h - 1) * s + k
    full_w = (w - 1) * s + k
    out = np.zeros((n, ch, full_h, full_w), np.float32)
    for b in range(n):
        for cch in range(ch):
            for i in range(h):
                for j in range(w):
                    out[b, cch, i * s:i * s + k, j * s:j * s + k] += (
                        x[b, cch, i, j] * ker)
    return out[:, :, p:p + h * s, p:p + w * s]


def test_nearest_upsampling():
    x = np.arange(8, dtype=np.float32).reshape(1, 2, 2, 2)
    y = mx.nd.UpSampling(nd.array(x), scale=3).asnumpy()
    assert y.shape == (1, 2, 6, 6)
    np.testing.assert_array_equal(y[0, 0, :3, :3], x[0, 0, 0, 0])


def test_bilinear_upsampling_matches_reference_deconv():
    rng = np.random.RandomState(0)
    for s in (2, 3):
        x = rng.randn(2, 3, 4, 5).astype(np.float32)
        y = mx.nd.UpSampling(nd.array(x), scale=s,
                             sample_type="bilinear").asnumpy()
        assert y.shape == (2, 3, 4 * s, 5 * s)
        np.testing.assert_allclose(y, _ref_bilinear_deconv(x, s),
                                   rtol=1e-5, atol=1e-5)


def test_bilinear_upsampling_constant_interior():
    """A constant input stays constant in the interior (kernel partition of
    unity away from borders)."""
    x = np.full((1, 1, 6, 6), 5.0, np.float32)
    y = mx.nd.UpSampling(nd.array(x), scale=2,
                         sample_type="bilinear").asnumpy()
    np.testing.assert_allclose(y[0, 0, 2:-2, 2:-2], 5.0, rtol=1e-6)


def test_bilinear_upsampling_nhwc():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 4, 4, 2).astype(np.float32)
    y = mx.nd.UpSampling(nd.array(x), scale=2, sample_type="bilinear",
                         layout="NHWC").asnumpy()
    x_nchw = np.transpose(x, (0, 3, 1, 2))
    expected = _ref_bilinear_deconv(x_nchw, 2)
    np.testing.assert_allclose(np.transpose(y, (0, 3, 1, 2)), expected,
                               rtol=1e-5, atol=1e-5)


def test_bilinear_kernel_matches_initializer():
    """ops kernel == mx.init.Bilinear weights (the reference's documented
    equivalence: UpSampling bilinear ≡ Deconvolution + Bilinear init)."""
    from incubator_mxnet_tpu.ops import _raw
    import jax.numpy as jnp
    init = mx.init.Bilinear()
    w = np.asarray(init._init(None, (1, 1, 4, 4), jnp.float32))
    k = np.asarray(jnp.outer(_raw.bilinear_kernel_1d(4),
                             _raw.bilinear_kernel_1d(4)))
    np.testing.assert_allclose(w[0, 0], k, rtol=1e-6)


def test_symbol_bilinear_upsampling():
    data = mx.sym.Variable("data")
    out = mx.sym.UpSampling(data, scale=2, sample_type="bilinear")
    x = np.random.RandomState(2).randn(1, 2, 3, 3).astype(np.float32)
    ex = out.bind(args={"data": nd.array(x)})
    (y,) = ex.forward()
    np.testing.assert_allclose(y.asnumpy(), _ref_bilinear_deconv(x, 2),
                               rtol=1e-5, atol=1e-5)


def test_bilinear_upsampling_grad():
    x = nd.array(np.random.RandomState(3).randn(1, 2, 3, 3).astype(np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.UpSampling(x, scale=2, sample_type="bilinear")
        loss = (y * y).sum()
    loss.backward()
    g = x._grad.asnumpy()
    assert np.all(np.isfinite(g)) and np.abs(g).sum() > 0
