"""REAL 2-process cluster healthmon acceptance (not mocks): runs
tools/health_cluster.py, which forms a loopback gloo cluster with an
injected slow rank (sleep on rank 1) and an injected NaN loss (rank 0),
and asserts the cross-rank contract — skew metric with slowest-rank
attribution on every rank, NaN watchdog alert within one step, and a
validated `mxdiag merge` timeline spanning both ranks.

The driver is shared with tools/health_smoke.sh so CI and the tier-1
suite exercise the identical harness; this test only asserts the
driver's verdict (and keeps its artifacts out of /tmp's shared path).
"""
import json
import os
import subprocess
import sys

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
_DRIVER = os.path.join(os.path.dirname(_HERE), "tools",
                       "health_cluster.py")

_TIMEOUT_S = int(os.environ.get("MXTPU_TEST_WORKER_TIMEOUT", "420"))


@pytest.mark.serial
def test_two_process_straggler_and_nan_detection(tmp_path):
    env = dict(os.environ)
    env["MXTPU_HM_OUT"] = str(tmp_path / "cluster")
    env["MXTPU_HM_TEST_STEPS"] = "20"
    env["MXTPU_HM_TEST_SLEEP_MS"] = "80"
    env["MXTPU_HM_NAN_STEP"] = "7"
    r = subprocess.run([sys.executable, _DRIVER], env=env,
                       capture_output=True, text=True,
                       timeout=_TIMEOUT_S + 60)
    assert r.returncode == 0, \
        f"health_cluster failed\nstdout:{r.stdout}\nstderr:{r.stderr[-3000:]}"
    verdict_lines = [ln for ln in r.stdout.splitlines()
                     if ln.startswith("HEALTH_SMOKE_OK ")]
    assert verdict_lines, f"no verdict line in {r.stdout!r}"
    verdict = json.loads(verdict_lines[0][len("HEALTH_SMOKE_OK "):])
    # the driver already asserted the detailed contract; re-assert the
    # headline numbers here so a weakened driver can't silently pass
    assert verdict["slowest_rank"] == 1
    assert verdict["skew_ms"] >= 0.4 * 80
    assert verdict["nan_alerts_rank0"] >= 1
    assert os.path.exists(verdict["merged_file"])
