"""mxtpu.commscope: static HLO collective extraction, mesh-axis
attribution, ICI link-time estimates, the resharding detector, the step
budget's collective-provenance fix, and the tooling that rides on it
(trace_check schema enforcement, perf_regress collective-bytes gate,
mxdiag comms renderer) — plus the 4-fake-device subprocess matrix
asserting each layout's expected collective signature."""
import importlib.util
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import incubator_mxnet_tpu as mx  # noqa: F401 — registers the package
from incubator_mxnet_tpu import commscope as cs
from incubator_mxnet_tpu import perfscope as ps
from incubator_mxnet_tpu import profiler as prof
from incubator_mxnet_tpu.commscope import extract, hlo
from incubator_mxnet_tpu.parallel import sharding as shmod


def _load_tool(name):
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _commscope_teardown():
    yield
    cs.disable()
    cs.reset_programs()
    ps.disable()
    ps.reset_programs()
    shmod.clear_mesh()
    shmod._LAST.clear()    # last-published layout feeds provenance too


# captured from a real XLA:CPU fsdp4 compile of the tier-1 MLP (shapes
# hand-checkable): one param all-gather, one grad all-reduce, the
# reduce-scatter-as-all-to-all decomposition, and an async pair
_HLO_FIXTURE = """\
HloModule jit_step_fn, is_scheduled=true

%fused_computation (param_0: f32[16,32]) -> f32[32,16] {
  %param_0 = f32[16,32]{1,0} parameter(0)
  ROOT %transpose.1 = f32[32,16]{0,1} transpose(f32[16,32]{1,0} %param_0), dimensions={1,0}
}

ENTRY %main {
  %param.1 = f32[4,8]{1,0} parameter(0), sharding={devices=[4,1]<=[4]}
  %copy.2 = f32[4,8]{1,0} copy(f32[4,8]{1,0} %param.1)
  %all-gather = f32[16,8]{1,0} all-gather(f32[4,8]{1,0} %copy.2), channel_id=1, replica_groups=[1,4]<=[4], dimensions={0}, use_global_device_ids=true
  %dot.1 = f32[16,32]{1,0} dot(f32[16,8]{1,0} %all-gather, f32[8,32]{1,0} %w)
  %all-reduce = f32[16,32]{1,0} all-reduce(f32[16,32]{1,0} %dot.1), channel_id=2, replica_groups={{0,1},{2,3}}, use_global_device_ids=true, to_apply=%add.clone
  %all-to-all.3 = (f32[1,4,1]{2,1,0}, f32[1,4,1]{2,1,0}) all-to-all(f32[1,4,1]{2,1,0} %slice_fusion, f32[1,4,1]{2,1,0} %slice_fusion.1), channel_id=3, replica_groups=[2,2]<=[2,2]T(1,0), dimensions={1}
  %all-gather-start = f32[8]{0} all-gather-start(f32[2]{0} %mul_fusion), channel_id=4, replica_groups=[1,4]<=[4], dimensions={0}
  %all-gather-done = f32[8]{0} all-gather-done(f32[8]{0} %all-gather-start)
  ROOT %tuple = tuple(%all-reduce)
}
"""


# ---------------------------------------------------------------------------
# HLO parsing
# ---------------------------------------------------------------------------

class TestShapeParsing:
    def test_simple_shape(self):
        assert hlo.parse_shape("f32[64,32]{1,0}") == [("f32", (64, 32))]

    def test_scalar(self):
        assert hlo.parse_shape("f32[]") == [("f32", ())]

    def test_tuple_shape(self):
        leaves = hlo.parse_shape("(f32[1,4,1]{2,1,0}, s32[2]{0})")
        assert leaves == [("f32", (1, 4, 1)), ("s32", (2,))]

    def test_bytes_f32(self):
        assert hlo.shape_bytes("f32[64,32]{1,0}") == 64 * 32 * 4

    def test_bytes_bf16_and_tuple(self):
        assert hlo.shape_bytes("(bf16[8,8]{1,0}, s32[4]{0})") \
            == 8 * 8 * 2 + 4 * 4

    def test_bytes_scalar_and_garbage(self):
        assert hlo.shape_bytes("f32[]") == 4
        assert hlo.shape_bytes("not a shape") == 0
        assert hlo.shape_bytes(None) == 0

    def test_unknown_dtype_counts_zero(self):
        # an unknown primitive type must not invent bytes
        assert hlo.shape_bytes("q77[64]{0}") == 0


class TestReplicaGroups:
    def test_explicit(self):
        assert hlo.parse_replica_groups("{{0,1},{2,3}}") == [[0, 1], [2, 3]]

    def test_iota_flat(self):
        assert hlo.parse_replica_groups("[1,4]<=[4]") == [[0, 1, 2, 3]]

    def test_iota_grouped(self):
        assert hlo.parse_replica_groups("[2,2]<=[4]") == [[0, 1], [2, 3]]

    def test_iota_transposed(self):
        # [2,2]<=[2,2]T(1,0): iota reshaped 2x2, transposed -> strided
        # groups — the dp axis of a (dp, mp) 2x2 mesh
        assert hlo.parse_replica_groups("[2,2]<=[2,2]T(1,0)") \
            == [[0, 2], [1, 3]]

    def test_malformed_returns_none(self):
        assert hlo.parse_replica_groups("") is None
        assert hlo.parse_replica_groups("[2,2]<=") is None
        assert hlo.parse_replica_groups("nonsense") is None


class TestParseCollectives:
    def test_empty_and_garbage_never_raise(self):
        assert hlo.parse_collectives("") == []
        assert hlo.parse_collectives(None) == []
        assert hlo.parse_collectives("ENTRY %main { garbage }") == []

    def test_no_collectives(self):
        txt = "ENTRY %m {\n  %dot = f32[8,8]{1,0} dot(%a, %b)\n}"
        assert hlo.parse_collectives(txt) == []

    def test_fixture_inventory(self):
        colls = hlo.parse_collectives(_HLO_FIXTURE)
        kinds = sorted(c["kind"] for c in colls)
        # the -done half of the async pair is NOT counted; -start is,
        # normalized to its base kind
        assert kinds == ["all-gather", "all-gather", "all-reduce",
                         "all-to-all"]

    def test_fused_computation_transpose_not_a_collective(self):
        # the fusion body above contains no collectives; nothing in it
        # may leak into the inventory
        colls = hlo.parse_collectives(_HLO_FIXTURE)
        assert all(not c["name"].startswith("transpose") for c in colls)

    def test_byte_accounting_vs_hand_computed(self):
        colls = {c["name"]: c for c in hlo.parse_collectives(_HLO_FIXTURE)}
        ag = colls["all-gather"]
        # all-gather: result f32[16,8] = 512 B > operand f32[4,8] = 128 B
        assert ag["result_bytes"] == 16 * 8 * 4
        assert ag["operand_bytes"] == 4 * 8 * 4
        assert ag["bytes"] == 16 * 8 * 4
        ar = colls["all-reduce"]
        assert ar["bytes"] == 16 * 32 * 4
        a2a = colls["all-to-all.3"]
        # tuple result: two f32[1,4,1] leaves
        assert a2a["result_bytes"] == 2 * 4 * 4

    def test_replica_group_and_channel_fields(self):
        colls = {c["name"]: c for c in hlo.parse_collectives(_HLO_FIXTURE)}
        assert colls["all-gather"]["replica_groups"] == [[0, 1, 2, 3]]
        assert colls["all-gather"]["group_size"] == 4
        assert colls["all-reduce"]["replica_groups"] == [[0, 1], [2, 3]]
        assert colls["all-to-all.3"]["replica_groups"] == [[0, 2], [1, 3]]
        assert colls["all-gather"]["channel_id"] == 1
        assert colls["all-gather"]["dims"] == [0]

    def test_unknown_collective_kind_never_raises(self):
        txt = ("ENTRY %m {\n"
               "  %collective-frobnicate = f32[8]{0} "
               "collective-frobnicate(f32[8]{0} %x), channel_id=1, "
               "replica_groups=[1,4]<=[4]\n}")
        colls = hlo.parse_collectives(txt)
        assert len(colls) == 1
        assert colls[0]["kind"] == "other"
        assert colls[0]["raw_kind"] == "collective-frobnicate"

    def test_async_start_tuple_not_double_counted(self):
        # a real TPU all-gather-start result bundles the source shard
        # NEXT TO the destination: (f32[2], f32[8]) — payload is the
        # 8-element destination (32 B), not the 40 B tuple sum
        txt = ("  %ag = (f32[2]{0}, f32[8]{0}) all-gather-start"
               "(f32[2]{0} %x), channel_id=5, replica_groups=[1,4]<=[4], "
               "dimensions={0}\n")
        colls = hlo.parse_collectives(txt)
        assert len(colls) == 1
        assert colls[0]["result_bytes"] == 8 * 4
        assert colls[0]["bytes"] == 8 * 4

    def test_sync_variadic_tuple_still_sums(self):
        # sync all-to-all's tuple result is N real payload buffers —
        # summing is correct there
        colls = {c["name"]: c for c in hlo.parse_collectives(_HLO_FIXTURE)}
        assert colls["all-to-all.3"]["result_bytes"] == 2 * 4 * 4

    def test_collective_broadcast_buckets_as_other(self):
        txt = ("  %collective-broadcast = f32[8]{0} "
               "collective-broadcast(f32[8]{0} %x), channel_id=9\n")
        colls = hlo.parse_collectives(txt)
        assert [c["kind"] for c in colls] == ["other"]


class TestProvenanceChase:
    def test_direct_parameter(self):
        defs = hlo.parse_instructions(_HLO_FIXTURE)
        assert defs["param.1"][0] == "parameter"
        # %copy.2 -> %param.1: one passthrough hop
        assert hlo.chases_to_parameter(defs, "copy.2")
        assert hlo.chases_to_parameter(defs, "param.1")

    def test_computed_value_is_not_a_parameter(self):
        defs = hlo.parse_instructions(_HLO_FIXTURE)
        assert not hlo.chases_to_parameter(defs, "dot.1")
        assert not hlo.chases_to_parameter(defs, "missing-name")

    def test_chase_depth_bounded(self):
        defs = {"a": ("copy", "a")}     # self-loop: must terminate
        assert not hlo.chases_to_parameter(defs, "a")


# ---------------------------------------------------------------------------
# estimates + peaks
# ---------------------------------------------------------------------------

class _FakeDevice:
    def __init__(self, kind):
        self.device_kind = kind


class TestPeaksAndEstimates:
    def test_cpu_fallback_row(self):
        p = cs.ici_peaks(_FakeDevice("cpu"))
        assert p["table_row"] == "cpu"
        assert p["ici_bytes_per_s"] == cs.ICI_TABLE["cpu"]

    def test_v5e_spellings(self):
        for kind in ("TPU v5 lite", "v5litepod-8", "tpu v5e"):
            assert cs.ici_peaks(_FakeDevice(kind))["table_row"] == "v5e", kind

    def test_v5p_not_shadowed_by_v5e(self):
        assert cs.ici_peaks(_FakeDevice("TPU v5p"))["table_row"] == "v5p"

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("MXTPU_PEAK_ICI_BW", "5e9")
        assert cs.ici_peaks(_FakeDevice("cpu"))["ici_bytes_per_s"] == 5e9

    def test_malformed_override_keeps_table(self, monkeypatch):
        monkeypatch.setenv("MXTPU_PEAK_ICI_BW", "not-a-number")
        assert cs.ici_peaks(_FakeDevice("cpu"))["ici_bytes_per_s"] \
            == cs.ICI_TABLE["cpu"]

    def test_all_reduce_ring_factor(self):
        # 2(n-1)/n * B / bw: n=4, 1 MiB at 1 GB/s -> 1.5 * 1.048576 ms
        ms = cs.estimate_ms("all-reduce", 2 ** 20, 4, 1e9)
        assert ms == pytest.approx(1.5 * 2 ** 20 / 1e9 * 1e3)

    def test_gather_scatter_factor(self):
        for kind in ("all-gather", "reduce-scatter", "all-to-all"):
            ms = cs.estimate_ms(kind, 4e6, 4, 1e9)
            assert ms == pytest.approx(0.75 * 4e6 / 1e9 * 1e3), kind

    def test_permute_full_payload(self):
        assert cs.estimate_ms("collective-permute", 1e6, 4, 1e9) \
            == pytest.approx(1.0)

    def test_degenerate_inputs_zero(self):
        assert cs.estimate_ms("all-reduce", 1e6, 1, 1e9) == 0.0
        assert cs.estimate_ms("all-reduce", 0, 4, 1e9) == 0.0
        assert cs.estimate_ms("all-reduce", None, None, None) == 0.0


# ---------------------------------------------------------------------------
# mesh-axis attribution (pure grid math — no devices needed)
# ---------------------------------------------------------------------------

class TestAxisAttribution:
    GRID_2X2 = np.arange(4).reshape(2, 2)    # (dp, mp): dp strided

    def test_single_axis_full_group(self):
        grid = np.arange(4)
        assert cs.attribute_axis([[0, 1, 2, 3]], grid, ["dp"]) == "dp"

    def test_2x2_mp_axis(self):
        # contiguous pairs vary the LAST axis: mp
        assert cs.attribute_axis([[0, 1], [2, 3]], self.GRID_2X2,
                                 ["dp", "mp"]) == "mp"

    def test_2x2_dp_axis(self):
        assert cs.attribute_axis([[0, 2], [1, 3]], self.GRID_2X2,
                                 ["dp", "mp"]) == "dp"

    def test_2x2_all_devices(self):
        assert cs.attribute_axis([[0, 1, 2, 3]], self.GRID_2X2,
                                 ["dp", "mp"]) == "all"

    def test_unrecognized_partition_is_mixed(self):
        assert cs.attribute_axis([[0, 3], [1, 2]], self.GRID_2X2,
                                 ["dp", "mp"]) == "mixed"

    def test_empty_groups_none(self):
        assert cs.attribute_axis(None, self.GRID_2X2, ["dp", "mp"]) is None
        assert cs.attribute_axis([], self.GRID_2X2, ["dp", "mp"]) is None


# ---------------------------------------------------------------------------
# resharding detector (synthetic records)
# ---------------------------------------------------------------------------

def _coll(kind, operands=(), name="c"):
    return {"kind": kind, "name": name, "operands": list(operands),
            "result_shape": "f32[16,8]{1,0}",
            "operand_shapes": ["f32[4,8]{1,0}"], "bytes": 512,
            "replica_groups": [[0, 1, 2, 3]], "group_size": 4}


class TestReshardingDetector:
    DEFS = {"param.1": ("parameter", None), "copy.2": ("copy", "param.1"),
            "dot.1": ("dot", "param.1")}

    def test_dp_all_reduce_clean(self):
        assert cs.detect_resharding([_coll("all-reduce")], self.DEFS,
                                    "dp") == []

    def test_dp_computed_gather_clean(self):
        # the loss-plumbing gather of a computed value: legitimate
        assert cs.detect_resharding([_coll("all-gather", ["dot.1"])],
                                    self.DEFS, "dp") == []

    def test_dp_param_gather_flagged(self):
        out = cs.detect_resharding([_coll("all-gather", ["copy.2"])],
                                   self.DEFS, "dp")
        assert len(out) == 1 and out[0]["reason"] == "param-gather"

    def test_dp_unexpected_kind_flagged(self):
        out = cs.detect_resharding([_coll("collective-permute")],
                                   self.DEFS, "dp")
        assert len(out) == 1 and out[0]["reason"] == "unexpected-kind"

    def test_fsdp_param_gather_is_the_mode(self):
        assert cs.detect_resharding([_coll("all-gather", ["param.1"]),
                                     _coll("all-to-all")],
                                    self.DEFS, "fsdp") == []

    def test_auto_accepts_cpu_reduce_scatter_decomposition(self):
        # XLA:CPU spells reduce-scatter as all-to-all + local reduce;
        # a healthy auto-mode layout must not be indicted for the
        # backend's spelling (the computed-value operand is the tell)
        assert cs.detect_resharding(
            [_coll("reduce-scatter", ["dot.1"]),
             _coll("all-to-all", ["dot.1"])], self.DEFS, "auto") == []

    def test_auto_param_gather_flagged(self):
        out = cs.detect_resharding([_coll("all-gather", ["param.1"])],
                                   self.DEFS, "auto")
        assert len(out) == 1

    def test_unknown_mode_conservative(self):
        # jit-cache/serving programs: nothing is out of signature
        assert cs.detect_resharding([_coll("all-gather", ["param.1"]),
                                     _coll("collective-permute")],
                                    self.DEFS, None) == []

    def test_other_kind_never_indicted(self):
        # an unknown HLO spelling (renamed op after an XLA upgrade) is
        # inventoried but must not trip the detector in ANY mode — the
        # parser's never-raise contract would otherwise hard-fail CI on
        # a correct layout
        for mode in ("dp", "fsdp", "auto", None):
            assert cs.detect_resharding([_coll("other")], self.DEFS,
                                        mode) == [], mode


# ---------------------------------------------------------------------------
# record_inventory / capture / counters
# ---------------------------------------------------------------------------

def _commscope_counters():
    return {k: v for k, v in prof.counters().items()
            if k.startswith("commscope/")}


class TestRecordInventory:
    def test_aggregation_and_counters(self):
        colls = hlo.parse_collectives(_HLO_FIXTURE)
        defs = hlo.parse_instructions(_HLO_FIXTURE)
        before = _commscope_counters().get(
            "commscope/commscope.collectives", 0)
        rec = cs.record_inventory("prog_a", colls, defs=defs, mode="fsdp",
                                  kind="train_step")
        assert rec["totals"]["count"] == 4
        assert rec["totals"]["bytes"] > 0
        assert rec["resharding_collectives"] == 0
        after = _commscope_counters()
        assert after["commscope/commscope.collectives"] == before + 4
        assert after["commscope/commscope.step_collective_bytes"] \
            == rec["totals"]["bytes"]

    def test_resharding_warns_and_counts(self):
        colls = hlo.parse_collectives(_HLO_FIXTURE)
        defs = hlo.parse_instructions(_HLO_FIXTURE)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            rec = cs.record_inventory("prog_bad", colls, defs=defs,
                                      mode="dp")
        # the fixture's param all-gather + the all-to-all are both out
        # of a pure-dp program's signature
        assert rec["resharding_collectives"] >= 2
        assert any("resharding" in str(w.message) for w in caught)
        assert rec["resharding"][0]["operand_shapes"]  # offending shapes

    def test_step_estimate_prefers_latest_train_step(self):
        cs.record_inventory("prog_x", [], kind="program")
        assert cs.step_estimate() is None
        cs.record_inventory(
            "fused_step", hlo.parse_collectives(_HLO_FIXTURE),
            kind="train_step")
        est = cs.step_estimate()
        assert est["program"] == "fused_step"
        assert est["bytes"] > 0 and est["est_ms"] >= 0

    def test_capture_without_mesh_records_empty(self):
        cs.enable()
        rec = cs.capture("unsharded_prog", kind="program")
        assert rec["totals"] == {"count": 0, "bytes": 0, "est_ms": 0.0}
        assert rec["hlo_available"] is True
        assert [p["name"] for p in cs.programs()] == ["unsharded_prog"]

    def test_enable_arms_perfscope(self):
        assert ps._PS is None
        cs.enable()
        assert ps._PS is not None

    def test_bench_extra_shape(self):
        cs.enable()
        cs.capture("p1")
        extra = cs.bench_extra()
        assert {"programs", "peaks", "step"} <= set(extra)
        assert extra["peaks"]["ici_bytes_per_s"] > 0


# ---------------------------------------------------------------------------
# StepBudget collective provenance (the PR's satellite bug fix)
# ---------------------------------------------------------------------------

class _FakeMesh:
    size = 4


class TestCollectiveProvenance:
    def _finish(self, probe=None):
        b = ps.StepBudget().begin()
        b.end(steps=10, steady_s=1.0)
        if probe is not None:
            b._probe = dict(median_ms=probe, min_ms=probe, max_ms=probe,
                            iters=1, steps_per_call=1)
        return b.finish()

    def test_unsharded_is_measured(self):
        d = self._finish()
        assert d["collective_source"] == "measured"

    def test_sharded_without_commscope_is_unavailable(self, monkeypatch):
        monkeypatch.setattr(shmod, "_MESH", _FakeMesh())
        d = self._finish()
        assert d["collective_source"] == "unavailable"
        assert d["collective_ms"] == 0.0

    @staticmethod
    def _record_sharded_train_step():
        # the captured program carries its OWN mesh shape — the
        # provenance decision reads it from here, not the registry
        cs.record_inventory(
            "fused_step", hlo.parse_collectives(_HLO_FIXTURE),
            kind="train_step", extra={"mesh": {"dp": 4}})

    def test_sharded_with_commscope_is_estimated(self, monkeypatch):
        monkeypatch.setattr(shmod, "_MESH", _FakeMesh())
        cs.enable()
        self._record_sharded_train_step()
        est = cs.step_estimate()["est_ms"]
        d = self._finish()
        assert d["collective_source"] == "estimated"
        # decomp rounds components to 4 decimals
        assert d["collective_ms"] == pytest.approx(min(est, d["step_ms"]),
                                                   abs=1e-4)
        assert d["collective_est"]["program"] == "fused_step"

    def test_explicit_mesh_without_registry_is_estimated(self):
        # a FusedTrainStep built with mesh= never registers a global
        # mesh; the captured program's mesh must still drive provenance
        # (the review finding: registry-only checking reported a
        # measured zero here)
        assert shmod.get_mesh() is None
        cs.enable()
        self._record_sharded_train_step()
        d = self._finish()
        assert d["collective_source"] == "estimated"

    def test_unsharded_capture_stays_measured(self):
        # commscope armed on a 1-device run: the captured program has
        # no mesh, so the honest zero stays "measured"
        cs.enable()
        cs.record_inventory("fused_step", [], kind="train_step")
        d = self._finish()
        assert d["collective_source"] == "measured"

    def test_unreadable_hlo_is_unavailable_not_estimated(self):
        # commscope LOOKED at a sharded program and could not read its
        # HLO: the zero inventory is ignorance — reporting it as an
        # estimated zero would reintroduce the measured-zero lie
        cs.enable()
        cs.record_inventory("fused_step", [], kind="train_step",
                            hlo_available=False,
                            extra={"mesh": {"dp": 4}})
        d = self._finish()
        assert d["collective_source"] == "unavailable"
        assert d["collective_ms"] == 0.0

    def test_estimated_zero_inventory_is_honest(self):
        # readable HLO, genuinely zero collectives on a mesh (fully
        # replicated compute): THAT zero is a finding, not ignorance
        cs.enable()
        cs.record_inventory("fused_step", [], kind="train_step",
                            extra={"mesh": {"dp": 4}})
        d = self._finish()
        assert d["collective_source"] == "estimated"
        assert d["collective_ms"] == 0.0

    def test_probe_peels_estimate_out_of_device(self, monkeypatch):
        monkeypatch.setattr(shmod, "_MESH", _FakeMesh())
        cs.enable()
        self._record_sharded_train_step()
        d = self._finish(probe=80.0)
        # device + collective must not double-count the probe's wall
        assert d["device_compute_ms"] + d["collective_ms"] \
            == pytest.approx(80.0, rel=1e-3)

    def test_components_still_sum(self, monkeypatch):
        monkeypatch.setattr(shmod, "_MESH", _FakeMesh())
        cs.enable()
        self._record_sharded_train_step()
        d = self._finish(probe=80.0)
        total = sum(d[k] for k in ("device_compute_ms", "collective_ms",
                                   "input_wait_ms", "host_gap_ms",
                                   "other_ms"))
        assert total == pytest.approx(d["step_ms"], rel=0.01)

    def test_measured_kvstore_wins_over_estimate(self, monkeypatch):
        # when the explicit-collective path DID measure time, the
        # estimate must not replace it
        monkeypatch.setattr(shmod, "_MESH", _FakeMesh())
        cs.enable()
        self._record_sharded_train_step()
        b = ps.StepBudget()
        b._snap0 = {k: 0.0 for k in b._TRACKED}
        b.end(steps=10, steady_s=1.0)
        b._snap1 = dict(b._snap1,
                        **{"mxtpu/kvstore.collective_ms": 50.0})
        d = b.finish()
        assert d["collective_source"] == "measured"
        assert d["collective_ms"] == pytest.approx(5.0)


# ---------------------------------------------------------------------------
# trace_check: commscope family + extra schema + provenance taxonomy
# ---------------------------------------------------------------------------

def _valid_commscope_extra():
    return {
        "peaks": {"device_kind": "cpu", "table_row": "cpu",
                  "ici_bytes_per_s": 1e9},
        "programs": [{
            "name": "fused_step", "mode": "fsdp", "mesh": {"dp": 4},
            "hlo_available": True,
            "collectives": [
                {"kind": "all-gather", "axis": "dp", "count": 7,
                 "bytes": 5000, "est_ms": 0.01},
                {"kind": "all-reduce", "axis": "dp", "count": 3,
                 "bytes": 2000, "est_ms": 0.02}],
            "totals": {"count": 10, "bytes": 7000, "est_ms": 0.03},
            "resharding_collectives": 0, "resharding": [],
            "estimated": True}],
        "step": {"program": "fused_step", "est_ms": 0.03, "bytes": 7000,
                 "count": 10, "resharding_collectives": 0},
    }


class TestTraceCheck:
    @pytest.fixture(scope="class")
    def tc(self):
        return _load_tool("trace_check")

    def test_valid_extra_passes(self, tc):
        assert tc.check_commscope_extra(_valid_commscope_extra()) == []

    def test_absent_extra_passes(self, tc):
        assert tc.check_commscope_extra(None) == []

    def test_unknown_kind_fails(self, tc):
        bad = _valid_commscope_extra()
        bad["programs"][0]["collectives"][0]["kind"] = "all-toaster"
        assert any("all-toaster" in e
                   for e in tc.check_commscope_extra(bad))

    def test_negative_bytes_fails(self, tc):
        bad = _valid_commscope_extra()
        bad["programs"][0]["collectives"][0]["bytes"] = -1
        assert tc.check_commscope_extra(bad)

    def test_non_numeric_est_fails(self, tc):
        bad = _valid_commscope_extra()
        bad["programs"][0]["totals"]["est_ms"] = "fast"
        assert tc.check_commscope_extra(bad)

    def test_count_mismatch_fails(self, tc):
        bad = _valid_commscope_extra()
        bad["programs"][0]["totals"]["count"] = 99
        assert any("totals.count" in e
                   for e in tc.check_commscope_extra(bad))

    def test_negative_resharding_fails(self, tc):
        bad = _valid_commscope_extra()
        bad["programs"][0]["resharding_collectives"] = -2
        assert tc.check_commscope_extra(bad)

    def test_missing_peaks_fails(self, tc):
        bad = _valid_commscope_extra()
        del bad["peaks"]
        assert tc.check_commscope_extra(bad)

    def test_commscope_family_enforced(self, tc):
        errs = tc.check_healthmon_kinds(
            {"commscope/commscope.collectives": "counter"})
        assert errs == []
        errs = tc.check_healthmon_kinds(
            {"commscope/commscope.invented": "counter"})
        assert any("COMMSCOPE_FAMILIES" in e for e in errs)
        errs = tc.check_healthmon_kinds(
            {"commscope/commscope.collectives": "gauge"})
        assert any("kind" in e for e in errs)

    def test_collective_source_taxonomy(self, tc):
        psx = {"peaks": {"peak_flops_f32": 1e12, "peak_flops_bf16": 2e12,
                         "hbm_bytes_per_s": 1e11},
               "programs": [],
               "decomposition": {
                   "step_ms": 10.0, "device_compute_ms": 8.0,
                   "collective_ms": 1.0, "input_wait_ms": 0.0,
                   "host_gap_ms": 1.0, "other_ms": 0.0,
                   "collective_source": "estimated"}}
        assert tc.check_perfscope_extra(psx) == []
        psx["decomposition"]["collective_source"] = "guessed"
        assert any("collective_source" in e
                   for e in tc.check_perfscope_extra(psx))

    def test_bench_json_validates_commscope(self, tc, tmp_path):
        doc = {"metric": "m", "value": 1.0, "unit": "x",
               "extra": {"mfu": 0.1, "commscope": _valid_commscope_extra()}}
        p = tmp_path / "BENCH_ok.json"
        p.write_text(json.dumps(doc))
        assert tc.check_bench_json(str(p)) == []
        doc["extra"]["commscope"]["programs"][0]["collectives"][0][
            "kind"] = "nope"
        p.write_text(json.dumps(doc))
        assert any("extra.commscope" in e
                   for e in tc.check_bench_json(str(p)))


# ---------------------------------------------------------------------------
# perf_regress: the collective-bytes layout gate
# ---------------------------------------------------------------------------

def _artifact(tmp_path, name, value=100.0, coll_bytes=None, reshard=None):
    doc = {"metric": "m_samples", "value": value, "unit": "samples/sec",
           "extra": {"mfu": 0.1}}
    if coll_bytes is not None:
        step = {"program": "fused_step", "est_ms": 0.1,
                "bytes": coll_bytes, "count": 4}
        if reshard is not None:
            step["resharding_collectives"] = reshard
        doc["extra"]["commscope"] = {
            "peaks": {"ici_bytes_per_s": 1e9}, "programs": [],
            "step": step}
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


class TestPerfRegressCollectiveGate:
    @pytest.fixture(scope="class")
    def pr(self):
        return _load_tool("perf_regress")

    def test_same_bytes_ok(self, pr, tmp_path):
        a = _artifact(tmp_path, "a.json", coll_bytes=7000)
        b = _artifact(tmp_path, "b.json", coll_bytes=7000)
        assert pr.main([a, b]) == 0

    def test_inflated_bytes_regress(self, pr, tmp_path):
        a = _artifact(tmp_path, "a.json", coll_bytes=7000)
        b = _artifact(tmp_path, "b.json", coll_bytes=14000)
        assert pr.main([a, b]) == 1

    def test_small_drift_within_threshold(self, pr, tmp_path):
        a = _artifact(tmp_path, "a.json", coll_bytes=7000)
        b = _artifact(tmp_path, "b.json", coll_bytes=7100)
        assert pr.main([a, b]) == 0

    def test_zero_to_nonzero_always_regress(self, pr, tmp_path):
        a = _artifact(tmp_path, "a.json", coll_bytes=0)
        b = _artifact(tmp_path, "b.json", coll_bytes=64)
        assert pr.main([a, b]) == 1

    def test_new_resharding_regress(self, pr, tmp_path):
        a = _artifact(tmp_path, "a.json", coll_bytes=7000, reshard=0)
        b = _artifact(tmp_path, "b.json", coll_bytes=7000, reshard=2)
        assert pr.main([a, b]) == 1

    def test_artifacts_without_commscope_skip_gate(self, pr, tmp_path):
        a = _artifact(tmp_path, "a.json")
        b = _artifact(tmp_path, "b.json", coll_bytes=9999)
        assert pr.main([a, b]) == 0

    def test_preexisting_resharding_vs_commscope_less_baseline_ok(
            self, pr, tmp_path):
        # a baseline predating commscope cannot indict a candidate's
        # known resharding count (same contract as the bytes gate)
        a = _artifact(tmp_path, "a.json")
        b = _artifact(tmp_path, "b.json", coll_bytes=7000, reshard=2)
        assert pr.main([a, b]) == 0


# ---------------------------------------------------------------------------
# mxdiag comms renderer
# ---------------------------------------------------------------------------

class TestMxdiagComms:
    @pytest.fixture(scope="class")
    def md(self):
        return _load_tool("mxdiag")

    def test_renders_table(self, md, capsys):
        doc = {"metric": "m", "value": 1.0, "unit": "x",
               "extra": {"commscope": _valid_commscope_extra()}}
        assert md.print_comms(doc) == 0
        out = capsys.readouterr().out
        assert "all-gather" in out and "axis dp" in out
        assert "fused_step" in out

    def test_resharding_rendered_loudly(self, md, capsys):
        extra = _valid_commscope_extra()
        extra["programs"][0]["resharding_collectives"] = 1
        extra["programs"][0]["resharding"] = [
            {"kind": "all-gather", "reason": "param-gather",
             "result_shape": "f32[32,8]{1,0}",
             "operand_shapes": ["f32[8,8]{1,0}"]}]
        doc = {"metric": "m", "value": 1.0, "unit": "x",
               "extra": {"commscope": extra}}
        assert md.print_comms(doc) == 0
        out = capsys.readouterr().out
        assert "RESHARD" in out and "param-gather" in out

    def test_missing_section_fails(self, md, capsys):
        assert md.print_comms({"metric": "m", "value": 1.0,
                               "extra": {}}) == 1

    def test_perf_renders_provenance(self, md, capsys):
        doc = {"metric": "m", "value": 1.0, "unit": "x",
               "extra": {"perfscope": {
                   "peaks": {"device_kind": "cpu", "table_row": "cpu",
                             "peak_flops_f32": 1e12,
                             "peak_flops_bf16": 2e12,
                             "hbm_bytes_per_s": 1e11},
                   "programs": [],
                   "decomposition": {
                       "step_ms": 10.0, "steps": 5,
                       "device_compute_ms": 8.0, "collective_ms": 1.0,
                       "input_wait_ms": 0.0, "host_gap_ms": 1.0,
                       "other_ms": 0.0, "source": "probe",
                       "collective_source": "unavailable"}}}}
        md.print_perf(doc)
        out = capsys.readouterr().out
        assert "UNAVAILABLE" in out


# ---------------------------------------------------------------------------
# the 4-fake-device subprocess matrix: expected signatures per layout
# ---------------------------------------------------------------------------

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "commscope_matrix_worker.py")


def _run_worker(layout):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)     # the worker pins its own
    proc = subprocess.run([sys.executable, _WORKER, layout],
                          capture_output=True, text=True, timeout=240,
                          env=env)
    assert proc.returncode == 0, \
        f"worker {layout} rc={proc.returncode}:\n{proc.stderr[-2000:]}"
    return json.loads(proc.stdout.strip().splitlines()[-1])


class TestSubprocessMatrix:
    @pytest.fixture(scope="class")
    def matrix(self):
        return {layout: _run_worker(layout)
                for layout in ("single", "dp4", "dp2mp2", "fsdp4",
                               "misannotated")}

    def test_single_device_no_collectives(self, matrix):
        rec = matrix["single"]
        assert rec["kinds"] == {}
        assert rec["program"]["totals"]["count"] == 0
        assert rec["collective_source"] == "measured"

    def test_dp4_all_reduce_signature(self, matrix):
        rec = matrix["dp4"]
        assert rec["devices"] == 4
        assert rec["kinds"].get("all-reduce", 0) > 0
        # pure data parallel must not reduce-scatter or permute
        assert "reduce-scatter" not in rec["kinds"]
        assert "collective-permute" not in rec["kinds"]
        assert rec["program"]["resharding_collectives"] == 0
        assert rec["axes"] == ["dp"]

    def test_fsdp4_gather_scatter_signature(self, matrix):
        rec = matrix["fsdp4"]
        kinds = rec["kinds"]
        assert kinds.get("all-gather", 0) > 0, kinds
        # the grad reduce-scatter: literal on TPU, decomposed into
        # all-to-all (+ local reduce) by XLA:CPU — either spelling
        assert kinds.get("reduce-scatter", 0) + kinds.get("all-to-all",
                                                          0) > 0, kinds
        assert rec["program"]["resharding_collectives"] == 0

    def test_dp2mp2_model_axis_collectives(self, matrix):
        rec = matrix["dp2mp2"]
        assert "mp" in rec["axes"], rec["axes"]
        assert rec["kinds"].get("all-reduce", 0) > 0
        assert rec["program"]["resharding_collectives"] == 0

    def test_misannotated_trips_detector(self, matrix):
        rec = matrix["misannotated"]
        assert rec["program"]["resharding_collectives"] > 0
        reasons = {r["reason"] for r in rec["program"]["resharding"]}
        assert "param-gather" in reasons or "unexpected-kind" in reasons
        assert rec["resharding_warned"]
        # the offending operand shapes are recorded for the human
        flagged = rec["program"]["resharding"][0]
        assert flagged.get("result_shape") or flagged.get("operand_shapes")
        assert rec["counters"][
            "commscope/commscope.resharding_collectives"] > 0

    def test_sharded_bytes_nonzero_and_estimated(self, matrix):
        for layout in ("dp4", "dp2mp2", "fsdp4"):
            rec = matrix[layout]
            assert rec["program"]["totals"]["bytes"] > 0, layout
            assert rec["step_estimate"]["bytes"] > 0, layout
            assert rec["collective_source"] == "estimated", layout

    def test_byte_accounting_scales_with_mode(self, matrix):
        # fsdp gathers every param each step: its payload must exceed
        # pure-dp's grad-reduce-only traffic on the same net
        assert matrix["fsdp4"]["program"]["totals"]["bytes"] \
            > matrix["dp4"]["program"]["totals"]["bytes"]
