"""ByteScheduler-style overlapped gradient communication (VERDICT r4 #7).

Parity model: ps-lite push/pull pipelining (src/kvstore/kvstore_dist.h)
and the BytePS/ByteScheduler scheduling the ymjiang fork exists for —
per-parameter aggregation issued mid-backward in reverse layer order,
priority-ordered (front layers first) with credit-based in-flight
throttling, numerically identical to the batched step() path.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd


def _mlp(n_layers=4, width=8, seed=0):
    net = gluon.nn.HybridSequential()
    for _ in range(n_layers):
        net.add(gluon.nn.Dense(width, in_units=width))
    net.initialize(init=mx.init.Xavier())
    # deterministic params for parity checks
    rng = np.random.RandomState(seed)
    for p in net.collect_params().values():
        p.set_data(nd.array(rng.randn(*p.shape).astype(np.float32)))
    return net


def _backward(net, seed=1):
    x = nd.array(np.random.RandomState(seed).randn(2, 8).astype(np.float32))
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()


def _force_two_workers(monkeypatch, tr):
    monkeypatch.setattr(type(tr._kvstore), "num_workers",
                        property(lambda self: 2), raising=False)


def test_grad_hook_fires_mid_backward_in_reverse_layer_order():
    """Hooks fire during the reverse walk, back layer first, and each
    fires exactly once with the finalized gradient value."""
    net = _mlp()
    params = [p for p in net.collect_params().values()
              if p.grad_req != "null"]
    fired = []
    for i, p in enumerate(params):
        p.register_grad_hook(lambda q, _i=i: fired.append(
            (_i, float(np.abs(q.grad().asnumpy()).sum()))))
    _backward(net)
    assert len(fired) == len(params)
    order = [i for i, _ in fired]
    # strictly reverse layer order: Dense3's (w,b) before Dense2's, etc.
    layer_of = [i // 2 for i in order]      # (weight, bias) pairs per layer
    assert layer_of == sorted(layer_of, reverse=True), order
    # the hook saw a REAL finalized grad (loss is quadratic -> nonzero)
    assert all(v > 0 for _, v in fired)
    for p in params:
        p.register_grad_hook(None)


def test_overlap_issues_during_backward_and_matches_batched_step(
        monkeypatch):
    """Aggregation is issued before step() is reached, and the resulting
    weights are bit-identical to the plain batched Trainer."""
    net_a, net_b = _mlp(seed=3), _mlp(seed=3)
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                         {"learning_rate": 0.1}, kvstore="dist_sync")
    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.1}, kvstore="dist_sync",
                         overlap_comm=True)
    _force_two_workers(monkeypatch, tr_a)
    _force_two_workers(monkeypatch, tr_b)

    for step in range(3):
        _backward(net_a, seed=step)
        _backward(net_b, seed=step)
        # hooks issued every bucket mid-backward: read BEFORE step() —
        # flush() resets the log at the start of every step
        assert len(tr_b._sched.issued_log) == len(tr_b._sched._buckets)
        tr_a.step(2)
        tr_b.step(2)
        # all buckets issued mid-backward -> flush had no stragglers, and
        # the log no longer accumulates across steps
        assert tr_b._sched.issued_log == []

    for pa, pb in zip(net_a.collect_params().values(),
                      net_b.collect_params().values()):
        np.testing.assert_array_equal(pa.data().asnumpy(),
                                      pb.data().asnumpy())


def test_priority_overtaking_under_zero_credit(monkeypatch):
    """With no credit, nothing issues mid-backward; the flush drains the
    priority heap front-layer-first — the ByteScheduler reordering
    (availability order is reverse, issue order is forward)."""
    net = _mlp()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.0},
                       kvstore="dist_sync", overlap_comm=True,
                       comm_credit_bytes=0)
    _force_two_workers(monkeypatch, tr)
    _backward(net)
    sched = tr._sched

    # zero credit: first bucket issues (heap drained before any inflight),
    # everything after queues -- so mid-backward issuance is at most 1
    assert len(sched.issued_log) <= 1
    mid_backward = list(sched.issued_log)
    tr.step(2)
    # flush() resets the log, then drains the queued buckets in strictly
    # ascending bucket priority; mid-backward buckets are not re-issued
    queued = sched.issued_log
    assert queued == sorted(queued), queued
    assert not set(mid_backward) & set(queued)


def test_bucketing_groups_consecutive_params(monkeypatch):
    net = _mlp(n_layers=4)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.0},
                       kvstore="dist_sync", overlap_comm=True,
                       comm_bucket_bytes=1 << 20)  # everything in 1 bucket
    _force_two_workers(monkeypatch, tr)
    assert len(tr._sched._buckets) == 1
    _backward(net)
    assert tr._sched.issued_log == [0]   # issued once, mid-backward
    tr.step(2)
    assert tr._sched.issued_log == []    # flush reset; no stragglers


def test_overlap_noop_on_single_worker():
    """num_workers == 1: hooks fire but schedule nothing (no identity
    pushpull burning dispatch), and step() works."""
    net = _mlp()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                       kvstore="dist_sync", overlap_comm=True)
    _backward(net)
    assert tr._sched.issued_log == []
    tr.step(2)


def test_overlap_requires_kvstore():
    net = _mlp()
    with pytest.raises(ValueError, match="kvstore"):
        gluon.Trainer(net.collect_params(), "sgd", {}, kvstore=None,
                      overlap_comm=True)


def test_update_without_allreduce_resets_scheduler(monkeypatch):
    """ADVICE r5: update() without allreduce_grads() used to strand the
    scheduler's _ready/_issued sets, so the NEXT backward's first grad
    hook raised the misleading 'second backward pass' error. update()
    now resets the per-pass state (without issuing anything new)."""
    net = _mlp()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                       kvstore="dist_sync", overlap_comm=True)
    _force_two_workers(monkeypatch, tr)
    _backward(net)
    assert tr._sched._issued            # buckets issued mid-backward
    tr.update(2)                        # user skipped allreduce_grads()
    assert not tr._sched._ready and not tr._sched._issued
    _backward(net)                      # must NOT raise
    tr.step(2)                          # and the normal path still works
    assert not tr._sched._ready and not tr._sched._issued


def test_allreduce_then_update_does_not_double_aggregate(monkeypatch):
    """The documented two-call sequence must stay numerically identical
    to step(): update()'s defensive reset must not re-issue (and so
    re-aggregate) buckets that allreduce_grads() already flushed."""
    net_a, net_b = _mlp(seed=5), _mlp(seed=5)
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                         {"learning_rate": 0.1}, kvstore="dist_sync")
    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.1}, kvstore="dist_sync",
                         overlap_comm=True)
    _force_two_workers(monkeypatch, tr_a)
    _force_two_workers(monkeypatch, tr_b)
    for step in range(2):
        _backward(net_a, seed=step)
        _backward(net_b, seed=step)
        tr_a.step(2)
        tr_b._optimizer.rescale_grad = 1.0 / 2
        tr_b.allreduce_grads()
        tr_b.update(2)
    for pa, pb in zip(net_a.collect_params().values(),
                      net_b.collect_params().values()):
        np.testing.assert_array_equal(pa.data().asnumpy(),
                                      pb.data().asnumpy())
