"""gluon.rnn tests (mirrors reference tests/python/unittest/test_gluon_rnn.py).
Numeric references: torch-cpu LSTM/GRU/RNN (same gate equations; gate-order
permuted where the conventions differ)."""
import numpy as np
import pytest
import torch

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.gluon import rnn


def _np(x):
    return x.asnumpy()


# ---------------------------------------------------------------------------
# cells
# ---------------------------------------------------------------------------

def test_rnn_cell_shapes():
    cell = rnn.RNNCell(16)
    cell.initialize()
    x = nd.random.uniform(shape=(4, 8))
    out, states = cell(x, cell.begin_state(4))
    assert out.shape == (4, 16)
    assert states[0].shape == (4, 16)


def test_lstm_cell_vs_torch():
    H, I, N = 8, 5, 3
    cell = rnn.LSTMCell(H)
    cell.initialize()
    x = nd.random.uniform(shape=(N, I), low=-1, high=1)
    h0 = nd.random.uniform(shape=(N, H), low=-1, high=1)
    c0 = nd.random.uniform(shape=(N, H), low=-1, high=1)
    out, (h1, c1) = cell(x, [h0, c0])

    tc = torch.nn.LSTMCell(I, H)
    # our gate order (reference rnn-inl.h): i, f, g, o == torch's i, f, g, o
    with torch.no_grad():
        tc.weight_ih.copy_(torch.tensor(_np(cell.i2h_weight.data())))
        tc.weight_hh.copy_(torch.tensor(_np(cell.h2h_weight.data())))
        tc.bias_ih.copy_(torch.tensor(_np(cell.i2h_bias.data())))
        tc.bias_hh.copy_(torch.tensor(_np(cell.h2h_bias.data())))
        th, tcell = tc(torch.tensor(_np(x)),
                       (torch.tensor(_np(h0)), torch.tensor(_np(c0))))
    np.testing.assert_allclose(_np(h1), th.numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(_np(c1), tcell.numpy(), rtol=1e-5, atol=1e-6)


def test_gru_cell_vs_torch():
    H, I, N = 6, 4, 2
    cell = rnn.GRUCell(H)
    cell.initialize()
    x = nd.random.uniform(shape=(N, I), low=-1, high=1)
    h0 = nd.random.uniform(shape=(N, H), low=-1, high=1)
    out, (h1,) = cell(x, [h0])

    tc = torch.nn.GRUCell(I, H)
    with torch.no_grad():
        tc.weight_ih.copy_(torch.tensor(_np(cell.i2h_weight.data())))
        tc.weight_hh.copy_(torch.tensor(_np(cell.h2h_weight.data())))
        tc.bias_ih.copy_(torch.tensor(_np(cell.i2h_bias.data())))
        tc.bias_hh.copy_(torch.tensor(_np(cell.h2h_bias.data())))
        th = tc(torch.tensor(_np(x)), torch.tensor(_np(h0)))
    np.testing.assert_allclose(_np(h1), th.numpy(), rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_cell_unroll_matches_layer():
    T, N, I, H = 5, 3, 4, 6
    cell = rnn.LSTMCell(H)
    cell.initialize()
    layer = rnn.LSTM(H, layout="NTC")
    layer.initialize()
    x = nd.random.uniform(shape=(N, T, I), low=-1, high=1)
    out_cell, _ = cell.unroll(T, x, layout="NTC")   # triggers deferred init
    layer(x[:, :1])                                 # ditto for the layer
    for name in ("i2h_weight", "h2h_weight", "i2h_bias", "h2h_bias"):
        cp = getattr(cell, name).data()
        layer.collect_params()[layer.prefix + "l0_" + name].set_data(cp)
    out_cell, _ = cell.unroll(T, x, layout="NTC")
    out_layer = layer(x)
    np.testing.assert_allclose(_np(out_cell), _np(out_layer),
                               rtol=1e-5, atol=1e-6)


def test_sequential_and_residual_cells():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.GRUCell(8))
    stack.add(rnn.ResidualCell(rnn.GRUCell(8)))
    stack.initialize()
    x = nd.random.uniform(shape=(2, 8))
    out, states = stack(x, stack.begin_state(2))
    assert out.shape == (2, 8)
    assert len(states) == 2


# ---------------------------------------------------------------------------
# fused layers vs torch
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("bidirectional", [False, True])
@pytest.mark.parametrize("num_layers", [1, 2])
def test_lstm_layer_vs_torch(num_layers, bidirectional):
    T, N, I, H = 7, 4, 5, 6
    layer = rnn.LSTM(H, num_layers=num_layers, layout="TNC",
                     bidirectional=bidirectional)
    layer.initialize()
    x = nd.random.uniform(shape=(T, N, I), low=-1, high=1)
    out = layer(x)

    t_l = torch.nn.LSTM(I, H, num_layers=num_layers,
                        bidirectional=bidirectional)
    D = 2 if bidirectional else 1
    with torch.no_grad():
        for layer_i in range(num_layers):
            for d in range(D):
                pre = f"{'r' if d else 'l'}{layer_i}_"
                sfx = "_reverse" if d else ""
                getattr(t_l, f"weight_ih_l{layer_i}{sfx}").copy_(torch.tensor(
                    _np(layer.collect_params()[layer.prefix + pre + "i2h_weight"].data())))
                getattr(t_l, f"weight_hh_l{layer_i}{sfx}").copy_(torch.tensor(
                    _np(layer.collect_params()[layer.prefix + pre + "h2h_weight"].data())))
                getattr(t_l, f"bias_ih_l{layer_i}{sfx}").copy_(torch.tensor(
                    _np(layer.collect_params()[layer.prefix + pre + "i2h_bias"].data())))
                getattr(t_l, f"bias_hh_l{layer_i}{sfx}").copy_(torch.tensor(
                    _np(layer.collect_params()[layer.prefix + pre + "h2h_bias"].data())))
        t_out, _ = t_l(torch.tensor(_np(x)))
    np.testing.assert_allclose(_np(out), t_out.numpy(), rtol=1e-4, atol=1e-5)


def test_gru_layer_vs_torch():
    T, N, I, H = 6, 3, 4, 5
    layer = rnn.GRU(H, layout="TNC")
    layer.initialize()
    x = nd.random.uniform(shape=(T, N, I), low=-1, high=1)
    out = layer(x)
    t_l = torch.nn.GRU(I, H)
    with torch.no_grad():
        t_l.weight_ih_l0.copy_(torch.tensor(
            _np(layer.collect_params()[layer.prefix + "l0_i2h_weight"].data())))
        t_l.weight_hh_l0.copy_(torch.tensor(
            _np(layer.collect_params()[layer.prefix + "l0_h2h_weight"].data())))
        t_l.bias_ih_l0.copy_(torch.tensor(
            _np(layer.collect_params()[layer.prefix + "l0_i2h_bias"].data())))
        t_l.bias_hh_l0.copy_(torch.tensor(
            _np(layer.collect_params()[layer.prefix + "l0_h2h_bias"].data())))
        t_out, _ = t_l(torch.tensor(_np(x)))
    np.testing.assert_allclose(_np(out), t_out.numpy(), rtol=1e-4, atol=1e-5)


def test_layer_states_roundtrip():
    layer = rnn.LSTM(8, num_layers=2, layout="NTC")
    layer.initialize()
    x = nd.random.uniform(shape=(3, 5, 4))
    states = layer.begin_state(3)
    out, new_states = layer(x, states)
    assert out.shape == (3, 5, 8)
    assert new_states[0].shape == (2, 3, 8)
    assert new_states[1].shape == (2, 3, 8)
    assert not np.allclose(_np(new_states[0]), 0)


def test_layer_ntc_tnc_parity():
    layer1 = rnn.GRU(6, layout="TNC")
    layer1.initialize()
    x = nd.random.uniform(shape=(4, 2, 3))  # T, N, C
    out1 = layer1(x)
    layer2 = rnn.GRU(6, layout="NTC", prefix=layer1.prefix,
                     params=layer1.collect_params())
    out2 = layer2(x.transpose((1, 0, 2)))
    np.testing.assert_allclose(_np(out1), _np(out2.transpose((1, 0, 2))),
                               rtol=1e-5, atol=1e-6)


def test_variable_length_masking():
    layer = rnn.LSTM(4, layout="NTC")
    layer.initialize()
    x = nd.random.uniform(shape=(2, 6, 3))
    vl = nd.array(np.array([6, 3], np.float32))
    out = layer(x, sequence_length=vl)
    o = _np(out)
    assert np.allclose(o[1, 3:], 0)      # masked past valid length
    assert not np.allclose(o[1, :3], 0)


@pytest.mark.slow
def test_rnn_backward_flows():
    layer = rnn.GRU(8, num_layers=2, layout="NTC")
    layer.initialize()
    x = nd.random.uniform(shape=(2, 5, 4))
    with mx.autograd.record():
        out = layer(x)
        loss = (out * out).sum()
    loss.backward()
    g = layer.collect_params()[layer.prefix + "l0_i2h_weight"].grad()
    assert g is not None and float(nd.abs(g).sum()) > 0


def test_rnn_hybridize_parity():
    net = gluon.nn.HybridSequential()
    net.add(rnn.LSTM(8, layout="NTC"))
    net.add(gluon.nn.Dense(3))
    net.initialize()
    x = nd.random.uniform(shape=(2, 5, 4))
    eager = net(x)
    net.hybridize()
    jitted = net(x)
    np.testing.assert_allclose(_np(eager), _np(jitted), rtol=1e-5, atol=1e-6)


def test_bidirectional_cell():
    l = rnn.LSTMCell(5)
    r = rnn.LSTMCell(5)
    bi = rnn.BidirectionalCell(l, r)
    bi.initialize()
    x = nd.random.uniform(shape=(3, 4, 2))  # N, T, C
    out, states = bi.unroll(4, x, layout="NTC")
    assert out.shape == (3, 4, 10)
    assert len(states) == 4


def test_zoneout_dropout_cells():
    base = rnn.GRUCell(6)
    z = rnn.ZoneoutCell(base, zoneout_outputs=0.5, zoneout_states=0.5)
    z.initialize()
    x = nd.random.uniform(shape=(4, 3))
    s = z.begin_state(4)
    out_eval, _ = z(x, s)   # no autograd → eval passthrough
    base_out, _ = base(x, s)
    np.testing.assert_allclose(_np(out_eval), _np(base_out), rtol=1e-6)
    d = rnn.DropoutCell(0.3)
    out, states = d(x, [])
    assert out.shape == x.shape and states == []


def test_bidirectional_layer_valid_length():
    # regression: reverse-direction mask must use true time index
    layer = rnn.LSTM(4, layout="NTC", bidirectional=True)
    layer.initialize()
    x = nd.random.uniform(shape=(2, 6, 3), low=-1, high=1)
    vl = nd.array(np.array([6, 3], np.float32))
    out = layer(x, sequence_length=vl)
    o = _np(out)
    assert np.allclose(o[1, 3:], 0), "padding must be zeroed"
    assert not np.allclose(o[1, :3], 0), "valid steps must be processed"
    # sample-1 valid prefix must equal running the same params on the
    # truncated sequence alone
    out_short = layer(x[1:2, :3], sequence_length=nd.array(np.array([3.0])))
    np.testing.assert_allclose(o[1, :3], _np(out_short)[0], rtol=1e-5,
                               atol=1e-6)


@pytest.mark.slow
def test_bidirectional_cell_valid_length():
    l, r = rnn.LSTMCell(4), rnn.LSTMCell(4)
    bi = rnn.BidirectionalCell(l, r)
    bi.initialize()
    x = nd.random.uniform(shape=(2, 6, 3), low=-1, high=1)
    vl = nd.array(np.array([6, 3], np.float32))
    out, states = bi.unroll(6, x, layout="NTC", valid_length=vl)
    o = _np(out)
    assert np.allclose(o[1, 3:], 0)
    out_short, _ = bi.unroll(3, x[1:2, :3], layout="NTC",
                             valid_length=nd.array(np.array([3.0])))
    np.testing.assert_allclose(o[1, :3], _np(out_short)[0], rtol=1e-5,
                               atol=1e-6)
