"""mxtpu.perfscope: roofline cost analysis, step-time decomposition,
and the BENCH regression gate (tools/perf_regress.py) — plus the
trace_check schema enforcement for the new perfscope.* counter family
and `extra.perfscope` BENCH section."""
import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import diagnostics as diag
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu import perfscope as ps
from incubator_mxnet_tpu import profiler as prof


def _load_tool(name):
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _perfscope_teardown():
    yield
    ps.disable()
    ps.reset_programs()
    diag.disable()


def _counters(prefix="perfscope/"):
    return {k: v for k, v in prof.counters().items()
            if k.startswith(prefix)}


# ---------------------------------------------------------------------------
# roofline classification
# ---------------------------------------------------------------------------

class TestClassify:
    def test_compute_bound(self):
        # AI far above any ridge
        r = ps.classify(1e12, 1e6)
        assert r["verdict"] == "compute_bound"
        assert r["ai"] == pytest.approx(1e6)
        assert r["est_compute_ms"] > 0

    def test_hbm_bound(self):
        # 1 FLOP per byte is below every ridge in the table
        r = ps.classify(1e9, 1e9)
        assert r["verdict"] == "hbm_bound"
        assert r["ai"] == pytest.approx(1.0)

    def test_zero_flops_is_trivial(self):
        r = ps.classify(0, 0)
        assert r["verdict"] == "trivial"
        assert r["flops"] == 0.0

    def test_small_flops_is_trivial(self):
        assert ps.classify(100.0, 1e12)["verdict"] == "trivial"

    def test_missing_flops_is_unknown(self):
        r = ps.classify(None, None)
        assert r["verdict"] == "unknown"
        assert r["flops"] is None and r["ai"] is None

    def test_garbage_inputs_are_unknown(self):
        assert ps.classify("not-a-number", {})["verdict"] == "unknown"

    def test_flops_without_bytes_is_compute_bound(self):
        # real FLOPs, zero reported traffic -> compute is the only ceiling
        r = ps.classify(1e10, 0)
        assert r["verdict"] == "compute_bound"
        assert r["ai"] is None

    def test_trivial_threshold_env_override(self, monkeypatch):
        monkeypatch.setenv("MXTPU_PERFSCOPE_TRIVIAL_FLOPS", "1")
        assert ps.classify(100.0, 1e12)["verdict"] == "hbm_bound"

    def test_verdict_taxonomy_is_closed(self):
        for args in ((1e12, 1e6), (1e9, 1e9), (0, 0), (None, None)):
            assert ps.classify(*args)["verdict"] in ps.ROOFLINE_VERDICTS


class _FakeDevice:
    def __init__(self, kind):
        self.device_kind = kind


class TestPeaks:
    def test_cpu_fallback(self):
        p = ps.device_peaks()
        assert p["table_row"] == "cpu"
        assert p["peak_flops_f32"] > 0 and p["hbm_bytes_per_s"] > 0

    @pytest.mark.parametrize("kind,row", [
        ("TPU v5 lite", "v5e"),       # what jax reports for a v5e
        ("v5litepod-8", "v5e"),       # the GCE accelerator type
        ("TPU v5e", "v5e"),
        ("TPU v4", "v4"),
        ("TPU v5p", "v5p"),           # must not fall into the v5e row
        ("weird accelerator", "cpu"),
    ])
    def test_device_kind_matching(self, kind, row):
        p = ps.device_peaks(_FakeDevice(kind))
        assert p["table_row"] == row

    def test_v5e_bf16_peak_matches_bench_constant(self):
        # PERF.md's MFU numbers were computed against 197 Tf bf16; the
        # table must reproduce that for the real chip's kind string
        p = ps.device_peaks(_FakeDevice("TPU v5 lite"))
        assert p["peak_flops_bf16"] == pytest.approx(197e12)
        assert p["peak_flops_f32"] == pytest.approx(99e12)

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("MXTPU_PEAK_FLOPS", "123e12")
        monkeypatch.setenv("MXTPU_PEAK_BW", "456e9")
        p = ps.device_peaks()
        assert p["peak_flops_f32"] == pytest.approx(123e12)
        assert p["peak_flops_bf16"] == pytest.approx(123e12)
        assert p["hbm_bytes_per_s"] == pytest.approx(456e9)

    def test_malformed_env_overrides_never_raise(self, monkeypatch):
        monkeypatch.setenv("MXTPU_PEAK_FLOPS", "197 Tf")
        monkeypatch.setenv("MXTPU_PEAK_BW", "lots")
        monkeypatch.setenv("MXTPU_PERFSCOPE_TRIVIAL_FLOPS", "tiny")
        p = ps.device_peaks()                       # table kept
        assert p["peak_flops_f32"] > 0
        assert ps.classify(1e12, 1e6)["verdict"] == "compute_bound"
        ps.record_program("t_env", 1e12, 1e6)       # never raises

    def test_bf16_uses_doubled_peak(self):
        from incubator_mxnet_tpu.perfscope.cost import peak_flops_for
        peaks = {"peak_flops_f32": 1.0, "peak_flops_bf16": 2.0}
        assert peak_flops_for("bfloat16", peaks) == 2.0
        assert peak_flops_for(jnp.float32, peaks) == 1.0


# ---------------------------------------------------------------------------
# cost analysis of real programs (CPU backend)
# ---------------------------------------------------------------------------

class TestAnalyze:
    def test_matmul_lowered(self):
        ps.enable()
        lowered = jax.jit(lambda a, b: (a @ b).sum()).lower(
            jax.ShapeDtypeStruct((256, 256), jnp.float32),
            jax.ShapeDtypeStruct((256, 256), jnp.float32))
        rec = ps.analyze_lowered(lowered, "t_matmul")
        assert rec["flops"] and rec["flops"] > 2 * 256 ** 3 * 0.9
        assert rec["verdict"] in ("compute_bound", "hbm_bound")
        names = [p["name"] for p in ps.programs()]
        assert "t_matmul" in names
        c = _counters()
        assert c["perfscope/perfscope.programs_analyzed"] >= 1

    def test_identity_program_missing_keys_is_unknown(self):
        # XLA:CPU reports an EMPTY analysis for data-movement-only
        # programs — the satellite's missing-cost_analysis-keys case
        ps.enable()
        lowered = jax.jit(lambda a: a).lower(
            jax.ShapeDtypeStruct((8,), jnp.float32))
        rec = ps.analyze_lowered(lowered, "t_identity")
        assert rec["verdict"] == "unknown"
        assert rec["flops"] is None
        assert _counters()["perfscope/perfscope.unknown"] >= 1

    def test_analyze_lowered_never_raises(self):
        ps.enable()
        rec = ps.analyze_lowered(object(), "t_garbage")
        assert rec["verdict"] == "unknown"

    def test_analyze_jit_never_raises(self):
        ps.enable()
        rec = ps.analyze_jit(object(), (jnp.ones(3),), "t_garbage_jit")
        assert rec["verdict"] == "unknown"

    def test_flight_compile_span_gains_cost_fields(self, tmp_path):
        # satellite: compile-span records carry flops/bytes/roofline
        diag.enable_flight_recorder(dump_dir=str(tmp_path),
                                    dump_on_crash=False)
        ps.enable()
        lowered = jax.jit(lambda a, b: a @ b).lower(
            jax.ShapeDtypeStruct((128, 128), jnp.float32),
            jax.ShapeDtypeStruct((128, 128), jnp.float32))
        ps.analyze_lowered(lowered, "t_flight")
        path = diag.dump_flight(reason="test")
        doc = json.load(open(path))
        spans = [e for e in doc["events"]
                 if e["kind"] == "compile"
                 and e["name"] == "perfscope.cost:t_flight"]
        assert len(spans) == 1
        args = spans[0]["args"]
        assert args["flops"] > 0
        assert args["bytes_accessed"] > 0
        assert args["roofline"] in ps.ROOFLINE_VERDICTS
        # the pretty-printer renders the enriched span without crashing
        mxdiag = _load_tool("mxdiag")
        mxdiag.print_flight(doc, 10)

    def test_last_analysis_wins_per_name(self):
        ps.enable()
        ps.record_program("t_dup", 1e12, 1e6)
        ps.record_program("t_dup", 1e9, 1e9)
        recs = [p for p in ps.programs() if p["name"] == "t_dup"]
        assert len(recs) == 1 and recs[0]["verdict"] == "hbm_bound"


# ---------------------------------------------------------------------------
# compile-site integration
# ---------------------------------------------------------------------------

def _tiny_net(units=8, in_units=16):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(units, in_units=in_units))
    net.initialize(init=mx.init.Xavier())
    return net


class TestCompileSites:
    def test_fused_step_capture(self):
        from incubator_mxnet_tpu.parallel import FusedTrainStep
        ps.enable()
        net = _tiny_net()
        L = gluon.loss.SoftmaxCrossEntropyLoss()
        step = FusedTrainStep(net, L, mx.optimizer.create("sgd"))
        x = nd.array(np.random.rand(4, 16).astype(np.float32))
        y = nd.array(np.random.randint(0, 8, 4))
        float(step(x, y))
        by_name = {p["name"]: p for p in ps.programs()}
        assert "fused_step" in by_name
        assert by_name["fused_step"]["kind"] == "train_step"
        assert by_name["fused_step"]["verdict"] in ps.ROOFLINE_VERDICTS
        # analysis happens once, not per step
        n0 = _counters()["perfscope/perfscope.programs_analyzed"]
        float(step(x, y))
        assert _counters()["perfscope/perfscope.programs_analyzed"] == n0

    def test_reanalysis_on_batch_signature_change(self):
        # a shape-driven recompile must refresh the program record —
        # the table has to describe the program actually being timed
        from incubator_mxnet_tpu.parallel import FusedTrainStep
        ps.enable()
        net = _tiny_net()
        L = gluon.loss.SoftmaxCrossEntropyLoss()
        step = FusedTrainStep(net, L, mx.optimizer.create("sgd"))
        x4 = nd.array(np.random.rand(4, 16).astype(np.float32))
        y4 = nd.array(np.random.randint(0, 8, 4))
        float(step(x4, y4))
        flops4 = {p["name"]: p["flops"] for p in ps.programs()}["fused_step"]
        x16 = nd.array(np.random.rand(16, 16).astype(np.float32))
        y16 = nd.array(np.random.randint(0, 8, 16))
        float(step(x16, y16))
        flops16 = {p["name"]: p["flops"] for p in ps.programs()}["fused_step"]
        assert flops16 > flops4

    def test_capture_does_not_double_count_selection(self):
        # perfscope's re-lowering must not re-increment the pallas
        # selection counters (ops/select quiet scope)
        from incubator_mxnet_tpu.ops import select as sel
        ps.enable()
        before = prof.counters().get("ops/pallas.selected.t_fake", 0) or 0
        with sel.quiet():
            sel._decide("t_fake", True, "ok")
        after = prof.counters().get("ops/pallas.selected.t_fake", 0) or 0
        assert after == before
        sel._decide("t_fake", True, "ok")    # un-quieted still counts
        assert prof.counters()["ops/pallas.selected.t_fake"] == before + 1

    def test_run_k_capture(self):
        from incubator_mxnet_tpu.parallel import FusedTrainStep
        ps.enable()
        net = _tiny_net()
        L = gluon.loss.SoftmaxCrossEntropyLoss()
        step = FusedTrainStep(net, L, mx.optimizer.create("sgd"))
        x = nd.array(np.random.rand(4, 16).astype(np.float32))
        y = nd.array(np.random.randint(0, 8, 4))
        xs = jnp.broadcast_to(x._data, (2,) + x._data.shape)
        ys = jnp.broadcast_to(y._data, (2,) + y._data.shape)
        float(step.run_k(xs, ys)[1])
        by_name = {p["name"]: p for p in ps.programs()}
        assert "fused_step_k2" in by_name
        assert by_name["fused_step_k2"]["k"] == 2

    def test_disabled_no_capture(self):
        from incubator_mxnet_tpu.parallel import FusedTrainStep
        assert not ps.enabled()
        net = _tiny_net()
        L = gluon.loss.SoftmaxCrossEntropyLoss()
        step = FusedTrainStep(net, L, mx.optimizer.create("sgd"))
        x = nd.array(np.random.rand(4, 16).astype(np.float32))
        y = nd.array(np.random.randint(0, 8, 4))
        float(step(x, y))
        assert all(not p["name"].startswith("fused_step")
                   for p in ps.programs())

    def test_jit_cache_capture(self):
        ps.enable()
        net = _tiny_net()
        net.hybridize()
        x = nd.array(np.random.rand(4, 16).astype(np.float32))
        net(x)
        jit_progs = [p for p in ps.programs() if p["kind"] == "jit_cache"]
        assert len(jit_progs) == 1
        assert jit_progs[0]["name"].startswith("jit:")
        assert jit_progs[0]["name"].endswith("4x16")

    def test_jit_cache_capture_opt_out(self):
        ps.enable(capture_jit_cache=False)
        net = _tiny_net()
        net.hybridize()
        net(nd.array(np.random.rand(4, 16).astype(np.float32)))
        assert not [p for p in ps.programs() if p["kind"] == "jit_cache"]

    def test_frozen_bucket_capture(self):
        from incubator_mxnet_tpu.serving import FrozenModel
        ps.enable()
        net = _tiny_net(units=4)
        FrozenModel(net, (16,), batch_buckets=(1, 2))
        buckets = sorted(p["bucket"] for p in ps.programs()
                         if p["kind"] == "serving_bucket")
        assert buckets == [1, 2]


# ---------------------------------------------------------------------------
# step-time decomposition
# ---------------------------------------------------------------------------

class TestStepBudget:
    def test_components_sum_to_step(self):
        ps.enable()
        f = jax.jit(lambda a: a @ a)
        x = jnp.ones((64, 64))
        f(x).block_until_ready()
        budget = ps.StepBudget().begin()
        import time as _t
        t0 = _t.perf_counter()
        for _ in range(8):
            td = _t.perf_counter()
            out = f(x)
            budget.add_dispatch(_t.perf_counter() - td)
        float(out.sum())
        dt = _t.perf_counter() - t0
        budget.end(steps=8, steady_s=dt)
        budget.probe(lambda: float(f(x).sum()), iters=3)
        d = budget.finish(model_flops_per_step=2 * 64 ** 3)
        comps = (d["device_compute_ms"] + d["collective_ms"]
                 + d["input_wait_ms"] + d["host_gap_ms"] + d["other_ms"])
        assert comps == pytest.approx(d["sum_ms"], abs=1e-3)
        # device is probe-clipped to the wall, so the sum never exceeds
        # step_ms by more than rounding
        assert abs(comps - d["step_ms"]) / d["step_ms"] < 0.15
        assert d["mfu"] is not None and d["mfu"] > 0
        g = _counters()
        assert g["perfscope/perfscope.step_ms"] == d["step_ms"]
        assert g["perfscope/perfscope.device_compute_ms"] == \
            d["device_compute_ms"]

    def test_input_wait_from_io_counter(self):
        ps.enable()
        budget = ps.StepBudget().begin()
        prof.counter("io.wait_ms", "io").increment(40.0)
        budget.end(steps=4, steady_s=0.1)   # 25 ms/step, 10 ms input wait
        d = budget.finish()
        assert d["input_wait_ms"] == pytest.approx(10.0)
        assert d["step_ms"] == pytest.approx(25.0)

    def test_collective_from_kvstore_counter(self):
        ps.enable()
        budget = ps.StepBudget().begin()
        prof.counter("kvstore.collective_ms").increment(20.0)
        budget.end(steps=4, steady_s=0.1)
        d = budget.finish()
        assert d["collective_ms"] == pytest.approx(5.0)

    def test_host_gap_capped_by_dispatch(self):
        ps.enable()
        budget = ps.StepBudget().begin()
        budget.add_dispatch(0.004)          # 1 ms/step measured host time
        budget.end(steps=4, steady_s=0.1)   # 25 ms/step wall
        d = budget.finish()
        # no probe: unexplained middle goes to device, host_gap <= 1ms
        assert d["host_gap_ms"] <= 1.0 + 1e-6
        assert d["device_compute_ms"] >= 23.0

    def test_probe_feeds_histogram(self):
        prof.reset_counters()
        p = ps.probe_device_time(lambda: None, iters=4)
        assert p["iters"] == 4 and p["median_ms"] >= 0
        h = prof.counters()["perfscope/perfscope.device_step_ms"]
        assert h["count"] == 4

    def test_mfu_counterfactuals(self):
        ps.enable()
        budget = ps.StepBudget().begin()
        prof.counter("io.wait_ms", "io").increment(200.0)  # 50 ms/step
        budget.end(steps=4, steady_s=0.4)                  # 100 ms/step
        d = budget.finish(model_flops_per_step=1e9)
        # removing 50 ms of input wait from a 100 ms step doubles MFU
        assert d["mfu_if_removed"]["input_wait"] == \
            pytest.approx(2 * d["mfu"], rel=1e-3)


class TestKVStoreCollectiveCounter:
    def test_timed_increments_when_perfscope_on(self):
        from incubator_mxnet_tpu.kvstore import _timed
        ps.enable()
        before = prof.counters().get("mxtpu/kvstore.collective_ms", 0)
        out = _timed("push", lambda: 42)
        assert out == 42
        after = prof.counters().get("mxtpu/kvstore.collective_ms", 0)
        assert after >= before >= 0 and after > 0

    def test_timed_passthrough_when_all_off(self):
        from incubator_mxnet_tpu.kvstore import _timed
        assert not ps.enabled()
        assert _timed("push", lambda: 7) == 7


# ---------------------------------------------------------------------------
# histogram percentiles under the perfscope family (satellite)
# ---------------------------------------------------------------------------

class TestPerfscopeHistogram:
    def test_percentile_interpolation(self):
        prof.reset_counters()
        h = prof.histogram("perfscope.device_step_ms", "perfscope")
        for v in (1.0, 2.0, 3.0, 4.0, 100.0):
            h.observe(v)
        snap = h.value
        assert snap["count"] == 5
        assert snap["min"] == 1.0 and snap["max"] == 100.0
        assert snap["p50"] <= snap["p95"] <= snap["p99"]
        # p50 of {1,2,3,4,100} lives in a low bucket; p99 near the max
        assert snap["p50"] <= 5.0
        assert snap["p99"] >= 50.0

    def test_single_observation_percentiles_clamped(self):
        prof.reset_counters()
        h = prof.histogram("perfscope.device_step_ms", "perfscope")
        h.observe(7.5)
        snap = h.value
        assert snap["p50"] == snap["p95"] == snap["p99"] == 7.5

    def test_empty_histogram(self):
        prof.reset_counters()
        h = prof.histogram("perfscope.device_step_ms", "perfscope")
        snap = h.value
        assert snap["count"] == 0 and snap["p50"] is None

    def test_family_table_accepts_histogram_kind(self):
        tc = _load_tool("trace_check")
        assert tc.check_healthmon_kinds(
            {"perfscope/perfscope.device_step_ms": "histogram"}) == []
        # a flipped kind is a schema violation
        assert tc.check_healthmon_kinds(
            {"perfscope/perfscope.device_step_ms": "counter"})


# ---------------------------------------------------------------------------
# trace_check: perfscope families + extra.perfscope schema
# ---------------------------------------------------------------------------

class TestTraceCheckPerfscope:
    def _good_section(self):
        return {
            "peaks": {"device_kind": "cpu", "table_row": "cpu",
                      "peak_flops_f32": 5e10, "peak_flops_bf16": 5e10,
                      "hbm_bytes_per_s": 2e10},
            "programs": [{"name": "fused_step", "verdict": "compute_bound",
                          "flops": 1e9, "bytes_accessed": 1e6, "ai": 1000.0}],
            "decomposition": {"step_ms": 100.0, "device_compute_ms": 90.0,
                              "collective_ms": 2.0, "input_wait_ms": 3.0,
                              "host_gap_ms": 4.0, "other_ms": 1.0,
                              "mfu": 0.2},
        }

    def test_good_section_validates(self):
        tc = _load_tool("trace_check")
        assert tc.check_perfscope_extra(self._good_section()) == []
        assert tc.check_perfscope_extra(None) == []

    def test_bad_verdict_fails(self):
        tc = _load_tool("trace_check")
        bad = self._good_section()
        bad["programs"][0]["verdict"] = "gpu_bound"
        assert any("verdict" in e for e in tc.check_perfscope_extra(bad))

    def test_sum_tolerance_enforced(self):
        tc = _load_tool("trace_check")
        bad = self._good_section()
        bad["decomposition"]["device_compute_ms"] = 10.0  # sum 20 vs 100
        assert any("sum" in e for e in tc.check_perfscope_extra(bad))

    def test_mfu_bounds(self):
        tc = _load_tool("trace_check")
        bad = self._good_section()
        bad["decomposition"]["mfu"] = 3.0
        assert any("mfu" in e for e in tc.check_perfscope_extra(bad))

    def test_unknown_family_fails(self):
        tc = _load_tool("trace_check")
        errs = tc.check_healthmon_kinds(
            {"perfscope/perfscope.invented": "counter"})
        assert errs and "PERFSCOPE_FAMILIES" in errs[0]

    def test_bench_json_with_perfscope(self, tmp_path):
        tc = _load_tool("trace_check")
        doc = {"metric": "m", "value": 1.0, "unit": "images/sec",
               "extra": {"mfu": 0.1, "perfscope": self._good_section()}}
        p = tmp_path / "BENCH_t.json"
        p.write_text(json.dumps(doc))
        assert tc.check_bench_json(str(p)) == []
        doc["extra"]["perfscope"]["programs"][0]["verdict"] = "nope"
        p.write_text(json.dumps(doc))
        assert tc.check_bench_json(str(p))


# ---------------------------------------------------------------------------
# perf_regress: the regression gate (satellite + acceptance)
# ---------------------------------------------------------------------------

def _bench_doc(value=1000.0, mfu=0.12, metric="m_img_s", p99=None,
               **over):
    doc = {"metric": metric, "value": value, "unit": "images/sec",
           "vs_baseline": None, "extra": {"mfu": mfu}}
    if p99 is not None:
        doc["extra"]["serving"] = {"p99_ms": p99}
    doc.update(over)
    return doc


class TestPerfRegress:
    def _write(self, tmp_path, name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_self_comparison_passes(self, tmp_path):
        pr = _load_tool("perf_regress")
        a = self._write(tmp_path, "BENCH_a.json", _bench_doc())
        assert pr.main([a, a]) == 0

    def test_20pct_regression_fails(self, tmp_path):
        pr = _load_tool("perf_regress")
        a = self._write(tmp_path, "BENCH_a.json", _bench_doc(1000.0))
        b = self._write(tmp_path, "BENCH_b.json",
                        _bench_doc(800.0, mfu=0.096))
        assert pr.main([a, b]) == 1

    def test_mfu_only_regression_fails(self, tmp_path):
        pr = _load_tool("perf_regress")
        a = self._write(tmp_path, "BENCH_a.json", _bench_doc(1000.0, 0.12))
        b = self._write(tmp_path, "BENCH_b.json", _bench_doc(1000.0, 0.08))
        assert pr.main([a, b]) == 1

    def test_p99_regression_fails(self, tmp_path):
        pr = _load_tool("perf_regress")
        a = self._write(tmp_path, "BENCH_a.json", _bench_doc(p99=10.0))
        b = self._write(tmp_path, "BENCH_b.json", _bench_doc(p99=20.0))
        assert pr.main([a, b]) == 1

    def test_small_drop_within_threshold_passes(self, tmp_path):
        pr = _load_tool("perf_regress")
        a = self._write(tmp_path, "BENCH_a.json", _bench_doc(1000.0, 0.12))
        b = self._write(tmp_path, "BENCH_b.json", _bench_doc(970.0, 0.1175))
        assert pr.main([a, b]) == 0

    def test_env_failure_candidate_skipped(self, tmp_path):
        pr = _load_tool("perf_regress")
        a = self._write(tmp_path, "BENCH_a.json", _bench_doc())
        b = self._write(tmp_path, "BENCH_b.json",
                        {"metric": "m_img_s", "value": 0.0,
                         "unit": "images/sec", "status": "env_failure",
                         "error": "preflight: probe hung"})
        assert pr.main([a, b]) == 0

    def test_legacy_error_artifact_skipped(self, tmp_path):
        # the BENCH_r02-r05 shape: driver wrapper, watchdog error line
        pr = _load_tool("perf_regress")
        a = self._write(tmp_path, "BENCH_a.json", _bench_doc())
        b = self._write(tmp_path, "BENCH_b.json", {
            "n": 2, "cmd": "python bench.py", "rc": 3,
            "parsed": {"metric": "m_img_s", "value": 0.0,
                       "unit": "images/sec", "vs_baseline": 0.0,
                       "error": "hard watchdog: backend init exceeded"}})
        assert pr.main([a, b]) == 0
        rec, why = pr.load_artifact(b)
        assert rec is None and "errored" in why

    def test_wrapper_with_null_parsed_skipped(self, tmp_path):
        pr = _load_tool("perf_regress")
        b = self._write(tmp_path, "BENCH_b.json",
                        {"n": 1, "cmd": "x", "rc": 1, "parsed": None})
        rec, why = pr.load_artifact(b)
        assert rec is None and "parsed" in why

    def test_trajectory_skips_env_failures(self, tmp_path):
        pr = _load_tool("perf_regress")
        self._write(tmp_path, "BENCH_r01.json", _bench_doc(1000.0))
        self._write(tmp_path, "BENCH_r02.json",
                    {"n": 2, "cmd": "x", "rc": 3,
                     "parsed": {"metric": "m_img_s", "value": 0.0,
                                "error": "hard watchdog"}})
        self._write(tmp_path, "BENCH_r03.json", _bench_doc(1020.0))
        self._write(tmp_path, "BENCH_r04.json", _bench_doc(990.0))
        # newest (r04) vs median of r01/r03: fine
        assert pr.main(["--dir", str(tmp_path)]) == 0
        # a degraded newest artifact trips the gate
        self._write(tmp_path, "BENCH_r05.json", _bench_doc(700.0, 0.08))
        assert pr.main(["--dir", str(tmp_path)]) == 1

    def test_trajectory_all_env_failures_is_ok(self, tmp_path):
        pr = _load_tool("perf_regress")
        self._write(tmp_path, "BENCH_r01.json",
                    {"n": 1, "cmd": "x", "rc": 3, "parsed": None})
        assert pr.main(["--dir", str(tmp_path)]) == 0

    def test_noise_widens_threshold(self, tmp_path):
        pr = _load_tool("perf_regress")
        # noisy trajectory: ±10% scatter; a 12% drop on the newest run
        # must NOT be flagged against a 2x noise band (20%)
        for i, v in enumerate((900.0, 1100.0, 1000.0), 1):
            self._write(tmp_path, f"BENCH_r0{i}.json",
                        _bench_doc(v, mfu=None))
        self._write(tmp_path, "BENCH_r04.json", _bench_doc(880.0, mfu=None))
        assert pr.main(["--dir", str(tmp_path)]) == 0

    def test_metric_mismatch_not_compared(self, tmp_path):
        pr = _load_tool("perf_regress")
        a = self._write(tmp_path, "BENCH_a.json",
                        _bench_doc(metric="resnet"))
        b = self._write(tmp_path, "BENCH_b.json",
                        _bench_doc(value=1.0, metric="lenet"))
        assert pr.main([a, b]) == 0


# ---------------------------------------------------------------------------
# mxdiag perf report
# ---------------------------------------------------------------------------

class TestMxdiagPerf:
    def test_report_renders(self, tmp_path, capsys):
        mxdiag = _load_tool("mxdiag")
        doc = _bench_doc()
        doc["extra"]["perfscope"] = {
            "peaks": {"device_kind": "cpu", "table_row": "cpu",
                      "peak_flops_f32": 5e10, "peak_flops_bf16": 5e10,
                      "hbm_bytes_per_s": 2e10},
            "programs": [{"name": "fused_step", "verdict": "compute_bound",
                          "flops": 8.7e8, "bytes_accessed": 2.2e8,
                          "ai": 3.9}],
            "decomposition": {"step_ms": 100.0, "device_compute_ms": 80.0,
                              "collective_ms": 5.0, "input_wait_ms": 10.0,
                              "host_gap_ms": 5.0, "other_ms": 0.0,
                              "steps": 50, "source": "probe",
                              "coverage": 1.0, "mfu": 0.1,
                              "mfu_device_only": 0.125,
                              "mfu_if_removed": {"input_wait": 0.111}},
        }
        p = tmp_path / "BENCH_x.json"
        p.write_text(json.dumps(doc))
        assert mxdiag.main(["perf", str(p)]) == 0
        out = capsys.readouterr().out
        assert "step budget" in out
        assert "device_compute" in out
        assert "MFU decomposition" in out
        assert "compute_bound" in out

    def test_report_without_perfscope_section(self, tmp_path, capsys):
        mxdiag = _load_tool("mxdiag")
        p = tmp_path / "BENCH_y.json"
        p.write_text(json.dumps(_bench_doc()))
        assert mxdiag.main(["perf", str(p)]) == 1
        assert "no extra.perfscope" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# bench_extra payload
# ---------------------------------------------------------------------------

class TestBenchExtra:
    def test_payload_shape_validates(self):
        ps.enable()
        lowered = jax.jit(lambda a, b: a @ b).lower(
            jax.ShapeDtypeStruct((64, 64), jnp.float32),
            jax.ShapeDtypeStruct((64, 64), jnp.float32))
        ps.analyze_lowered(lowered, "t_payload")
        payload = ps.bench_extra({"step_ms": 10.0, "device_compute_ms": 9.0,
                                  "collective_ms": 0.0,
                                  "input_wait_ms": 0.5, "host_gap_ms": 0.5,
                                  "other_ms": 0.0})
        tc = _load_tool("trace_check")
        assert tc.check_perfscope_extra(payload) == []
        assert json.loads(json.dumps(payload))  # JSON-serializable

    def test_enable_from_env(self, monkeypatch):
        monkeypatch.setenv("MXTPU_PERFSCOPE", "1")
        ps.enable_from_env()
        assert ps.enabled() and ps._PS.capture_jit_cache
        ps.disable()
        monkeypatch.setenv("MXTPU_PERFSCOPE", "jit0")
        ps.enable_from_env()
        assert ps.enabled() and not ps._PS.capture_jit_cache
