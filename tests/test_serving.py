"""mxtpu.serving — AOT-compiled inference with dynamic batching.

Covers the acceptance surface of the serving subsystem: FrozenModel
bit-exactness and bucket policy, the batcher's admission-control edge
cases (deadline expiry is a REJECTION not a silent drop, oversized /
mistyped inputs are clean client errors, queue-full backpressure fails
fast, graceful drain completes accepted work), the HTTP front end with
concurrent clients demonstrably coalescing, and the telemetry contract
(counters + latency histograms visible to the exporters and the flight
recorder with zero extra wiring).
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd, serving
from incubator_mxnet_tpu import profiler as prof
from incubator_mxnet_tpu.serving import (DeadlineExceededError,
                                         DynamicBatcher, FrozenModel,
                                         InvalidInputError, ModelServer,
                                         QueueFullError, ServerClosedError)


def _mlp(in_units=6, out=3, seed=0):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, in_units=in_units, activation="relu"),
            gluon.nn.Dense(out, in_units=16))
    net.initialize(init=mx.init.Xavier())
    rng = np.random.RandomState(seed)
    for p in net.collect_params().values():
        p.set_data(nd.array(rng.randn(*p.shape).astype(np.float32) * 0.1))
    return net


@pytest.fixture
def frozen():
    return FrozenModel(_mlp(), input_shape=(6,), batch_buckets=(1, 2, 4, 8))


# ---------------------------------------------------------------------------
# FrozenModel
# ---------------------------------------------------------------------------

def test_frozen_precompiles_every_bucket_and_matches_eager(frozen):
    net = _mlp()          # same seeded params as the fixture's source
    net_h = _mlp()
    net_h.hybridize()
    assert set(frozen._exec) == {1, 2, 4, 8}
    for n in (1, 3, 5, 8):
        x = np.random.RandomState(n).randn(n, 6).astype(np.float32)
        out = frozen(x).asnumpy()
        # BIT-exact vs the hybridized forward: freezing runs the same
        # whole-graph XLA program as the CachedOp. Per-op eager can
        # legitimately differ by 1 ULP from any compiled path (fusion),
        # so that comparison is allclose at float32 resolution.
        np.testing.assert_array_equal(out, net_h(nd.array(x)).asnumpy())
        np.testing.assert_allclose(out, net(nd.array(x)).asnumpy(),
                                   rtol=1e-6, atol=1e-7)


def test_frozen_padding_rows_do_not_leak_into_real_rows(frozen):
    x = np.random.RandomState(1).randn(3, 6).astype(np.float32)
    padded = frozen.predict_batch(x)[0]              # bucket 4, 1 pad row
    exact = frozen.predict_batch(
        np.concatenate([x, np.random.RandomState(9).randn(1, 6)
                        .astype(np.float32)]))[0][:3]  # same bucket, junk row
    np.testing.assert_array_equal(padded, exact)


def test_frozen_is_immutable_after_training(frozen):
    x = np.random.RandomState(2).randn(2, 6).astype(np.float32)
    before = frozen(x).asnumpy()
    net = _mlp(seed=0)
    for p in net.collect_params().values():          # "train" the source
        p.set_data(p.data() * 0 + 1)
    np.testing.assert_array_equal(frozen(x).asnumpy(), before)


def test_frozen_bucket_policy(frozen):
    assert frozen.bucket_for(1) == 1
    assert frozen.bucket_for(3) == 4
    assert frozen.bucket_for(8) == 8
    with pytest.raises(InvalidInputError):
        frozen.bucket_for(9)


def test_freeze_handoff_and_env_buckets(monkeypatch):
    monkeypatch.setenv("MXTPU_SERVING_BUCKETS", "1,4")
    fm = _mlp().freeze(input_shape=(6,))
    assert fm.buckets == (1, 4)


def test_frozen_from_exported_checkpoint(tmp_path):
    net = _mlp()
    net.hybridize()
    x = nd.array(np.random.RandomState(3).randn(2, 6).astype(np.float32))
    ref = net(x).asnumpy()
    prefix = str(tmp_path / "served")
    net.export(prefix)
    fm = FrozenModel.from_exported(prefix, input_shape=(6,),
                                   input_name="data",
                                   batch_buckets=(1, 2))
    np.testing.assert_allclose(fm(x).asnumpy(), ref, rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# DynamicBatcher admission control
# ---------------------------------------------------------------------------

def test_batcher_coalesces_concurrent_requests(frozen):
    b = DynamicBatcher(frozen, max_delay_ms=50, queue_limit=64).start()
    prof.reset_counters()
    xs = np.random.RandomState(4).randn(12, 6).astype(np.float32)
    results = [None] * 12

    def client(i):
        results[i] = b.predict(xs[i])

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    b.stop()
    stats = b.stats()
    assert stats["serving.responses"] == 12
    assert stats["serving.batches"] < 12          # demonstrably coalesced
    assert stats["batch_fill"] > 1.5
    net = _mlp()
    for i in range(12):
        ref = net(nd.array(xs[i:i + 1])).asnumpy()[0]
        np.testing.assert_array_equal(results[i][0], ref)


def test_deadline_expired_requests_rejected_not_dropped(frozen):
    b = DynamicBatcher(frozen, max_delay_ms=1, queue_limit=8)
    # batcher NOT started: requests age in the queue past their deadline
    req = b.submit(np.zeros(6, np.float32), timeout_ms=20)
    time.sleep(0.08)
    b.start()                                     # dispatcher finds it late
    with pytest.raises(DeadlineExceededError):
        req.wait(5.0)
    b.stop()
    assert prof.counters().get("serving/serving.rejected_deadline", 0) >= 1


def test_oversized_input_is_clean_client_error(frozen):
    b = DynamicBatcher(frozen)
    with pytest.raises(InvalidInputError) as ei:
        b.submit(np.zeros((9, 6), np.float32))    # > largest bucket... but
    # a multi-sample array is first rejected as not-a-single-sample
    assert ei.value.code == 400


def test_shape_and_dtype_mismatch_rejected(frozen):
    b = DynamicBatcher(frozen)
    with pytest.raises(InvalidInputError):
        b.submit(np.zeros(7, np.float32))         # wrong shape
    with pytest.raises(InvalidInputError):
        b.submit(np.zeros(6, np.float64))         # wrong dtype
    assert prof.counters().get("serving/serving.requests", 0) >= 0


def test_queue_full_backpressure_fails_fast(frozen):
    b = DynamicBatcher(frozen, queue_limit=4)     # not started: queue holds
    for _ in range(4):
        b.submit(np.zeros(6, np.float32))
    with pytest.raises(QueueFullError) as ei:
        b.submit(np.zeros(6, np.float32))
    assert ei.value.code == 429
    b._closed = True                              # discard quietly
    b._stopped = True


def test_graceful_drain_completes_accepted_requests(frozen):
    b = DynamicBatcher(frozen, max_delay_ms=500, queue_limit=64)
    reqs = [b.submit(np.random.RandomState(i).randn(6).astype(np.float32),
                     timeout_ms=0)               # 0 = no deadline
            for i in range(6)]
    b.start()
    b.stop(drain=True)                            # must serve all six
    for r in reqs:
        out = r.wait(0.1)                         # already fulfilled
        assert out[0].shape == (3,)
    with pytest.raises(ServerClosedError):
        b.submit(np.zeros(6, np.float32))


def test_stop_without_drain_rejects_not_drops(frozen):
    b = DynamicBatcher(frozen, queue_limit=8)
    reqs = [b.submit(np.zeros(6, np.float32)) for _ in range(3)]
    b.stop(drain=False)
    for r in reqs:
        with pytest.raises(ServerClosedError):
            r.wait(1.0)


# ---------------------------------------------------------------------------
# ModelServer (HTTP)
# ---------------------------------------------------------------------------

def _post(url, doc, timeout=30):
    body = json.dumps(doc).encode()
    req = urllib.request.Request(url, data=body,
                                 headers={"Content-Type":
                                          "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_http_server_concurrent_clients_batch_and_bit_exact(frozen):
    prof.reset_counters()
    srv = ModelServer(frozen, max_delay_ms=25, queue_limit=128)
    host, port = srv.start()
    url = f"http://{host}:{port}/predict"
    n = 64
    xs = np.random.RandomState(7).randn(n, 6).astype(np.float32)
    out = [None] * n
    errs = []

    def client(i):
        try:
            _, out[i] = _post(url, {"data": xs[i].tolist()})
        except Exception as e:  # noqa: BLE001
            errs.append((i, repr(e)))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs[:3]
    stats = srv.stats()
    srv.stop()
    # zero dropped; demonstrable coalescing; sane latency telemetry
    assert stats["serving.responses"] == n
    assert stats["batch_fill"] > 1.5, stats
    assert 0 < stats["p50_ms"] <= stats["p95_ms"] <= stats["p99_ms"]
    # bit-exact vs the compiled forward on the SAME batch composition
    # each request was actually served in (batch_id/batch_index report
    # it); eager-per-op is checked at float32 resolution — see the
    # FrozenModel test for why
    net_h = _mlp()
    net_h.hybridize()
    by_batch = {}
    for i in range(n):
        by_batch.setdefault(out[i]["batch_id"], []).append(i)
    for idxs in by_batch.values():
        rows = sorted(idxs, key=lambda i: out[i]["batch_index"])
        xb = xs[rows]
        bucket = frozen.bucket_for(len(rows))
        if bucket != len(rows):
            xb = np.concatenate(
                [xb, np.zeros((bucket - len(rows), 6), np.float32)])
        ref = net_h(nd.array(xb)).asnumpy()
        for pos, i in enumerate(rows):
            got = np.asarray(out[i]["output"], np.float32)
            np.testing.assert_array_equal(got, ref[pos])
    net = _mlp()
    for i in range(0, n, 8):
        ref1 = net(nd.array(xs[i:i + 1])).asnumpy()[0]
        np.testing.assert_allclose(
            np.asarray(out[i]["output"], np.float32), ref1,
            rtol=1e-6, atol=1e-7)
    assert any(o["batch_size"] > 1 for o in out)


def test_http_error_codes_and_healthz(frozen):
    srv = ModelServer(frozen, max_delay_ms=5)
    host, port = srv.start()
    base = f"http://{host}:{port}"
    with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
        doc = json.loads(r.read())
        assert r.status == 200 and doc["status"] == "ok"
        assert doc["buckets"] == [1, 2, 4, 8]
    # malformed body -> 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{base}/predict", {"nope": 1})
    assert ei.value.code == 400
    # wrong shape -> 400 with the taxonomy name
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{base}/predict", {"data": [1.0, 2.0]})
    assert ei.value.code == 400
    assert json.loads(ei.value.read())["error"] == "InvalidInputError"
    # unknown route -> 404
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{base}/bogus", timeout=10)
    assert ei.value.code == 404
    srv.stop()


def test_http_stats_and_telemetry_flow_through_exporters(frozen):
    from incubator_mxnet_tpu import diagnostics as diag
    from incubator_mxnet_tpu.diagnostics import flight as _flight
    prof.reset_counters()
    diag.enable_flight_recorder(dump_on_crash=False, record_ops=False)
    try:
        srv = ModelServer(frozen, max_delay_ms=5)
        host, port = srv.start()
        for i in range(5):
            _post(f"http://{host}:{port}/predict",
                  {"data": [0.1 * i] * 6})
        with urllib.request.urlopen(f"http://{host}:{port}/stats",
                                    timeout=10) as r:
            stats = json.loads(r.read())
        srv.stop()
        assert stats["serving.responses"] == 5
        assert stats["qps"] > 0
        assert stats["serving.latency_ms"]["count"] == 5
        # Prometheus text: histogram family with cumulative buckets
        text = diag.prometheus_text()
        assert "# TYPE serving_serving_latency_ms histogram" in text
        assert 'serving_serving_latency_ms_bucket{le="+Inf"} 5.0' in text
        # flight dump carries serving events + the histogram snapshot
        path = _flight.dump(reason="test")
        doc = json.load(open(path))
        assert any(e["kind"] == "serving" for e in doc["events"])
        assert doc["counter_kinds"]["serving/serving.latency_ms"] == \
            "histogram"
        assert doc["counters"]["serving/serving.latency_ms"]["count"] == 5
    finally:
        diag.disable_flight_recorder()


# ---------------------------------------------------------------------------
# Histogram kind
# ---------------------------------------------------------------------------

def test_histogram_percentiles_and_snapshot_shape():
    prof.reset_counters()
    h = prof.histogram("t.lat_ms", "serving")
    for v in [1.0] * 50 + [10.0] * 45 + [400.0] * 5:
        h.observe(v)
    s = h.value
    assert s["count"] == 100 and s["buckets"]["+Inf"] == 100
    assert s["min"] == 1.0 and s["max"] == 400.0
    assert s["p50"] <= s["p95"] <= s["p99"] <= 400.0
    assert s["p50"] <= 10.0 and s["p99"] > 10.0
    # registered in the shared registry with its kind
    assert prof.counter_kinds()["serving/t.lat_ms"] == "histogram"
    # a name already registered as a counter cannot become a histogram
    prof.counter("t.plain", "serving").increment()
    with pytest.raises(TypeError):
        prof.histogram("t.plain", "serving")


def test_histogram_concurrent_observe_consistency():
    prof.reset_counters()
    h = prof.histogram("t.conc", "serving")
    n_threads, per = 8, 500

    def work(seed):
        rng = np.random.RandomState(seed)
        for _ in range(per):
            h.observe(float(rng.gamma(2.0, 5.0)))

    ts = [threading.Thread(target=work, args=(i,)) for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    s = h.value
    assert s["count"] == n_threads * per
    assert s["buckets"]["+Inf"] == n_threads * per


def test_trace_check_validates_serving_artifacts(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "trace_check", "tools/trace_check.py")
    tc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tc)
    prof.reset_counters()
    h = prof.histogram("t.check", "serving")
    for v in (1.0, 5.0, 300.0):
        h.observe(v)
    assert tc.check_histogram_snapshot(h.value) == []
    bad = h.value
    bad["buckets"]["+Inf"] = 99                   # torn snapshot
    assert tc.check_histogram_snapshot(bad)
    # bench-json serving section validation
    good = {"metric": "serving_x", "value": 1.0, "extra": {"serving": {
        "requests": 3, "responses": 3, "batches": 2, "batch_fill": 1.5,
        "p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0, "qps": 10.0,
        "latency_ms": h.value}}}
    p = tmp_path / "BENCH_serving.json"
    p.write_text(json.dumps(good))
    assert tc.check_bench_json(str(p)) == []
    assert tc.check_file(str(p)) == []            # auto-detected kind
    good["extra"]["serving"]["p99_ms"] = 0.5      # unordered percentiles
    p.write_text(json.dumps(good))
    assert tc.check_bench_json(str(p))


# ---------------------------------------------------------------------------
# deep /healthz (healthmon PR satellite)
# ---------------------------------------------------------------------------

def _get_healthz(base):
    try:
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_deep_healthz_reports_checks_when_healthy(frozen):
    srv = ModelServer(frozen, max_delay_ms=2)
    host, port = srv.start()
    base = f"http://{host}:{port}"
    try:
        code, doc = _get_healthz(base)
        assert code == 200 and doc["status"] == "ok"
        checks = doc["checks"]
        assert checks["batcher_alive"] is True
        assert checks["queue_depth"] == 0
        assert checks["queue_limit"] == srv.batcher.queue_limit
        assert checks["queue_saturation"] == 0.0
        assert checks["last_predict_age_s"] is None   # no traffic yet
        assert checks["healthmon"]["enabled"] is False
        # after a predict the freshness age becomes a small number
        _post(base + "/predict", {"data": np.zeros(6).tolist()})
        code, doc = _get_healthz(base)
        assert code == 200
        age = doc["checks"]["last_predict_age_s"]
        assert age is not None and 0 <= age < 10
    finally:
        srv.stop()


def test_deep_healthz_503_when_dispatcher_dead(frozen):
    srv = ModelServer(frozen, max_delay_ms=2)
    host, port = srv.start()
    base = f"http://{host}:{port}"
    try:
        # kill the dispatcher thread without marking the server draining
        # — exactly the wedge a load balancer must be able to see
        srv.batcher.stop(drain=True)
        srv._draining = False
        code, doc = _get_healthz(base)
        assert code == 503 and doc["status"] == "degraded"
        assert "batcher_dead" in doc["problems"]
    finally:
        srv.stop()


def test_deep_healthz_503_when_queue_saturated(frozen):
    srv = ModelServer(frozen, max_delay_ms=2, queue_limit=4)
    host, port = srv.start()
    base = f"http://{host}:{port}"
    try:
        # saturate without serving: park requests in the queue with the
        # dispatcher parked (stopped thread, queue left intact)
        srv.batcher._stopped = True
        srv.batcher._thread.join(2)
        for _ in range(4):
            srv.batcher._q.append(object())
        code, doc = _get_healthz(base)
        assert code == 503
        assert "queue_saturated" in doc["problems"]
        assert doc["checks"]["queue_saturation"] >= 1.0
        srv.batcher._q.clear()
    finally:
        srv.stop(drain=False)


def test_deep_healthz_draining_still_503_with_checks(frozen):
    srv = ModelServer(frozen, max_delay_ms=2)
    host, port = srv.start()
    base = f"http://{host}:{port}"
    try:
        srv._draining = True
        code, doc = _get_healthz(base)
        assert code == 503 and doc["status"] == "draining"
        assert "checks" in doc            # deep info even while draining
    finally:
        srv._draining = False
        srv.stop()


def test_deep_healthz_reports_healthmon_watchdog_status(frozen):
    from incubator_mxnet_tpu import healthmon as hm
    from incubator_mxnet_tpu.profiler.counters import reset_counters
    srv = ModelServer(frozen, max_delay_ms=2)
    host, port = srv.start()
    base = f"http://{host}:{port}"
    try:
        import tempfile
        mon = hm.enable(hm_dir=tempfile.mkdtemp(), stall_timeout_s=0)
        mon.observe_loss(float("nan"))
        code, doc = _get_healthz(base)
        # training-side alerts are REPORTED, not a routing failure
        assert code == 200
        assert doc["checks"]["healthmon"]["enabled"] is True
        assert doc["checks"]["healthmon"]["nan_alerts"] == 1
    finally:
        hm.disable()
        reset_counters()
        srv.stop()


def test_serving_batches_emit_structured_events(frozen, tmp_path):
    from incubator_mxnet_tpu import healthmon as hm
    mon = hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0)
    b = DynamicBatcher(frozen, max_delay_ms=2).start()
    try:
        b.predict(np.zeros(6, np.float32))
    finally:
        b.stop()
        hm.disable()
    recs = [json.loads(ln) for ln in open(mon.events.path)
            if ln.strip()]
    batch = [r for r in recs if r["name"] == "serving.batch"]
    assert batch and batch[0]["kind"] == "serving"
    assert batch[0]["args"]["n"] == 1
