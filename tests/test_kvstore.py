"""KVStore: bucketed compiled collectives, compression, row_sparse_pull.

Parity model: python/mxnet/kvstore.py + src/kvstore/kvstore_dist.h
(dist_sync_device semantics on the 8-virtual-device CPU mesh).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd

import jax
import jax.numpy as jnp


def _nd(x):
    return nd.array(np.asarray(x, np.float32))


def test_local_pushpull_scalar_key():
    kv = mx.kv.create("local")
    kv.init(3, _nd(np.ones((2, 3))))
    vals = [_nd(np.full((2, 3), i, np.float32)) for i in range(1, 5)]
    out = _nd(np.zeros((2, 3)))
    kv.pushpull(3, vals, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 10.0))


def test_push_pull_accumulate():
    kv = mx.kv.create("device")
    kv.init("w", _nd(np.zeros((4,))))
    kv.push("w", _nd(np.arange(4)))
    out = _nd(np.zeros((4,)))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.arange(4))


def test_batched_pushpull_50_keys_multidevice():
    """50 params, shards on 8 distinct devices → one bucketed compiled
    collective; result equals the per-key sum and is replicated."""
    devs = jax.devices()
    n_dev = min(8, len(devs))
    kv = mx.kv.create("dist_sync_device")
    rng = np.random.RandomState(0)
    keys = [f"p{i}" for i in range(50)]
    shapes = [(3, 5), (7,), (2, 2, 2), (11,), (4, 3)] * 10
    per_key = []
    expected = []
    for shp in shapes:
        shards_np = [rng.randn(*shp).astype(np.float32) for _ in range(n_dev)]
        expected.append(np.sum(shards_np, axis=0))
        shards = [nd.NDArray(jax.device_put(jnp.asarray(s), devs[d]))
                  for d, s in enumerate(shards_np)]
        per_key.append(shards)
    outs = [_nd(np.zeros(shp)) for shp in shapes]
    kv.pushpull(keys, per_key, out=outs)
    for o, e in zip(outs, expected):
        np.testing.assert_allclose(o.asnumpy(), e, rtol=1e-5, atol=1e-5)
    # the reduce computation was compiled once for the whole batch
    assert len(kv._allreduce._reduce_cache) == 1
    # repeat with new values: cache hit, still correct
    kv.pushpull(keys, per_key, out=outs)
    assert len(kv._allreduce._reduce_cache) == 1


def test_same_device_shards_tree_sum():
    kv = mx.kv.create("device")
    vals = [[_nd(np.full((3,), i + j)) for j in range(4)] for i in range(2)]
    aggs = kv.pushpull(["a", "b"], vals)
    np.testing.assert_allclose(aggs[0].asnumpy(), np.full((3,), 0 + 1 + 2 + 3))
    np.testing.assert_allclose(aggs[1].asnumpy(), np.full((3,), 1 + 2 + 3 + 4))


def test_server_side_optimizer():
    kv = mx.kv.create("local")
    kv.init("w", _nd(np.ones((4,))))
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.5))
    kv.push("w", _nd(np.ones((4,))))
    out = _nd(np.zeros((4,)))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((4,), 0.5))


def test_gradient_compression_2bit_error_feedback():
    kv = mx.kv.create("dist_sync_device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    devs = jax.devices()
    g0 = np.array([0.3, -0.7, 0.9, 0.0], np.float32)
    g1 = np.array([0.3, 0.1, -0.2, 0.6], np.float32)
    shards = [nd.NDArray(jax.device_put(jnp.asarray(g), devs[i]))
              for i, g in enumerate([g0, g1])]
    agg = kv.pushpull(["g"], [shards])[0].asnumpy()
    # each shard quantized to {-.5, 0, .5}: q0=[0,-.5,.5,0], q1=[0,0,0,.5]
    np.testing.assert_allclose(agg, [0.0, -0.5, 0.5, 0.5])
    # residuals carry the quantization error for the next round
    r0 = np.asarray(kv._residuals[("g", 0)])
    np.testing.assert_allclose(r0, [0.3, -0.2, 0.4, 0.0], atol=1e-6)
    # second push: residual + grad crosses threshold where it should
    agg2 = kv.pushpull(["g"], [shards])[0].asnumpy()
    # shard0 acc = g0 + r0 = [.6, -.9, 1.3, 0] → q=[.5,-.5,.5,0]
    # shard1 acc = g1 + r1 = [.6, .2, -.4, 1.2] → q=[.5,0,0,.5]
    np.testing.assert_allclose(agg2, [1.0, -0.5, 0.5, 0.5])


def test_gradient_compression_fp16():
    kv = mx.kv.create("dist_sync_device")
    kv.set_gradient_compression({"type": "fp16"})
    devs = jax.devices()
    g = np.array([1.0001, 2.0], np.float32)
    shards = [nd.NDArray(jax.device_put(jnp.asarray(g), devs[i]))
              for i in range(2)]
    agg = kv.pushpull(["g"], [shards])[0].asnumpy()
    expected = 2 * g.astype(np.float16).astype(np.float32)
    np.testing.assert_allclose(agg, expected)


def test_gradient_compression_rejects_unknown():
    kv = mx.kv.create("dist_sync_device")
    with pytest.raises(ValueError, match="unsupported gradient compression"):
        kv.set_gradient_compression({"type": "1bit"})


def test_row_sparse_pull_selected_rows():
    kv = mx.kv.create("local")
    w = np.arange(20, dtype=np.float32).reshape(5, 4)
    kv.init("emb", _nd(w))
    out = _nd(np.zeros((5, 4)))
    kv.row_sparse_pull("emb", out=out, row_ids=nd.array(np.array([1, 3])))
    expected = np.zeros((5, 4), np.float32)
    expected[[1, 3]] = w[[1, 3]]
    np.testing.assert_allclose(out.asnumpy(), expected)


def test_dist_async_equals_sync_single_host():
    """Single-slot pushes with plain SGD: async per-push updates coincide
    with sync aggregated updates (one push = one update either way), so
    results are bit-identical — the degenerate case of the async model."""
    results = {}
    for mode in ("dist_sync", "dist_async"):
        kv = mx.kv.create(mode)
        kv.init("w", _nd(np.ones((3,))))
        kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.1))
        for step in range(3):
            kv.push("w", _nd(np.full((3,), step + 1.0)))
        out = _nd(np.zeros((3,)))
        kv.pull("w", out=out)
        results[mode] = out.asnumpy()
    np.testing.assert_array_equal(results["dist_sync"], results["dist_async"])


def test_trainer_batched_allreduce_matches_manual(monkeypatch):
    """Trainer.allreduce_grads routes ALL params through one list-form
    pushpull (one bucketed collective)."""
    from incubator_mxnet_tpu import gluon

    net = gluon.nn.Dense(4, in_units=3)
    net.initialize()
    x = _nd(np.random.RandomState(0).randn(2, 3))
    with mx.autograd.record():
        y = net(x)
        loss = (y * y).sum()
    loss.backward()

    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.0}, kvstore="dist_sync")
    calls = []
    orig = tr._kvstore.pushpull

    def spy(key, value, out=None, priority=0):
        calls.append(key)
        return orig(key, value, out=out, priority=priority)

    monkeypatch.setattr(tr._kvstore, "pushpull", spy)
    monkeypatch.setattr(type(tr._kvstore), "num_workers",
                        property(lambda self: 2), raising=False)
    tr.step(2)
    assert len(calls) == 1 and isinstance(calls[0], list)


# ---------------------------------------------------------------------------
# dist_async semantics (parity: src/kvstore/kvstore_dist_server.h — per-worker
# arrival-order updates, no aggregation barrier, bounded induced staleness)
# ---------------------------------------------------------------------------

class _CountingSGD(mx.optimizer.Optimizer):
    """SGD that counts server-side update calls."""

    def __init__(self, learning_rate=0.1):
        super().__init__(learning_rate=learning_rate)
        self.calls = 0

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        self.calls += 1
        weight._data = weight._data - self.lr * grad._data
        return state


def test_async_applies_per_worker_updates():
    """A push of N device slots is N server updates in async mode (the
    defining difference from sync's aggregate-then-update)."""
    n_workers = 4
    kv_sync = mx.kv.create("dist_sync")
    kv_async = mx.kv.create("dist_async")
    opt_s, opt_a = _CountingSGD(), _CountingSGD()
    for kv, opt in ((kv_sync, opt_s), (kv_async, opt_a)):
        kv.init("w", _nd(np.zeros((3,))))
        kv.set_optimizer(opt)
        kv.push("w", [_nd(np.full((3,), i + 1.0)) for i in range(n_workers)])
        kv.barrier()
    assert opt_s.calls == 1
    assert opt_a.calls == n_workers
    # plain SGD is linear, so the final weights still agree: sum of
    # per-worker steps == one aggregated step
    ws, wa = _nd(np.zeros((3,))), _nd(np.zeros((3,)))
    kv_sync.pull("w", out=ws)
    kv_async.pull("w", out=wa)
    np.testing.assert_allclose(ws.asnumpy(), wa.asnumpy(), rtol=1e-6)


def test_async_staleness_reorders_but_loses_nothing():
    """With induced staleness, pushes apply late and out of order, but a
    barrier() drains everything: for linear SGD the final weight equals
    the deterministic result regardless of order (sum of all steps)."""
    kv = mx.kv.create("dist_async")
    kv.init("w", _nd(np.zeros((2,))))
    kv.set_optimizer(_CountingSGD(learning_rate=1.0))
    kv.set_async_staleness(max_delay=3, seed=7)
    total = np.zeros((2,), np.float32)
    rng = np.random.RandomState(0)
    saw_pending = False
    for step in range(20):
        grads = [rng.randn(2).astype(np.float32) for _ in range(4)]
        total += np.sum(grads, axis=0)
        kv.push("w", [_nd(g) for g in grads])
        saw_pending = saw_pending or kv._async_queue.pending_count > 0
    assert saw_pending, "staleness simulation never delayed a push"
    assert kv._async_queue.delayed_total > 0
    kv.barrier()
    assert kv._async_queue.pending_count == 0
    out = _nd(np.zeros((2,)))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), -total, rtol=1e-5, atol=1e-5)


def test_async_pull_sees_stale_weights():
    """Between pushes, delayed updates are genuinely invisible to pull —
    the staleness the reference's async mode exposes to workers."""
    kv = mx.kv.create("dist_async")
    kv.init("w", _nd(np.zeros((1,))))
    kv.set_optimizer(_CountingSGD(learning_rate=1.0))
    kv.set_async_staleness(max_delay=50, seed=3)
    applied = []
    for step in range(30):
        kv.push("w", [_nd(np.ones((1,))) for _ in range(2)])
        out = _nd(np.zeros((1,)))
        kv.pull("w", out=out)
        applied.append(-float(out.asnumpy()[0]))
    pushed = [(i + 1) * 2.0 for i in range(30)]
    assert any(a < p for a, p in zip(applied, pushed)), \
        "pull never observed stale weights under max_delay=50"
    kv.barrier()
    out = _nd(np.zeros((1,)))
    kv.pull("w", out=out)
    assert -float(out.asnumpy()[0]) == pushed[-1]


def test_async_sgd_converges_despite_staleness():
    """Asynchronous SGD on a least-squares problem: 4 virtual workers
    compute gradients from the (possibly stale) pulled weights; training
    still converges (the classic async-PS robustness result)."""
    rng = np.random.RandomState(0)
    X = rng.randn(64, 5).astype(np.float32)
    w_true = np.array([1.0, -2.0, 0.5, 3.0, -1.0], np.float32)
    y = X @ w_true

    kv = mx.kv.create("dist_async")
    kv.init("w", _nd(np.zeros((5,))))
    kv.set_optimizer(_CountingSGD(learning_rate=0.02))
    kv.set_async_staleness(max_delay=2, seed=1)

    shards = np.split(np.arange(64), 4)
    w_pull = _nd(np.zeros((5,)))
    for step in range(200):
        kv.pull("w", out=w_pull)          # workers read possibly-stale w
        w_cur = w_pull.asnumpy()
        grads = []
        for s in shards:
            err = X[s] @ w_cur - y[s]
            grads.append(_nd(X[s].T @ err / len(s)))
        kv.push("w", grads)
    kv.barrier()
    kv.pull("w", out=w_pull)
    final_loss = float(np.mean((X @ w_pull.asnumpy() - y) ** 2))
    assert final_loss < 1e-3, final_loss
    assert kv._async_queue.delayed_total > 0  # staleness actually happened


def test_trainer_update_on_kvstore_dist_async():
    """update_on_kvstore (auto-resolved for dist_async): the optimizer
    runs SERVER-side — step() pushes grads and pulls updated weights;
    with one worker this matches local-update training exactly, and
    update() is refused (reference trainer semantics)."""
    from incubator_mxnet_tpu import gluon

    def build():
        mx.random.seed(5)
        np.random.seed(5)
        net = gluon.nn.Dense(3, in_units=4)
        net.initialize(init=mx.init.Xavier())
        return net

    def run(net, kvstore):
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9},
                           kvstore=kvstore)
        x = _nd(np.random.RandomState(0).randn(6, 4))
        for _ in range(4):
            with mx.autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
            tr.step(6)
        return tr

    net_a, net_b = build(), build()
    tr_a = run(net_a, "dist_async")
    run(net_b, None)
    assert tr_a._update_on_kvstore        # auto-resolved True
    for pa, pb in zip(net_a.collect_params().values(),
                      net_b.collect_params().values()):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(), rtol=1e-6)
    with pytest.raises(ValueError, match="update_on_kvstore"):
        tr_a.update(6)


def test_trainer_update_on_kvstore_conflicts():
    from incubator_mxnet_tpu import gluon
    net = gluon.nn.Dense(2, in_units=2)
    net.initialize()
    with pytest.raises(ValueError, match="kvstore"):
        gluon.Trainer(net.collect_params(), "sgd", {}, kvstore=None,
                      update_on_kvstore=True)
    with pytest.raises(ValueError, match="incompatible"):
        gluon.Trainer(net.collect_params(), "sgd", {},
                      kvstore="dist_async", update_on_kvstore=True,
                      overlap_comm=True)
