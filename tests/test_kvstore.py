"""KVStore: bucketed compiled collectives, compression, row_sparse_pull.

Parity model: python/mxnet/kvstore.py + src/kvstore/kvstore_dist.h
(dist_sync_device semantics on the 8-virtual-device CPU mesh).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd

import jax
import jax.numpy as jnp


def _nd(x):
    return nd.array(np.asarray(x, np.float32))


def test_local_pushpull_scalar_key():
    kv = mx.kv.create("local")
    kv.init(3, _nd(np.ones((2, 3))))
    vals = [_nd(np.full((2, 3), i, np.float32)) for i in range(1, 5)]
    out = _nd(np.zeros((2, 3)))
    kv.pushpull(3, vals, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 10.0))


def test_push_pull_accumulate():
    kv = mx.kv.create("device")
    kv.init("w", _nd(np.zeros((4,))))
    kv.push("w", _nd(np.arange(4)))
    out = _nd(np.zeros((4,)))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.arange(4))


def test_batched_pushpull_50_keys_multidevice():
    """50 params, shards on 8 distinct devices → one bucketed compiled
    collective; result equals the per-key sum and is replicated."""
    devs = jax.devices()
    n_dev = min(8, len(devs))
    kv = mx.kv.create("dist_sync_device")
    rng = np.random.RandomState(0)
    keys = [f"p{i}" for i in range(50)]
    shapes = [(3, 5), (7,), (2, 2, 2), (11,), (4, 3)] * 10
    per_key = []
    expected = []
    for shp in shapes:
        shards_np = [rng.randn(*shp).astype(np.float32) for _ in range(n_dev)]
        expected.append(np.sum(shards_np, axis=0))
        shards = [nd.NDArray(jax.device_put(jnp.asarray(s), devs[d]))
                  for d, s in enumerate(shards_np)]
        per_key.append(shards)
    outs = [_nd(np.zeros(shp)) for shp in shapes]
    kv.pushpull(keys, per_key, out=outs)
    for o, e in zip(outs, expected):
        np.testing.assert_allclose(o.asnumpy(), e, rtol=1e-5, atol=1e-5)
    # the reduce computation was compiled once for the whole batch
    assert len(kv._allreduce._reduce_cache) == 1
    # repeat with new values: cache hit, still correct
    kv.pushpull(keys, per_key, out=outs)
    assert len(kv._allreduce._reduce_cache) == 1


def test_same_device_shards_tree_sum():
    kv = mx.kv.create("device")
    vals = [[_nd(np.full((3,), i + j)) for j in range(4)] for i in range(2)]
    aggs = kv.pushpull(["a", "b"], vals)
    np.testing.assert_allclose(aggs[0].asnumpy(), np.full((3,), 0 + 1 + 2 + 3))
    np.testing.assert_allclose(aggs[1].asnumpy(), np.full((3,), 1 + 2 + 3 + 4))


def test_server_side_optimizer():
    kv = mx.kv.create("local")
    kv.init("w", _nd(np.ones((4,))))
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.5))
    kv.push("w", _nd(np.ones((4,))))
    out = _nd(np.zeros((4,)))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((4,), 0.5))


def test_gradient_compression_2bit_error_feedback():
    kv = mx.kv.create("dist_sync_device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    devs = jax.devices()
    g0 = np.array([0.3, -0.7, 0.9, 0.0], np.float32)
    g1 = np.array([0.3, 0.1, -0.2, 0.6], np.float32)
    shards = [nd.NDArray(jax.device_put(jnp.asarray(g), devs[i]))
              for i, g in enumerate([g0, g1])]
    agg = kv.pushpull(["g"], [shards])[0].asnumpy()
    # each shard quantized to {-.5, 0, .5}: q0=[0,-.5,.5,0], q1=[0,0,0,.5]
    np.testing.assert_allclose(agg, [0.0, -0.5, 0.5, 0.5])
    # residuals carry the quantization error for the next round
    r0 = np.asarray(kv._residuals[("g", 0)])
    np.testing.assert_allclose(r0, [0.3, -0.2, 0.4, 0.0], atol=1e-6)
    # second push: residual + grad crosses threshold where it should
    agg2 = kv.pushpull(["g"], [shards])[0].asnumpy()
    # shard0 acc = g0 + r0 = [.6, -.9, 1.3, 0] → q=[.5,-.5,.5,0]
    # shard1 acc = g1 + r1 = [.6, .2, -.4, 1.2] → q=[.5,0,0,.5]
    np.testing.assert_allclose(agg2, [1.0, -0.5, 0.5, 0.5])


def test_gradient_compression_fp16():
    kv = mx.kv.create("dist_sync_device")
    kv.set_gradient_compression({"type": "fp16"})
    devs = jax.devices()
    g = np.array([1.0001, 2.0], np.float32)
    shards = [nd.NDArray(jax.device_put(jnp.asarray(g), devs[i]))
              for i in range(2)]
    agg = kv.pushpull(["g"], [shards])[0].asnumpy()
    expected = 2 * g.astype(np.float16).astype(np.float32)
    np.testing.assert_allclose(agg, expected)


def test_gradient_compression_rejects_unknown():
    kv = mx.kv.create("dist_sync_device")
    with pytest.raises(ValueError, match="unsupported gradient compression"):
        kv.set_gradient_compression({"type": "1bit"})


def test_row_sparse_pull_selected_rows():
    kv = mx.kv.create("local")
    w = np.arange(20, dtype=np.float32).reshape(5, 4)
    kv.init("emb", _nd(w))
    out = _nd(np.zeros((5, 4)))
    kv.row_sparse_pull("emb", out=out, row_ids=nd.array(np.array([1, 3])))
    expected = np.zeros((5, 4), np.float32)
    expected[[1, 3]] = w[[1, 3]]
    np.testing.assert_allclose(out.asnumpy(), expected)


def test_dist_async_equals_sync_single_host():
    """Single-process: dist_async update stream is program order, so results
    are bit-identical to dist_sync (see kvstore module docstring)."""
    results = {}
    for mode in ("dist_sync", "dist_async"):
        kv = mx.kv.create(mode)
        kv.init("w", _nd(np.ones((3,))))
        kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.1))
        for step in range(3):
            kv.push("w", _nd(np.full((3,), step + 1.0)))
        out = _nd(np.zeros((3,)))
        kv.pull("w", out=out)
        results[mode] = out.asnumpy()
    np.testing.assert_array_equal(results["dist_sync"], results["dist_async"])


def test_trainer_batched_allreduce_matches_manual(monkeypatch):
    """Trainer.allreduce_grads routes ALL params through one list-form
    pushpull (one bucketed collective)."""
    from incubator_mxnet_tpu import gluon

    net = gluon.nn.Dense(4, in_units=3)
    net.initialize()
    x = _nd(np.random.RandomState(0).randn(2, 3))
    with mx.autograd.record():
        y = net(x)
        loss = (y * y).sum()
    loss.backward()

    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.0}, kvstore="dist_sync")
    calls = []
    orig = tr._kvstore.pushpull

    def spy(key, value, out=None, priority=0):
        calls.append(key)
        return orig(key, value, out=out, priority=priority)

    monkeypatch.setattr(tr._kvstore, "pushpull", spy)
    monkeypatch.setattr(type(tr._kvstore), "num_workers",
                        property(lambda self: 2), raising=False)
    tr.step(2)
    assert len(calls) == 1 and isinstance(calls[0], list)
