"""mxtpu.autotune: the knob table's documented precedence (call-site >
BENCH_* > MXTPU_* > cached winner > default) with conflict warnings
pinned, the pallas spelling matrix, mesh-grammar parsing, the pruning
rules firing on the right gap taxonomy, budget exhaustion returning
best-so-far, cache hit skipping the search, corrupt/stale cache entries
rejected and counted, subprocess trial death as a counted skip (never a
crash), and the tooling satellites (trace_check AUTOTUNE_FAMILIES +
check_autotune_extra, perf_regress knob-diff context notes, mxdiag tune
rendering, perf_sweep knob splitting). Search logic runs against
DETERMINISTIC fake measurement fixtures — no real training."""
import importlib.util
import json
import os
import stat

import pytest

import incubator_mxnet_tpu as mx  # noqa: F401 — package init
from incubator_mxnet_tpu import profiler as prof
from incubator_mxnet_tpu.autotune import knobs, space
from incubator_mxnet_tpu.autotune import trial as trial_mod
from incubator_mxnet_tpu.autotune.cache import (TuningCache, SCHEMA,
                                                fingerprint)
from incubator_mxnet_tpu.autotune.knobs import KnobConfig
from incubator_mxnet_tpu.autotune.tuner import search
from incubator_mxnet_tpu.autotune.trial import (TrialResult,
                                                measurement_from_artifact,
                                                score, trial_env)


def _load_tool(name):
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# every env spelling the knob table reads — cleared around each test so
# the suite's own environment can't leak into resolution
_KNOB_ENV_VARS = ("BENCH_LOOP_CHUNK", "MXTPU_LOOP_CHUNK", "BENCH_REMAT",
                  "MXTPU_REMAT", "BENCH_REMAT_POLICY",
                  "MXTPU_REMAT_POLICY", "BENCH_PREFETCH_DEPTH",
                  "MXTPU_PREFETCH_DEPTH", "BENCH_MESH", "MXTPU_MESH",
                  "BENCH_BATCH", "MXTPU_PALLAS", "MXTPU_NO_PALLAS",
                  "MXTPU_FORCE_PALLAS", "MXTPU_AUTOTUNE",
                  "MXTPU_AUTOTUNE_CACHE")


@pytest.fixture(autouse=True)
def _clean_knob_state(monkeypatch):
    for var in _KNOB_ENV_VARS:
        monkeypatch.delenv(var, raising=False)
    knobs.clear_cached_defaults()
    knobs.reset_warned()
    yield
    knobs.clear_cached_defaults()
    knobs.reset_warned()


def _counter(name):
    return prof.counters().get("autotune/" + name) or 0


def _meas(busy=None, step_ms=10.0, mfu=0.1, value=100.0, gaps=None,
          mfu_if_removed=None):
    return {"busy_fraction": busy, "step_ms": step_ms, "mfu": mfu,
            "value": value, "gaps": gaps,
            "mfu_if_removed": mfu_if_removed,
            "provenance": ("measured(profile)" if busy is not None
                           else "host_wall")}


GAPS_INPUT = {"input_starved_ms": 4.0, "dispatch_serialized_ms": 0.5,
              "host_gap_ms": 0.5}
GAPS_DISPATCH = {"input_starved_ms": 0.2, "dispatch_serialized_ms": 3.0,
                 "host_gap_ms": 2.0}


# ---------------------------------------------------------------------------
# KnobConfig: precedence, conflicts, spellings
# ---------------------------------------------------------------------------

class TestKnobPrecedence:
    def test_defaults_and_sources(self):
        cfg = KnobConfig.from_env()
        assert cfg.to_dict() == {"loop_chunk": 0, "remat": False,
                                 "remat_policy": None,
                                 "prefetch_depth": 2, "io_workers": 2,
                                 "pallas": "auto",
                                 "mesh": None, "batch": None}
        assert set(cfg.sources.values()) == {"default"}

    def test_call_site_beats_bench_env(self, monkeypatch):
        monkeypatch.setenv("BENCH_LOOP_CHUNK", "8")
        cfg = KnobConfig.from_env(loop_chunk=2)
        assert cfg.loop_chunk == 2
        assert cfg.sources["loop_chunk"] == "call_site"

    def test_bench_beats_mxtpu_with_conflict_warning(self, monkeypatch):
        monkeypatch.setenv("BENCH_LOOP_CHUNK", "8")
        monkeypatch.setenv("MXTPU_LOOP_CHUNK", "4")
        before = _counter("autotune.env_conflicts")
        with pytest.warns(UserWarning, match="BENCH_LOOP_CHUNK=8.*wins"):
            cfg = KnobConfig.from_env()
        assert cfg.loop_chunk == 8
        assert cfg.sources["loop_chunk"] == "BENCH_LOOP_CHUNK"
        assert _counter("autotune.env_conflicts") == before + 1
        # once per knob per process: the second resolve stays quiet
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("error")
            KnobConfig.from_env()

    def test_agreeing_spellings_do_not_warn(self, monkeypatch):
        monkeypatch.setenv("BENCH_LOOP_CHUNK", "4")
        monkeypatch.setenv("MXTPU_LOOP_CHUNK", "4")
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("error")
            cfg = KnobConfig.from_env()
        assert cfg.loop_chunk == 4

    def test_mxtpu_beats_cached(self, monkeypatch):
        monkeypatch.setenv("MXTPU_LOOP_CHUNK", "4")
        knobs.set_cached_defaults({"loop_chunk": 8})
        cfg = KnobConfig.from_env()
        assert cfg.loop_chunk == 4
        assert cfg.sources["loop_chunk"] == "MXTPU_LOOP_CHUNK"

    def test_cached_beats_default(self):
        knobs.set_cached_defaults({"loop_chunk": 8, "prefetch_depth": 4,
                                   "unknown_future_field": 1})
        cfg = KnobConfig.from_env()
        assert cfg.loop_chunk == 8
        assert cfg.prefetch_depth == 4
        assert cfg.sources["loop_chunk"] == "cached"
        # unknown keys from a future cache schema are ignored, not fatal
        assert "unknown_future_field" not in knobs.cached_defaults()

    def test_garbage_env_raises(self, monkeypatch):
        monkeypatch.setenv("BENCH_LOOP_CHUNK", "many")
        with pytest.raises(ValueError):
            KnobConfig.from_env()
        monkeypatch.setenv("BENCH_LOOP_CHUNK", "4")
        monkeypatch.setenv("BENCH_REMAT_POLICY", "sometimes")
        with pytest.raises(ValueError, match="remat_policy"):
            KnobConfig.from_env()

    def test_from_dict_unknown_field_raises(self):
        with pytest.raises(ValueError, match="unknown knob"):
            KnobConfig.from_dict({"loop_chunk": 2, "warp_drive": 9})

    def test_unparseable_loser_cannot_crash_a_valid_winner(
            self, monkeypatch):
        # a stale `export MXTPU_PREFETCH_DEPTH=bogus` in a shell profile
        # must not break a run whose valid BENCH_* spelling already won
        monkeypatch.setenv("BENCH_PREFETCH_DEPTH", "4")
        monkeypatch.setenv("MXTPU_PREFETCH_DEPTH", "bogus")
        with pytest.warns(UserWarning, match="ignoring unparseable"):
            cfg = KnobConfig.from_env()
        assert cfg.prefetch_depth == 4
        # with no winner set, the garbage var is the decider: still a
        # loud parse error naming the value, not a silent default
        monkeypatch.delenv("BENCH_PREFETCH_DEPTH")
        with pytest.raises(ValueError):
            KnobConfig.from_env()

    def test_zero_depth_and_batch_same_verdict_everywhere(
            self, monkeypatch):
        # env parse, dict construction, and the TrainLoop constructor
        # must agree: 0 is an error, never a silent unset/default
        monkeypatch.setenv("BENCH_PREFETCH_DEPTH", "0")
        with pytest.raises(ValueError, match="prefetch_depth"):
            KnobConfig.from_env()
        monkeypatch.delenv("BENCH_PREFETCH_DEPTH")
        with pytest.raises(ValueError, match="batch"):
            KnobConfig.from_dict({"batch": 0})
        with pytest.raises(ValueError, match="prefetch_depth"):
            KnobConfig(prefetch_depth=0)


class TestPallasSpellings:
    @pytest.mark.parametrize("env,want", [
        ({}, "auto"),
        ({"MXTPU_PALLAS": "0"}, "off"),
        ({"MXTPU_PALLAS": "off"}, "off"),
        ({"MXTPU_PALLAS": "1"}, "on"),
        ({"MXTPU_PALLAS": "force"}, "force"),
        ({"MXTPU_NO_PALLAS": "1"}, "off"),
        ({"MXTPU_FORCE_PALLAS": "1"}, "force"),
    ])
    def test_spelling_matrix(self, monkeypatch, env, want):
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        assert KnobConfig.from_env().pallas == want

    def test_conflict_off_wins_and_warns(self, monkeypatch):
        # mirrors ops/pallas.enabled()'s if-order: the off spelling wins
        # over force — the knob table must DESCRIBE dispatch, not
        # contradict it
        monkeypatch.setenv("MXTPU_PALLAS", "force")
        monkeypatch.setenv("MXTPU_NO_PALLAS", "1")
        with pytest.warns(UserWarning, match="pallas"):
            cfg = KnobConfig.from_env()
        assert cfg.pallas == "off"
        from incubator_mxnet_tpu.ops import pallas as pallas_mod
        assert pallas_mod.enabled() is False

    def test_to_env_round_trip(self, monkeypatch):
        cfg = KnobConfig(loop_chunk=8, remat=True, remat_policy="dots",
                         prefetch_depth=4, pallas="off", mesh="dp2mp2",
                         batch=64)
        for k, v in cfg.to_env().items():
            monkeypatch.setenv(k, v)
        assert KnobConfig.from_env() == cfg


class TestMeshGrammar:
    def test_valid_specs(self):
        assert knobs.parse_mesh("dp4") == ("dp", {"dp": 4})
        assert knobs.parse_mesh("fsdp4") == ("fsdp", {"dp": 4})
        mode, axes = knobs.parse_mesh("dp2mp2")
        assert mode == "auto" and axes == {"dp": 2, "mp": 2}
        assert knobs.parse_mesh("") == (None, {})

    def test_bad_grammar_raises(self):
        with pytest.raises(ValueError, match="axis-size tokens"):
            knobs.parse_mesh("dp4x")
        with pytest.raises(ValueError, match="more than once"):
            knobs.parse_mesh("dp2dp2")
        with pytest.raises(ValueError, match="model axis"):
            knobs.parse_mesh("fsdp2mp2")


# ---------------------------------------------------------------------------
# consumer resolution: TrainLoop / Trainer ride the same table
# ---------------------------------------------------------------------------

class TestConsumerResolution:
    def test_resolve_chunk_layers(self, monkeypatch):
        from incubator_mxnet_tpu.trainloop import resolve_chunk
        assert resolve_chunk() == 4                      # default
        knobs.set_cached_defaults({"loop_chunk": 8})
        assert resolve_chunk() == 8                      # cached winner
        monkeypatch.setenv("MXTPU_LOOP_CHUNK", "6")
        assert resolve_chunk() == 6                      # MXTPU beats it
        monkeypatch.setenv("BENCH_LOOP_CHUNK", "2")
        assert resolve_chunk() == 2                      # BENCH beats it
        assert resolve_chunk(explicit=3) == 3            # arg beats all

    def test_trainer_loop_chunk_through_knobs(self, monkeypatch):
        from incubator_mxnet_tpu import gluon
        net = gluon.nn.Dense(2, in_units=3)
        net.initialize()
        monkeypatch.setenv("BENCH_LOOP_CHUNK", "5")
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        assert tr.loop_chunk == 5
        tr2 = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, loop_chunk=2)
        assert tr2.loop_chunk == 2


# ---------------------------------------------------------------------------
# space: pruning rules + candidate generation
# ---------------------------------------------------------------------------

class TestPruning:
    def test_input_starved_prunes_remat_not_prefetch(self):
        plan = space.prune_plan(_meas(busy=0.5, gaps=GAPS_INPUT))
        assert plan["diagnosis"] == "input_starved"
        assert plan["allowed"][0] == "prefetch_depth"
        assert "remat_policy" in plan["pruned"]
        assert "pallas" in plan["pruned"]
        assert "prefetch_depth" not in plan["pruned"]

    def test_dispatch_bound_prefers_loop_chunk(self):
        plan = space.prune_plan(_meas(busy=0.41, gaps=GAPS_DISPATCH))
        assert plan["diagnosis"] == "dispatch_bound"
        assert plan["allowed"][0] == "loop_chunk"
        assert "remat_policy" in plan["pruned"]

    def test_device_bound_prunes_dispatch_knobs(self):
        plan = space.prune_plan(_meas(
            busy=0.93, step_ms=10.0,
            gaps={"input_starved_ms": 0.1, "dispatch_serialized_ms": 0.2,
                  "host_gap_ms": 0.1}))
        assert plan["diagnosis"] == "device_bound"
        assert "loop_chunk" in plan["pruned"]
        assert "prefetch_depth" in plan["pruned"]
        assert "pallas" in plan["allowed"]
        assert "remat_policy" in plan["allowed"]

    def test_no_measurement_prunes_nothing_core(self):
        plan = space.prune_plan(None)
        assert plan["diagnosis"] == "unknown"
        for knob in ("loop_chunk", "prefetch_depth", "remat_policy",
                     "pallas"):
            assert knob in plan["allowed"]

    def test_mesh_needs_counterfactual_and_candidates(self):
        m = _meas(busy=0.5, gaps=GAPS_DISPATCH, mfu=0.10,
                  mfu_if_removed={"collective": 0.12})
        # candidates supplied + 20% promised gain -> explored
        plan = space.prune_plan(m, mesh_candidates=("dp4",))
        assert "mesh" in plan["allowed"]
        # weak counterfactual -> pruned even with candidates
        m2 = _meas(busy=0.5, gaps=GAPS_DISPATCH, mfu=0.10,
                   mfu_if_removed={"collective": 0.101})
        plan2 = space.prune_plan(m2, mesh_candidates=("dp4",))
        assert "mesh" in plan2["pruned"]
        # no candidates -> pruned regardless of the counterfactual
        plan3 = space.prune_plan(m)
        assert "mesh" in plan3["pruned"]

    def test_candidates_are_single_coordinate_moves(self):
        base = KnobConfig()
        plan = space.prune_plan(_meas(busy=0.41, gaps=GAPS_DISPATCH))
        cands = space.candidates(base, plan)
        assert cands, "dispatch-bound must propose moves"
        base_d = base.to_dict()
        for knob, value, cfg in cands:
            diff = {k for k, v in cfg.to_dict().items()
                    if v != base_d[k]}
            if knob == "remat_policy":
                assert diff <= {"remat", "remat_policy"}
            else:
                assert diff == {knob}
            assert cfg != base      # the incumbent is never re-proposed


# ---------------------------------------------------------------------------
# trial: measurement extraction, scoring, subprocess isolation
# ---------------------------------------------------------------------------

class TestTrial:
    def test_measurement_from_artifact(self):
        doc = {"value": 123.0, "extra": {
            "mfu": 0.07,
            "devicescope": {"busy_fraction": 0.41,
                            "gaps": {"taxonomy": GAPS_DISPATCH}},
            "perfscope": {"decomposition": {
                "step_ms": 9.5,
                "mfu_if_removed": {"collective": 0.08}}}}}
        m = measurement_from_artifact(doc)
        assert m["busy_fraction"] == 0.41
        assert m["gaps"] == GAPS_DISPATCH
        assert m["step_ms"] == 9.5
        assert m["value"] == 123.0
        assert m["provenance"] == "measured(profile)"

    def test_no_window_degrades_to_host_wall(self):
        m = measurement_from_artifact({"value": 50.0, "extra": {}})
        assert m["busy_fraction"] is None
        assert m["provenance"] == "host_wall"

    def test_score_ordering(self):
        measured_low = _meas(busy=0.40, value=500.0)
        measured_high = _meas(busy=0.70, value=100.0)
        unmeasured_fast = _meas(busy=None, value=9999.0)
        assert score(measured_high) > score(measured_low)
        # any measured trial outranks an unmeasured one
        assert score(measured_low) > score(unmeasured_fast)
        # near-tie on busy defers to throughput (the remat guard)
        a = _meas(busy=0.701, value=100.0)
        b = _meas(busy=0.699, value=200.0)
        assert score(b) > score(a)

    def test_trial_env_scrubs_and_pins(self, monkeypatch):
        monkeypatch.setenv("BENCH_MODEL", "resnet50")
        monkeypatch.setenv("BENCH_STEPS", "999")
        monkeypatch.setenv("MXTPU_AUTOTUNE", "1")
        monkeypatch.setenv("MXTPU_PALLAS", "force")
        env = trial_env(KnobConfig(loop_chunk=8), model="lenet",
                        steps=8, measure=True)
        assert env["BENCH_MODEL"] == "lenet"       # scrubbed, re-pinned
        assert env["BENCH_STEPS"] == "8"
        assert env["MXTPU_AUTOTUNE"] == "0"        # no recursion
        assert "MXTPU_PALLAS" not in env           # config owns pallas
        assert env["BENCH_LOOP_CHUNK"] == "8"
        assert env["BENCH_DEVICESCOPE"] == "1"
        assert env["BENCH_K1_CONTROL"] == "0"

    def _stub(self, tmp_path, body):
        p = tmp_path / "stub_bench.py"
        p.write_text(body)
        os.chmod(p, os.stat(p).st_mode | stat.S_IEXEC)
        return str(p)

    def test_subprocess_death_is_counted_failure(self, tmp_path):
        stub = self._stub(tmp_path, "import sys; sys.exit(1)\n")
        r = trial_mod.run_trial(KnobConfig(), bench_path=stub, timeout=30)
        assert r.status == "failed"
        assert "no JSON" in r.error

    def test_subprocess_timeout_is_failure(self, tmp_path):
        stub = self._stub(tmp_path, "import time; time.sleep(60)\n")
        r = trial_mod.run_trial(KnobConfig(), bench_path=stub, timeout=1)
        assert r.status == "failed"
        assert "timed out" in r.error

    def test_env_failure_artifact_is_failure(self, tmp_path):
        stub = self._stub(tmp_path, (
            'print(\'{"metric": "m", "value": 0.0, '
            '"status": "env_failure", "error": "wedged tunnel"}\')\n'))
        r = trial_mod.run_trial(KnobConfig(), bench_path=stub, timeout=30)
        assert r.status == "failed"
        assert "wedged tunnel" in r.error

    def test_ok_stub_yields_measurement(self, tmp_path):
        doc = {"metric": "m", "value": 200.0, "unit": "img/s",
               "extra": {"mfu": 0.1,
                         "devicescope": {"busy_fraction": 0.66}}}
        stub = self._stub(tmp_path,
                          f"print('noise')\nprint('{json.dumps(doc)}')\n")
        r = trial_mod.run_trial(KnobConfig(loop_chunk=4),
                                bench_path=stub, timeout=30)
        assert r.ok
        assert r.measurement["busy_fraction"] == 0.66
        assert r.measurement["provenance"] == "measured(profile)"
        assert r.row()["config"]["loop_chunk"] == 4


# ---------------------------------------------------------------------------
# cache: trust rules
# ---------------------------------------------------------------------------

class TestCache:
    KEY = ("lenet|b64|float32", None, "cpu")

    def _store(self, cache):
        return cache.store(*self.KEY, winner=KnobConfig(loop_chunk=8),
                           score={"busy_fraction": 0.7,
                                  "provenance": "measured(profile)"},
                           default={"busy_fraction": 0.4},
                           diagnosis="dispatch_bound")

    def test_roundtrip(self, tmp_path):
        cache = TuningCache(str(tmp_path))
        self._store(cache)
        entry = cache.lookup(*self.KEY)
        assert entry["winner"]["loop_chunk"] == 8
        assert entry["score"]["busy_fraction"] == 0.7
        assert entry["diagnosis"] == "dispatch_bound"
        assert cache.rejects == 0

    def test_miss_is_none(self, tmp_path):
        assert TuningCache(str(tmp_path)).lookup(*self.KEY) is None

    def test_corrupt_entry_rejected_and_counted(self, tmp_path):
        cache = TuningCache(str(tmp_path))
        self._store(cache)
        with open(cache.path_for(*self.KEY), "w") as f:
            f.write("{torn write")
        before = _counter("autotune.cache_rejects")
        with pytest.warns(UserWarning, match="rejected"):
            assert cache.lookup(*self.KEY) is None
        assert cache.rejects == 1
        assert _counter("autotune.cache_rejects") == before + 1

    def test_schema_bump_rejected(self, tmp_path):
        cache = TuningCache(str(tmp_path))
        self._store(cache)
        path = cache.path_for(*self.KEY)
        doc = json.load(open(path))
        doc["schema"] = "mxtpu.autotune-cache/999"
        json.dump(doc, open(path, "w"))
        with pytest.warns(UserWarning, match="schema"):
            assert cache.lookup(*self.KEY) is None

    def test_device_kind_case_normalized(self, tmp_path):
        # jax reports 'TPU v4' raw; perfscope's peaks table lowercases
        # to 'tpu v4'. Both spellings must land on ONE cache key, or
        # sweep-ingested winners are never found by the driver's lookup
        cache = TuningCache(str(tmp_path))
        cache.store(self.KEY[0], None, "tpu v4",
                    winner=KnobConfig(loop_chunk=8),
                    score={"busy_fraction": 0.7})
        entry = cache.lookup(self.KEY[0], None, "TPU v4")
        assert entry is not None and entry["winner"]["loop_chunk"] == 8

    def test_device_kind_mismatch_rejected(self, tmp_path):
        # a winner tuned on CPU must never configure a TPU run: craft
        # the collision by copying the cpu entry onto the tpu key's path
        cache = TuningCache(str(tmp_path))
        entry = self._store(cache)
        tpu_key = (self.KEY[0], None, "TPU v5e")
        with open(cache.path_for(*tpu_key), "w") as f:
            json.dump(entry, f)
        with pytest.warns(UserWarning, match="device_kind mismatch"):
            assert cache.lookup(*tpu_key) is None

    def test_unparseable_winner_rejected(self, tmp_path):
        cache = TuningCache(str(tmp_path))
        self._store(cache)
        path = cache.path_for(*self.KEY)
        doc = json.load(open(path))
        doc["winner"] = {"warp_drive": 9}
        json.dump(doc, open(path, "w"))
        with pytest.warns(UserWarning, match="winner"):
            assert cache.lookup(*self.KEY) is None

    def test_ingest_picks_best(self, tmp_path):
        cache = TuningCache(str(tmp_path))
        rows = [TrialResult(KnobConfig(), "ok",
                            measurement=_meas(busy=0.4)),
                TrialResult(KnobConfig(loop_chunk=8), "ok",
                            measurement=_meas(busy=0.7)),
                TrialResult(KnobConfig(loop_chunk=4), "failed",
                            error="died"),
                TrialResult(None, "ok", measurement=_meas(busy=0.99))]
        entry = cache.ingest(rows, *self.KEY)
        assert entry["winner"]["loop_chunk"] == 8   # config-less &
        assert len(entry["trials"]) == 4            # failed rows skipped
        assert cache.lookup(*self.KEY)["winner"]["loop_chunk"] == 8

    def test_fingerprint_structural(self):
        from incubator_mxnet_tpu import gluon
        net = gluon.nn.Dense(4, in_units=3)
        net.initialize()
        fp1 = fingerprint(model=net, batch=32, dtype="float32")
        net2 = gluon.nn.Dense(4, in_units=3)
        net2.initialize()
        fp2 = fingerprint(model=net2, batch=32, dtype="float32")
        assert fp1 == fp2                  # same structure, same key
        net3 = gluon.nn.Dense(5, in_units=3)
        net3.initialize()
        assert fingerprint(model=net3, batch=32,
                           dtype="float32") != fp1


# ---------------------------------------------------------------------------
# search: deterministic fake measurement fixtures
# ---------------------------------------------------------------------------

def _fake_runner(busy_by_chunk=None, fail_configs=(), gaps=None,
                 calls=None):
    """A deterministic runner: busy fraction keyed by loop_chunk, gaps
    fixed, named configs fail."""
    busy_by_chunk = busy_by_chunk or {0: 0.41, 4: 0.60, 8: 0.75}
    gaps = gaps or GAPS_DISPATCH

    def run(cfg, knob=None, value=None):
        if calls is not None:
            calls.append(cfg)
        if cfg.describe() in fail_configs:
            return TrialResult(cfg, "failed", knob=knob, value=value,
                               error="injected trial death")
        busy = busy_by_chunk.get(cfg.loop_chunk, 0.5)
        m = _meas(busy=busy, step_ms=10.0, value=100 + busy * 100,
                  gaps=gaps)
        return TrialResult(cfg, "ok", measurement=m, knob=knob,
                           value=value)
    return run


class TestSearch:
    def test_budget_exhaustion_returns_best_so_far(self, tmp_path):
        calls = []
        r = search(model="lenet", runner=_fake_runner(calls=calls),
                   cache_dir=str(tmp_path), budget=2)
        assert len(calls) == 2                 # baseline + ONE move
        assert r.exhausted is True
        assert r.winner is not None            # best-so-far, not None
        assert r.to_extra()["budget_exhausted"] is True

    def test_pruning_restricts_moves_and_counts(self, tmp_path):
        before = _counter("autotune.trials_pruned")
        calls = []
        r = search(model="lenet", runner=_fake_runner(calls=calls),
                   cache_dir=str(tmp_path), budget=10)
        # dispatch-bound baseline: no remat/pallas move may ever run
        for cfg in calls:
            assert cfg.remat is False and cfg.pallas == "auto"
        assert "remat_policy" in r.pruned
        assert "pallas" in r.pruned
        assert _counter("autotune.trials_pruned") > before

    def test_winner_beats_or_ties_default_by_construction(self, tmp_path):
        r = search(model="lenet", runner=_fake_runner(),
                   cache_dir=str(tmp_path), budget=6)
        assert r.score["busy_fraction"] >= r.default["busy_fraction"]
        assert r.winner.loop_chunk == 8

    def test_cache_hit_skips_search(self, tmp_path):
        search(model="lenet", runner=_fake_runner(),
               cache_dir=str(tmp_path), budget=6)
        calls = []
        before_hits = _counter("autotune.cache_hits")
        r = search(model="lenet", runner=_fake_runner(calls=calls),
                   cache_dir=str(tmp_path), budget=6)
        assert r.cache_hit is True
        assert calls == []                     # runner never invoked
        assert r.trials_attempted == 0
        assert r.winner.loop_chunk == 8
        assert _counter("autotune.cache_hits") == before_hits + 1

    def test_different_key_misses(self, tmp_path):
        search(model="lenet", runner=_fake_runner(),
               cache_dir=str(tmp_path), budget=4)
        r = search(model="lenet", batch=256, runner=_fake_runner(),
                   cache_dir=str(tmp_path), budget=4)
        assert r.cache_hit is False

    def test_failed_trial_is_counted_skip(self, tmp_path):
        before = _counter("autotune.trials_failed")
        r = search(model="lenet",
                   runner=_fake_runner(fail_configs=("loop_chunk=4",)),
                   cache_dir=str(tmp_path), budget=6)
        assert r.trials_failed == 1
        assert _counter("autotune.trials_failed") == before + 1
        assert r.winner is not None            # search survived
        rows = r.to_extra()["trial_table"]
        assert any(row["status"] == "failed"
                   and "injected" in row["error"] for row in rows)

    def test_runner_exception_is_counted_skip(self, tmp_path):
        def exploding(cfg, knob=None, value=None):
            if cfg.loop_chunk == 4:
                raise RuntimeError("runner blew up")
            return _fake_runner()(cfg, knob=knob, value=value)
        r = search(model="lenet", runner=exploding,
                   cache_dir=str(tmp_path), budget=6)
        assert r.winner is not None
        assert r.trials_failed == 1

    def test_all_trials_fail_returns_error_result(self, tmp_path):
        def dead(cfg, knob=None, value=None):
            return TrialResult(cfg, "failed", knob=knob, value=value,
                               error="always dead")
        r = search(model="lenet", runner=dead, cache_dir=str(tmp_path),
                   budget=3)
        assert r.winner is None
        assert r.error == "every trial failed"
        # nothing cached: the next search re-runs
        r2 = search(model="lenet", runner=_fake_runner(),
                    cache_dir=str(tmp_path), budget=3)
        assert r2.cache_hit is False and r2.winner is not None

    def test_extra_validates_under_trace_check(self, tmp_path):
        tc = _load_tool("trace_check")
        r = search(model="lenet", runner=_fake_runner(),
                   cache_dir=str(tmp_path), budget=4)
        assert tc.check_autotune_extra(r.to_extra()) == []
        r_hit = search(model="lenet", runner=_fake_runner(),
                       cache_dir=str(tmp_path), budget=4)
        assert tc.check_autotune_extra(r_hit.to_extra()) == []

    def test_ensure_tuned_installs_cached_defaults(self, tmp_path,
                                                   monkeypatch):
        from incubator_mxnet_tpu import autotune as at
        monkeypatch.setattr(
            "incubator_mxnet_tpu.autotune.tuner.run_trial",
            lambda cfg, **kw: _fake_runner()(cfg, knob=kw.get("knob"),
                                             value=kw.get("value")))
        res = at.ensure_tuned(model="lenet", budget=4,
                              cache_dir=str(tmp_path))
        assert res.winner.loop_chunk == 8
        assert knobs.cached_defaults()["loop_chunk"] == 8
        # the installed winner feeds every consumer through the table
        from incubator_mxnet_tpu.trainloop import resolve_chunk
        assert resolve_chunk() == 8


# ---------------------------------------------------------------------------
# tooling satellites
# ---------------------------------------------------------------------------

class TestTraceCheck:
    def test_autotune_families_enforced(self):
        tc = _load_tool("trace_check")
        assert tc.check_healthmon_kinds(
            {"autotune/autotune.trials": "counter",
             "autotune/autotune.best_busy_fraction": "gauge"}) == []
        errs = tc.check_healthmon_kinds(
            {"autotune/autotune.made_up": "counter"})
        assert errs and "AUTOTUNE_FAMILIES" in errs[0]
        errs = tc.check_healthmon_kinds(
            {"autotune/autotune.trials": "gauge"})
        assert errs and "kind" in errs[0]

    def _good_extra(self):
        return {"enabled": True, "cache_hit": False, "trials": 3,
                "trials_failed": 0, "trials_pruned": 2, "budget": 6,
                "budget_exhausted": False, "diagnosis": "dispatch_bound",
                "winner": KnobConfig(loop_chunk=8).to_dict(),
                "resolved": KnobConfig(loop_chunk=8).to_dict(),
                "score": {"busy_fraction": 0.7, "step_ms": 9.0,
                          "mfu": 0.1, "value": 100.0,
                          "provenance": "measured(profile)"},
                "default": {"busy_fraction": 0.4, "step_ms": 12.0,
                            "mfu": 0.08, "value": 80.0,
                            "provenance": "measured(profile)"},
                "pruned": {"remat_policy": "dispatch-bound"},
                "trial_table": [
                    {"knob": None, "value": None, "status": "ok",
                     "config": KnobConfig().to_dict()},
                    {"knob": "loop_chunk", "value": 8, "status": "ok",
                     "config": KnobConfig(loop_chunk=8).to_dict()}],
                "cache": {"fingerprint": "lenet|b64", "mesh": None,
                          "device_kind": "cpu"},
                "error": None}

    def test_check_autotune_extra_matrix(self):
        tc = _load_tool("trace_check")
        assert tc.check_autotune_extra(None) == []
        assert tc.check_autotune_extra({"enabled": False}) == []
        assert tc.check_autotune_extra(self._good_extra()) == []
        # cache hit with nonzero trials violates the contract
        bad = dict(self._good_extra(), cache_hit=True)
        assert any("trials=0" in e
                   for e in tc.check_autotune_extra(bad))
        # unknown knob field in the winner
        bad = self._good_extra()
        bad["winner"] = dict(bad["winner"], warp_drive=9)
        assert any("unknown knob" in e
                   for e in tc.check_autotune_extra(bad))
        # provenance outside the closed taxonomy
        bad = self._good_extra()
        bad["score"] = dict(bad["score"], provenance="vibes")
        assert any("provenance" in e
                   for e in tc.check_autotune_extra(bad))
        # busy fraction outside [0, 1]
        bad = self._good_extra()
        bad["score"] = dict(bad["score"], busy_fraction=1.5)
        assert any("busy_fraction" in e
                   for e in tc.check_autotune_extra(bad))
        # a failed trial row must carry its reason
        bad = self._good_extra()
        bad["trial_table"] = [{"status": "failed", "config": None}]
        assert any("error" in e for e in tc.check_autotune_extra(bad))
        # enabled + error-free needs a winner
        bad = dict(self._good_extra(), winner=None)
        assert any("winner" in e for e in tc.check_autotune_extra(bad))

    def test_check_bench_json_accepts_autotune(self, tmp_path):
        tc = _load_tool("trace_check")
        doc = {"metric": "m", "value": 1.0, "unit": "u",
               "extra": {"mfu": 0.1, "autotune": self._good_extra()}}
        p = tmp_path / "BENCH_at.json"
        p.write_text(json.dumps(doc))
        assert tc.check_bench_json(str(p)) == []
        doc["extra"]["autotune"]["trials"] = -1
        p.write_text(json.dumps(doc))
        assert any("extra.autotune" in e
                   for e in tc.check_bench_json(str(p)))


class TestPerfRegress:
    def _artifact(self, tmp_path, name, value, knobs_dict):
        doc = {"metric": "m", "value": value, "unit": "img/s",
               "extra": {"mfu": 0.1,
                         "autotune": {"enabled": True, "cache_hit": True,
                                      "trials": 0,
                                      "resolved": knobs_dict}}}
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_knob_diff_is_context_note_not_verdict(self, tmp_path):
        pr = _load_tool("perf_regress")
        a = self._artifact(tmp_path, "a.json", 100.0,
                           KnobConfig().to_dict())
        b = self._artifact(tmp_path, "b.json", 100.0,
                           KnobConfig(loop_chunk=8).to_dict())
        ra, _ = pr.load_artifact(a)
        rb, _ = pr.load_artifact(b)
        regs, notes = pr.compare(ra, rb)
        assert regs == []                  # a knob diff alone never fails
        assert any("CONTEXT: knob config differs" in n
                   and "loop_chunk: 0 -> 8" in n for n in notes)

    def test_knob_diff_rides_alongside_real_regression(self, tmp_path):
        pr = _load_tool("perf_regress")
        a = self._artifact(tmp_path, "a.json", 100.0,
                           KnobConfig().to_dict())
        b = self._artifact(tmp_path, "b.json", 50.0,
                           KnobConfig(loop_chunk=8).to_dict())
        ra, _ = pr.load_artifact(a)
        rb, _ = pr.load_artifact(b)
        regs, notes = pr.compare(ra, rb)
        assert regs                        # the 50% drop still fires...
        assert any("CONTEXT: knob config differs" in n
                   for n in notes)         # ...WITH the context attached

    def test_one_sided_knobs_skipped(self, tmp_path):
        pr = _load_tool("perf_regress")
        a = self._artifact(tmp_path, "a.json", 100.0,
                           KnobConfig().to_dict())
        doc = {"metric": "m", "value": 100.0, "unit": "img/s",
               "extra": {"mfu": 0.1}}
        b = tmp_path / "b.json"
        b.write_text(json.dumps(doc))
        ra, _ = pr.load_artifact(a)
        rb, _ = pr.load_artifact(str(b))
        regs, notes = pr.compare(ra, rb)
        assert regs == []
        assert any("knob context skipped" in n for n in notes)


class TestMxdiagTune:
    def test_renders_search_and_hit_shapes(self, tmp_path, capsys):
        md = _load_tool("mxdiag")
        tc = _load_tool("trace_check")
        extra = TestTraceCheck()._good_extra()
        assert tc.check_autotune_extra(extra) == []
        doc = {"metric": "m", "value": 100.0, "unit": "img/s",
               "extra": {"model": "lenet", "batch": 64,
                         "dtype": "float32", "mfu": 0.1,
                         "autotune": extra}}
        assert md.print_tune(doc) == 0
        out = capsys.readouterr().out
        assert "MISS" in out and "<< WINNER" in out
        assert "dispatch-bound" in out     # pruning reason rendered
        assert "vs default" in out
        doc["extra"]["autotune"] = dict(extra, cache_hit=True, trials=0)
        assert md.print_tune(doc) == 0
        assert "HIT (0 trials" in capsys.readouterr().out

    def test_renders_disabled_and_missing(self, capsys):
        md = _load_tool("mxdiag")
        doc = {"metric": "m", "value": 1.0, "unit": "u",
               "extra": {"autotune": {"enabled": False}}}
        assert md.print_tune(doc) == 0
        assert "DISABLED" in capsys.readouterr().out
        assert md.print_tune({"metric": "m", "value": 1.0,
                              "extra": {}}) == 1

    def test_override_note(self, capsys):
        md = _load_tool("mxdiag")
        extra = TestTraceCheck()._good_extra()
        extra["resolved"] = dict(extra["winner"], loop_chunk=2)
        doc = {"metric": "m", "value": 1.0, "unit": "u",
               "extra": {"autotune": extra}}
        md.print_tune(doc)
        assert "OVERRODE" in capsys.readouterr().out


class TestPerfSweepSplit:
    def test_split_knobs(self):
        ps = _load_tool("perf_sweep")
        cfg, extras = ps._split_knobs({"BENCH_LOOP_CHUNK": "8",
                                       "BENCH_REMAT": "1",
                                       "BENCH_BATCH": "256",
                                       "BENCH_K": "1",
                                       "BENCH_S2D": "1"})
        assert cfg.loop_chunk == 8 and cfg.remat and cfg.batch == 256
        assert extras == {"BENCH_K": "1", "BENCH_S2D": "1"}
        cfg2, extras2 = ps._split_knobs({"BENCH_STEPS": "20"})
        assert cfg2 is None                # warm run: NO knob env
        assert extras2 == {"BENCH_STEPS": "20"}
