"""profiler / test_utils / runtime Features / model alias tests
(SURVEY.md §2.25-26, §5)."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, profiler, runtime, test_utils


def test_profiler_records_ops_and_scopes(tmp_path):
    profiler.reset()
    profiler.set_config(filename=str(tmp_path / "trace.json"))
    profiler.set_state("run")
    with profiler.scope("my_region"):
        a = nd.ones((8, 8))
        b = (a * 2 + 1).sum()
        b.wait_to_read()
    profiler.set_state("stop")
    table = profiler.dumps()
    assert "my_region" in table
    assert "Calls" in table
    profiler.dump()
    assert os.path.exists(tmp_path / "trace.json")
    import json
    with open(tmp_path / "trace.json") as f:
        trace = json.load(f)
    assert len(trace["traceEvents"]) >= 2
    profiler.reset()
    assert profiler.dumps().count("\n") == 0  # only header remains


def test_profiler_pause_resume():
    profiler.reset()
    profiler.set_state("run")
    profiler.pause()
    nd.ones((4,)).wait_to_read()
    n_paused = profiler.dumps().count("\n")
    profiler.resume()
    (nd.ones((4,)) + 1).wait_to_read()
    profiler.set_state("stop")
    assert profiler.dumps().count("\n") >= n_paused
    profiler.reset()


def test_profiler_off_has_no_hook():
    from incubator_mxnet_tpu import ndarray as nd_mod
    profiler.set_state("stop")
    assert nd_mod._op_hook is None


def test_device_memory_stats():
    stats = profiler.device_memory_stats()
    assert isinstance(stats, dict)  # may be empty on some backends


def test_assert_almost_equal():
    test_utils.assert_almost_equal(nd.ones((3,)), np.ones(3))
    with pytest.raises(AssertionError, match="max abs err"):
        test_utils.assert_almost_equal(nd.ones((3,)), np.zeros(3))


def test_test_utils_helpers():
    assert test_utils.same(nd.zeros((2, 2)), np.zeros((2, 2)))
    assert test_utils.almost_equal(1.0, 1.0 + 1e-9)
    x = test_utils.rand_ndarray((3, 4))
    assert x.shape == (3, 4)
    shp = test_utils.rand_shape_nd(3, dim=5)
    assert len(shp) == 3 and all(1 <= d <= 5 for d in shp)
    assert test_utils.default_context() is not None


def test_runtime_features():
    feats = runtime.Features()
    assert feats.is_enabled("CPU")
    assert feats.is_enabled("bf16")           # case-insensitive
    assert not feats.is_enabled("OPENCV")
    assert not feats.is_enabled("NONEXISTENT")
    assert any(f.name == "PALLAS" for f in runtime.feature_list())
    assert "CPU" in repr(feats)


def test_model_alias_checkpoint(tmp_path):
    assert mx.model.save_checkpoint is mx.module.save_checkpoint
    import incubator_mxnet_tpu.symbol as sym
    x = sym.Variable("data")
    w = sym.Variable("w")
    out = sym.FullyConnected(x, w, num_hidden=3, no_bias=True)
    prefix = str(tmp_path / "ckpt")
    arg = {"w": nd.ones((3, 4))}
    mx.model.save_checkpoint(prefix, 7, out, arg, {})
    s2, arg2, aux2 = mx.model.load_checkpoint(prefix, 7)
    np.testing.assert_array_equal(arg2["w"].asnumpy(), arg["w"].asnumpy())
    assert aux2 == {}


def test_check_numeric_gradient():
    from incubator_mxnet_tpu import test_utils, nd
    x = nd.array(np.random.RandomState(0).randn(3, 4).astype(np.float32))
    test_utils.check_numeric_gradient(lambda a: (a * a).sum() + a.sum(), [x])


def test_check_symbolic_forward_backward():
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import test_utils
    data = mx.sym.Variable("data")
    out = mx.sym.square(data)
    x = np.array([[1.0, -2.0], [3.0, 0.5]], np.float32)
    test_utils.check_symbolic_forward(out, {"data": x}, [x * x])
    og = np.ones_like(x)
    test_utils.check_symbolic_backward(out, {"data": x}, [og],
                                       {"data": 2 * x})


def test_plot_network_emits_dot():
    """Parity: mx.viz.plot_network — DOT source with reference node
    scheme; weights hidden by default; .save writes, .render explains."""
    import pytest
    from incubator_mxnet_tpu import symbol as sym
    x = sym.Variable("data")
    net = sym.FullyConnected(sym.Activation(sym.Convolution(
        x, kernel=(3, 3), num_filter=8, name="conv0"), act_type="relu"),
        num_hidden=10, name="fc0")
    g = mx.viz.plot_network(net, shape={"data": (1, 3, 8, 8)})
    src = g.source
    assert src.startswith('digraph') and "conv0" in src and "fc0" in src
    assert "conv0_weight" not in src
    assert "conv0_weight" in mx.viz.plot_network(
        net, hide_weights=False).source
    with pytest.raises(ImportError):
        g.render()


def test_plot_network_escaping_and_node_attrs():
    from incubator_mxnet_tpu import symbol as sym
    x = sym.Variable('a"b')
    out = sym.relu(x, name="r0")
    g = mx.viz.plot_network(out, title='my "best" net', hide_weights=False,
                            node_attrs={"fontsize": "9"})
    src = g.source
    assert '\\"best\\"' in src and '"a\\"b"' in src   # DOT-escaped
    assert 'fontsize="9"' in src                      # node_attrs merged
