"""linalg op family vs numpy/scipy references (parity:
python/mxnet/ndarray/linalg.py, src/operator/tensor/la_op.cc)."""
import numpy as np
import scipy.linalg as sla

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.ndarray import linalg

rng = np.random.RandomState(0)


def _spd(n, batch=()):
    a = rng.randn(*batch, n, n).astype(np.float32)
    return a @ np.swapaxes(a, -1, -2) + n * np.eye(n, dtype=np.float32)


def test_gemm_and_gemm2():
    A = rng.randn(3, 4).astype(np.float32)
    B = rng.randn(4, 5).astype(np.float32)
    C = rng.randn(3, 5).astype(np.float32)
    out = linalg.gemm(nd.array(A), nd.array(B), nd.array(C),
                      alpha=2.0, beta=0.5).asnumpy()
    np.testing.assert_allclose(out, 2 * A @ B + 0.5 * C, rtol=1e-5)
    out2 = linalg.gemm2(nd.array(A), nd.array(B.T),
                        transpose_b=True).asnumpy()
    np.testing.assert_allclose(out2, A @ B, rtol=1e-5)


def test_potrf_potri_sumlogdiag():
    S = _spd(4)
    L = linalg.potrf(nd.array(S)).asnumpy()
    np.testing.assert_allclose(L @ L.T, S, rtol=1e-4, atol=1e-4)
    Sinv = linalg.potri(nd.array(L)).asnumpy()
    np.testing.assert_allclose(Sinv, np.linalg.inv(S), rtol=1e-3, atol=1e-3)
    sld = float(linalg.sumlogdiag(nd.array(L)).asnumpy())
    np.testing.assert_allclose(2 * sld, np.linalg.slogdet(S)[1], rtol=1e-4)


def test_trmm_trsm():
    L = np.tril(rng.randn(4, 4).astype(np.float32)) + 4 * np.eye(4, dtype=np.float32)
    B = rng.randn(4, 3).astype(np.float32)
    out = linalg.trmm(nd.array(L), nd.array(B), alpha=2.0).asnumpy()
    np.testing.assert_allclose(out, 2 * L @ B, rtol=1e-5)
    X = linalg.trsm(nd.array(L), nd.array(B)).asnumpy()
    np.testing.assert_allclose(L @ X, B, rtol=1e-4, atol=1e-5)
    # rightside + transpose
    B2 = rng.randn(3, 4).astype(np.float32)
    X2 = linalg.trsm(nd.array(L), nd.array(B2), rightside=True,
                     transpose=True).asnumpy()
    np.testing.assert_allclose(X2 @ L.T, B2, rtol=1e-4, atol=1e-5)


def test_syrk_batched():
    A = rng.randn(2, 3, 5).astype(np.float32)
    out = linalg.syrk(nd.array(A), alpha=0.5).asnumpy()
    np.testing.assert_allclose(out, 0.5 * A @ np.swapaxes(A, -1, -2),
                               rtol=1e-5)


def test_gelqf():
    A = rng.randn(3, 6).astype(np.float32)
    L, Q = linalg.gelqf(nd.array(A))
    L, Q = L.asnumpy(), Q.asnumpy()
    np.testing.assert_allclose(L @ Q, A, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(Q @ Q.T, np.eye(3), atol=1e-5)
    assert np.allclose(np.triu(L, 1), 0, atol=1e-5)


def test_syevd():
    S = _spd(5)
    U, lam = linalg.syevd(nd.array(S))
    U, lam = U.asnumpy(), lam.asnumpy()
    np.testing.assert_allclose(U.T @ np.diag(lam) @ U, S, rtol=1e-3,
                               atol=1e-3)


def test_inverse_det_slogdet():
    S = _spd(4)
    np.testing.assert_allclose(linalg.inverse(nd.array(S)).asnumpy(),
                               np.linalg.inv(S), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(float(linalg.det(nd.array(S)).asnumpy()),
                               np.linalg.det(S), rtol=1e-3)
    sign, logabs = linalg.slogdet(nd.array(S))
    np.testing.assert_allclose(float(logabs.asnumpy()),
                               np.linalg.slogdet(S)[1], rtol=1e-4)


def test_diag_trian_roundtrips():
    v = rng.randn(2, 4).astype(np.float32)
    D = linalg.makediag(nd.array(v)).asnumpy()
    assert D.shape == (2, 4, 4)
    np.testing.assert_allclose(D[0], np.diag(v[0]), rtol=1e-6)
    back = linalg.extractdiag(nd.array(D)).asnumpy()
    np.testing.assert_allclose(back, v)
    # packed triangle roundtrip
    M = np.tril(rng.randn(4, 4).astype(np.float32))
    packed = linalg.extracttrian(nd.array(M)).asnumpy()
    assert packed.shape == (10,)
    M2 = linalg.maketrian(nd.array(packed)).asnumpy()
    np.testing.assert_allclose(M2, M, rtol=1e-6)


def test_linalg_grad_flows():
    S = _spd(3)
    a = nd.array(S)
    a.attach_grad()
    with mx.autograd.record():
        L = linalg.potrf(a)
        loss = linalg.sumlogdiag(L)
    loss.backward()
    g = a._grad.asnumpy()
    # d/dA of 0.5*logdet(A) = 0.5*A^-1
    np.testing.assert_allclose(g, 0.5 * np.linalg.inv(S), rtol=1e-3,
                               atol=1e-4)


def test_trian_offset_band():
    # positive offset selects the UPPER band (reference offset-sign rule)
    v = np.array([1.0, 2.0, 3.0], np.float32)
    M = linalg.maketrian(nd.array(v), offset=1).asnumpy()
    assert M.shape == (3, 3)
    expected = np.array([[0, 1, 2], [0, 0, 3], [0, 0, 0]], np.float32)
    np.testing.assert_allclose(M, expected)
    back = linalg.extracttrian(nd.array(M), offset=1).asnumpy()
    np.testing.assert_allclose(back, v)
