"""Examples stay runnable (slow tier): each script is executed with tiny
arguments in a subprocess on the CPU backend."""
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "examples", script), *args],
        capture_output=True, text=True, timeout=500, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


@pytest.mark.slow
def test_example_mnist():
    out = _run("train_mnist_gluon.py", "--epochs", "1",
               "--num-examples", "512", "--batch-size", "64")
    assert "accuracy=" in out


@pytest.mark.slow
def test_example_resnet_mesh():
    out = _run("train_resnet_mesh.py", "--model", "resnet18_v1", "--dp", "8",
               "--batch-size", "16", "--size", "32", "--steps", "2",
               "--dtype", "float32")
    assert "img/s" in out


@pytest.mark.slow
def test_example_bert():
    out = _run("bert_pretrain_toy.py", "--steps", "4", "--layers", "1",
               "--seq-len", "32")
    assert "loss" in out


@pytest.mark.slow
def test_example_bert_ring():
    out = _run("bert_pretrain_toy.py", "--steps", "2", "--layers", "1",
               "--seq-len", "64", "--ring-sp", "8")
    assert "loss" in out


@pytest.mark.slow
def test_example_ssd():
    out = _run("train_ssd_toy.py", "--epochs", "1")
    assert "detect()" in out


@pytest.mark.slow
def test_example_rnn_bucketing():
    out = _run("train_rnn_bucketing.py", "--num-sentences", "800",
               "--epochs", "3")
    assert "perplexity=" in out


@pytest.mark.slow
def test_example_quantize_inference():
    out = _run("quantize_inference.py")
    assert "agreement" in out


@pytest.mark.slow
def test_example_onnx():
    out = _run("onnx_export_import.py", "--steps", "5")
    assert "OK: ONNX round trip preserves predictions" in out


@pytest.mark.slow
def test_example_train_lm():
    out = _run("train_lm.py", "--steps", "60")
    assert "greedy :" in out and "loss" in out


@pytest.mark.slow
def test_example_train_lm_distributed(tmp_path):
    out = _run("train_lm_distributed.py", "--steps", "12",
               "--save-every", "6", "--ckpt-dir", str(tmp_path / "ck"))
    assert "dp mesh" in out and "checkpoint ->" in out
    out2 = _run("train_lm_distributed.py", "--steps", "16",
                "--save-every", "8", "--ckpt-dir", str(tmp_path / "ck"))
    assert "resumed from step" in out2


@pytest.mark.slow
def test_example_estimator_mnist(tmp_path):
    out = _run("estimator_mnist.py", "--epochs", "2",
               "--num-examples", "512", "--ckpt-dir", str(tmp_path))
    acc = float(out.split("final validation accuracy=")[1].split()[0])
    assert acc > 0.5, acc  # the blobs are deliberately learnable
    assert (tmp_path / "lenet-best.params").exists()
