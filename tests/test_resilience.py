"""mxtpu.resilience — the recovery-policy matrix (ISSUE 12).

Covers: manifest integrity + torn-checkpoint fallback, atomic-save
invisibility, bounded rotation, save-is-async (the training thread
never blocks past the boundary copy), bit-exact resume at constant lr,
data-cursor resume not replaying consumed batches, NaN -> rollback ->
retries-exhausted -> escalate, stall -> supervised restart routing,
elastic evict/leave/re-join, disabled-mode zero overhead, and the
tooling contracts (trace_check families + extra, perf_regress
recovered-run notes, mxdiag recover rendering). The chaos harness
(tools/chaos_cluster.py) runs as a subprocess acceptance test.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd, resilience
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.io.prefetch import DevicePrefetcher
from incubator_mxnet_tpu.parallel import (CorruptCheckpointError,
                                          latest_step, list_steps,
                                          read_manifest,
                                          restore_train_step,
                                          save_train_step,
                                          verify_checkpoint)
from incubator_mxnet_tpu.parallel import checkpoint as ckpt_mod
from incubator_mxnet_tpu.profiler.counters import counters
from incubator_mxnet_tpu.resilience import (CheckpointManager,
                                            ElasticGroup,
                                            RecoveryEscalated, Supervisor)
from incubator_mxnet_tpu.trainloop import TrainLoop

_HERE = os.path.dirname(os.path.abspath(__file__))
_TOOLS = os.path.join(os.path.dirname(_HERE), "tools")


def _load_tool(name):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_rtool_" + name, os.path.join(_TOOLS, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# toy fixtures
# ---------------------------------------------------------------------------

_W = np.random.RandomState(7).randn(8, 1).astype(np.float32)


@pytest.fixture
def _fresh_compile_session():
    """Disable the persistent XLA compile cache for a bit-exactness
    test: the cache can hand the resumed executor an executable
    compiled by a PREVIOUS process, and XLA:CPU codegen is not
    bit-stable across compile sessions — last-float-bit divergence
    that is compiler noise, not a resume bug. Restored state itself is
    exact (the other Supervisor tests pin that); bit-exact loss
    comparison is only meaningful between executables born in one
    compiler session, so this test compiles everything fresh."""
    import jax
    old = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", old)


def _batch(i, poison=False):
    r = np.random.RandomState(1000 + i)
    x = r.randn(16, 8).astype(np.float32)
    if poison:
        x[0, 0] = np.nan
    return (x, (x @ _W).astype(np.float32))


def _loop(seed=0, chunk=2):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(1))
    net.initialize(init=mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    return TrainLoop(net, gluon.loss.L2Loss(), tr, chunk=chunk)


def _snap():
    return {k: v for k, v in counters().items()
            if k.startswith("resilience/") and not isinstance(v, dict)}


# ---------------------------------------------------------------------------
# checkpoint layer: manifest, atomicity, fallback, rotation, async
# ---------------------------------------------------------------------------

class TestCheckpointIntegrity:
    def _built_step(self, n_steps=2):
        loop = _loop()
        data = [_batch(i) for i in range(20)]
        loop.fit(data, steps=n_steps, cycle=False)
        return loop.step

    def test_manifest_written_and_verifies(self, tmp_path):
        step = self._built_step()
        p = save_train_step(str(tmp_path), step, cursor=5)
        status, errs = verify_checkpoint(p)
        assert (status, errs) == ("ok", [])
        man = read_manifest(p)
        assert man["schema"].startswith("mxtpu.ckpt-manifest/")
        assert man["meta"] == {"num_update": 2, "cursor": 5}
        assert man["files"]          # per-shard digests present
        for rec in man["files"].values():
            assert rec["bytes"] >= 0 and len(rec["sha256"]) == 64

    def test_torn_checkpoint_detected_and_fallback(self, tmp_path):
        step = self._built_step()
        save_train_step(str(tmp_path), step)        # good @ 2
        data = [_batch(i) for i in range(20, 26)]
        for xy in [data[i:i + 2] for i in range(0, 4, 2)]:
            step.run_k(np.stack([b[0] for b in xy]),
                       np.stack([b[1] for b in xy]))
        p2 = save_train_step(str(tmp_path), step)   # newest @ 6
        # tear the newest: bit-flip its largest payload file
        victim, size = None, -1
        for root, _d, files in os.walk(p2):
            for f in files:
                if f == "manifest.json":
                    continue
                fp = os.path.join(root, f)
                if os.path.getsize(fp) > size:
                    victim, size = fp, os.path.getsize(fp)
        with open(victim, "r+b") as f:
            b = f.read(1)
            f.seek(0)
            f.write(bytes([b[0] ^ 0xFF]))
        assert verify_checkpoint(p2)[0] == "corrupt"
        before = counters().get(
            "resilience/resilience.corrupt_checkpoints", 0)
        fresh = self._built_step(n_steps=2)
        # latest-good path: falls back past the torn one, counted
        n = restore_train_step(str(tmp_path), fresh)
        assert n == 2
        assert counters()["resilience/resilience.corrupt_checkpoints"] \
            == before + 1
        # explicit request for the torn step: refuses loudly
        with pytest.raises(CorruptCheckpointError):
            restore_train_step(str(tmp_path), fresh, step_num=6)

    def test_all_corrupt_raises(self, tmp_path):
        step = self._built_step()
        p = save_train_step(str(tmp_path), step)
        man = os.path.join(p, "manifest.json")
        doc = json.load(open(man))
        first = next(iter(doc["files"]))
        doc["files"][first]["sha256"] = "0" * 64
        json.dump(doc, open(man, "w"))
        fresh = self._built_step()
        with pytest.raises(CorruptCheckpointError, match="every"):
            restore_train_step(str(tmp_path), fresh)

    def test_inflight_temp_dir_never_visible(self, tmp_path):
        """A crashed mid-save leaves only a dot-prefixed temp dir —
        latest_step/list_steps must never surface it."""
        step = self._built_step()
        save_train_step(str(tmp_path), step)
        os.makedirs(tmp_path / ".tmp_step_00000099.1234.5678")
        (tmp_path / ".tmp_step_00000099.1234.5678" / "junk").write_bytes(
            b"torn")
        assert latest_step(str(tmp_path)) == 2
        assert list_steps(str(tmp_path)) == [2]

    def test_rotation_bounded(self, tmp_path):
        step = self._built_step()
        mgr = CheckpointManager(str(tmp_path), step, every=1, keep=2)
        try:
            for i in range(5):
                mgr.save_now(step_num=10 + i, block=True)
            mgr.wait()
            time.sleep(0.05)       # let the last prune land
            assert len(list_steps(str(tmp_path))) <= 2
            assert list_steps(str(tmp_path))[-1] == 14
            assert counters()[
                "resilience/resilience.checkpoints_pruned"] >= 3
        finally:
            mgr.close()

    def test_cadence_not_stretched_by_chunk_misalignment(self, tmp_path):
        """every=3 with a chunk advancing num_update by 2 must still
        checkpoint roughly every 3 steps (crossing the boundary), not
        every lcm(3,2)=6 (landing exactly on it)."""
        step = self._built_step()
        mgr = CheckpointManager(str(tmp_path), step, every=3, keep=10)
        try:
            saved = [n for n in range(2, 14, 2)
                     if mgr.maybe_save(step_num=n) and mgr.wait(5)]
            assert saved == [4, 6, 10, 12]
        finally:
            mgr.close()

    def test_cadence_reanchors_after_rollback(self, tmp_path):
        """A restore moves num_update below the save high-water mark;
        replayed steps must checkpoint on cadence again instead of
        waiting to re-cross the old mark."""
        step = self._built_step()
        mgr = CheckpointManager(str(tmp_path), step, every=2, keep=10)
        try:
            assert mgr.maybe_save(step_num=2) and mgr.wait(5)
            assert mgr.maybe_save(step_num=8) and mgr.wait(5)
            # tear the newest so the restore lands BELOW the high-water
            man = tmp_path / "step_00000008" / "manifest.json"
            doc = json.loads(man.read_text())
            first = next(iter(doc["files"]))
            doc["files"][first]["sha256"] = "0" * 64
            man.write_text(json.dumps(doc))
            n, _cur = mgr.restore_last_good()
            assert n == 2
            assert mgr.maybe_save(step_num=4)   # replay checkpoints
        finally:
            mgr.close()

    def test_save_is_async_never_blocks_past_copy(self, tmp_path,
                                                  monkeypatch):
        """The training thread pays the boundary copy only: with a slow
        serializer, maybe_save returns fast and an in-flight save turns
        the next boundary into a counted skip, not a wait."""
        step = self._built_step()
        real_save = ckpt_mod.save_tree

        def slow_save(directory, n, tree, meta=None):
            time.sleep(0.6)
            return real_save(directory, n, tree, meta=meta)

        monkeypatch.setattr(ckpt_mod, "save_tree", slow_save)
        mgr = CheckpointManager(str(tmp_path), step, every=1, keep=3)
        try:
            skipped0 = counters().get(
                "resilience/resilience.saves_skipped", 0)
            t0 = time.perf_counter()
            assert mgr.maybe_save(step_num=1)
            first = time.perf_counter() - t0
            assert first < 0.4, \
                f"maybe_save blocked {first:.3f}s on serialization"
            t0 = time.perf_counter()
            assert not mgr.maybe_save(step_num=2)   # in flight -> skip
            assert time.perf_counter() - t0 < 0.2
            assert counters()["resilience/resilience.saves_skipped"] \
                == skipped0 + 1
            mgr.wait()
            assert mgr.last_saved_step == 1
        finally:
            mgr.close()


# ---------------------------------------------------------------------------
# supervisor: resume exactness, cursor, rollback, escalation, stall
# ---------------------------------------------------------------------------

class TestSupervisor:
    def test_bit_exact_resume_constant_lr(self, tmp_path,
                                          _fresh_compile_session):
        data = [_batch(i) for i in range(40)]
        gold = _loop().fit(data, steps=12, cycle=False)

        d = str(tmp_path / "ck")
        loop1 = _loop()
        first = loop1.fit(data, steps=6,
                          resilience=Supervisor(d, every=100))

        # the resume contract, asserted where it is guaranteed: the
        # restored state is BIT-identical to the live state the first
        # run ended with (params + optimizer + rng + update counter)
        import jax
        live = jax.tree_util.tree_leaves(ckpt_mod._host_tree(loop1.step))
        loop2 = _loop()
        x, y = _batch(0)
        loop2.step.ensure_built(nd.array(x), nd.array(y))
        restore_train_step(d, loop2.step)
        assert loop2.step._num_update == 6
        restored = jax.tree_util.tree_leaves(
            ckpt_mod._host_tree(loop2.step))
        assert len(live) == len(restored)
        for a, b in zip(live, restored):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        resumed = _loop().fit(data, steps=12,
                              resilience=Supervisor(d, every=100))
        got = np.concatenate([first, resumed])
        # the continued trajectory matches the uninterrupted one. NOT
        # assert_array_equal: gold and the resumed run execute
        # separately-compiled XLA:CPU programs, and the autotuner's
        # per-compile choices (measured: a ~2^-8 dot-precision variant
        # under load) are not bit-stable across compiles — compiler
        # variance, not resume state drift (pinned bit-exactly above).
        # A reset/diverged trajectory differs by >100%; 1e-2 is far
        # below that and above the measured compiler noise.
        np.testing.assert_allclose(got, gold, rtol=1e-2)

    def test_ambient_arming_degrades_not_crashes(self, tmp_path,
                                                 monkeypatch):
        # MXTPU_RESILIENCE_DIR arms every Trainer ambiently; an
        # epochs-driven fit that predates resilience must keep working
        # (unsupervised + warning), and resilience=False opts a single
        # call out of the ambient default
        amb = str(tmp_path / "amb")
        monkeypatch.setenv("MXTPU_RESILIENCE_DIR", amb)
        data = [_batch(i) for i in range(8)]
        with pytest.warns(UserWarning, match="UNSUPERVISED"):
            losses = _loop().fit(data, epochs=1)
        assert len(losses) == 8
        losses = _loop().fit(data, steps=4, resilience=False)
        assert len(losses) == 4
        assert not os.path.isdir(amb)   # nothing ever armed
        # explicit misuse still raises
        with pytest.raises(ValueError, match="steps-driven"):
            _loop().fit(data, epochs=1,
                        resilience=Supervisor(str(tmp_path / "x")))

    def test_cursor_resume_skips_consumed_batches(self, tmp_path):
        data = [_batch(i) for i in range(40)]
        d = str(tmp_path / "ck")
        _loop().fit(data, steps=6, resilience=Supervisor(d, every=100))
        man = read_manifest(
            os.path.join(d, f"step_{6:08d}"))
        assert man["meta"]["cursor"] == 6   # 3 chunks x 2 batches
        skipped0 = counters().get("io/io.batches_skipped", 0)
        _loop().fit(data, steps=12, resilience=Supervisor(d, every=100))
        assert counters()["io/io.batches_skipped"] == skipped0 + 6

    def test_nan_rollback_skips_poison_and_converges(self, tmp_path):
        data = [_batch(i, poison=(i == 7)) for i in range(60)]
        rb0 = counters().get("resilience/resilience.rollbacks", 0)
        loop = _loop()
        losses = loop.fit(data, steps=12,
                          resilience=Supervisor(str(tmp_path), every=2))
        assert len(losses) == 12
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]
        c = counters()
        assert c["resilience/resilience.rollbacks"] == rb0 + 1
        assert c["resilience/resilience.recoveries_total"] >= 1
        assert loop.step._num_update == 12

    def test_retries_exhausted_escalates(self, tmp_path):
        data = [_batch(i, poison=True) for i in range(60)]
        esc0 = counters().get(
            "resilience/resilience.retries_exhausted", 0)
        with pytest.raises(RecoveryEscalated, match="consecutive"):
            _loop().fit(data, steps=12,
                        resilience=Supervisor(str(tmp_path), every=2,
                                              max_retries=2,
                                              backoff_s=0.0))
        assert counters()[
            "resilience/resilience.retries_exhausted"] == esc0 + 1

    def test_reread_mode_retries_same_chunk(self, tmp_path):
        """skip_poison=False re-reads the faulting chunk — with a
        persistent poison batch that means escalation after exactly
        max_retries re-reads (the transient-fault policy)."""
        data = [_batch(i, poison=(i == 3)) for i in range(60)]
        with pytest.raises(RecoveryEscalated):
            _loop().fit(data, steps=12,
                        resilience=Supervisor(str(tmp_path), every=2,
                                              max_retries=1,
                                              backoff_s=0.0,
                                              skip_poison=False))

    def test_stall_routes_to_registered_supervisor(self, tmp_path):
        sup = Supervisor(str(tmp_path), on_stall="none")
        resilience._register(sup)
        try:
            mon = mx.healthmon.enable(
                hm_dir=str(tmp_path), stall_timeout_s=0,
                events_path=str(tmp_path / "ev.jsonl"))
            r0 = counters().get(
                "resilience/resilience.restarts_requested", 0)
            mon._alert("stall", {"age_s": 12.0})
            assert counters()[
                "resilience/resilience.restarts_requested"] == r0 + 1
            # non-stall verdicts are the drive loop's problem, not the
            # alert hook's
            mon._alert("nan_loss", {"value": "nan"})
            assert counters()[
                "resilience/resilience.restarts_requested"] == r0 + 1
            ev = (tmp_path / "ev.jsonl").read_text()
            assert "resilience.restart_requested" in ev
        finally:
            mx.healthmon.disable()
            resilience._unregister(sup)

    def test_invalid_on_stall_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="on_stall"):
            Supervisor(str(tmp_path), on_stall="reboot")

    def test_epochs_mode_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="steps-driven"):
            _loop().fit([_batch(i) for i in range(8)], epochs=1,
                        resilience=str(tmp_path))

    def test_healthmon_status_carries_resilience(self):
        st = mx.healthmon.status()
        assert "resilience" in st
        rs = st["resilience"]
        for key in ("supervised", "last_checkpoint_step",
                    "recoveries_total", "rollback_in_progress"):
            assert key in rs
        assert rs["supervised"] is False


class TestDisabledOverhead:
    def test_plain_fit_touches_no_resilience_state(self):
        """The disabled-cost contract: an unsupervised fit leaves every
        resilience counter untouched and registers no supervisor."""
        before = _snap()
        data = [_batch(i) for i in range(20)]
        _loop().fit(data, steps=4, cycle=False)
        assert _snap() == before
        assert resilience.current() is None
        assert not resilience.supervised()


# ---------------------------------------------------------------------------
# prefetcher cursor skip
# ---------------------------------------------------------------------------

class TestPrefetcherSkip:
    def test_skip_drops_exactly_n(self):
        items = [(np.full((2, 2), i, np.float32),
                  np.full((2, 1), i, np.float32)) for i in range(10)]
        skipped0 = counters().get("io/io.batches_skipped", 0)
        with DevicePrefetcher(items, depth=2, skip=3) as pf:
            got = [float(np.asarray(x)[0, 0]) for x, _ in pf]
        assert got == [3.0, 4.0, 5.0, 6.0, 7.0, 8.0, 9.0]
        assert counters()["io/io.batches_skipped"] == skipped0 + 3

    def test_negative_skip_rejected(self):
        with pytest.raises(ValueError, match="skip"):
            DevicePrefetcher([], skip=-1)

    def test_cycling_skip_folds_to_epoch_position(self):
        # a long run's absolute cursor through a cycling source resumes
        # at cursor % epoch — after ONE learning pass, whole epochs of
        # the skip fold away instead of being read and discarded
        items = [(np.full((2, 2), i, np.float32),
                  np.full((2, 1), i, np.float32)) for i in range(4)]
        skipped0 = counters().get("io/io.batches_skipped", 0)
        with DevicePrefetcher(items, depth=2, skip=10, cycle=True) as pf:
            got = [float(np.asarray(next(pf)[0])[0, 0]) for _ in range(3)]
        assert got == [2.0, 3.0, 0.0]           # 10 % 4 = 2
        # one full learning pass (4) + in-epoch remainder (2), not 10
        assert counters()["io/io.batches_skipped"] == skipped0 + 6

    def test_sharded_rejoin_replays_zero_batches(self, tmp_path):
        # the PR 17 resume matrix: sharded record reader x skip cursor
        # x an evicted rank re-joining. The re-joined rank must resume
        # ITS shard exactly where the cursor says — zero replayed
        # batches, zero holes, order bit-identical to a serial rank
        # that never left, at any decode-pool width.
        from incubator_mxnet_tpu import recordio
        from incubator_mxnet_tpu.io.pipeline import ShardedRecordReader
        idx = str(tmp_path / "s.idx")
        rec = str(tmp_path / "s.rec")
        w = recordio.MXIndexedRecordIO(idx, rec, "w")
        for i in range(21):
            w.write_idx(i, recordio.pack(
                recordio.IRHeader(0, float(i), i, 0),
                np.full((2, 2), i, np.float32).tobytes()))
        w.close()

        def decode(payload):
            _h, s = recordio.unpack(payload)
            x = np.frombuffer(s, np.float32).reshape(2, 2).copy()
            return x, x[:, :1]

        def rank_reader():
            return ShardedRecordReader(idx, rec, rank=1, num_ranks=3,
                                       decode_fn=decode)

        def trace(pf, n=None):
            out = []
            for x, _ in pf:
                out.append(int(np.asarray(x)[0, 0]))
                if n is not None and len(out) == n:
                    break
            return out

        # the never-evicted serial reference for this rank's shard
        with DevicePrefetcher(rank_reader(), depth=1,
                              workers=1) as pf:
            gold = trace(pf)
        assert gold == list(range(1, 21, 3))      # keys[1::3]

        # rank trains 3 batches through the 4-worker pool, is evicted
        # (close), re-joins with skip=cursor: the tail must butt-join
        cursor = 3
        with DevicePrefetcher(rank_reader(), depth=2, workers=4) as pf:
            head = trace(pf, n=cursor)
        with DevicePrefetcher(rank_reader(), depth=2, workers=4,
                              skip=cursor) as pf:
            tail = trace(pf)
        assert head + tail == gold                # zero replay, no holes
        assert len(set(head + tail)) == len(gold)


# ---------------------------------------------------------------------------
# elastic membership
# ---------------------------------------------------------------------------

class TestElastic:
    def _pair(self, timeout=1.0):
        g0 = ElasticGroup(rank=0, sync_timeout_s=timeout)
        g1 = ElasticGroup(rank=1, addr=g0.addr, sync_timeout_s=timeout)
        g0.join()
        g1.join()
        return g0, g1

    def test_evict_on_deadline_and_survivor_continues(self):
        g0, g1 = self._pair()
        try:
            out = {}

            def run(g, steps, die_at=None):
                v = np.full(3, float(g.rank + 1), np.float32)
                hist = []
                for s in range(1, steps + 1):
                    if die_at == s:
                        return
                    mean, info = g.sync(s, v)
                    hist.append((s, float(mean[0]), info["generation"],
                                 tuple(info["departed"])))
                out[g.rank] = hist

            t0 = threading.Thread(target=run, args=(g0, 3))
            t1 = threading.Thread(target=run, args=(g1, 3, 2))
            t0.start(); t1.start(); t0.join(); t1.join()
            hist = out[0]
            assert hist[0] == (1, 1.5, 1, ())       # both contributed
            assert hist[1][3] == (1,)               # eviction observed
            assert hist[2] == (3, 1.0, 2, ())       # solo, new gen
        finally:
            g0.leave()

    def test_graceful_leave_is_not_a_departure(self):
        g0, g1 = self._pair()
        try:
            done = threading.Event()

            def r1():
                g1.sync(1, np.zeros(2, np.float32))
                g1.leave()
                done.set()

            t = threading.Thread(target=r1)
            t.start()
            g0.sync(1, np.zeros(2, np.float32))
            t.join()
            assert done.wait(5)
            _, info = g0.sync(2, np.zeros(2, np.float32))
            assert info["membership_changed"]
            assert info["left"] == [1]
            assert info["departed"] == []           # no rollback cue
        finally:
            g0.leave()

    def test_rejoin_waits_for_checkpoint_boundary(self):
        g0 = ElasticGroup(rank=0, sync_timeout_s=1.0)
        try:
            g0.join()
            g0.sync(1, np.zeros(2, np.float32))     # group started
            g1 = ElasticGroup(rank=1, addr=g0.addr, sync_timeout_s=1.0)
            # no checkpoint yet: not admitted
            with pytest.raises(TimeoutError):
                g1.join(poll_s=0.05, timeout_s=0.4)
            g0.report_checkpoint(1, "/tmp/ck/step_1")
            j = g1.join(poll_s=0.05, timeout_s=5)
            assert j["admitted"] and j["last_good"]["step"] == 1
            assert j["next_step"] == 2
        finally:
            g0.leave()

    def test_ahead_member_never_evicted_from_stale_round(self):
        g0 = ElasticGroup(rank=0, sync_timeout_s=1.0)
        try:
            g0.join()
            for s in (1, 2, 3):
                g0.sync(s, np.full(2, 10.0, np.float32))
            g0.report_checkpoint(3, "/tmp/ck/step_3")
            g1 = ElasticGroup(rank=1, addr=g0.addr, sync_timeout_s=1.0)
            g1.join()
            # a lagging joiner replaying round 2 (stale): rank 0 already
            # synced past it — the round must complete WITHOUT waiting
            # out the deadline and WITHOUT evicting rank 0
            t0 = time.perf_counter()
            mean, info = g1.sync(2, np.full(2, 20.0, np.float32))
            assert time.perf_counter() - t0 < 0.9
            assert 0 in info["members"]
            assert float(mean[0]) == 15.0   # rank 0's round-2 vec kept
        finally:
            g0.leave()

    def test_evicted_rank_must_rejoin(self):
        g0, g1 = self._pair(timeout=0.5)
        try:
            g0.sync(1, np.zeros(2, np.float32))     # evicts silent g1
            with pytest.raises(RuntimeError, match="not a member"):
                g1.sync(2, np.zeros(2, np.float32))
        finally:
            g0.leave()


# ---------------------------------------------------------------------------
# tooling: trace_check, perf_regress, mxdiag recover
# ---------------------------------------------------------------------------

class TestTooling:
    def test_resilience_families_enforced(self):
        tc = _load_tool("trace_check")
        ok = {"resilience/resilience.rollbacks": "counter",
              "resilience/resilience.save_ms": "histogram",
              "resilience/resilience.last_checkpoint_step": "gauge"}
        assert tc.check_healthmon_kinds(ok) == []
        bad_name = {"resilience/resilience.invented": "counter"}
        assert tc.check_healthmon_kinds(bad_name)
        bad_kind = {"resilience/resilience.rollbacks": "gauge"}
        assert tc.check_healthmon_kinds(bad_kind)

    def test_check_resilience_extra_matrix(self):
        tc = _load_tool("trace_check")
        good = {"enabled": True, "checkpoints_saved": 3,
                "last_checkpoint_step": 30, "recoveries_total": 1,
                "rollbacks": 1, "steps_lost_last": 2,
                "steps_lost_total": 2,
                "save": {"count": 3, "p50_ms": 50.0, "p95_ms": 80.0},
                "copy": {"count": 3, "p50_ms": 1.0, "p95_ms": 2.0},
                "every": 10, "keep": 3}
        assert tc.check_resilience_extra(good) == []
        assert tc.check_resilience_extra(None) == []
        assert tc.check_resilience_extra(
            dict(good, rollbacks=-1))
        assert tc.check_resilience_extra(
            dict(good, save={"count": 3, "p50_ms": 90.0,
                             "p95_ms": 80.0}))
        assert tc.check_resilience_extra(
            dict(good, recoveries_total=2, rollbacks=0,
                 resumes=0))      # recovery with no trail
        assert tc.check_resilience_extra(dict(good, keep=0))

    def test_perf_regress_notes_recovery_and_accepts(self, tmp_path):
        pr = _load_tool("perf_regress")
        base = {"metric": "train_throughput", "value": 100.0,
                "unit": "img/s", "extra": {"mfu": 0.1}}
        cand = dict(base, extra={
            "mfu": 0.1,
            "resilience": {"enabled": True, "checkpoints_saved": 2,
                           "recoveries_total": 1, "rollbacks": 1,
                           "steps_lost_last": 4, "steps_lost_total": 4,
                           "save": None, "copy": None}})
        bp, cp = tmp_path / "b.json", tmp_path / "c.json"
        bp.write_text(json.dumps(base))
        cp.write_text(json.dumps(cand))
        b, err = pr.load_artifact(str(bp))
        assert err is None
        c, err = pr.load_artifact(str(cp))
        assert err is None and c["recoveries"] == 1 \
            and c["steps_lost"] == 4
        regs, notes = pr.compare(b, c)
        assert not regs             # a recovered run is USABLE
        assert any("RECOVERED 1 time(s), 4 step(s) lost" in n
                   for n in notes)

    def test_mxdiag_recover_renders_and_flags(self, tmp_path, capsys):
        md = _load_tool("mxdiag")
        ev = tmp_path / "ev.jsonl"

        def rec(ts, kind, name, step=None, args=None):
            d = {"schema": "mxtpu.events/1", "ts": ts, "run_id": "r1",
                 "rank": 0, "step": step, "kind": kind, "name": name}
            if args:
                d["args"] = args
            return json.dumps(d)

        lines = [
            rec(1.0, "lifecycle", "events.open"),
            rec(2.0, "resilience", "resilience.checkpoint_saved",
                step=4, args={"save_ms": 50}),
            rec(3.0, "alert", "healthmon.nan_loss", step=7,
                args={"value": "nan"}),
            rec(3.1, "resilience", "resilience.rollback", step=7,
                args={"from_step": 7, "to_step": 4, "steps_lost": 3,
                      "attempt": 1, "reason": "nan_loss"}),
            rec(4.0, "trainer", "step", step=12),
        ]
        ev.write_text("\n".join(lines) + "\n")
        merged = md.merge_timelines([str(ev)])
        assert md.print_recover(merged) == 0
        out = capsys.readouterr().out
        assert "FAULT" in out and "rollback" in out
        assert "steps_replayed=3" in out
        # an unhandled fault (no action after it) must flag
        ev2 = tmp_path / "ev2.jsonl"
        ev2.write_text("\n".join(lines[:3]) + "\n")
        assert md.print_recover(md.merge_timelines([str(ev2)])) == 1


# ---------------------------------------------------------------------------
# chaos acceptance (subprocess; the ISSUE's tier-1 bar)
# ---------------------------------------------------------------------------

@pytest.mark.serial
def test_chaos_harness_self_heals_through_all_faults(tmp_path):
    """NaN injection, torn checkpoint, frozen rank (stall -> restart),
    and a mid-step rank SIGKILL with elastic re-join: training must run
    to completion with loss DECREASING and >= 1 recovery per fault on
    all three surfaces (counters, flight, events) — asserted by the
    harness itself; re-asserted on the headline here so a weakened
    driver can't silently pass."""
    env = dict(os.environ)
    env["MXTPU_CHAOS_OUT"] = str(tmp_path / "chaos")
    env["MXTPU_CHAOS_STEPS"] = "16"
    env["MXTPU_CHAOS_NAN_BATCH"] = "7"
    env["MXTPU_CHAOS_KILL_STEP"] = "6"
    env["MXTPU_CHAOS_FREEZE_BATCH"] = "6"
    env["MXTPU_CHAOS_CKPT_EVERY"] = "3"
    r = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "chaos_cluster.py")],
        env=env, capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, \
        f"chaos failed\nstdout:{r.stdout[-4000:]}\nstderr:{r.stderr[-3000:]}"
    verdicts = [ln for ln in r.stdout.splitlines()
                if ln.startswith("CHAOS_OK ")]
    assert verdicts, f"no CHAOS_OK in {r.stdout[-2000:]}"
    doc = json.loads(verdicts[0][len("CHAOS_OK "):])
    for scenario in ("nan", "torn", "freeze", "kill"):
        assert scenario in doc, f"scenario {scenario} missing: {doc}"
        assert doc[scenario]["losses"]["decreased"], \
            f"{scenario}: loss did not decrease: {doc[scenario]}"
    assert doc["nan"]["rollbacks"] >= 1
    assert doc["torn"]["corrupt_detected"] >= 1
    assert doc["torn"]["resumes"] >= 1
    assert doc["freeze"]["resumes"] >= 1
    assert doc["kill"]["departures"] >= 1
    assert doc["kill"]["joins"] >= 1
    assert os.path.exists(doc["merged_file"])
