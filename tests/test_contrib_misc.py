"""gluon.contrib, gluon.model_zoo namespace, mx.callback, mx.visualization,
mx.distributed (parity: python/mxnet/gluon/contrib, gluon/model_zoo,
callback.py, visualization.py, the launcher topology env)."""
import logging

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd


# -- contrib.nn -------------------------------------------------------------

def test_sync_batchnorm_is_global_under_mesh():
    """Under the compiled mesh path arrays are global-view, so BatchNorm
    statistics are already cross-device — SyncBatchNorm == BatchNorm here.
    Check dp-sharded fused step equals the single-device full-batch step
    (the property the reference needs an NCCL allreduce for)."""
    import jax
    from incubator_mxnet_tpu.parallel import FusedTrainStep, make_mesh

    def build():
        mx.random.seed(0)
        np.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(4, 3, padding=1, layout="NHWC"),
                gluon.contrib.nn.SyncBatchNorm(axis=-1),
                gluon.nn.Flatten(), gluon.nn.Dense(3))
        net.initialize(init=mx.init.Xavier())
        return net

    x = np.random.RandomState(0).randn(16, 8, 8, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 3, 16)
    L = gluon.loss.SoftmaxCrossEntropyLoss()

    net1 = build()
    step1 = FusedTrainStep(net1, L, mx.optimizer.create("sgd", learning_rate=0.1))
    l1 = float(step1(nd.array(x), nd.array(y)))

    net2 = build()
    mesh = make_mesh({"dp": min(8, len(jax.devices()))})
    step2 = FusedTrainStep(net2, L, mx.optimizer.create("sgd", learning_rate=0.1),
                           mesh=mesh)
    l2 = float(step2(nd.array(x), nd.array(y)))
    assert abs(l1 - l2) < 1e-4, (l1, l2)
    w1 = list(net1.collect_params().values())
    w2 = list(net2.collect_params().values())
    for p1, p2 in zip(w1, w2):
        np.testing.assert_allclose(p1.data().asnumpy(), p2.data().asnumpy(),
                                   rtol=2e-4, atol=2e-4)


def test_hybrid_concurrent_and_identity():
    blk = gluon.contrib.nn.HybridConcurrent(axis=-1)
    blk.add(gluon.nn.Dense(3), gluon.nn.Dense(2),
            gluon.contrib.nn.Identity())
    blk.initialize()
    x = nd.array(np.random.RandomState(0).randn(4, 5).astype(np.float32))
    out = blk(x)
    assert out.shape == (4, 3 + 2 + 5)
    np.testing.assert_allclose(out.asnumpy()[:, 5:], x.asnumpy(), rtol=1e-6)


def test_sparse_embedding_contrib():
    emb = gluon.contrib.nn.SparseEmbedding(20, 4)
    emb.initialize()
    ids = nd.array(np.array([1, 5]))
    with autograd.record():
        loss = (emb(ids) ** 2).sum()
    loss.backward()
    from incubator_mxnet_tpu.ndarray import sparse
    assert isinstance(emb.weight.grad(), sparse.RowSparseNDArray)


# -- model_zoo namespace ----------------------------------------------------

def test_model_zoo_vision_namespace():
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    net = vision.get_model("squeezenet1_1", classes=7)
    net.initialize()
    assert net(nd.ones((1, 64, 64, 3))).shape == (1, 7)
    net2 = vision.resnet18_v1(classes=4)
    assert net2 is not None
    with pytest.raises(ValueError, match="pretrained"):
        vision.get_model("resnet18_v1", pretrained=True)


# -- callbacks --------------------------------------------------------------

class _Param:
    def __init__(self, epoch, nbatch, metric):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = metric


def test_speedometer_logs(caplog):
    m = mx.metric.Accuracy()
    m.update(nd.array(np.array([0, 1])), nd.array(np.array([[0.9, 0.1],
                                                            [0.2, 0.8]])))
    sp = mx.callback.Speedometer(batch_size=32, frequent=2, auto_reset=False)
    with caplog.at_level(logging.INFO):
        for nb in range(5):
            sp(_Param(0, nb, m))
    assert any("samples/sec" in r.message for r in caplog.records)


def test_do_checkpoint_saves(tmp_path):
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    cb = mx.callback.do_checkpoint(str(tmp_path / "model"), period=1)
    arg = {"fc_weight": nd.ones((3, 4)), "fc_bias": nd.zeros((3,))}
    cb(0, out, arg, {})
    assert (tmp_path / "model-0001.params").exists()
    assert (tmp_path / "model-symbol.json").exists()


# -- visualization ----------------------------------------------------------

def test_print_summary(capsys):
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="act1")
    out = mx.sym.FullyConnected(h, num_hidden=2, name="fc2")
    total = mx.viz.print_summary(out, shape={"data": (1, 4)})
    printed = capsys.readouterr().out
    assert "fc1" in printed and "fc2" in printed
    # fc1: 4*8+8, fc2: 8*2+2
    assert total == (4 * 8 + 8) + (8 * 2 + 2)
    with pytest.raises(ImportError, match="graphviz"):
        mx.viz.plot_network(out)


# -- distributed ------------------------------------------------------------

def test_distributed_single_host():
    assert mx.distributed.rank() == 0
    assert mx.distributed.num_workers() == 1
    mx.distributed.barrier()            # no-op single process
    mesh = mx.distributed.global_mesh({"dp": -1})
    assert mesh.devices.size == len(mx.distributed.global_devices())
