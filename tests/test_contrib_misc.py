"""gluon.contrib, gluon.model_zoo namespace, mx.callback, mx.visualization,
mx.distributed (parity: python/mxnet/gluon/contrib, gluon/model_zoo,
callback.py, visualization.py, the launcher topology env)."""
import logging

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd


# -- contrib.nn -------------------------------------------------------------

def test_sync_batchnorm_is_global_under_mesh():
    """Under the compiled mesh path arrays are global-view, so BatchNorm
    statistics are already cross-device — SyncBatchNorm == BatchNorm here.
    Check dp-sharded fused step equals the single-device full-batch step
    (the property the reference needs an NCCL allreduce for)."""
    import jax
    from incubator_mxnet_tpu.parallel import FusedTrainStep, make_mesh

    def build():
        mx.random.seed(0)
        np.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(4, 3, padding=1, layout="NHWC"),
                gluon.contrib.nn.SyncBatchNorm(axis=-1),
                gluon.nn.Flatten(), gluon.nn.Dense(3))
        net.initialize(init=mx.init.Xavier())
        return net

    x = np.random.RandomState(0).randn(16, 6, 6, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 3, 16)
    L = gluon.loss.SoftmaxCrossEntropyLoss()

    net1 = build()
    step1 = FusedTrainStep(net1, L, mx.optimizer.create("sgd", learning_rate=0.1))
    l1 = float(step1(nd.array(x), nd.array(y)))

    net2 = build()
    mesh = make_mesh({"dp": min(8, len(jax.devices()))})
    step2 = FusedTrainStep(net2, L, mx.optimizer.create("sgd", learning_rate=0.1),
                           mesh=mesh)
    l2 = float(step2(nd.array(x), nd.array(y)))
    assert abs(l1 - l2) < 1e-4, (l1, l2)
    w1 = list(net1.collect_params().values())
    w2 = list(net2.collect_params().values())
    for p1, p2 in zip(w1, w2):
        np.testing.assert_allclose(p1.data().asnumpy(), p2.data().asnumpy(),
                                   rtol=2e-4, atol=2e-4)


def test_hybrid_concurrent_and_identity():
    blk = gluon.contrib.nn.HybridConcurrent(axis=-1)
    blk.add(gluon.nn.Dense(3), gluon.nn.Dense(2),
            gluon.contrib.nn.Identity())
    blk.initialize()
    x = nd.array(np.random.RandomState(0).randn(4, 5).astype(np.float32))
    out = blk(x)
    assert out.shape == (4, 3 + 2 + 5)
    np.testing.assert_allclose(out.asnumpy()[:, 5:], x.asnumpy(), rtol=1e-6)


def test_sparse_embedding_contrib():
    emb = gluon.contrib.nn.SparseEmbedding(20, 4)
    emb.initialize()
    ids = nd.array(np.array([1, 5]))
    with autograd.record():
        loss = (emb(ids) ** 2).sum()
    loss.backward()
    from incubator_mxnet_tpu.ndarray import sparse
    assert isinstance(emb.weight.grad(), sparse.RowSparseNDArray)


# -- model_zoo namespace ----------------------------------------------------

def test_model_zoo_vision_namespace():
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    net = vision.get_model("squeezenet1_1", classes=7)
    net.initialize()
    assert net(nd.ones((1, 64, 64, 3))).shape == (1, 7)
    net2 = vision.resnet18_v1(classes=4)
    assert net2 is not None
    with pytest.raises(ValueError, match="pretrained"):
        vision.get_model("resnet18_v1", pretrained=True)


# -- callbacks --------------------------------------------------------------

class _Param:
    def __init__(self, epoch, nbatch, metric):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = metric


def test_speedometer_logs(caplog):
    m = mx.metric.Accuracy()
    m.update(nd.array(np.array([0, 1])), nd.array(np.array([[0.9, 0.1],
                                                            [0.2, 0.8]])))
    sp = mx.callback.Speedometer(batch_size=32, frequent=2, auto_reset=False)
    with caplog.at_level(logging.INFO):
        for nb in range(5):
            sp(_Param(0, nb, m))
    assert any("samples/sec" in r.message for r in caplog.records)


def test_do_checkpoint_saves(tmp_path):
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    cb = mx.callback.do_checkpoint(str(tmp_path / "model"), period=1)
    arg = {"fc_weight": nd.ones((3, 4)), "fc_bias": nd.zeros((3,))}
    cb(0, out, arg, {})
    assert (tmp_path / "model-0001.params").exists()
    assert (tmp_path / "model-symbol.json").exists()


# -- visualization ----------------------------------------------------------

def test_print_summary(capsys):
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="act1")
    out = mx.sym.FullyConnected(h, num_hidden=2, name="fc2")
    total = mx.viz.print_summary(out, shape={"data": (1, 4)})
    printed = capsys.readouterr().out
    assert "fc1" in printed and "fc2" in printed
    # fc1: 4*8+8, fc2: 8*2+2
    assert total == (4 * 8 + 8) + (8 * 2 + 2)
    # plot_network now returns a DOT-carrying digraph; only .render()
    # needs the absent graphviz binary
    g = mx.viz.plot_network(out)
    assert "fc1" in g.source and g.source.startswith("digraph")
    with pytest.raises(ImportError, match="graphviz"):
        g.render()


# -- distributed ------------------------------------------------------------

def test_distributed_single_host():
    assert mx.distributed.rank() == 0
    assert mx.distributed.num_workers() == 1
    mx.distributed.barrier()            # no-op single process
    mesh = mx.distributed.global_mesh({"dp": -1})
    assert mesh.devices.size == len(mx.distributed.global_devices())


# -- Monitor ----------------------------------------------------------------

def test_monitor_collects_stats():
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    mod = mx.mod.Module(out, label_names=[])
    mod.bind(data_shapes=[("data", (4, 3))], label_shapes=None)
    mod.init_params()
    mon = mx.Monitor(interval=2, pattern=".*weight.*|.*output.*")
    mon.install(mod)
    from incubator_mxnet_tpu.io import DataBatch
    collected = []
    for step in range(4):
        mon.tic()
        mod.forward(DataBatch([nd.ones((4, 3))]), is_train=True)
        mod.backward()
        collected.append(mon.toc())
    assert collected[0] and collected[2]          # interval=2: steps 0,2
    assert collected[1] == [] and collected[3] == []
    names = {name for _, name, _ in collected[0]}
    assert "fc_weight" in names and "output0" in names
    assert all(np.isfinite(v) for _, _, v in collected[0])


# -- LibSVMIter -------------------------------------------------------------

def test_libsvm_iter(tmp_path):
    path = tmp_path / "train.libsvm"
    path.write_text("1 0:1.5 3:2.0\n"
                    "0 1:1.0\n"
                    "1 2:3.0 4:1.0\n"
                    "0 0:0.5 4:2.5\n")
    it = mx.io.LibSVMIter(str(path), data_shape=(5,), batch_size=2)
    from incubator_mxnet_tpu.ndarray import sparse
    batches = list(it)
    assert len(batches) == 2
    csr = batches[0].data[0]
    assert isinstance(csr, sparse.CSRNDArray)
    dense = csr.asnumpy()
    np.testing.assert_allclose(dense[0], [1.5, 0, 0, 2.0, 0])
    np.testing.assert_allclose(dense[1], [0, 1.0, 0, 0, 0])
    np.testing.assert_array_equal(batches[0].label[0].asnumpy(), [1.0, 0.0])
    # sparse.dot consumes the batch directly
    w = nd.array(np.random.RandomState(0).randn(5, 3).astype(np.float32))
    out = sparse.dot(csr, w)
    np.testing.assert_allclose(out.asnumpy(), dense @ w.asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_libsvm_iter_pads_last_batch(tmp_path):
    path = tmp_path / "odd.libsvm"
    path.write_text("1 0:1.0\n0 1:1.0\n1 2:1.0\n")
    it = mx.io.LibSVMIter(str(path), data_shape=(4,), batch_size=2)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].pad == 0 and batches[1].pad == 1
    assert batches[1].data[0].shape == (2, 4)


def test_monitor_rejects_garbage_and_sees_buckets():
    import pytest as _pytest
    mon = mx.Monitor(interval=1)
    with _pytest.raises(TypeError, match="cannot monitor"):
        mon.install(object())
