"""mxtpu.healthmon: structured event log, watchdogs (NaN / step-time /
stall), cross-rank skew timeline, Trainer + kvstore + serving hooks, the
mxtpu.events/1 validator, and the mxdiag merge tool."""
import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import diagnostics as diag
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu import healthmon as hm
from incubator_mxnet_tpu.healthmon.events import EventLog
from incubator_mxnet_tpu.healthmon.skew import (CollectiveTimeline,
                                                RECORD_FIELDS)
from incubator_mxnet_tpu.healthmon.watchdog import (NaNSentinel,
                                                    StallWatchdog,
                                                    StepTimeRegression)
from incubator_mxnet_tpu.profiler.counters import (counters as
                                                   counters_snapshot)


def _tool(name):
    base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(base, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _hm_teardown():
    yield
    hm.disable()
    diag.disable()
    from incubator_mxnet_tpu.profiler.counters import reset_counters
    reset_counters()


def _read_events(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------

class TestEventLog:
    def test_records_carry_correlation_ids_and_schema(self, tmp_path):
        p = str(tmp_path / "ev.jsonl")
        log = EventLog(p, "run-abc", 3)
        log.emit("trainer", "step", step=7, args={"ms": 1.5})
        log.emit("alert", "healthmon.nan_loss")
        log.close()
        recs = _read_events(p)
        assert all(r["schema"].startswith("mxtpu.events/") for r in recs)
        # schema /2: every record carries the monotonic companion so an
        # NTP step can't reorder a cross-process merge
        assert all(isinstance(r["mono"], float) for r in recs)
        assert all(r["run_id"] == "run-abc" and r["rank"] == 3
                   for r in recs)
        step_rec = [r for r in recs if r["name"] == "step"][0]
        assert step_rec["step"] == 7 and step_rec["args"] == {"ms": 1.5}
        assert recs[-1]["step"] is None

    def test_timestamps_monotone_under_concurrent_writers(self, tmp_path):
        p = str(tmp_path / "ev.jsonl")
        log = EventLog(p, "r", 0)

        def spam(k):
            for i in range(200):
                log.emit("t", f"w{k}.{i}")

        threads = [threading.Thread(target=spam, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        log.close()
        recs = _read_events(p)
        assert len(recs) == 1 + 4 * 200
        ts = [r["ts"] for r in recs]
        assert ts == sorted(ts)

    def test_emit_after_close_is_noop(self, tmp_path):
        p = str(tmp_path / "ev.jsonl")
        log = EventLog(p, "r", 0)
        log.close()
        log.emit("t", "late")          # must not raise
        assert len(_read_events(p)) == 1

    def test_module_emit_noop_when_off(self):
        from incubator_mxnet_tpu.healthmon import events as ev
        assert ev._LOG is None
        ev.emit("t", "nothing")        # no log, no error

    def test_validator_accepts_and_rejects(self, tmp_path):
        tc = _tool("trace_check")
        p = str(tmp_path / "ev.jsonl")
        log = EventLog(p, "run-x", 0)
        log.emit("trainer", "step", step=1)
        log.close()
        assert tc.check_events_jsonl(p) == []
        assert tc.check_file(p) == []    # auto-detected as events, not
                                         # metrics series
        # broken records
        bad = str(tmp_path / "bad.jsonl")
        with open(bad, "w") as f:
            f.write(json.dumps({"schema": "mxtpu.events/1", "ts": 2.0,
                                "run_id": "r", "rank": 0, "kind": "k",
                                "name": "n"}) + "\n")
            f.write(json.dumps({"schema": "mxtpu.events/1", "ts": 1.0,
                                "run_id": "", "rank": -1, "kind": "k",
                                "name": ""}) + "\n")
        errs = "\n".join(tc.check_events_jsonl(bad))
        assert "ts went backwards" in errs
        assert "run_id" in errs and "rank" in errs and "'name'" in errs

    def test_healthmon_family_schema_enforced(self):
        tc = _tool("trace_check")
        ok = {"healthmon/healthmon.nan_alerts": "counter",
              "healthmon/healthmon.collective_skew_ms": "gauge",
              "serving/serving.latency_ms": "histogram"}
        assert tc.check_healthmon_kinds(ok) == []
        bad = {"healthmon/healthmon.nan_alerts": "gauge",
               "healthmon/healthmon.surprise_metric": "counter"}
        errs = "\n".join(tc.check_healthmon_kinds(bad))
        assert "kind" in errs and "unknown healthmon" in errs


# ---------------------------------------------------------------------------
# watchdogs
# ---------------------------------------------------------------------------

class TestWatchdogs:
    def test_nan_sentinel_alerts_once_per_bad_value(self):
        alerts = []
        s = NaNSentinel(lambda n, a, step=None: alerts.append((n, step)))
        assert s.check(1.0, step=1) is False
        assert s.check(float("nan"), step=2) is True
        assert s.check(float("inf"), step=3) is True
        assert s.alerts == 2
        assert alerts == [("nan_loss", 2), ("nan_loss", 3)]

    def test_nan_sentinel_raise_mode(self):
        s = NaNSentinel(lambda *a, **k: None, on_nan="raise")
        with pytest.raises(FloatingPointError):
            s.check(float("nan"), step=5)
        with pytest.raises(ValueError):
            NaNSentinel(lambda *a, **k: None, on_nan="explode")

    def test_step_time_regression_after_warmup(self):
        alerts = []
        r = StepTimeRegression(lambda n, a, step=None: alerts.append(a),
                               factor=2.0, warmup=3)
        for _ in range(5):
            assert r.observe(10.0) is False
        assert r.observe(15.0) is False      # under 2x
        assert r.observe(50.0) is True       # way over
        assert r.regressions == 1
        assert alerts[0]["step_ms"] == 50.0

    def test_regression_silent_during_warmup(self):
        r = StepTimeRegression(lambda *a, **k: None, factor=2.0, warmup=5)
        assert r.observe(1.0) is False
        assert r.observe(100.0) is False     # still warming up

    def test_stall_watchdog_fires_once_and_rearms(self):
        fired = []
        w = StallWatchdog(0.2, lambda age: fired.append(age),
                          check_interval_s=0.03)
        w.start()
        try:
            time.sleep(0.5)
            assert len(fired) == 1           # one fire per stall, no spam
            w.beat()                         # progress resumes
            time.sleep(0.5)
            assert len(fired) == 2           # re-armed, fired again
        finally:
            w.stop()
        assert not w.is_alive()

    def test_stall_watchdog_quiet_while_beating(self):
        fired = []
        w = StallWatchdog(0.3, lambda age: fired.append(age),
                          check_interval_s=0.03)
        w.start()
        try:
            for _ in range(10):
                time.sleep(0.05)
                w.beat()
            assert fired == []
        finally:
            w.stop()


# ---------------------------------------------------------------------------
# skew timeline
# ---------------------------------------------------------------------------

class TestSkewTimeline:
    def _table(self, computes):
        rows = []
        for r, c in enumerate(computes):
            rows.append([r, 10, c + 2.0, 2.0, c, 0])
        return np.array(rows, dtype=np.float64)

    def test_skew_and_slowest_rank_attribution(self):
        tl = CollectiveTimeline(rank=0)
        summary = tl.ingest_table(self._table([5.0, 90.0, 6.0, 5.5]))
        assert summary["skew_ms"] == pytest.approx(85.0)
        assert summary["slowest_rank"] == 1
        assert summary["flagged_ranks"] == [1]
        snap = counters_snapshot()
        assert snap["healthmon/healthmon.collective_skew_ms"] == \
            pytest.approx(85.0)
        assert snap["healthmon/healthmon.slowest_rank"] == 1
        assert snap["healthmon/healthmon.straggler_flags"] == 1
        assert tl.last_table[1]["compute_ewma_ms"] == 90.0

    def test_balanced_ranks_flag_nothing(self):
        tl = CollectiveTimeline(rank=0)
        summary = tl.ingest_table(self._table([5.0, 5.2, 5.1, 4.9]))
        assert summary["flagged_ranks"] == []
        assert summary["skew_ms"] < 1.0

    def test_ewma_decomposition(self):
        tl = CollectiveTimeline(rank=2, alpha=0.5)
        tl.record_step(1, 10.0, 4.0)
        tl.record_step(2, 20.0, 4.0)
        assert tl.step_ewma == pytest.approx(15.0)
        assert tl.coll_ewma == pytest.approx(4.0)
        assert tl.compute_ewma == pytest.approx(11.0)
        rec = tl.local_record(2, nan_alerts=3)
        assert list(rec[:2]) == [2, 2]
        assert rec[len(RECORD_FIELDS) - 1] == 3

    def test_single_process_exchange_degenerates(self):
        tl = CollectiveTimeline(rank=0)
        tl.record_step(1, 8.0, 1.0)
        summary = tl.exchange(1)
        assert summary["n_ranks"] == 1 and summary["skew_ms"] == 0.0


# ---------------------------------------------------------------------------
# HealthMonitor integration (single process)
# ---------------------------------------------------------------------------

def _train(n=3, hm_kwargs=None, lr=0.1):
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": lr})
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    x = nd.array(np.random.rand(4, 8).astype(np.float32))
    y = nd.array(np.random.randint(0, 4, 4))
    loss = None
    for _ in range(n):
        with mx.autograd.record():
            loss = L(net(x), y).mean()
        loss.backward()
        tr.step(4)
    return float(loss.asscalar())


class TestHealthMonitor:
    def test_trainer_hooks_feed_steps_events_and_phases(self, tmp_path):
        mon = hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0,
                        exchange_every=2)
        _train(n=4)
        assert mon.step == 4
        snap = counters_snapshot()
        assert snap["healthmon/healthmon.steps"] == 4
        assert snap["healthmon/healthmon.exchanges"] == 2
        hm.disable()
        recs = _read_events(mon.events.path)
        steps = [r for r in recs if r["name"] == "step"]
        assert len(steps) == 4
        assert {"allreduce_ms", "update_ms", "step_ms",
                "batch_size"} <= set(steps[-1]["args"])
        assert any(r["name"] == "skew_report" for r in recs)
        tc = _tool("trace_check")
        assert tc.check_events_jsonl(mon.events.path) == []

    def test_grad_norm_sentinel_gauge_and_nan(self, tmp_path):
        mon = hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0,
                        exchange_every=0, grad_norm_every=1)
        _train(n=2)
        snap = counters_snapshot()
        assert snap["healthmon/healthmon.grad_global_norm"] > 0
        assert "healthmon/healthmon.nan_alerts" not in snap
        # non-finite gradients (an inf scaled into the loss) must trip
        # the sentinel on the very next step
        net = gluon.nn.Dense(2)
        net.initialize(init=mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        x = nd.array(np.random.rand(2, 3).astype(np.float32))
        with mx.autograd.record():
            loss = (net(x) * float("inf")).mean()
        loss.backward()
        tr.step(2)
        snap = counters_snapshot()
        assert snap.get("healthmon/healthmon.nan_alerts", 0) >= 1
        assert mon.nan.alerts >= 1

    def test_observe_loss_alert_lands_in_all_three_surfaces(self,
                                                            tmp_path):
        diag.enable_flight_recorder(dump_on_crash=False,
                                    dump_dir=str(tmp_path))
        mon = hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0)
        assert hm.observe_loss(0.5) is False
        assert hm.observe_loss(float("nan"), step=11) is True
        assert counters_snapshot()[
            "healthmon/healthmon.nan_alerts"] == 1
        path = diag.dump_flight(reason="t")
        doc = json.load(open(path))
        assert any(e["kind"] == "alert" and
                   e["name"] == "healthmon.nan_loss"
                   for e in doc["events"])
        hm.disable()
        recs = _read_events(mon.events.path)
        alert = [r for r in recs if r["name"] == "healthmon.nan_loss"][0]
        assert alert["step"] == 11 and alert["kind"] == "alert"

    def test_stall_triggers_flight_dump_with_last_known_state(
            self, tmp_path):
        diag.enable_flight_recorder(dump_on_crash=False,
                                    dump_dir=str(tmp_path))
        mon = hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0.25,
                        stall_check_interval_s=0.05, exchange_every=1)
        _train(n=2)        # populates the timeline's last_table
        stall_path = os.path.join(str(tmp_path),
                                  f"mxtpu_stall_{os.getpid()}.json")

        def _dump_has_state():
            # the counter increments BEFORE the dump write, and a stall
            # can fire mid-compile (before last_table exists) under
            # suite load — so wait for the artifact that matters: a
            # written dump whose stall event carries the per-rank state
            # (each fire rewrites the same path with the full ring)
            if not os.path.exists(stall_path):
                return None
            try:
                d = json.load(open(stall_path))
            except ValueError:
                return None          # racing the atomic replace
            evs = [e for e in d["events"]
                   if e["name"] == "healthmon.stall"
                   and "last_known_ranks" in e.get("args", {})]
            return d if evs else None
        deadline = time.time() + 10.0
        doc = None
        while time.time() < deadline and doc is None:
            doc = _dump_has_state()
            time.sleep(0.05)
        assert doc is not None, "no stall dump with last-known state"
        assert counters_snapshot()[
            "healthmon/healthmon.stall_alerts"] >= 1
        assert doc["reason"] == "healthmon.stall"
        tc = _tool("trace_check")
        assert tc.check_flight(stall_path) == []

    def test_kvstore_collective_timing_feeds_timeline(self, tmp_path):
        mon = hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0,
                        exchange_every=0)
        kv = mx.kv.create("local")
        a = nd.ones((4, 4))
        kv.init("w", a)
        out = nd.zeros((4, 4))
        kv.pushpull("w", a, out=out)
        kv.pull("w", out=out)
        hm.disable()
        recs = _read_events(mon.events.path)
        colls = [r for r in recs if r["kind"] == "collective"]
        names = {r["name"] for r in colls}
        assert "kvstore.pushpull" in names and "kvstore.pull" in names
        assert all(r["args"]["ms"] >= 0 for r in colls)

    def test_mark_step_for_custom_loops(self, tmp_path):
        mon = hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0)
        for _ in range(3):
            hm.mark_step()
        assert mon.step == 3
        hm.mark_step(loss=float("nan"))
        assert counters_snapshot()[
            "healthmon/healthmon.nan_alerts"] == 1

    def test_numerics_unchanged_under_healthmon(self, tmp_path):
        np.random.seed(3)
        mx.random.seed(3)
        ref = _train(n=3)
        np.random.seed(3)
        mx.random.seed(3)
        hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0,
                  exchange_every=1, grad_norm_every=1)
        got = _train(n=3)
        assert got == pytest.approx(ref, rel=1e-6)

    def test_enable_disable_roundtrip_and_env(self, tmp_path,
                                              monkeypatch):
        assert not hm.enabled()
        hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0)
        assert hm.enabled() and hm.current() is not None
        hm.disable()
        assert not hm.enabled() and hm.current() is None
        monkeypatch.setenv("MXTPU_HEALTHMON", "1")
        monkeypatch.setenv("MXTPU_HM_DIR", str(tmp_path))
        monkeypatch.setenv("MXTPU_HM_STALL_S", "0")
        hm.enable_from_env()
        assert hm.enabled()

    def test_import_time_enable_does_not_materialize_backend(
            self, tmp_path):
        """MXTPU_HEALTHMON=1 arms at import, BEFORE mx.distributed.init
        — if enabling touched jax.process_index() the backend would
        materialize and every rank's later init() would fail. Run in a
        clean interpreter: this process's backend is long live."""
        import subprocess
        import sys as _sys
        code = (
            "import sys; sys.path.insert(0, %r)\n"
            "import incubator_mxnet_tpu as mx\n"
            "from jax._src import xla_bridge\n"
            "assert not xla_bridge._backends, xla_bridge._backends\n"
            "assert mx.healthmon.enabled()\n"
            "print('clean')\n"
            % os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        env = dict(os.environ, MXTPU_HEALTHMON="1",
                   MXTPU_HM_DIR=str(tmp_path))
        r = subprocess.run([_sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0 and "clean" in r.stdout, \
            r.stdout + r.stderr

    def test_rank_from_launcher_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MXTPU_PROCESS_ID", "3")
        mon = hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0)
        assert mon.rank == 3
        assert mon.events.path.endswith("events_rank3.jsonl")

    def test_reenable_starts_fresh_event_series(self, tmp_path):
        """Same path across enables must truncate, not append — an
        appended prior run breaks the monotonic-ts file contract."""
        p = str(tmp_path / "ev.jsonl")
        hm.enable(hm_dir=str(tmp_path), events_path=p, stall_timeout_s=0)
        hm.mark_step()
        hm.disable()
        n_first = len(_read_events(p))
        hm.enable(hm_dir=str(tmp_path), events_path=p, stall_timeout_s=0)
        hm.disable()
        recs = _read_events(p)
        assert len(recs) < n_first          # truncated, not appended
        assert all(r["name"] != "step" for r in recs)

    def test_run_id_env_wins(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MXTPU_RUN_ID", "the-run")
        mon = hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0)
        assert mon.run_id == "the-run"
        hm.disable()
        recs = _read_events(mon.events.path)
        assert all(r["run_id"] == "the-run" for r in recs)

    def test_failed_enable_reads_as_disabled(self, tmp_path):
        """A constructor failure must not leave enabled() True over a
        closed monitor (silently dead telemetry)."""
        hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0)
        with pytest.raises(OSError):
            # events_path pointing at a DIRECTORY: open() fails
            hm.enable(hm_dir=str(tmp_path), events_path=str(tmp_path),
                      stall_timeout_s=0)
        assert not hm.enabled() and hm.current() is None

    def test_exchange_failure_is_observable(self, tmp_path,
                                            monkeypatch):
        """An exchange that raises must leave a counter + event, not
        vanish — the operator debugging a misaligned cluster needs the
        breadcrumb."""
        mon = hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0,
                        exchange_every=1)
        monkeypatch.setattr(mon.timeline, "exchange",
                            lambda *a, **k: (_ for _ in ()).throw(
                                RuntimeError("gloo timeout")))
        mon.step_end()               # must not raise
        snap = counters_snapshot()
        assert snap["healthmon/healthmon.exchange_errors"] == 1
        hm.disable()
        recs = _read_events(mon.events.path)
        err = [r for r in recs
               if r["name"] == "healthmon.exchange_error"][0]
        assert "gloo timeout" in err["args"]["error"]
        tc = _tool("trace_check")
        assert tc.check_healthmon_kinds(
            {"healthmon/healthmon.exchange_errors": "counter"}) == []


# ---------------------------------------------------------------------------
# dist_async TCP health exchange (transport logic, single process)
# ---------------------------------------------------------------------------

class TestAsyncHealthExchange:
    def test_rank0_merges_records_locally(self):
        from incubator_mxnet_tpu.kvstore.async_ps import AsyncPSTransport
        t = AsyncPSTransport.__new__(AsyncPSTransport)   # no cluster
        t.rank = 0
        t._health = {1: [1.0, 5.0, 9.0, 2.0, 7.0, 0.0]}
        t._lock = threading.Lock()
        merged = t.health_exchange([0.0, 5.0, 3.0, 1.0, 2.0, 0.0])
        assert sorted(merged) == [0, 1]
        assert merged[0][4] == 2.0 and merged[1][4] == 7.0


# ---------------------------------------------------------------------------
# mxdiag merge
# ---------------------------------------------------------------------------

class TestMxdiagMerge:
    def _write_rank(self, tmp_path, rank, t0):
        p = str(tmp_path / f"events_rank{rank}.jsonl")
        with open(p, "w") as f:
            for i in range(3):
                f.write(json.dumps({
                    "schema": "mxtpu.events/1", "ts": t0 + i + rank * 0.5,
                    "run_id": "run-m", "rank": rank, "step": i,
                    "kind": "trainer", "name": "step"}) + "\n")
        return p

    def test_merge_interleaves_by_timestamp_with_rank_tags(self,
                                                           tmp_path):
        md = _tool("mxdiag")
        p0 = self._write_rank(tmp_path, 0, 100.0)
        p1 = self._write_rank(tmp_path, 1, 100.0)
        out = str(tmp_path / "merged.jsonl")
        merged = md.merge_timelines([p0, p1], out_path=out)
        assert [r["rank"] for r in merged] == [0, 1, 0, 1, 0, 1]
        ts = [r["ts"] for r in merged]
        assert ts == sorted(ts)
        tc = _tool("trace_check")
        assert tc.check_events_jsonl(out) == []
        recs = _read_events(out)
        assert all(r["run_id"] == "run-m" for r in recs)

    def test_merge_takes_flight_dumps_with_rank_from_env(self, tmp_path):
        md = _tool("mxdiag")
        flight = str(tmp_path / "flight.json")
        with open(flight, "w") as f:
            json.dump({"schema": "mxtpu.flight/1", "dumped_at": 101.0,
                       "reason": "t", "env": {"rank": 5}, "config": {},
                       "counters": {}, "counter_kinds": {},
                       "events": [{"ts": 100.2, "kind": "op",
                                   "name": "dot"}]}, f)
        p0 = self._write_rank(tmp_path, 0, 100.0)
        merged = md.merge_timelines([p0, flight])
        assert {r["rank"] for r in merged} == {0, 5}
        flight_rec = [r for r in merged if r["rank"] == 5][0]
        assert flight_rec["name"] == "dot"

    def test_merge_preserves_each_records_run_id(self, tmp_path):
        """Inputs from different runs must keep their own run_ids in the
        merged output — stamping one file's id over another's records
        would forge the correlation the id exists to enforce."""
        md = _tool("mxdiag")
        p0 = str(tmp_path / "a.jsonl")
        p1 = str(tmp_path / "b.jsonl")
        for p, rid in ((p0, "run-A"), (p1, "run-B")):
            with open(p, "w") as f:
                f.write(json.dumps({
                    "schema": "mxtpu.events/1", "ts": 100.0,
                    "run_id": rid, "rank": 0, "step": 1,
                    "kind": "t", "name": "n"}) + "\n")
        out = str(tmp_path / "m.jsonl")
        md.merge_timelines([p0, p1], out_path=out)
        rids = [r["run_id"] for r in _read_events(out)]
        assert sorted(rids) == ["run-A", "run-B"]

    def test_merge_flight_records_get_consensus_run_id(self, tmp_path):
        md = _tool("mxdiag")
        p0 = self._write_rank(tmp_path, 0, 100.0)      # run_id run-m
        flight = str(tmp_path / "flight.json")
        with open(flight, "w") as f:
            json.dump({"schema": "mxtpu.flight/1", "dumped_at": 101.0,
                       "reason": "t", "env": {"rank": 1}, "config": {},
                       "counters": {}, "counter_kinds": {},
                       "events": [{"ts": 100.5, "kind": "op",
                                   "name": "dot"}]}, f)
        out = str(tmp_path / "m.jsonl")
        md.merge_timelines([p0, flight], out_path=out)
        recs = _read_events(out)
        # single events-run consensus: the flight record inherits it
        assert all(r["run_id"] == "run-m" for r in recs)

    def test_merge_rejects_metrics_series(self, tmp_path):
        md = _tool("mxdiag")
        p = str(tmp_path / "metrics.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps({"ts": 1.0, "counters": {}}) + "\n")
        with pytest.raises(ValueError):
            md.merge_timelines([p])

    def test_merge_cli(self, tmp_path, capsys):
        md = _tool("mxdiag")
        p0 = self._write_rank(tmp_path, 0, 100.0)
        p1 = self._write_rank(tmp_path, 1, 100.0)
        out = str(tmp_path / "m.jsonl")
        assert md.main(["merge", p0, p1, "-o", out, "--tail", "4"]) == 0
        printed = capsys.readouterr().out
        assert "[rank 0]" in printed and "[rank 1]" in printed
        assert "2 rank(s)" in printed
        assert os.path.exists(out)
        assert md.main(["merge", str(tmp_path / "nope.jsonl")]) == 1
