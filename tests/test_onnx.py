"""ONNX export/import round-trip tests (reference python/mxnet/contrib/onnx/
tests: tests/python-pytest/onnx/test_models.py, test_node.py).

The pip `onnx` package is absent in this image, so validation is structural
(parse the emitted proto with independently generated bindings, check the
graph invariants the onnx checker enforces) plus numerical round-trip parity
through import_model.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.contrib import onnx as onnx_mxnet
from incubator_mxnet_tpu.contrib.onnx import P


def _params_for(net, data_shapes, skip=("data", "softmax_label", "label")):
    rng = np.random.RandomState(0)
    args, _, auxs = net.infer_shape(**data_shapes)
    params = {}
    for n, s in zip(net.list_arguments() + net.list_auxiliary_states(),
                    args + auxs):
        if n in skip or s is None:
            continue
        params[n] = mx.nd.array(rng.uniform(-0.5, 0.5, s).astype("float32"))
    return params


def _forward(net, feed, params):
    args = {k: v for k, v in params.items() if k in net.list_arguments()}
    args.update(feed)
    for n in net.list_arguments():
        if n not in args:  # unused labels etc.
            args[n] = mx.nd.array(np.zeros((1,), np.float32))
    auxs = {k: v for k, v in params.items()
            if k in net.list_auxiliary_states()}
    return net.bind(args=args, aux_states=auxs).forward(
        is_train=False)[0].asnumpy()


def _roundtrip(net, data_shape, atol=1e-5):
    params = _params_for(net, {"data": data_shape})
    buf = onnx_mxnet.export_model(net, params, [data_shape])
    sym2, arg2, aux2 = onnx_mxnet.import_model(buf)
    rng = np.random.RandomState(1)
    x = mx.nd.array(rng.uniform(-1, 1, data_shape).astype("float32"))
    y1 = _forward(net, {"data": x}, params)
    p2 = dict(arg2)
    p2.update(aux2)
    y2 = _forward(sym2, {"data": x}, p2)
    assert y1.shape == y2.shape
    np.testing.assert_allclose(y1, y2, atol=atol, rtol=1e-4)
    return buf


class TestProtoWire:
    def test_model_parses_and_validates(self):
        sym = mx.sym
        net = sym.FullyConnected(sym.Variable("data"), num_hidden=4,
                                 name="fc")
        params = _params_for(net, {"data": (2, 8)})
        buf = onnx_mxnet.export_model(net, params, [(2, 8)])
        m = P.ModelProto()
        m.ParseFromString(buf)
        assert m.ir_version == 8
        assert m.opset_import[0].version == 13
        # onnx-checker invariants: every node input is produced before use
        produced = {t.name for t in m.graph.initializer}
        produced |= {v.name for v in m.graph.input}
        for node in m.graph.node:
            for i in node.input:
                assert i in produced, i
            produced |= set(node.output)
        out_names = {v.name for v in m.graph.output}
        assert out_names <= produced

    def test_initializer_raw_data_little_endian(self):
        arr = np.arange(6, dtype=np.float32).reshape(2, 3)
        t = onnx_mxnet._np_to_tensor("w", arr)
        assert tuple(t.dims) == (2, 3)
        assert t.data_type == P.TensorProto.FLOAT
        back = np.frombuffer(t.raw_data, "<f4").reshape(2, 3)
        np.testing.assert_array_equal(back, arr)
        np.testing.assert_array_equal(onnx_mxnet._tensor_to_np(t), arr)

    def test_get_model_metadata(self):
        sym = mx.sym
        net = sym.FullyConnected(sym.Variable("data"), num_hidden=4,
                                 name="fc")
        buf = onnx_mxnet.export_model(net, _params_for(net, {"data": (2, 8)}),
                                      [(2, 8)])
        meta = onnx_mxnet.get_model_metadata(buf)
        assert meta["input_tensor_data"] == [("data", (2, 8))]
        assert meta["output_tensor_data"][0][0] == "fc"
        assert meta["output_tensor_data"][0][1] == (2, 4)


class TestRoundTrip:
    def test_lenet_style_cnn(self):
        sym = mx.sym
        data = sym.Variable("data")
        net = sym.Convolution(data, num_filter=8, kernel=(3, 3), pad=(1, 1),
                              name="c1")
        net = sym.BatchNorm(net, name="bn1")
        net = sym.Activation(net, act_type="relu", name="r1")
        net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                          pool_type="max", name="p1")
        net = sym.Convolution(net, num_filter=16, kernel=(3, 3),
                              no_bias=True, name="c2")
        net = sym.Activation(net, act_type="tanh", name="r2")
        net = sym.Pooling(net, global_pool=True, pool_type="avg", name="gap")
        net = sym.FullyConnected(sym.Flatten(net), num_hidden=10, name="fc1")
        net = sym.SoftmaxOutput(net, name="softmax")
        _roundtrip(net, (2, 3, 16, 16))

    def test_mlp_dropout_elemwise(self):
        sym = mx.sym
        data = sym.Variable("data")
        h = sym.FullyConnected(data, num_hidden=16, name="fc1")
        h = sym.Activation(h, act_type="sigmoid", name="a1")
        h = sym.Dropout(h, p=0.5, name="drop1")
        h2 = sym.FullyConnected(data, num_hidden=16, name="fc2",
                                flatten=False)
        net = (h + h2) * 2.0 - 1.5
        net = sym.clip(net, a_min=-1.0, a_max=1.0, name="clipped")
        _roundtrip(net, (4, 8))

    def test_resnet_style_block(self):
        sym = mx.sym
        data = sym.Variable("data")
        body = sym.Convolution(data, num_filter=4, kernel=(3, 3),
                               pad=(1, 1), no_bias=True, name="c1")
        body = sym.BatchNorm(body, fix_gamma=True, name="bn1")
        body = sym.Activation(body, act_type="relu", name="r1")
        body = sym.Convolution(body, num_filter=4, kernel=(3, 3),
                               pad=(1, 1), no_bias=True, name="c2")
        net = sym.broadcast_add(body, data, name="res")
        net = sym.LeakyReLU(net, slope=0.1, name="lr")
        _roundtrip(net, (2, 4, 8, 8))

    def test_deconv_concat_reshape(self):
        sym = mx.sym
        data = sym.Variable("data")
        up = sym.Deconvolution(data, num_filter=4, kernel=(2, 2),
                               stride=(2, 2), name="up")
        a = sym.slice_axis(up, axis=1, begin=0, end=2, name="sl")
        b = sym.slice_axis(up, axis=1, begin=2, end=None, name="sr")
        net = sym.Concat(a, b, dim=1, name="cat")
        net = sym.Reshape(net, shape=(0, -1), name="rs")
        _roundtrip(net, (2, 3, 4, 4))

    def test_add_n_sum_roundtrip(self):
        sym = mx.sym
        data = sym.Variable("data")
        a = sym.FullyConnected(data, num_hidden=4, name="fa")
        b = sym.FullyConnected(data, num_hidden=4, name="fb")
        net = sym.add_n(a, b, data, name="s3")
        _roundtrip(net, (2, 4))

    def test_shared_initializer_not_destroyed(self):
        # two Unsqueeze nodes sharing one axes initializer (legal ONNX,
        # common after constant dedup) must both import
        sym = mx.sym
        data = sym.Variable("data")
        net = sym.expand_dims(data, axis=1, name="u1") \
            + sym.expand_dims(data, axis=1, name="u2")
        buf = onnx_mxnet.export_model(net, {}, [(2, 3)])
        m = P.ModelProto()
        m.ParseFromString(buf)
        # force both Unsqueeze nodes onto ONE shared axes initializer
        axes_names = [n.input[1] for n in m.graph.node
                      if n.op_type == "Unsqueeze"]
        assert len(axes_names) == 2
        shared = axes_names[0]
        for n in m.graph.node:
            if n.op_type == "Unsqueeze":
                n.input[1] = shared
        keep = [t for t in m.graph.initializer
                if t.name != axes_names[1]]
        del m.graph.initializer[:]
        m.graph.initializer.extend(keep)
        sym2, arg2, aux2 = onnx_mxnet.import_model(m.SerializeToString())
        x = mx.nd.array(np.random.RandomState(0).uniform(
            -1, 1, (2, 3)).astype("float32"))
        y1 = _forward(net, {"data": x}, {})
        y2 = _forward(sym2, {"data": x}, {})
        np.testing.assert_allclose(y1, y2, atol=1e-6)

    def test_pad_roundtrip(self):
        sym = mx.sym
        data = sym.Variable("data")
        net = sym.Pad(data, mode="constant",
                      pad_width=(0, 0, 0, 0, 1, 2, 3, 0),
                      constant_value=1.5, name="pd")
        net = sym.Pad(net, mode="edge",
                      pad_width=(0, 0, 0, 0, 1, 1, 1, 1), name="pe")
        _roundtrip(net, (2, 3, 4, 5))

    def test_asymmetric_conv_pads_import(self):
        # a TF/Keras-style ONNX Conv with pads=[1,1,2,2] (begin != end)
        # must import via an inserted Pad node, numerically exact
        sym = mx.sym
        data = sym.Variable("data")
        net = sym.Convolution(data, num_filter=4, kernel=(3, 3),
                              pad=(1, 1), name="c")
        params = _params_for(net, {"data": (1, 3, 8, 8)})
        buf = onnx_mxnet.export_model(net, params, [(1, 3, 8, 8)])
        m = P.ModelProto()
        m.ParseFromString(buf)
        conv = next(n for n in m.graph.node if n.op_type == "Conv")
        for att in conv.attribute:
            if att.name == "pads":
                del att.ints[:]
                att.ints.extend([1, 1, 2, 2])  # asymmetric
        sym2, arg2, aux2 = onnx_mxnet.import_model(m.SerializeToString())
        x = mx.nd.array(np.random.RandomState(0).uniform(
            -1, 1, (1, 3, 8, 8)).astype("float32"))
        y2 = _forward(sym2, {"data": x}, arg2)
        # ground truth: jax conv with the exact asymmetric padding
        import jax
        w = params["c_weight"].asnumpy()
        b = params["c_bias"].asnumpy()
        ref = jax.lax.conv_general_dilated(
            x.asnumpy(), w, (1, 1), [(1, 2), (1, 2)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        ref = ref + b.reshape(1, -1, 1, 1)
        np.testing.assert_allclose(y2, np.asarray(ref), atol=1e-5,
                                   rtol=1e-4)

    def test_reductions_and_unary(self):
        sym = mx.sym
        data = sym.Variable("data")
        net = sym.exp(data) + sym.sqrt(sym.abs(data))
        net = sym.sum(net, axis=2, keepdims=True)
        net = sym.mean(net, axis=1)
        _roundtrip(net, (2, 3, 5))

    def test_embedding_softmax(self):
        sym = mx.sym
        data = sym.Variable("data")
        emb = sym.Embedding(data, input_dim=11, output_dim=6, name="emb")
        net = sym.softmax(sym.FullyConnected(emb, num_hidden=5, name="fc"),
                          axis=-1, name="sm")
        params = _params_for(net, {"data": (3, 4)})
        buf = onnx_mxnet.export_model(net, params, [(3, 4)])
        sym2, arg2, aux2 = onnx_mxnet.import_model(buf)
        idx = mx.nd.array(np.array([[1, 2, 3, 10], [0, 5, 6, 7],
                                    [9, 9, 1, 0]], np.float32))
        y1 = _forward(net, {"data": idx}, params)
        p2 = dict(arg2)
        p2.update(aux2)
        y2 = _forward(sym2, {"data": idx}, p2)
        np.testing.assert_allclose(y1, y2, atol=1e-5, rtol=1e-4)

    def test_multi_output_group(self):
        sym = mx.sym
        data = sym.Variable("data")
        a = sym.FullyConnected(data, num_hidden=3, name="heada")
        b = sym.FullyConnected(data, num_hidden=5, name="headb")
        net = mx.sym.Group([a, b])
        params = _params_for(net, {"data": (2, 4)})
        buf = onnx_mxnet.export_model(net, params, [(2, 4)])
        meta = onnx_mxnet.get_model_metadata(buf)
        assert [n for n, _ in meta["output_tensor_data"]] == ["heada",
                                                              "headb"]
        sym2, arg2, aux2 = onnx_mxnet.import_model(buf)
        assert len(sym2.list_outputs()) == 2

    def test_model_zoo_resnet18_exports(self):
        # the representative model-zoo CNN (NCHW build for ONNX), via the
        # Gluon->Symbol tracer (gluon/symbolize.py)
        from incubator_mxnet_tpu.models import get_model
        from incubator_mxnet_tpu.gluon.symbolize import trace_symbol
        net = get_model("resnet18_v1", classes=10, layout="NCHW")
        x = mx.nd.array(np.random.RandomState(0).uniform(
            0, 1, (1, 3, 32, 32)).astype("float32"))
        net.initialize()
        y_ref = net(x).asnumpy()
        ysym, arg_p, aux_p = trace_symbol(net)
        params = dict(arg_p)
        params.update(aux_p)
        buf = onnx_mxnet.export_model(ysym, params, [(1, 3, 32, 32)])
        sym2, arg2, aux2 = onnx_mxnet.import_model(buf)
        assert set(aux2) == set(aux_p)  # BN stats classified as aux
        p2 = dict(arg2)
        p2.update(aux2)
        y2 = _forward(sym2, {"data": x}, p2)
        np.testing.assert_allclose(y_ref, y2, atol=1e-4, rtol=1e-3)


class TestErrors:
    def test_nhwc_rejected(self):
        sym = mx.sym
        net = sym.Convolution(sym.Variable("data"), num_filter=4,
                              kernel=(3, 3), layout="NHWC", name="c")
        params = _params_for(net, {"data": (1, 8, 8, 3)})
        with pytest.raises(NotImplementedError, match="NCHW"):
            onnx_mxnet.export_model(net, params, [(1, 8, 8, 3)])

    def test_unsupported_op_message_lists_supported(self):
        sym = mx.sym
        net = sym.SequenceMask(sym.Variable("data")) \
            if hasattr(sym, "SequenceMask") else None
        if net is None:
            pytest.skip("no handy unsupported op")
        with pytest.raises(NotImplementedError, match="Supported"):
            onnx_mxnet.export_model(net, {}, [(2, 2)])


def test_dot_transpose_b_exports_correctly():
    """dot with transpose flags must emit a Transpose before MatMul, not
    silently drop the flag (the weight-tied LM head pattern)."""
    from incubator_mxnet_tpu import symbol as S
    rng = np.random.RandomState(0)
    w = mx.nd.array(rng.randn(6, 5).astype(np.float32))  # (vocab, units)
    x = mx.nd.array(rng.randn(3, 5).astype(np.float32))
    s = S.dot(S.Variable("data"), S.Variable("w"), transpose_b=True)
    buf = onnx_mxnet.export_model(s, {"w": w}, [(3, 5)])
    sym2, arg2, aux2 = onnx_mxnet.import_model(buf)
    out = sym2.bind(mx.cpu(), {**arg2, **aux2, "data": x}).forward()[0]
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy() @ w.asnumpy().T,
                               rtol=1e-5, atol=1e-6)


def test_dot_transpose_on_activation_input():
    # b is a graph input (not in params); its rank comes from the shape
    # pass, so the export succeeds and matches eager numerics
    from incubator_mxnet_tpu import symbol as S
    s = S.dot(S.Variable("a"), S.Variable("b"), transpose_b=True)
    buf = onnx_mxnet.export_model(s, {}, [(3, 5), (6, 5)])
    sym2, arg2, aux2 = onnx_mxnet.import_model(buf)
    rng = np.random.RandomState(0)
    a = mx.nd.array(rng.rand(3, 5).astype(np.float32))
    b = mx.nd.array(rng.rand(6, 5).astype(np.float32))
    out = sym2.bind(mx.cpu(), {**arg2, **aux2, "a": a, "b": b}).forward()[0]
    np.testing.assert_allclose(out.asnumpy(),
                               a.asnumpy() @ b.asnumpy().T,
                               rtol=1e-5, atol=1e-5)


def test_dot_transpose_shape_gap_raises():
    # no input_shape -> the shape pass never ran -> activation rank is a
    # genuine gap and the exporter must refuse with guidance
    from incubator_mxnet_tpu import symbol as S
    from incubator_mxnet_tpu.contrib.onnx import _Exporter
    import json as _json
    s = S.dot(S.Variable("a"), S.Variable("b"), transpose_b=True)
    ex = _Exporter(_json.loads(s.tojson()), {}, 13, np.float32,
                   input_shapes=None)
    with pytest.raises(NotImplementedError, match="transpose"):
        ex.run()


def test_consumed_label_input_uses_spare_shape_entry():
    # *_label names are skipped by the shape pass's label heuristic, but a
    # graph that really consumes one stays exportable via a spare
    # input_shape entry — and a missing spare raises with guidance
    from incubator_mxnet_tpu import symbol as S
    s = S.broadcast_add(S.Variable("x"), S.Variable("w_label"))
    buf = onnx_mxnet.export_model(s, {}, [(2, 3), (2, 3)])
    m = onnx_mxnet._load_model_proto(buf)
    shapes = {i.name: tuple(d.dim_value for d in i.type.tensor_type.shape.dim)
              for i in m.graph.input}
    assert shapes == {"x": (2, 3), "w_label": (2, 3)}
    with pytest.raises(ValueError, match="input_shape has"):
        onnx_mxnet.export_model(s, {}, [(2, 3)])


class TestTransformerONNX:
    """Transformer-family export: the shape-annotated exporter decomposes
    multihead_attention/LayerNorm/SliceChannel/slice_like/swapaxes into
    opset-13 ONNX; imported graphs reproduce eager numerics."""

    def _roundtrip(self, net, shape, seed=0):
        from incubator_mxnet_tpu.gluon.symbolize import trace_symbol
        net.initialize(init=mx.init.Xavier())
        x = mx.nd.array(np.random.RandomState(seed).randint(
            0, 29, shape).astype(np.float32))
        ref = net(x).asnumpy()
        sym, args, aux = trace_symbol(net, "data")
        buf = onnx_mxnet.export_model(sym, {**args, **aux}, [shape])
        sym2, arg2, aux2 = onnx_mxnet.import_model(buf)
        ex = sym2.bind(mx.cpu(), {**arg2, **aux2, "data": x})
        out = ex.forward()[0].asnumpy()
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)
        return sym2, arg2, aux2

    def test_transformer_lm_roundtrip(self):
        from incubator_mxnet_tpu.models import TransformerLM
        mx.random.seed(0)
        np.random.seed(0)
        m = TransformerLM(vocab_size=30, num_layers=2, units=32,
                          hidden_size=64, num_heads=4, max_length=16)
        self._roundtrip(m, (2, 8))

    def test_causality_survives_onnx(self):
        """The constant causal mask in the exported graph must actually
        mask: changing a future token cannot change past logits."""
        from incubator_mxnet_tpu.models import TransformerLM
        from incubator_mxnet_tpu.gluon.symbolize import trace_symbol
        mx.random.seed(1)
        np.random.seed(1)
        m = TransformerLM(vocab_size=20, num_layers=1, units=32,
                          hidden_size=64, num_heads=4, max_length=8)
        m.initialize(init=mx.init.Xavier())
        sym, args, aux = trace_symbol(m, "data")
        buf = onnx_mxnet.export_model(sym, {**args, **aux}, [(1, 6)])
        sym2, arg2, aux2 = onnx_mxnet.import_model(buf)
        a = np.random.RandomState(2).randint(0, 20, (1, 6)).astype(
            np.float32)
        b = a.copy()
        b[0, -1] = (b[0, -1] + 1) % 20
        outs = [sym2.bind(mx.cpu(), {**arg2, **aux2,
                                     "data": mx.nd.array(v)})
                .forward()[0].asnumpy() for v in (a, b)]
        np.testing.assert_allclose(outs[0][:, :-1], outs[1][:, :-1],
                                   atol=1e-5)
        assert np.abs(outs[0][:, -1] - outs[1][:, -1]).max() > 1e-4

    def test_bert_roundtrip(self):
        from incubator_mxnet_tpu.models.bert import BERTModel
        mx.random.seed(0)
        np.random.seed(0)
        m = BERTModel(num_layers=2, units=32, hidden_size=64, num_heads=4,
                      max_length=16, vocab_size=30, dropout=0.0,
                      use_pooler=False)
        self._roundtrip(m, (2, 10))

    def test_sym_attention_with_mask_roundtrip(self):
        from incubator_mxnet_tpu import symbol as S
        from incubator_mxnet_tpu import ops
        rng = np.random.RandomState(3)
        q = mx.nd.array(rng.randn(2, 6, 16).astype(np.float32))
        maskv = mx.nd.array((rng.rand(1, 1, 6, 6) > 0.4)
                            .astype(np.float32))
        s = S.multihead_attention(S.Variable("q"), S.Variable("q2"),
                                  S.Variable("q3"), num_heads=4,
                                  mask=S.Variable("mask"))
        buf = onnx_mxnet.export_model(
            s, {}, [(2, 6, 16), (2, 6, 16), (2, 6, 16), (1, 1, 6, 6)])
        sym2, arg2, aux2 = onnx_mxnet.import_model(buf)
        out = sym2.bind(mx.cpu(), {**arg2, **aux2, "q": q, "q2": q,
                                   "q3": q, "mask": maskv}).forward()[0]
        ref = ops.multihead_attention(q, q, q, 4, mask=maskv)
        np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                                   rtol=2e-5, atol=2e-5)


def test_reimported_attention_model_reexports():
    """import -> export cycle: MatMul imports as batch_dot, which must
    itself export (regression: the cycle used to die on 'batch_dot')."""
    from incubator_mxnet_tpu.models import TransformerLM
    from incubator_mxnet_tpu.gluon.symbolize import trace_symbol
    mx.random.seed(0)
    np.random.seed(0)
    m = TransformerLM(vocab_size=20, num_layers=1, units=32,
                      hidden_size=64, num_heads=4, max_length=8)
    m.initialize(init=mx.init.Xavier())
    sym, args, aux = trace_symbol(m, "data")
    buf = onnx_mxnet.export_model(sym, {**args, **aux}, [(2, 6)])
    sym2, arg2, aux2 = onnx_mxnet.import_model(buf)
    buf2 = onnx_mxnet.export_model(sym2, {**arg2, **aux2}, [(2, 6)])
    sym3, arg3, aux3 = onnx_mxnet.import_model(buf2)
    x = mx.nd.array(np.random.RandomState(1).randint(0, 20, (2, 6))
                    .astype(np.float32))
    ref = m(x).asnumpy()
    out = sym3.bind(mx.cpu(), {**arg3, **aux3, "data": x}).forward()[0]
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=2e-5, atol=2e-5)


def test_softmaxoutput_label_does_not_steal_shape():
    """Shape annotation must skip label variables: a graph with
    SoftmaxOutput (label input dropped at export) plus shape-dependent
    ops downstream still exports with the documented one-shape-per-data
    input."""
    sym = mx.sym
    data = sym.Variable("data")
    h = sym.swapaxes(sym.FullyConnected(data, num_hidden=6, flatten=False,
                                        name="fc"), a1=1, a2=2, name="sw")
    net = sym.SoftmaxOutput(sym.Flatten(h), name="softmax")
    params = _params_for(net, {"data": (2, 3, 4)})
    buf = onnx_mxnet.export_model(net, params, [(2, 3, 4)])
    assert buf


def test_dot_rank3_rhs_export_refuses():
    """dot with a rank>2 rhs contracts differently from MatMul — export
    must refuse, not silently change numerics."""
    from incubator_mxnet_tpu import symbol as S
    w = mx.nd.array(np.random.RandomState(0).randn(5, 5, 6)
                    .astype(np.float32))
    s = S.dot(S.Variable("data"), S.Variable("w"))
    with pytest.raises(NotImplementedError, match="batch_dot"):
        onnx_mxnet.export_model(s, {"w": w}, [(4, 5)])


def test_label_named_data_input_gets_shape():
    """Only exact 'label'/'*_label' names are treated as droppable label
    variables; a data input whose name merely CONTAINS 'label' must
    still receive its shape."""
    sym = mx.sym
    s = sym.swapaxes(sym.Variable("label_weights"), a1=1, a2=2)
    buf = onnx_mxnet.export_model(s, {}, [(2, 3, 4)])
    assert buf


def test_too_few_input_shapes_is_clear_error():
    from incubator_mxnet_tpu import symbol as S
    s = S.multihead_attention(S.Variable("q"), S.Variable("k"),
                              S.Variable("v"), num_heads=2)
    with pytest.raises(ValueError, match="data inputs"):
        onnx_mxnet.export_model(s, {}, [(2, 4, 8), (2, 4, 8)])
