"""Module API tests (mirrors reference tests/python/unittest/test_module.py)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import symbol as sym
from incubator_mxnet_tpu.io import NDArrayIter
from incubator_mxnet_tpu.module import Module, load_checkpoint


def _mlp_symbol(num_hidden=32, classes=4):
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=num_hidden, name="fc1")
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, num_hidden=classes, name="fc2")
    return sym.SoftmaxOutput(fc2, sym.Variable("softmax_label"),
                             normalization="batch", name="softmax")


def _toy_data(n=256, dim=10, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim, classes)
    x = rng.randn(n, dim).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.randn(n, classes), axis=1).astype(np.float32)
    return x, y


def test_module_fit_converges():
    x, y = _toy_data()
    train = NDArrayIter(x, y, batch_size=32, shuffle=True)
    mod = Module(_mlp_symbol(), data_names=("data",),
                 label_names=("softmax_label",))
    mod.fit(train, num_epoch=20, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            initializer=mx.init.Xavier())
    score = mod.score(NDArrayIter(x, y, batch_size=32), "acc")
    assert dict(score)["accuracy"] > 0.9, score


def test_module_predict_shape():
    x, y = _toy_data(n=100)
    it = NDArrayIter(x, y, batch_size=32)  # 100 % 32 != 0 → pad path
    mod = Module(_mlp_symbol())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    out = mod.predict(it)
    assert out.shape == (100, 4)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1), np.ones(100),
                               rtol=1e-5)


def test_module_checkpoint_roundtrip(tmp_path):
    x, y = _toy_data(n=64)
    it = NDArrayIter(x, y, batch_size=32)
    mod = Module(_mlp_symbol())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "toy")
    mod.save_checkpoint(prefix, 3)
    symbol, arg_params, aux_params = load_checkpoint(prefix, 3)
    assert set(arg_params) == {"fc1_weight", "fc1_bias", "fc2_weight",
                               "fc2_bias"}
    mod2 = Module(symbol)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params(arg_params=arg_params, aux_params=aux_params)
    out1 = mod.predict(it).asnumpy()
    out2 = mod2.predict(it).asnumpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-5)


def test_module_input_grads():
    xsym = sym.Variable("data")
    out = sym.LinearRegressionOutput(
        sym.FullyConnected(xsym, num_hidden=1, name="fc"),
        sym.Variable("softmax_label"))
    mod = Module(out)
    mod.bind(data_shapes=[("data", (4, 3))],
             label_shapes=[("softmax_label", (4, 1))], inputs_need_grad=True)
    mod.init_params(initializer=mx.init.One())
    from incubator_mxnet_tpu.io import DataBatch
    import incubator_mxnet_tpu.ndarray as nd
    batch = DataBatch([nd.ones((4, 3))], [nd.zeros((4, 1))])
    mod.forward(batch, is_train=True)
    mod.backward()
    g = mod.get_input_grads()[0]
    assert g is not None and g.shape == (4, 3)
    # pred = 3 (ones weight, zero bias — bias params always zero-init);
    # grad wrt x = (pred - label) * W = 3
    np.testing.assert_allclose(g.asnumpy(), np.full((4, 3), 3.0), rtol=1e-5)


def test_module_batchnorm_aux():
    data = sym.Variable("data")
    net = sym.BatchNorm(sym.FullyConnected(data, num_hidden=8, name="fc"),
                        name="bn")
    net = sym.SoftmaxOutput(sym.FullyConnected(net, num_hidden=2, name="out"),
                            sym.Variable("softmax_label"))
    x, y = _toy_data(n=64, dim=6, classes=2)
    it = NDArrayIter(x, y, batch_size=16)
    mod = Module(net)
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.init.Xavier())
    _, aux = mod.get_params()
    assert not np.allclose(aux["bn_moving_mean"].asnumpy(), 0.0)


def test_module_load_resumes_weights(tmp_path):
    # regression: Module.load + fit must keep checkpoint weights
    x, y = _toy_data(n=64)
    it = NDArrayIter(x, y, batch_size=32)
    mod = Module(_mlp_symbol())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "resume")
    mod.save_checkpoint(prefix, 1)
    mod2 = Module.load(prefix, 1)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params()  # must pick up the preloaded checkpoint, not re-init
    w1 = mod.get_params()[0]["fc1_weight"].asnumpy()
    w2 = mod2.get_params()[0]["fc1_weight"].asnumpy()
    np.testing.assert_allclose(w1, w2)
