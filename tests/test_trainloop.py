"""Whole-loop train executor (mxtpu.trainloop) + satellites:

* run_k per-micro-step lr: bit-exact vs a sequential loop with constant
  lr, within-tolerance with a decaying schedule (the k-granularity
  scheduler-coarsening regression test);
* in-program lr (lr_scheduler.as_jax closed forms) matches the host
  schedulers step-for-step, including warmup and mid-run handoff;
* TrainLoop: chunk resolution (arg > Trainer.loop_chunk > env), fit
  drives the prefetcher, losses decrease, donation safety after chunks;
* DevicePrefetcher: ordering, chunk stacking, drain/early-stop without
  leaking the device buffer, io.* counters;
* Pallas selection (ops/select) parity on CPU (interpret-mode kernels):
  conv_bn_relu / scale_shift_act / BatchNormReLU, the MXTPU_PALLAS=0
  escape hatch, and the capture log;
* persistent-compile-cache guard (runtime/cache_guard): pass and trip
  paths.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import TrainLoop, gluon, nd
from incubator_mxnet_tpu import profiler as prof
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.io import DevicePrefetcher
from incubator_mxnet_tpu.parallel import FusedTrainStep


def _net(seed=0):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    return net


L = gluon.loss.SoftmaxCrossEntropyLoss()


def _data(seed=0, batch=8, n=1):
    rng = np.random.RandomState(seed)
    out = [(nd.array(rng.randn(batch, 8).astype(np.float32)),
            nd.array(rng.randint(0, 4, batch))) for _ in range(n)]
    return out[0] if n == 1 else out


def _stacked(k, seed=0, batch=8):
    pairs = _data(seed=seed, batch=batch, n=k)
    xs = jnp.stack([p[0]._data for p in pairs])
    ys = jnp.stack([p[1]._data for p in pairs])
    return xs, ys


# ---------------------------------------------------------------------------
# satellite: run_k scheduler coarsening fix
# ---------------------------------------------------------------------------

class TestRunKScheduleExact:
    def test_constant_lr_bit_exact(self):
        s1 = FusedTrainStep(_net(), L, mx.optimizer.create(
            "sgd", learning_rate=0.1, momentum=0.9))
        xs, ys = _stacked(4)
        seq = np.asarray([float(s1(nd.array(np.asarray(xs[i])),
                                   nd.array(np.asarray(ys[i]))))
                          for i in range(4)], np.float32)
        s2 = FusedTrainStep(_net(), L, mx.optimizer.create(
            "sgd", learning_rate=0.1, momentum=0.9))
        kl = s2.run_k(xs, ys).asnumpy().astype(np.float32)
        assert np.array_equal(kl, seq), (kl, seq)
        assert s2.optimizer.num_update == 4

    def test_decaying_schedule_matches_sequential(self):
        def mk():
            return mx.optimizer.create(
                "sgd", learning_rate=0.2,
                lr_scheduler=mx.lr_scheduler.FactorScheduler(
                    step=2, factor=0.5, base_lr=0.2))
        s1 = FusedTrainStep(_net(), L, mk())
        xs, ys = _stacked(6)
        seq = [float(s1(nd.array(np.asarray(xs[i])),
                        nd.array(np.asarray(ys[i])))) for i in range(6)]
        s2 = FusedTrainStep(_net(), L, mk())
        kl = s2.run_k(xs, ys).asnumpy()
        np.testing.assert_allclose(kl, seq, rtol=1e-6)
        # the scheduler advanced exactly like the sequential loop
        assert s2.optimizer.learning_rate == s1.optimizer.learning_rate

    def test_mixing_run_k_and_single_steps_keeps_schedule(self):
        def mk():
            return mx.optimizer.create(
                "sgd", learning_rate=0.2,
                lr_scheduler=mx.lr_scheduler.FactorScheduler(
                    step=3, factor=0.1, base_lr=0.2))
        s1 = FusedTrainStep(_net(), L, mk())
        xs, ys = _stacked(4)
        seq = [float(s1(nd.array(np.asarray(xs[i])),
                        nd.array(np.asarray(ys[i])))) for i in range(4)]
        x4, y4 = _data(seed=77)
        seq.append(float(s1(x4, y4)))
        s2 = FusedTrainStep(_net(), L, mk())
        got = list(s2.run_k(xs, ys).asnumpy())
        got.append(float(s2(x4, y4)))
        np.testing.assert_allclose(got, seq, rtol=1e-6)


class TestAsJaxSchedules:
    @pytest.mark.parametrize("mk", [
        lambda: mx.lr_scheduler.FactorScheduler(step=5, factor=0.5,
                                                base_lr=0.4),
        lambda: mx.lr_scheduler.FactorScheduler(step=3, factor=0.1,
                                                base_lr=1.0,
                                                stop_factor_lr=1e-3),
        lambda: mx.lr_scheduler.FactorScheduler(step=4, factor=0.7,
                                                base_lr=0.2, warmup_steps=6,
                                                warmup_begin_lr=0.01),
        lambda: mx.lr_scheduler.MultiFactorScheduler(step=[4, 9, 15],
                                                     factor=0.3,
                                                     base_lr=0.5),
        lambda: mx.lr_scheduler.MultiFactorScheduler(step=[3, 7], factor=0.5,
                                                     base_lr=0.5,
                                                     warmup_steps=2),
        lambda: mx.lr_scheduler.PolyScheduler(max_update=20, base_lr=0.3,
                                              pwr=2, final_lr=0.01),
        lambda: mx.lr_scheduler.CosineScheduler(max_update=25, base_lr=0.3,
                                                final_lr=0.02,
                                                warmup_steps=5),
        lambda: mx.lr_scheduler.LinearScheduler(max_update=18, base_lr=0.25),
    ])
    def test_matches_host(self, mk):
        host, traced = mk(), mk()
        fn = traced.as_jax()
        hv = [float(host(t)) for t in range(1, 30)]
        jv = [float(fn(t)) for t in range(1, 30)]
        np.testing.assert_allclose(jv, hv, rtol=1e-6, atol=1e-7)

    def test_midrun_handoff_stateful(self):
        h = mx.lr_scheduler.FactorScheduler(step=4, factor=0.5, base_lr=0.8)
        for t in range(1, 11):
            h(t)
        fn = h.as_jax()                 # closed form FROM current state
        ref = mx.lr_scheduler.FactorScheduler(step=4, factor=0.5,
                                              base_lr=0.8)
        want = [ref(t) for t in range(1, 25)][10:]
        got = [float(fn(t)) for t in range(11, 25)]
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_custom_scheduler_has_no_closed_form(self):
        class Weird(mx.lr_scheduler.LRScheduler):
            def __call__(self, num_update):
                return 0.1 / (1 + num_update % 7)
        assert Weird().as_jax() is None
        # ...and the executor still matches sequentially (host lr table)
        def mk():
            return mx.optimizer.create("sgd", learning_rate=0.1,
                                       lr_scheduler=Weird())
        s1 = FusedTrainStep(_net(), L, mk())
        xs, ys = _stacked(5)
        seq = [float(s1(nd.array(np.asarray(xs[i])),
                        nd.array(np.asarray(ys[i])))) for i in range(5)]
        s2 = FusedTrainStep(_net(), L, mk(), schedule_in_program=True)
        kl = s2.run_k(xs, ys).asnumpy()
        np.testing.assert_allclose(kl, seq, rtol=1e-6)
        assert s2._lr_program is None   # fell back to the host table


# ---------------------------------------------------------------------------
# TrainLoop executor
# ---------------------------------------------------------------------------

class TestTrainLoop:
    def test_bit_exact_vs_sequential_fused_path(self):
        s1 = FusedTrainStep(_net(), L, mx.optimizer.create(
            "sgd", learning_rate=0.1))
        xs, ys = _stacked(4)
        seq = np.asarray([float(s1(nd.array(np.asarray(xs[i])),
                                   nd.array(np.asarray(ys[i]))))
                          for i in range(4)], np.float32)
        loop = TrainLoop(_net(), L, mx.optimizer.create(
            "sgd", learning_rate=0.1), chunk=4)
        got = loop.run_chunk(xs, ys).asnumpy().astype(np.float32)
        assert np.array_equal(got, seq)

    def test_in_program_lr_matches_sequential(self):
        def mk():
            return mx.optimizer.create(
                "sgd", learning_rate=0.3,
                lr_scheduler=mx.lr_scheduler.CosineScheduler(
                    max_update=12, base_lr=0.3, final_lr=0.01))
        s1 = FusedTrainStep(_net(), L, mk())
        xs, ys = _stacked(8)
        seq = [float(s1(nd.array(np.asarray(xs[i])),
                        nd.array(np.asarray(ys[i])))) for i in range(8)]
        loop = TrainLoop(_net(), L, mk(), chunk=8)
        got = loop.run_chunk(xs, ys).asnumpy()
        assert loop.in_program_lr          # the schedule compiled on device
        np.testing.assert_allclose(got, seq, rtol=1e-5, atol=1e-6)

    def test_chunk_resolution(self, monkeypatch):
        net = _net()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, loop_chunk=6)
        assert TrainLoop(net, L, tr).chunk == 6
        assert TrainLoop(net, L, tr, chunk=3).chunk == 3
        monkeypatch.setenv("MXTPU_LOOP_CHUNK", "5")
        tr2 = gluon.Trainer(_net().collect_params(), "sgd",
                            {"learning_rate": 0.1})
        assert tr2.loop_chunk == 5
        assert TrainLoop(net, L, mx.optimizer.create("sgd")).chunk == 5
        monkeypatch.delenv("MXTPU_LOOP_CHUNK")
        assert TrainLoop(net, L, mx.optimizer.create("sgd")).chunk == 4

    def test_fit_trains_and_counts(self):
        loop = TrainLoop(_net(), L, mx.optimizer.create(
            "sgd", learning_rate=0.5), chunk=4)
        data = _data(seed=3, n=4) * 10          # 40 batches, recycled shapes
        losses = loop.fit(data, steps=40)
        assert losses.shape == (40,)
        assert losses[-4:].mean() < losses[:4].mean()
        assert loop.num_update == 40
        c = prof.counters()
        assert c["io/io.batches_prefetched"] >= 40
        assert "io/io.wait_ms" in c
        assert c["trainloop/trainloop.steps"] >= 40
        assert c["mxtpu/trainer.dispatches_per_step"] == 0.25

    def test_fit_epochs_drops_partial_chunk(self):
        loop = TrainLoop(_net(), L, mx.optimizer.create("sgd"), chunk=4)
        losses = loop.fit(_data(seed=3, n=10), epochs=1)  # 10 → 2 chunks
        assert losses.shape == (8,)

    def test_fit_epochs_resets_data_iter_each_epoch(self):
        """A DataIter source must rewind at every epoch start — epoch 2+
        of an exhausted iterator would otherwise silently contribute
        nothing."""
        import incubator_mxnet_tpu.io as mio
        rng = np.random.RandomState(0)
        X = rng.randn(32, 8).astype(np.float32)
        Y = rng.randint(0, 4, 32).astype(np.float32)
        it = mio.NDArrayIter(X, Y, batch_size=8)    # 4 batches/epoch
        loop = TrainLoop(_net(), L, mx.optimizer.create("sgd"), chunk=4)
        losses = loop.fit(it, epochs=3)
        assert losses.shape == (12,)                # 1 chunk x 3 epochs

    def test_fit_steps_exhausted_source_raises_clearly(self):
        loop = TrainLoop(_net(), L, mx.optimizer.create("sgd"), chunk=4)
        gen = (b for b in _data(seed=3, n=8))       # 8 batches, no rewind
        with pytest.raises(ValueError, match="exhausted after 8 of 16"):
            loop.fit(gen, steps=16)

    def test_fit_labelless_source_rejected(self):
        loop = TrainLoop(_net(), L, mx.optimizer.create("sgd"), chunk=2)
        bare = [np.zeros((4, 8), np.float32) for _ in range(4)]
        with pytest.raises(ValueError, match="labeled batches"):
            loop.fit(bare, steps=2)

    def test_fit_epochs_oneshot_iterator_raises(self):
        loop = TrainLoop(_net(), L, mx.optimizer.create("sgd"), chunk=4)
        gen = (b for b in _data(seed=3, n=8))       # can't rewind
        with pytest.raises(ValueError, match="epoch 2 produced no"):
            loop.fit(gen, epochs=2)

    def test_donation_safety_between_chunks(self):
        """Params stay readable between chunks (rebound to the donated
        program's outputs), and a reader between chunks doesn't poison
        the next dispatch."""
        net = _net()
        loop = TrainLoop(net, L, mx.optimizer.create(
            "sgd", learning_rate=0.1), chunk=3)
        xs, ys = _stacked(3)
        loop.run_chunk(xs, ys)
        snap1 = {k: v.data().asnumpy().copy()
                 for k, v in net.collect_params().items()}
        loop.run_chunk(xs, ys)
        snap2 = {k: v.data().asnumpy().copy()
                 for k, v in net.collect_params().items()}
        changed = any(not np.array_equal(snap1[k], snap2[k]) for k in snap1)
        assert changed, "second chunk did not update parameters"
        # and the params still drive an eager forward
        x, _ = _data()
        assert np.isfinite(net(x).asnumpy()).all()

    def test_steps_smaller_than_chunk_rejected(self):
        loop = TrainLoop(_net(), L, mx.optimizer.create("sgd"), chunk=8)
        with pytest.raises(ValueError, match="less than one chunk"):
            loop.fit(_data(n=4), steps=4)

    @pytest.mark.parametrize("policy", ["dots", "nothing", "everything"])
    def test_remat_policies_match_plain(self, policy):
        x, y = _data()
        s1 = FusedTrainStep(_net(), L, mx.optimizer.create(
            "sgd", learning_rate=0.1))
        a = float(s1(x, y))
        s2 = FusedTrainStep(_net(), L, mx.optimizer.create(
            "sgd", learning_rate=0.1), remat=True, remat_policy=policy)
        np.testing.assert_allclose(float(s2(x, y)), a, rtol=1e-6)

    def test_bad_remat_policy_raises(self):
        step = FusedTrainStep(_net(), L, "sgd", remat=True,
                              remat_policy="bogus")
        with pytest.raises(ValueError, match="remat_policy"):
            step(*_data())


# ---------------------------------------------------------------------------
# DevicePrefetcher
# ---------------------------------------------------------------------------

class TestDevicePrefetcher:
    def test_order_and_values(self):
        data = _data(seed=5, n=6)
        with DevicePrefetcher(data, depth=2) as pf:
            got = list(pf)
        assert len(got) == 6
        for (x, y), (gx, gy) in zip(data, got):
            np.testing.assert_array_equal(x.asnumpy(), np.asarray(gx))
            np.testing.assert_array_equal(y.asnumpy(), np.asarray(gy))

    def test_chunk_stacking(self):
        data = _data(seed=5, n=7)
        with DevicePrefetcher(data, depth=2, chunk=3) as pf:
            got = list(pf)
        assert len(got) == 2                  # 7 → two chunks, tail dropped
        assert got[0][0].shape == (3, 8, 8)
        np.testing.assert_array_equal(
            np.asarray(got[1][0])[0], data[3][0].asnumpy())

    def test_early_stop_drains_without_leak(self):
        data = _data(seed=5, n=50)
        pf = DevicePrefetcher(data, depth=3)
        next(pf)                              # consume one, buffer fills
        pf.close()                            # early stop mid-stream
        assert not pf._thread.is_alive()
        assert pf._buf.qsize() == 0           # no device refs parked
        with pytest.raises(StopIteration):
            next(pf)
        pf.close()                            # idempotent

    def test_source_error_surfaces_at_next(self):
        def bad():
            yield _data()
            raise RuntimeError("decode exploded")
        pf = DevicePrefetcher(bad(), depth=2)
        next(pf)
        with pytest.raises(RuntimeError, match="decode exploded"):
            next(pf)
        pf.close()

    def test_cycle_restarts_data_iter(self):
        import incubator_mxnet_tpu.io as mio
        X = np.random.RandomState(0).rand(8, 4).astype(np.float32)
        it = mio.NDArrayIter(X, X[:, 0], batch_size=4)
        with DevicePrefetcher(it, depth=2, cycle=True) as pf:
            got = [next(pf) for _ in range(5)]    # 2 per epoch, cycles
        assert len(got) == 5

    def test_close_abandons_worker_blocked_in_source(self, monkeypatch):
        """A worker parked inside the source's next() can't be
        interrupted; close() must return after its deadline instead of
        hanging the training process."""
        import threading
        import time as _time
        from incubator_mxnet_tpu.io import prefetch as _pfmod
        monkeypatch.setattr(_pfmod, "_CLOSE_DEADLINE_S", 0.3)
        release = threading.Event()

        def blocking():
            yield _data(seed=0)
            release.wait(30)          # park until the test releases us

        pf = DevicePrefetcher(blocking(), depth=2)
        next(pf)
        t0 = _time.monotonic()
        pf.close()                    # worker is stuck inside wait(30)
        assert _time.monotonic() - t0 < 2.0
        assert pf._buf.qsize() == 0
        release.set()

    def test_mixed_labels_in_chunk_rejected(self):
        x = np.zeros((4, 8), np.float32)
        src = [(x, np.zeros(4, np.float32)), (x, None)]
        pf = DevicePrefetcher(src, depth=2, chunk=2)
        with pytest.raises(ValueError, match="mixed labeled"):
            next(pf)
        pf.close()

    def test_wait_counter_advances_on_slow_source(self):
        import time as _time
        base = prof.counters().get("io/io.wait_ms", 0)

        def slow():
            for i in range(3):
                _time.sleep(0.05)
                yield _data(seed=i)
        with DevicePrefetcher(slow(), depth=2) as pf:
            list(pf)
        assert prof.counters()["io/io.wait_ms"] > base


# ---------------------------------------------------------------------------
# Pallas selection + interpret-mode kernel parity (CPU)
# ---------------------------------------------------------------------------

@pytest.fixture
def force_pallas(monkeypatch):
    monkeypatch.setenv("MXTPU_PALLAS", "force")
    yield


class TestPallasSelection:
    def test_escape_hatch_master_switch(self, monkeypatch):
        from incubator_mxnet_tpu.ops import pallas as P
        monkeypatch.setenv("MXTPU_PALLAS", "0")
        assert not P.enabled()
        monkeypatch.setenv("MXTPU_PALLAS", "force")
        assert P.enabled()
        # the natural MXTPU_*=1 spelling is explicit-on, not a no-op
        # (off-TPU: interpret-mode kernels)
        monkeypatch.setenv("MXTPU_PALLAS", "1")
        assert P.enabled() or P.is_tpu()
        monkeypatch.delenv("MXTPU_PALLAS")
        monkeypatch.setenv("MXTPU_NO_PALLAS", "1")
        assert not P.enabled()

    def test_selection_counters_and_capture(self, force_pallas):
        from incubator_mxnet_tpu.ops import select as S
        x = jnp.ones((4, 32))
        g = jnp.ones((32,))
        with S.capture() as log:
            assert S.layer_norm(x, g, -1)
            assert not S.flash_attention(mask=jnp.ones((4, 4)),
                                         dropout_active=False)
        assert log == [
            {"kernel": "layer_norm", "selected": True, "reason": "ok"},
            {"kernel": "flash_attention", "selected": False,
             "reason": "explicit mask"}]
        c = prof.counters()
        assert c["ops/pallas.selected.layer_norm"] >= 1
        assert c["ops/pallas.rejected.flash_attention"] >= 1

    def test_scale_shift_act_parity_fwd_bwd(self, force_pallas):
        from incubator_mxnet_tpu.ops import pallas as P
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(6, 7, 32).astype(np.float32))
        s = jnp.asarray(rng.rand(32).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(32).astype(np.float32))

        def ref(x, s, b):
            return jnp.maximum(x * s + b, 0.0)

        got, vg = jax.vjp(lambda *a: P.scale_shift_act(*a, act="relu"),
                          x, s, b)
        want, vr = jax.vjp(ref, x, s, b)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)
        ct = jnp.ones_like(want)
        for g1, g2, nm in zip(vg(ct), vr(ct), "xsb"):
            np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                       atol=1e-5, err_msg=nm)

    @pytest.mark.parametrize("geometry", ["1x1", "3x3"])
    def test_conv_bn_relu_parity(self, force_pallas, geometry):
        from incubator_mxnet_tpu.ops import pallas as P, _raw
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 5, 5, 16).astype(np.float32))
        kh = 1 if geometry == "1x1" else 3
        pad = (0, 0) if geometry == "1x1" else (1, 1)
        w = jnp.asarray(rng.randn(kh, kh, 16, 24).astype(np.float32) * 0.2)
        g = jnp.asarray(rng.rand(24).astype(np.float32) + 0.5)
        be = jnp.asarray(rng.randn(24).astype(np.float32))
        mm = jnp.asarray(rng.randn(24).astype(np.float32) * 0.1)
        mv = jnp.asarray(rng.rand(24).astype(np.float32) + 0.5)

        def ref(x, w):
            y = jax.lax.conv_general_dilated(
                x, w, (1, 1), [(p, p) for p in pad],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            yy, _, _ = _raw.batch_norm(y, g, be, mm, mv, axis=-1,
                                       training=False)
            return jnp.maximum(yy, 0)

        got, vg = jax.vjp(
            lambda x, w: P.conv_bn_relu(x, w, g, be, mm, mv, pad=pad), x, w)
        want, vr = jax.vjp(ref, x, w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5)
        ct = jnp.ones_like(want)
        for g1, g2, nm in zip(vg(ct), vr(ct), ["x", "w"]):
            np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                       atol=2e-4, rtol=1e-4, err_msg=nm)

    def test_batch_norm_relu_block_fused_parity(self, force_pallas):
        """nn.BatchNormReLU (fused epilogue) vs nn.BatchNorm + relu —
        training AND inference mode, channels-last."""
        def mk(cls):
            mx.random.seed(0)
            np.random.seed(0)
            b = cls(axis=-1, in_channels=16)
            b.initialize()
            return b
        x = nd.array(np.random.RandomState(1)
                     .randn(4, 6, 16).astype(np.float32))
        for train in (True, False):
            fused, plain = mk(nn.BatchNormReLU), mk(nn.BatchNorm)
            with mx.autograd.record(train_mode=train):
                yf = fused(x)
                yp = plain(x).relu()
            np.testing.assert_allclose(yf.asnumpy(), yp.asnumpy(),
                                       atol=1e-5,
                                       err_msg=f"train={train}")
            np.testing.assert_allclose(
                fused.running_mean.data().asnumpy(),
                plain.running_mean.data().asnumpy(), atol=1e-6)

    def test_unsupported_act_falls_back_to_xla(self, force_pallas):
        """Activations outside the epilogue kernel's table (relu/relu6)
        must route to the XLA chain, not raise from the pallas kernel."""
        from incubator_mxnet_tpu.ops import _raw, select as S
        x = jnp.ones((4, 32))
        assert not S.scale_shift_act(x, -1, act="sigmoid")
        y, _, _ = _raw.batch_norm(
            x, jnp.ones(32), jnp.zeros(32), jnp.zeros(32), jnp.ones(32),
            axis=-1, training=False, act="sigmoid")
        np.testing.assert_allclose(np.asarray(y),
                                   1 / (1 + np.exp(-1.0)), atol=1e-6)

    def test_conv_bn_relu_op_training_fallback(self, force_pallas):
        """The NDArray-level ConvBNReLU op in training mode falls back to
        the exact conv→BN(batch stats)→relu chain."""
        from incubator_mxnet_tpu import ops
        rng = np.random.RandomState(0)
        x = nd.array(rng.randn(2, 5, 5, 8).astype(np.float32))
        w = nd.array(rng.randn(1, 1, 8, 12).astype(np.float32))
        g = nd.array(np.ones(12, np.float32))
        b = nd.array(np.zeros(12, np.float32))
        mm = nd.array(np.zeros(12, np.float32))
        mv = nd.array(np.ones(12, np.float32))
        with mx.autograd.record():
            y = ops.ConvBNReLU(x, w, g, b, mm, mv)
        from incubator_mxnet_tpu.ops import _raw
        ref = jax.lax.conv_general_dilated(
            x._data, w._data, (1, 1), [(0, 0), (0, 0)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        ry, _, _ = _raw.batch_norm(ref, g._data, b._data, mm._data,
                                   mv._data, axis=-1, training=True)
        np.testing.assert_allclose(y.asnumpy(),
                                   np.maximum(np.asarray(ry), 0), atol=1e-5)

    def test_hybridize_records_selection(self, force_pallas, tmp_path):
        """hybridize() tracing routes through the selection layer: the
        trace's decisions show in the counters and in the flight ring
        (_build_cache captures them into a pallas.selection record)."""
        from incubator_mxnet_tpu import diagnostics as diag
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(32), nn.LayerNorm(in_channels=32))
        net.initialize()
        net.hybridize()
        x = nd.array(np.random.RandomState(0)
                     .randn(4, 8).astype(np.float32))
        before = prof.counters().get("ops/pallas.selected.layer_norm", 0)
        diag.enable_flight_recorder(dump_dir=str(tmp_path))
        try:
            net(x)                       # first call = the CachedOp trace
        finally:
            from incubator_mxnet_tpu.diagnostics import flight as _flight
            events = (list(_flight._REC.events)
                      if _flight._REC is not None else [])
            diag.disable_flight_recorder()
        assert prof.counters()["ops/pallas.selected.layer_norm"] > before
        sel = [e for e in events
               if e.get("name", "").startswith("pallas.selection:")]
        assert sel, f"no pallas.selection record in flight ring: " \
                    f"{[e.get('name') for e in events][:10]}"
        decisions = sel[-1]["args"]["decisions"]
        assert any(d["kernel"] == "layer_norm" and d["selected"]
                   for d in decisions)


# ---------------------------------------------------------------------------
# persistent-compile-cache guard
# ---------------------------------------------------------------------------

class TestCacheGuard:
    def test_canary_passes_and_caches_verdict(self):
        from incubator_mxnet_tpu.runtime import cache_guard as cg
        cg._reset_for_tests()
        try:
            assert cg.check() is True
            assert cg.verdict() is True
        finally:
            cg._reset_for_tests()

    def test_corrupt_read_trips_and_disables_cache(self, monkeypatch):
        from incubator_mxnet_tpu.runtime import cache_guard as cg
        cg._reset_for_tests()
        old_enabled = jax.config.jax_enable_compilation_cache
        monkeypatch.setattr(
            cg, "_canary_values",
            lambda: (np.zeros((8, 128), np.float32),
                     np.full((4,), 1e19, np.float32)))
        monkeypatch.setattr(cg, "_cache_active", lambda: True)
        try:
            with pytest.warns(RuntimeWarning, match="integrity canary"):
                assert cg.check() is False
            assert jax.config.jax_enable_compilation_cache is False
            assert prof.counters()["mxtpu/compile_cache.guard_tripped"] >= 1
        finally:
            jax.config.update("jax_enable_compilation_cache", old_enabled)
            cg._reset_for_tests()

    def test_env_opt_out(self, monkeypatch):
        from incubator_mxnet_tpu.runtime import cache_guard as cg
        cg._reset_for_tests()
        monkeypatch.setenv("MXTPU_CACHE_GUARD", "0")
        called = []
        monkeypatch.setattr(cg, "_canary_values",
                            lambda: called.append(1) or (None, None))
        try:
            assert cg.check() is True
            assert not called
        finally:
            cg._reset_for_tests()
