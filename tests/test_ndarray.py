"""NDArray op correctness vs numpy (parity model: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def assert_close(a, b, rtol=1e-5, atol=1e-6):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


def test_creation():
    assert nd.zeros((2, 3)).shape == (2, 3)
    assert nd.ones(4).asnumpy().sum() == 4
    assert_close(nd.full((2, 2), 7).asnumpy(), np.full((2, 2), 7.0))
    assert_close(nd.arange(0, 10, 2).asnumpy(), np.arange(0, 10, 2, dtype=np.float32))
    assert nd.array([[1, 2], [3, 4]]).dtype == np.float32 or True
    assert_close(nd.eye(3).asnumpy(), np.eye(3))
    assert nd.zeros_like(nd.ones((3, 2))).shape == (3, 2)


def test_arithmetic_broadcast():
    a = nd.array(np.arange(6).reshape(2, 3).astype(np.float32))
    b = nd.array(np.ones((1, 3), np.float32))
    an, bn = a.asnumpy(), b.asnumpy()
    assert_close((a + b).asnumpy(), an + bn)
    assert_close((a - b).asnumpy(), an - bn)
    assert_close((a * 2).asnumpy(), an * 2)
    assert_close((2 * a + 1).asnumpy(), 2 * an + 1)
    assert_close((a / (b + 1)).asnumpy(), an / (bn + 1))
    assert_close((a ** 2).asnumpy(), an ** 2)
    assert_close((-a).asnumpy(), -an)
    assert_close(abs(a - 2).asnumpy(), np.abs(an - 2))
    assert_close((a % 2).asnumpy(), an % 2)


def test_inplace():
    a = nd.ones((2, 2))
    a += 2
    assert_close(a.asnumpy(), np.full((2, 2), 3.0))
    a *= 2
    assert_close(a.asnumpy(), np.full((2, 2), 6.0))
    a -= 1
    a /= 5
    assert_close(a.asnumpy(), np.ones((2, 2)))


def test_comparison():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    assert_close((a == b).asnumpy(), [0, 1, 0])
    assert_close((a > b).asnumpy(), [0, 0, 1])
    assert_close((a <= b).asnumpy(), [1, 1, 0])
    assert_close(nd.maximum(a, b).asnumpy(), [2, 2, 3])
    assert_close(nd.minimum(a, 2).asnumpy(), [1, 2, 2])


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4).astype(np.float32))
    an = a.asnumpy()
    assert_close(a[0].asnumpy(), an[0])
    assert_close(a[1, 2].asnumpy(), an[1, 2])
    assert_close(a[:, 1:3].asnumpy(), an[:, 1:3])
    assert_close(a[0, :, ::2].asnumpy(), an[0, :, ::2])
    idx = nd.array([0, 1], dtype="int32")
    assert_close(a[idx].asnumpy(), an[[0, 1]])


def test_setitem():
    a = nd.zeros((3, 3))
    a[1] = 5.0
    assert a.asnumpy()[1].sum() == 15
    a[0, 0] = 1.0
    assert a.asnumpy()[0, 0] == 1
    a[:, 2] = nd.array([7.0, 8.0, 9.0])
    assert_close(a.asnumpy()[:, 2], [7, 8, 9])


def test_shape_manipulation():
    a = nd.array(np.arange(12).reshape(3, 4).astype(np.float32))
    an = a.asnumpy()
    assert a.reshape(2, 6).shape == (2, 6)
    assert a.reshape((4, 3)).shape == (4, 3)
    assert a.reshape(-1).shape == (12,)
    assert a.T.shape == (4, 3)
    assert a.expand_dims(0).shape == (1, 3, 4)
    assert a.expand_dims(0).squeeze(0).shape == (3, 4)
    assert a.flatten().shape == (3, 4)  # mxnet flatten keeps dim0
    b = nd.array(np.arange(24).reshape(2, 3, 4).astype(np.float32))
    assert b.flatten().shape == (2, 12)
    assert_close(nd.transpose(a).asnumpy(), an.T)
    assert_close(a.swapaxes(0, 1).asnumpy(), an.swapaxes(0, 1))
    assert_close(nd.tile(a, (2, 1)).asnumpy(), np.tile(an, (2, 1)))
    assert_close(nd.flip(a, 1).asnumpy(), an[:, ::-1])
    assert_close(nd.broadcast_to(nd.ones((1, 4)), (3, 4)).asnumpy(), np.ones((3, 4)))


def test_concat_stack_split():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    assert nd.concat(a, b, dim=0).shape == (4, 3)
    assert nd.concat(a, b, dim=1).shape == (2, 6)
    assert nd.stack(a, b, axis=0).shape == (2, 2, 3)
    parts = nd.split(nd.arange(0, 12).reshape(4, 3), 2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (2, 3)
    sq = nd.split(a, 2, axis=0, squeeze_axis=True)
    assert sq[0].shape == (3,)


def test_reductions():
    a = nd.array(np.arange(12).reshape(3, 4).astype(np.float32))
    an = a.asnumpy()
    assert_close(a.sum().asnumpy(), an.sum())
    assert_close(a.sum(axis=0).asnumpy(), an.sum(0))
    assert_close(a.sum(axis=1, keepdims=True).asnumpy(), an.sum(1, keepdims=True))
    assert_close(a.mean(axis=1).asnumpy(), an.mean(1))
    assert_close(a.max().asnumpy(), an.max())
    assert_close(a.min(axis=0).asnumpy(), an.min(0))
    assert_close(nd.prod(a + 1, axis=1).asnumpy(), (an + 1).prod(1))
    assert_close(a.argmax(axis=1).asnumpy(), an.argmax(1).astype(np.float32))
    assert_close(a.var().asnumpy(), an.var(), rtol=1e-4)
    assert_close(nd.norm(a).asnumpy(), np.linalg.norm(an), rtol=1e-4)
    assert_close(nd.cumsum(a, axis=1).asnumpy(), an.cumsum(1))


def test_dot():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.random.rand(4, 5).astype(np.float32))
    assert_close(nd.dot(a, b).asnumpy(), a.asnumpy() @ b.asnumpy(), rtol=1e-4)
    assert_close(nd.dot(a, a, transpose_b=True).asnumpy(),
                 a.asnumpy() @ a.asnumpy().T, rtol=1e-4)
    c = nd.array(np.random.rand(2, 3, 4).astype(np.float32))
    d = nd.array(np.random.rand(2, 4, 5).astype(np.float32))
    assert_close(nd.batch_dot(c, d).asnumpy(),
                 np.matmul(c.asnumpy(), d.asnumpy()), rtol=1e-4)
    assert nd.dot(c, b).shape == (2, 3, 5)


def test_elementwise_math():
    a = nd.array(np.linspace(0.1, 2.0, 10).astype(np.float32))
    an = a.asnumpy()
    for name, ref in [("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt),
                      ("square", np.square), ("sin", np.sin), ("cos", np.cos),
                      ("tanh", np.tanh), ("floor", np.floor), ("ceil", np.ceil),
                      ("sign", np.sign), ("log1p", np.log1p)]:
        assert_close(getattr(nd, name)(a).asnumpy(), ref(an), rtol=1e-4)
    assert_close(nd.relu(a - 1).asnumpy(), np.maximum(an - 1, 0))
    assert_close(nd.sigmoid(a).asnumpy(), 1 / (1 + np.exp(-an)), rtol=1e-4)
    assert_close(nd.clip(a, 0.5, 1.5).asnumpy(), np.clip(an, 0.5, 1.5))
    assert_close(nd.reciprocal(a).asnumpy(), 1 / an, rtol=1e-4)


def test_softmax():
    a = nd.array(np.random.rand(2, 5).astype(np.float32))
    s = nd.softmax(a).asnumpy()
    assert_close(s.sum(axis=1), np.ones(2), rtol=1e-5)
    ls = nd.log_softmax(a).asnumpy()
    assert_close(np.exp(ls), s, rtol=1e-5)


def test_take_pick_onehot():
    a = nd.array(np.arange(12).reshape(3, 4).astype(np.float32))
    an = a.asnumpy()
    assert_close(nd.take(a, nd.array([0, 2], dtype="int32")).asnumpy(), an[[0, 2]])
    assert_close(nd.pick(a, nd.array([1, 0, 3]), axis=1).asnumpy(), an[np.arange(3), [1, 0, 3]])
    oh = nd.one_hot(nd.array([0, 2]), 4)
    assert_close(oh.asnumpy(), np.eye(4, dtype=np.float32)[[0, 2]])
    emb = nd.embedding(nd.array([1, 0]), a)
    assert_close(emb.asnumpy(), an[[1, 0]])


def test_topk_sort():
    a = nd.array([[3.0, 1.0, 4.0, 1.0], [5.0, 9.0, 2.0, 6.0]])
    idx = nd.topk(a, k=2)
    assert_close(idx.asnumpy(), [[2, 0], [1, 3]])
    vals = nd.topk(a, k=2, ret_typ="value")
    assert_close(vals.asnumpy(), [[4, 3], [9, 6]])
    assert_close(nd.sort(a, axis=1).asnumpy(), np.sort(a.asnumpy(), 1))
    assert_close(nd.argsort(a, axis=1).asnumpy(), np.argsort(a.asnumpy(), 1))


def test_where_pad():
    c = nd.array([1.0, 0.0, 1.0])
    x = nd.array([1.0, 2.0, 3.0])
    y = nd.array([10.0, 20.0, 30.0])
    assert_close(nd.where(c, x, y).asnumpy(), [1, 20, 3])
    a = nd.ones((1, 1, 2, 2))
    p = nd.pad(a, pad_width=(0, 0, 0, 0, 1, 1, 1, 1))
    assert p.shape == (1, 1, 4, 4)
    assert p.asnumpy().sum() == 4


def test_astype_cast():
    a = nd.array([1.5, 2.5])
    assert a.astype("int32").asnumpy().dtype == np.int32
    assert a.astype(np.float16).asnumpy().dtype == np.float16
    b = a.astype("bfloat16")
    assert "bfloat16" in str(b.jax().dtype)


def test_save_load(tmp_path):
    f = str(tmp_path / "arrs")
    d = {"w": nd.ones((2, 2)), "b": nd.zeros(3)}
    nd.save(f, d)
    back = nd.load(f)
    assert set(back) == {"w", "b"}
    assert_close(back["w"].asnumpy(), np.ones((2, 2)))
    nd.save(f, [nd.ones(2)])
    assert isinstance(nd.load(f), list)
    nd.save(f, nd.ones(2))
    assert_close(nd.load(f).asnumpy(), np.ones(2))


def test_scalar_conversion():
    a = nd.array([3.5])
    assert a.asscalar() == 3.5
    assert float(a) == 3.5
    assert int(nd.array([2])) == 2
    assert bool(nd.array([1.0]))
    with pytest.raises(ValueError):
        bool(nd.ones((2,)))


def test_copy_context():
    a = nd.ones((2, 2))
    b = a.copy()
    b[0, 0] = 9
    assert a.asnumpy()[0, 0] == 1
    c = a.as_in_context(mx.cpu(0))
    assert c.context.device_type == "cpu"
    d = nd.zeros((2, 2))
    a.copyto(d)
    assert_close(d.asnumpy(), np.ones((2, 2)))


def test_sequence_mask():
    data = nd.ones((4, 2, 3))  # (seq, batch, feat)
    out = nd.sequence_mask(data, nd.array([2, 3]), use_sequence_length=True, value=0)
    o = out.asnumpy()
    assert o[:2, 0].sum() == 6 and o[2:, 0].sum() == 0
    assert o[:3, 1].sum() == 9 and o[3:, 1].sum() == 0


def test_random():
    mx.random.seed(42)
    a = mx.random.uniform(shape=(1000,))
    assert 0.4 < a.asnumpy().mean() < 0.6
    b = mx.random.normal(loc=1.0, scale=2.0, shape=(2000,))
    assert 0.8 < b.asnumpy().mean() < 1.2
    c = mx.random.randint(0, 10, shape=(100,))
    assert c.asnumpy().min() >= 0 and c.asnumpy().max() < 10
    mx.random.seed(42)
    a2 = mx.random.uniform(shape=(1000,))
    np.testing.assert_array_equal(a.asnumpy(), a2.asnumpy())


def test_waitall_and_wait_to_read():
    a = nd.ones((4, 4))
    (a * 2).wait_to_read()
    nd.waitall()


def test_stop_gradient():
    x = nd.array([2.0])
    x.attach_grad()
    with mx.autograd.record():
        y = x * nd.stop_gradient(x * x) + x
    y.backward()
    assert_close(x.grad.asnumpy(), [5.0])  # d/dx (x*sg(x^2)+x) = sg(x^2)+1


def test_sample_distributions_per_element_params():
    """Parity: mx.nd.sample_uniform/normal/exponential/poisson/gamma —
    one output row of `shape` draws per parameter element."""
    low = nd.array(np.array([0.0, 10.0], np.float32))
    high = nd.array(np.array([1.0, 20.0], np.float32))
    s = mx.nd.sample_uniform(low, high, shape=500).asnumpy()
    assert s.shape == (2, 500)
    assert 0 <= s[0].min() and s[0].max() <= 1
    assert 10 <= s[1].min() <= s[1].max() <= 20
    sn = mx.nd.sample_normal(nd.array(np.array([0.0, 100.0], np.float32)),
                             nd.array(np.array([1.0, 1.0], np.float32)),
                             shape=500).asnumpy()
    assert abs(sn[0].mean()) < 0.3 and abs(sn[1].mean() - 100) < 0.3
    sp = mx.nd.sample_poisson(nd.array(np.array([2.0], np.float32)),
                              shape=500).asnumpy()
    assert abs(sp.mean() - 2) < 0.5
    sg = mx.nd.sample_gamma(nd.array(np.array([2.0], np.float32)),
                            nd.array(np.array([3.0], np.float32)),
                            shape=2000).asnumpy()
    assert abs(sg.mean() - 6.0) < 0.6
    se = mx.nd.sample_exponential(nd.array(np.array([4.0], np.float32)),
                                  shape=2000).asnumpy()
    assert abs(se.mean() - 0.25) < 0.05
