"""mxtpu.embedding tier-1 (ISSUE 19): dedup lookup equivalence vs plain
gather, the shared OOR-id policy (gluon.nn.Embedding index bugfix rides
the same normalize_ids), row-sparse optimizer parity vs the dense
reference on overlapping/duplicate ids, bit-parity of the sharded
(4-fake-device model axis) DLRM step vs single-device, and the
resharding detector on REAL compiled lookup HLO (quiet on a
vocab-annotated table, fires on a deliberately dp-pinned one)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.embedding import (EmbeddingBag, LazyAdam,
                                           RowSparseAdaGrad,
                                           ShardedEmbedding, dedup_capacity,
                                           dedup_lookup, embed,
                                           normalize_ids, segment_rowgrads)
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.ndarray import sparse as ndsparse
from incubator_mxnet_tpu.parallel import FusedTrainStep, make_mesh, sharding


@pytest.fixture(autouse=True)
def _clean_registry():
    """Every test starts and ends without a process-global mesh."""
    sharding.clear_mesh()
    yield
    sharding.clear_mesh()


# ---------------------------------------------------------------- lookup

class TestNormalizeIds:
    def test_float_carrier_rounds_not_truncates(self):
        # the historical bug: 2.9999998 (a float32 that *means* 3) must
        # land on row 3 — astype(int32) alone truncates it to 2
        ids = jnp.asarray([2.9999998, 0.0, 5.0000002], jnp.float32)
        out = normalize_ids(ids, 16)
        assert out.dtype == jnp.int32
        assert out.tolist() == [3, 0, 5]

    def test_int_dtypes_cast_to_int32(self):
        out = normalize_ids(jnp.asarray([1, 2], jnp.int16), 16)
        assert out.dtype == jnp.int32 and out.tolist() == [1, 2]

    def test_clip_policy_clips_and_counts(self):
        from incubator_mxnet_tpu.profiler.counters import counters
        before = counters().get("embedding/embedding.oor_ids", 0)
        out = normalize_ids(jnp.asarray([-3, 7, 99], jnp.int32), 8,
                            policy="clip")
        assert out.tolist() == [0, 7, 7]
        assert counters()["embedding/embedding.oor_ids"] == before + 2

    def test_error_policy_raises_on_concrete_oor(self):
        with pytest.raises(ValueError, match="outside"):
            normalize_ids(jnp.asarray([99], jnp.int32), 8, policy="error")
        # in-range ids pass through untouched under "error"
        assert normalize_ids(jnp.asarray([7], jnp.int32), 8,
                             policy="error").tolist() == [7]

    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError, match="policy"):
            normalize_ids(jnp.asarray([0], jnp.int32), 8, policy="wat")


class TestDedupLookup:
    def test_matches_plain_gather_with_duplicates(self):
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(32, 6).astype(np.float32))
        ids = jnp.asarray(rng.randint(0, 32, size=(4, 9)), jnp.int32)
        cap = dedup_capacity(ids.size, 32)
        out = dedup_lookup(w, ids, cap)
        ref = jnp.take(w, ids, axis=0)
        assert np.array_equal(np.asarray(out), np.asarray(ref))

    def test_capacity_clamp_is_lossless(self):
        # min(n_ids, vocab) always covers every distinct id
        assert dedup_capacity(1000, 32) == 32
        assert dedup_capacity(8, 32) == 8
        assert dedup_capacity(1000, 32, capacity=16) == 16

    def test_embed_dedup_on_off_identical(self):
        rng = np.random.RandomState(1)
        w = jnp.asarray(rng.randn(16, 4).astype(np.float32))
        ids = jnp.asarray(rng.randint(0, 16, size=(3, 5)).astype(np.float32))
        a = embed(ids, w, 16, dedup=True)
        b = embed(ids, w, 16, dedup=False)
        assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_embed_is_jit_safe(self):
        rng = np.random.RandomState(2)
        w = jnp.asarray(rng.randn(16, 4).astype(np.float32))
        ids = jnp.asarray(rng.randint(0, 16, size=(8,)), jnp.int32)
        f = jax.jit(lambda i, wt: embed(i, wt, 16, dedup=True))
        assert np.array_equal(np.asarray(f(ids, w)),
                              np.asarray(jnp.take(w, ids, axis=0)))


class TestSegmentRowgrads:
    def test_sums_duplicates_like_dense_scatter_add(self):
        rng = np.random.RandomState(3)
        V, D = 16, 4
        ids = jnp.asarray([3, 7, 3, 0, 7, 7], jnp.int32)
        g = jnp.asarray(rng.randn(6, D).astype(np.float32))
        uniq, rows, valid = segment_rowgrads(ids, g, capacity=6)
        dense = np.zeros((V, D), np.float32)
        np.add.at(dense, np.asarray(ids), np.asarray(g))
        rebuilt = np.zeros((V, D), np.float32)
        for u, r, v in zip(np.asarray(uniq), np.asarray(rows),
                           np.asarray(valid)):
            if v:
                rebuilt[int(u)] += r
        np.testing.assert_allclose(rebuilt, dense, rtol=1e-6)
        # exactly 3 distinct ids are marked valid
        assert int(np.asarray(valid).sum()) == 3


# ---------------------------------------------------------------- blocks

class TestShardedEmbeddingBlock:
    def test_forward_matches_take_and_annotates_vocab(self):
        mx.random.seed(0)
        emb = ShardedEmbedding(32, 8)
        emb.initialize(init=mx.init.Normal(0.05))
        assert emb.weight._sharding == P("vocab", None)
        ids = nd.array(np.random.RandomState(0)
                       .randint(0, 32, size=(4, 5)).astype(np.float32))
        out = emb(ids)
        ref = jnp.take(emb.weight.data()._data,
                       ids._data.astype(jnp.int32), axis=0)
        assert np.array_equal(np.asarray(out._data), np.asarray(ref))

    def test_bag_pools_inside_the_op(self):
        mx.random.seed(0)
        for mode, red in (("sum", jnp.sum), ("mean", jnp.mean)):
            bag = EmbeddingBag(16, 4, mode=mode)
            bag.initialize(init=mx.init.Normal(0.05))
            ids = nd.array(np.random.RandomState(1)
                           .randint(0, 16, size=(3, 6)).astype(np.float32))
            out = bag(ids)
            ref = red(jnp.take(bag.weight.data()._data,
                               ids._data.astype(jnp.int32), axis=0), axis=-2)
            np.testing.assert_allclose(np.asarray(out._data),
                                       np.asarray(ref), rtol=1e-6)

    def test_gluon_embedding_shares_the_policy(self):
        """Satellite 1: nn.Embedding normalizes float carriers by
        rounding and honors the same OOR policy as ShardedEmbedding."""
        mx.random.seed(0)
        emb = nn.Embedding(8, 4)
        emb.initialize(init=mx.init.Normal(0.05))
        w = emb.weight.data()._data
        out = emb(nd.array(np.asarray([2.9999998, 99.0], np.float32)))
        assert np.array_equal(np.asarray(out._data[0]), np.asarray(w[3]))
        assert np.array_equal(np.asarray(out._data[1]), np.asarray(w[7]))
        strict = nn.Embedding(8, 4, oor_policy="error")
        strict.initialize(init=mx.init.Normal(0.05))
        with pytest.raises(ValueError, match="outside"):
            strict(nd.array(np.asarray([99.0], np.float32)))


# ------------------------------------------------------ sparse optimizers

def _rsp(ids, rows, shape):
    return ndsparse.RowSparseNDArray(jnp.asarray(rows),
                                     jnp.asarray(ids, jnp.int32), shape)


def _parity_case(seed=0, V=24, D=5, nnz=7):
    rng = np.random.RandomState(seed)
    w = rng.randn(V, D).astype(np.float32)
    ids = rng.choice(V, size=nnz, replace=False).astype(np.int32)
    rows = rng.randn(nnz, D).astype(np.float32)
    return w, ids, rows


class TestRowSparseOptimizers:
    def test_registry_names(self):
        assert isinstance(mx.optimizer.create("rowsparseadagrad"),
                          RowSparseAdaGrad)
        assert isinstance(mx.optimizer.create("lazyadam"), LazyAdam)

    @pytest.mark.parametrize("name,dense_name",
                             [("rowsparseadagrad", "adagrad"),
                              ("lazyadam", "adam")])
    def test_bit_parity_with_dense_reference(self, name, dense_name):
        """The row-sparse scatter update is BIT-identical to the dense
        update on the same batch (wd=0: a dense grad's zero rows move
        nothing, so restricting to touched rows is exact)."""
        w_np, ids, rows = _parity_case()
        sp = mx.optimizer.create(name, learning_rate=0.05)
        dn = mx.optimizer.create(dense_name, learning_rate=0.05)
        w_sp, w_dn = nd.array(w_np.copy()), nd.array(w_np.copy())
        st_sp = sp.create_state(0, w_sp._data)
        st_dn = dn.create_state(0, w_dn._data)
        for step in range(3):
            rsp = _rsp(ids, rows * (step + 1), w_np.shape)
            st_sp = sp.update(0, w_sp, rsp, st_sp)
            st_dn = dn.update(0, w_dn, rsp.todense(), st_dn)
        assert np.array_equal(np.asarray(w_sp._data), np.asarray(w_dn._data))
        for a, b in zip(jax.tree_util.tree_leaves(st_sp),
                        jax.tree_util.tree_leaves(st_dn)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_lazy_semantics_with_weight_decay(self):
        """wd>0: touched rows match the dense update restricted to those
        rows; UNTOUCHED rows stay bit-identical (the lazy contract — the
        dense reference would decay them)."""
        w_np, ids, rows = _parity_case(seed=1)
        sp = mx.optimizer.create("rowsparseadagrad", learning_rate=0.05,
                                 wd=0.01)
        dn = mx.optimizer.create("adagrad", learning_rate=0.05, wd=0.01)
        w_sp, w_dn = nd.array(w_np.copy()), nd.array(w_np.copy())
        st_sp = sp.create_state(0, w_sp._data)
        st_dn = dn.create_state(0, w_dn._data)
        st_sp = sp.update(0, w_sp, _rsp(ids, rows, w_np.shape), st_sp)
        dense_grad = _rsp(ids, rows, w_np.shape).todense()
        st_dn = dn.update(0, w_dn, dense_grad, st_dn)
        touched = np.zeros(w_np.shape[0], bool)
        touched[ids] = True
        got, ref = np.asarray(w_sp._data), np.asarray(w_dn._data)
        assert np.array_equal(got[touched], ref[touched])
        assert np.array_equal(got[~touched], w_np[~touched])
        # the dense reference DID decay the untouched rows — the two
        # semantics genuinely differ there, which is what lazy means
        assert not np.array_equal(ref[~touched], w_np[~touched])

    def test_lazy_update_false_densifies(self):
        w_np, ids, rows = _parity_case(seed=2)
        sp = mx.optimizer.create("rowsparseadagrad", learning_rate=0.05,
                                 wd=0.01, lazy_update=False)
        dn = mx.optimizer.create("adagrad", learning_rate=0.05, wd=0.01)
        w_sp, w_dn = nd.array(w_np.copy()), nd.array(w_np.copy())
        st_sp = sp.create_state(0, w_sp._data)
        st_dn = dn.create_state(0, w_dn._data)
        rsp = _rsp(ids, rows, w_np.shape)
        st_sp = sp.update(0, w_sp, rsp, st_sp)
        st_dn = dn.update(0, w_dn, rsp.todense(), st_dn)
        assert np.array_equal(np.asarray(w_sp._data), np.asarray(w_dn._data))

    def test_oor_rows_dropped_not_scattered(self):
        """Rows flagged invalid (padding / out-of-range) must not touch
        the table — the OOB-scatter-drop trick, not a clamp to row 0."""
        from incubator_mxnet_tpu.embedding.optimizers import adagrad_rows
        V, D = 8, 3
        w = jnp.zeros((V, D), jnp.float32)
        hist = jnp.zeros((V, D), jnp.float32)
        rows = jnp.asarray([0, 2], jnp.int32)
        g = jnp.ones((2, D), jnp.float32)
        valid = jnp.asarray([False, True])
        new_w, _ = adagrad_rows(w, hist, rows, g, lr=0.1, wd=0.0,
                                eps=1e-7, valid=valid)
        got = np.asarray(new_w)
        assert np.array_equal(got[0], np.zeros(D))     # dropped, not row 0
        assert not np.array_equal(got[2], np.zeros(D))  # the valid row moved


# ------------------------------------------------- sharded DLRM bit-parity

needs8 = pytest.mark.skipif(len(jax.devices()) < 8,
                            reason="needs 8 virtual devices")


@pytest.fixture(autouse=True)
def _no_persistent_compile_cache():
    """Same hazard as tests/test_sharding.py: this jaxlib's CPU backend
    has mis-deserialized persistent-cache entries for donated sharded
    fused-step executables. Compile fresh in this module."""
    from jax._src import compilation_cache as cc
    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    cc.reset_cache()
    yield
    jax.config.update("jax_enable_compilation_cache", old)
    cc.reset_cache()


def _dlrm_step(mode=None, n=3):
    from incubator_mxnet_tpu.models.dlrm import dlrm_loss, dlrm_small
    mx.random.seed(0)
    np.random.seed(0)
    net = dlrm_small(num_tables=4, vocab_size=64, embed_dim=8,
                     dense_dim=4, bag_size=2, bottom_units=(16,),
                     top_units=(16,))
    net.initialize(init=mx.init.Normal(0.05))
    opt = mx.optimizer.create("rowsparseadagrad", learning_rate=0.05)
    step = FusedTrainStep(net, lambda o, y: dlrm_loss(o, y).mean(),
                          opt, sharding=mode)
    rng = np.random.RandomState(7)
    losses = []
    for _ in range(n):
        dense = rng.randn(16, 4).astype(np.float32)
        ids = rng.randint(0, 64, size=(16, 8)).astype(np.float32)
        y = (rng.rand(16) < 0.5).astype(np.float32)
        losses.append(float(step(nd.array(np.concatenate([dense, ids], 1)),
                                 nd.array(y))))
    return losses, step


@needs8
class TestShardedDLRM:
    def test_mp4_bit_identical_and_table_sharded(self):
        ref, _ = _dlrm_step()
        sharding.clear_mesh()
        sharding.set_mesh(make_mesh({"mp": 4}, devices=jax.devices()[:4]))
        losses, step = _dlrm_step(mode="auto")
        assert losses == ref                       # BIT-level, not allclose
        tables = [p for p in step.params if "embed" in p.name
                  and "weight" in p.name]
        assert tables
        for p in tables:
            raw = p.data()._data
            assert "mp" in str(raw.sharding.spec)
            shard0 = next(s for s in raw.addressable_shards
                          if s.device == jax.devices()[0])
            # vocab axis really split 4 ways on device 0
            assert shard0.data.shape[0] * 4 == p.shape[0]


# ------------------------------------------------- resharding detector

def _lookup_lowered(mesh, table_spec, ids_spec):
    """Lower a jitted dedup-lookup loss (the real kernel shape) under
    explicit in/out shardings and return the Lowered object."""
    V, D, N, CAP = 64, 8, 256, 64
    rng = np.random.RandomState(0)
    w = jax.device_put(rng.randn(V, D).astype(np.float32),
                       NamedSharding(mesh, table_spec))
    ids = jax.device_put(rng.randint(0, V, size=(N,)).astype(np.int32),
                         NamedSharding(mesh, ids_spec))

    def loss(wt, i):
        uniq, inv = jnp.unique(i, size=CAP, fill_value=0,
                               return_inverse=True)
        rows = jnp.take(wt, uniq, axis=0)
        out = jnp.take(rows, inv.reshape(i.shape), axis=0)
        return jnp.sum(out * out)

    f = jax.jit(jax.value_and_grad(loss),
                in_shardings=(NamedSharding(mesh, table_spec),
                              NamedSharding(mesh, ids_spec)),
                out_shardings=(NamedSharding(mesh, P()),
                               NamedSharding(mesh, table_spec)))
    return f.lower(w, ids)


@needs8
class TestLookupResharding:
    def test_quiet_on_vocab_sharded_table(self):
        """Correctly annotated table (vocab→mp) + replicated ids: XLA
        spells the sharded gather as masked-gather + all-reduce of a
        COMPUTED block — the detector must stay quiet."""
        from incubator_mxnet_tpu import commscope as cs
        mesh = Mesh(np.array(jax.devices()[:4]), ("mp",))
        lowered = _lookup_lowered(mesh, P("mp", None), P())
        rec = cs.capture("test_lookup_clean", lowered=lowered,
                         mesh=mesh, mode="auto")
        assert rec["collectives"]                  # it IS a sharded program
        assert rec["resharding_collectives"] == 0

    def test_fires_on_dp_pinned_table(self):
        """Deliberately dp-pinned table + batch-sharded ids in dp mode:
        the gather must all-gather a program PARAMETER — the param-gather
        rule indicts it."""
        from incubator_mxnet_tpu import commscope as cs
        mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
        lowered = _lookup_lowered(mesh, P("dp", None), P("dp"))
        with pytest.warns(UserWarning, match="resharding"):
            rec = cs.capture("test_lookup_dp_pinned", lowered=lowered,
                             mesh=mesh, mode="dp")
        assert rec["resharding_collectives"] > 0
        reasons = {f["reason"] for f in rec["resharding"]}
        assert "param-gather" in reasons or "unexpected-kind" in reasons
