"""gluon.contrib.rnn tests (parity: reference
tests/python/unittest/test_gluon_contrib.py): VariationalDropoutCell,
LSTMPCell, convolutional RNN/LSTM/GRU cells."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.gluon.contrib import rnn as crnn


def test_lstmp_shapes_and_projection():
    cell = crnn.LSTMPCell(16, 8, input_size=6)
    cell.initialize()
    x = nd.array(np.random.RandomState(0).randn(4, 6).astype(np.float32))
    out, states = cell(x, cell.begin_state(4))
    assert out.shape == (4, 8)                      # projected
    assert states[0].shape == (4, 8)                # r
    assert states[1].shape == (4, 16)               # c
    # the projection is exactly h @ Wr^T: recompute from the cell weights
    wi = cell.i2h_weight.data().asnumpy()
    wh = cell.h2h_weight.data().asnumpy()
    wr = cell.h2r_weight.data().asnumpy()
    pre = x.asnumpy() @ wi.T + np.zeros(64) + np.zeros((4, 8)) @ wh.T
    i, f, g, o = np.split(pre, 4, axis=-1)
    sig = lambda v: 1 / (1 + np.exp(-v))
    c2 = sig(f) * 0 + sig(i) * np.tanh(g)
    h2 = sig(o) * np.tanh(c2)
    np.testing.assert_allclose(out.asnumpy(), h2 @ wr.T, rtol=2e-5,
                               atol=2e-5)


def test_lstmp_unroll_trains():
    cell = crnn.LSTMPCell(12, 6, input_size=5)
    cell.initialize()
    tr = gluon.Trainer(cell.collect_params(), "adam",
                       {"learning_rate": 0.01})
    X = nd.array(np.random.RandomState(1).randn(8, 4, 5).astype(np.float32))
    losses = []
    for _ in range(5):
        with autograd.record():
            out, _ = cell.unroll(4, X, merge_outputs=True)
            loss = (out ** 2).mean()
        loss.backward()
        tr.step(8)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_variational_dropout_locked_mask():
    """The SAME mask applies at every timestep (train mode): with all-ones
    input and drop_inputs only, each timestep sees identical input scaling,
    so a pure-linear base cell gives identical step outputs."""
    base = gluon.rnn.RNNCell(4, activation="tanh", input_size=4)
    vd = crnn.VariationalDropoutCell(base, drop_inputs=0.5)
    vd.initialize()
    # zero the recurrent weight so output depends only on the (masked) input
    for name, p in vd.collect_params().items():
        if name.endswith("h2h_weight"):
            p.set_data(nd.zeros(p.shape))
    seq = nd.array(np.ones((2, 6, 4), np.float32))
    with autograd.record():
        out, _ = vd.unroll(6, seq, merge_outputs=True)
    o = out.asnumpy()
    for t in range(1, 6):
        np.testing.assert_allclose(o[:, t], o[:, 0], rtol=1e-6)
    # eval mode: identity (no dropout)
    out_eval, _ = vd.unroll(6, seq, merge_outputs=True)
    base_out, _ = base.unroll(6, seq, merge_outputs=True)
    np.testing.assert_allclose(out_eval.asnumpy(), base_out.asnumpy(),
                               rtol=1e-6)


def test_variational_dropout_fresh_mask_per_sequence():
    base = gluon.rnn.RNNCell(4, input_size=4)
    vd = crnn.VariationalDropoutCell(base, drop_inputs=0.5)
    vd.initialize()
    seq = nd.array(np.ones((2, 3, 4), np.float32))
    with autograd.record():
        o1, _ = vd.unroll(3, seq, merge_outputs=True)
        o2, _ = vd.unroll(3, seq, merge_outputs=True)
    # two unrolls draw independent masks (overwhelmingly different)
    assert not np.allclose(o1.asnumpy(), o2.asnumpy())


@pytest.mark.parametrize("cls,ishape,layout", [
    (crnn.Conv1DRNNCell, (2, 10), "NCW"),
    (crnn.Conv2DRNNCell, (2, 6, 6), "NCHW"),
    (crnn.Conv1DLSTMCell, (2, 10), "NCW"),
    (crnn.Conv2DLSTMCell, (3, 8, 8), "NCHW"),
    (crnn.Conv3DLSTMCell, (2, 4, 4, 4), "NCDHW"),
    (crnn.Conv2DGRUCell, (2, 6, 6), "NCHW"),
])
def test_conv_cells_shapes(cls, ishape, layout):
    c = cls(input_shape=ishape, hidden_channels=4, i2h_kernel=3,
            h2h_kernel=3, i2h_pad=1)
    c.initialize()
    rng = np.random.RandomState(2)
    x = nd.array(rng.randn(2, *ishape).astype(np.float32))
    states = c.begin_state(2)
    out, new_states = c(x, states)
    assert out.shape == (2, 4) + ishape[1:]
    assert len(new_states) == len(states)
    # three-step unroll keeps shapes and is differentiable
    seq = [nd.array(rng.randn(2, *ishape).astype(np.float32))
           for _ in range(3)]
    with autograd.record():
        outs, _ = c.unroll(3, seq, merge_outputs=False)
        loss = sum((o ** 2).mean() for o in outs)
    loss.backward()
    g = c.i2h_weight.grad().asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_conv_lstm_matches_manual_conv():
    """One Conv2DLSTM step equals gate math on nn.Conv2D outputs with the
    same weights."""
    c = crnn.Conv2DLSTMCell(input_shape=(2, 5, 5), hidden_channels=3,
                            i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    c.initialize()
    rng = np.random.RandomState(3)
    x = nd.array(rng.randn(1, 2, 5, 5).astype(np.float32))
    h0 = nd.array(rng.randn(1, 3, 5, 5).astype(np.float32))
    c0 = nd.array(rng.randn(1, 3, 5, 5).astype(np.float32))
    out, (h1, c1) = c(x, [h0, c0])

    from incubator_mxnet_tpu.ops import _raw
    import jax.numpy as jnp
    pi = _raw.conv(x._data, c.i2h_weight.data()._data,
                   c.i2h_bias.data()._data, kernel=(3, 3), pad=(1, 1))
    ph = _raw.conv(h0._data, c.h2h_weight.data()._data,
                   c.h2h_bias.data()._data, kernel=(3, 3), pad=(1, 1))
    pre = np.asarray(pi + ph)
    i, f, g, o = np.split(pre, 4, axis=1)
    sig = lambda v: 1 / (1 + np.exp(-v))
    c_ref = sig(f) * c0.asnumpy() + sig(i) * np.tanh(g)
    h_ref = sig(o) * np.tanh(c_ref)
    np.testing.assert_allclose(h1.asnumpy(), h_ref, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(c1.asnumpy(), c_ref, rtol=2e-5, atol=2e-5)


def test_conv_cell_even_h2h_kernel_rejected():
    with pytest.raises(ValueError):
        crnn.Conv2DRNNCell(input_shape=(2, 6, 6), hidden_channels=4,
                           i2h_kernel=3, h2h_kernel=2)
