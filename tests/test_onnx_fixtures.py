"""ONNX golden-fixture tests (VERDICT r4 #9): the committed .onnx bytes
freeze the exporter's wire format, making the "wire-compatible" claim
falsifiable — a refactor that changes serialization fails byte-equality
here even though the in-repo importer (same authorship, shared bugs)
would still round-trip. Where `onnxruntime` exists, the same bytes run
through the foreign parser and must match our importer numerically.

Parity: python/mxnet/contrib/onnx's test suite runs the real onnx
checker; this is the closest equivalent in a zero-egress image.
"""
import os
import sys

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.contrib import onnx as onnx_mxnet

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))

from gen_onnx_fixtures import BUILDERS, FIXDIR, export_bytes  # noqa: E402


def _fixture(name):
    with open(os.path.join(FIXDIR, f"{name}.onnx"), "rb") as f:
        return f.read()


@pytest.mark.parametrize("name", sorted(BUILDERS))
def test_wire_format_is_byte_stable(name):
    """Re-exporting the fixture model reproduces the committed bytes
    EXACTLY. If this fails after an intentional format change, regenerate
    with tools/gen_onnx_fixtures.py and review the diff in the PR."""
    committed = _fixture(name)
    fresh = export_bytes(name)
    if fresh != committed:
        m_old = onnx_mxnet._load_model_proto(committed)
        m_new = onnx_mxnet._load_model_proto(fresh)
        ops_old = [n.op_type for n in m_old.graph.node]
        ops_new = [n.op_type for n in m_new.graph.node]
        pytest.fail(
            f"exported wire bytes changed for {name}: "
            f"{len(committed)} -> {len(fresh)} bytes; node ops "
            f"{'UNCHANGED' if ops_old == ops_new else 'CHANGED'} "
            f"({len(ops_old)} -> {len(ops_new)} nodes). If intentional, "
            "regenerate fixtures via tools/gen_onnx_fixtures.py")


@pytest.mark.parametrize("name,n_inputs,opset", [("lenet", 1, 13),
                                                 ("tiny_transformer", 1, 13)])
def test_fixture_structure(name, n_inputs, opset):
    m = onnx_mxnet._load_model_proto(_fixture(name))
    assert m.opset_import[0].version == opset
    assert len(m.graph.input) == n_inputs
    assert m.graph.input[0].name == "data"
    assert len(m.graph.output) >= 1
    assert len(m.graph.node) > 3
    # every node input resolves to a graph input, initializer, or an
    # earlier node output — the basic well-formedness the onnx checker
    # enforces
    known = {i.name for i in m.graph.input} | \
        {t.name for t in m.graph.initializer}
    for node in m.graph.node:
        for i in node.input:
            assert i == "" or i in known, f"dangling input {i!r} in {name}"
        known.update(node.output)


@pytest.mark.parametrize("name", sorted(BUILDERS))
def test_fixture_imports_and_runs(name):
    """The committed bytes (not a fresh export) import and execute."""
    sym2, args2, aux2 = onnx_mxnet.import_model(_fixture(name))
    shape = BUILDERS[name]()[2]
    x = mx.nd.array(np.random.RandomState(0).rand(*shape).astype(np.float32)
                    if name == "lenet" else
                    np.random.RandomState(0).randint(0, 17, shape)
                    .astype(np.float32))
    out = sym2.bind(mx.cpu(), {**args2, **aux2, "data": x}).forward()[0]
    assert np.isfinite(out.asnumpy()).all()


@pytest.mark.parametrize("name", sorted(BUILDERS))
def test_onnxruntime_parity(name):
    """Foreign-parser validation: activates wherever onnxruntime exists
    (zero-egress CI lacks it; the fixture makes the claim portable)."""
    ort = pytest.importorskip("onnxruntime")
    blob = _fixture(name)
    sess = ort.InferenceSession(blob)
    shape = BUILDERS[name]()[2]
    x = (np.random.RandomState(0).rand(*shape).astype(np.float32)
         if name == "lenet" else
         np.random.RandomState(0).randint(0, 17, shape).astype(np.float32))
    ort_out = sess.run(None, {"data": x})[0]
    sym2, args2, aux2 = onnx_mxnet.import_model(blob)
    ours = sym2.bind(mx.cpu(), {**args2, **aux2,
                                "data": mx.nd.array(x)}).forward()[0]
    np.testing.assert_allclose(ort_out, ours.asnumpy(), rtol=2e-5,
                               atol=2e-5)
