"""Gluon blocks/layers/trainer (parity model: tests/python/unittest/test_gluon.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.gluon import nn


def assert_close(a, b, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


def test_dense_shapes_and_values():
    d = nn.Dense(4, in_units=3, use_bias=True)
    d.initialize(init=mx.init.One())
    x = nd.array([[1.0, 2.0, 3.0]])
    out = d(x)
    assert out.shape == (1, 4)
    # per-param init (bias=zeros) takes precedence over the global One()
    assert_close(out.asnumpy(), np.full((1, 4), 6.0))


def test_dense_deferred_init():
    d = nn.Dense(8)
    d.initialize()
    x = nd.ones((2, 5))
    out = d(x)
    assert out.shape == (2, 8)
    assert d.weight.shape == (8, 5)


def test_dense_no_flatten():
    d = nn.Dense(6, flatten=False)
    d.initialize()
    out = d(nd.ones((2, 3, 4)))
    assert out.shape == (2, 3, 6)


def test_conv2d():
    c = nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3)
    c.initialize()
    out = c(nd.ones((2, 3, 16, 16)))
    assert out.shape == (2, 8, 16, 16)
    c2 = nn.Conv2D(4, kernel_size=3, strides=2)
    c2.initialize()
    assert c2(nd.ones((1, 3, 9, 9))).shape == (1, 4, 4, 4)
    # grouped
    c3 = nn.Conv2D(8, kernel_size=1, groups=2, in_channels=4)
    c3.initialize()
    assert c3(nd.ones((1, 4, 5, 5))).shape == (1, 8, 5, 5)


def test_conv2d_nhwc():
    c = nn.Conv2D(8, kernel_size=3, padding=1, layout="NHWC", in_channels=3)
    c.initialize()
    out = c(nd.ones((2, 16, 16, 3)))
    assert out.shape == (2, 16, 16, 8)


def test_conv_transpose():
    c = nn.Conv2DTranspose(4, kernel_size=2, strides=2, in_channels=3)
    c.initialize()
    out = c(nd.ones((1, 3, 8, 8)))
    assert out.shape == (1, 4, 16, 16)


def test_pooling():
    x = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    mp = nn.MaxPool2D(2)
    assert_close(mp(x).asnumpy().ravel(), [5, 7, 13, 15])
    ap = nn.AvgPool2D(2)
    assert_close(ap(x).asnumpy().ravel(), [2.5, 4.5, 10.5, 12.5])
    g = nn.GlobalAvgPool2D()
    assert g(x).shape == (1, 1, 1, 1)
    assert_close(g(x).asnumpy().ravel(), [7.5])


def test_batchnorm_train_vs_infer():
    bn = nn.BatchNorm(in_channels=3, momentum=0.5)
    bn.initialize()
    x = nd.array(np.random.randn(8, 3, 4, 4).astype(np.float32) * 2 + 1)
    with autograd.record():
        y = bn(x)
    # batch-normalized output ~ zero mean unit var per channel
    yn = y.asnumpy()
    assert abs(yn.mean()) < 1e-5
    assert abs(yn.std() - 1) < 1e-2
    rm = bn.running_mean.data().asnumpy()
    assert np.abs(rm).max() > 0  # updated
    y2 = bn(x)  # inference uses running stats => different from train output
    assert np.abs(y2.asnumpy() - yn).max() > 1e-3


def test_layernorm():
    ln = nn.LayerNorm(in_channels=6)
    ln.initialize()
    x = nd.array(np.random.randn(4, 6).astype(np.float32) * 3 + 2)
    y = ln(x).asnumpy()
    assert_close(y.mean(-1), np.zeros(4), atol=1e-5)
    assert_close(y.std(-1), np.ones(4), rtol=1e-2)


def test_embedding():
    e = nn.Embedding(10, 4)
    e.initialize()
    out = e(nd.array([[1, 2], [3, 4]]))
    assert out.shape == (2, 2, 4)


def test_dropout_modes():
    do = nn.Dropout(0.5)
    x = nd.ones((100, 100))
    assert_close(do(x).asnumpy(), np.ones((100, 100)))  # inference = identity
    with autograd.record():
        y = do(x)
    yn = y.asnumpy()
    assert (yn == 0).mean() > 0.3  # roughly half dropped
    assert abs(yn.mean() - 1.0) < 0.1  # inverted scaling


def test_sequential_indexing():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(3), nn.Dense(2))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)


def test_hybridize_parity_and_caching():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.BatchNorm(), nn.Dense(4))
    net.initialize()
    x = nd.array(np.random.randn(8, 12).astype(np.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert_close(eager, hybrid, rtol=1e-5, atol=1e-5)
    # second call hits the cache (same signature)
    assert len(net._cache) == 1
    net(x)
    assert len(net._cache) == 1
    # new shape => new entry
    net(nd.array(np.random.randn(4, 12).astype(np.float32)))
    assert len(net._cache) == 2


def test_hybridize_grad_parity():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="tanh"), nn.Dense(1))
    net.initialize()
    x = nd.array(np.random.randn(8, 5).astype(np.float32))
    params = list(net.collect_params().values())

    def grads():
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        return [p.grad().asnumpy().copy() for p in params]

    eager = grads()
    net.hybridize()
    hybrid = grads()
    for ge, gh in zip(eager, hybrid):
        assert_close(ge, gh, rtol=1e-4, atol=1e-5)


def test_hybridized_bn_aux_writeback():
    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.BatchNorm())
    net.initialize()
    net.hybridize()
    x = nd.array(np.random.randn(16, 4).astype(np.float32))
    net(x)  # completes deferred init (inference mode, no aux drift)
    bn = net[1]
    before = bn.running_mean.data().asnumpy().copy()
    with autograd.record():
        net(x)
    after = bn.running_mean.data().asnumpy()
    assert np.abs(after - before).max() > 0


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net.initialize()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)
    x = nd.ones((1, 4))
    ref = net(x).asnumpy()
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    # fresh params differ...
    net2.initialize()
    # names differ per-instance prefix; load with mapping by order is out of
    # scope — reload into the SAME net after perturbing
    for p in net.collect_params().values():
        p.set_data(p.data() * 0)
    assert np.abs(net(x).asnumpy()).max() == 0
    net.load_parameters(f)
    assert_close(net(x).asnumpy(), ref)


def test_trainer_sgd_momentum():
    w = gluon.Parameter("w", shape=(2,), init="zeros")
    w.initialize()
    w.set_data(nd.array([1.0, 2.0]))
    tr = gluon.Trainer({"w": w}, "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    for step in range(3):
        with autograd.record():
            loss = (w.data() * nd.array([1.0, 1.0])).sum()
        loss.backward()
        tr.step(1)
    # manual: grad=1 each step; mom: m=-0.1, w=0.9; m=-0.19,w=0.71; m=-0.271,w=0.439
    assert_close(w.data().asnumpy(), [0.439, 1.439], rtol=1e-5)


def test_trainer_learning_rate():
    w = gluon.Parameter("w", shape=(1,), init="ones")
    w.initialize()
    tr = gluon.Trainer({"w": w}, "sgd", {"learning_rate": 0.5})
    assert tr.learning_rate == 0.5
    tr.set_learning_rate(0.25)
    assert tr.learning_rate == 0.25


def test_trainer_states_roundtrip(tmp_path):
    w = gluon.Parameter("w", shape=(2,), init="ones")
    w.initialize()
    tr = gluon.Trainer({"w": w}, "adam", {"learning_rate": 0.01})
    with autograd.record():
        (w.data() ** 2).sum().backward()
    tr.step(1)
    f = str(tmp_path / "trainer.states")
    tr.save_states(f)
    tr2 = gluon.Trainer({"w": w}, "adam", {"learning_rate": 0.01})
    tr2.load_states(f)
    assert tr2._optimizer.num_update == tr._optimizer.num_update


@pytest.mark.slow
def test_lenet_convergence():
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(
        nn.Conv2D(6, kernel_size=5, padding=2, activation="relu"),
        nn.MaxPool2D(2),
        nn.Conv2D(16, kernel_size=5, activation="relu"),
        nn.MaxPool2D(2),
        nn.Flatten(),
        nn.Dense(64, activation="relu"),
        nn.Dense(10),
    )
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    # separable synthetic "digits": class k = bright blob at position k
    n, k = 64, 10
    labels = np.random.randint(0, k, n)
    X = np.zeros((n, 1, 28, 28), np.float32)
    for i, l in enumerate(labels):
        X[i, 0, 2 + l * 2: 6 + l * 2, 4:24] = 1.0
    X += 0.1 * np.random.randn(*X.shape).astype(np.float32)
    Xn, yn = nd.array(X), nd.array(labels)
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.003})
    first = None
    for i in range(40):
        with autograd.record():
            loss = L(net(Xn), yn).mean()
        loss.backward()
        tr.step(1)
        if first is None:
            first = float(loss)
    last = float(loss)
    acc = (net(Xn).argmax(axis=1).asnumpy() == labels).mean()
    assert last < first * 0.2, (first, last)
    assert acc > 0.9, acc


def test_loss_values():
    pred = nd.array([[1.0, 2.0], [3.0, 4.0]])
    label = nd.array([[1.5, 2.5], [2.0, 3.0]])
    l2 = gluon.loss.L2Loss()(pred, label).asnumpy()
    assert_close(l2, [(0.25 + 0.25) / 4, (1 + 1) / 4])
    l1 = gluon.loss.L1Loss()(pred, label).asnumpy()
    assert_close(l1, [0.5, 1.0])
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    logits = nd.array([[10.0, 0.0], [0.0, 10.0]])
    lab = nd.array([0, 1])
    assert sce(logits, lab).asnumpy().max() < 1e-3
    h = gluon.loss.HuberLoss(rho=1.0)(nd.array([[0.0]]), nd.array([[3.0]])).asnumpy()
    assert_close(h, [2.5])  # |3| - 0.5
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    big = bce(nd.array([[100.0]]), nd.array([[0.0]])).asnumpy()
    assert_close(big, [100.0], rtol=1e-3)


def test_prelu_and_activations():
    p = nn.PReLU()
    p.initialize()
    out = p(nd.array([[-2.0, 3.0]]))
    assert_close(out.asnumpy(), [[-0.5, 3.0]])
    for act in ["relu", "sigmoid", "tanh", "softrelu", "gelu", "swish"]:
        a = nn.Activation(act)
        assert a(nd.array([0.5])).shape == (1,)


def test_collect_params_select():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    only_w = net.collect_params(".*weight")
    assert all("weight" in k for k in only_w.keys())
    assert len(only_w) == 2


def test_constant_param():
    c = gluon.Constant("const", nd.array([1.0, 2.0]))
    c.initialize()
    assert_close(c.data().asnumpy(), [1, 2])
    assert c.grad_req == "null"


def test_split_and_load_and_clip_global_norm():
    from incubator_mxnet_tpu.gluon import utils as gutils
    import incubator_mxnet_tpu as mx
    x = nd.array(np.arange(24, dtype=np.float32).reshape(8, 3))
    parts = gutils.split_data(x, 4)
    assert [p.shape for p in parts] == [(2, 3)] * 4
    np.testing.assert_allclose(parts[1].asnumpy(), x.asnumpy()[2:4])
    ragged = gutils.split_data(x, 3, even_split=False)
    assert [p.shape[0] for p in ragged] == [2, 2, 4]
    loaded = gutils.split_and_load(x.asnumpy(), [mx.cpu()])
    assert loaded[0].shape == (8, 3)
    # clip_global_norm: joint norm scaled to max_norm
    a = nd.array(np.full((3,), 3.0, np.float32))
    b = nd.array(np.full((4,), 4.0, np.float32))
    pre = np.sqrt(3 * 9.0 + 4 * 16.0)
    norm = gutils.clip_global_norm([a, b], 1.0)
    np.testing.assert_allclose(norm, pre, rtol=1e-5)
    post = np.sqrt((a.asnumpy() ** 2).sum() + (b.asnumpy() ** 2).sum())
    np.testing.assert_allclose(post, 1.0, rtol=1e-5)


def test_check_sha1_and_local_download(tmp_path):
    from incubator_mxnet_tpu.gluon import utils as gutils
    import hashlib
    src = tmp_path / "blob.bin"
    src.write_bytes(b"hello tpu")
    digest = hashlib.sha1(b"hello tpu").hexdigest()
    assert gutils.check_sha1(str(src), digest)
    dest = gutils.download(str(src), path=str(tmp_path / "copy.bin"),
                           sha1_hash=digest)
    assert open(dest, "rb").read() == b"hello tpu"
    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="no network egress"):
        gutils.download("https://example.com/x.bin")
