"""Test config: force the CPU backend with 8 virtual devices so mesh/
collective tests run without TPU hardware (SURVEY.md §4). Must run before
jax is imported anywhere."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# sitecustomize may have already pinned an accelerator platform at interpreter
# startup; override before any backend is materialized.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite's wall-time is dominated by XLA CPU
# compiles; caching them makes repeat runs (CI re-runs, -x iterating) start
# hot. Safe to delete the directory at any time.
try:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_test_cache"))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
except Exception:
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption("--run-slow", action="store_true", default=False,
                     help="also run tests marked slow (full-size model "
                          "compiles, heavyweight parity checks)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavyweight test, skipped unless --run-slow "
                   "or RUN_SLOW=1")
    config.addinivalue_line(
        "markers", "serial: must not run concurrently with other tests "
                   "(multi-process rendezvous on a reserved port); tier-1 "
                   "runs with xdist disabled, and any parallel runner "
                   "must isolate these")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--run-slow") or os.environ.get("RUN_SLOW") == "1":
        return
    skip = pytest.mark.skip(reason="slow: use --run-slow / RUN_SLOW=1")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _seed():
    import incubator_mxnet_tpu as mx
    np.random.seed(0)
    mx.random.seed(0)
    yield
