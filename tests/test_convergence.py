"""Convergence smokes (SURVEY.md §4: LeNet→synthetic-MNIST high train acc,
BERT MLM loss decreasing, SSD loss decreasing). Each smoke is small enough
to finish in well under a minute on the CPU test backend."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.models import get_model


def _synthetic_mnist(n_per_class=16, classes=4, seed=0):
    """Separable image classes: one noisy fixed template per class."""
    rng = np.random.RandomState(seed)
    templates = rng.rand(classes, 28, 28, 1).astype(np.float32)
    xs, ys = [], []
    for c in range(classes):
        noise = rng.randn(n_per_class, 28, 28, 1).astype(np.float32) * 0.3
        xs.append(templates[c][None] + noise)
        ys.append(np.full(n_per_class, c, np.int32))
    x = np.concatenate(xs)
    y = np.concatenate(ys)
    perm = rng.permutation(len(y))
    return x[perm], y[perm]


def test_lenet_synthetic_mnist_convergence():
    mx.random.seed(0)
    x_np, y_np = _synthetic_mnist()
    net = get_model("lenet", classes=4, layout="NHWC")
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    x, y = nd.array(x_np), nd.array(y_np)
    for _ in range(60):
        with autograd.record():
            out = net(x)
            loss = L(out, y)
        loss.backward()
        tr.step(x.shape[0])
    pred = net(x).asnumpy().argmax(axis=1)
    acc = (pred == y_np).mean()
    assert acc > 0.95, f"LeNet train acc {acc:.3f} <= 0.95"


def test_bert_mlm_loss_decreases():
    from incubator_mxnet_tpu.models.bert import (
        BERTModel, BERTForPretrain, BERTPretrainLoss)
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    V, B, T, M = 32, 8, 16, 4
    bert = BERTModel(num_layers=1, units=32, hidden_size=64, num_heads=4,
                     max_length=T, vocab_size=V, dropout=0.0,
                     token_type_vocab_size=2, use_pooler=True)
    model = BERTForPretrain(bert, vocab_size=V)
    model.initialize(init=mx.init.Normal(0.02))
    model.hybridize()
    L = BERTPretrainLoss()
    tr = gluon.Trainer(model.collect_params(), "adam",
                       {"learning_rate": 3e-3})
    ids = nd.array(rng.randint(0, V, (B, T)))
    types = nd.zeros((B, T))
    vlen = nd.array(np.full(B, T, np.int32))
    pos = nd.array(np.stack([rng.choice(T, M, replace=False)
                             for _ in range(B)]))
    mlm_label = nd.array(rng.randint(0, V, (B, M)))
    nsp_label = nd.array(rng.randint(0, 2, B))
    losses = []
    for _ in range(50):
        with autograd.record():
            mlm, nsp = model(ids, types, vlen, pos)
            loss = L(mlm, nsp, mlm_label, nsp_label)
        loss.backward()
        tr.step(B)
        losses.append(float(loss.asnumpy().mean()))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    # overall downward trend, not a lucky endpoint
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.5


def test_ssd_loss_decreases():
    from incubator_mxnet_tpu.models.ssd import SSD, SSDLoss
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    backbone = gluon.nn.HybridSequential()
    backbone.add(gluon.nn.Conv2D(16, 3, strides=2, padding=1, layout="NHWC",
                                 activation="relu"),
                 gluon.nn.Conv2D(32, 3, strides=2, padding=1, layout="NHWC",
                                 activation="relu"))
    net = SSD(backbone, num_classes=2,
              sizes=[[0.2, 0.3], [0.5, 0.6]], ratios=[[1, 2]] * 2,
              extra_channels=(64,), layout="NHWC")
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    L = SSDLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    B = 4
    x = nd.array(rng.rand(B, 24, 24, 3).astype(np.float32))
    # one gt box per image
    label = np.zeros((B, 1, 5), np.float32)
    for b in range(B):
        x0, y0 = rng.rand(2) * 0.4
        label[b, 0] = [rng.randint(0, 2), x0, y0, x0 + 0.4, y0 + 0.4]
    label = nd.array(label)
    # FRESH targets every step: hard negatives are re-mined against the
    # current predictions, exactly like the reference training loop
    # (example/ssd train.py -> MultiBoxTarget inside the iteration)
    losses = []
    for _ in range(15):
        with autograd.record():
            anchor, cls_pred, box_pred = net(x)
            with autograd.pause():
                bt, bm, ct = net.targets(anchor, cls_pred, label,
                                         negative_mining_ratio=3)
            loss = L(cls_pred, box_pred, ct, bt, bm)
        loss.backward()
        tr.step(B)
        losses.append(float(loss.asnumpy().mean()))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


@pytest.mark.slow
def test_ssd_fresh_targets_converges_and_detects():
    """Full fresh-target training to plateau + detection-quality proxy:
    after overfitting 4 toy images, the top decoded detection must overlap
    its ground-truth box (mean IoU) and the loss must have flattened.
    Covers VERDICT round-3 weak #4: no frozen-targets shortcut anywhere."""
    from incubator_mxnet_tpu.models.ssd import SSD, SSDLoss
    from incubator_mxnet_tpu import ops
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    backbone = gluon.nn.HybridSequential()
    backbone.add(gluon.nn.Conv2D(16, 3, strides=2, padding=1,
                                 layout="NHWC", activation="relu"),
                 gluon.nn.Conv2D(32, 3, strides=2, padding=1,
                                 layout="NHWC", activation="relu"))
    net = SSD(backbone, num_classes=2,
              sizes=[[0.2, 0.3], [0.5, 0.6]], ratios=[[1, 2]] * 2,
              extra_channels=(64,), layout="NHWC")
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    L = SSDLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    B = 4
    x = nd.array(rng.rand(B, 24, 24, 3).astype(np.float32))
    label = np.zeros((B, 1, 5), np.float32)
    for b in range(B):
        x0, y0 = rng.rand(2) * 0.4
        label[b, 0] = [rng.randint(0, 2), x0, y0, x0 + 0.4, y0 + 0.4]
    label_nd = nd.array(label)
    losses = []
    for _ in range(60):
        with autograd.record():
            anchor, cls_pred, box_pred = net(x)
            with autograd.pause():
                bt, bm, ct = net.targets(anchor, cls_pred, label_nd,
                                         negative_mining_ratio=3)
            loss = L(cls_pred, box_pred, ct, bt, bm)
        loss.backward()
        tr.step(B)
        losses.append(float(loss.asnumpy().mean()))
    # converged AND plateaued
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    assert abs(losses[-1] - losses[-5]) < 0.05 * losses[-1]

    # detection-quality proxy: decode + NMS, top box vs ground truth
    anchor, cls_pred, box_pred = net(x)
    cls_prob = nd.softmax(cls_pred, axis=-1).transpose((0, 2, 1))
    det = ops.MultiBoxDetection(cls_prob, box_pred.reshape((B, -1)),
                                anchor, nms_threshold=0.45).asnumpy()
    ious = []
    for b in range(B):
        rows = det[b]
        rows = rows[rows[:, 0] >= 0]
        assert len(rows), "no surviving detections for image %d" % b
        best = rows[np.argmax(rows[:, 1])]
        gx0, gy0, gx1, gy1 = label[b, 0, 1:]
        bx0, by0, bx1, by1 = best[2:]
        ix0, iy0 = max(gx0, bx0), max(gy0, by0)
        ix1, iy1 = min(gx1, bx1), min(gy1, by1)
        inter = max(0.0, ix1 - ix0) * max(0.0, iy1 - iy0)
        union = ((gx1 - gx0) * (gy1 - gy0)
                 + (bx1 - bx0) * (by1 - by0) - inter)
        ious.append(inter / union)
    assert np.mean(ious) > 0.4, ious
