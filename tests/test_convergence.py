"""Convergence smokes (SURVEY.md §4: LeNet→synthetic-MNIST high train acc,
BERT MLM loss decreasing, SSD loss decreasing). Each smoke is small enough
to finish in well under a minute on the CPU test backend."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.models import get_model


def _synthetic_mnist(n_per_class=16, classes=4, seed=0):
    """Separable image classes: one noisy fixed template per class."""
    rng = np.random.RandomState(seed)
    templates = rng.rand(classes, 28, 28, 1).astype(np.float32)
    xs, ys = [], []
    for c in range(classes):
        noise = rng.randn(n_per_class, 28, 28, 1).astype(np.float32) * 0.3
        xs.append(templates[c][None] + noise)
        ys.append(np.full(n_per_class, c, np.int32))
    x = np.concatenate(xs)
    y = np.concatenate(ys)
    perm = rng.permutation(len(y))
    return x[perm], y[perm]


def test_lenet_synthetic_mnist_convergence():
    mx.random.seed(0)
    x_np, y_np = _synthetic_mnist()
    net = get_model("lenet", classes=4, layout="NHWC")
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    x, y = nd.array(x_np), nd.array(y_np)
    for _ in range(60):
        with autograd.record():
            out = net(x)
            loss = L(out, y)
        loss.backward()
        tr.step(x.shape[0])
    pred = net(x).asnumpy().argmax(axis=1)
    acc = (pred == y_np).mean()
    assert acc > 0.95, f"LeNet train acc {acc:.3f} <= 0.95"


def test_bert_mlm_loss_decreases():
    from incubator_mxnet_tpu.models.bert import (
        BERTModel, BERTForPretrain, BERTPretrainLoss)
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    V, B, T, M = 32, 8, 16, 4
    bert = BERTModel(num_layers=1, units=32, hidden_size=64, num_heads=4,
                     max_length=T, vocab_size=V, dropout=0.0,
                     token_type_vocab_size=2, use_pooler=True)
    model = BERTForPretrain(bert, vocab_size=V)
    model.initialize(init=mx.init.Normal(0.02))
    model.hybridize()
    L = BERTPretrainLoss()
    tr = gluon.Trainer(model.collect_params(), "adam",
                       {"learning_rate": 3e-3})
    ids = nd.array(rng.randint(0, V, (B, T)))
    types = nd.zeros((B, T))
    vlen = nd.array(np.full(B, T, np.int32))
    pos = nd.array(np.stack([rng.choice(T, M, replace=False)
                             for _ in range(B)]))
    mlm_label = nd.array(rng.randint(0, V, (B, M)))
    nsp_label = nd.array(rng.randint(0, 2, B))
    losses = []
    for _ in range(50):
        with autograd.record():
            mlm, nsp = model(ids, types, vlen, pos)
            loss = L(mlm, nsp, mlm_label, nsp_label)
        loss.backward()
        tr.step(B)
        losses.append(float(loss.asnumpy().mean()))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    # overall downward trend, not a lucky endpoint
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.5


def test_ssd_loss_decreases():
    from incubator_mxnet_tpu.models.ssd import SSD, SSDLoss
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    backbone = gluon.nn.HybridSequential()
    backbone.add(gluon.nn.Conv2D(16, 3, strides=2, padding=1, layout="NHWC",
                                 activation="relu"),
                 gluon.nn.Conv2D(32, 3, strides=2, padding=1, layout="NHWC",
                                 activation="relu"))
    net = SSD(backbone, num_classes=2,
              sizes=[[0.2, 0.3], [0.5, 0.6]], ratios=[[1, 2]] * 2,
              extra_channels=(64,), layout="NHWC")
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    L = SSDLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    B = 4
    x = nd.array(rng.rand(B, 24, 24, 3).astype(np.float32))
    # one gt box per image
    label = np.zeros((B, 1, 5), np.float32)
    for b in range(B):
        x0, y0 = rng.rand(2) * 0.4
        label[b, 0] = [rng.randint(0, 2), x0, y0, x0 + 0.4, y0 + 0.4]
    label = nd.array(label)
    # with hard-negative mining off the targets depend only on anchors and
    # labels — compute once outside the loop (keeps the smoke fast)
    with autograd.pause():
        anchor0, cls_pred0, _ = net(x)
        bt, bm, ct = net.targets(anchor0, cls_pred0, label,
                                 negative_mining_ratio=-1)
    losses = []
    for _ in range(15):
        with autograd.record():
            anchor, cls_pred, box_pred = net(x)
            loss = L(cls_pred, box_pred, ct, bt, bm)
        loss.backward()
        tr.step(B)
        losses.append(float(loss.asnumpy().mean()))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
