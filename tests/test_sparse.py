"""Sparse NDArray: RowSparse/CSR storage, cast_storage, sparse.dot,
sparse Embedding gradients, lazy SGD update, kv.row_sparse_pull.

Parity model: python/mxnet/ndarray/sparse.py +
src/operator/tensor/cast_storage-inl.h + sgd lazy_update.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.ndarray import sparse


def test_row_sparse_roundtrip():
    dense = np.zeros((6, 3), np.float32)
    dense[[1, 4]] = np.random.RandomState(0).randn(2, 3)
    rsp = sparse.cast_storage(nd.array(dense), "row_sparse")
    assert rsp.stype == "row_sparse"
    assert rsp.nnz == 2
    np.testing.assert_array_equal(np.asarray(rsp.indices), [1, 4])
    np.testing.assert_allclose(rsp.asnumpy(), dense)
    back = rsp.tostype("default")
    np.testing.assert_allclose(back.asnumpy(), dense)


def test_row_sparse_array_sorting():
    data = np.arange(6, dtype=np.float32).reshape(2, 3)
    rsp = sparse.row_sparse_array((data, [5, 2]), shape=(8, 3))
    np.testing.assert_array_equal(np.asarray(rsp.indices), [2, 5])
    dense = rsp.asnumpy()
    np.testing.assert_allclose(dense[2], data[1])
    np.testing.assert_allclose(dense[5], data[0])


def test_row_sparse_add_merge():
    a = sparse.row_sparse_array((np.ones((2, 3), np.float32), [0, 2]),
                                shape=(5, 3))
    b = sparse.row_sparse_array((np.full((2, 3), 2.0, np.float32), [2, 4]),
                                shape=(5, 3))
    c = a + b
    assert c.stype == "row_sparse" and c.nnz == 3
    expected = np.zeros((5, 3), np.float32)
    expected[0] = 1.0
    expected[2] = 3.0
    expected[4] = 2.0
    np.testing.assert_allclose(c.asnumpy(), expected)


def test_retain():
    rsp = sparse.row_sparse_array(
        (np.arange(9, dtype=np.float32).reshape(3, 3), [1, 3, 5]),
        shape=(7, 3))
    kept = sparse.retain(rsp, [3, 6])
    np.testing.assert_array_equal(np.asarray(kept.indices), [3])
    np.testing.assert_allclose(kept.asnumpy()[3], rsp.asnumpy()[3])


def test_csr_roundtrip_and_dot():
    rng = np.random.RandomState(1)
    dense = rng.randn(5, 7).astype(np.float32)
    dense[np.abs(dense) < 0.8] = 0.0
    csr = sparse.cast_storage(nd.array(dense), "csr")
    assert csr.stype == "csr"
    np.testing.assert_allclose(csr.asnumpy(), dense, rtol=1e-6)
    rhs = rng.randn(7, 4).astype(np.float32)
    out = sparse.dot(csr, nd.array(rhs))
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs, rtol=1e-5,
                               atol=1e-5)
    outT = sparse.dot(csr, nd.array(rng.randn(5, 4).astype(np.float32)),
                      transpose_a=True)
    assert outT.shape == (7, 4)


def test_sparse_zeros():
    z = sparse.zeros("row_sparse", (4, 3))
    assert z.nnz == 0
    np.testing.assert_allclose(z.asnumpy(), np.zeros((4, 3)))
    zc = sparse.zeros("csr", (4, 3))
    np.testing.assert_allclose(zc.asnumpy(), np.zeros((4, 3)))


def test_embedding_sparse_grad():
    w = nd.array(np.random.RandomState(0).randn(10, 4).astype(np.float32))
    w.attach_grad()
    ids = nd.array(np.array([[1, 3], [3, 7]]))
    with mx.autograd.record():
        out = nd.embedding(ids, w, sparse_grad=True)
        loss = (out * out).sum()
    loss.backward()
    g = w._grad
    assert isinstance(g, sparse.RowSparseNDArray)
    np.testing.assert_array_equal(np.asarray(g.indices), [1, 3, 7])
    # dense reference
    w2 = nd.array(w.asnumpy())
    w2.attach_grad()
    with mx.autograd.record():
        out2 = nd.embedding(ids, w2)
        loss2 = (out2 * out2).sum()
    loss2.backward()
    np.testing.assert_allclose(g.asnumpy(), w2._grad.asnumpy(), rtol=1e-6)


def test_sgd_lazy_update_matches_dense():
    rng = np.random.RandomState(2)
    w_np = rng.randn(8, 3).astype(np.float32)
    g_rows = rng.randn(2, 3).astype(np.float32)
    rows = np.array([1, 5])
    for momentum in (0.0, 0.9):
        opt_s = mx.optimizer.create("sgd", learning_rate=0.1,
                                    momentum=momentum, wd=0.01)
        opt_d = mx.optimizer.create("sgd", learning_rate=0.1,
                                    momentum=momentum, wd=0.01,
                                    lazy_update=False)
        w_s, w_d = nd.array(w_np), nd.array(w_np)
        st_s = opt_s.create_state_multi_precision(0, w_s._data)
        st_d = opt_d.create_state_multi_precision(0, w_d._data)
        rsp = sparse.row_sparse_array((g_rows, rows), shape=(8, 3))
        st_s = opt_s.update(0, w_s, rsp, st_s)
        # dense reference: zero grad everywhere but the rows. NOTE lazy vs
        # dense differ on wd/momentum for untouched rows — with fresh state
        # and wd applied to touched rows only, compare rows directly.
        st_d = opt_d.update(0, w_d, rsp, st_d)
        np.testing.assert_allclose(w_s.asnumpy()[rows], w_d.asnumpy()[rows],
                                   rtol=1e-5, atol=1e-6)
        # untouched rows unchanged in lazy mode
        other = [i for i in range(8) if i not in rows]
        np.testing.assert_allclose(w_s.asnumpy()[other], w_np[other])


def test_gluon_embedding_sparse_train_step():
    """End-to-end: gluon Embedding(sparse_grad=True) + Trainer step only
    moves looked-up rows; matches a dense-grad reference run."""
    from incubator_mxnet_tpu import gluon
    rng = np.random.RandomState(3)
    init_w = rng.randn(12, 4).astype(np.float32)

    def run(sparse_grad):
        emb = gluon.nn.Embedding(12, 4, sparse_grad=sparse_grad)
        emb.initialize()
        emb.weight.set_data(nd.array(init_w))
        tr = gluon.Trainer(emb.collect_params(), "sgd",
                           {"learning_rate": 0.5})
        ids = nd.array(np.array([2, 2, 9]))
        with mx.autograd.record():
            out = emb(ids)
            loss = (out * out).sum()
        loss.backward()
        tr.step(1)
        return emb.weight.data().asnumpy()

    w_sparse = run(True)
    w_dense = run(False)
    np.testing.assert_allclose(w_sparse, w_dense, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w_sparse[[0, 1, 3]], init_w[[0, 1, 3]])


def test_kvstore_row_sparse_pull_returns_sparse():
    kv = mx.kv.create("local")
    w = np.arange(15, dtype=np.float32).reshape(5, 3)
    kv.init("emb", nd.array(w))
    rsp = kv.row_sparse_pull("emb", row_ids=nd.array(np.array([4, 1, 1])))
    assert isinstance(rsp, sparse.RowSparseNDArray)
    np.testing.assert_array_equal(np.asarray(rsp.indices), [1, 4])
    np.testing.assert_allclose(rsp.asnumpy()[[1, 4]], w[[1, 4]])
    np.testing.assert_allclose(rsp.asnumpy()[[0, 2, 3]], 0.0)


# ---------------------------------------------------------------------------
# elementwise sparse algebra (parity: python/mxnet/ndarray/sparse.py
# elemwise_add/sub/mul, operator overloads, storage fallback warnings)
# ---------------------------------------------------------------------------

def _rand_csr(rng, shape, density=0.3):
    dense = rng.randn(*shape).astype(np.float32)
    dense[rng.rand(*shape) > density] = 0.0
    return sparse.csr_matrix(nd.array(dense)), dense


def _rand_rsp(rng, shape, density=0.5):
    dense = rng.randn(*shape).astype(np.float32)
    dead = rng.rand(shape[0]) > density
    dense[dead] = 0.0
    return sparse.row_sparse_array(nd.array(dense)), dense


def test_csr_add_sub_union():
    rng = np.random.RandomState(0)
    a, da = _rand_csr(rng, (5, 7))
    b, db = _rand_csr(rng, (5, 7))
    s = sparse.add(a, b)
    assert s.stype == "csr"
    np.testing.assert_allclose(s.asnumpy(), da + db, rtol=1e-6)
    d = sparse.subtract(a, b)
    assert d.stype == "csr"
    np.testing.assert_allclose(d.asnumpy(), da - db, rtol=1e-6)
    # operator overloads route the same kernels
    np.testing.assert_allclose((a + b).asnumpy(), da + db, rtol=1e-6)
    np.testing.assert_allclose((a - b).asnumpy(), da - db, rtol=1e-6)


def test_csr_mul_intersection_stays_sparse():
    rng = np.random.RandomState(1)
    a, da = _rand_csr(rng, (4, 6))
    b, db = _rand_csr(rng, (4, 6))
    m = sparse.multiply(a, b)
    assert m.stype == "csr"
    np.testing.assert_allclose(m.asnumpy(), da * db, rtol=1e-6)
    # nnz of the product is at most the smaller pattern
    assert m.nnz <= min(a.nnz, b.nnz)


def test_csr_mul_dense_keeps_pattern():
    rng = np.random.RandomState(2)
    a, da = _rand_csr(rng, (4, 6))
    dense = rng.randn(4, 6).astype(np.float32)
    m = sparse.multiply(a, nd.array(dense))
    assert m.stype == "csr" and m.nnz == a.nnz
    np.testing.assert_allclose(m.asnumpy(), da * dense, rtol=1e-6)


def test_rsp_add_sub_mul():
    rng = np.random.RandomState(3)
    a, da = _rand_rsp(rng, (6, 3))
    b, db = _rand_rsp(rng, (6, 3))
    np.testing.assert_allclose(sparse.add(a, b).asnumpy(), da + db,
                               rtol=1e-6)
    np.testing.assert_allclose((a - b).asnumpy(), da - db, rtol=1e-6)
    m = sparse.multiply(a, b)
    assert m.stype == "row_sparse"
    np.testing.assert_allclose(m.asnumpy(), da * db, rtol=1e-6)


def test_scalar_ops_stay_sparse():
    rng = np.random.RandomState(4)
    a, da = _rand_csr(rng, (3, 5))
    r, dr = _rand_rsp(rng, (5, 2))
    m = sparse.multiply(a, 2.5)
    assert m.stype == "csr"
    np.testing.assert_allclose(m.asnumpy(), da * 2.5, rtol=1e-6)
    d = sparse.divide(r, 2.0)
    assert d.stype == "row_sparse"
    np.testing.assert_allclose(d.asnumpy(), dr / 2.0, rtol=1e-6)
    np.testing.assert_allclose((2.5 * a).asnumpy(), da * 2.5, rtol=1e-6)


def test_storage_fallback_warns_once():
    import warnings as w
    rng = np.random.RandomState(5)
    a, da = _rand_csr(rng, (3, 4))
    dense = nd.array(rng.randn(3, 4).astype(np.float32))
    sparse._FALLBACK_WARNED.clear()
    with w.catch_warnings(record=True) as rec:
        w.simplefilter("always")
        out = sparse.add(a, dense)
        out2 = sparse.add(a, dense)
    fb = [x for x in rec if issubclass(x.category,
                                       sparse.StorageFallbackWarning)]
    assert len(fb) == 1  # warned once per op/storage signature
    assert isinstance(out, nd.NDArray) and not isinstance(
        out, sparse.BaseSparseNDArray)
    np.testing.assert_allclose(out.asnumpy(), da + dense.asnumpy(),
                               rtol=1e-6)
    np.testing.assert_allclose(out2.asnumpy(), out.asnumpy())


def test_sparse_div_fallback():
    import warnings as w
    rng = np.random.RandomState(6)
    a, da = _rand_csr(rng, (3, 4))
    dense = nd.array(np.full((3, 4), 2.0, np.float32))
    sparse._FALLBACK_WARNED.clear()
    with w.catch_warnings(record=True) as rec:
        w.simplefilter("always")
        out = sparse.divide(a, dense)
    assert any(issubclass(x.category, sparse.StorageFallbackWarning)
               for x in rec)
    np.testing.assert_allclose(out.asnumpy(), da / 2.0, rtol=1e-6)


def test_elemwise_shape_mismatch_raises():
    rng = np.random.RandomState(7)
    a, _ = _rand_csr(rng, (3, 4))
    b, _ = _rand_csr(rng, (4, 3))
    with pytest.raises(ValueError, match="shape mismatch"):
        sparse.add(a, b)


def test_dot_csr_rsp():
    rng = np.random.RandomState(8)
    a, da = _rand_csr(rng, (4, 6))
    r, dr = _rand_rsp(rng, (6, 3))
    out = sparse.dot(a, r)
    np.testing.assert_allclose(out.asnumpy(), da @ dr, rtol=1e-5,
                               atol=1e-5)
    x = rng.randn(4, 2).astype(np.float32)
    outT = sparse.dot(a, nd.array(x), transpose_a=True)
    assert outT.shape == (6, 2)
    # regression: transpose_a must gather rhs by nnz ROW ids, not column
    # indices (a silent-NaN bug when shape[1] > shape[0])
    np.testing.assert_allclose(outT.asnumpy(), da.T @ x, rtol=1e-5,
                               atol=1e-5)


def test_dot_dense_csr_transpose_identity():
    rng = np.random.RandomState(9)
    a, da = _rand_csr(rng, (4, 6))
    x = rng.randn(3, 4).astype(np.float32)
    out = sparse.dot(nd.array(x), a)
    np.testing.assert_allclose(out.asnumpy(), x @ da, rtol=1e-5, atol=1e-5)
    x2 = rng.randn(3, 6).astype(np.float32)
    out2 = sparse.dot(nd.array(x2), a, transpose_b=True)
    np.testing.assert_allclose(out2.asnumpy(), x2 @ da.T, rtol=1e-5,
                               atol=1e-5)


def test_dot_csr_transpose_b_unsupported():
    rng = np.random.RandomState(10)
    a, _ = _rand_csr(rng, (4, 6))
    with pytest.raises(NotImplementedError):
        sparse.dot(a, nd.array(np.ones((2, 6), np.float32)),
                   transpose_b=True)
