"""Gluon Estimator API (reference tests/python/unittest/test_gluon_estimator.py
and test_gluon_event_handler.py)."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, metric as metric_mod, nd
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.gluon.contrib.estimator import (
    BatchEnd, CheckpointHandler, EarlyStoppingHandler, Estimator,
    GradientUpdateHandler, LoggingHandler, MetricHandler, StoppingHandler,
    ValidationHandler)


def _toy(n=64, d=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, classes).astype(np.float32)
    y = (x @ w).argmax(axis=1).astype(np.float32)
    ds = gluon.data.ArrayDataset(nd.array(x), nd.array(y))
    return gluon.data.DataLoader(ds, batch_size=16)


def _net(classes=4):
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(classes))
    net.initialize(init=mx.init.Xavier())
    return net


def _estimator(net=None, metrics=None):
    net = net or _net()
    return Estimator(
        net=net,
        loss=gluon.loss.SoftmaxCrossEntropyLoss(),
        train_metrics=metrics,
        trainer=gluon.Trainer(net.collect_params(), "adam",
                              {"learning_rate": 1e-2}),
    )


def test_fit_epochs_trains():
    data = _toy()
    est = _estimator(metrics=metric_mod.Accuracy())
    est.fit(train_data=data, epochs=5)
    names = dict(m.get_name_value()[0] for m in est.train_metrics)
    assert names["accuracy"] > 0.5
    # train loss metric rides along automatically
    assert any("softmaxcrossentropyloss" in n for n in names)


def test_fit_batches_stops_at_count():
    data = _toy()
    est = _estimator()
    seen = []

    class Counter(BatchEnd):
        def batch_end(self, estimator, *args, **kwargs):
            seen.append(1)

    est.fit(train_data=data, batches=6, event_handlers=[Counter()])
    assert len(seen) == 6


def test_epochs_and_batches_exclusive():
    est = _estimator()
    with pytest.raises(ValueError):
        est.fit(train_data=_toy(), epochs=1, batches=1)
    with pytest.raises(ValueError):
        est.fit(train_data=_toy())


def test_validation_handler_runs_every_epoch():
    data = _toy()
    val = _toy(seed=1)
    est = _estimator(metrics=metric_mod.Accuracy())
    est.fit(train_data=data, val_data=val, epochs=2)
    names = dict(m.get_name_value()[0] for m in est.val_metrics)
    assert not np.isnan(list(names.values())[0])


def test_evaluate_standalone():
    est = _estimator(metrics=metric_mod.Accuracy())
    res = est.evaluate(_toy(seed=2))
    assert any("loss" in k for k in res)


def test_checkpoint_handler(tmp_path):
    data = _toy()
    est = _estimator(metrics=metric_mod.Accuracy())
    ckpt = CheckpointHandler(str(tmp_path), model_prefix="toy",
                             monitor=est.train_metrics[0], save_best=True)
    est.fit(train_data=data, epochs=3, event_handlers=[ckpt])
    files = sorted(os.listdir(tmp_path))
    assert "toy-epoch0.params" in files and "toy-epoch2.params" in files
    assert "toy-best.params" in files
    assert "toy-epoch0.states" in files
    # params round-trip into a fresh net
    net2 = _net()
    net2.load_parameters(str(tmp_path / "toy-epoch2.params"))


def test_checkpoint_max_checkpoints(tmp_path):
    data = _toy()
    est = _estimator()
    ckpt = CheckpointHandler(str(tmp_path), model_prefix="m",
                             max_checkpoints=2)
    est.fit(train_data=data, epochs=4, event_handlers=[ckpt])
    params = [f for f in os.listdir(tmp_path) if f.endswith(".params")]
    assert sorted(params) == ["m-epoch2.params", "m-epoch3.params"]


def test_early_stopping_stops():
    data = _toy()
    est = _estimator(metrics=metric_mod.Accuracy())

    class Frozen(metric_mod.EvalMetric):
        """Monitor that never improves."""

        def __init__(self):
            super().__init__("frozen")

        def update(self, labels, preds):
            pass

        def get(self):
            return "frozen", 0.5

    stopper = EarlyStoppingHandler(monitor=Frozen(), patience=1, mode="max")
    epochs_run = []

    class EpochCounter(LoggingHandler):
        def epoch_end(self, estimator, *args, **kwargs):
            epochs_run.append(1)
            super().epoch_end(estimator, *args, **kwargs)

    est.fit(train_data=data, epochs=50,
            event_handlers=[stopper, EpochCounter()])
    # patience=1: epoch0 sets best? no — first epoch_end: 0.5 not > best
    # (-inf)... it IS an improvement; epoch1 no improvement (wait=1),
    # epoch2 no improvement (wait=2 > patience) -> stop well before 50
    assert 2 <= len(epochs_run) <= 4
    assert stopper.stopped_epoch is not None


def test_handler_priority_order():
    """GradientUpdateHandler (priority -2000) must run before
    MetricHandler (-1000), which runs before LoggingHandler (1000)."""
    est = _estimator()
    handlers = est._prepare_handlers(None, [])
    batch_end = est._categorize(handlers)[3]
    kinds = [type(h).__name__ for h in batch_end]
    assert kinds.index("GradientUpdateHandler") < kinds.index(
        "MetricHandler") < kinds.index("LoggingHandler")


def test_custom_gradient_update_accumulation():
    """Replacing GradientUpdateHandler customizes the update cadence
    (here: step every 2 batches => gradient accumulation)."""
    data = _toy()
    net = _net()
    est = Estimator(net=net, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                    trainer=gluon.Trainer(net.collect_params(), "sgd",
                                          {"learning_rate": 1e-2}))
    steps = []

    class EveryTwo(GradientUpdateHandler):
        def __init__(self):
            self.n = 0

        def batch_end(self, estimator, *args, **kwargs):
            self.n += 1
            if self.n % 2 == 0:
                estimator.trainer.step(32)
                steps.append(1)

    est.fit(train_data=data, batches=8, event_handlers=[EveryTwo()])
    assert len(steps) == 4


def test_rejects_non_loss_and_non_metric():
    net = _net()
    with pytest.raises(ValueError):
        Estimator(net=net, loss=lambda a, b: a)
    with pytest.raises(ValueError):
        Estimator(net=net, loss=gluon.loss.L2Loss(),
                  train_metrics="accuracy")


def test_fit_empty_loader_raises():
    est = _estimator()
    with pytest.raises(ValueError, match="no batches"):
        est.fit(train_data=[], batches=4)


def test_evaluate_dispatches_event_handlers():
    est = _estimator(metrics=metric_mod.Accuracy())
    events = []

    class Observer(LoggingHandler):
        def epoch_begin(self, estimator, *args, **kwargs):
            events.append("eb")

        def batch_end(self, estimator, *args, **kwargs):
            assert kwargs.get("pred") is not None
            events.append("be")

        def epoch_end(self, estimator, *args, **kwargs):
            events.append("ee")

    est.evaluate(_toy(), event_handlers=[Observer()])
    assert events[0] == "eb" and events[-1] == "ee"
    assert events.count("be") == 4  # 64 samples / batch 16


def test_fit_zero_epochs_is_noop():
    est = _estimator()
    est.net(nd.array(np.zeros((1, 8), np.float32)))  # materialize params
    before = {k: v.data().asnumpy().copy()
              for k, v in est.net.collect_params().items()}
    est.fit(train_data=_toy(), epochs=0)
    est.fit(train_data=_toy(), batches=0)
    for k, v in est.net.collect_params().items():
        np.testing.assert_array_equal(before[k], v.data().asnumpy())
    with pytest.raises(ValueError, match=">= 0"):
        est.fit(train_data=_toy(), epochs=-1)
