"""REAL multi-process cluster tests (not mocks): two OS processes form a
jax cluster over loopback gloo and train data-parallel with each process
contributing its own batch shard.

Complements tests/test_multihost_mock.py (which patches process_count to
cover branch logic): here `jax.distributed.initialize`, cross-process
collectives, the process-spanning Mesh, and `distributed.barrier` all
actually execute — the runbook in distributed.py's docstring, verbatim.
Reference parity: tools/launch.py + dmlc tracker rendezvous, replaced by
the coordinator bootstrap.
"""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
_WORKER = os.path.join(_HERE, "mh_worker.py")


def _free_port(_next=[0]):
    """Reserve a coordination-service port OUTSIDE the kernel's ephemeral
    range (Linux default 32768+). The old bind-port-0 probe was racy
    under full-suite load: between closing the probe socket and the
    worker's coordinator binding it, any other test's OUTGOING connection
    (HTTP smoke servers, async-PS transports) could be assigned the same
    ephemeral port, and the rendezvous then failed with address-in-use.
    A dedicated low range nothing else allocates from (plus a per-pid
    stagger and a rotating cursor so back-to-back tests in one session
    never reuse a port still in TIME_WAIT) isolates the coordinator."""
    base = 21000 + (os.getpid() * 131) % 1000
    for off in range(2000):
        port = 21000 + (base - 21000 + _next[0] + off) % 2000
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", port))
        except OSError:
            continue
        finally:
            s.close()
        _next[0] = (port - 21000 + 1) % 2000
        return port
    raise RuntimeError("no free coordination port in 21000-22999")


# Startup deadline for worker rendezvous: under full-suite load the two
# workers' heavy imports start staggered by tens of seconds, so both the
# in-worker jax rendezvous (MXTPU_INIT_TIMEOUT -> initialization_timeout)
# and the parent's communicate() wait get explicit, generous budgets.
_INIT_TIMEOUT_S = int(os.environ.get("MXTPU_TEST_INIT_TIMEOUT", "180"))
_WORKER_TIMEOUT_S = int(os.environ.get("MXTPU_TEST_WORKER_TIMEOUT", "420"))


def _cluster_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker pins its own 2-device count
    env["MXTPU_INIT_TIMEOUT"] = str(_INIT_TIMEOUT_S)
    return env


def _run_cluster(nproc, steps, timeout=_WORKER_TIMEOUT_S):
    port = str(_free_port())
    env = _cluster_env()
    procs = [subprocess.Popen(
        [sys.executable, _WORKER, str(pid), str(nproc), port, str(steps)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for pid in range(nproc)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"
    return outs


def _parse(outs, key):
    vals = []
    for _, out, _ in outs:
        for ln in out.splitlines():
            if ln.startswith(key):
                vals.append(ln[len(key):].split())
    return vals


def test_two_process_dp_training_matches_single_process():
    steps = 25
    outs = _run_cluster(2, steps)

    # every process saw the same replicated final weights
    ws = _parse(outs, "FINAL_W ")
    assert len(ws) == 2
    w0 = np.array([float(v) for v in ws[0]])
    w1 = np.array([float(v) for v in ws[1]])
    np.testing.assert_allclose(w0, w1, rtol=1e-6)

    # barriers drained and shutdown completed on both
    assert all(_parse([o], "BARRIER_OK") for o in outs)
    assert all(_parse([o], "SHUTDOWN_OK") for o in outs)

    # single-process ground truth on the same global problem
    rng = np.random.RandomState(0)
    X = rng.randn(16, 5).astype(np.float32)
    y = X @ np.arange(5, dtype=np.float32)
    w = np.zeros(5, np.float32)
    for _ in range(steps):
        g = 2.0 * X.T @ (X @ w - y) / len(X)
        w = w - 0.05 * g
    np.testing.assert_allclose(w0, w, rtol=1e-4, atol=1e-5)

    losses = _parse(outs, "FINAL_LOSS ")
    assert float(losses[0][0]) < 1.0


def test_two_process_dist_async_push_crosses_process_boundary():
    """REAL cross-process dist_async (VERDICT r4 #8): each worker's push
    travels to the rank-0 server over the coordination service and is
    applied as an independent per-worker server-side update under induced
    staleness; convergence and per-worker applied counts are asserted."""
    steps = 60
    worker = os.path.join(_HERE, "mh_async_worker.py")
    port = str(_free_port())
    env = _cluster_env()
    procs = [subprocess.Popen(
        [sys.executable, worker, str(pid), "2", port, str(steps)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=_WORKER_TIMEOUT_S)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"

    # after the barrier every worker pulled identical final weights
    ws = _parse(outs, "FINAL_W ")
    assert len(ws) == 2
    w0 = np.array([float(v) for v in ws[0]])
    w1 = np.array([float(v) for v in ws[1]])
    np.testing.assert_allclose(w0, w1, rtol=1e-5, atol=1e-6)

    # async SGD on half-batches with staleness still converges
    losses = [float(v[0]) for v in _parse(outs, "FINAL_LOSS ")]
    assert all(l < 1.0 for l in losses), losses

    # per-worker accounting: the server applied EVERY push from EACH
    # worker exactly once — 2 workers x `steps` pushes
    counts = _parse(outs, "APPLIED ")[0]
    applied = dict(kv.split(":") for kv in counts)
    assert applied == {"0": str(steps), "1": str(steps)}, applied
    assert all(_parse([o], "SHUTDOWN_OK") for o in outs)


@pytest.mark.serial
def test_two_process_overlap_trainer_matches_single_process():
    """REAL cross-process overlapped gradient communication: buckets
    issue mid-backward on both ranks in deterministic order and aggregate
    through the actual process_allgather collective; finals must be
    rank-identical AND equal single-process full-batch training.

    Marked `serial` (and given an isolated coordination port + widened
    startup deadline): it passes alone in ~18 s but used to flake under
    full-suite load when its rendezvous port was re-assigned or its
    workers started staggered past the old 240 s budget."""
    steps = 10
    worker = os.path.join(_HERE, "mh_overlap_worker.py")
    port = str(_free_port())
    env = _cluster_env()
    procs = [subprocess.Popen(
        [sys.executable, worker, str(pid), "2", port, str(steps)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=_WORKER_TIMEOUT_S)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"

    # per-param lines, identical across ranks
    params = [dict((ln.split()[1], np.array([float(v) for v in
                                             ln.split()[2:]]))
                   for ln in out.splitlines() if ln.startswith("PARAM "))
              for _, out, _ in outs]
    assert params[0].keys() == params[1].keys() and params[0]
    for k in params[0]:
        np.testing.assert_allclose(params[0][k], params[1][k], rtol=1e-6)

    # single-process ground truth: same net, full batch, plain Trainer.
    # Explicit prefixes: the suite parent's global auto-name counter has
    # drifted (dense_349...) while fresh workers start at dense_0, so a
    # by-generated-name lookup only worked when this test ran alone —
    # the actual cause of the "fails under full-suite load" flake.
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd
    mx.random.seed(7)
    np.random.seed(7)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, in_units=6, activation="relu",
                           prefix="ref0_"),
            gluon.nn.Dense(3, in_units=8, prefix="ref1_"))
    net.initialize(init=mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    rng = np.random.RandomState(3)
    X = nd.array(rng.randn(8, 6).astype(np.float32))
    Y = nd.array(rng.randn(8, 3).astype(np.float32))
    L = gluon.loss.L2Loss()
    for _ in range(steps):
        with autograd.record():
            loss = L(net(X), Y).sum()
        loss.backward()
        tr.step(X.shape[0])
    # positional alignment: both sides sorted — workers are fresh
    # processes (dense_0*/dense_1*), reference uses fixed prefixes
    ref = sorted(net.collect_params().items())
    got = sorted(params[0].items())
    assert len(ref) == len(got)
    for (_, p), (wname, wvals) in zip(ref, got):
        np.testing.assert_allclose(wvals, p.data().asnumpy().ravel(),
                                    rtol=1e-4, atol=1e-6,
                                    err_msg=wname)


@pytest.mark.slow
def test_four_process_cluster():
    outs = _run_cluster(4, 10)
    ws = _parse(outs, "FINAL_W ")
    assert len(ws) == 4
    ref = np.array([float(v) for v in ws[0]])
    for w in ws[1:]:
        np.testing.assert_allclose(np.array([float(v) for v in w]), ref,
                                   rtol=1e-6)


def test_launch_py_runs_local_cluster():
    """tools/launch.py (the reference launcher's analogue) spawns N local
    workers whose unmodified `mx.distributed.init()` picks the cluster up
    from the MXTPU_* env it sets."""
    launcher = os.path.join(_HERE, "..", "tools", "launch.py")
    script = (
        "import os;"
        "os.environ['JAX_PLATFORMS']='cpu';"
        "os.environ['XLA_FLAGS']='--xla_force_host_platform_device_count=2';"
        "import jax;"
        "jax.config.update('jax_platforms', 'cpu');"
        "import incubator_mxnet_tpu as mx;"
        "mx.distributed.init();"
        "assert mx.distributed.is_initialized();"
        "n=mx.distributed.num_workers();"
        "r=mx.distributed.rank();"
        "print('RANK', r, 'OF', n, 'DEVS', len(jax.devices()))")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, launcher, "-n", "2", "--",
         sys.executable, "-c", script],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=os.path.join(_HERE, ".."))
    assert r.returncode == 0, f"launcher rc={r.returncode}\n{r.stdout}\n{r.stderr}"
    lines = sorted(ln for ln in r.stdout.splitlines() if "RANK" in ln)
    # both ranks formed one 2-process cluster spanning 4 CPU devices
    assert len(lines) == 2, r.stdout
    assert "RANK 0 OF 2 DEVS 4" in lines[0]
    assert "RANK 1 OF 2 DEVS 4" in lines[1]


def test_launch_py_fail_fast_on_worker_crash():
    """A crashing worker must terminate the rest promptly (not hang the
    job in rank-order waits)."""
    launcher = os.path.join(_HERE, "..", "tools", "launch.py")
    # rank 1 exits rc=3 immediately; rank 0 would sleep for 300s
    script = ("import os,sys,time;"
              "r=int(os.environ['MXTPU_PROCESS_ID']);"
              "sys.exit(3) if r==1 else time.sleep(300)")
    import time as _t
    t0 = _t.time()
    r = subprocess.run(
        [sys.executable, launcher, "-n", "2", "--",
         sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 3, (r.returncode, r.stderr)
    assert _t.time() - t0 < 60, "launcher failed to fail fast"
    assert "worker 1 exited rc=3" in r.stderr


def test_distributed_init_ignores_partial_env(monkeypatch):
    """A stray MXTPU_NUM_PROCESSES (no coordinator) must not reroute a
    plain single-host init() into an explicit rendezvous crash."""
    import incubator_mxnet_tpu as mx
    monkeypatch.setenv("MXTPU_NUM_PROCESSES", "1")
    monkeypatch.delenv("MXTPU_COORDINATOR", raising=False)
    monkeypatch.delenv("MXTPU_PROCESS_ID", raising=False)
    assert not mx.distributed.is_initialized()
    mx.distributed.init()  # must not raise
