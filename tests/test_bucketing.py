"""BucketingModule + BucketSentenceIter (parity:
python/mxnet/module/bucketing_module.py + python/mxnet/rnn/io.py).

Variable-length training: each bucket compiles its own static-shape XLA
executable while every bucket trains the SAME shared parameter arrays."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def _sym_gen(seq_len):
    """Tiny bucketed classifier: embed -> mean over time -> FC."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    emb = mx.sym.Embedding(data, input_dim=20, output_dim=8,
                           name="embed")
    pooled = mx.sym.mean(emb, axis=1)
    fc = mx.sym.FullyConnected(pooled, num_hidden=3, name="fc")
    out = mx.sym.SoftmaxOutput(fc, label, name="softmax")
    return out, ("data",), ("softmax_label",)


def _batch(bucket, batch_size=4, seed=0):
    rng = np.random.RandomState(seed + bucket)
    from incubator_mxnet_tpu.io import DataBatch, DataDesc
    data = nd.array(rng.randint(0, 20, (batch_size, bucket)))
    label = nd.array(rng.randint(0, 3, batch_size))
    return DataBatch(
        [data], [label], bucket_key=bucket,
        provide_data=[DataDesc("data", (batch_size, bucket), np.float32)],
        provide_label=[DataDesc("softmax_label", (batch_size,), np.float32)])


def test_bucketing_module_shares_params():
    mod = mx.mod.BucketingModule(_sym_gen, default_bucket_key=12)
    b0 = _batch(12)
    mod.bind(data_shapes=b0.provide_data, label_shapes=b0.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    # forward through two different buckets
    for bucket in (12, 5, 8):
        batch = _batch(bucket)
        mod.forward(batch, is_train=True)
        out = mod.get_outputs()[0]
        assert out.shape == (4, 3)
        mod.backward()
        mod.update()
    # all buckets share the default bucket's arrays (same objects)
    emb_default = mod._buckets[12]._exec.arg_dict["embed_weight"]
    for key in (5, 8):
        assert mod._buckets[key]._exec.arg_dict["embed_weight"] is emb_default


def test_bucketing_module_learns():
    """Loss decreases training across interleaved bucket sizes."""
    mod = mx.mod.BucketingModule(_sym_gen, default_bucket_key=10)
    b0 = _batch(10)
    mod.bind(data_shapes=b0.provide_data, label_shapes=b0.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    rng = np.random.RandomState(42)
    from incubator_mxnet_tpu.io import DataBatch, DataDesc

    def fixed_batch(bucket):
        # deterministic, learnable mapping: label = first token % 3
        data = rng.randint(0, 20, (8, bucket))
        label = data[:, 0] % 3
        return DataBatch(
            [nd.array(data)], [nd.array(label)], bucket_key=bucket,
            provide_data=[DataDesc("data", (8, bucket), np.float32)],
            provide_label=[DataDesc("softmax_label", (8,), np.float32)])

    batches = [fixed_batch(b) for b in (10, 6, 10, 6, 10, 6)]
    metric = mx.metric.Accuracy()

    def epoch_acc():
        metric.reset()
        for batch in batches:
            mod.forward(batch, is_train=False)
            mod.update_metric(metric, batch.label)
        return metric.get_name_value()[0][1]

    acc0 = epoch_acc()
    for _ in range(40):
        for batch in batches:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
    acc1 = epoch_acc()
    assert acc1 > max(acc0, 0.7), (acc0, acc1)


def test_bucket_sentence_iter():
    rng = np.random.RandomState(0)
    sentences = [list(rng.randint(1, 20, rng.randint(2, 15)))
                 for _ in range(100)]
    it = mx.io.BucketSentenceIter(sentences, batch_size=4,
                                  buckets=[5, 10, 15])
    assert it.default_bucket_key == 15
    seen_buckets = set()
    n = 0
    for batch in it:
        b = batch.bucket_key
        seen_buckets.add(b)
        assert batch.data[0].shape == (4, b)
        assert batch.label[0].shape == (4, b)
        n += 1
    assert n > 0 and len(seen_buckets) >= 2
    # labels are next tokens
    it.reset()
    batch = next(iter(it))
    d = batch.data[0].asnumpy()
    l = batch.label[0].asnumpy()
    np.testing.assert_array_equal(l[:, :-1], d[:, 1:])


def test_bucketing_with_sentence_iter_end_to_end():
    rng = np.random.RandomState(1)
    sentences = [list(rng.randint(1, 20, rng.randint(3, 10)))
                 for _ in range(64)]
    it = mx.io.BucketSentenceIter(sentences, batch_size=8, buckets=[5, 10])
    mod = mx.mod.BucketingModule(_sym_gen,
                                 default_bucket_key=it.default_bucket_key)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    for batch in it:
        # classifier head: use first label column as the class (toy)
        batch.label = [nd.array(batch.label[0].asnumpy()[:, 0] % 3)]
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    assert len(mod._buckets) >= 2


def test_bucket_sentence_iter_tn_layout():
    sentences = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
    it = mx.io.BucketSentenceIter(sentences * 4, batch_size=4, buckets=[5],
                                  layout="TN", dtype="int32")
    batch = next(iter(it))
    assert batch.data[0].shape == (5, 4)          # time-major
    assert it.provide_data[0].shape == (5, 4)


def test_bucketing_rebind_clears_buckets():
    mod = mx.mod.BucketingModule(_sym_gen, default_bucket_key=10)
    b = _batch(10)
    mod.bind(data_shapes=b.provide_data, label_shapes=b.provide_label)
    mod.init_params()
    mod.forward(_batch(6), is_train=False)
    assert 6 in mod._buckets
    mod.bind(data_shapes=b.provide_data, label_shapes=b.provide_label,
             force_rebind=True)
    assert 6 not in mod._buckets                  # stale buckets dropped
    assert not mod.params_initialized
