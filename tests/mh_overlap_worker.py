"""Worker for the REAL cross-process overlapped-Trainer test.

Each process of a 2-process cluster trains the same net on its OWN half
of the global batch via `Trainer(overlap_comm=True, kvstore='dist_sync')`
— gradient buckets are issued mid-backward and aggregated by the REAL
cross-process collective (`process_allgather` inside
KVStore._batch_aggregate), in deterministic order on every process (the
SPMD requirement). Final weights must be identical across ranks AND
match the given single-process ground truth recomputed by the test.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import autograd, gluon, nd  # noqa: E402


def main():
    pid = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    steps = int(sys.argv[4])

    mx.distributed.init(coordinator_address=f"127.0.0.1:{port}",
                        num_processes=nproc, process_id=pid)

    mx.random.seed(7)
    np.random.seed(7)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, in_units=6, activation="relu"),
            gluon.nn.Dense(3, in_units=8))
    net.initialize(init=mx.init.Xavier())

    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore="dist_sync",
                       overlap_comm=True)
    assert tr._kvstore.num_workers == nproc
    assert tr._sched._deterministic, "multi-process must issue in order"

    rng = np.random.RandomState(3)
    X = rng.randn(8, 6).astype(np.float32)
    Y = rng.randn(8, 3).astype(np.float32)
    per = 8 // nproc
    Xl = nd.array(X[pid * per:(pid + 1) * per])
    Yl = nd.array(Y[pid * per:(pid + 1) * per])
    L = gluon.loss.L2Loss()

    for _ in range(steps):
        with autograd.record():
            loss = L(net(Xl), Yl).sum()   # local-shard SUM: psum = global
        loss.backward()
        assert tr._sched.issued_log, "buckets must issue mid-backward"
        tr.step(len(X))                   # rescale by the GLOBAL batch
        # (flush() resets issued_log at the start of every step)

    for name, p in sorted(net.collect_params().items()):
        flat = " ".join(f"{v:.6f}" for v in p.data().asnumpy().ravel())
        print(f"PARAM {name} {flat}", flush=True)
    mx.distributed.barrier()
    mx.distributed.shutdown()
    print("SHUTDOWN_OK", flush=True)


if __name__ == "__main__":
    main()
