"""Worker program for tests/test_multihost_real.py — runs as one process
of a REAL 2-process jax cluster (gloo collectives over loopback).

Each process owns half the global batch (2 local CPU devices -> 4-device
global dp mesh) and trains a linear model for N steps; the final weights
are printed and must match the single-process result bit-for-bit-ish
(same global batch, same seed). Exercises the exact API surface of the
multi-host runbook in distributed.py: init -> global_mesh ->
make_array_from_process_local_data -> jitted step with replicated
out_shardings -> barrier -> shutdown.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402


def main():
    pid = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    steps = int(sys.argv[4])

    mx.distributed.init(coordinator_address=f"127.0.0.1:{port}",
                        num_processes=nproc, process_id=pid)
    assert mx.distributed.rank() == pid
    assert mx.distributed.num_workers() == nproc
    assert len(mx.distributed.global_devices()) == 2 * nproc
    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == nproc, kv.num_workers

    mesh = mx.distributed.global_mesh({"dp": -1})
    # deterministic global problem, identical on every process
    rng = np.random.RandomState(0)
    X = rng.randn(16, 5).astype(np.float32)
    w_true = np.arange(5, dtype=np.float32)
    y = X @ w_true
    # each process contributes ITS OWN shard of the global batch
    per = 16 // nproc
    X_local, y_local = X[pid * per:(pid + 1) * per], \
        y[pid * per:(pid + 1) * per]
    xs = NamedSharding(mesh, P("dp"))
    rs = NamedSharding(mesh, P())
    Xg = jax.make_array_from_process_local_data(xs, X_local)
    yg = jax.make_array_from_process_local_data(xs, y_local)

    @jax.jit
    def step(w, Xg, yg):
        # mean over the GLOBAL batch: GSPMD inserts the cross-process
        # all-reduce for the contraction over the dp-sharded axis
        def loss(w):
            return jnp.mean((Xg @ w - yg) ** 2)
        g = jax.grad(loss)(w)
        return w - 0.05 * g

    w = jax.device_put(jnp.zeros((5,), jnp.float32), rs)
    for _ in range(steps):
        w = step(w, Xg, yg)
    final = np.asarray(jax.device_get(w))
    print("FINAL_W", " ".join(f"{v:.6f}" for v in final), flush=True)
    loss = float(np.mean((X @ final - y) ** 2))
    print("FINAL_LOSS", f"{loss:.6f}", flush=True)
    mx.distributed.barrier()
    print("BARRIER_OK", flush=True)
    mx.distributed.shutdown()
    print("SHUTDOWN_OK", flush=True)


if __name__ == "__main__":
    main()
