"""mxtpu.io pipeline (PR 17) — the staged host ingest engine:

* shard_keys / ShardedRecordReader: disjoint deterministic rank shards,
  decode hook, reset()/cycle, io.records_read telemetry;
* Pipeline order determinism: batch order is bit-identical to the
  serial reader at any worker count, even when a slow transform
  scrambles decode completion order;
* resume cursor x decode pool: skip=N through a 4-worker pool yields
  exactly the serial tail — the data-cursor contract resilience resumes
  depend on;
* per-stage counters (io.read_ms / decode_ms / stage_ms / put_ms) and
  the io.workers gauge;
* error propagation from every stage (source, transform) to next();
* the transfer gate + deferred-put safety model: on XLA:CPU no pipeline
  worker thread may issue an XLA call while donating executions run —
  the loaded stress test that pins the PR 14 1-in-3 segfault fix;
* trace_check.check_io_extra schema validation for the extra.io BENCH
  section the smoke gates on.
"""
import os
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, recordio
from incubator_mxnet_tpu import profiler as prof
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.io import DevicePrefetcher
from incubator_mxnet_tpu.io.pipeline import (Pipeline, ShardedRecordReader,
                                             TRANSFER_GATE, transfer_gate)
from incubator_mxnet_tpu.profiler import counters

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
from trace_check import check_io_extra  # noqa: E402


def _np_batches(n, batch=4, dim=3, seed=0):
    """Deterministic numpy (x, y) pairs; x[0,0] encodes the batch index
    so order assertions are cheap."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        x = rng.randn(batch, dim).astype(np.float32)
        x[0, 0] = float(i)
        out.append((x, np.full((batch,), i, np.float32)))
    return out


def _order(pf):
    """Consume a prefetcher fully, returning the batch-index trace
    encoded in x[0,0] (chunk mode: x has a leading chunk axis)."""
    seen = []
    for x, _y in pf:
        x = np.asarray(x)
        if x.ndim == 3:                      # chunked: (k, batch, dim)
            seen.extend(int(v) for v in x[:, 0, 0])
        else:
            seen.append(int(x[0, 0]))
    return seen


# ---------------------------------------------------------------------------
# sharded record reader
# ---------------------------------------------------------------------------

def _write_rec(tmp_path, n=10):
    idx = str(tmp_path / "t.idx")
    rec = str(tmp_path / "t.rec")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        payload = recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                                np.full((4,), i, np.int32).tobytes())
        w.write_idx(i, payload)
    w.close()
    return idx, rec


class TestShardKeys:
    def test_disjoint_and_complete(self):
        keys = list(range(103))
        shards = [recordio.shard_keys(keys, r, 4) for r in range(4)]
        flat = sorted(k for s in shards for k in s)
        assert flat == keys                       # complete, no dupes
        sizes = sorted(len(s) for s in shards)
        assert sizes[-1] - sizes[0] <= 1          # within one record

    def test_pure_function_of_inputs(self):
        keys = list(range(20))
        assert recordio.shard_keys(keys, 2, 4) \
            == recordio.shard_keys(keys, 2, 4) == keys[2::4]

    def test_bad_geometry_rejected(self):
        with pytest.raises(ValueError, match="num_ranks"):
            recordio.shard_keys([1], 0, 0)
        with pytest.raises(ValueError, match="rank"):
            recordio.shard_keys([1], 3, 2)


class TestShardedRecordReader:
    def test_roundtrip_and_decode(self, tmp_path):
        idx, rec = _write_rec(tmp_path, n=10)

        def decode(payload):
            hdr, s = recordio.unpack(payload)
            return np.frombuffer(s, np.int32).copy(), hdr.label

        with ShardedRecordReader(idx, rec, decode_fn=decode) as rd:
            assert len(rd) == 10
            rows = list(rd)
        assert [int(lbl) for _, lbl in rows] == list(range(10))
        assert all((row == int(lbl)).all() for row, lbl in rows)

    def test_shards_disjoint_deterministic(self, tmp_path):
        idx, rec = _write_rec(tmp_path, n=11)

        def ids(rank, num):
            with ShardedRecordReader(idx, rec, rank=rank,
                                     num_ranks=num) as rd:
                return [recordio.unpack(p)[0].id for p in rd]

        per_rank = [ids(r, 3) for r in range(3)]
        assert sorted(i for s in per_rank for i in s) == list(range(11))
        assert per_rank == [ids(r, 3) for r in range(3)]   # replayable

    def test_reset_and_counters(self, tmp_path):
        idx, rec = _write_rec(tmp_path, n=6)
        base = counters().get("io/io.records_read", 0)
        with ShardedRecordReader(idx, rec, rank=1, num_ranks=2) as rd:
            first = list(rd)
            rd.reset()
            assert list(rd) == first
        assert counters()["io/io.records_read"] == base + 2 * len(first)
        c = counters()
        assert c["io/io.shard_rank"] == 1
        assert c["io/io.shard_ranks"] == 2

    def test_empty_index_rejected(self, tmp_path):
        idx = str(tmp_path / "e.idx")
        rec = str(tmp_path / "e.rec")
        recordio.MXIndexedRecordIO(idx, rec, "w").close()
        with pytest.raises(ValueError, match="no index"):
            ShardedRecordReader(idx, rec)


# ---------------------------------------------------------------------------
# pipeline ordering + cursor semantics
# ---------------------------------------------------------------------------

class TestPipelineOrder:
    def test_order_matches_serial_any_worker_count(self):
        data = _np_batches(24)
        gold = _order(DevicePrefetcher(iter(data), depth=2, workers=1))
        for w in (2, 4):
            got = _order(DevicePrefetcher(iter(data), depth=2, workers=w))
            assert got == gold == list(range(24))

    def test_order_pinned_under_scrambled_completion(self):
        # a transform whose latency DECREASES with batch index makes
        # later chunks finish decode first — the staging ring must
        # still emit in sequence order
        data = _np_batches(12)

        def slow(x, y):
            time.sleep(0.03 * max(0.0, 6.0 - float(x[0, 0]) / 2))
            return x, y

        got = _order(DevicePrefetcher(iter(data), depth=2, workers=4,
                                      transform=slow))
        assert got == list(range(12))

    def test_chunk_stacking_order(self):
        data = _np_batches(12)
        got = _order(DevicePrefetcher(iter(data), depth=2, chunk=3,
                                      workers=4))
        assert got == list(range(12))

    def test_skip_cursor_parity_with_serial(self):
        # the resume contract: skip=N through a pool == serial tail
        data = _np_batches(20)
        for skip in (0, 3, 7):
            serial = _order(DevicePrefetcher(iter(data), depth=1,
                                             workers=1, skip=skip))
            pooled = _order(DevicePrefetcher(iter(data), depth=3,
                                             workers=4, skip=skip))
            assert pooled == serial == list(range(skip, 20))

    def test_cycling_skip_folds_under_pool(self):
        # absolute cursor 25 through a 10-batch cycling source folds to
        # epoch position 5 — same as the serial reader's fold
        data = _np_batches(10)

        class Src:
            def __iter__(self):
                return iter(list(data))

        out = []
        with DevicePrefetcher(Src(), depth=2, workers=4, cycle=True,
                              skip=25) as pf:
            for x, _ in pf:
                out.append(int(np.asarray(x)[0, 0]))
                if len(out) == 7:
                    break
        assert out == [5, 6, 7, 8, 9, 0, 1]

    def test_transform_error_surfaces_at_next(self):
        data = _np_batches(6)

        def boom(x, y):
            if int(x[0, 0]) == 3:
                raise RuntimeError("decode exploded")
            return x, y

        pf = DevicePrefetcher(iter(data), depth=2, workers=4,
                              transform=boom)
        with pytest.raises(RuntimeError, match="decode exploded"):
            _order(pf)

    def test_workers_knob_resolution_and_floor(self, monkeypatch):
        monkeypatch.setenv("MXTPU_IO_WORKERS", "3")
        with DevicePrefetcher(iter(_np_batches(2)), depth=1) as pf:
            assert pf._workers == 3
        # call-site beats env
        with DevicePrefetcher(iter(_np_batches(2)), depth=1,
                              workers=1) as pf:
            assert pf._workers == 1
        with pytest.raises(ValueError, match="workers"):
            DevicePrefetcher(iter(_np_batches(2)), depth=1, workers=0)

    def test_stage_counters_accumulate(self):
        keys = ("io/io.read_ms", "io/io.decode_ms", "io/io.stage_ms",
                "io/io.put_ms")
        base = {k: counters().get(k, 0) for k in keys}

        def slow(x, y):
            time.sleep(0.005)
            return x, y

        _order(DevicePrefetcher(iter(_np_batches(8)), depth=2, workers=2,
                                transform=slow))
        c = counters()
        assert c["io/io.workers"] == 2
        # decode wall must register the injected 5 ms x 8 batches
        assert c["io/io.decode_ms"] - base["io/io.decode_ms"] > 20
        for k in keys:
            assert c[k] >= base[k]

    def test_close_midstream_drains_and_joins(self):
        def slow_src():
            for b in _np_batches(100):
                time.sleep(0.002)
                yield b

        pf = DevicePrefetcher(slow_src(), depth=3, workers=4)
        next(pf)
        pf.close()
        assert pf._buf.qsize() == 0
        assert not any(t.is_alive() for t in pf._threads)
        pf.close()                               # idempotent


# ---------------------------------------------------------------------------
# transfer-gate / deferred-put safety model (the PR 14 segfault pin)
# ---------------------------------------------------------------------------

class TestTransferSafety:
    def test_gate_is_process_wide_lock(self):
        assert transfer_gate() is TRANSFER_GATE
        from incubator_mxnet_tpu.parallel import trainer_step
        assert trainer_step._TRANSFER_GATE is TRANSFER_GATE

    @pytest.mark.skipif(jax.default_backend() != "cpu",
                        reason="deferred-put model is CPU-only")
    def test_no_xla_calls_off_consumer_thread_on_cpu(self, monkeypatch):
        # the safety invariant itself: on XLA:CPU every device_put the
        # pipeline issues must run on the CONSUMER's thread (the one
        # that also dispatches), never on a pipeline worker
        put_threads = set()
        real_put = jax.device_put

        def spy(x, *a, **k):
            put_threads.add(threading.current_thread().name)
            return real_put(x, *a, **k)

        # pipeline.py imports jax lazily inside _to_device, so patching
        # the module attribute covers every pipeline call site
        monkeypatch.setattr(jax, "device_put", spy)
        consumer = threading.current_thread().name
        got = _order(DevicePrefetcher(iter(_np_batches(8)), depth=2,
                                      workers=4))
        assert got == list(range(8))
        assert put_threads == {consumer}, \
            f"device_put leaked onto pipeline threads: {put_threads}"

    def test_loaded_donation_stress(self):
        # the regression pin for the PR 14 1-in-3 flake: donating
        # executions dispatched back-to-back while the 4-worker
        # pipeline churns — under the old off-thread device_put this
        # segfaulted XLA:CPU within a few hundred steps
        mx.random.seed(0)
        np.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="relu"), nn.Dense(1))
        net.initialize(init=mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.01}, kvstore=None)
        loop = mx.TrainLoop(net, gluon.loss.L2Loss(), tr, chunk=2,
                            io_workers=4, prefetch_depth=3)
        w = np.random.RandomState(7).randn(3, 1).astype(np.float32)
        data = [(x, (x @ w).astype(np.float32))
                for x, _ in _np_batches(60, batch=8)]
        losses = loop.fit(data, steps=40, cycle=True)
        assert len(losses) == 40
        assert np.isfinite(losses).all()


# ---------------------------------------------------------------------------
# extra.io schema (trace_check.check_io_extra)
# ---------------------------------------------------------------------------

def _good_io():
    return {"workers": 4, "depth": 2, "batches_prefetched": 24,
            "wait_ms": 1.5, "read_ms": 0.2, "decode_ms": 480.0,
            "stage_ms": 3.0, "put_ms": 12.0, "batches_skipped": 0,
            "records_read": 96, "slow_ms": 20.0}


class TestCheckIoExtra:
    def test_absent_ok_and_good_ok(self):
        assert check_io_extra(None) == []
        assert check_io_extra(_good_io()) == []

    def test_optional_keys_optional(self):
        io = _good_io()
        for k in ("batches_skipped", "records_read", "slow_ms"):
            io.pop(k)
        assert check_io_extra(io) == []

    @pytest.mark.parametrize("mutate, frag", [
        (lambda d: d.pop("workers"), "workers"),
        (lambda d: d.update(workers=0), "workers"),
        (lambda d: d.update(depth=True), "depth"),
        (lambda d: d.pop("wait_ms"), "wait_ms"),
        (lambda d: d.update(decode_ms=-1), "decode_ms"),
        (lambda d: d.update(slow_ms="20"), "slow_ms"),
    ])
    def test_bad_shapes_rejected(self, mutate, frag):
        io = _good_io()
        mutate(io)
        errs = check_io_extra(io)
        assert errs and any(frag in e for e in errs), errs

    def test_non_dict_rejected(self):
        assert check_io_extra([1, 2]) == \
            ["must be an object, got list"]
