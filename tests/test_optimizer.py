"""Optimizer update rules vs hand-computed NumPy (parity: test_optimizer.py)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, optimizer as opt
from incubator_mxnet_tpu.optimizer import lr_scheduler as lrs


def assert_close(a, b, rtol=1e-5, atol=1e-6):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


def run_steps(o, w0, grads):
    w = nd.array(np.array(w0, np.float32))
    state = o.create_state_multi_precision(0, w._data)
    for g in grads:
        state = o.update(0, w, nd.array(np.array(g, np.float32)), state)
    return w.asnumpy()


def test_sgd():
    w = run_steps(opt.create("sgd", learning_rate=0.1), [1.0], [[1.0], [1.0]])
    assert_close(w, [0.8])


def test_sgd_momentum_wd():
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9, wd=0.1)
    w = np.array([1.0]); m = np.zeros(1)
    ref = w.copy()
    for _ in range(3):
        g = np.array([0.5]) + 0.1 * ref
        m = 0.9 * m - 0.1 * g
        ref = ref + m
    got = run_steps(o, [1.0], [[0.5]] * 3)
    assert_close(got, ref, rtol=1e-5)


def test_adam():
    o = opt.create("adam", learning_rate=0.01)
    w = np.array([1.0]); m = np.zeros(1); v = np.zeros(1)
    ref = w.copy()
    for t in range(1, 4):
        g = np.array([2.0])
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mh = m / (1 - 0.9 ** t)
        vh = v / (1 - 0.999 ** t)
        ref = ref - 0.01 * mh / (np.sqrt(vh) + 1e-8)
        del t
    got = run_steps(o, [1.0], [[2.0]] * 3)
    assert_close(got, ref, rtol=1e-5)


def test_adamw_decoupled_wd():
    o = opt.create("adamw", learning_rate=0.01, wd=0.1)
    got = run_steps(o, [1.0], [[0.0]])
    # zero grad => update = -lr * wd * w only
    assert_close(got, [1.0 - 0.01 * 0.1 * 1.0], rtol=1e-6)


def test_adagrad():
    o = opt.create("adagrad", learning_rate=0.1)
    got = run_steps(o, [1.0], [[2.0], [2.0]])
    h1 = 4.0
    w1 = 1.0 - 0.1 * 2 / (np.sqrt(h1) + 1e-7)
    h2 = 8.0
    w2 = w1 - 0.1 * 2 / (np.sqrt(h2) + 1e-7)
    assert_close(got, [w2], rtol=1e-5)


def test_rmsprop():
    o = opt.create("rmsprop", learning_rate=0.01, gamma1=0.9)
    got = run_steps(o, [1.0], [[1.0]])
    n = 0.1
    assert_close(got, [1.0 - 0.01 / (np.sqrt(n) + 1e-8)], rtol=1e-5)


def test_lamb_runs():
    o = opt.create("lamb", learning_rate=0.01)
    got = run_steps(o, [1.0, 2.0], [[0.1, 0.2]] * 2)
    assert got.shape == (2,)
    assert np.all(np.isfinite(got))


def test_clip_and_rescale():
    o = opt.create("sgd", learning_rate=1.0, rescale_grad=0.5, clip_gradient=0.4)
    got = run_steps(o, [1.0], [[2.0]])  # 2*0.5=1 -> clip 0.4 -> w=0.6
    assert_close(got, [0.6])


def test_multi_precision():
    o = opt.create("sgd", learning_rate=0.1, multi_precision=True)
    w = nd.array(np.array([1.0], np.float32)).astype("bfloat16")
    state = o.create_state_multi_precision(0, w._data)
    assert state[0].dtype == np.float32  # master weights
    state = o.update(0, w, nd.array([0.001]).astype("bfloat16"), state)
    # master tracks small updates below bf16 resolution
    assert float(state[0][0]) < 1.0


def test_nag():
    o = opt.create("nag", learning_rate=0.1, momentum=0.9)
    got = run_steps(o, [1.0], [[1.0]])
    # m=-0.1; w = 1 + 0.9*(-0.1) - 0.1 = 0.81
    assert_close(got, [0.81], rtol=1e-5)


def test_registry_create():
    for name in ["sgd", "nag", "adam", "adamw", "adagrad", "adadelta",
                 "rmsprop", "ftrl", "lamb", "signum", "dcasgd", "sgld"]:
        o = opt.create(name)
        assert isinstance(o, opt.Optimizer)


def test_lr_schedulers():
    s = lrs.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(1) == 1.0
    assert s(11) == 0.5
    m = lrs.MultiFactorScheduler(step=[5, 10], factor=0.1, base_lr=1.0)
    assert m(1) == 1.0
    assert abs(m(6) - 0.1) < 1e-9
    assert abs(m(11) - 0.01) < 1e-9
    p = lrs.PolyScheduler(max_update=100, base_lr=1.0, pwr=1)
    assert abs(p(50) - 0.5) < 1e-6
    c = lrs.CosineScheduler(max_update=100, base_lr=1.0)
    assert abs(c(50) - 0.5) < 1e-6
    assert c(100) == 0.0
    w = lrs.CosineScheduler(max_update=100, base_lr=1.0, warmup_steps=10)
    assert w(5) == 0.5  # linear warmup
    # reference semantics: Optimizer.__init__ overrides the scheduler's
    # base_lr with its learning_rate (default 0.01) — the scheduler's own
    # base_lr only matters when the scheduler is used standalone
    o = opt.create("sgd",
                   lr_scheduler=lrs.FactorScheduler(step=10, base_lr=2.0))
    assert o.learning_rate == 0.01
    o2 = opt.create("sgd", learning_rate=2.0,
                    lr_scheduler=lrs.FactorScheduler(step=10))
    assert o2.learning_rate == 2.0


def test_optimizer_with_scheduler_in_trainer():
    from incubator_mxnet_tpu import autograd, gluon
    w = gluon.Parameter("w", shape=(1,), init="ones")
    w.initialize()
    sched = lrs.FactorScheduler(step=1, factor=0.1, base_lr=1.0)
    tr = gluon.Trainer({"w": w}, "sgd", {"lr_scheduler": sched, "learning_rate": 1.0})
    with autograd.record():
        (w.data() * 1.0).sum().backward()
    tr.step(1)
    assert np.isfinite(w.data().asnumpy()).all()


def test_adamax():
    o = opt.create("adamax", learning_rate=0.002)
    w = np.array([1.0]); m = np.zeros(1); u = np.zeros(1)
    ref = w.copy()
    for t in range(1, 4):
        g = np.array([2.0])
        m = 0.9 * m + 0.1 * g
        u = np.maximum(0.999 * u, np.abs(g))
        ref = ref - (0.002 / (1 - 0.9 ** t)) * m / (u + 1e-8)
    got = run_steps(o, [1.0], [[2.0]] * 3)
    assert_close(got, ref, rtol=1e-5)


def test_nadam():
    o = opt.create("nadam", learning_rate=0.001)
    b1, b2, sd, eps = 0.9, 0.999, 0.004, 1e-8
    w = np.array([1.0]); m = np.zeros(1); v = np.zeros(1); msch = 1.0
    ref = w.copy()
    for t in range(1, 5):
        g = np.array([0.7])
        mt = b1 * (1 - 0.5 * 0.96 ** (t * sd))
        mt1 = b1 * (1 - 0.5 * 0.96 ** ((t + 1) * sd))
        msch = msch * mt
        msch_next = msch * mt1
        gp = g / (1 - msch)
        m = b1 * m + (1 - b1) * g
        mp = m / (1 - msch_next)
        v = b2 * v + (1 - b2) * g * g
        vp = v / (1 - b2 ** t)
        mbar = (1 - mt) * gp + mt1 * mp
        ref = ref - 0.001 * mbar / (np.sqrt(vp) + eps)
    got = run_steps(o, [1.0], [[0.7]] * 4)
    assert_close(got, ref, rtol=1e-5)


def test_ftml():
    o = opt.create("ftml", learning_rate=0.0025)
    b1, b2, eps, lr = 0.6, 0.999, 1e-8, 0.0025
    w = np.array([1.0]); d = np.zeros(1); v = np.zeros(1); z = np.zeros(1)
    ref = w.copy()
    for t in range(1, 4):
        g = np.array([1.5])
        v = b2 * v + (1 - b2) * g * g
        d_t = (1 - b1 ** t) / lr * (np.sqrt(v / (1 - b2 ** t)) + eps)
        sigma = d_t - b1 * d
        z = b1 * z + (1 - b1) * g - sigma * ref
        ref = -z / d_t
        d = d_t
    got = run_steps(o, [1.0], [[1.5]] * 3)
    assert_close(got, ref, rtol=1e-5)


def test_lars_trust_ratio():
    o = opt.create("lars", learning_rate=0.1, momentum=0.0, eta=0.001,
                   wd=0.01)
    w0 = np.array([3.0, 4.0])            # ||w|| = 5
    g0 = np.array([0.6, 0.8])            # ||g|| = 1
    trust = 0.001 * 5.0 / (1.0 + 0.01 * 5.0 + 1e-9)
    ref = w0 - trust * 0.1 * (g0 + 0.01 * w0)
    got = run_steps(o, w0, [g0])
    assert_close(got, ref, rtol=1e-5)


def test_lars_zero_grad_trust_is_one():
    o = opt.create("lars", learning_rate=0.1, momentum=0.0)
    got = run_steps(o, [2.0], [[0.0]])
    assert_close(got, [2.0])


def test_optimizer_learning_rate_becomes_scheduler_base():
    """Parity: Optimizer.__init__ sets lr_scheduler.base_lr to the given
    learning_rate (python/mxnet/optimizer/optimizer.py), so
    create('sgd', learning_rate=0.2, lr_scheduler=FactorScheduler(...))
    starts at 0.2, not the scheduler's default base."""
    from incubator_mxnet_tpu.optimizer import lr_scheduler
    opt = mx.optimizer.create(
        "sgd", learning_rate=0.2,
        lr_scheduler=lr_scheduler.FactorScheduler(step=2, factor=0.5))
    opt.num_update = 1
    assert abs(opt.learning_rate - 0.2) < 1e-9
    opt.num_update = 3
    assert abs(opt.learning_rate - 0.1) < 1e-9
