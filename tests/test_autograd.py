"""Tape autograd semantics (parity model: tests/python/unittest/test_autograd.py)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, nd


def assert_close(a, b, rtol=1e-5, atol=1e-6):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_close(x.grad.asnumpy(), [2, 4, 6])


def test_shared_input():
    w = nd.array([2.0, 3.0])
    w.attach_grad()
    with autograd.record():
        y = (w * w * w).sum()
    y.backward()
    assert_close(w.grad.asnumpy(), 3 * np.array([2.0, 3.0]) ** 2)


def test_multi_leaf():
    a = nd.array([1.0, 2.0]); a.attach_grad()
    b = nd.array([3.0, 4.0]); b.attach_grad()
    with autograd.record():
        y = (a * b + a).sum()
    y.backward()
    assert_close(a.grad.asnumpy(), [4, 5])
    assert_close(b.grad.asnumpy(), [1, 2])


def test_head_grads():
    x = nd.array([1.0, 2.0]); x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(nd.array([10.0, 20.0]))
    assert_close(x.grad.asnumpy(), [30, 60])


def test_is_recording_training():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        with autograd.predict_mode():
            assert autograd.is_recording()
            assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()
        assert not autograd.is_recording()


def test_pause_stops_tape():
    x = nd.array([1.0]); x.attach_grad()
    with autograd.record():
        y = x * 2
        with autograd.pause():
            z = y * 100  # not recorded
        w = y * 3
    w.backward()
    assert_close(x.grad.asnumpy(), [6.0])


def test_grad_function():
    x = nd.array([3.0]); x.attach_grad()
    with autograd.record():
        y = x * x
    g = autograd.grad(y, x)
    assert_close(g.asnumpy(), [6.0])
    assert x.grad.asnumpy()[0] == 0.0  # .grad untouched by grad()


def test_higher_order():
    x = nd.array([2.0]); x.attach_grad()
    with autograd.record():
        y = x * x * x
        g1 = autograd.grad(y, x, create_graph=True)  # 3x^2
        g2 = autograd.grad(g1, x, create_graph=True)  # 6x
    assert_close(g1.asnumpy(), [12.0])
    assert_close(g2.asnumpy(), [12.0])


def test_detach():
    x = nd.array([2.0]); x.attach_grad()
    with autograd.record():
        y = x * 2
        z = y.detach() * x
    z.backward()
    assert_close(x.grad.asnumpy(), [4.0])  # detach blocks the y path


def test_grad_req_add():
    x = nd.array([1.0]); x.attach_grad(grad_req="add")
    for _ in range(2):
        with autograd.record():
            (x * 3).backward()
    assert_close(x.grad.asnumpy(), [6.0])
    x.attach_grad()  # reset to write
    with autograd.record():
        (x * 3).backward()
    assert_close(x.grad.asnumpy(), [3.0])


def test_grad_through_reshape_indexing():
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3)); x.attach_grad()
    with autograd.record():
        y = x.reshape(3, 2)[1:].sum()
    y.backward()
    assert_close(x.grad.asnumpy(), [[0, 0, 1], [1, 1, 1]])


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self._saved
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0]); x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-np.array([0.0, 1.0])))
    assert_close(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_mark_variables():
    x = nd.array([2.0])
    g = nd.zeros(1)
    autograd.mark_variables([x], [g])
    with autograd.record():
        (x * 5).backward()
    assert_close(g.asnumpy(), [5.0])


def test_backward_through_concat_split():
    a = nd.ones((2, 2)); a.attach_grad()
    b = nd.ones((2, 2)); b.attach_grad()
    with autograd.record():
        c = nd.concat(a * 2, b * 3, dim=0)
        p, q = nd.split(c, 2, axis=0)
        (p.sum() + 2 * q.sum()).backward()
    assert_close(a.grad.asnumpy(), np.full((2, 2), 2.0))
    assert_close(b.grad.asnumpy(), np.full((2, 2), 6.0))


def test_get_symbol_lifts_tape_to_symbol():
    """Parity: mx.autograd.get_symbol — imperative trace -> Symbol with
    identical forward values and gradients."""
    x = nd.array(np.random.RandomState(0).randn(4, 3).astype(np.float32))
    w = nd.array(np.random.RandomState(1).randn(3, 2).astype(np.float32))
    w.attach_grad()
    x.attach_grad()
    with autograd.record():
        y = nd.dot(x, w)
        z = (nd.tanh(y) + 1.0).sum()
    z.backward()
    tape_gw = w.grad.asnumpy().copy()

    s = autograd.get_symbol(z)
    args = s.list_arguments()
    assert args == ["var0", "var1"]
    ex = s.bind(args={args[0]: x.asnumpy(), args[1]: w.asnumpy()},
                args_grad={args[1]: np.zeros_like(w.asnumpy())},
                grad_req={args[0]: "null", args[1]: "write"})
    v = ex.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(v, z.asnumpy(), rtol=1e-6)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict[args[1]].asnumpy(), tape_gw,
                               rtol=1e-5)


def test_get_symbol_bakes_constants_and_reuses_leaves():
    """Non-leaf constants captured by the trace are baked into the graph;
    a leaf used twice maps to ONE Variable."""
    a = nd.array(np.array([1.0, 2.0], np.float32))
    a.attach_grad()
    c = nd.array(np.array([10.0, 20.0], np.float32))   # no grad: constant
    with autograd.record():
        out = a * a + c
    s = autograd.get_symbol(out)
    assert s.list_arguments() == ["var0"]              # a appears once
    ex = s.bind(args={"var0": np.array([3.0, 4.0], np.float32)},
                grad_req="null")
    np.testing.assert_allclose(ex.forward()[0].asnumpy(),
                               [19.0, 36.0])           # 9+10, 16+20


def test_get_symbol_requires_recorded_array():
    import pytest
    plain = nd.array(np.ones(3, np.float32))
    with pytest.raises(ValueError, match="record"):
        autograd.get_symbol(plain)
    with pytest.raises(TypeError):
        autograd.get_symbol(np.ones(3))


def test_get_symbol_deep_tape_no_recursion_error():
    """Eager-loop tapes run thousands of ops deep; lifting and executing
    must not hit Python's recursion limit."""
    y = nd.array(np.zeros(2, np.float32))
    y.attach_grad()
    with autograd.record():
        out = y
        for _ in range(1500):
            out = out + 1.0
    s = autograd.get_symbol(out)
    ex = s.bind(args={"var0": np.zeros(2, np.float32)}, grad_req="null")
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), 1500.0)


def test_get_symbol_rejects_custom_function():
    import pytest

    class Double(autograd.Function):
        def forward(self, x):
            return x * 2

        def backward(self, dy):
            return dy * 2

    a = nd.array(np.ones(2, np.float32))
    a.attach_grad()
    with autograd.record():
        out = Double()(a) + 1.0
    with pytest.raises(ValueError, match="custom autograd.Function"):
        autograd.get_symbol(out)


def test_get_symbol_leaf_numbering_first_reach_order():
    """var numbering follows depth-first first-reach order from the
    output, even when a leaf's subtree lifts after a sibling subtree."""
    a = nd.array(np.array([1.0, 2.0], np.float32)); a.attach_grad()
    b = nd.array(np.array([3.0, 4.0], np.float32)); b.attach_grad()
    with autograd.record():
        out = a + nd.tanh(b * 2.0)      # DFS reaches `a` (input 0) first
    s = autograd.get_symbol(out)
    ex = s.bind(args={"var0": np.array([10.0, 20.0], np.float32),
                      "var1": np.array([0.0, 0.0], np.float32)},
                grad_req="null")
    # var0 must be `a`: tanh(0)=0, so out == the var0 values exactly
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [10.0, 20.0])
