"""Model zoo + fused/distributed train step (SURVEY.md §2.19, §2.22)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.models import get_model
from incubator_mxnet_tpu.parallel import FusedTrainStep, make_mesh


def test_resnet18_shapes():
    net = get_model("resnet18_v1", classes=10, layout="NHWC")
    net.initialize()
    out = net(nd.ones((2, 32, 32, 3)))
    assert out.shape == (2, 10)


def test_resnet_v2_shapes():
    net = get_model("resnet18_v2", classes=7, layout="NHWC")
    net.initialize()
    assert net(nd.ones((2, 32, 32, 3))).shape == (2, 7)


def test_resnet50_param_count():
    net = get_model("resnet50_v1", classes=1000, layout="NHWC")
    net.initialize()
    net(nd.ones((1, 64, 64, 3)))
    n_params = sum(int(np.prod(p.shape))
                   for p in net.collect_params().values()
                   if p.grad_req != "null")
    # reference ResNet-50 ~25.5M learnable params
    assert 25e6 < n_params < 26e6, n_params


def test_lenet_forward():
    net = get_model("lenet")
    net.initialize()
    assert net(nd.ones((4, 1, 28, 28))).shape == (4, 10)


def test_get_model_unknown():
    with pytest.raises(ValueError):
        get_model("resnet999")


def test_fused_step_single_device():
    np.random.seed(0)
    mx.random.seed(0)
    net = get_model("lenet")
    net.initialize()
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    step = FusedTrainStep(net, L, "adam")
    x = nd.array(np.random.randn(8, 1, 28, 28).astype(np.float32))
    y = nd.array(np.random.randint(0, 10, 8))
    l0 = float(step(x, y))
    for _ in range(25):
        l = float(step(x, y))
    assert l < l0 * 0.5


def test_fused_step_dp_mesh_matches_single():
    """dp-sharded fused step must equal the single-device step numerically."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    np.random.seed(0)
    mx.random.seed(0)
    x = nd.array(np.random.randn(16, 10).astype(np.float32))
    y = nd.array(np.random.randint(0, 3, 16))
    L = gluon.loss.SoftmaxCrossEntropyLoss()

    def train(mesh, steps=5):
        np.random.seed(1)
        mx.random.seed(1)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(3))
        net.initialize(init=mx.init.Xavier())
        step = FusedTrainStep(net, L, mx.optimizer.create(
            "sgd", learning_rate=0.1, momentum=0.9), mesh=mesh)
        losses = [float(step(x, y)) for _ in range(steps)]
        return losses

    single = train(None)
    dp = train(make_mesh({"dp": 8}))
    np.testing.assert_allclose(single, dp, rtol=2e-4, atol=1e-5)


def test_fused_step_batchnorm_aux():
    """BatchNorm running stats must update through the fused step."""
    np.random.seed(0)
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8), gluon.nn.BatchNorm())
    net.initialize()
    L = gluon.loss.L2Loss()
    step = FusedTrainStep(net, L, "sgd")
    x = nd.array(np.random.randn(16, 4).astype(np.float32) + 3)
    y = nd.array(np.random.randn(16, 8).astype(np.float32))
    step(x, y)
    bn = net[1]
    rm = bn.running_mean.data().asnumpy()
    assert np.abs(rm).max() > 0


def test_mesh_helpers():
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    m = make_mesh({"dp": 2, "tp": -1})
    assert m.shape["dp"] == 2 and m.shape["tp"] == 4
    with pytest.raises(ValueError):
        make_mesh({"dp": 64})


def test_fused_step_remat_matches_plain():
    """remat recomputes activations in backward; the math is identical."""
    from incubator_mxnet_tpu.parallel import FusedTrainStep

    def build():
        mx.random.seed(0)
        np.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu"),
                gluon.nn.Dense(8, activation="relu"),
                gluon.nn.Dense(3))
        net.initialize(init=mx.init.Xavier())
        return net

    x = nd.array(np.random.RandomState(0).randn(8, 6).astype(np.float32))
    y = nd.array(np.random.RandomState(1).randint(0, 3, 8))
    L = gluon.loss.SoftmaxCrossEntropyLoss()

    losses = {}
    for remat in (False, True):
        net = build()
        step = FusedTrainStep(net, L,
                              mx.optimizer.create("sgd", learning_rate=0.1),
                              remat=remat)
        losses[remat] = [float(step(x, y)) for _ in range(3)]
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-6)


def test_fused_step_zero1_state_sharding_matches():
    """ZeRO-1 optimizer-state sharding is a pure layout change: training
    matches the replicated-state run bit-for-bit (up to float assoc), and
    the momentum buffers really are sharded over dp."""
    import jax
    from jax.sharding import PartitionSpec as P

    def build():
        mx.random.seed(0)
        np.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(32, activation="relu", in_units=16),
                gluon.nn.Dense(8, in_units=32))
        net.initialize(init=mx.init.Xavier())
        return net

    x = nd.array(np.random.RandomState(0).randn(16, 16).astype(np.float32))
    y = nd.array(np.random.RandomState(1).randint(0, 8, 16))
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = make_mesh({"dp": 8})

    runs = {}
    steps = {}
    for zero1 in (False, True):
        net = build()
        step = FusedTrainStep(net, L,
                              mx.optimizer.create("sgd", learning_rate=0.1,
                                                  momentum=0.9),
                              mesh=mesh, shard_optimizer_states=zero1)
        runs[zero1] = [float(step(x, y)) for _ in range(3)]
        steps[zero1] = step
    np.testing.assert_allclose(runs[True], runs[False], rtol=1e-5)
    # a (32,16)-shaped momentum is actually sharded over the 8-way dp axis
    sharded = [s for st in steps[True]._states for s in st
               if hasattr(s, "sharding") and np.shape(s)
               and np.shape(s)[0] % 8 == 0]
    assert any(s.sharding.spec != P() for s in sharded), \
        "no optimizer state ended up dp-sharded"
