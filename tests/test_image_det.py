"""ImageDetIter + detection augmenters (parity:
python/mxnet/image/detection.py) feeding SSD targets."""
import io as _io

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import image, nd


def _png_bytes(arr):
    from PIL import Image
    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


@pytest.fixture
def det_rec(tmp_path):
    """Synthetic detection record file: colored boxes on black."""
    from incubator_mxnet_tpu import recordio
    rec_path = str(tmp_path / "det.rec")
    idx_path = str(tmp_path / "det.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = np.random.RandomState(0)
    for i in range(8):
        img = np.zeros((48, 64, 3), np.uint8)
        cls = i % 3
        x0, y0 = rng.uniform(0.1, 0.4, 2)
        x1, y1 = x0 + 0.3, y0 + 0.4
        img[int(y0 * 48):int(y1 * 48), int(x0 * 64):int(x1 * 64), cls] = 255
        # reference det label: [header_w=2, obj_w=5, (cls,x0,y0,x1,y1)]
        label = [2, 5, float(cls), x0, y0, x1, y1]
        header = recordio.IRHeader(0, label, i, 0)
        rec.write_idx(i, recordio.pack(header, _png_bytes(img)))
    rec.close()
    return rec_path


def test_image_det_iter_shapes(det_rec):
    it = image.ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                            path_imgrec=det_rec)
    batch = next(iter(it))
    assert batch.data[0].shape == (4, 3, 32, 32)
    assert batch.label[0].shape[0] == 4 and batch.label[0].shape[2] == 5
    lab = batch.label[0].asnumpy()
    valid = lab[lab[:, :, 0] >= 0]
    assert len(valid) == 4                     # one object per image
    assert ((valid[:, 1:] >= 0) & (valid[:, 1:] <= 1)).all()


def test_det_flip_flips_boxes():
    aug = image.DetHorizontalFlipAug(p=1.0)
    img = np.zeros((10, 10, 3), np.uint8)
    label = np.array([[1.0, 0.1, 0.2, 0.4, 0.8],
                      [-1, -1, -1, -1, -1]], np.float32)
    img2, lab2 = aug(img, label)
    np.testing.assert_allclose(lab2[0], [1.0, 0.6, 0.2, 0.9, 0.8],
                               rtol=1e-6)
    assert (lab2[1] == -1).all()               # padding untouched


def test_det_random_crop_keeps_coverage():
    rng = np.random.RandomState(1)
    aug = image.DetRandomCropAug(min_object_covered=0.5,
                                 area_range=(0.5, 1.0))
    img = rng.randint(0, 255, (40, 40, 3)).astype(np.uint8)
    label = np.array([[2.0, 0.3, 0.3, 0.7, 0.7]], np.float32)
    for _ in range(10):
        img2, lab2 = aug(img, label)
        if (lab2[:, 0] >= 0).any():
            b = lab2[0]
            assert 0 <= b[1] <= b[3] <= 1 and 0 <= b[2] <= b[4] <= 1


def test_det_iter_feeds_ssd_targets(det_rec):
    """End-to-end: ImageDetIter batches flow into MultiBoxTarget."""
    it = image.ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                            path_imgrec=det_rec, rand_mirror=True)
    batch = next(iter(it))
    anchors = mx.nd.contrib.MultiBoxPrior(
        nd.zeros((1, 8, 8, 16)), sizes=[0.4, 0.6], ratios=[1, 2],
        layout="NHWC")
    A = anchors.shape[1]
    cls_pred = nd.zeros((4, 4, A))             # (B, C+1, A)
    bt, bm, ct = mx.nd.contrib.MultiBoxTarget(anchors, batch.label[0],
                                              cls_pred)
    assert ct.shape == (4, A)
    assert (ct.asnumpy() >= 0).any()           # some anchors matched


def test_det_iter_pads_last_batch(det_rec):
    it = image.ImageDetIter(batch_size=3, data_shape=(3, 32, 32),
                            path_imgrec=det_rec)   # 8 samples, bs 3
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 1


def test_det_augmenter_rejects_unknown_kwargs(det_rec):
    import pytest as _pytest
    with _pytest.raises(TypeError):
        image.ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                           path_imgrec=det_rec, rand_miror=True)  # typo


def test_det_iter_batch_larger_than_dataset(det_rec):
    it = image.ImageDetIter(batch_size=20, data_shape=(3, 32, 32),
                            path_imgrec=det_rec)    # only 8 samples
    batch = next(iter(it))
    assert batch.pad == 12
    assert np.isfinite(batch.data[0].asnumpy()).all()
    # wrapped rows repeat real samples, not uninitialized memory
    d = batch.data[0].asnumpy()
    np.testing.assert_allclose(d[8], d[0])


def test_io_image_det_record_iter(det_rec):
    """mx.io.ImageDetRecordIter: the io-namespace spelling routes to the
    same detection pipeline (label_pad_width counts floats like the
    reference)."""
    from incubator_mxnet_tpu import io as mio
    it = mio.ImageDetRecordIter(path_imgrec=det_rec, batch_size=4,
                                data_shape=(3, 32, 32),
                                label_pad_width=2 + 5 * 3)
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 32, 32)
    assert batch.label[0].shape == (4, 3, 5)
    lab = batch.label[0].asnumpy()
    assert ((lab[..., 0] >= -1) & (lab[..., 0] <= 2)).all()


def test_io_image_det_record_iter_rejects_small_pad(det_rec):
    """Insufficient label_pad_width raises instead of dropping boxes."""
    from incubator_mxnet_tpu import io as mio
    # records have 1 object but force max_objs=0 is impossible (min 1);
    # build a 2-object record set inline instead
    import numpy as np
    from incubator_mxnet_tpu import recordio
    import tempfile, os
    d = tempfile.mkdtemp()
    rec_path = os.path.join(d, "two.rec")
    rec = recordio.MXIndexedRecordIO(os.path.join(d, "two.idx"), rec_path, "w")
    img = np.zeros((32, 32, 3), np.uint8)
    label = [2, 5, 0.0, 0.1, 0.1, 0.5, 0.5, 1.0, 0.2, 0.2, 0.8, 0.8]
    rec.write_idx(0, recordio.pack(recordio.IRHeader(0, np.asarray(
        label, np.float32), 0, 0), _png_bytes(img)))
    rec.close()
    with pytest.raises(ValueError):
        mio.ImageDetRecordIter(path_imgrec=rec_path, batch_size=1,
                               data_shape=(3, 32, 32),
                               label_pad_width=2 + 5 * 1)  # fits only 1 obj
