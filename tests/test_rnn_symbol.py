"""mx.rnn symbol-API cell tests (mirrors reference
tests/python/unittest/test_rnn.py): cell unroll shapes/parity with the
gluon cells, FusedRNNCell vs unfused parity, modifier cells, Module
integration, plus the mx.contrib / namespace surface."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd, rnn
from incubator_mxnet_tpu import symbol as sym

B, T, I, H = 4, 5, 6, 8


def _bind_forward(out_syms, args):
    group = sym.Group(out_syms) if isinstance(out_syms, list) else out_syms
    ex = group.bind(args={k: np.asarray(v, np.float32)
                          for k, v in args.items()}, grad_req="null")
    return [o.asnumpy() for o in ex.forward()]


def _rand(shape, rng):
    return rng.randn(*shape).astype(np.float32) * 0.2


# ---------------------------------------------------------------------------
# cells: shapes + parity vs gluon
# ---------------------------------------------------------------------------

def test_rnn_cell_unroll_shapes():
    cell = rnn.RNNCell(H, prefix="rnn_")
    x = sym.Variable("x")
    outputs, states = cell.unroll(T, x, cell.begin_state(batch_size=B),
                                  layout="NTC", merge_outputs=True)
    rng = np.random.RandomState(0)
    outs = _bind_forward(outputs, {
        "x": _rand((B, T, I), rng),
        "rnn_i2h_weight": _rand((H, I), rng), "rnn_i2h_bias": np.zeros(H),
        "rnn_h2h_weight": _rand((H, H), rng), "rnn_h2h_bias": np.zeros(H)})
    assert outs[0].shape == (B, T, H)
    assert np.isfinite(outs[0]).all()


@pytest.mark.parametrize("mode", ["lstm", "gru"])
def test_cell_matches_gluon(mode):
    """Symbol cell unroll == gluon cell stepping with identical weights."""
    rng = np.random.RandomState(1)
    G = {"lstm": 4, "gru": 3}[mode]
    wi, bi = _rand((G * H, I), rng), _rand((G * H,), rng)
    wh, bh = _rand((G * H, H), rng), _rand((G * H,), rng)
    x = _rand((B, T, I), rng)

    cell = (rnn.LSTMCell(H, prefix="l0_") if mode == "lstm"
            else rnn.GRUCell(H, prefix="l0_"))
    outputs, _ = cell.unroll(T, sym.Variable("x"),
                             cell.begin_state(batch_size=B),
                             layout="NTC", merge_outputs=True)
    out = _bind_forward(outputs, {
        "x": x, "l0_i2h_weight": wi, "l0_i2h_bias": bi,
        "l0_h2h_weight": wh, "l0_h2h_bias": bh})[0]

    gcell = (gluon.rnn.LSTMCell(H, input_size=I) if mode == "lstm"
             else gluon.rnn.GRUCell(H, input_size=I))
    gcell.initialize()
    params = gcell.collect_params()
    for k, v in {"i2h_weight": wi, "i2h_bias": bi,
                 "h2h_weight": wh, "h2h_bias": bh}.items():
        [p for n, p in params.items() if n.endswith(k)][0].set_data(
            nd.array(v))
    states = gcell.begin_state(batch_size=B)
    gouts = []
    for t in range(T):
        o, states = gcell(nd.array(x[:, t]), states)
        gouts.append(o.asnumpy())
    np.testing.assert_allclose(out, np.stack(gouts, axis=1), rtol=2e-5,
                               atol=2e-5)


def test_fused_cell_matches_unfused():
    """FusedRNNCell (RNN op / lax.scan) == its unfuse() stack, with the
    packed parameter vector mapped onto the unfused weight names."""
    rng = np.random.RandomState(2)
    fused = rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="f_")
    x = _rand((B, T, I), rng)
    wi, wh = _rand((4 * H, I), rng), _rand((4 * H, H), rng)
    bi, bh = _rand((4 * H,), rng), _rand((4 * H,), rng)
    packed = np.concatenate([wi.ravel(), wh.ravel(), bi, bh])
    assert packed.size == fused.param_size(I)

    outputs, _ = fused.unroll(T, sym.Variable("x"),
                              fused.begin_state(batch_size=B),
                              layout="NTC", merge_outputs=True)
    fout = _bind_forward(outputs, {"x": x, "f_parameters": packed})[0]

    unfused = fused.unfuse()
    outputs2, _ = unfused.unroll(T, sym.Variable("x"),
                                 unfused.begin_state(batch_size=B),
                                 layout="NTC", merge_outputs=True)
    uout = _bind_forward(outputs2, {
        "x": x, "f_l0_i2h_weight": wi, "f_l0_i2h_bias": bi,
        "f_l0_h2h_weight": wh, "f_l0_h2h_bias": bh})[0]
    np.testing.assert_allclose(fout, uout, rtol=2e-5, atol=2e-5)


def test_sequential_and_residual_cells():
    rng = np.random.RandomState(3)
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.RNNCell(I, prefix="s0_"))   # same width for the residual
    stack.add(rnn.ResidualCell(rnn.RNNCell(I, prefix="s1_")))
    outputs, states = stack.unroll(T, sym.Variable("x"),
                                   stack.begin_state(batch_size=B),
                                   layout="NTC", merge_outputs=True)
    args = {"x": _rand((B, T, I), rng)}
    for p in ("s0_", "s1_"):
        args.update({f"{p}i2h_weight": _rand((I, I), rng),
                     f"{p}i2h_bias": np.zeros(I),
                     f"{p}h2h_weight": _rand((I, I), rng),
                     f"{p}h2h_bias": np.zeros(I)})
    out = _bind_forward(outputs, args)[0]
    assert out.shape == (B, T, I) and np.isfinite(out).all()


def test_residual_cell_is_sum():
    rng = np.random.RandomState(4)
    res = rnn.ResidualCell(rnn.RNNCell(I, prefix="r_"))
    base = rnn.RNNCell(I, prefix="r_")
    x = sym.Variable("x")
    weights = {"r_i2h_weight": _rand((I, I), rng), "r_i2h_bias": np.zeros(I),
               "r_h2h_weight": _rand((I, I), rng), "r_h2h_bias": np.zeros(I)}
    xval = _rand((B, T, I), rng)
    out_res, _ = res.unroll(T, x, res.begin_state(batch_size=B),
                            merge_outputs=True)
    vres = _bind_forward(out_res, dict(weights, x=xval))[0]
    out_base, _ = base.unroll(T, x, base.begin_state(batch_size=B),
                              merge_outputs=True)
    vbase = _bind_forward(out_base, dict(weights, x=xval))[0]
    np.testing.assert_allclose(vres, vbase + xval, rtol=1e-5, atol=1e-5)


def test_bidirectional_cell_shapes():
    rng = np.random.RandomState(5)
    bi = rnn.BidirectionalCell(rnn.GRUCell(H, prefix="fw_"),
                               rnn.GRUCell(H, prefix="bw_"))
    outputs, states = bi.unroll(T, sym.Variable("x"),
                                bi.begin_state(batch_size=B),
                                layout="NTC", merge_outputs=True)
    args = {"x": _rand((B, T, I), rng)}
    for p in ("fw_", "bw_"):
        args.update({f"{p}i2h_weight": _rand((3 * H, I), rng),
                     f"{p}i2h_bias": np.zeros(3 * H),
                     f"{p}h2h_weight": _rand((3 * H, H), rng),
                     f"{p}h2h_bias": np.zeros(3 * H)})
    out = _bind_forward(outputs, args)[0]
    assert out.shape == (B, T, 2 * H)
    assert len(states) == 2


def test_lstm_forget_bias_honored_by_module():
    """Variable(init=...) attr flows through Module.init_params: the i2h
    bias forget block comes up at forget_bias, everything else 0."""
    cell = rnn.LSTMCell(H, prefix="fb_", forget_bias=2.5)
    outputs, _ = cell.unroll(3, sym.Variable("data"),
                             cell.begin_state(batch_size=B),
                             merge_outputs=True)
    mod = mx.mod.Module(outputs, data_names=("data",), label_names=())
    mod.bind(data_shapes=[("data", (B, 3, I))])
    mod.init_params(initializer=mx.init.Zero())
    args, _ = mod.get_params()
    bias = args["fb_i2h_bias"].asnumpy()
    expect = np.zeros(4 * H, np.float32)
    expect[H:2 * H] = 2.5
    np.testing.assert_allclose(bias, expect)


def test_dropout_cell_inference_identity():
    cell = rnn.SequentialRNNCell()
    cell.add(rnn.DropoutCell(0.5, prefix="do_"))
    outputs, _ = cell.unroll(T, sym.Variable("x"), begin_state=[],
                             merge_outputs=True)
    rng = np.random.RandomState(6)
    x = _rand((B, T, I), rng)
    out = _bind_forward(outputs, {"x": x})[0]  # eval mode: identity
    np.testing.assert_allclose(out, x, rtol=1e-6)


# ---------------------------------------------------------------------------
# namespaces + sym.contrib parity
# ---------------------------------------------------------------------------

def test_namespace_aliases():
    assert mx.lr_scheduler.FactorScheduler is \
        mx.optimizer.lr_scheduler.FactorScheduler
    assert mx.executor.Executor is mx.symbol.executor.Executor
    assert mx.attribute.AttrScope is mx.AttrScope
    assert mx.contrib.nd is mx.nd.contrib
    assert mx.contrib.sym is mx.sym.contrib
    assert mx.util.is_np_shape() and mx.util.is_np_array()
    reg = mx.registry.get_register_func(object, "thing")
    create = mx.registry.get_create_func(object, "thing")

    class Thing:
        pass
    reg(Thing, "a_thing")
    assert isinstance(create("a_thing"), Thing)


def test_sym_contrib_multibox_matches_nd():
    rng = np.random.RandomState(7)
    feat = rng.randn(1, 8, 4, 6).astype(np.float32)
    s = sym.Variable("feat")
    prior_s = mx.sym.contrib.MultiBoxPrior(s, sizes=(0.4, 0.8),
                                           ratios=(1.0, 2.0))
    out = _bind_forward(prior_s, {"feat": feat})[0]
    ref = mx.nd.contrib.MultiBoxPrior(nd.array(feat), sizes=(0.4, 0.8),
                                      ratios=(1.0, 2.0)).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-6)

    anchors = ref                                     # (1, A, 4)
    A = anchors.shape[1]
    cls_prob = np.abs(rng.randn(2, 3, A).astype(np.float32))
    cls_prob /= cls_prob.sum(axis=1, keepdims=True)
    loc_pred = rng.randn(2, A * 4).astype(np.float32) * 0.1
    det_s = mx.sym.contrib.MultiBoxDetection(
        sym.Variable("cp"), sym.Variable("lp"), sym.Variable("anc"))
    det = _bind_forward(det_s, {"cp": cls_prob, "lp": loc_pred,
                                "anc": anchors})[0]
    dref = mx.nd.contrib.MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), nd.array(anchors)).asnumpy()
    np.testing.assert_allclose(det, dref, rtol=1e-5, atol=1e-6)


def test_sym_contrib_box_nms_matches_nd():
    rng = np.random.RandomState(8)
    boxes = np.abs(rng.rand(10, 6)).astype(np.float32)
    out = _bind_forward(mx.sym.contrib.box_nms(sym.Variable("b"),
                                               overlap_thresh=0.5),
                        {"b": boxes})[0]
    ref = mx.nd.contrib.box_nms(nd.array(boxes), overlap_thresh=0.5).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_sym_slice_and_elemwise():
    a = sym.Variable("a")
    out = sym.slice(a, begin=(1, 0), end=(3, 2))
    v = _bind_forward(out, {"a": np.arange(12).reshape(4, 3)})[0]
    np.testing.assert_array_equal(v, np.arange(12).reshape(4, 3)[1:3, 0:2])
    s = sym.elemwise_add(a, a)
    v2 = _bind_forward(s, {"a": np.ones((2, 2))})[0]
    np.testing.assert_allclose(v2, 2 * np.ones((2, 2)))


def test_multibox_prior_clip():
    feat = np.zeros((1, 4, 2, 2), np.float32)
    unclipped = mx.nd.contrib.MultiBoxPrior(nd.array(feat),
                                            sizes=(1.4,)).asnumpy()
    clipped = mx.nd.contrib.MultiBoxPrior(nd.array(feat), sizes=(1.4,),
                                          clip=True).asnumpy()
    assert unclipped.min() < 0 and clipped.min() >= 0 and clipped.max() <= 1


def test_variable_shape_and_init_attrs_flow_to_module():
    """Variable(shape=..., init=<instance>) participates in shape inference
    and Module.init_params recreates the initializer with its params."""
    x = sym.Variable("data")
    w = sym.Variable("w", shape=(I, 4), init=mx.init.Constant(5.0))
    out = sym.dot(x, w)
    mod = mx.mod.Module(out, data_names=("data",), label_names=())
    mod.bind(data_shapes=[("data", (B, I))], label_shapes=None)
    mod.init_params()
    args, _ = mod.get_params()
    np.testing.assert_allclose(args["w"].asnumpy(), 5.0)


def test_registry_shares_builtin_registries():
    create = mx.registry.get_create_func(mx.optimizer.Optimizer, "optimizer")
    o = create("sgd", learning_rate=0.5)
    assert isinstance(o, mx.optimizer.SGD) and o.learning_rate == 0.5


@pytest.mark.parametrize("op,args,kwargs", [
    ("ceil", 1, {}), ("floor", 1, {}), ("rint", 1, {}),
    ("gamma", 1, {}), ("log1p", 1, {}), ("arctanh", 1, {}),
    ("softsign", 1, {}), ("hypot", 2, {}), ("arctan2", 2, {}),
    ("tile", 1, {"reps": (2, 1)}), ("repeat", 1, {"repeats": 2, "axis": 1}),
    ("swapaxes", 1, {"a1": 0, "a2": 1}), ("diag", 1, {"k": 0}),
    ("cast", 1, {"dtype": "float16"}),
    ("one_hot", 1, {"depth": 5}),
    ("nansum", 1, {"axis": 1}), ("argmin", 1, {"axis": 1}),
    ("norm", 1, {"axis": 1}), ("sort", 1, {"axis": -1, "is_ascend": False}),
    ("argsort", 1, {"axis": -1}),
    ("topk", 1, {"k": 2, "ret_typ": "value"}),
])
def test_sym_nd_mirror_parity(op, args, kwargs):
    """sym.<op> executes the nd implementation: outputs must be identical."""
    rng = np.random.RandomState(11)
    if op == "one_hot":
        vals = [rng.randint(0, 5, (3, 4)).astype(np.float32)]
    else:
        vals = [np.abs(rng.randn(3, 4)).astype(np.float32) * 0.8 + 0.1
                for _ in range(args)]
    syms = [sym.Variable(f"in{i}") for i in range(args)]
    out_sym = getattr(sym, op)(*syms, **kwargs)
    got = _bind_forward(out_sym, {f"in{i}": v for i, v in enumerate(vals)})[0]
    want = getattr(nd, op)(*[nd.array(v) for v in vals], **kwargs)
    want = want[0] if isinstance(want, (list, tuple)) else want
    np.testing.assert_allclose(got, want.asnumpy(), rtol=1e-6, atol=1e-6)


def test_sym_mirror_keyword_inputs():
    """Mirror builders accept keyword Symbol inputs like hand-written ones."""
    x = sym.Variable("x")
    out = sym.ceil(data=x)
    assert out.list_arguments() == ["x"]
    v = _bind_forward(out, {"x": np.array([[1.2, 2.7]], np.float32)})[0]
    np.testing.assert_allclose(v, [[2.0, 3.0]])
    out2 = sym.take(sym.Variable("a"), indices=sym.Variable("i"), axis=0)
    assert out2.list_arguments() == ["a", "i"]
    with pytest.raises(TypeError):
        sym.ceil(bogus=x)


def test_fused_rnn_forget_bias_init():
    """forget_bias threads into the packed-parameter initializer
    (reference init.FusedRNN) and into unfuse()'s LSTMCells."""
    from incubator_mxnet_tpu import initializer as init
    import jax

    fused = rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="fb_",
                             forget_bias=2.0)
    size = fused.param_size(I)
    fi = init.FusedRNN(init.Zero(), H, 1, "lstm", False, 2.0)
    packed = np.asarray(fi(jax.random.PRNGKey(0), (size,), "float32"))
    # layout: wi, wh, then bi, bh; forget gate is slice [H:2H] of each
    bi = packed[size - 8 * H: size - 4 * H]
    bh = packed[size - 4 * H:]
    np.testing.assert_allclose(bi[H:2 * H], 2.0)
    np.testing.assert_allclose(bh[H:2 * H], 0.0)
    np.testing.assert_allclose(bi[:H], 0.0)

    cell = fused.unfuse()._cells[0]
    assert isinstance(cell, rnn.LSTMCell)


def test_fused_rnn_init_defers_to_user_initializer():
    """The auto-attached FusedRNN attr must NOT override the initializer
    the user passes to init_params: weights come from the user init, only
    the forget-gate biases are stamped on top."""
    from incubator_mxnet_tpu import initializer as init
    import incubator_mxnet_tpu.module as mod

    fused = rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="fb_",
                             forget_bias=2.0)
    outs, _ = fused.unroll(T, sym.Variable("x"), layout="NTC",
                           merge_outputs=True)
    m = mod.Module(outs, data_names=["x"], label_names=None)
    m.bind(data_shapes=[("x", (B, T, I))])
    m.init_params(initializer=init.Zero())
    packed = m.get_params()[0]["fb_parameters"].asnumpy()
    sz = packed.size
    bi = packed[sz - 8 * H: sz - 4 * H]
    np.testing.assert_allclose(bi[H:2 * H], 2.0)      # forget bias stamped
    np.testing.assert_allclose(packed[:sz - 8 * H], 0.0)  # Zero honored


def test_fused_rnn_init_attr_roundtrip_keeps_inner():
    """An explicit inner initializer survives the Variable-attr JSON
    round trip (to_attr_str serializes nested initializers)."""
    from incubator_mxnet_tpu import initializer as init
    from incubator_mxnet_tpu.module import _init_from_attr
    import jax

    fused = rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="rt_")
    size = fused.param_size(I)
    fi = init.FusedRNN(init.One(), H, 1, "lstm", False, 3.0)
    fi2 = _init_from_attr(fi.to_attr_str())
    a = np.asarray(fi2(jax.random.PRNGKey(0), (size,), "float32"))
    np.testing.assert_allclose(a[:size - 8 * H], 1.0)
    np.testing.assert_allclose(a[size - 8 * H + H:size - 8 * H + 2 * H], 3.0)
