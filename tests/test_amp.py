"""AMP tests (mirrors reference tests/python/ amp + multi-precision
optimizer coverage)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import amp, autograd, gluon, nd


def _toy(dtype=None):
    mx.random.seed(0)
    np.random.seed(0)
    net = gluon.nn.Dense(1, in_units=4)
    net.initialize()
    if dtype:
        net.cast(dtype)
    xs = np.random.randn(16, 4).astype(np.float32)
    ys = xs @ np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    x, y = nd.array(xs), nd.array(ys)
    if dtype:
        x = x.astype(dtype)
        y = y.astype(dtype)
    return net, x, y


def test_amp_init_sets_dtype():
    amp.init()
    assert amp.target_dtype() == "bfloat16"
    amp.init("float16")
    assert amp.target_dtype() == "float16"
    amp.init("bfloat16")


def test_scaled_training_matches_unscaled():
    """Static scale S: scaled loss + unscale-in-step == vanilla training."""
    L = gluon.loss.L2Loss()

    def run(scaled):
        net, x, y = _toy()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        if scaled:
            amp.init_trainer(tr, amp.LossScaler(init_scale=128.0))
        for _ in range(5):
            with autograd.record():
                loss = L(net(x), y)
                if scaled:
                    with amp.scale_loss(loss, tr) as sl:
                        sl.backward()
                else:
                    loss.backward()
            tr.step(16)
        return net.weight.data().asnumpy()

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5, atol=1e-6)


def test_dynamic_scaler_backoff_and_growth():
    s = amp.DynamicLossScaler(init_scale=1024.0, growth_interval=3)
    s.update(overflow=True)
    assert s.loss_scale == 512.0
    for _ in range(3):
        s.update(overflow=False)
    assert s.loss_scale == 1024.0


def test_overflow_skips_update():
    net, x, y = _toy()
    L = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    scaler = amp.DynamicLossScaler(init_scale=1024.0)
    amp.init_trainer(tr, scaler)
    w0 = net.weight.data().asnumpy().copy()
    with autograd.record():
        loss = L(net(x), y)
        loss.backward()
    # poison the gradient with inf
    g = net.weight.grad()
    g._data = (g._data * np.inf).astype(g._data.dtype)
    tr.step(16)
    np.testing.assert_array_equal(net.weight.data().asnumpy(), w0)
    assert scaler.loss_scale == 512.0


def test_bf16_cast_training_converges():
    """bf16 params + multi_precision master weights still learn."""
    net, x, y = _toy("bfloat16")
    L = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.5, "multi_precision": True})
    losses = []
    for _ in range(40):
        with autograd.record():
            loss = L(net(x), y)
        loss.backward()
        tr.step(16)
        losses.append(float(loss.asnumpy().mean()))
    assert net.weight.data().dtype == "bfloat16"
    assert losses[-1] < losses[0] * 0.7, losses


def test_unscale_explicit():
    net, x, y = _toy()
    L = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd")
    amp.init_trainer(tr, amp.LossScaler(init_scale=64.0))
    with autograd.record():
        loss = L(net(x), y)
        with amp.scale_loss(loss, tr) as sl:
            sl.backward()
    g_scaled = net.weight.grad().asnumpy().copy()
    amp.unscale(tr)
    np.testing.assert_allclose(net.weight.grad().asnumpy(),
                               g_scaled / 64.0, rtol=1e-6)
    # scaler state preserved; the following step must not unscale again
    assert tr._amp_loss_scaler.loss_scale == 64.0
    w_before = net.weight.data().asnumpy().copy()
    g_unscaled = net.weight.grad().asnumpy().copy()
    tr.step(1)
    expected = w_before - 0.01 * g_unscaled  # sgd default lr, scale 1.0
    np.testing.assert_allclose(net.weight.data().asnumpy(), expected,
                               rtol=1e-5, atol=1e-7)
    assert not tr._amp_unscaled  # flag consumed


def test_update_path_also_wrapped():
    """allreduce_grads() + update() must unscale like step()."""
    L = gluon.loss.L2Loss()

    def run(use_update):
        net, x, y = _toy()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        amp.init_trainer(tr, amp.LossScaler(init_scale=256.0))
        for _ in range(3):
            with autograd.record():
                loss = L(net(x), y)
                with amp.scale_loss(loss, tr) as sl:
                    sl.backward()
            if use_update:
                tr.allreduce_grads()
                tr.update(16)
            else:
                tr.step(16)
        return net.weight.data().asnumpy()

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6)


def test_dynamic_scaler_runs_on_device():
    """The per-step found-inf/backoff path keeps scale + counter as device
    arrays (no host bool() in the hot loop — VERDICT r1 weak #6)."""
    import jax
    net, x, y = _toy()
    L = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    scaler = amp.DynamicLossScaler(init_scale=1024.0, growth_interval=2)
    amp.init_trainer(tr, scaler)
    for _ in range(3):
        with autograd.record():
            loss = L(net(x), y)
            with amp.scale_loss(loss, tr) as scaled:
                scaled.backward()
        tr.step(16)
    assert isinstance(scaler._scale_dev, jax.Array)
    assert isinstance(scaler._unskipped_dev, jax.Array)
    # growth_interval=2, 3 clean steps → scale grew once
    assert scaler.loss_scale == 2048.0
