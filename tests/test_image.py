"""Image pipeline tests (SURVEY.md §2.17 / VERDICT r1 Missing #2):
recordio pack/unpack, mx.image ops + augmenters, ImageRecordIter feeding
training. Mirrors reference tests/python/unittest/test_image.py +
test_recordio.py."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, image, io as mio, nd, recordio


def _rand_img(rng, h=40, w=32):
    return rng.randint(0, 255, (h, w, 3)).astype(np.uint8)


@pytest.fixture(scope="module")
def rec_file(tmp_path_factory):
    """Synthetic indexed .rec of 32 encoded JPEGs, labels 0..3."""
    d = tmp_path_factory.mktemp("rec")
    rec_path = str(d / "train.rec")
    idx_path = str(d / "train.idx")
    rng = np.random.RandomState(0)
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    imgs = []
    for i in range(32):
        img = _rand_img(rng)
        imgs.append(img)
        header = recordio.IRHeader(0, float(i % 4), i, 0)
        writer.write_idx(i, recordio.pack_img(header, img, quality=95))
    writer.close()
    return rec_path, imgs


# ---------------------------------------------------------------------------
# recordio
# ---------------------------------------------------------------------------

def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [bytes([i]) * (i + 1) for i in range(10)]
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    got = []
    while True:
        b = r.read()
        if b is None:
            break
        got.append(b)
    assert got == payloads


def test_indexed_recordio_random_access(tmp_path):
    rec, idx = str(tmp_path / "t.rec"), str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(20):
        w.write_idx(i, f"payload-{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r.read_idx(13) == b"payload-13"
    assert r.read_idx(2) == b"payload-2"
    assert r.keys == list(range(20))


def test_pack_unpack_scalar_and_multi_label():
    h = recordio.IRHeader(0, 3.0, 7, 0)
    hdr, data = recordio.unpack(recordio.pack(h, b"abc"))
    assert hdr.label == 3.0 and hdr.id == 7 and data == b"abc"
    h2 = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0]), 9, 0)
    hdr2, data2 = recordio.unpack(recordio.pack(h2, b"xy"))
    np.testing.assert_allclose(hdr2.label, [1, 2, 3])
    assert data2 == b"xy"


def test_pack_img_decode_close(tmp_path):
    # smooth gradient: JPEG-friendly, so roundtrip must be close
    yy, xx = np.meshgrid(np.arange(40), np.arange(32), indexing="ij")
    img = np.stack([yy * 6, xx * 7, (yy + xx) * 3], -1).astype(np.uint8)
    payload = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img,
                                quality=100)
    hdr, dec = recordio.unpack_img(payload)
    assert hdr.label == 1.0
    assert dec.shape == img.shape
    # JPEG is lossy: close, not exact
    assert np.abs(dec.astype(int) - img.astype(int)).mean() < 12


# ---------------------------------------------------------------------------
# image ops + augmenters
# ---------------------------------------------------------------------------

def test_imdecode_imresize():
    rng = np.random.RandomState(2)
    img = _rand_img(rng, 24, 16)
    payload = recordio.pack_img(recordio.IRHeader(0, 0.0, 0, 0), img,
                                img_fmt=".png")
    _, raw = recordio.unpack(payload)
    dec = image.imdecode(raw)
    assert dec.shape == (24, 16, 3)
    np.testing.assert_array_equal(dec.asnumpy(), img)  # png is lossless
    r = image.imresize(dec, 8, 12)
    assert r.shape == (12, 8, 3)


def test_resize_short_preserves_aspect():
    x = nd.array(np.zeros((40, 20, 3), np.uint8))
    out = image.resize_short(x, 10)
    assert out.shape == (20, 10, 3)
    out2 = image.resize_short(nd.array(np.zeros((20, 40, 3), np.uint8)), 10)
    assert out2.shape == (10, 20, 3)


def test_crops():
    x = nd.array(np.arange(6 * 8 * 3).reshape(6, 8, 3).astype(np.uint8))
    fc = image.fixed_crop(x, 2, 1, 4, 3)
    np.testing.assert_array_equal(fc.asnumpy(), x.asnumpy()[1:4, 2:6])
    cc, rect = image.center_crop(x, (4, 2))
    assert cc.shape == (2, 4, 3) and rect == (2, 2, 4, 2)
    rc, rect2 = image.random_crop(x, (4, 2))
    assert rc.shape == (2, 4, 3)
    rsc, _ = image.random_size_crop(x, (4, 2), (0.3, 1.0), (0.5, 2.0))
    assert rsc.shape == (2, 4, 3)


def test_color_normalize():
    x = nd.array(np.full((2, 2, 3), 10.0, np.float32))
    out = image.color_normalize(x, nd.array(np.array([1.0, 2.0, 3.0])),
                                nd.array(np.array([2.0, 2.0, 2.0])))
    np.testing.assert_allclose(out.asnumpy()[0, 0], [4.5, 4.0, 3.5])


def test_augmenter_stack_shapes_and_determinism():
    rng = np.random.RandomState(3)
    img = nd.array(_rand_img(rng, 50, 60))
    augs = image.CreateAugmenter((3, 24, 24), resize=30, rand_crop=True,
                                 rand_mirror=True, brightness=0.1,
                                 contrast=0.1, saturation=0.1, hue=0.1,
                                 pca_noise=0.05, mean=True, std=True)
    out = img
    for a in augs:
        out = a(out)
    arr = out.asnumpy() if isinstance(out, nd.NDArray) else np.asarray(out)
    assert arr.shape == (24, 24, 3)
    assert arr.dtype == np.float32


def test_horizontal_flip():
    img = nd.array(np.arange(12).reshape(2, 2, 3).astype(np.uint8))
    flip = image.HorizontalFlipAug(p=1.0)
    np.testing.assert_array_equal(flip(img).asnumpy(),
                                  img.asnumpy()[:, ::-1])


# ---------------------------------------------------------------------------
# ImageIter / ImageRecordIter
# ---------------------------------------------------------------------------

def test_image_iter_from_rec(rec_file):
    rec_path, _ = rec_file
    it = image.ImageIter(batch_size=8, data_shape=(3, 24, 24),
                         path_imgrec=rec_path)
    batch = it.next()
    assert batch.data[0].shape == (8, 3, 24, 24)
    assert batch.label[0].shape == (8,)
    n = 1 + sum(1 for _ in it)
    assert n == 4


def test_image_record_iter_batches(rec_file):
    rec_path, _ = rec_file
    it = mio.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 24, 24),
                             batch_size=8, shuffle=True, rand_crop=True,
                             rand_mirror=True, preprocess_threads=2)
    seen = 0
    for batch in it:
        assert batch.data[0].shape == (8, 3, 24, 24)
        assert np.isfinite(batch.data[0].asnumpy()).all()
        labels = batch.label[0].asnumpy()
        assert ((labels >= 0) & (labels <= 3)).all()
        seen += batch.data[0].shape[0] - batch.pad
    assert seen == 32
    # reset -> second epoch works
    it.reset()
    assert sum(1 for _ in it) == 4


def test_image_record_iter_nhwc_and_normalize(rec_file):
    rec_path, _ = rec_file
    it = mio.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 16, 16),
                             batch_size=4, layout="NHWC",
                             mean_r=123.68, mean_g=116.28, mean_b=103.53,
                             std_r=58.4, std_g=57.1, std_b=57.4)
    batch = it.next()
    assert batch.data[0].shape == (4, 16, 16, 3)
    arr = batch.data[0].asnumpy()
    assert np.abs(arr).max() < 5.0  # normalized range


def test_image_record_iter_label_content_unshuffled(rec_file):
    rec_path, _ = rec_file
    it = mio.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 16, 16),
                             batch_size=8, shuffle=False)
    batch = it.next()
    np.testing.assert_allclose(batch.label[0].asnumpy(),
                               np.arange(8) % 4)


def test_image_record_iter_feeds_module_fit(rec_file):
    """End-to-end: .rec -> ImageRecordIter -> Module.fit one epoch."""
    rec_path, _ = rec_file
    it = mio.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 16, 16),
                             batch_size=8, shuffle=True)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(mx.sym.Flatten(data), num_hidden=4)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=1,
            optimizer_params={"learning_rate": 0.01})
    score = mod.score(it, "acc")
    assert 0.0 <= dict(score)["accuracy"] <= 1.0


def test_image_record_iter_feeds_fused_step(rec_file):
    """The TPU hot path: NHWC batches into a compiled train step."""
    from incubator_mxnet_tpu.parallel import FusedTrainStep
    rec_path, _ = rec_file
    it = mio.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 16, 16),
                             batch_size=8, layout="NHWC",
                             mean_r=128, mean_g=128, mean_b=128,
                             std_r=64, std_g=64, std_b=64)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, 3, layout="NHWC"), gluon.nn.Flatten(),
            gluon.nn.Dense(4))
    net.initialize()
    step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd")
    losses = []
    for batch in it:
        losses.append(float(step(batch.data[0], batch.label[0])))
    assert len(losses) == 4 and all(np.isfinite(l) for l in losses)


def test_image_record_dataset(rec_file):
    """gluon.data.vision.ImageRecordDataset: .rec -> (HWC image, label)
    samples, DataLoader-composable (reference
    python/mxnet/gluon/data/vision/datasets.py ImageRecordDataset)."""
    from incubator_mxnet_tpu.gluon.data.vision import ImageRecordDataset
    rec_path, imgs = rec_file
    ds = ImageRecordDataset(rec_path)
    assert len(ds) == 32
    img, label = ds[5]
    assert img.shape == imgs[5].shape and label == 5 % 4
    # exact parity with the direct recordio decode of the same record
    from incubator_mxnet_tpu.gluon.data import RecordFileDataset
    _, direct = recordio.unpack_img(RecordFileDataset(rec_path)[5])
    assert np.array_equal(img.asnumpy(), direct.astype(np.float32))
    loader = gluon.data.DataLoader(ds, batch_size=8)
    xb, yb = next(iter(loader))
    assert xb.shape == (8, 40, 32, 3) and yb.shape == (8,)


def test_nd_module_level_surface():
    """mx.nd module functions mirroring NDArray methods (reference nd API)."""
    a = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    assert mx.nd.empty_like(a).shape == (2, 2)
    assert np.allclose(mx.nd.mod(a, 2).asnumpy(), [[1, 0], [1, 0]])
    assert mx.nd.astype(a, "float16").dtype == np.float16
    b = mx.nd.zeros((2, 2))
    a.copyto(b)
    assert np.allclose(b.asnumpy(), a.asnumpy())


def test_native_jpeg_decoder_matches_pil():
    """runtime.decode_jpeg (libjpeg, GIL-free) decodes bit-identically to
    PIL and fails gracefully on junk (falls back to PIL in imdecode)."""
    from incubator_mxnet_tpu import runtime
    import io as _io
    from PIL import Image
    if not runtime.jpeg_decode_available():
        pytest.skip("native jpeg decoder unavailable (no g++/libjpeg)")
    rng = np.random.RandomState(9)
    img = rng.randint(0, 255, (32, 24, 3)).astype(np.uint8)
    buf = _io.BytesIO()
    Image.fromarray(img).save(buf, format="JPEG", quality=90)
    data = buf.getvalue()
    nat = runtime.decode_jpeg(data)
    pil = np.asarray(Image.open(_io.BytesIO(data)).convert("RGB"))
    np.testing.assert_array_equal(nat, pil)
    gray = runtime.decode_jpeg(data, channels=1)
    assert gray.shape == (32, 24, 1)
    assert runtime.decode_jpeg(data[:40]) is None      # cut inside header
    # cut inside scan data: libjpeg pads with a fake EOI + warning; the
    # decoder must surface that as failure, not silent garbage
    assert runtime.decode_jpeg(data[:len(data) // 2]) is None
    # imdecode grayscale is identical to PIL's convert('L') luma on both
    # native and fallback paths
    pil_gray = np.asarray(Image.open(_io.BytesIO(data)).convert("L"))
    np.testing.assert_array_equal(
        image.imdecode(data, flag=0).asnumpy()[..., 0], pil_gray)
    # imdecode routes JPEG through the native path and PNG through PIL
    d = image.imdecode(data)
    np.testing.assert_array_equal(d.asnumpy(), pil)
    png = _io.BytesIO()
    Image.fromarray(img).save(png, format="PNG")
    np.testing.assert_array_equal(image.imdecode(png.getvalue()).asnumpy(),
                                  img)


def test_copy_make_border():
    img = nd.array(np.arange(12, dtype=np.float32).reshape(2, 2, 3))
    b = image.copyMakeBorder(img, 1, 1, 2, 2, border_type=0,
                             values=5.0).asnumpy()
    assert b.shape == (4, 6, 3)
    assert (b[0] == 5.0).all() and (b[:, 0] == 5.0).all()
    np.testing.assert_array_equal(b[1:3, 2:4], img.asnumpy())
    r = image.copyMakeBorder(img, 1, 0, 0, 0, border_type=1).asnumpy()
    np.testing.assert_array_equal(r[0], img.asnumpy()[0])
    import pytest
    with pytest.raises(ValueError):
        image.copyMakeBorder(img, 1, 1, 1, 1, border_type=4)


@pytest.mark.slow
def test_im2rec_cli_roundtrip(tmp_path):
    """tools/im2rec.py: folder -> .lst/.rec/.idx consumable by
    ImageRecordIter with subdirectory labels (reference tools/im2rec)."""
    import subprocess
    import sys as _sys
    from PIL import Image as PILImage
    root = tmp_path / "imgs"
    rng = np.random.RandomState(0)
    for ci, cls in enumerate(["cat", "dog"]):
        (root / cls).mkdir(parents=True)
        for i in range(3):
            arr = rng.randint(0, 255, (20 + ci, 24, 3), np.uint8)
            PILImage.fromarray(arr).save(root / cls / f"{i}.jpg",
                                         quality=95)
    prefix = str(tmp_path / "data")
    out = subprocess.run(
        [_sys.executable,
         os.path.join(os.path.dirname(os.path.dirname(
             os.path.abspath(__file__))), "tools", "im2rec.py"),
         prefix, str(root), "--resize", "16"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 PALLAS_AXON_POOL_IPS=""))
    assert out.returncode == 0, out.stderr[-500:]
    assert os.path.exists(prefix + ".lst")
    assert os.path.exists(prefix + ".rec")
    it = mio.ImageRecordIter(path_imgrec=prefix + ".rec",
                             data_shape=(3, 16, 16), batch_size=6,
                             shuffle=False)
    batch = it.next()
    labels = batch.label[0].asnumpy()
    np.testing.assert_allclose(sorted(labels), [0, 0, 0, 1, 1, 1])
