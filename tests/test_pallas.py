"""Pallas kernel correctness vs XLA references (interpret mode on CPU).

Mirrors the reference's operator tests for the hand-written attention
kernels (tests/python/unittest/test_operator.py multihead attention cases).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from incubator_mxnet_tpu.ops.pallas import flash_attention, layer_norm


def naive_attention(q, k, v, scale, causal):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        lq, lk = q.shape[2], k.shape[2]
        tri = jnp.tril(jnp.ones((lq, lk), dtype=bool), k=lk - lq)
        s = jnp.where(tri, s, -1e30)
    w = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("lq,lk,d", [(32, 32, 16), (48, 80, 32)])
def test_flash_forward(causal, lq, lk, d):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 2, lq, d).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 2, lk, d).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 2, lk, d).astype(np.float32))
    scale = 1.0 / np.sqrt(d)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                          interpret=True)
    ref = naive_attention(q, k, v, scale, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads(causal):
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 32, 16).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 2, 32, 16).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 2, 32, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(1, 2, 32, 16).astype(np.float32))
    scale = 0.25

    def f_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                            interpret=True)
        return jnp.sum(o * w)

    def f_ref(q, k, v):
        return jnp.sum(naive_attention(q, k, v, scale, causal) * w)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_flash_causal_cross_length():
    # bottom-right-aligned causal (decode semantics): query row r sees
    # cols <= r + (lk - lq), matching the XLA path's tril(k=lk-lq)
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 2, 48, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 2, 48, 8).astype(np.float32))
    w = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    scale = 1.0 / np.sqrt(8)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16,
                          interpret=True)
    ref = naive_attention(q, k, v, scale, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    g1 = jax.grad(lambda *a: jnp.sum(flash_attention(
        *a, causal=True, block_q=16, block_k=16, interpret=True) * w),
        argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: jnp.sum(naive_attention(*a, scale, True) * w),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_decode_step():
    # single-query causal decode: must attend over the whole KV cache
    rng = np.random.RandomState(8)
    q = jnp.asarray(rng.randn(2, 2, 1, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 2, 33, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 2, 33, 8).astype(np.float32))
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16,
                          interpret=True)
    ref = naive_attention(q, k, v, 1.0 / np.sqrt(8), True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_unaligned_lengths():
    # lengths that need padding to block multiples; padded KV must be masked
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 1, 23, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 1, 37, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 1, 37, 8).astype(np.float32))
    out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
    ref = naive_attention(q, k, v, 1.0 / np.sqrt(8), False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_bf16():
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(1, 2, 32, 16)).astype(jnp.bfloat16)
    k = jnp.asarray(rng.randn(1, 2, 32, 16)).astype(jnp.bfloat16)
    v = jnp.asarray(rng.randn(1, 2, 32, 16)).astype(jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
    ref = naive_attention(q, k, v, 0.25, False)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref, dtype=np.float32),
                               rtol=3e-2, atol=3e-2)


def test_layer_norm_kernel():
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(6, 33).astype(np.float32))
    g = jnp.asarray(rng.randn(33).astype(np.float32))
    b = jnp.asarray(rng.randn(33).astype(np.float32))

    def ref(x, g, b):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * g + b

    out = layer_norm(x, g, b, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref(x, g, b)),
                               rtol=1e-5, atol=1e-5)

    w = jnp.asarray(rng.randn(6, 33).astype(np.float32))
    g1 = jax.grad(lambda *a: jnp.sum(layer_norm(*a, interpret=True) * w),
                  argnums=(0, 1, 2))(x, g, b)
    g2 = jax.grad(lambda *a: jnp.sum(ref(*a) * w), argnums=(0, 1, 2))(x, g, b)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_mha_routes_to_flash(monkeypatch):
    # with the force flag, ops.multihead_attention should produce the same
    # values through the pallas path as the XLA path
    monkeypatch.setenv("MXTPU_FORCE_PALLAS", "1")
    from incubator_mxnet_tpu.ops import _raw
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(2, 32, 32).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 32, 32).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 32, 32).astype(np.float32))
    out = _raw.multihead_attention(q, k, v, num_heads=4)
    monkeypatch.delenv("MXTPU_FORCE_PALLAS")
    monkeypatch.setenv("MXTPU_NO_PALLAS", "1")
    ref = _raw.multihead_attention(q, k, v, num_heads=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_enabled_detects_plugin_tpu_platforms(monkeypatch):
    """The real chip can register under a plugin platform name (axon
    relay: platform 'axon', device_kind 'TPU v5 lite'); enabled() must
    detect TPU by device kind, not only the canonical backend name."""
    import jax
    from incubator_mxnet_tpu.ops import pallas

    class FakeDev:
        device_kind = "TPU v5 lite"

    monkeypatch.delenv("MXTPU_FORCE_PALLAS", raising=False)
    monkeypatch.delenv("MXTPU_NO_PALLAS", raising=False)
    monkeypatch.setenv("MXTPU_PALLAS_SELFTEST", "0")  # no Mosaic on CPU
    pallas._reset_selftest_for_tests()
    monkeypatch.setattr(jax, "default_backend", lambda: "axon")
    monkeypatch.setattr(jax, "devices", lambda: [FakeDev()])
    try:
        assert pallas.enabled()
        monkeypatch.setattr(jax, "devices", lambda: [type("C", (), {
            "device_kind": "cpu"})()])
        assert not pallas.enabled()
    finally:
        pallas._reset_selftest_for_tests()


def test_is_tpu_consistent_across_dispatch_sites(monkeypatch):
    """One definition of "on TPU": under a plugin platform with TPU
    devices, enabled() is True AND interpret-mode selection sees a real
    TPU (Mosaic, not interpret) AND runtime features report TPU."""
    import jax
    from incubator_mxnet_tpu.ops import pallas

    class FakeDev:
        device_kind = "TPU v5 lite"

    monkeypatch.delenv("MXTPU_FORCE_PALLAS", raising=False)
    monkeypatch.delenv("MXTPU_NO_PALLAS", raising=False)
    monkeypatch.setenv("MXTPU_PALLAS_SELFTEST", "0")  # no Mosaic on CPU
    pallas._reset_selftest_for_tests()
    monkeypatch.setattr(jax, "default_backend", lambda: "axon")
    monkeypatch.setattr(jax, "devices", lambda: [FakeDev()])
    try:
        assert pallas.is_tpu() and pallas.enabled()
    finally:
        pallas._reset_selftest_for_tests()
    from incubator_mxnet_tpu.runtime import features
    assert features.Features().is_enabled("TPU")


# ---------------------------------------------------------------------------
# on-device kernel self-test gating (kernels_ok)
# ---------------------------------------------------------------------------

def test_selftest_not_run_off_tpu(monkeypatch):
    """Off-TPU, kernels_ok() trusts interpret-mode test coverage and never
    compiles anything."""
    from incubator_mxnet_tpu.ops import pallas

    monkeypatch.delenv("MXTPU_PALLAS_SELFTEST", raising=False)
    monkeypatch.setattr(pallas, "is_tpu", lambda: False)
    monkeypatch.setattr(pallas, "_selftest",
                        lambda: (_ for _ in ()).throw(AssertionError(
                            "selftest must not run off-TPU")))
    pallas._reset_selftest_for_tests()
    try:
        assert pallas.kernels_ok()
    finally:
        pallas._reset_selftest_for_tests()


def test_selftest_passes_with_correct_kernels(monkeypatch):
    """The self-test's own reference math must accept the real kernels
    (run in interpret mode here) — otherwise it would spuriously disable
    pallas on the chip."""
    import functools
    from incubator_mxnet_tpu.ops import pallas
    from incubator_mxnet_tpu.ops.pallas import flash_attention, layer_norm

    monkeypatch.setattr(pallas, "layer_norm",
                        functools.partial(layer_norm, interpret=True))
    monkeypatch.setattr(pallas, "flash_attention",
                        functools.partial(flash_attention, interpret=True))
    assert pallas._selftest() is True


def test_selftest_failure_disables_pallas(monkeypatch):
    """A kernel producing wrong numbers (or raising) flips dispatch to the
    XLA path for the process, with a warning — it must not propagate."""
    import functools
    from incubator_mxnet_tpu.ops import pallas
    from incubator_mxnet_tpu.ops.pallas import layer_norm

    monkeypatch.setattr(pallas, "layer_norm",
                        functools.partial(layer_norm, interpret=True))
    monkeypatch.setattr(pallas, "flash_attention",
                        lambda q, k, v, **kw: q * 0.0)  # very wrong
    with pytest.warns(RuntimeWarning, match="self-test"):
        assert pallas._selftest() is False

    # and kernels_ok()/enabled() honor the verdict on a (fake) TPU
    monkeypatch.delenv("MXTPU_FORCE_PALLAS", raising=False)
    monkeypatch.delenv("MXTPU_NO_PALLAS", raising=False)
    monkeypatch.delenv("MXTPU_PALLAS_SELFTEST", raising=False)
    monkeypatch.setattr(pallas, "is_tpu", lambda: True)
    monkeypatch.setattr(pallas, "_selftest", lambda: False)
    pallas._reset_selftest_for_tests()
    try:
        assert not pallas.kernels_ok()
        assert not pallas.enabled()
        # cached: a later flip of _selftest must not re-run
        monkeypatch.setattr(pallas, "_selftest", lambda: True)
        assert not pallas.kernels_ok()
    finally:
        pallas._reset_selftest_for_tests()


def test_selftest_skip_env(monkeypatch):
    """MXTPU_PALLAS_SELFTEST=0 trusts the kernels without compiling."""
    from incubator_mxnet_tpu.ops import pallas

    monkeypatch.setenv("MXTPU_PALLAS_SELFTEST", "0")
    monkeypatch.setattr(pallas, "is_tpu", lambda: True)
    monkeypatch.setattr(pallas, "_selftest",
                        lambda: (_ for _ in ()).throw(AssertionError(
                            "selftest must be skipped")))
    pallas._reset_selftest_for_tests()
    try:
        assert pallas.kernels_ok()
    finally:
        pallas._reset_selftest_for_tests()
