"""mxtpu.mxlint — static analyzer + strict-mode runtime auditor.

Covers the PR 14 acceptance matrix: every rule fires on its bad fixture
and stays quiet on its good one (tests/fixtures/mxlint/),
suppression-with-reason is honored while a reasonless directive is
itself a finding, the counter-family tables have ONE home (the
trace_check drift test), the secondary-knob accessors resolve
call-site > env > default, the repo tree lints CLEAN end-to-end, and
the runtime auditor detects an injected host sync / a forced re-jit /
a donated-buffer read while the off path pays one predicate.
"""
import importlib.util
import json
import os
import subprocess
import sys

import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.mxlint import engine, families, rules, runtime
from incubator_mxnet_tpu.profiler.counters import (counters as
                                                   counters_snapshot,
                                                   reset_counters)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "mxlint")


def _load_tool(name):
    path = os.path.join(REPO, "tools", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fixture(name):
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as f:
        return f.read()


def _lint_fixture(name, relpath, rule):
    """Run ONE rule over one fixture as if it lived at ``relpath``."""
    return engine.lint_sources([(relpath, _fixture(name))], [rule])


# the (bad fixture, good fixture, pretend package path, rule id) matrix
RULE_MATRIX = [
    ("raw_env_read_bad.py", "raw_env_read_good.py",
     "incubator_mxnet_tpu/somemod.py", "raw-env-read"),
    ("unregistered_counter_bad.py", "unregistered_counter_good.py",
     "incubator_mxnet_tpu/somemod.py", "unregistered-counter"),
    ("raise_in_never_raise_bad.py", "raise_in_never_raise_good.py",
     "incubator_mxnet_tpu/devicescope/ingest.py",
     "raise-in-never-raise"),
    ("unnormalized_device_kind_bad.py",
     "unnormalized_device_kind_good.py",
     "incubator_mxnet_tpu/somemod.py", "unnormalized-device-kind"),
    ("thread_shared_mutation_bad.py", "thread_shared_mutation_good.py",
     "incubator_mxnet_tpu/serving/batcher.py", "thread-shared-mutation"),
]


class TestRuleMatrix:
    @pytest.mark.parametrize("bad,good,relpath,rule_id", RULE_MATRIX,
                             ids=[m[3] for m in RULE_MATRIX])
    def test_bad_fires_good_quiet(self, bad, good, relpath, rule_id):
        rule = rules.rule_by_id(rule_id)
        found = _lint_fixture(bad, relpath, rule)
        assert found, f"{rule_id} must fire on {bad}"
        assert all(f.rule == rule_id for f in found)
        assert all(f.hint for f in found), "every finding carries a hint"
        rule = rules.rule_by_id(rule_id)     # fresh (stateful rules)
        quiet = _lint_fixture(good, relpath, rule)
        assert quiet == [], \
            f"{rule_id} must stay quiet on {good}: {quiet}"

    def test_raw_env_read_catches_every_spelling(self):
        found = _lint_fixture("raw_env_read_bad.py",
                              "incubator_mxnet_tpu/somemod.py",
                              rules.rule_by_id("raw-env-read"))
        # .get / os.getenv / bare getenv / subscript / membership /
        # dynamic-name helper
        assert len(found) == 6

    def test_raw_env_read_skips_driver_layer(self):
        # bench.py / tools are the BENCH_* driver spelling — out of scope
        rule = rules.rule_by_id("raw-env-read")
        assert engine.lint_sources(
            [("bench.py", _fixture("raw_env_read_bad.py"))],
            [rule]) == []

    def test_raw_env_read_exempts_knob_home(self):
        rule = rules.rule_by_id("raw-env-read")
        assert engine.lint_sources(
            [("incubator_mxnet_tpu/autotune/knobs.py",
              _fixture("raw_env_read_bad.py"))], [rule]) == []

    def test_raw_env_read_allowlist_is_file_scoped(self):
        src = 'import os\nv = os.environ.get("MXTPU_HEALTHMON", "0")\n'
        rule = rules.rule_by_id("raw-env-read")
        ok = engine.lint_sources(
            [("incubator_mxnet_tpu/healthmon/__init__.py", src)], [rule])
        assert ok == []          # allowlisted THERE
        elsewhere = engine.lint_sources(
            [("incubator_mxnet_tpu/somemod.py", src)], [rule])
        assert len(elsewhere) == 1   # but only there

    def test_every_allowlist_entry_has_reason_and_files(self):
        for name, entry in rules.RAW_ENV_ALLOWLIST.items():
            assert entry["reason"].strip(), name
            assert entry["files"] is None or entry["files"], name

    def test_unregistered_counter_names_the_metric(self):
        found = _lint_fixture("unregistered_counter_bad.py",
                              "incubator_mxnet_tpu/somemod.py",
                              rules.rule_by_id("unregistered-counter"))
        msgs = " ".join(f.message for f in found)
        assert "healthmon/healthmon.not_a_real_metric" in msgs
        assert "autotune/autotune.invented_histogram" in msgs
        # kind mismatches: a gauge observed as histogram, a counter
        # written as gauge
        assert "perfscope/perfscope.mfu" in msgs
        assert "resilience/resilience.rollbacks" in msgs
        assert len(found) == 4

    def test_duplicated_table_pair(self):
        rule = rules.rule_by_id("duplicated-default-table")
        found = engine.lint_sources(
            [("incubator_mxnet_tpu/bench_tables.py",
              _fixture("duplicated_default_table_bad_a.py")),
             ("tools/sweep_tables.py",
              _fixture("duplicated_default_table_bad_b.py"))], [rule])
        assert len(found) == 1
        # the non-package copy is the flagged one; the package copy is
        # named as the canonical home
        assert found[0].path == "tools/sweep_tables.py"
        assert "DEFAULT_BATCH" in found[0].message
        rule = rules.rule_by_id("duplicated-default-table")
        assert engine.lint_sources(
            [("incubator_mxnet_tpu/a.py",
              _fixture("duplicated_default_table_good.py")),
             ("tools/b.py",
              _fixture("duplicated_default_table_bad_a.py"))],
            [rule]) == []


class TestSuppression:
    def test_with_reason_honored(self):
        rule = rules.rule_by_id("raw-env-read")
        assert engine.lint_sources(
            [("incubator_mxnet_tpu/somemod.py",
              _fixture("suppression_with_reason.py"))], [rule]) == []

    def test_without_reason_rejected(self):
        rule = rules.rule_by_id("raw-env-read")
        found = engine.lint_sources(
            [("incubator_mxnet_tpu/somemod.py",
              _fixture("suppression_without_reason.py"))], [rule])
        by_rule = {f.rule for f in found}
        # the directive suppresses NOTHING (the read still fires) and is
        # itself a finding
        assert "raw-env-read" in by_rule
        assert engine.SUPPRESSION_RULE_ID in by_rule

    def test_multiline_reason_covers_next_code_line(self):
        src = ("import os\n"
               "# mxlint: disable=raw-env-read -- reason line one\n"
               "# continues over a second comment line\n"
               'v = os.environ.get("MXTPU_K", "1")\n')
        assert engine.lint_sources(
            [("incubator_mxnet_tpu/m.py", src)],
            [rules.rule_by_id("raw-env-read")]) == []

    def test_disable_file_scope(self):
        src = ('"""mod."""\n'
               "# mxlint: disable-file=raw-env-read -- fixture-wide "
               "waiver\n"
               "import os\n"
               'a = os.environ.get("MXTPU_A", "1")\n'
               'b = os.environ.get("MXTPU_B", "1")\n')
        assert engine.lint_sources(
            [("incubator_mxnet_tpu/m.py", src)],
            [rules.rule_by_id("raw-env-read")]) == []

    def test_cross_file_rule_honors_suppression(self):
        # duplicated-default-table reports from finish(), AFTER the
        # engine's per-file filter — the directive must still work
        rule = rules.rule_by_id("duplicated-default-table")
        copy_src = _fixture("duplicated_default_table_bad_b.py").replace(
            "MY_BATCH_TABLE = {",
            "# mxlint: disable=duplicated-default-table -- deliberately "
            "independent copy\nMY_BATCH_TABLE = {")
        assert engine.lint_sources(
            [("incubator_mxnet_tpu/a.py",
              _fixture("duplicated_default_table_bad_a.py")),
             ("tools/b.py", copy_src)], [rule]) == []

    def test_suppression_only_covers_its_rule(self):
        src = ("import os\n"
               "# mxlint: disable=unregistered-counter -- wrong rule\n"
               'v = os.environ.get("MXTPU_K", "1")\n')
        found = engine.lint_sources(
            [("incubator_mxnet_tpu/m.py", src)],
            [rules.rule_by_id("raw-env-read")])
        assert [f.rule for f in found] == ["raw-env-read"]


class TestFamiliesSingleHome:
    def test_trace_check_derives_from_families(self):
        """THE drift test: trace_check's exported tables must BE the
        family-home tables (someone re-inlining a literal dict fails
        here)."""
        tc = _load_tool("trace_check")
        assert tc.HEALTHMON_FAMILIES == families.family_table("healthmon")
        assert tc.IO_TRAINLOOP_FAMILIES == families.family_table(
            "io", "trainloop")
        assert tc.SHARDING_FAMILIES == families.family_table("sharding")
        assert tc.PERFSCOPE_FAMILIES == families.family_table("perfscope")
        assert tc.COMMSCOPE_FAMILIES == families.family_table("commscope")
        assert tc.DEVICESCOPE_FAMILIES == families.family_table(
            "devicescope")
        assert tc.SERVESCOPE_FAMILIES == families.family_table(
            "servescope")
        assert tc.RESILIENCE_FAMILIES == families.family_table(
            "resilience")
        assert tc.AUTOTUNE_FAMILIES == families.family_table("autotune")
        assert tc.MXLINT_FAMILIES == families.family_table("mxlint")

    def test_table_shape(self):
        for domain, table in families.FAMILY_TABLES.items():
            for full, kind in table.items():
                assert full.startswith(f"{domain}/{domain}."), full
                assert kind in ("counter", "gauge", "histogram"), full

    def test_mxlint_family_accepted_by_kind_checker(self):
        tc = _load_tool("trace_check")
        kinds = {k: v for k, v in families.family_table("mxlint").items()}
        assert tc.check_healthmon_kinds(kinds) == []
        bad = dict(kinds)
        bad["mxlint/mxlint.invented"] = "counter"
        assert tc.check_healthmon_kinds(bad)

    def test_known_metric_helpers(self):
        assert families.known_metric("healthmon/healthmon.nan_alerts")
        assert not families.known_metric("healthmon/healthmon.nope")
        assert families.known_metric("bulk/anything")   # ungoverned
        assert families.metric_kind(
            "perfscope/perfscope.device_step_ms") == "histogram"


class TestEnvAccessors:
    def setup_method(self):
        for k in ("MXTPU_T_INT", "MXTPU_T_FLAG", "MXTPU_T_STR"):
            os.environ.pop(k, None)

    teardown_method = setup_method

    def test_precedence_call_site_beats_env(self):
        from incubator_mxnet_tpu.autotune import knobs
        os.environ["MXTPU_T_INT"] = "5"
        assert knobs.env_int("MXTPU_T_INT", 1) == 5
        assert knobs.env_int("MXTPU_T_INT", 1, call_site=9) == 9
        assert knobs.env_int("MXTPU_T_INT_UNSET", 7) == 7

    def test_empty_env_is_unset(self):
        from incubator_mxnet_tpu.autotune import knobs
        os.environ["MXTPU_T_STR"] = "   "
        assert knobs.env_str("MXTPU_T_STR", "d") == "d"
        assert knobs.env_raw("MXTPU_T_STR") is None

    def test_int_garbage_raises_naming_the_knob(self):
        from incubator_mxnet_tpu.autotune import knobs
        os.environ["MXTPU_T_INT"] = "banana"
        with pytest.raises(ValueError, match="MXTPU_T_INT"):
            knobs.env_int("MXTPU_T_INT", 1)

    def test_int_garbage_degrades_for_never_raise_consumers(self):
        from incubator_mxnet_tpu.autotune import knobs
        knobs.reset_warned()
        os.environ["MXTPU_T_INT"] = "banana"
        with pytest.warns(UserWarning, match="MXTPU_T_INT"):
            assert knobs.env_int("MXTPU_T_INT", 3,
                                 on_error="default") == 3

    def test_flag_spelling_table(self):
        from incubator_mxnet_tpu.autotune import knobs
        for raw, want in (("1", True), ("true", True), ("on", True),
                          ("yes", True), ("0", False), ("false", False),
                          ("off", False), ("no", False)):
            os.environ["MXTPU_T_FLAG"] = raw
            assert knobs.env_flag("MXTPU_T_FLAG", not want) is want, raw

    def test_flag_garbage_warns_and_defaults(self):
        from incubator_mxnet_tpu.autotune import knobs
        knobs.reset_warned()
        os.environ["MXTPU_T_FLAG"] = "maybe"
        with pytest.warns(UserWarning, match="MXTPU_T_FLAG"):
            assert knobs.env_flag("MXTPU_T_FLAG", True) is True

    def test_pallas_switch_rides_the_knob_home(self):
        """The PR 14 bugfix: a cached tuning winner's pallas knob now
        reaches ops/pallas.enabled() (it used to read raw env BELOW the
        cache layer and silently ignore the winner)."""
        from incubator_mxnet_tpu.autotune import knobs
        from incubator_mxnet_tpu.ops import pallas
        for k in ("MXTPU_PALLAS", "MXTPU_NO_PALLAS",
                  "MXTPU_FORCE_PALLAS"):
            os.environ.pop(k, None)
        knobs.clear_cached_defaults()
        try:
            assert pallas.enabled() is False       # cpu default: auto
            knobs.set_cached_defaults({"pallas": "force"})
            assert pallas.enabled() is True        # winner applies
            os.environ["MXTPU_PALLAS"] = "0"       # env still beats it
            assert pallas.enabled() is False
        finally:
            os.environ.pop("MXTPU_PALLAS", None)
            knobs.clear_cached_defaults()


class TestTreeClean:
    def test_repo_lints_clean_end_to_end(self):
        """The acceptance gate, as a tier-1 test: tools/mxlint.py
        --check over the real tree finds nothing."""
        cli = _load_tool("mxlint")
        findings, _ = cli.run_lint()
        assert findings == [], "\n".join(
            f.render(root=REPO) for f in findings)

    def test_lint_tree_on_package_dir_keeps_rule_scope(self, tmp_path):
        # linting the package dir DIRECTLY (commonpath strips the
        # prefix) must still put files in raw-env-read's jurisdiction
        import incubator_mxnet_tpu.mxlint as mxl
        pkg = tmp_path / "incubator_mxnet_tpu"
        pkg.mkdir()
        (pkg / "victim.py").write_text(
            'import os\nv = os.environ.get("MXTPU_FOO")\n')
        found = mxl.lint_tree([str(pkg)])
        assert [f.rule for f in found] == ["raw-env-read"]

    def test_cli_check_exit_codes(self, tmp_path):
        cli = _load_tool("mxlint")
        assert cli.main(["--check"]) == 0
        bad = tmp_path / "incubator_mxnet_tpu" / "m.py"
        bad.parent.mkdir()
        bad.write_text('import os\nv = os.environ.get("MXTPU_X", "")\n')
        assert cli.main(["--check", str(tmp_path)]) == 1

    def test_cli_errors_on_nonexistent_path(self, tmp_path):
        # a typo'd gate invocation must FAIL, never report a clean
        # empty lint set
        cli = _load_tool("mxlint")
        assert cli.main(["--check", str(tmp_path / "nope")]) == 2

    def test_allowlist_and_scopes_are_component_anchored(self):
        src = 'import os\nv = os.environ.get("MXTPU_HEALTHMON", "0")\n'
        rule = rules.rule_by_id("raw-env-read")
        # a suffix-colliding module must NOT inherit healthmon's waiver
        hit = engine.lint_sources(
            [("incubator_mxnet_tpu/myhealthmon/__init__.py", src)],
            [rule])
        assert len(hit) == 1
        # nor may a fake mxlint-suffixed path escape the rule wholesale
        hit2 = engine.lint_sources(
            [("incubator_mxnet_tpu/foo_mxlint/rules.py",
              'import os\nv = os.environ.get("MXTPU_X", "")\n')],
            [rules.rule_by_id("raw-env-read")])
        assert len(hit2) == 1

    def test_list_rules_covers_every_rule(self, capsys):
        cli = _load_tool("mxlint")
        assert cli.main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rid in rules.RULES:
            assert rid in out

    def test_json_output(self, tmp_path, capsys):
        cli = _load_tool("mxlint")
        bad = tmp_path / "incubator_mxnet_tpu" / "m.py"
        bad.parent.mkdir()
        bad.write_text('import os\nv = os.environ.get("MXTPU_X", "")\n')
        assert cli.main(["--check", "--json", str(tmp_path)]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["count"] == 1
        assert doc["findings"][0]["rule"] == "raw-env-read"

    def test_mxdiag_lint_renders_report(self, tmp_path):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "mxdiag.py"),
             "lint", os.path.join(FIXTURES, "raw_env_read_bad.py")],
            capture_output=True, text=True)
        # fixtures lack the package prefix, so raw-env-read stays
        # quiet — but the report must render and exit clean
        assert "mxlint findings" in out.stdout


class TestRuntimeAuditor:
    def setup_method(self):
        runtime.disable()
        reset_counters()

    def teardown_method(self):
        runtime.disable()
        reset_counters()

    def _counters(self):
        return counters_snapshot()

    def test_injected_host_sync_fires_detection(self):
        """An NDArray materialization inside a guarded dispatch is a
        counted host-sync trip (the CPU-provable channel of the
        transfer-guard detector) — and the dispatch still completes."""
        aud = runtime.enable()
        x = nd.ones((4, 4))

        def leaky_step():
            return float(x.asnumpy().sum())      # injected host sync

        v = aud.guarded(leaky_step)
        assert v == 16.0                          # detection, not death
        c = self._counters()
        assert c["mxlint/mxlint.transfer_guard_trips"] == 1
        assert c["mxlint/mxlint.guarded_dispatches"] == 1

    def test_sync_outside_guard_not_counted(self):
        runtime.enable()
        x = nd.ones((2,))
        x.asnumpy()                               # legit boundary fetch
        assert self._counters().get(
            "mxlint/mxlint.transfer_guard_trips", 0) == 0

    def test_allowed_sync_counted_separately(self):
        aud = runtime.enable()
        x = nd.ones((2,))

        def step():
            with runtime.allowed_sync("boundary barrier"):
                x.asnumpy()
            return 1

        assert aud.guarded(step) == 1
        c = self._counters()
        assert c["mxlint/mxlint.transfer_guard_trips"] == 0
        assert c["mxlint/mxlint.allowed_syncs"] == 1

    def test_accelerator_guard_trip_counts_once_and_reraises(self):
        """On a real accelerator the jax guard raises mid-dispatch —
        the XLA execution already ran and may have donated its inputs,
        so there is NO side-effect-safe re-run: strict mode counts ONE
        trip and re-raises loudly (a re-run would double-apply the
        update — the CPU sentinel path is the detect-and-continue
        channel)."""
        aud = runtime.enable()
        calls = []

        def accelerator_like_step():
            calls.append(1)
            # what jax raises under transfer_guard("disallow")
            raise RuntimeError(
                "Disallowed device-to-host transfer: ...")

        with pytest.raises(RuntimeError, match="[Dd]isallowed"):
            aud.guarded(accelerator_like_step)
        assert len(calls) == 1                    # never re-run
        assert self._counters()[
            "mxlint/mxlint.transfer_guard_trips"] == 1

    def test_forced_rejit_fires_recompile_counter(self):
        """A perfscope capture of a known program name after warmup is
        a steady-state recompile: counted AND named."""
        aud = runtime.enable()
        aud.note_program("fused_step")            # warmup compile
        aud.mark_warmup_done()
        aud.note_program("fused_step")            # the storm
        aud.note_program("fused_step")
        aud.note_program("fresh_program")         # first sight: fine
        c = self._counters()
        assert c["mxlint/mxlint.recompiles"] == 2
        extra = runtime.bench_extra()
        assert extra["recompiles"] == 2
        assert extra["recompiled_programs"] == ["fused_step"]

    def test_recompile_hook_rides_record_program(self):
        """End-to-end through perfscope: record_program pushes into the
        armed auditor."""
        from incubator_mxnet_tpu.perfscope import cost
        runtime.enable()
        cost.record_program("prog_a", 1e9, 1e6)
        runtime.mark_warmup_done()
        cost.record_program("prog_a", 1e9, 1e6)
        assert self._counters()["mxlint/mxlint.recompiles"] == 1

    def test_donated_buffer_read_counted_and_reraised(self):
        import jax.numpy as jnp
        aud = runtime.enable()
        arr = jnp.ones((4,)) * 2

        def read_deleted():
            arr.delete()                          # stand-in for donation
            return float(arr[0])

        with pytest.raises(RuntimeError, match="[Dd]eleted"):
            aud.guarded(read_deleted)
        assert self._counters()[
            "mxlint/mxlint.donation_violations"] == 1

    def test_off_path_pays_one_predicate(self):
        """Strict off: no auditor, no mxlint counters, the ndarray/
        perfscope hooks are None (ONE predicate each)."""
        assert runtime.enabled() is False
        assert nd._STRICT_SYNC is None
        from incubator_mxnet_tpu.perfscope import cost
        assert cost._STRICT_HOOK is None
        x = nd.ones((8,))
        x.asnumpy()
        assert runtime.guarded(lambda: 41 + 1) == 42
        assert not [k for k in self._counters() if k.startswith("mxlint/")]

    def test_enable_installs_and_disable_removes_hooks(self):
        runtime.enable()
        from incubator_mxnet_tpu.perfscope import cost
        assert nd._STRICT_SYNC is not None
        assert cost._STRICT_HOOK is not None
        assert self._counters()["mxlint/mxlint.strict"] == 1
        runtime.disable()
        assert nd._STRICT_SYNC is None
        assert cost._STRICT_HOOK is None
        assert self._counters()["mxlint/mxlint.strict"] == 0

    def test_bench_extra_shapes_validate(self):
        tc = _load_tool("trace_check")
        assert runtime.bench_extra() == {"strict": False}
        assert tc.check_mxlint_extra({"strict": False}) == []
        aud = runtime.enable()
        x = nd.ones((2,))
        aud.guarded(lambda: x.asnumpy())          # one trip
        extra = runtime.bench_extra()
        assert extra["strict"] is True
        assert extra["findings"] == 1 == extra["transfer_guard_trips"]
        assert tc.check_mxlint_extra(extra) == []
        # findings gauge settles for the counters surface
        assert self._counters()["mxlint/mxlint.findings"] == 1

    def test_check_mxlint_extra_bad_shapes(self):
        tc = _load_tool("trace_check")
        assert tc.check_mxlint_extra(None) == []
        assert tc.check_mxlint_extra([]) != []
        assert tc.check_mxlint_extra({}) != []
        good = {"strict": True, "findings": 1,
                "transfer_guard_trips": 1, "allowed_syncs": 0,
                "recompiles": 0, "recompiled_programs": [],
                "donation_violations": 0, "guarded_dispatches": 5}
        assert tc.check_mxlint_extra(good) == []
        bad_sum = dict(good, findings=3)
        assert any("findings" in e
                   for e in tc.check_mxlint_extra(bad_sum))
        bad_named = dict(good, recompiled_programs=["x"])
        assert any("recompiled_programs" in e
                   for e in tc.check_mxlint_extra(bad_named))
        bad_neg = dict(good, recompiles=-1)
        assert tc.check_mxlint_extra(bad_neg) != []

    def test_strict_steady_loop_is_clean(self):
        """A real FusedTrainStep steady loop under the guard: zero
        trips, zero recompiles — the invariant the strict lenet smoke
        pins on the full bench path."""
        from incubator_mxnet_tpu import gluon
        from incubator_mxnet_tpu.parallel import FusedTrainStep
        net = gluon.nn.Dense(4)
        net.initialize()
        L = gluon.loss.L2Loss()
        opt = mx.optimizer.create("sgd", learning_rate=0.05)
        step = FusedTrainStep(net, L, opt)
        x = nd.ones((8, 6))
        y = nd.zeros((8, 4))
        float(step(x, y))                         # compile + warmup
        aud = runtime.enable()
        aud.mark_warmup_done()
        for _ in range(5):
            loss = aud.guarded(lambda: step(x, y))
        float(loss)                               # boundary: outside
        c = self._counters()
        assert c["mxlint/mxlint.transfer_guard_trips"] == 0
        assert c["mxlint/mxlint.recompiles"] == 0
        assert c["mxlint/mxlint.guarded_dispatches"] == 5
