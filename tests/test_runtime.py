"""Native runtime tests: dependency engine semantics, storage pool,
token queue, DataLoader prefetch pipeline (SURVEY.md §2.4, §2.27)."""
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, runtime
from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader


def test_native_builds():
    assert runtime.native_available(), "C++ runtime failed to build"


class TestEngine:
    def test_write_write_ordering(self):
        eng = runtime.Engine(4)
        v = eng.new_var()
        out = []
        for i in range(50):
            eng.push(lambda i=i: out.append(i), mutable_vars=[v])
        eng.wait_for_var(v)
        assert out == list(range(50))   # writes serialize in program order

    def test_reads_run_concurrently(self):
        eng = runtime.Engine(4)
        v = eng.new_var()
        active = []
        peak = []
        lock = threading.Lock()

        def reader():
            with lock:
                active.append(1)
                peak.append(len(active))
            time.sleep(0.05)
            with lock:
                active.pop()

        for _ in range(4):
            eng.push(reader, const_vars=[v])
        eng.wait_all()
        assert max(peak) > 1            # overlapping readers

    def test_write_waits_for_reads(self):
        eng = runtime.Engine(4)
        v = eng.new_var()
        events = []
        lock = threading.Lock()

        def slow_read():
            time.sleep(0.05)
            with lock:
                events.append("r")

        def write():
            with lock:
                events.append("w")

        eng.push(slow_read, const_vars=[v])
        eng.push(slow_read, const_vars=[v])
        eng.push(write, mutable_vars=[v])
        eng.wait_for_var(v)
        assert events == ["r", "r", "w"]

    def test_independent_vars_parallel(self):
        eng = runtime.Engine(4)
        v1, v2 = eng.new_var(), eng.new_var()
        t0 = time.perf_counter()
        for v in (v1, v2):
            eng.push(lambda: time.sleep(0.1), mutable_vars=[v])
        eng.wait_all()
        assert time.perf_counter() - t0 < 0.19   # ran in parallel

    def test_read_after_write_sees_result(self):
        eng = runtime.Engine(2)
        v = eng.new_var()
        box = {}
        eng.push(lambda: box.__setitem__("x", 42), mutable_vars=[v])
        got = []
        eng.push(lambda: got.append(box.get("x")), const_vars=[v])
        eng.wait_all()
        assert got == [42]

    def test_python_fallback_semantics(self):
        eng = runtime.Engine(4, force_python=True)
        v = eng.new_var()
        out = []
        for i in range(20):
            eng.push(lambda i=i: out.append(i), mutable_vars=[v])
        eng.wait_for_var(v)
        eng.wait_all()
        assert out == list(range(20))

    def test_python_fallback_write_waits_for_reads(self):
        """Regression: a write pushed after reads must wait for them."""
        eng = runtime.Engine(4, force_python=True)
        v = eng.new_var()
        events = []
        lock = threading.Lock()

        def slow_read():
            time.sleep(0.05)
            with lock:
                events.append("r")

        def write():
            with lock:
                events.append("w")

        eng.push(slow_read, const_vars=[v])
        eng.push(slow_read, const_vars=[v])
        eng.push(write, mutable_vars=[v])
        eng.wait_for_var(v)
        assert events == ["r", "r", "w"]

    def test_wait_for_unknown_var_returns(self):
        eng = runtime.Engine(2)
        eng.wait_for_var(999999)   # must not abort/hang

    def test_many_ops_stress(self):
        """Thunk lifetime: thousands of callbacks through the persistent
        dispatcher must not corrupt the process."""
        eng = runtime.Engine(8)
        v = [eng.new_var() for _ in range(8)]
        counter = {"n": 0}
        lock = threading.Lock()

        def bump():
            with lock:
                counter["n"] += 1

        for i in range(4000):
            eng.push(bump, mutable_vars=[v[i % 8]])
        eng.wait_all()
        assert counter["n"] == 4000


class TestStoragePool:
    def test_alloc_free_reuse(self):
        pool = runtime.StoragePool()
        p1 = pool.alloc(1000)
        assert p1
        stats = pool.stats()
        assert stats["bytes_in_use"] == 1024      # rounded to bucket
        pool.free(p1)
        stats = pool.stats()
        assert stats["bytes_in_use"] == 0
        assert stats["bytes_pooled"] == 1024
        p2 = pool.alloc(900)                       # same bucket -> reused
        assert p2 == p1
        assert pool.stats()["bytes_pooled"] == 0
        pool.free(p2)

    def test_double_free_ignored(self):
        pool = runtime.StoragePool()
        p = pool.alloc(64)
        pool.free(p)
        pool.free(p)                               # no crash, no double count
        assert pool.stats()["bytes_pooled"] == 256


class TestTokenQueue:
    def test_fifo_and_len(self):
        q = runtime.TokenQueue(8)
        for i in range(5):
            assert q.push(i)
        assert len(q) == 5
        assert [q.pop() for _ in range(5)] == list(range(5))

    def test_bounded_blocking_push(self):
        q = runtime.TokenQueue(2)
        q.push(0)
        q.push(1)
        state = {"pushed": False}

        def producer():
            q.push(2)                              # blocks until a pop
            state["pushed"] = True

        t = threading.Thread(target=producer)
        t.start()
        time.sleep(0.05)
        assert not state["pushed"]                 # still blocked (full)
        assert q.pop() == 0
        t.join(timeout=2)
        assert state["pushed"]

    def test_close_unblocks(self):
        q = runtime.TokenQueue(1)
        got = []

        def consumer():
            got.append(q.pop())

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.02)
        q.close()
        t.join(timeout=2)
        assert got == [None]
        assert q.push(7) is False                  # closed


class TestDataLoaderPrefetch:
    def _ds(self, n=64):
        x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
        y = np.arange(n, dtype=np.int32)
        return ArrayDataset(x, y)

    def test_ordered_and_complete(self):
        dl = DataLoader(self._ds(), batch_size=8, num_workers=3)
        seen = [b[1].asnumpy() for b in dl]
        np.testing.assert_array_equal(np.concatenate(seen), np.arange(64))

    def test_matches_sequential(self):
        ds = self._ds(40)
        seq = [b[0].asnumpy() for b in DataLoader(ds, batch_size=8)]
        par = [b[0].asnumpy() for b in
               DataLoader(ds, batch_size=8, num_workers=4)]
        for a, b in zip(seq, par):
            np.testing.assert_array_equal(a, b)

    def test_early_break_does_not_hang(self):
        dl = DataLoader(self._ds(), batch_size=4, num_workers=2, prefetch=2)
        it = iter(dl)
        next(it)
        it.close()                                  # generator close path

    def test_worker_exception_propagates(self):
        class Bad:
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise RuntimeError("boom")
                return np.zeros(2, np.float32)

        dl = DataLoader(Bad(), batch_size=4, num_workers=2)
        with pytest.raises(RuntimeError, match="boom"):
            list(dl)


def test_engine_push_error_propagates_to_wait():
    """An exception inside a pushed op must not vanish in the callback
    trampoline: it re-raises from wait_for_var(var) and wait_all()."""
    eng = runtime.Engine(num_threads=2)
    v = eng.new_var()

    def bad():
        raise ValueError("engine-op-boom")

    eng.push(bad, mutable_vars=[v])
    with pytest.raises(ValueError, match="engine-op-boom"):
        eng.wait_for_var(v)

    eng.push(bad, mutable_vars=[v])
    with pytest.raises(ValueError, match="engine-op-boom"):
        eng.wait_all()
    # errors are consumed once raised; subsequent waits are clean
    eng.wait_all()

    # unrelated vars don't see the error
    eng.push(bad, mutable_vars=[v])
    other = eng.new_var()
    eng.push(lambda: None, mutable_vars=[other])
    eng.wait_for_var(other)
    with pytest.raises(ValueError):
        eng.wait_all()


def test_features_pallas_flag_reflects_ops():
    from incubator_mxnet_tpu.ops import pallas
    feats = runtime.Features()
    assert feats.is_enabled("PALLAS") == bool(pallas.enabled())


def test_prefetch_window_is_bounded():
    """A straggler first batch must not let completed batches pile up past
    the prefetch window."""
    import time as _t
    peak = {"inflight": 0, "n": 0}
    lock = threading.Lock()

    class SlowFirst:
        def __len__(self):
            return 64

        def __getitem__(self, i):
            with lock:
                peak["n"] += 1
                peak["inflight"] = max(peak["inflight"], peak["n"])
            if i == 0:
                _t.sleep(0.3)
            with lock:
                peak["n"] -= 1
            return np.zeros(2, np.float32)

    dl = DataLoader(SlowFirst(), batch_size=4, num_workers=4, prefetch=3)
    list(dl)
    # in-flight batches bounded by prefetch window (x batch items)
    assert peak["inflight"] <= 3 * 4 + 4, peak


def test_engine_module_surface():
    from incubator_mxnet_tpu import engine
    assert engine.engine_type() in ("native", "python")
    v = engine.new_var()
    out = []
    engine.push(lambda: out.append(1), mutable_vars=[v])
    engine.wait_for_var(v)
    assert out == [1]
    engine.wait_all()
