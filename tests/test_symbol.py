"""Symbol API tests (mirrors reference tests/python/unittest/test_symbol.py
and test_executor.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import symbol as sym


def test_variable_and_arguments():
    x = sym.Variable("x")
    w = sym.Variable("w")
    y = sym.dot(x, w)
    assert y.list_arguments() == ["x", "w"]
    assert y.list_outputs() == [y.name + "_output"]


def test_compose_arithmetic_eval():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = 2.0 * a + b / 4 - 1.0
    ex = c.bind(args={"a": np.full((2, 3), 3.0, np.float32),
                      "b": np.full((2, 3), 8.0, np.float32)}, grad_req="null")
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, np.full((2, 3), 7.0), rtol=1e-6)


def test_mlp_infer_shape():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=64, name="fc1")
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, num_hidden=10, name="fc2")
    out = sym.SoftmaxOutput(fc2, sym.Variable("label"), name="softmax")
    args = out.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
                    "label"]
    arg_shapes, out_shapes, aux_shapes = out.infer_shape(data=(32, 100),
                                                         label=(32,))
    d = dict(zip(args, arg_shapes))
    assert d["fc1_weight"] == (64, 100)
    assert d["fc1_bias"] == (64,)
    assert d["fc2_weight"] == (10, 64)
    assert out_shapes == [(32, 10)]


def test_conv_infer_shape():
    data = sym.Variable("data")
    c1 = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                         name="conv1")
    p1 = sym.Pooling(c1, kernel=(2, 2), stride=(2, 2))
    arg_shapes, out_shapes, _ = p1.infer_shape(data=(2, 3, 32, 32))
    d = dict(zip(p1.list_arguments(), arg_shapes))
    assert d["conv1_weight"] == (8, 3, 3, 3)
    assert d["conv1_bias"] == (8,)
    assert out_shapes == [(2, 8, 16, 16)]


def test_batchnorm_aux_states():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data, name="bn")
    assert bn.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]
    assert "bn_gamma" in bn.list_arguments()
    ex = bn.simple_bind(data=(4, 3, 8, 8))
    x = np.random.randn(4, 3, 8, 8).astype(np.float32)
    ex.arg_dict["bn_gamma"][:] = 1.0
    mm0 = ex.aux_dict["bn_moving_mean"].asnumpy().copy()
    ex.forward(is_train=True, data=x)
    mm1 = ex.aux_dict["bn_moving_mean"].asnumpy()
    assert not np.allclose(mm0, mm1)  # train mode updates running stats


def test_simple_bind_forward_backward():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, num_hidden=4, name="fc")
    out = sym.SoftmaxOutput(fc, sym.Variable("label"), name="softmax")
    ex = out.simple_bind(data=(8, 5), label=(8,))
    rng = np.random.RandomState(0)
    ex.arg_dict["fc_weight"][:] = rng.randn(4, 5).astype(np.float32) * 0.1
    x = rng.randn(8, 5).astype(np.float32)
    y = rng.randint(0, 4, (8,)).astype(np.float32)
    outs = ex.forward(is_train=True, data=x, label=y)
    p = outs[0].asnumpy()
    np.testing.assert_allclose(p.sum(axis=1), np.ones(8), rtol=1e-5)
    ex.backward()
    gw = ex.grad_dict["fc_weight"].asnumpy()
    # reference semantics: dlogits = p - one_hot(label); dW = dlogits^T x
    oh = np.eye(4)[y.astype(int)]
    expect = (p - oh).T @ x
    np.testing.assert_allclose(gw, expect, rtol=1e-4, atol=1e-5)


def test_linear_regression_output_grad():
    x = sym.Variable("x")
    out = sym.LinearRegressionOutput(x, sym.Variable("label"))
    ex = out.simple_bind(x=(4, 2), label=(4, 2), grad_req="write")
    xv = np.random.randn(4, 2).astype(np.float32)
    lv = np.random.randn(4, 2).astype(np.float32)
    ex.forward(is_train=True, x=xv, label=lv)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(), xv - lv, rtol=1e-5)


def test_json_roundtrip():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=7, name="fc1")
    act = sym.Activation(fc1, act_type="tanh")
    js = act.tojson()
    act2 = sym.load_json(js)
    assert act2.list_arguments() == act.list_arguments()
    a1, o1, _ = act.infer_shape(data=(3, 4))
    a2, o2, _ = act2.infer_shape(data=(3, 4))
    assert o1 == o2 and a1 == a2
    # numeric parity
    ex1 = act.simple_bind(data=(3, 4))
    ex2 = act2.simple_bind(data=(3, 4))
    w = np.random.randn(7, 4).astype(np.float32)
    x = np.random.randn(3, 4).astype(np.float32)
    for ex in (ex1, ex2):
        ex.arg_dict["fc1_weight"][:] = w
        ex.forward(data=x)
    np.testing.assert_allclose(ex1.outputs[0].asnumpy(),
                               ex2.outputs[0].asnumpy(), rtol=1e-6)


def test_group_and_internals():
    a = sym.Variable("a")
    b = sym.relu(a, name="r")
    c = sym.tanh(a, name="t")
    g = sym.Group([b, c])
    assert len(g.list_outputs()) == 2
    ex = g.bind(args={"a": np.array([[-1.0, 2.0]], np.float32)}, grad_req="null")
    o = ex.forward()
    np.testing.assert_allclose(o[0].asnumpy(), [[0.0, 2.0]])
    np.testing.assert_allclose(o[1].asnumpy(), np.tanh([[-1.0, 2.0]]), rtol=1e-6)
    internals = b.get_internals()
    assert "a" in internals.list_outputs()[0]


def test_grad_req_add_and_null():
    x = sym.Variable("x")
    y = sym.sum(x * x)
    ex = y.bind(args={"x": np.array([1.0, 2.0], np.float32)},
                grad_req="add")
    ex.forward(is_train=True)
    ex.backward()
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(), [4.0, 8.0])


def test_slice_and_concat():
    a = sym.Variable("a")
    parts = sym.SliceChannel(a, num_outputs=2, axis=1)
    back = sym.Concat(parts[0], parts[1], dim=1)
    ex = back.bind(args={"a": np.arange(8, dtype=np.float32).reshape(2, 4)},
                   grad_req="null")
    np.testing.assert_allclose(ex.forward()[0].asnumpy(),
                               np.arange(8, dtype=np.float32).reshape(2, 4))


def test_dropout_train_vs_eval():
    x = sym.Variable("x")
    d = sym.Dropout(x, p=0.5)
    ex = d.bind(args={"x": np.ones((100, 100), np.float32)}, grad_req="null")
    out_eval = ex.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out_eval, np.ones((100, 100)))
    out_train = ex.forward(is_train=True)[0].asnumpy()
    assert (out_train == 0).mean() > 0.3


def test_backward_respects_train_mode_switch():
    # regression: backward jit must be keyed by is_train, not frozen
    x = sym.Variable("x")
    d = sym.sum(sym.Dropout(x, p=0.5))
    ex = d.bind(args={"x": np.ones((64, 64), np.float32)}, grad_req="write")
    ex.forward(is_train=False)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(), 1.0)  # no mask
    ex.forward(is_train=True)
    ex.backward()
    g = ex.grad_dict["x"].asnumpy()
    assert (g == 0).mean() > 0.3  # dropout mask applied in train backward


def test_infer_type():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, num_hidden=4, name="fc")
    args, outs, _ = fc.infer_type(data="float32")
    d = dict(zip(fc.list_arguments(), args))
    assert d["fc_weight"] == np.dtype(np.float32)
    assert outs == [np.dtype(np.float32)]


def test_load_json_no_name_collision():
    # regression: auto-name counter must advance past loaded node names
    a = sym.Variable("a")
    f1 = sym.FullyConnected(a, num_hidden=3)  # auto-named fullyconnected{N}
    loaded = sym.load_json(f1.tojson())
    f2 = sym.FullyConnected(loaded, num_hidden=2)
    args = f2.list_arguments()
    assert len(args) == len(set(args)), args


def test_attr_scope():
    import incubator_mxnet_tpu as mx
    with mx.AttrScope(group="stage1", lr_mult="2"):
        a = mx.sym.Variable("a")
        with mx.AttrScope(group="stage2"):
            b = mx.sym.Variable("b")
    c = mx.sym.Variable("c")
    assert a.attr("group") == "stage1" and a.attr("lr_mult") == "2"
    assert b.attr("group") == "stage2" and b.attr("lr_mult") == "2"
    assert c.attr("group") is None


def test_svm_output_hinge_gradients():
    """Parity: mx.sym.SVMOutput (src/operator/svm_output.cc) — identity
    forward, one-vs-all hinge backward; L1 and L2 variants."""
    x = np.array([[2.0, 0.5, -1.0]], np.float32)
    lab = np.array([0.0], np.float32)
    for use_linear, want in ((True, [[0.0, 1.0, 0.0]]),
                             # L2: -2*y*max(0, 1-y*x): y=[+1,-1,-1],
                             # viol=[-1,1.5,0] -> [0, 2*1.5, 0]
                             (False, [[0.0, 3.0, 0.0]])):
        out = sym.SVMOutput(sym.Variable("d"), sym.Variable("l"),
                            margin=1.0, use_linear=use_linear)
        ex = out.bind(args={"d": x, "l": lab},
                      args_grad={"d": np.zeros_like(x)},
                      grad_req={"d": "write", "l": "null"})
        np.testing.assert_allclose(ex.forward(is_train=True)[0].asnumpy(),
                                   x)
        ex.backward()
        np.testing.assert_allclose(ex.grad_dict["d"].asnumpy(), want)
