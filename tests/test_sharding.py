"""mxtpu.sharding tier-1 (ISSUE 8): mesh registry + logical axis rules,
Block.shard annotations, resolution fallbacks, the sharded one-jit
executor's bit-parity matrix (dp / dp×mp / fsdp vs the single-device
trainer), FSDP per-device memory reduction, and the subprocess CPU-mesh
matrix on 4 REAL fake devices (shard_matrix_worker.py)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.gluon.trainer import Trainer
from incubator_mxnet_tpu.parallel import (FusedTrainStep, fsdp, make_mesh,
                                          sharding)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


@pytest.fixture(autouse=True)
def _clean_registry():
    """Every test starts and ends without a process-global mesh."""
    sharding.clear_mesh()
    yield
    sharding.clear_mesh()


@pytest.fixture(autouse=True)
def _no_persistent_compile_cache():
    """Same hazard as tests/test_sharded_checkpoint.py: this jaxlib's CPU
    backend has mis-deserialized persistent-cache entries for donated
    sharded fused-step executables. Compile fresh in this module."""
    from jax._src import compilation_cache as cc
    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    cc.reset_cache()
    yield
    jax.config.update("jax_enable_compilation_cache", old)
    cc.reset_cache()


def _net():
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"),
            nn.Dense(16, activation="relu"),
            nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    return net


def _data(seed, batch=16):
    rng = np.random.RandomState(seed)
    return (nd.array(rng.randn(batch, 8).astype(np.float32)),
            nd.array(rng.randint(0, 4, batch)))


def _run(mode=None, mesh=None, n=4, annotate=None, momentum=0.0, **kw):
    net = _net()
    if annotate is not None:
        annotate(net)
    step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mx.optimizer.create("sgd", learning_rate=0.1,
                                              momentum=momentum),
                          mesh=mesh, sharding=mode, **kw)
    return [float(step(*_data(100 + i))) for i in range(n)], step


@pytest.fixture(scope="module")
def ref_losses():
    """Single-device reference, computed once for the parity matrix."""
    sharding.clear_mesh()
    losses, _ = _run()
    return losses


# ---------------------------------------------------------------------------
# make_mesh edge cases
# ---------------------------------------------------------------------------

class TestMakeMesh:
    def test_minus1_absorbs_remainder(self):
        mesh = make_mesh({"dp": -1, "mp": 2})
        assert mesh.shape == {"dp": len(jax.devices()) // 2, "mp": 2}

    def test_multiple_minus1_rejected(self):
        with pytest.raises(ValueError, match="more than one -1"):
            make_mesh({"dp": -1, "mp": -1})

    def test_oversubscribed_message_names_counts(self):
        with pytest.raises(ValueError, match=r"needs 16 devices.*have 8"):
            make_mesh({"dp": 4, "mp": 4})

    def test_minus1_nondividing_rejected(self):
        with pytest.raises(ValueError, match="do not divide evenly"):
            make_mesh({"dp": -1, "mp": 3})

    def test_zero_and_negative_sizes_rejected(self):
        with pytest.raises(ValueError, match="must be positive"):
            make_mesh({"dp": 0})
        with pytest.raises(ValueError, match="must be positive"):
            make_mesh({"dp": -2})

    def test_single_device_mesh_is_a_noop(self, ref_losses):
        """A 1-device mesh must train bit-identically to no mesh at all
        (laptop-to-pod: same construction code everywhere)."""
        mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
        losses, step = _run(mode="auto", mesh=mesh)
        assert losses == ref_losses
        assert all(p.data()._data.sharding.spec == P()
                   for p in step.params)


# ---------------------------------------------------------------------------
# mesh registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_set_get_clear(self):
        assert sharding.get_mesh() is None
        mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
        assert sharding.set_mesh(mesh) is mesh
        assert sharding.get_mesh() is mesh
        sharding.clear_mesh()
        assert sharding.get_mesh() is None

    def test_required_raises_without_mesh(self):
        with pytest.raises(RuntimeError, match="no global mesh"):
            sharding.get_mesh(required=True)

    def test_use_mesh_scopes_and_restores(self):
        outer = make_mesh({"dp": 2}, devices=jax.devices()[:2])
        inner = make_mesh({"dp": 4}, devices=jax.devices()[:4])
        sharding.set_mesh(outer)
        with sharding.use_mesh(inner):
            assert sharding.get_mesh() is inner
        assert sharding.get_mesh() is outer

    def test_axis_detection(self):
        mesh = make_mesh({"dp": 2, "mp": 2}, devices=jax.devices()[:4])
        assert sharding.data_axis(mesh) == "dp"
        assert sharding.model_axis(mesh) == "mp"
        tp_mesh = make_mesh({"dp": 4, "tp": 2})
        assert sharding.model_axis(tp_mesh) == "tp"   # seed helper alias
        assert sharding.data_axis(make_mesh({"sp": 8})) is None


# ---------------------------------------------------------------------------
# logical axis rules + resolution
# ---------------------------------------------------------------------------

class TestRules:
    def test_mesh_axes_pass_through(self):
        mesh = make_mesh({"dp": 2, "mp": 2}, devices=jax.devices()[:4])
        assert sharding.resolve_axis("mp", mesh) == "mp"
        assert sharding.resolve_axis(None, mesh) is None

    def test_logical_names_map_by_rule_priority(self):
        mp_mesh = make_mesh({"dp": 2, "mp": 2}, devices=jax.devices()[:4])
        tp_mesh = make_mesh({"dp": 4, "tp": 2})
        assert sharding.resolve_axis("model", mp_mesh) == "mp"
        assert sharding.resolve_axis("model", tp_mesh) == "tp"
        assert sharding.resolve_axis("batch", mp_mesh) == "dp"

    def test_unknown_logical_replicates(self):
        mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
        assert sharding.resolve_axis("model", mesh) is None   # no mp/tp
        assert sharding.resolve_axis("garbage", mesh) is None

    def test_axis_rules_prepend_and_restore(self):
        mesh = make_mesh({"dp": 2, "mp": 2}, devices=jax.devices()[:4])
        with sharding.axis_rules(("model", None)):
            assert sharding.resolve_axis("model", mesh) is None
            with sharding.axis_rules(("model", "dp")):
                assert sharding.resolve_axis("model", mesh) == "dp"
            assert sharding.resolve_axis("model", mesh) is None
        assert sharding.resolve_axis("model", mesh) == "mp"

    def test_axis_rules_validates_pairs(self):
        with pytest.raises(ValueError, match="2-tuples"):
            with sharding.axis_rules("model"):
                pass

    def test_resolve_spec_tuples_and_trailing_none(self):
        mesh = make_mesh({"dp": 2, "mp": 2}, devices=jax.devices()[:4])
        assert sharding.resolve_spec(P(("dp", "mp"), None), mesh) \
            == P(("dp", "mp"))
        assert sharding.resolve_spec(P("vocab", None), mesh) == P("mp")
        assert sharding.resolve_spec(None, mesh) == P()

    def test_resolve_param_divisibility_fallback(self):
        mesh = make_mesh({"dp": 2, "mp": 2}, devices=jax.devices()[:4])
        from incubator_mxnet_tpu.gluon.parameter import Parameter
        good = Parameter("w", shape=(8, 4))
        good._sharding = P("mp", None)
        assert sharding.resolve_param(good, mesh).spec == P("mp")
        odd = Parameter("w2", shape=(7, 4))          # 7 % 2 != 0
        odd._sharding = P("mp", None)
        from incubator_mxnet_tpu import profiler as prof
        before = prof.counters().get(
            "sharding/sharding.fallback_replicated", 0)
        assert sharding.resolve_param(odd, mesh).spec == P()
        assert prof.counters()["sharding/sharding.fallback_replicated"] \
            == before + 1


# ---------------------------------------------------------------------------
# Block.shard + auto_shard
# ---------------------------------------------------------------------------

class TestBlockShard:
    def test_spec_applies_to_matching_rank_recursively(self):
        net = _net()
        net.shard(P("model", None))
        for blk in net._children.values():
            assert blk.weight._sharding == P("model", None)
            assert blk.bias._sharding is None          # 1-D: untouched

    def test_by_name_kwargs(self):
        net = _net()
        dense = list(net._children.values())[0]
        dense.shard(weight=P(None, "mp"), bias=P())
        assert dense.weight._sharding == P(None, "mp")
        assert dense.bias._sharding == P()

    def test_none_clears_subtree(self):
        net = _net()
        net.shard(P("model", None))
        net.shard(None)
        assert all(p._sharding is None
                   for p in net.collect_params().values())

    def test_rejects_non_partitionspec(self):
        net = _net()
        with pytest.raises(TypeError, match="PartitionSpec"):
            net.shard(("model", None))
        with pytest.raises(TypeError, match="PartitionSpec"):
            net.shard(weight="mp")

    def test_unmatched_keyword_raises(self):
        """A typo'd keyword must not leave the model silently
        replicated while the user believes it is sharded."""
        dense = list(_net()._children.values())[0]
        with pytest.raises(ValueError, match="wieght"):
            dense.shard(wieght=P("model", None))

    def test_auto_shard_defaults(self):
        net = nn.HybridSequential()
        net.add(nn.Dense(16), nn.BatchNorm(), nn.Embedding(12, 8))
        sharding.auto_shard(net)
        dense, bn, emb = net._children.values()
        assert dense.weight._sharding == P("model", None)
        assert dense.bias._sharding is None
        assert emb.weight._sharding == P("model", None)
        assert bn.gamma._sharding is None and bn.beta._sharding is None

    def test_auto_shard_keeps_existing_annotations(self):
        net = nn.HybridSequential()
        net.add(nn.Dense(16))
        dense = list(net._children.values())[0]
        dense.weight._sharding = P(None, "mp")
        sharding.auto_shard(net)
        assert dense.weight._sharding == P(None, "mp")


# ---------------------------------------------------------------------------
# the sharded executor: bit-parity matrix + layouts (in-process, 4 of
# the suite's 8 virtual devices)
# ---------------------------------------------------------------------------

class TestShardedExecutor:
    def test_dp4_bit_identical(self, ref_losses):
        sharding.set_mesh(make_mesh({"dp": 4}, devices=jax.devices()[:4]))
        losses, step = _run(mode="dp")
        assert losses == ref_losses          # BIT-level, not allclose
        assert step.mesh is sharding.get_mesh()   # registry pickup

    def test_2x2_auto_bit_identical_and_mp_sharded(self, ref_losses):
        sharding.set_mesh(make_mesh({"dp": 2, "mp": 2},
                                    devices=jax.devices()[:4]))
        losses, step = _run(mode="auto")
        assert losses == ref_losses
        # 'auto' resolves ephemerally: the net's own annotations stay
        # untouched, so a later 'dp' build is not silently model-sharded
        assert all(p._sharding is None for p in step.params)
        specs = {p.name: p.data()._data.sharding.spec for p in step.params}
        weights = {k: v for k, v in specs.items() if "weight" in k}
        biases = {k: v for k, v in specs.items() if "bias" in k}
        assert weights and all("mp" in str(s) for s in weights.values())
        assert all(s == P() for s in biases.values())
        # shard shapes: units dim really split in half on device 0
        w0 = next(p for p in step.params if "weight" in p.name)
        shard0 = next(iter(w0.data()._data.addressable_shards)).data
        assert shard0.shape[0] * 2 == w0.shape[0]

    def test_explicit_logical_annotation_bit_identical(self, ref_losses):
        sharding.set_mesh(make_mesh({"dp": 2, "mp": 2},
                                    devices=jax.devices()[:4]))
        losses, step = _run(mode="dp",
                            annotate=lambda n: n.shard(P("model", None)))
        assert losses == ref_losses
        assert any("mp" in str(p.data()._data.sharding.spec)
                   for p in step.params)

    def test_axis_rules_pin_replicated(self, ref_losses):
        sharding.set_mesh(make_mesh({"dp": 2, "mp": 2},
                                    devices=jax.devices()[:4]))
        with sharding.axis_rules(("model", None)):
            losses, step = _run(mode="auto")
        assert losses == ref_losses
        assert all(p.data()._data.sharding.spec == P()
                   for p in step.params)

    def test_fsdp_parity_memory_and_states(self):
        """FSDP: same math up to the collective's reduction order (~1 ulp
        per step on XLA:CPU), params AND momentum sharded over dp, and
        per-device bytes reduced by ~the dp degree."""
        sharding.clear_mesh()
        ref, _ = _run(momentum=0.9)
        sharding.set_mesh(make_mesh({"dp": 4}, devices=jax.devices()[:4]))
        losses, step = _run(mode="fsdp", momentum=0.9)
        np.testing.assert_allclose(losses, ref, rtol=1e-5, atol=1e-6)
        specs = [p.data()._data.sharding.spec for p in step.params]
        assert any("dp" in str(s) for s in specs)
        state_specs = [getattr(s, "sharding", None).spec
                       for s in jax.tree_util.tree_leaves(step._states)]
        assert any("dp" in str(s) for s in state_specs)
        report = fsdp.memory_report(step)
        assert report["param_bytes_per_device"] \
            < report["param_bytes_logical"]
        assert report["reduction"] >= 2.0
        assert report["state_bytes_per_device"] > 0
        summ = sharding.summary()
        assert summ["fsdp"] and summ["params_data_sharded"] > 0

    def test_fsdp_honors_explicit_replicate_pin(self):
        """An explicit replicate annotation (shard(weight=P())) is the
        user saying "no per-step all-gathers for this one" — FSDP must
        not dp-shard it anyway (the every-mode annotation contract)."""
        sharding.set_mesh(make_mesh({"dp": 4}, devices=jax.devices()[:4]))

        def pin_first(net):
            list(net._children.values())[0].shard(weight=P())

        losses, step = _run(mode="fsdp", annotate=pin_first)
        pinned = next(p for p in step.params if p._sharding == P())
        assert pinned.data()._data.sharding.spec == P()
        # the rest still FSDP-shard
        assert any("dp" in str(p.data()._data.sharding.spec)
                   for p in step.params)

    def test_fsdp_shards_dissolved_annotations(self):
        """An auto_shard'ed net (P('model', None) annotations) on a
        dp-ONLY mesh: 'model' dissolves, and FSDP must still shard the
        weights over dp — a dissolved hint must not silently cost the
        mode its entire memory saving."""
        sharding.set_mesh(make_mesh({"dp": 4}, devices=jax.devices()[:4]))
        losses, step = _run(mode="fsdp", annotate=sharding.auto_shard)
        weights = [p for p in step.params if "weight" in p.name]
        assert weights and all(
            "dp" in str(p.data()._data.sharding.spec) for p in weights)

    def test_dissolved_annotation_counts_fallback(self):
        """'counted, never silent': an annotation whose axes don't exist
        on this mesh must tick sharding.fallback_replicated."""
        from incubator_mxnet_tpu import profiler as prof
        from incubator_mxnet_tpu.gluon.parameter import Parameter
        mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
        p = Parameter("w", shape=(8, 4))
        p._sharding = P("model", None)     # no mp/tp on this mesh
        before = prof.counters().get(
            "sharding/sharding.fallback_replicated", 0)
        assert sharding.resolve_param(p, mesh).spec == P()
        assert prof.counters()["sharding/sharding.fallback_replicated"] \
            == before + 1
        # an explicit pin is NOT a fallback — requested and delivered
        p2 = Parameter("w2", shape=(8, 4))
        p2._sharding = P()
        assert sharding.resolve_param(p2, mesh).spec == P()
        assert prof.counters()["sharding/sharding.fallback_replicated"] \
            == before + 1

    def test_mesh_gauges_zeroed_on_clear(self):
        from incubator_mxnet_tpu import profiler as prof
        sharding.set_mesh(make_mesh({"dp": 4}, devices=jax.devices()[:4]))
        assert prof.counters()["sharding/sharding.mesh_devices"] == 4
        sharding.clear_mesh()
        assert prof.counters()["sharding/sharding.mesh_devices"] == 0

    def test_fsdp_spec_edge_cases(self):
        mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
        assert fsdp.fsdp_spec((8, 3), mesh) == P("dp", None)
        assert fsdp.fsdp_spec((7, 3), mesh) is None     # 7 % 4
        assert fsdp.fsdp_spec((), mesh) is None         # scalar
        one = make_mesh({"dp": 1}, devices=jax.devices()[:1])
        assert fsdp.fsdp_spec((8,), one) is None        # dp degree 1

    def test_invalid_mode_rejected(self):
        with pytest.raises(ValueError, match="unknown sharding mode"):
            _run(mode="zap")

    def test_trainer_flag_and_env_plumb_through(self, monkeypatch):
        net = _net()
        tr = Trainer(net.collect_params(), "sgd",
                     {"learning_rate": 0.1}, sharding="fsdp")
        assert tr.sharding == "fsdp"
        sharding.set_mesh(make_mesh({"dp": 4}, devices=jax.devices()[:4]))
        step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
        assert step.sharding == "fsdp"
        assert step.shard_optimizer_states
        monkeypatch.setenv("MXTPU_SHARDING", "auto")
        tr2 = Trainer(_net().collect_params(), "sgd")
        assert tr2.sharding == "auto"
        monkeypatch.setenv("MXTPU_SHARDING", "bogus")
        with pytest.raises(ValueError, match="unknown sharding mode"):
            Trainer(_net().collect_params(), "sgd")

    def test_trainloop_sharded_chunk_bit_identical(self, ref_losses):
        """The whole-loop executor under a mesh: one donated program per
        2-step chunk, dp-sharded stacked batches, constant lr — losses
        must equal the single-device sequential run bit-for-bit."""
        from incubator_mxnet_tpu.trainloop import TrainLoop
        import jax.numpy as jnp
        sharding.set_mesh(make_mesh({"dp": 4}, devices=jax.devices()[:4]))
        net = _net()
        tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                     sharding="dp", loop_chunk=2)
        loop = TrainLoop(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
        out = []
        for c in range(2):
            xs = jnp.stack([_data(100 + 2 * c + i)[0]._data
                            for i in range(2)])
            ys = jnp.stack([_data(100 + 2 * c + i)[1]._data
                            for i in range(2)])
            out.extend(float(v) for v in loop.run_chunk(xs, ys).asnumpy())
        assert out == ref_losses
        assert loop.step.mesh is sharding.get_mesh()


# ---------------------------------------------------------------------------
# integrations: kvstore mesh reuse, diagnostics per-device census,
# seed helpers over the registry
# ---------------------------------------------------------------------------

class TestIntegrations:
    def test_kvstore_reuses_registry_mesh(self):
        from incubator_mxnet_tpu.kvstore import _BucketedAllReduce
        from incubator_mxnet_tpu import profiler as prof
        devs = tuple(jax.devices())
        gm = sharding.set_mesh(make_mesh({"dp": -1}))
        before = prof.counters().get("mxtpu/kvstore.mesh_reuse", 0)
        mesh = _BucketedAllReduce._collective_mesh(devs)
        assert mesh is gm                 # IDENTITY reuse, not a copy
        assert prof.counters()["mxtpu/kvstore.mesh_reuse"] == before + 1
        # subset of the registry devices: falls back to a private mesh
        sub = _BucketedAllReduce._collective_mesh(devs[:4])
        assert prof.counters()["mxtpu/kvstore.mesh_reuse"] == before + 1
        assert sub.devices.shape == (4,) and sub.axis_names == ("kv",)
        # a multi-axis registry mesh can't flatten to the reduce's one
        # axis — private mesh, not counted
        sharding.set_mesh(make_mesh({"dp": 4, "mp": 2}))
        multi = _BucketedAllReduce._collective_mesh(devs)
        assert multi.axis_names == ("kv",)
        assert prof.counters()["mxtpu/kvstore.mesh_reuse"] == before + 1

    def test_kvstore_aggregation_rides_reused_mesh(self):
        """End to end: device aggregation with the registry mesh reused
        still sums correctly (the reduce must use the mesh's own axis
        name — 'dp' here — not a hardcoded 'kv')."""
        import jax.numpy as jnp
        gm = sharding.set_mesh(make_mesh({"dp": -1}))
        kv = mx.kv.create("dist_sync_device")
        devs = jax.devices()
        shards_np = [np.full((3, 5), i + 1.0, np.float32)
                     for i in range(len(devs))]
        shards = [nd.NDArray(jax.device_put(jnp.asarray(s), d))
                  for s, d in zip(shards_np, devs)]
        out = [nd.array(np.zeros((3, 5), np.float32))]
        kv.pushpull(["g0"], [shards], out=out)
        np.testing.assert_allclose(out[0].asnumpy(),
                                   np.sum(shards_np, axis=0))
        (_, mesh), = kv._allreduce._reduce_cache.values()
        assert mesh is gm                  # the reduce compiled ON it

    def test_reconcile_reports_per_device_bytes(self):
        import jax.numpy as jnp
        from incubator_mxnet_tpu.diagnostics import memory as dmem
        from jax.sharding import NamedSharding
        mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
        base = dmem.reconcile()["per_device_live_bytes"]
        big = jnp.zeros((1024, 256), jnp.float32)          # 1 MiB
        repl = jax.device_put(big, NamedSharding(mesh, P()))
        shrd = jax.device_put(big, NamedSharding(mesh, P("dp")))
        after = dmem.reconcile()["per_device_live_bytes"]
        d0 = str(jax.devices()[0])
        delta = after.get(d0, 0) - base.get(d0, 0)
        # replicated costs 1 MiB on device 0, the dp shard 1/4 MiB
        assert delta >= big.nbytes + big.nbytes // 4
        del repl, shrd

    def test_tensor_parallel_defaults_via_registry(self):
        from incubator_mxnet_tpu.parallel import column_parallel, row_parallel
        sharding.set_mesh(make_mesh({"dp": 4, "tp": 2}))
        d = nn.Dense(8, in_units=4)
        column_parallel(d)                       # axis=None → registry tp
        assert d.weight._sharding == P("tp", None)
        sharding.clear_mesh()
        d2 = nn.Dense(8, in_units=4)
        row_parallel(d2)                         # no mesh → logical name
        assert d2.weight._sharding == P(None, "model")

    def test_moe_resolve_shardings_via_registry(self):
        from incubator_mxnet_tpu.parallel import MoEFFN
        layer = MoEFFN(8, 16, 32)
        sharding.set_mesh(make_mesh({"ep": 8}))
        resolved = layer.resolve_shardings()
        assert resolved["w1"].spec == P("ep")
        assert resolved["gate_w"].spec == P()
        # an ep the expert count doesn't divide → replicated, not an error
        bad = MoEFFN(6, 16, 32)
        assert bad.resolve_shardings()["w1"].spec == P()
        sharding.clear_mesh()
        with pytest.raises(RuntimeError, match="no global mesh"):
            layer.resolve_shardings()


# ---------------------------------------------------------------------------
# the subprocess CPU-mesh matrix: 4 REAL fake devices per layout
# (what the in-process tests can't prove: the layouts on a genuine
# 4-device process, plus the FSDP checkpoint round trip — the
# migrated zero1 coverage lives in test_sharded_checkpoint.py)
# ---------------------------------------------------------------------------

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "shard_matrix_worker.py")


def _run_worker(layout, *extra):
    env = dict(os.environ)
    # the worker pins its own XLA_FLAGS/JAX_PLATFORMS before importing jax
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run([sys.executable, _WORKER, layout, *extra],
                          capture_output=True, text=True, timeout=240,
                          env=env)
    assert proc.returncode == 0, \
        f"worker {layout} rc={proc.returncode}:\n{proc.stderr[-2000:]}"
    return json.loads(proc.stdout.strip().splitlines()[-1])


class TestSubprocessMatrix:
    @pytest.fixture(scope="class")
    def matrix(self):
        return {layout: _run_worker(layout)
                for layout in ("single", "dp2mp2", "fsdp4")}

    def test_2x2_bit_identical_to_single_device(self, matrix):
        assert matrix["dp2mp2"]["devices"] == 4
        assert matrix["dp2mp2"]["losses_hex"] \
            == matrix["single"]["losses_hex"]

    def test_2x2_weights_on_mp_with_halved_shards(self, matrix):
        specs = matrix["dp2mp2"]["specs"]
        shard0 = matrix["dp2mp2"]["shard0_shapes"]
        weights = [k for k in specs if "weight" in k]
        assert weights
        for k in weights:
            assert "mp" in specs[k], f"{k}: {specs[k]}"
        # dense_0: (32, 8) weight → (16, 8) per mp shard
        w0 = weights[0]
        assert shard0[w0][0] * 2 == 32

    def test_fsdp_parity_and_per_device_reduction(self, matrix):
        single, fs = matrix["single"], matrix["fsdp4"]
        np.testing.assert_allclose(fs["losses"], single["losses"],
                                   rtol=1e-5, atol=1e-6)
        rep = fs["report"]
        assert rep["reduction"] >= 2.0
        # the diagnostics ledger census agrees: device 0 holds fewer
        # live bytes than the logical param total would cost replicated
        per_dev = fs["per_device_live_bytes"]
        assert per_dev and all(v > 0 for v in per_dev.values())
