"""tp/pp/sp/ep parallelism tests on the 8-virtual-device CPU mesh
(SURVEY.md §2.22, §4: parity of distributed vs single-device math)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.parallel import (
    make_mesh, ring_attention, ring_self_attention, pipeline_apply,
    moe_ffn, MoEFFN, annotate_bert_tp, FusedTrainStep)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def _ref_attention(q, k, v, causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (d ** 0.5)
    if causal:
        L = q.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((L, L), bool)), s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


# ---------------------------------------------------------------------------
# sp: ring attention
# ---------------------------------------------------------------------------

class TestRingAttention:
    def test_matches_dense(self):
        mesh = make_mesh({"sp": 8})
        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(2, 4, 64, 16), jnp.float32)
                   for _ in range(3))
        out = ring_attention(q, k, v, mesh, "sp")
        ref = _ref_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_causal_matches_dense(self):
        mesh = make_mesh({"sp": 8})
        rng = np.random.RandomState(1)
        q, k, v = (jnp.asarray(rng.randn(1, 2, 32, 8), jnp.float32)
                   for _ in range(3))
        out = ring_attention(q, k, v, mesh, "sp", causal=True)
        ref = _ref_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.slow
    def test_grad_matches_dense(self):
        mesh = make_mesh({"sp": 4})
        rng = np.random.RandomState(2)
        q, k, v = (jnp.asarray(rng.randn(1, 2, 16, 8), jnp.float32)
                   for _ in range(3))

        g_ring = jax.grad(lambda a, b, c: ring_attention(
            a, b, c, mesh, "sp").sum())(q, k, v)
        g_ref = jax.grad(lambda a, b, c: _ref_attention(a, b, c).sum())(q, k, v)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                                   rtol=1e-3, atol=1e-3)

    def test_jit_sharded_inputs(self):
        mesh = make_mesh({"sp": 8})
        rng = np.random.RandomState(3)
        q, k, v = (jnp.asarray(rng.randn(2, 2, 128, 16), jnp.float32)
                   for _ in range(3))
        spec = NamedSharding(mesh, P(None, None, "sp", None))
        qs, ks, vs = (jax.device_put(t, spec) for t in (q, k, v))
        out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh, "sp",
                                                     causal=True))(qs, ks, vs)
        ref = _ref_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_ring_self_attention_block(self):
        mesh = make_mesh({"sp": 4})
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(2, 32, 16), jnp.float32)
        wqkv = jnp.asarray(rng.randn(16, 48) * 0.1, jnp.float32)
        wo = jnp.asarray(rng.randn(16, 16) * 0.1, jnp.float32)
        out = ring_self_attention(x, wqkv, wo, 4, mesh, "sp")
        q, k, v = jnp.split(x @ wqkv, 3, -1)

        def heads(t):
            return t.reshape(2, 32, 4, 4).transpose(0, 2, 1, 3)
        ref = _ref_attention(heads(q), heads(k), heads(v))
        ref = ref.transpose(0, 2, 1, 3).reshape(2, 32, 16) @ wo
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# pp: pipeline
# ---------------------------------------------------------------------------

class TestPipeline:
    def _stage(self, params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    def _stack(self, rng, n, d):
        return {"w": jnp.asarray(rng.randn(n, d, d) * 0.3, jnp.float32),
                "b": jnp.asarray(rng.randn(n, d) * 0.1, jnp.float32)}

    def test_matches_sequential(self):
        mesh = make_mesh({"pp": 4})
        rng = np.random.RandomState(0)
        params = self._stack(rng, 4, 8)
        x = jnp.asarray(rng.randn(16, 8), jnp.float32)
        y = pipeline_apply(self._stage, params, x, mesh, axis="pp", n_micro=4)
        ref = x
        for s in range(4):
            ref = self._stage({"w": params["w"][s], "b": params["b"][s]}, ref)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_more_microbatches_than_stages(self):
        mesh = make_mesh({"pp": 2})
        rng = np.random.RandomState(1)
        params = self._stack(rng, 2, 4)
        x = jnp.asarray(rng.randn(24, 4), jnp.float32)
        y = pipeline_apply(self._stage, params, x, mesh, axis="pp", n_micro=8)
        ref = x
        for s in range(2):
            ref = self._stage({"w": params["w"][s], "b": params["b"][s]}, ref)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_grad_flows(self):
        mesh = make_mesh({"pp": 4})
        rng = np.random.RandomState(2)
        params = self._stack(rng, 4, 8)
        x = jnp.asarray(rng.randn(8, 8), jnp.float32)

        def loss_pp(p):
            return pipeline_apply(self._stage, p, x, mesh,
                                  axis="pp", n_micro=4).sum()

        def loss_seq(p):
            h = x
            for s in range(4):
                h = self._stage({"w": p["w"][s], "b": p["b"][s]}, h)
            return h.sum()

        g_pp = jax.grad(loss_pp)(params)
        g_seq = jax.grad(loss_seq)(params)
        np.testing.assert_allclose(np.asarray(g_pp["w"]),
                                   np.asarray(g_seq["w"]),
                                   rtol=1e-4, atol=1e-4)

    def test_shape_change_rejected(self):
        mesh = make_mesh({"pp": 2})
        params = {"w": jnp.zeros((2, 4, 6))}
        with pytest.raises(ValueError, match="preserve activation shape"):
            pipeline_apply(lambda p, x: x @ p["w"], params,
                           jnp.zeros((8, 4)), mesh, axis="pp")


# ---------------------------------------------------------------------------
# ep: mixture of experts
# ---------------------------------------------------------------------------

class TestMoE:
    @pytest.mark.slow
    def test_top1_routes_to_best_expert(self):
        # gate that deterministically prefers expert = token % E
        e, d = 4, 8
        layer = MoEFFN(e, d, 16, top_k=1, capacity_factor=4.0)
        params = layer.init(jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.RandomState(0).randn(2, 12, d), jnp.float32)
        y, aux = layer(params, x)
        assert y.shape == x.shape
        assert np.isfinite(np.asarray(y)).all()
        assert 0.0 < float(aux) < 10.0  # balance loss ~1 near uniform routing

    def test_capacity_drops_tokens(self):
        # all tokens prefer expert 0; capacity 1 keeps only the first
        d, e = 4, 2
        gate_w = jnp.zeros((d, e)).at[:, 0].set(5.0)
        w1 = jnp.ones((e, d, 4)) * 0.1
        b1 = jnp.zeros((e, 4))
        w2 = jnp.ones((e, 4, d)) * 0.1
        b2 = jnp.zeros((e, d))
        x = jnp.ones((1, 4, d))
        y, _ = moe_ffn(x, gate_w, w1, b1, w2, b2, top_k=1,
                       capacity_factor=0.5)  # cap = 1
        y = np.asarray(y)
        assert np.abs(y[0, 0]).sum() > 0          # first token served
        assert np.abs(y[0, 2:]).sum() == 0        # overflow tokens dropped

    def test_ep_sharded_matches_local(self):
        mesh = make_mesh({"ep": 8})
        layer = MoEFFN(8, 16, 32, top_k=2)
        params = layer.init(jax.random.PRNGKey(1))
        x = jnp.asarray(np.random.RandomState(1).randn(2, 16, 16), jnp.float32)
        y_local, aux_local = layer(params, x)
        sharded = {k: jax.device_put(v, NamedSharding(mesh, s))
                   for (k, v), s in zip(params.items(),
                                        layer.shardings().values())}
        y_ep, aux_ep = jax.jit(layer)(sharded, x)
        np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_local),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(float(aux_ep), float(aux_local), rtol=1e-5)

    def test_grad_flows(self):
        layer = MoEFFN(4, 8, 16, top_k=2)
        params = layer.init(jax.random.PRNGKey(2))
        x = jnp.asarray(np.random.RandomState(2).randn(1, 8, 8), jnp.float32)
        g = jax.grad(lambda p: layer(p, x)[0].sum())(params)
        assert float(jnp.abs(g["w1"]).sum()) > 0
        assert float(jnp.abs(g["gate_w"]).sum()) > 0


# ---------------------------------------------------------------------------
# sp: long-context BERT on ring attention
# ---------------------------------------------------------------------------

class TestBERTRingAttention:
    def _build(self, ring):
        from incubator_mxnet_tpu.models.bert import BERTModel
        mx.random.seed(0)
        np.random.seed(0)
        return BERTModel(num_layers=2, units=16, hidden_size=32, num_heads=2,
                         max_length=64, vocab_size=40, dropout=0.0,
                         use_pooler=False, ring=ring)

    def test_matches_dense_attention(self):
        mesh = make_mesh({"sp": 8})
        ids = np.random.RandomState(0).randint(0, 40, (2, 64))
        net_d = self._build(None)
        net_d.initialize()
        seq_d = net_d(nd.array(ids)).asnumpy()
        net_r = self._build((mesh, "sp"))
        net_r.initialize()   # same seeds -> same init
        seq_r = net_r(nd.array(ids)).asnumpy()
        np.testing.assert_allclose(seq_r, seq_d, rtol=2e-4, atol=2e-4)

    @pytest.mark.slow
    def test_ring_bert_trains_fused(self):
        mesh = make_mesh({"sp": 8})
        net = self._build((mesh, "sp"))
        head = gluon.nn.Dense(4, flatten=False, in_units=16)
        full = gluon.nn.HybridSequential()
        full.add(net)
        full.add(head)
        full.initialize()
        step = FusedTrainStep(full, gluon.loss.SoftmaxCrossEntropyLoss(),
                              mx.optimizer.create("adam", learning_rate=1e-2),
                              mesh=None)
        ids = nd.array(np.random.RandomState(1).randint(0, 40, (2, 64)))
        y = nd.array(np.random.RandomState(2).randint(0, 4, (2, 64)))
        l0 = float(step(ids, y))
        for _ in range(5):
            l = float(step(ids, y))
        assert np.isfinite(l) and l < l0

    def test_mask_rejected(self):
        mesh = make_mesh({"sp": 4})
        net = self._build((mesh, "sp"))
        net.initialize()
        ids = nd.array(np.zeros((1, 32), np.int32))
        vl = nd.array(np.array([10]))
        with pytest.raises(ValueError, match="ring attention"):
            net(ids, None, vl)


# ---------------------------------------------------------------------------
# tp: tensor parallel BERT
# ---------------------------------------------------------------------------

class TestTensorParallel:
    @pytest.mark.slow
    def test_bert_tp_dp_step_matches_single(self):
        """FusedTrainStep on a dp×tp mesh == single-device step (same math,
        XLA inserts the Megatron collectives)."""
        from incubator_mxnet_tpu.models.bert import BERTModel

        def build():
            mx.random.seed(0)
            np.random.seed(0)
            bert = BERTModel(num_layers=2, units=32, hidden_size=64,
                             num_heads=4, max_length=32, vocab_size=50,
                             dropout=0.0, use_pooler=True)
            net = gluon.nn.HybridSequential()
            net.add(bert)

            class Head(gluon.nn.HybridBlock):
                def __init__(self):
                    super().__init__()
                    self.out = gluon.nn.Dense(2, in_units=32)

                def forward(self, seq_pooled):
                    return self.out(seq_pooled[1])
            net.add(Head())
            net.initialize()
            return net, bert

        ids = np.random.RandomState(0).randint(0, 50, (8, 16))
        y = np.random.RandomState(1).randint(0, 2, 8)
        L = gluon.loss.SoftmaxCrossEntropyLoss()

        losses = {}
        for mode in ("single", "tp"):
            net, bert = build()
            if mode == "tp":
                annotate_bert_tp(bert)
                mesh = make_mesh({"dp": 2, "tp": 4})
            else:
                mesh = None
            step = FusedTrainStep(net, L, mx.optimizer.create(
                "sgd", learning_rate=0.1), mesh=mesh)
            ls = [float(step(nd.array(ids), nd.array(y))) for _ in range(3)]
            losses[mode] = ls
        np.testing.assert_allclose(losses["tp"], losses["single"],
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_ring_attention_long_context_8k():
    """Long-context evidence: exact ring attention at 8192 tokens sharded
    over 8 devices matches dense attention (within bf16-free fp32
    tolerance) — per-device memory is O(L/n)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from incubator_mxnet_tpu.parallel import make_mesh, ring_attention

    mesh = make_mesh({"sp": 8})
    L, D = 8192, 16
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 2, L, D), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, L, D), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, L, D), jnp.float32)
    spec = NamedSharding(mesh, P(None, None, "sp", None))
    qs, ks, vs = (jax.device_put(t, spec) for t in (q, k, v))

    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh, "sp",
                                                 causal=True))(qs, ks, vs)
    # dense reference on a SLICE of query rows (full dense is O(L^2) host
    # memory); rows from the middle and the end cross shard boundaries
    rows = np.r_[0:64, 4080:4144, L - 64:L]
    scale = 1.0 / np.sqrt(D)
    qr = np.asarray(q)[0, 0][rows]
    scores = (qr @ np.asarray(k)[0, 0].T) * scale            # (R, L)
    mask = rows[:, None] >= np.arange(L)[None, :]            # causal
    scores = np.where(mask, scores, -1e30)
    p = np.exp(scores - scores.max(axis=-1, keepdims=True))
    p /= p.sum(axis=-1, keepdims=True)
    expected = p @ np.asarray(v)[0, 0]
    np.testing.assert_allclose(np.asarray(out)[0, 0][rows], expected,
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# sp: Ulysses all-to-all sequence parallelism
# ---------------------------------------------------------------------------

class TestUlyssesAttention:
    def test_matches_dense(self):
        from incubator_mxnet_tpu.parallel import ulysses_attention
        mesh = make_mesh({"sp": 8})
        rng = np.random.RandomState(10)
        q, k, v = (jnp.asarray(rng.randn(2, 8, 64, 16), jnp.float32)
                   for _ in range(3))
        out = ulysses_attention(q, k, v, mesh, "sp")
        ref = _ref_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_causal_matches_ring(self):
        from incubator_mxnet_tpu.parallel import (ring_attention,
                                                  ulysses_attention)
        mesh = make_mesh({"sp": 8})
        rng = np.random.RandomState(11)
        q, k, v = (jnp.asarray(rng.randn(1, 8, 32, 8), jnp.float32)
                   for _ in range(3))
        out_u = ulysses_attention(q, k, v, mesh, "sp", causal=True)
        out_r = ring_attention(q, k, v, mesh, "sp", causal=True)
        np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_r),
                                   rtol=1e-4, atol=1e-4)

    def test_grad_matches_dense(self):
        from incubator_mxnet_tpu.parallel import ulysses_attention
        mesh = make_mesh({"sp": 4})
        rng = np.random.RandomState(12)
        q, k, v = (jnp.asarray(rng.randn(1, 4, 16, 8), jnp.float32)
                   for _ in range(3))
        g_u = jax.grad(lambda a, b, c: ulysses_attention(
            a, b, c, mesh, "sp").sum())(q, k, v)
        g_ref = jax.grad(lambda a, b, c: _ref_attention(a, b, c).sum())(
            q, k, v)
        np.testing.assert_allclose(np.asarray(g_u), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-4)

    def test_heads_not_divisible_rejected(self):
        from incubator_mxnet_tpu.parallel import ulysses_attention
        mesh = make_mesh({"sp": 8})
        q = jnp.zeros((1, 4, 64, 8), jnp.float32)   # 4 heads < sp=8
        with pytest.raises(ValueError):
            ulysses_attention(q, q, q, mesh, "sp")

    def test_self_attention_block(self):
        from incubator_mxnet_tpu.parallel import ulysses_self_attention
        mesh = make_mesh({"sp": 8})
        rng = np.random.RandomState(13)
        d, heads = 32, 8
        x = jnp.asarray(rng.randn(2, 64, d), jnp.float32)
        wqkv = jnp.asarray(rng.randn(d, 3 * d) * 0.05, jnp.float32)
        wo = jnp.asarray(rng.randn(d, d) * 0.05, jnp.float32)
        out = ulysses_self_attention(x, wqkv, wo, heads, mesh, "sp")
        assert out.shape == x.shape
        assert np.isfinite(np.asarray(out)).all()


class TestBERTUlysses:
    def test_matches_dense_attention(self):
        from incubator_mxnet_tpu.models.bert import BERTModel

        def build(ring):
            mx.random.seed(0)
            np.random.seed(0)
            return BERTModel(num_layers=2, units=16, hidden_size=32,
                             num_heads=8, max_length=64, vocab_size=40,
                             dropout=0.0, use_pooler=False, ring=ring)

        mesh = make_mesh({"sp": 8})
        ids = np.random.RandomState(0).randint(0, 40, (2, 64))
        net_d = build(None)
        net_d.initialize()
        seq_d = net_d(nd.array(ids)).asnumpy()
        net_u = build((mesh, "sp", "ulysses"))
        net_u.initialize()   # same seeds -> same init
        seq_u = net_u(nd.array(ids)).asnumpy()
        np.testing.assert_allclose(seq_u, seq_d, rtol=2e-4, atol=2e-4)


class TestRunK:
    def _build_net(self):
        mx.random.seed(0)
        np.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu", in_units=8),
                gluon.nn.Dense(4, in_units=16))
        net.initialize(init=mx.init.Xavier())
        return net

    def test_run_k_matches_sequential_steps(self):
        """k micro-steps inside one lax.scan program == k separate
        dispatched steps (same math, k× fewer dispatches)."""
        rng = np.random.RandomState(0)
        xs = rng.randn(4, 8, 8).astype(np.float32)
        ys = rng.randint(0, 4, (4, 8))
        L = gluon.loss.SoftmaxCrossEntropyLoss()

        net1 = self._build_net()
        s1 = FusedTrainStep(net1, L, mx.optimizer.create(
            "sgd", learning_rate=0.1, momentum=0.9), mesh=None)
        seq_losses = [float(s1(nd.array(xs[i]), nd.array(ys[i])))
                      for i in range(4)]

        net2 = self._build_net()
        s2 = FusedTrainStep(net2, L, mx.optimizer.create(
            "sgd", learning_rate=0.1, momentum=0.9), mesh=None)
        k_losses = s2.run_k(xs, ys).asnumpy()

        np.testing.assert_allclose(k_losses, seq_losses, rtol=1e-5,
                                   atol=1e-6)
        for (n1, p1), (n2, p2) in zip(
                sorted(net1.collect_params().items()),
                sorted(net2.collect_params().items())):
            np.testing.assert_allclose(p2.data().asnumpy(),
                                       p1.data().asnumpy(),
                                       rtol=1e-5, atol=1e-6)

    def test_run_k_on_dp_mesh(self):
        """run_k under a dp mesh: batches shard over dp, k axis stays on
        host order; losses finite and params update."""
        mesh = make_mesh({"dp": 8})
        rng = np.random.RandomState(1)
        xs = rng.randn(3, 16, 8).astype(np.float32)
        ys = rng.randint(0, 4, (3, 16))
        net = self._build_net()
        L = gluon.loss.SoftmaxCrossEntropyLoss()
        step = FusedTrainStep(net, L, mx.optimizer.create(
            "sgd", learning_rate=0.1), mesh=mesh)
        before = {n: p.data().asnumpy().copy()
                  for n, p in net.collect_params().items()}
        losses = step.run_k(xs, ys).asnumpy()
        assert losses.shape == (3,) and np.isfinite(losses).all()
        changed = any(not np.allclose(p.data().asnumpy(), before[n])
                      for n, p in net.collect_params().items())
        assert changed, "run_k did not update parameters"
        # mixing run_k and single steps keeps working
        l4 = float(step(nd.array(xs[0]), nd.array(ys[0])))
        assert np.isfinite(l4)

    def test_run_k_accepts_list_of_batches(self):
        rng = np.random.RandomState(2)
        batches = [(nd.array(rng.randn(8, 8).astype(np.float32)),
                    nd.array(rng.randint(0, 4, 8))) for _ in range(2)]
        net = self._build_net()
        step = FusedTrainStep(net,
                              gluon.loss.SoftmaxCrossEntropyLoss(),
                              mx.optimizer.create("sgd", learning_rate=0.05))
        losses = step.run_k([b[0] for b in batches],
                            [b[1] for b in batches]).asnumpy()
        assert losses.shape == (2,) and np.isfinite(losses).all()
