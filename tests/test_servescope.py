"""mxtpu.servescope — request-lifecycle tracing & tail-latency
attribution for the serving path.

Covers the acceptance surface of the seventh observability layer: span
lifecycle through the batcher (including every rejection path and the
drain), the hand-computed five-way attribution identity, batch_id
correlation across the mxtpu.events/1 stream, quantile-cohort
attribution summing to measured e2e latency, the sampling/off-path
overhead contract, the /stats-/healthz satellites (single-snapshot
consistency, resharding + attribution verdicts), serve_load's knee
detection and env-failure artifact, and the trace_check / perf_regress
/ mxdiag tooling integration.
"""
import importlib.util
import json
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd, servescope, serving
from incubator_mxnet_tpu import profiler as prof
from incubator_mxnet_tpu.servescope import spans as ss_spans
from incubator_mxnet_tpu.servescope.budget import (LatencyBudget,
                                                   quantile_cohorts)
from incubator_mxnet_tpu.serving import (DeadlineExceededError,
                                         DynamicBatcher, FrozenModel,
                                         ServerClosedError)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(name, f"tools/{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mlp(in_units=6, out=3, seed=0):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, in_units=in_units, activation="relu"),
            gluon.nn.Dense(out, in_units=16))
    net.initialize(init=mx.init.Xavier())
    rng = np.random.RandomState(seed)
    for p in net.collect_params().values():
        p.set_data(nd.array(rng.randn(*p.shape).astype(np.float32) * 0.1))
    return net


@pytest.fixture
def frozen():
    return FrozenModel(_mlp(), input_shape=(6,), batch_buckets=(1, 2, 4, 8))


@pytest.fixture
def armed():
    """Servescope armed with a fresh budget; disarmed after."""
    servescope.enable()
    yield servescope._SS
    servescope.disable()


def _drive(batcher, n=12, timeout_ms=None):
    results = [None] * n
    xs = np.random.RandomState(4).randn(n, 6).astype(np.float32)

    def client(i):
        results[i] = batcher.predict(xs[i], timeout_ms=timeout_ms)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


# ---------------------------------------------------------------------------
# span lifecycle
# ---------------------------------------------------------------------------

def test_span_lifecycle_through_batcher(frozen, armed):
    b = DynamicBatcher(frozen, max_delay_ms=20, queue_limit=64).start()
    _drive(b, 12)
    b.stop()
    att = servescope.attribution()
    assert att["requests"] == 12
    overall = att["overall"]
    assert overall["count"] == 12
    # every component distribution exists and the taxonomy is closed
    assert set(overall["component_dist"]) == set(ss_spans.COMPONENTS)
    snap = prof.counters()
    assert snap["servescope/servescope.requests_traced"] == 12
    assert snap["servescope/servescope.e2e_ms"]["count"] == 12


def test_span_rejection_deadline_path(frozen, armed):
    b = DynamicBatcher(frozen, max_delay_ms=1, queue_limit=8)
    # batcher not started: the request ages past its deadline in queue
    req = b.submit(np.zeros(6, np.float32), timeout_ms=20)
    assert req.span is not None
    time.sleep(0.08)
    b.start()
    with pytest.raises(DeadlineExceededError):
        req.wait(5.0)
    b.stop()
    assert req.span.status == "rejected_deadline"
    snap = prof.counters()
    assert snap["servescope/servescope.rejections_traced"] >= 1
    # rejections never feed the latency budget
    assert servescope.attribution()["requests"] == 0


def test_span_drain_rejection_path(frozen, armed):
    b = DynamicBatcher(frozen, queue_limit=8)
    reqs = [b.submit(np.zeros(6, np.float32)) for _ in range(3)]
    b.stop(drain=False)
    for r in reqs:
        with pytest.raises(ServerClosedError):
            r.wait(1.0)
    # drain=False rejections are fulfilled without touching the span
    # machinery's responded path
    assert servescope.attribution()["requests"] == 0


def test_post_batch_deadline_rejected_and_counted(frozen, armed,
                                                  monkeypatch):
    """A deadline that expires DURING batch execution is a rejection
    under its own counter — previously these were lost entirely."""
    prof.reset_counters()
    orig = frozen.predict_batch

    def slow_predict(x, timings=None):
        out = orig(x, timings=timings)
        time.sleep(0.08)            # the batch outlives the deadline
        return out

    monkeypatch.setattr(frozen, "predict_batch", slow_predict)
    b = DynamicBatcher(frozen, max_delay_ms=1, queue_limit=8).start()
    req = b.submit(np.zeros(6, np.float32), timeout_ms=50)
    with pytest.raises(DeadlineExceededError) as ei:
        req.wait(5.0)
    b.stop()
    assert "during batch execution" in str(ei.value)
    snap = prof.counters()
    assert snap.get(
        "serving/serving.rejected_deadline_post_batch", 0) == 1
    # distinct from the pre-batch counter, and NOT a response
    assert snap.get("serving/serving.rejected_deadline", 0) == 0
    assert snap.get("serving/serving.responses", 0) == 0
    assert req.span.status == "rejected_deadline_post_batch"


def test_batch_error_rejects_spans(frozen, armed, monkeypatch):
    def boom(x, timings=None):
        raise RuntimeError("injected device failure")

    monkeypatch.setattr(frozen, "predict_batch", boom)
    b = DynamicBatcher(frozen, max_delay_ms=1, queue_limit=8).start()
    req = b.submit(np.zeros(6, np.float32), timeout_ms=0)
    with pytest.raises(RuntimeError):
        req.wait(5.0)
    b.stop()
    assert req.span.status == "batch_error"


# ---------------------------------------------------------------------------
# attribution math (hand-computed)
# ---------------------------------------------------------------------------

def test_components_hand_computed():
    """The five-way split on a synthetic span with known marks:
    admitted t=0, gather at 10 ms, dispatched at 15 ms, predict wall
    20 ms (pad 2 + exec 16 + unpad 1 + residual 1), responded 1 ms
    after device_done; bucket 8, 6 real rows."""
    span = ss_spans.RequestSpan(1, 0.0)
    span.gather_start = 0.010
    span.t_dispatched = 0.015
    span.t_device_done = 0.035
    span.t_respond = 0.036
    span.bucket, span.real = 8, 6
    span.timings = {"pad_ms": 2.0, "exec_ms": 16.0, "unpad_ms": 1.0}
    c = ss_spans.components_of(span)
    assert c["queue_wait_ms"] == pytest.approx(10.0)
    assert c["coalesce_delay_ms"] == pytest.approx(5.0)
    # device_exec = exec * real/bucket = 16 * 6/8
    assert c["device_exec_ms"] == pytest.approx(12.0)
    # pad_overhead = pad copy + exec * padded/bucket = 2 + 16 * 2/8
    assert c["pad_overhead_ms"] == pytest.approx(6.0)
    # respond = fulfil delta + unpad + unattributed predict residual
    assert c["respond_ms"] == pytest.approx(3.0)
    assert c["e2e_ms"] == pytest.approx(36.0)
    # the accounting identity, exactly
    assert sum(c[k] for k in ss_spans.COMPONENTS) == \
        pytest.approx(c["e2e_ms"])


def test_components_arrived_mid_coalesce():
    """A request admitted AFTER the gather started has zero queue_wait;
    its whole pre-dispatch time is coalesce delay."""
    span = ss_spans.RequestSpan(2, 0.020)
    span.gather_start = 0.010          # batch window opened earlier
    span.t_dispatched = 0.030
    span.t_device_done = 0.040
    span.t_respond = 0.040
    span.bucket = span.real = 4
    c = ss_spans.components_of(span)
    assert c["queue_wait_ms"] == 0.0
    assert c["coalesce_delay_ms"] == pytest.approx(10.0)
    assert c["pad_overhead_ms"] == 0.0
    assert sum(c[k] for k in ss_spans.COMPONENTS) == \
        pytest.approx(c["e2e_ms"])


def test_attribution_sums_to_measured_e2e(frozen, armed):
    """Real traffic: every quantile cohort's component sum equals its
    cohort mean e2e exactly, and sits within the 10% neighborhood of
    the quantile by construction."""
    b = DynamicBatcher(frozen, max_delay_ms=10, queue_limit=128).start()
    _drive(b, 24)
    b.stop()
    att = servescope.attribution()
    for grp in [att["overall"]] + list(att["per_bucket"].values()):
        for q, a in grp["attribution"].items():
            comp_sum = sum(a["components"].values())
            assert comp_sum == pytest.approx(a["sum_ms"], abs=0.01)
            assert a["sum_ms"] >= a["e2e_ms"] - 0.01
            assert a["sum_ms"] <= a["e2e_ms"] * 1.11, \
                f"{q}: cohort mean outside the neighborhood cap"


def test_quantile_cohort_outlier_cannot_smear_p99():
    """A lone 20x outlier above p99 must not inflate the p99
    attribution (the value-capped cohort excludes it)."""
    entries = []
    for i in range(199):
        entries.append({"e2e_ms": 10.0 + i * 0.01,
                        "queue_wait_ms": 5.0 + i * 0.01,
                        "coalesce_delay_ms": 2.0, "pad_overhead_ms": 1.0,
                        "device_exec_ms": 1.5, "respond_ms": 0.5})
    entries.append({"e2e_ms": 250.0, "queue_wait_ms": 245.0,
                    "coalesce_delay_ms": 2.0, "pad_overhead_ms": 1.0,
                    "device_exec_ms": 1.5, "respond_ms": 0.5})
    att = quantile_cohorts(entries)
    p99 = att["p99"]
    assert p99["e2e_ms"] < 12.1          # the nearest-rank p99, not 250
    assert p99["sum_ms"] <= p99["e2e_ms"] * 1.11
    assert p99["top_component"] == "queue_wait_ms"


def test_quantile_cohorts_single_entry():
    e = {"e2e_ms": 7.0, "queue_wait_ms": 1.0, "coalesce_delay_ms": 2.0,
         "pad_overhead_ms": 0.5, "device_exec_ms": 3.0, "respond_ms": 0.5}
    att = quantile_cohorts([e])
    for q in ("p50", "p95", "p99"):
        assert att[q]["e2e_ms"] == 7.0
        assert att[q]["sum_ms"] == pytest.approx(7.0)
        assert att[q]["cohort"] == 1


# ---------------------------------------------------------------------------
# correlation (mxtpu.events/1) + flight
# ---------------------------------------------------------------------------

def test_batch_id_correlation_across_events(frozen, armed, tmp_path):
    from incubator_mxnet_tpu.healthmon import events as hm_events
    hm_events.open_log(str(tmp_path / "ev.jsonl"), run_id="t-ss", rank=0)
    b = DynamicBatcher(frozen, max_delay_ms=20, queue_limit=64).start()
    _drive(b, 8)
    b.stop()
    hm_events.close_log()
    recs = [json.loads(ln) for ln in open(tmp_path / "ev.jsonl")
            if ln.strip()]
    req_recs = [r for r in recs if r["name"] == "serving.request"]
    batch_ids = {(r.get("args") or {}).get("batch_id")
                 for r in recs if r["name"] == "serving.batch"}
    assert len(req_recs) == 8
    for r in req_recs:
        args = r["args"]
        assert args["status"] == "responded"
        assert args["batch_id"] in batch_ids
        assert args["bucket"] in (1, 2, 4, 8)
        # components travel with the event
        for key in ss_spans.COMPONENTS:
            assert isinstance(args[key], (int, float))
        assert r["run_id"] == "t-ss"


def test_spans_land_in_flight_ring(frozen, armed):
    from incubator_mxnet_tpu import diagnostics as diag
    from incubator_mxnet_tpu.diagnostics import flight as _flight
    diag.enable_flight_recorder(dump_on_crash=False, record_ops=False)
    try:
        b = DynamicBatcher(frozen, max_delay_ms=5).start()
        b.predict(np.zeros(6, np.float32))
        b.stop()
        path = _flight.dump(reason="test")
        doc = json.load(open(path))
        assert any(e["name"] == "serving.request" for e in doc["events"])
    finally:
        diag.disable_flight_recorder()


# ---------------------------------------------------------------------------
# sampling / off-path contract
# ---------------------------------------------------------------------------

def test_sampling_stride_resolution(monkeypatch):
    assert servescope._resolve_sample(None) == 1
    assert servescope._resolve_sample(0.1) == 10
    assert servescope._resolve_sample(0.25) == 4
    assert servescope._resolve_sample(8) == 8
    assert servescope._resolve_sample("garbage") == 1
    assert servescope._resolve_sample(0) == 1
    monkeypatch.setenv("MXTPU_SERVESCOPE_SAMPLE", "0.5")
    assert servescope._resolve_sample(None) == 2


def test_sampled_mode_traces_subset_counts_rest(frozen):
    prof.reset_counters()
    servescope.enable(sample=3)
    try:
        b = DynamicBatcher(frozen, max_delay_ms=5, queue_limit=64).start()
        _drive(b, 9)
        b.stop()
        snap = prof.counters()
        traced = snap.get("servescope/servescope.requests_traced", 0)
        skipped = snap.get("servescope/servescope.sampled_out", 0)
        assert traced == 3            # every 3rd of 9
        assert skipped == 6
        assert snap["servescope/servescope.sample_every"] == 3
        # serving-side accounting still sees every request
        assert snap["serving/serving.responses"] == 9
    finally:
        servescope.disable()


def test_off_path_pays_one_predicate(frozen):
    """With servescope off, requests carry no span and no servescope
    metric is ever touched — the disabled path is byte-identical to
    the pre-servescope batcher."""
    servescope.disable()
    prof.reset_counters()
    b = DynamicBatcher(frozen, max_delay_ms=5).start()
    req = b.submit(np.zeros(6, np.float32))
    req.wait(5.0)
    b.stop()
    assert req.span is None
    assert not any(k.startswith("servescope/")
                   for k in prof.counters())


def test_enable_from_env(monkeypatch):
    servescope.disable()
    monkeypatch.setenv("MXTPU_SERVESCOPE", "1")
    monkeypatch.setenv("MXTPU_SERVESCOPE_SAMPLE", "4")
    servescope.enable_from_env()
    try:
        assert servescope.enabled()
        assert servescope._SS.sample_every == 4
    finally:
        servescope.disable()


# ---------------------------------------------------------------------------
# /stats + /healthz satellites
# ---------------------------------------------------------------------------

def test_stats_consistent_the_instant_predict_returns(frozen):
    """The epoch-mixing bugfix: telemetry lands BEFORE the client is
    fulfilled, so a /stats read the moment predict() returns already
    contains that request on every surface."""
    servescope.disable()
    prof.reset_counters()
    b = DynamicBatcher(frozen, max_delay_ms=1).start()
    for k in range(1, 6):
        b.predict(np.zeros(6, np.float32))
        s = b.stats()
        assert s["serving.responses"] == k
        assert s["serving.latency_ms"]["count"] == k
        assert s["p50_ms"] is not None
    b.stop()


def test_healthz_and_stats_carry_verdicts(frozen):
    import urllib.request
    from incubator_mxnet_tpu import commscope, perfscope
    prof.reset_counters()
    perfscope.enable()
    commscope.enable()
    servescope.enable()
    try:
        # recompile under armed scopes so the bucket programs register
        fm = FrozenModel(_mlp(), input_shape=(6,),
                         batch_buckets=(1, 2, 4))
        srv = serving.ModelServer(fm, max_delay_ms=2)
        host, port = srv.start()
        base = f"http://{host}:{port}"
        for _ in range(3):
            body = json.dumps({"data": [0.0] * 6}).encode()
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/predict", data=body,
                headers={"Content-Type": "application/json"}),
                timeout=30).read()
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            doc = json.loads(r.read())
        checks = doc["checks"]
        assert set(checks["resharding"]["buckets"]) == {"1", "2", "4"}
        for v in checks["resharding"]["buckets"].values():
            assert v["resharding_collectives"] == 0
        assert checks["resharding"]["buckets_flagged"] == []
        assert checks["servescope_p99"]["top_component"] in \
            ss_spans.COMPONENTS
        with urllib.request.urlopen(f"{base}/stats", timeout=10) as r:
            stats = json.loads(r.read())
        assert "resharding" in stats and "servescope" in stats
        assert stats["servescope"]["requests_traced"] == 3
        srv.stop()
    finally:
        servescope.disable()
        commscope.disable()
        perfscope.disable()


def test_attribution_joins_bucket_verdicts(frozen):
    from incubator_mxnet_tpu import commscope, perfscope
    perfscope.enable()
    commscope.enable()
    servescope.enable()
    try:
        fm = FrozenModel(_mlp(), input_shape=(6,), batch_buckets=(1, 4))
        b = DynamicBatcher(fm, max_delay_ms=10, queue_limit=64).start()
        _drive(b, 8)
        b.stop()
        att = servescope.attribution()
        for grp in att["per_bucket"].values():
            assert grp["verdict"] in ("compute_bound", "hbm_bound",
                                      "trivial", "unknown")
            assert grp["resharding_collectives"] == 0
            assert grp["hlo_available"] is True
        assert att["device_exec_source"] == "host_wall"
        assert att["advice"]
    finally:
        servescope.disable()
        commscope.disable()
        perfscope.disable()


# ---------------------------------------------------------------------------
# devicescope upgrade (stale-window / drift rules)
# ---------------------------------------------------------------------------

class _FakeWindow:
    def __init__(self, completed_at, busy_ms=9.0, dispatches=5,
                 dispatch_ms=50.0, workload="serving"):
        self.completed_at = completed_at
        self.logdir = "/tmp/fake_win"
        self.dispatch_ms = dispatch_ms      # accumulated host exec wall
        self.steps_done = dispatches
        self.workload = workload            # who stepped it
        self._busy = busy_ms

    def summary(self):
        return {"per_step": {"device_busy_ms": self._busy}}


def test_device_window_upgrades_provenance(frozen, armed, monkeypatch):
    """A devicescope window completed AFTER the budget began upgrades
    device_exec to measured(profile); one completed BEFORE it (someone
    else's traffic) is rejected — PR 10's stale-window rule."""
    from incubator_mxnet_tpu import devicescope as ds
    b = DynamicBatcher(frozen, max_delay_ms=5).start()
    b.predict(np.zeros(6, np.float32))
    b.stop()
    ds.enable()
    try:
        # stale: completed before this budget's begin marker
        monkeypatch.setattr(ds, "last_window", lambda: _FakeWindow(0.0))
        att = servescope.attribution()
        assert att["device_exec_source"] == "host_wall"
        assert att["device_window"] is None
        # fresh but stepped by the TRAIN loop (train and serve share a
        # process): wrong workload identity, rejected despite freshness
        monkeypatch.setattr(
            ds, "last_window",
            lambda: _FakeWindow(time.monotonic(), workload="train"))
        att = servescope.attribution()
        assert att["device_exec_source"] == "host_wall"
        assert att["device_window"] is None
        # fresh: measured busy 9 ms vs host wall 50/5 = 10 ms per
        # dispatch -> 10% drift, under the 25% threshold
        monkeypatch.setattr(ds, "last_window",
                            lambda: _FakeWindow(time.monotonic()))
        att = servescope.attribution()
        assert att["device_exec_source"] == "measured(profile)"
        w = att["device_window"]
        assert w["measured_busy_ms_per_dispatch"] == 9.0
        assert w["host_wall_ms_per_dispatch"] == pytest.approx(10.0)
        assert w["drift"] == pytest.approx(0.1)
        assert w["drift_warning"] is False
    finally:
        ds.disable()


def test_device_window_drift_warns_loudly(frozen, armed, monkeypatch):
    import warnings as _warnings
    from incubator_mxnet_tpu import devicescope as ds
    prof.reset_counters()
    b = DynamicBatcher(frozen, max_delay_ms=5).start()
    b.predict(np.zeros(6, np.float32))
    b.stop()
    ds.enable()
    try:
        # measured 2 ms vs host 10 ms -> 80% drift, over the threshold
        monkeypatch.setattr(
            ds, "last_window",
            lambda: _FakeWindow(time.monotonic(), busy_ms=2.0))
        with _warnings.catch_warnings(record=True) as rec:
            _warnings.simplefilter("always")
            att = servescope.attribution()
        assert att["device_window"]["drift_warning"] is True
        assert any("disagree" in str(w.message) for w in rec)
        snap = prof.counters()
        assert snap.get(
            "servescope/servescope.device_drift_warnings", 0) == 1
        # warned once per budget, counted once
        att = servescope.attribution()
        assert prof.counters().get(
            "servescope/servescope.device_drift_warnings", 0) == 1
    finally:
        ds.disable()


def test_batcher_marks_active_devicescope_window(frozen, armed,
                                                 monkeypatch):
    from incubator_mxnet_tpu import devicescope as ds
    ds.enable()
    marks = []

    class _Rec:
        def step(self, n=1, dispatch_ms=0.0, sync=None, workload=None):
            marks.append((n, dispatch_ms, workload))

    try:
        monkeypatch.setattr(ds, "active_window", lambda: _Rec())
        b = DynamicBatcher(frozen, max_delay_ms=5).start()
        b.predict(np.zeros(6, np.float32))
        b.stop()
        assert len(marks) == 1
        assert marks[0][0] == 1 and marks[0][1] > 0   # one mark, exec wall
        assert marks[0][2] == "serving"               # identity stamp
    finally:
        ds.disable()


# ---------------------------------------------------------------------------
# serve_load units
# ---------------------------------------------------------------------------

def test_find_knee_throughput_saturation():
    sl = _load_tool("serve_load")
    levels = [
        {"concurrency": 4, "qps": 100.0, "p99_ms": 5.0},
        {"concurrency": 8, "qps": 200.0, "p99_ms": 5.5},
        {"concurrency": 16, "qps": 400.0, "p99_ms": 6.0},
        {"concurrency": 32, "qps": 410.0, "p99_ms": 12.0},
        {"concurrency": 64, "qps": 415.0, "p99_ms": 30.0},
    ]
    idx, reason = sl.find_knee(levels)
    assert idx == 2                      # last level that still scaled
    assert "saturated" in reason


def test_find_knee_p99_inflection():
    sl = _load_tool("serve_load")
    levels = [
        {"concurrency": 4, "qps": 100.0, "p99_ms": 5.0},
        {"concurrency": 8, "qps": 200.0, "p99_ms": 6.0},
        {"concurrency": 16, "qps": 390.0, "p99_ms": 40.0},  # inflected
    ]
    idx, reason = sl.find_knee(levels)
    assert idx == 1
    assert "inflected" in reason


def test_find_knee_no_saturation_and_base_saturated():
    sl = _load_tool("serve_load")
    scaling = [{"concurrency": c, "qps": 100.0 * c, "p99_ms": 5.0}
               for c in (4, 8, 16)]
    idx, reason = sl.find_knee(scaling)
    assert idx == 2 and "no saturation" in reason
    flat = [{"concurrency": 4, "qps": 100.0, "p99_ms": 5.0},
            {"concurrency": 8, "qps": 101.0, "p99_ms": 9.0}]
    idx, _ = sl.find_knee(flat)
    assert idx == 0


def test_run_level_closed_loop_and_server_death():
    sl = _load_tool("serve_load")
    calls = []

    def ok_send(i):
        calls.append(i)
        time.sleep(0.001)

    lv = sl.run_level(ok_send, concurrency=4, total_requests=20)
    assert lv["ok"] == 20 and lv["errors"] == 0
    assert sorted(calls) == list(range(20))     # closed loop covers all
    assert lv["p50_ms"] <= lv["p95_ms"] <= lv["p99_ms"]
    assert lv["qps"] > 0

    def dead_send(i):
        raise ConnectionRefusedError("server gone")

    with pytest.raises(sl.ServerDied):
        sl.run_level(dead_send, concurrency=4, total_requests=8)


def test_env_failure_artifact_on_server_death(tmp_path):
    sl = _load_tool("serve_load")
    out = tmp_path / "BENCH_dead.json"
    doc = sl.write_env_failure(str(out), "serve_load_lenet_qps_at_knee",
                               "all requests failed: connection refused")
    assert doc["status"] == "env_failure" and doc["value"] == 0.0
    # perf_regress must SKIP it, never adopt it as a baseline
    pr = _load_tool("perf_regress")
    rec, why = pr.load_artifact(str(out))
    assert rec is None and "env_failure" in why


def test_build_result_shape_validates(tmp_path):
    sl = _load_tool("serve_load")
    tc = _load_tool("trace_check")
    levels = [
        {"concurrency": 4, "qps": 100.0, "p50_ms": 3.0, "p95_ms": 4.0,
         "p99_ms": 5.0, "requests": 50, "ok": 50, "errors": 0,
         "wall_s": 0.5, "mean_ms": 3.2, "first_error": None},
        {"concurrency": 8, "qps": 105.0, "p50_ms": 6.0, "p95_ms": 8.0,
         "p99_ms": 10.0, "requests": 50, "ok": 50, "errors": 0,
         "wall_s": 0.5, "mean_ms": 6.2, "first_error": None},
    ]
    h = prof.Histogram("t.sl", "serving")
    for v in (3.0, 4.0, 5.0):
        h.observe(v)
    stats = {"serving.requests": 100, "serving.responses": 3,
             "serving.batches": 2, "batch_fill": 1.5,
             "serving.latency_ms": h.value}
    doc = sl.build_result("lenet", levels, 0, "test", stats)
    p = tmp_path / "BENCH_sl.json"
    p.write_text(json.dumps(doc))
    assert tc.check_bench_json(str(p)) == []
    assert doc["value"] == 100.0
    assert doc["extra"]["serving"]["p99_ms"] == 5.0
    assert doc["extra"]["serve_load"]["knee_concurrency"] == 4


# ---------------------------------------------------------------------------
# trace_check schema enforcement
# ---------------------------------------------------------------------------

def test_trace_check_servescope_families():
    tc = _load_tool("trace_check")
    ok = dict.fromkeys(
        ["servescope/servescope.requests_traced",
         "servescope/servescope.sampled_out"], "counter")
    ok["servescope/servescope.e2e_ms"] = "histogram"
    ok["servescope/servescope.sample_every"] = "gauge"
    assert tc.check_healthmon_kinds(ok) == []
    bad = {"servescope/servescope.made_up": "counter"}
    assert tc.check_healthmon_kinds(bad)
    flipped = {"servescope/servescope.requests_traced": "gauge"}
    assert tc.check_healthmon_kinds(flipped)


def _good_group():
    comps = {"queue_wait_ms": 4.0, "coalesce_delay_ms": 1.0,
             "pad_overhead_ms": 0.5, "device_exec_ms": 2.0,
             "respond_ms": 0.5}
    att = {"e2e_ms": 8.0, "cohort": 2, "components": comps,
           "sum_ms": 8.0, "top_component": "queue_wait_ms",
           "top_share": 0.5}
    return {"count": 10,
            "e2e_ms": {"p50": 5.0, "p95": 7.0, "p99": 8.0, "mean": 5.5,
                       "max": 8.5},
            "component_dist": {k: {"p50": 1.0, "p95": 2.0, "p99": 3.0,
                                   "mean": 1.5} for k in comps},
            "attribution": {"p50": dict(att), "p95": dict(att),
                            "p99": dict(att)}}


def test_trace_check_servescope_extra_good_and_bad():
    tc = _load_tool("trace_check")
    good = {"sample_every": 1, "requests": 10,
            "components": list(tc.SERVESCOPE_COMPONENTS),
            "device_exec_source": "host_wall",
            "overall": _good_group(),
            "per_bucket": {"4": dict(_good_group(), bucket=4,
                                     verdict="compute_bound",
                                     resharding_collectives=0,
                                     hlo_available=True)}}
    assert tc.check_servescope_extra(None) == []
    assert tc.check_servescope_extra(good) == []
    # sum far from the quantile -> structural error
    bad = json.loads(json.dumps(good))
    bad["overall"]["attribution"]["p99"]["components"]["queue_wait_ms"] \
        = 40.0
    bad["overall"]["attribution"]["p99"]["sum_ms"] = 44.0
    assert tc.check_servescope_extra(bad)
    # unknown component name
    bad2 = json.loads(json.dumps(good))
    bad2["overall"]["attribution"]["p99"]["components"]["gpu_ms"] = 1.0
    assert tc.check_servescope_extra(bad2)
    # bad verdict taxonomy
    bad3 = json.loads(json.dumps(good))
    bad3["per_bucket"]["4"]["verdict"] = "warp_bound"
    assert tc.check_servescope_extra(bad3)
    # bad provenance
    bad4 = json.loads(json.dumps(good))
    bad4["device_exec_source"] = "vibes"
    assert tc.check_servescope_extra(bad4)


def test_trace_check_serve_load_extra():
    tc = _load_tool("trace_check")
    good = {"levels": [
        {"concurrency": 4, "qps": 100.0, "p50_ms": 1.0, "p95_ms": 2.0,
         "p99_ms": 3.0},
        {"concurrency": 8, "qps": 110.0, "p50_ms": 2.0, "p95_ms": 3.0,
         "p99_ms": 4.0}],
        "knee_index": 1, "knee_concurrency": 8, "qps_at_knee": 110.0,
        "p99_at_knee_ms": 4.0}
    assert tc.check_serve_load_extra(None) == []
    assert tc.check_serve_load_extra(good) == []
    bad = json.loads(json.dumps(good))
    bad["knee_index"] = 5
    assert tc.check_serve_load_extra(bad)
    bad2 = json.loads(json.dumps(good))
    bad2["levels"][1]["concurrency"] = 4       # not ascending
    assert tc.check_serve_load_extra(bad2)
    bad3 = json.loads(json.dumps(good))
    bad3["qps_at_knee"] = 999.0                 # disagrees with the level
    assert tc.check_serve_load_extra(bad3)


# ---------------------------------------------------------------------------
# perf_regress gates
# ---------------------------------------------------------------------------

def _serve_load_artifact(tmp_path, name, qps, p99, knee=8):
    doc = {"metric": "serve_load_lenet_qps_at_knee", "value": qps,
           "unit": "requests/sec",
           "extra": {"serving": {"p99_ms": p99},
                     "serve_load": {"knee_concurrency": knee}}}
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_perf_regress_serve_load_gates(tmp_path):
    pr = _load_tool("perf_regress")
    base, _ = pr.load_artifact(
        _serve_load_artifact(tmp_path, "a.json", 100.0, 50.0))
    same, _ = pr.load_artifact(
        _serve_load_artifact(tmp_path, "b.json", 100.0, 50.0))
    regs, notes = pr.compare(base, same, p99_threshold=0.15)
    assert not regs
    assert any("saturation knee" in n for n in notes)
    # injected 20% p99 degradation flagged at the serving threshold
    worse, _ = pr.load_artifact(
        _serve_load_artifact(tmp_path, "c.json", 100.0, 60.0))
    regs, _ = pr.compare(base, worse, p99_threshold=0.15)
    assert any("p99_ms" in r for r in regs)
    # knee shift alone is a note (discrete ramp), not a regression
    shifted, _ = pr.load_artifact(
        _serve_load_artifact(tmp_path, "d.json", 100.0, 50.0, knee=4))
    regs, notes = pr.compare(base, shifted, p99_threshold=0.15)
    assert not regs
    assert any("knee moved down" in n for n in notes)
    # both-sides contract: a baseline without a sweep yields a note
    plain = dict(base, knee_concurrency=None)
    regs, notes = pr.compare(plain, shifted, p99_threshold=0.15)
    assert not regs
    assert any("needs a sweep on both sides" in n for n in notes)


# ---------------------------------------------------------------------------
# mxdiag serve renderer
# ---------------------------------------------------------------------------

def test_mxdiag_serve_renders(tmp_path, capsys):
    md = _load_tool("mxdiag")
    doc = {"metric": "serve_load_lenet_qps_at_knee", "value": 100.0,
           "unit": "requests/sec",
           "extra": {
               "model": "serve_load_lenet",
               "serving": {"requests": 10, "responses": 10, "batches": 4,
                           "batch_fill": 2.5, "rejected_queue_full": 0,
                           "rejected_deadline": 0,
                           "rejected_deadline_post_batch": 0,
                           "rejected_invalid": 0},
               "serve_load": {"levels": [
                   {"concurrency": 4, "qps": 100.0, "p50_ms": 1.0,
                    "p95_ms": 2.0, "p99_ms": 3.0, "errors": 0}],
                   "knee_index": 0, "knee_reason": "test"},
               "servescope": {"sample_every": 1, "requests": 10,
                              "device_exec_source": "host_wall",
                              "overall": _good_group(),
                              "per_bucket": {"4": dict(
                                  _good_group(), bucket=4, fill=0.9,
                                  verdict="compute_bound",
                                  resharding_collectives=0)},
                              "advice": "p99 is 50% queue_wait at "
                                        "bucket 4 - raise max_batch"}}}
    p = tmp_path / "BENCH_sl.json"
    p.write_text(json.dumps(doc))
    assert md.main(["serve", str(p)]) == 0
    out = capsys.readouterr().out
    assert "KNEE" in out
    assert "queue_wait" in out and "<< TAIL" in out
    assert "ADVICE" in out
    # env-failure artifact renders the failure, rc 1
    bad = tmp_path / "BENCH_bad.json"
    bad.write_text(json.dumps({"metric": "m", "value": 0.0,
                               "status": "env_failure", "error": "boom"}))
    assert md.main(["serve", str(bad)]) == 1


# ---------------------------------------------------------------------------
# bench integration shape
# ---------------------------------------------------------------------------

def test_bench_extra_shape_validates(frozen, armed, tmp_path):
    tc = _load_tool("trace_check")
    b = DynamicBatcher(frozen, max_delay_ms=10, queue_limit=64).start()
    _drive(b, 16)
    b.stop()
    h = prof.counters().get("serving/serving.latency_ms") or {}
    doc = {"metric": "serving_test", "value": 1.0,
           "extra": {"serving": {
               "requests": 16, "responses": 16, "batches": 4,
               "batch_fill": 4.0, "p50_ms": 1.0, "p95_ms": 2.0,
               "p99_ms": 3.0, "qps": 10.0, "latency_ms": h},
               "servescope": servescope.bench_extra()}}
    p = tmp_path / "BENCH_ss.json"
    p.write_text(json.dumps(doc))
    assert tc.check_bench_json(str(p)) == [], \
        tc.check_bench_json(str(p))[:3]
