"""BERT model tests (mirrors gluonnlp tests/unittest/test_models.py bert
cases + scripts/bert pretraining smoke)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.models.bert import (
    BERTModel, BERTForPretrain, BERTPretrainLoss, get_bert_model)


def tiny_bert(**kw):
    cfg = dict(num_layers=2, units=32, hidden_size=64, num_heads=4,
               max_length=64, vocab_size=100, dropout=0.0)
    cfg.update(kw)
    return BERTModel(**cfg)


def test_bert_forward_shapes():
    mx.random.seed(0)
    net = tiny_bert()
    net.initialize()
    B, L = 2, 16
    ids = nd.array(np.random.randint(0, 100, (B, L)))
    tt = nd.array(np.random.randint(0, 2, (B, L)))
    vl = nd.array(np.array([16, 9]))
    seq, pooled = net(ids, tt, vl)
    assert seq.shape == (B, L, 32)
    assert pooled.shape == (B, 32)
    assert np.isfinite(seq.asnumpy()).all()


def test_bert_valid_length_masks_padding():
    """Positions past valid_length must not affect earlier outputs."""
    mx.random.seed(0)
    net = tiny_bert()
    net.initialize()
    B, L, VL = 1, 12, 7
    ids = np.random.randint(0, 100, (B, L))
    vl = nd.array(np.array([VL]))
    seq1, _ = net(nd.array(ids), None, vl)
    ids2 = ids.copy()
    ids2[:, VL:] = 55  # change only padded tokens
    seq2, _ = net(nd.array(ids2), None, vl)
    np.testing.assert_allclose(seq1.asnumpy()[:, :VL],
                               seq2.asnumpy()[:, :VL], rtol=2e-5, atol=2e-5)


def test_bert_hybridize_parity():
    mx.random.seed(0)
    net = tiny_bert()
    net.initialize()
    B, L = 2, 8
    ids = nd.array(np.random.randint(0, 100, (B, L)))
    seq_e, pooled_e = net(ids)
    net.hybridize()
    seq_h, pooled_h = net(ids)
    np.testing.assert_allclose(seq_e.asnumpy(), seq_h.asnumpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(pooled_e.asnumpy(), pooled_h.asnumpy(),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_bert_pretrain_loss_decreases():
    mx.random.seed(0)
    np.random.seed(0)
    bert = tiny_bert()
    net = BERTForPretrain(bert, vocab_size=100)
    net.initialize()
    B, L, M = 4, 16, 3
    ids = nd.array(np.random.randint(0, 100, (B, L)))
    tt = nd.array(np.zeros((B, L), dtype=np.int32))
    vl = nd.array(np.full((B,), L))
    pos = nd.array(np.random.randint(0, L, (B, M)))
    mlm_labels = nd.array(np.random.randint(0, 100, (B, M)))
    nsp_labels = nd.array(np.random.randint(0, 2, (B,)))
    L_fn = BERTPretrainLoss()
    trainer = gluon.Trainer(net.collect_params(), "adamw",
                            {"learning_rate": 1e-3})
    losses = []
    for _ in range(12):
        with autograd.record():
            mlm, nsp = net(ids, tt, vl, pos)
            loss = L_fn(mlm, nsp, mlm_labels, nsp_labels)
        loss.backward()
        trainer.step(B)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0] * 0.8, losses


def test_bert_mlm_ignores_pad_label():
    """Loss over labels padded with -1 == loss over only the valid slots."""
    mx.random.seed(0)
    bert = tiny_bert()
    net = BERTForPretrain(bert, vocab_size=100)
    net.initialize()
    B, L = 2, 8
    ids = nd.array(np.random.randint(0, 100, (B, L)))
    tt = nd.array(np.zeros((B, L), dtype=np.int32))
    vl = nd.array(np.full((B,), L))
    nspl = nd.array(np.zeros((B,), dtype=np.int32))
    L_fn = BERTPretrainLoss()
    # padded: one valid slot per row + three -1 pads (at the same position 0)
    pos4 = nd.array(np.zeros((B, 4), dtype=np.int32))
    mlm4, nsp = net(ids, tt, vl, pos4)
    labels4 = nd.array(np.array([[5, -1, -1, -1], [7, -1, -1, -1]]))
    l_padded = float(L_fn(mlm4, nsp, labels4, nspl).asnumpy())
    # unpadded: only the valid slots
    pos1 = nd.array(np.zeros((B, 1), dtype=np.int32))
    mlm1, nsp1 = net(ids, tt, vl, pos1)
    labels1 = nd.array(np.array([[5], [7]]))
    l_valid = float(L_fn(mlm1, nsp1, labels1, nspl).asnumpy())
    assert abs(l_padded - l_valid) < 1e-5
    # and a padded slot flipped to a valid label MUST change the loss
    labels4b = nd.array(np.array([[5, 42, -1, -1], [7, -1, -1, -1]]))
    l_changed = float(L_fn(mlm4, nsp, labels4b, nspl).asnumpy())
    assert abs(l_changed - l_padded) > 1e-4


def test_get_bert_model_configs():
    net = get_bert_model("bert_12_768_12", vocab_size=50)
    assert len(net.encoder.cells) == 12
    net = get_bert_model("bert_24_1024_16", vocab_size=50)
    assert len(net.encoder.cells) == 24
