"""Control-flow ops (parity: mx.nd.contrib.foreach/while_loop/cond,
src/operator/control_flow.cc) — compiled loops via lax.scan/cond,
differentiable through the tape."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, nd


def test_foreach_cumsum():
    data = nd.array(np.arange(5, dtype=np.float32))
    init = nd.array(np.zeros(1, np.float32))

    def body(x, s):
        new_s = s + x
        return new_s, new_s

    outs, final = mx.nd.contrib.foreach(body, data, init)
    np.testing.assert_allclose(outs.asnumpy().ravel(),
                               np.cumsum(np.arange(5)))
    np.testing.assert_allclose(final.asnumpy(), [10.0])


def test_foreach_rnn_like_multi_state():
    rng = np.random.RandomState(0)
    T, B, D = 4, 2, 3
    xs = nd.array(rng.randn(T, B, D).astype(np.float32))
    h0 = nd.array(np.zeros((B, D), np.float32))
    c0 = nd.array(np.ones((B, D), np.float32))

    def body(x, states):
        h, c = states
        new_h = nd.tanh(x + h)
        new_c = c * 0.5
        return [new_h], [new_h, new_c]

    outs, (hT, cT) = mx.nd.contrib.foreach(body, xs, [h0, c0])
    assert outs[0].shape == (T, B, D)
    np.testing.assert_allclose(cT.asnumpy(), np.full((B, D), 1 / 16),
                               rtol=1e-6)


def test_foreach_grad_flows():
    data = nd.array(np.arange(1.0, 4.0, dtype=np.float32))
    w = nd.array(np.array([2.0], np.float32))
    w.attach_grad()

    def body(x, s):
        new_s = s + x * w
        return new_s, new_s

    with autograd.record():
        outs, final = mx.nd.contrib.foreach(body, data,
                                            nd.array(np.zeros(1, np.float32)))
        loss = final.sum()
    loss.backward()
    # d(sum(x_i * w))/dw = sum(x) = 6
    np.testing.assert_allclose(w.grad.asnumpy(), [6.0])


def test_while_loop_counts():
    i0 = nd.array(np.array([0.0], np.float32))

    def cond_fn(i):
        return i < 4.0

    def body(i):
        return i * 10.0, i + 1.0

    outs, final = mx.nd.contrib.while_loop(cond_fn, body, i0,
                                           max_iterations=8)
    np.testing.assert_allclose(final.asnumpy(), [4.0])
    o = outs[0].asnumpy().ravel()
    np.testing.assert_allclose(o[:4], [0.0, 10.0, 20.0, 30.0])
    np.testing.assert_allclose(o[4:], 0.0)   # padded tail (reference shape)


def test_cond_branches():
    x = nd.array(np.array([3.0], np.float32))
    out_t = mx.nd.contrib.cond(nd.array(np.array(1.0)),
                               lambda v: v * 2.0,
                               lambda v: v - 1.0, x)
    np.testing.assert_allclose(out_t.asnumpy(), [6.0])
    out_f = mx.nd.contrib.cond(nd.array(np.array(0.0)),
                               lambda v: v * 2.0,
                               lambda v: v - 1.0, x)
    np.testing.assert_allclose(out_f.asnumpy(), [2.0])


def test_cond_grad():
    x = nd.array(np.array([3.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.contrib.cond(nd.array(np.array(1.0)),
                               lambda v: v * v, lambda v: v, x)
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0])


def test_while_loop_reference_calling_convention_nd():
    """Upstream convention: cond/func take the loop vars unpacked."""
    i0 = nd.array(np.array([0.0], np.float32))
    a0 = nd.array(np.array([0.0], np.float32))

    def cond_fn(i, acc):
        return i < 4.0

    def body(i, acc):
        return i * 10.0, [i + 1.0, acc + i]

    outs, final = mx.nd.contrib.while_loop(cond_fn, body, [i0, a0],
                                           max_iterations=8)
    np.testing.assert_allclose(final[0].asnumpy(), [4.0])
    np.testing.assert_allclose(final[1].asnumpy(), [6.0])
    o = outs[0].asnumpy().ravel()
    np.testing.assert_allclose(o[:4], [0.0, 10.0, 20.0, 30.0])
    np.testing.assert_allclose(o[4:], 0.0)


def test_make_loop_caller_convention_matrix():
    """Convention resolution: list-style funcs (even with extra defaulted
    params) keep the list; only funcs that NEED all vars unpack."""
    from incubator_mxnet_tpu.base import make_loop_caller
    assert make_loop_caller(lambda a, b: (a, b), 2, False)([1, 2]) == (1, 2)
    assert make_loop_caller(lambda vs: vs, 2, False)([1, 2]) == [1, 2]
    assert make_loop_caller(
        lambda vs, debug=False: vs, 2, False)([1, 2]) == [1, 2]
    assert make_loop_caller(lambda *vs: vs, 2, False)([1, 2]) == (1, 2)
    assert make_loop_caller(lambda v: v, 1, True)([7]) == 7
    assert make_loop_caller(lambda vs: vs, 1, False)([7]) == [7]
