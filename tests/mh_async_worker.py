"""Worker for the REAL cross-process dist_async test (VERDICT r4 #8).

Each process of a 2-process loopback cluster trains linear regression by
pushing its OWN shard's gradients through a `dist_async` KVStore: every
push crosses a process boundary to the rank-0 server (over the jax
coordination service), is applied as an independent server-side SGD
update in arrival order — under induced bounded staleness — and pulls
return whatever the server has published at that moment. No aggregation
barrier exists until the final kv.barrier().

Parity: src/kvstore/kvstore_dist_server.h async push semantics.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import nd  # noqa: E402


def main():
    pid = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    steps = int(sys.argv[4])

    mx.distributed.init(coordinator_address=f"127.0.0.1:{port}",
                        num_processes=nproc, process_id=pid)
    kv = mx.kv.create("dist_async")
    assert kv.num_workers == nproc
    # smaller lr than the sync test: async applies each worker's shard
    # gradient as its own update (2x the update count) under staleness,
    # which destabilizes the quadratic at the sync step size
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.02))
    if pid == 0:
        # REAL cross-process staleness: the server holds back a seeded
        # random subset of arrived pushes up to 2 service rounds
        kv.set_async_staleness(2, seed=0)

    # deterministic global problem; each worker owns its shard
    rng = np.random.RandomState(0)
    X = rng.randn(16, 5).astype(np.float32)
    w_true = np.arange(5, dtype=np.float32)
    y = X @ w_true
    per = 16 // nproc
    Xl, yl = X[pid * per:(pid + 1) * per], y[pid * per:(pid + 1) * per]

    kv.init("w", nd.zeros((5,)))
    w_out = nd.zeros((5,))
    for _ in range(steps):
        # pace on OWN acknowledged pushes (<=2 outstanding), as ps-lite
        # workers implicitly do by pulling post-update weights; peers'
        # pushes still interleave with unbounded cross-worker staleness
        kv._ps().wait_outstanding(2)
        kv.pull("w", out=w_out)            # may MISS peers' in-flight pushes
        w = w_out.asnumpy()
        grad = 2.0 * Xl.T @ (Xl @ w - yl) / len(Xl)
        kv.push("w", nd.array(grad))       # independent server-side update

    kv.barrier()                           # drain: all pushes applied
    kv.pull("w", out=w_out)
    final = w_out.asnumpy()
    counts = kv.async_applied_counts()
    print("FINAL_W", " ".join(f"{v:.6f}" for v in final), flush=True)
    print("FINAL_LOSS", f"{float(np.mean((X @ final - y) ** 2)):.6f}",
          flush=True)
    print("APPLIED", " ".join(f"{r}:{counts[r]}" for r in sorted(counts)),
          flush=True)
    mx.distributed.barrier()
    mx.distributed.shutdown()
    print("SHUTDOWN_OK", flush=True)


if __name__ == "__main__":
    main()
