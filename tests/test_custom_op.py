"""Custom operator registration (parity: python/mxnet/operator.py —
the classic Sigmoid CustomOp example from the reference docs, run through
both the eager nd.Custom path and the compiled sym.Custom executor)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, operator
from incubator_mxnet_tpu import symbol as sym


@mx.operator.register("test_sigmoid")
class SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return Sigmoid()


class Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = 1.0 / (1.0 + np.exp(-x))
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        gy = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], mx.nd.array(gy * y * (1 - y)))


@mx.operator.register("test_scale2")
class Scale2Prop(mx.operator.CustomOpProp):
    """Two-output op: (x*2, x+1) — exercises multi-output plumbing."""

    def list_outputs(self):
        return ["doubled", "plus1"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0], in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return Scale2()


class Scale2(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], mx.nd.array(x * 2))
        self.assign(out_data[1], req[1], mx.nd.array(x + 1))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        g0 = out_grad[0].asnumpy()
        g1 = out_grad[1].asnumpy()
        self.assign(in_grad[0], req[0], mx.nd.array(g0 * 2 + g1))


def test_nd_custom_forward():
    x = np.array([-1.0, 0.0, 2.0], np.float32)
    y = mx.nd.Custom(nd.array(x), op_type="test_sigmoid")
    np.testing.assert_allclose(y.asnumpy(), 1 / (1 + np.exp(-x)), rtol=1e-6)


def test_nd_custom_backward():
    x = np.array([[-1.0, 0.5], [2.0, -0.3]], np.float32)
    a = nd.array(x)
    a.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Custom(a, op_type="test_sigmoid")
        loss = (y * nd.array(np.ones_like(x) * 3.0)).sum()
    loss.backward()
    s = 1 / (1 + np.exp(-x))
    np.testing.assert_allclose(a._grad.asnumpy(), 3.0 * s * (1 - s),
                               rtol=1e-5)


def test_sym_custom_executor_forward_backward():
    data = mx.sym.Variable("data")
    out = mx.sym.Custom(data, op_type="test_sigmoid", name="sig")
    x = np.array([[-2.0, 0.0, 1.0]], np.float32)
    ex = out.bind(args={"data": nd.array(x)},
                  args_grad={"data": nd.zeros((1, 3))})
    (y,) = ex.forward()
    s = 1 / (1 + np.exp(-x))
    np.testing.assert_allclose(y.asnumpy(), s, rtol=1e-6)
    ex.backward(nd.array(np.ones_like(x)))
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(), s * (1 - s),
                               rtol=1e-5)


def test_nd_custom_multi_output():
    x = np.array([1.0, 2.0], np.float32)
    a = nd.array(x)
    a.attach_grad()
    with mx.autograd.record():
        d, p = mx.nd.Custom(a, op_type="test_scale2")
        loss = d.sum() + (p * p).sum()
    loss.backward()
    np.testing.assert_allclose(d.asnumpy(), x * 2)
    np.testing.assert_allclose(p.asnumpy(), x + 1)
    # dloss/dx = 2 + 2*(x+1)
    np.testing.assert_allclose(a._grad.asnumpy(), 2 + 2 * (x + 1), rtol=1e-6)


def test_custom_unregistered_raises():
    with pytest.raises(KeyError, match="no custom op registered"):
        mx.nd.Custom(nd.zeros((2,)), op_type="nope_not_here")


@operator.register("scale_with_counter")
class ScaleWithCounterProp(operator.CustomOpProp):
    """out = 2*x; aux 'count' increments per forward (reference-style
    auxiliary state, mutated in place)."""

    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return ["count"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], [[1]]

    def create_operator(self, ctx, in_shapes, in_dtypes):
        class _Op(operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0], in_data[0] * 2)
                aux[0]._data = aux[0]._data + 1

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                self.assign(in_grad[0], req[0], out_grad[0] * 2)
        return _Op()


def test_custom_op_aux_states_symbol():
    """sym.Custom with auxiliary states: aux binds via aux_states, the
    forward's in-place update writes back, grads flow to data only."""
    x = sym.Variable("x")
    aux = sym.Variable("count")
    out = sym.Custom(x, aux, op_type="scale_with_counter")
    assert out.list_auxiliary_states() == ["count"]
    ex = out.bind(args={"x": np.array([1.0, 2.0], np.float32)},
                  aux_states={"count": np.zeros(1, np.float32)},
                  args_grad={"x": np.zeros(2, np.float32)},
                  grad_req={"x": "write"})
    v = ex.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(v, [2.0, 4.0])
    np.testing.assert_allclose(ex.aux_dict["count"].asnumpy(), [1.0])
    ex.forward(is_train=True)
    np.testing.assert_allclose(ex.aux_dict["count"].asnumpy(), [2.0])
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(), [2.0, 2.0])


def test_custom_op_aux_states_eager():
    """nd.Custom mutates the caller's aux NDArray in place."""
    x = nd.array(np.array([3.0], np.float32))
    count = nd.array(np.zeros(1, np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Custom(x, count, op_type="scale_with_counter")
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(y.asnumpy(), [6.0])
    np.testing.assert_allclose(count.asnumpy(), [1.0])   # mutated in place
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0])


def test_sym_custom_multi_output_backward():
    """Symbolic Custom with n_out != n_in: backward callback arg slicing
    must route out_data/out_grad correctly (regression guard)."""
    x = np.array([1.0, 2.0], np.float32)
    out = mx.sym.Custom(mx.sym.Variable("a"), op_type="test_scale2")
    loss = sym.sum(out[0]) + sym.sum(out[1] * out[1])
    ex = loss.bind(args={"a": x},
                   args_grad={"a": np.zeros(2, np.float32)},
                   grad_req={"a": "write"})
    v = ex.forward(is_train=True)[0].asnumpy()
    ex.backward()
    # loss = 2x + (x+1)^2 -> dloss/dx = 2 + 2(x+1)
    np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(),
                               2 + 2 * (x + 1), rtol=1e-5)


def test_sym_custom_auto_creates_aux_variable():
    """Reference style: aux declared by the prop but not passed appears
    automatically as {name}_{auxname}."""
    out = mx.sym.Custom(mx.sym.Variable("x"),
                        op_type="scale_with_counter", name="swc")
    assert out.list_auxiliary_states() == ["swc_count"]
    ex = out.bind(args={"x": np.array([1.0], np.float32)},
                  aux_states={"swc_count": np.zeros(1, np.float32)},
                  grad_req="null")
    ex.forward(is_train=True)
    np.testing.assert_allclose(ex.aux_dict["swc_count"].asnumpy(), [1.0])


def test_sym_custom_backward_sees_post_forward_aux():
    """Symbolic backward receives the aux values AFTER forward's in-place
    update (reference semantics; matches the eager path)."""
    seen = {}

    @operator.register("aux_reader")
    class AuxReaderProp(operator.CustomOpProp):
        def list_arguments(self): return ["data"]
        def list_outputs(self): return ["output"]
        def list_auxiliary_states(self): return ["flag"]
        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], [[1]]
        def create_operator(self, ctx, shapes, dtypes):
            class _Op(operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    aux[0]._data = aux[0]._data * 0 + 7.0
                    self.assign(out_data[0], req[0], in_data[0])
                def backward(self, req, og, ind, outd, ig, aux):
                    seen["aux_in_bwd"] = float(np.asarray(aux[0]._data)[0])
                    self.assign(ig[0], req[0], og[0])
            return _Op()

    out = mx.sym.Custom(mx.sym.Variable("x"), op_type="aux_reader",
                        name="ar")
    ex = out.bind(args={"x": np.ones(2, np.float32)},
                  aux_states={"ar_flag": np.zeros(1, np.float32)},
                  args_grad={"x": np.zeros(2, np.float32)},
                  grad_req={"x": "write"})
    ex.forward(is_train=True)
    ex.backward(nd.array(np.ones(2, np.float32)))
    assert seen["aux_in_bwd"] == 7.0, seen
