"""Multi-host branches exercised with mocks (parity targets:
src/kvstore/kvstore_dist.h semantics, tools/launch.py bootstrap).

This environment is always single-process, so the `jax.process_count() > 1`
branches can never run for real here; these tests monkeypatch the process
topology and the cross-process allgather so the code paths execute and
their MATH is checked (per-host partial sums -> global sum), not just
their reachability. The real-cluster runbook lives in README
("Multi-host training").
"""
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


class TestKVStoreDistBranch:
    def test_dist_aggregation_sums_across_processes(self, monkeypatch):
        """kvstore dist mode: local (per-host) aggregate, then
        process_allgather + sum = global sum — mocked as two hosts where
        the "other" host contributes 2x this host's gradient."""
        kv = mx.kv.create("dist_sync")
        assert kv._is_dist
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        from jax.experimental import multihost_utils
        calls = []

        def fake_allgather(a):
            calls.append(np.asarray(a))
            return jnp.stack([a, 2 * a])

        monkeypatch.setattr(multihost_utils, "process_allgather",
                            fake_allgather)
        g1 = nd.array(np.full((4,), 1.0, np.float32))
        g2 = nd.array(np.full((4,), 2.0, np.float32))
        kv.init("w", nd.zeros((4,)))
        out = nd.zeros((4,))
        kv.pushpull("w", [g1, g2], out=out)
        # local sum = 3; mocked global = 3 + 2*3 = 9
        np.testing.assert_allclose(out.asnumpy(), 9.0)
        assert len(calls) == 1          # one allgather per key batch

    def test_dist_rank_and_size_follow_process_topology(self, monkeypatch):
        kv = mx.kv.create("dist_sync_device")
        monkeypatch.setattr(jax, "process_count", lambda: 4)
        monkeypatch.setattr(jax, "process_index", lambda: 3)
        assert kv.num_workers == 4
        assert kv.rank == 3
        local = mx.kv.create("device")
        assert local.num_workers == 1 and local.rank == 0

    def test_local_mode_never_calls_allgather(self, monkeypatch):
        kv = mx.kv.create("device")
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        from jax.experimental import multihost_utils

        def boom(a):
            raise AssertionError("local kvstore must not allgather")

        monkeypatch.setattr(multihost_utils, "process_allgather", boom)
        kv.init("w", nd.zeros((4,)))
        out = nd.zeros((4,))
        kv.pushpull("w", nd.array(np.ones(4, np.float32)), out=out)
        np.testing.assert_allclose(out.asnumpy(), 1.0)


class TestDistributedBootstrap:
    def _reset(self):
        from incubator_mxnet_tpu import distributed
        distributed._state["initialized"] = False
        return distributed

    def test_init_passes_cluster_spec(self, monkeypatch):
        dist = self._reset()
        seen = {}

        def fake_initialize(**kw):
            seen.update(kw)

        monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
        dist.init(coordinator_address="host0:1234", num_processes=4,
                  process_id=2)
        assert dist.is_initialized()
        assert seen == {"coordinator_address": "host0:1234",
                        "num_processes": 4, "process_id": 2,
                        "local_device_ids": None}
        # idempotent: a second init must not re-rendezvous
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: (_ for _ in ()).throw(
                                AssertionError("re-initialized")))
        dist.init(coordinator_address="host0:1234", num_processes=4,
                  process_id=2)
        dist._state["initialized"] = False

    def test_init_autodiscovery_failure_degrades_with_warning(
            self, monkeypatch, caplog):
        dist = self._reset()

        def fail():
            raise RuntimeError("no coordinator")

        monkeypatch.setattr(jax.distributed, "initialize", fail)
        with caplog.at_level(logging.WARNING):
            dist.init()
        assert not dist.is_initialized()
        assert any("auto-discovery failed" in r.message
                   for r in caplog.records)

    def test_rank_size_and_barrier(self, monkeypatch):
        dist = self._reset()
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        assert dist.rank() == 1
        assert dist.num_workers() == 2
        from jax.experimental import multihost_utils
        synced = []
        monkeypatch.setattr(multihost_utils, "sync_global_devices",
                            lambda name: synced.append(name))
        dist.barrier("step42")
        assert synced == ["step42"]

    def test_barrier_single_process_is_noop(self, monkeypatch):
        dist = self._reset()
        monkeypatch.setattr(jax, "process_count", lambda: 1)
        from jax.experimental import multihost_utils
        monkeypatch.setattr(
            multihost_utils, "sync_global_devices",
            lambda name: (_ for _ in ()).throw(
                AssertionError("must not sync single-process")))
        dist.barrier()

    def test_shutdown_calls_jax_and_resets(self, monkeypatch):
        dist = self._reset()
        monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: None)
        dist.init(coordinator_address="h:1", num_processes=2, process_id=0)
        stopped = []
        monkeypatch.setattr(jax.distributed, "shutdown",
                            lambda: stopped.append(True))
        dist.shutdown()
        assert stopped == [True]
        assert not dist.is_initialized()
