"""mxtpu.fleet — continuous batching, quantized/sharded FrozenModel,
and the replica fleet.

Covers the fleet acceptance surface: iteration-level (slot-based)
admission with the ``slotted`` span mark and the full rejection
taxonomy preserved, the stop(drain=True) admission race (a queued
request must settle with ServerClosedError, never hang), int8/bf16
quantized parity bounds per bucket, mesh-sharded bucket compiles that
are provably resharding-clean (and the ReshardingGateError surface),
the shared on-disk CompileCache (replica N+1 skips the XLA compile),
the Router's least-loaded dispatch + zero-drop draining deploy, and
the fleet halves of the tooling contract (merge_serving_stats,
check_fleet_extra).

Everything here is in-process and CPU-only; the spawned-worker
multi-process path is exercised end to end by tools/fleet_smoke.sh.
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd, servescope
from incubator_mxnet_tpu import profiler as prof
from incubator_mxnet_tpu.fleet import (CompileCache, ContinuousBatcher,
                                       ReplicaSet, Router)
from incubator_mxnet_tpu.parallel import make_mesh
from incubator_mxnet_tpu.serving import (DeadlineExceededError, FrozenModel,
                                         ModelServer, QueueFullError,
                                         ReshardingGateError,
                                         ServerClosedError)


def _mlp(in_units=6, out=3, seed=0):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, in_units=in_units, activation="relu"),
            gluon.nn.Dense(out, in_units=16))
    net.initialize(init=mx.init.Xavier())
    rng = np.random.RandomState(seed)
    for p in net.collect_params().values():
        p.set_data(nd.array(rng.randn(*p.shape).astype(np.float32) * 0.1))
    return net


@pytest.fixture
def frozen():
    return FrozenModel(_mlp(), input_shape=(6,), batch_buckets=(1, 2, 4, 8))


@pytest.fixture
def armed():
    """Servescope armed (sample=1: every request gets a span)."""
    servescope.enable()
    yield servescope._SS
    servescope.disable()


def _load_tool(name):
    import importlib.util
    spec = importlib.util.spec_from_file_location(name, f"tools/{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _post(url, doc, timeout=30):
    body = json.dumps(doc).encode()
    req = urllib.request.Request(url, data=body,
                                 headers={"Content-Type":
                                          "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class _Blocked:
    """Hold the frozen model's exec open so the continuous batcher is
    provably mid-flight while we admit more requests."""

    def __init__(self, frozen_model):
        self.entered = threading.Event()
        self.release = threading.Event()
        orig = frozen_model.predict_batch

        def slow(x, timings=None):
            self.entered.set()
            assert self.release.wait(10), "test never released the exec"
            return orig(x, timings=timings)

        frozen_model.predict_batch = slow


# ---------------------------------------------------------------------------
# ContinuousBatcher — iteration-level scheduling
# ---------------------------------------------------------------------------

def test_continuous_batcher_serves_correct_results(frozen):
    prof.reset_counters()
    b = ContinuousBatcher(frozen, queue_limit=32).start()
    try:
        xs = np.random.RandomState(7).randn(8, 6).astype(np.float32)
        results = [None] * 8

        def client(i):
            results[i] = b.predict(xs[i])

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        want = frozen.predict_batch(xs)[0]
        got = np.stack([r[0] for r in results])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    finally:
        b.stop(drain=True)


def test_midflight_admission_is_slotted_and_counted(frozen, armed):
    prof.reset_counters()
    gate = _Blocked(frozen)
    b = ContinuousBatcher(frozen, queue_limit=8,
                          default_timeout_ms=10_000).start()
    try:
        first = b.submit(np.zeros(6, np.float32))
        assert gate.entered.wait(10)     # iteration 1 is on the device
        # admitted while a dispatch is in flight: rides the NEXT
        # iteration's slots, span stamped, counter incremented
        mid = b.submit(np.ones(6, np.float32))
        assert mid.span is not None and mid.span.slotted
        assert first.span is not None and not first.span.slotted
        assert prof.counters().get(
            "serving/serving.slotted_admissions", 0) == 1
        gate.release.set()
        first.wait(timeout=10)
        out = mid.wait(timeout=10)
        want = frozen.predict_batch(
            np.ones((1, 6), np.float32))[0][0]
        np.testing.assert_allclose(out[0], want, rtol=1e-5, atol=1e-6)
    finally:
        gate.release.set()
        b.stop(drain=True)


def test_midflight_admissions_keep_rejection_taxonomy(frozen, armed):
    """Slotted requests still go through the base class's admission
    control unchanged: deadline expiry is a rejection (not a silent
    drop) and queue-limit backpressure fails fast."""
    prof.reset_counters()
    gate = _Blocked(frozen)
    b = ContinuousBatcher(frozen, queue_limit=2,
                          default_timeout_ms=10_000).start()
    try:
        b.submit(np.zeros(6, np.float32))
        assert gate.entered.wait(10)
        ok = b.submit(np.ones(6, np.float32))                 # queued: 1
        doomed = b.submit(np.ones(6, np.float32),
                          timeout_ms=1)                       # queued: 2
        assert ok.span.slotted and doomed.span.slotted
        with pytest.raises(QueueFullError):                   # queued: full
            b.submit(np.ones(6, np.float32))
        time.sleep(0.01)                  # let doomed's 1 ms deadline pass
        gate.release.set()
        ok.wait(timeout=10)
        with pytest.raises(DeadlineExceededError):
            doomed.wait(timeout=10)
        c = prof.counters()
        assert c.get("serving/serving.rejected_deadline", 0) >= 1
        assert c.get("serving/serving.rejected_queue_full", 0) >= 1
        assert c.get("serving/serving.slotted_admissions", 0) == 2
    finally:
        gate.release.set()
        b.stop(drain=True)


@pytest.mark.parametrize("kind", ["dynamic", "continuous"])
def test_stop_drain_race_settles_queued_requests(frozen, armed, kind):
    """The drain race pin: a request admitted before stop(drain=True)
    whose dispatcher never runs again must settle promptly with
    ServerClosedError and a settled span — never hang. The
    never-started batcher is the deterministic worst case (there is no
    dispatcher at all to flush the queue)."""
    from incubator_mxnet_tpu.serving import DynamicBatcher
    prof.reset_counters()
    cls = DynamicBatcher if kind == "dynamic" else ContinuousBatcher
    b = cls(frozen)                       # never started, on purpose
    req = b.submit(np.zeros(6, np.float32))
    t0 = time.perf_counter()
    b.stop(drain=True, timeout=2.0)
    with pytest.raises(ServerClosedError):
        req.wait(timeout=2.0)
    assert time.perf_counter() - t0 < 2.0, \
        "queued request hung across stop(drain=True)"
    assert prof.counters().get("serving/serving.rejected_closed", 0) >= 1


# ---------------------------------------------------------------------------
# FrozenModel.quantize — int8 / bf16 parity per bucket
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode,bound", [("bf16", 1e-2), ("int8", 5e-2)])
def test_quantize_parity_bounds_per_bucket(frozen, mode, bound):
    q = frozen.quantize(mode)
    assert q.buckets == frozen.buckets
    for n in frozen.buckets:
        x = np.random.RandomState(n).randn(n, 6).astype(np.float32)
        ref = frozen.predict_batch(x)[0]
        got = q.predict_batch(x)[0]
        assert got.dtype == ref.dtype     # request/response dtype untouched
        maxdiff = float(np.max(np.abs(got - ref)))
        assert maxdiff < bound, \
            f"{mode} bucket {n}: maxdiff {maxdiff} vs float32 " \
            f"exceeds {bound}"


def test_quantize_rejects_unknown_mode(frozen):
    with pytest.raises(ValueError, match="int8.*bf16|bf16.*int8"):
        frozen.quantize("fp4")


# ---------------------------------------------------------------------------
# Sharded FrozenModel — resharding-clean serve path
# ---------------------------------------------------------------------------

def test_sharded_buckets_compile_resharding_clean():
    """A dp-sharded FrozenModel passes the reshard gate at freeze time
    and its commscope verdict proves zero resharding collectives in
    every compiled bucket (the accidental-all-gather catastrophe the
    gate exists to catch)."""
    mesh = make_mesh({"dp": -1})          # all 8 fake CPU devices
    net = _mlp()
    fm = FrozenModel(net, input_shape=(6,), batch_buckets=(1, 8),
                     mesh=mesh)           # reshard_gate=True default
    verdicts = fm.comm_verdicts()
    assert set(verdicts) == {"1", "8"}, \
        "commscope never captured the sharded bucket compiles"
    for b, v in verdicts.items():
        assert v.get("resharding_collectives") == 0, \
            f"bucket {b} compiled with resharding collectives: {v}"
    # sharded numerics match the unsharded float32 reference
    ref = FrozenModel(_mlp(), input_shape=(6,), batch_buckets=(1, 8))
    x = np.random.RandomState(3).randn(8, 6).astype(np.float32)
    np.testing.assert_allclose(fm.predict_batch(x)[0],
                               ref.predict_batch(x)[0],
                               rtol=1e-5, atol=1e-5)


def test_reshard_gate_refuses_flagged_layout():
    mesh = make_mesh({"dp": -1})
    fm = FrozenModel(_mlp(), input_shape=(6,), batch_buckets=(1,),
                     mesh=mesh)
    fm.comm_verdicts = lambda: {"1": {"resharding_collectives": 3,
                                      "hlo_available": True}}
    with pytest.raises(ReshardingGateError, match="resharding"):
        fm._check_reshard_gate()


# ---------------------------------------------------------------------------
# CompileCache — replica N+1 skips the XLA compile
# ---------------------------------------------------------------------------

def test_compile_cache_miss_then_hit(tmp_path):
    prof.reset_counters()
    cache = CompileCache(str(tmp_path / "aot"))
    buckets = (1, 4)
    m1 = FrozenModel(_mlp(), input_shape=(6,), batch_buckets=buckets,
                     compile_cache=cache)
    c = prof.counters()
    assert c.get("fleet/fleet.compile_cache_misses", 0) == len(buckets)
    assert c.get("fleet/fleet.compile_cache_stores", 0) == len(buckets)
    assert c.get("fleet/fleet.compile_cache_hits", 0) == 0
    assert cache.entries() == len(buckets)
    # replica N+1: same arch, same buckets — every warmup is a hit
    m2 = FrozenModel(_mlp(), input_shape=(6,), batch_buckets=buckets,
                     compile_cache=cache)
    c = prof.counters()
    assert c.get("fleet/fleet.compile_cache_hits", 0) == len(buckets)
    assert c.get("fleet/fleet.compile_cache_misses", 0) == len(buckets)
    x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
    np.testing.assert_array_equal(m1.predict_batch(x)[0],
                                  m2.predict_batch(x)[0])


# ---------------------------------------------------------------------------
# ReplicaSet + Router — least-loaded dispatch, draining deploys
# ---------------------------------------------------------------------------

def _factory(compile_cache=None):
    return FrozenModel(_mlp(), input_shape=(6,), batch_buckets=(1, 2, 4),
                       compile_cache=compile_cache)


@pytest.fixture
def fleet(tmp_path):
    prof.reset_counters()
    rset = ReplicaSet(_factory, n=2, batcher="continuous",
                      compile_cache=CompileCache(str(tmp_path / "aot")),
                      server_kwargs={"max_delay_ms": 0.0})
    rset.start()
    router = Router(rset, poll_interval_s=10.0)
    host, port = router.start()
    yield rset, router, f"http://{host}:{port}"
    router.stop()
    rset.stop(drain=False)


def test_router_dispatches_across_replicas_and_tags_reply(fleet):
    rset, router, base = fleet
    x = np.zeros(6, np.float32).tolist()
    seen = set()
    for _ in range(8):
        status, doc = _post(f"{base}/predict", {"data": x})
        assert status == 200
        seen.add(doc["replica"])
    assert seen == {"replica0", "replica1"}, \
        f"least-loaded dispatch never balanced: {seen}"
    stats = router.stats()
    assert stats["fleet.routed"] >= 8
    assert stats["dispatch_imbalance"] >= 1.0
    # shared cache: replica 1's warmup was a hit, not a recompile
    c = prof.counters()
    assert c.get("fleet/fleet.compile_cache_hits", 0) >= 3


def test_router_routes_around_draining_replica(fleet):
    rset, router, base = fleet
    rep0 = router.replicas[0]
    assert router.drain(rep0, timeout=10.0)
    x = np.zeros(6, np.float32).tolist()
    for _ in range(4):
        status, doc = _post(f"{base}/predict", {"data": x})
        assert status == 200
        assert doc["replica"] == "replica1"
    router.readmit(rep0)
    seen = {_post(f"{base}/predict", {"data": x})[1]["replica"]
            for _ in range(8)}
    assert "replica0" in seen


def test_deploy_swaps_every_replica_with_zero_drops(fleet, tmp_path):
    rset, router, base = fleet
    stop = threading.Event()
    failures = []
    x = np.zeros(6, np.float32).tolist()

    def client():
        while not stop.is_set():
            try:
                status, doc = _post(f"{base}/predict", {"data": x},
                                    timeout=30)
                if status != 200:
                    failures.append(doc)
            except Exception as e:  # noqa: BLE001
                failures.append(repr(e))

    threads = [threading.Thread(target=client) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.2)           # traffic flowing before the deploy
        router.deploy(_factory, compile_cache=rset.compile_cache,
                      timeout=30.0)
        time.sleep(0.2)           # traffic flowing after the deploy
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not failures, f"deploy dropped/errored requests: {failures[:3]}"
    c = prof.counters()
    assert c.get("fleet/fleet.drains", 0) == 2
    assert c.get("fleet/fleet.swaps", 0) == 2
    assert c.get("fleet/fleet.readmits", 0) == 2


def test_model_server_continuous_batcher_knob(frozen):
    srv = ModelServer(frozen, batcher="continuous")
    assert isinstance(srv.batcher, ContinuousBatcher)
    assert srv.stats()["batcher"] == "continuous"
    with pytest.raises(ValueError, match="batcher"):
        ModelServer(frozen, batcher="clairvoyant")


# ---------------------------------------------------------------------------
# Tooling contract — merge_serving_stats, check_fleet_extra
# ---------------------------------------------------------------------------

def _snap(requests, lat_buckets, count, total):
    return {"serving.requests": requests, "serving.batches": requests,
            "serving.batched_requests": requests,
            "serving.latency_ms": {"count": count, "sum": total,
                                   "min": 1.0, "max": 50.0,
                                   "p50": 5.0, "p95": 20.0, "p99": 40.0,
                                   "buckets": lat_buckets}}


def test_merge_serving_stats_sums_counters_and_merges_histograms():
    sl = _load_tool("serve_load")
    a = _snap(10, {"5": 6, "25": 9, "100": 10, "+Inf": 10}, 10, 80.0)
    b = _snap(30, {"5": 10, "25": 25, "100": 30, "+Inf": 30}, 30, 400.0)
    merged = sl.merge_serving_stats([a, b])
    assert merged["serving.requests"] == 40
    h = merged["serving.latency_ms"]
    assert h["count"] == 40 and h["sum"] == 480.0
    assert h["min"] == 1.0 and h["max"] == 50.0
    assert h["buckets"] == {"5": 16, "25": 34, "100": 40, "+Inf": 40}
    # percentiles re-estimated from MERGED buckets, ordered
    assert h["p50"] <= h["p95"] <= h["p99"]
    assert h["p50"] == 25.0      # rank 20 of 40: cum 16@5 < 20 <= 34@25
    assert h["p99"] == 100.0     # rank 40 of 40 lands in the last bucket
    assert merged["batch_fill"] == 1.0


def test_check_fleet_extra_schema():
    tc = _load_tool("trace_check")
    good = {"replicas": 2,
            "per_replica": [
                {"name": "replica0", "requests": 40, "qps": 100.0,
                 "p50_ms": 4.0, "p95_ms": 9.0, "p99_ms": 12.0},
                {"name": "replica1", "requests": 38, "qps": 95.0,
                 "p50_ms": 4.1, "p95_ms": 9.3, "p99_ms": 13.0}],
            "dispatch_imbalance": 1.03, "routed": 78,
            "routed_errors": 0, "no_replica_available": 0}
    assert tc.check_fleet_extra(good) == []
    assert tc.check_fleet_extra(None) == []

    bad = dict(good, replicas=3)
    assert any("per_replica has 2 rows" in e
               for e in tc.check_fleet_extra(bad))
    bad = dict(good, routed=10)
    assert any("lost accounting" in e for e in tc.check_fleet_extra(bad))
    bad = dict(good, dispatch_imbalance=0.5)
    assert any("dispatch_imbalance" in e
               for e in tc.check_fleet_extra(bad))
    unordered = json.loads(json.dumps(good))
    unordered["per_replica"][0]["p50_ms"] = 99.0
    assert any("ordered" in e for e in tc.check_fleet_extra(unordered))
