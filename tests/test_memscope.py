"""mxtpu.memscope: static per-program footprints (capture through the
perfscope funnel and direct lowered/compiled handoff, unavailable
backends degrade to the honest all-None shape), the bounded watermark
ring, capacity/headroom math with the like-with-like pairing,
analytic-vs-measured reconciliation incl. the drift warning, OOM
forensics assembled from a synthesized RESOURCE_EXHAUSTED, the off
path's one-predicate contract, the deep-/healthz headroom embed, the
autotuner's memory-feasibility pruner (counter == payload), and the
tooling satellites (trace_check check_memscope_extra both ways,
perf_regress peak-memory gate incl. both-sides and same-instrument
skips, mxdiag mem rendering, profiler.device_memory_stats
normalization)."""
import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu import memscope as ms
from incubator_mxnet_tpu import perfscope as ps
from incubator_mxnet_tpu import profiler as prof
from incubator_mxnet_tpu.autotune.knobs import KnobConfig
from incubator_mxnet_tpu.autotune.trial import TrialResult
from incubator_mxnet_tpu.autotune.tuner import search
from incubator_mxnet_tpu.memscope import feasibility as feas
from incubator_mxnet_tpu.memscope import footprint as fp
from incubator_mxnet_tpu.memscope import forensics as forens
from incubator_mxnet_tpu.memscope.watermark import (WatermarkRing,
                                                    host_rss_bytes)
from incubator_mxnet_tpu.profiler import tpu as prof_tpu

GiB = 2 ** 30


def _load_tool(name):
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _memscope_teardown(monkeypatch):
    # the capacity/headroom knobs must come from THIS test, never from
    # the invoking shell (the smoke exports MXTPU_MEMSCOPE_CAPACITY)
    for var in ("MXTPU_MEMSCOPE", "MXTPU_MEMSCOPE_RING",
                "MXTPU_MEMSCOPE_HEADROOM", "MXTPU_MEMSCOPE_CAPACITY"):
        monkeypatch.delenv(var, raising=False)
    yield
    ms.disable()
    ms.reset()
    ps.disable()          # ms.enable() arms perfscope too
    ps.reset_programs()
    assert not prof_tpu.tracing(), \
        "a test leaked an active jax profiler trace"


def _counters(prefix="memscope/"):
    return {k: v for k, v in prof.counters().items()
            if k.startswith(prefix)}


def _lowered(n=8):
    def f(x):
        return jnp.tanh(x @ x.T).sum()
    return jax.jit(f).lower(jnp.zeros((n, n), jnp.float32))


# ---------------------------------------------------------------------------
# static footprints
# ---------------------------------------------------------------------------

class TestFootprint:
    def test_capture_from_lowered_derives_peak_on_cpu(self):
        before = _counters().get("memscope/memscope.programs_captured", 0)
        rec = fp.capture("prog_lowered", lowered=_lowered())
        assert rec["available"] is True
        # CPU jaxlib's memory_analysis has no peak field: the peak must
        # be DERIVED (arg+out+temp+code), never invented as "reported"
        assert rec["provenance"] == "derived"
        assert isinstance(rec["peak_bytes"], int) and rec["peak_bytes"] > 0
        for f in fp.BYTE_FIELDS:
            v = rec[f]
            assert v is None or (isinstance(v, int) and v >= 0), (f, v)
        assert rec["peak_bytes"] == sum(
            rec[f] or 0 for f in ("argument_bytes", "output_bytes",
                                  "temp_bytes", "generated_code_bytes"))
        assert fp.footprint_of("prog_lowered") == rec
        after = _counters()["memscope/memscope.programs_captured"]
        assert after == before + 1

    def test_capture_from_compiled_is_equivalent(self):
        low = _lowered()
        via_lowered = fp.capture("prog_a", lowered=low)
        via_compiled = fp.capture("prog_b", compiled=low.compile())
        for f in fp.BYTE_FIELDS + ("peak_bytes", "provenance"):
            assert via_lowered[f] == via_compiled[f], f

    def test_reported_peak_when_backend_carries_one(self):
        class _Analysis:
            argument_size_in_bytes = 100
            output_size_in_bytes = 10
            temp_size_in_bytes = 50
            generated_code_size_in_bytes = 5
            peak_memory_in_bytes = 999

        class _Compiled:
            def memory_analysis(self):
                return _Analysis()

        rec = fp.capture("prog_tpu_like", compiled=_Compiled())
        assert rec["provenance"] == "reported"
        assert rec["peak_bytes"] == 999      # the backend's word wins

    def test_unavailable_backend_degrades_counted_not_raised(self):
        class _Compiled:
            def memory_analysis(self):
                raise NotImplementedError("no analysis on this backend")

        before = _counters().get("memscope/memscope.capture_unknown", 0)
        rec = fp.capture("prog_dark", compiled=_Compiled())
        assert rec["available"] is False
        assert rec["provenance"] == "unavailable"
        # honest Nones, not invented zeros (trace_check pins this too)
        for f in fp.BYTE_FIELDS + ("peak_bytes",):
            assert rec[f] is None, f
        assert _counters()["memscope/memscope.capture_unknown"] \
            == before + 1

    def test_capture_never_raises_on_garbage(self):
        # object() has no .compile / .memory_analysis: the record
        # degrades instead of the compile site blowing up
        rec = fp.capture("prog_junk", lowered=object())
        assert rec["available"] is False

    def test_recompile_overwrites_by_name(self):
        fp.capture("prog_x", lowered=_lowered(4))
        small = fp.footprint_of("prog_x")["peak_bytes"]
        fp.capture("prog_x", lowered=_lowered(64))
        big = fp.footprint_of("prog_x")["peak_bytes"]
        assert big > small
        assert sum(1 for r in fp.footprints()
                   if r["name"] == "prog_x") == 1

    def test_perfscope_funnel_captures_when_armed(self):
        ms.enable()
        assert ps.enabled()          # memscope arms its host layer
        net = gluon.nn.Dense(4, in_units=6)
        net.initialize()
        net.hybridize()
        net(nd.array(np.zeros((2, 6), np.float32)))
        recs = fp.footprints()
        assert recs, "hybridize jit cache compile produced no footprint"
        assert any(r["available"] for r in recs)
        # the join key: every footprint name must resolve a perfscope
        # roofline verdict in the bench payload
        joined = ms.bench_extra()["programs"]
        assert any(r.get("roofline") is not None for r in joined), \
            [r.get("name") for r in joined]

    def test_off_path_funnel_does_not_capture(self):
        ps.enable()                  # perfscope alone, memscope off
        net = gluon.nn.Dense(4, in_units=6)
        net.initialize()
        net.hybridize()
        net(nd.array(np.zeros((2, 6), np.float32)))
        assert fp.footprints() == []


# ---------------------------------------------------------------------------
# watermark ring
# ---------------------------------------------------------------------------

class TestWatermarkRing:
    def test_ring_stays_bounded_while_samples_count_total(self):
        r = WatermarkRing(4)
        for i in range(10):
            r.sample(step=i)
        s = r.summary()
        assert s["samples"] == 10
        assert s["ring"] == 4 and s["ring_limit"] == 4
        # oldest evicted: the survivors are the LAST four steps
        assert [t["step"] for t in r.snapshot()] == [6, 7, 8, 9]
        assert len(s["tail"]) <= 8

    def test_cpu_devices_degrade_but_host_rss_is_real(self):
        r = WatermarkRing(8)
        rec = r.sample(step=1)
        # XLA:CPU devices report no allocator stats
        assert rec["available"] is False
        assert all(d == {"available": False}
                   for d in rec["devices"].values())
        assert rec["host_rss_bytes"] and rec["host_rss_bytes"] > 0
        s = r.summary()
        assert s["device"] is None
        rss = s["host_rss"]
        assert rss["peak"] >= rss["latest"] > 0
        assert rss["p50"] <= rss["p95"] <= rss["peak"]

    def test_limit_sanitized(self):
        assert WatermarkRing("bogus").limit == 256
        assert WatermarkRing(0).limit == 1
        assert WatermarkRing(-3).limit == 1

    def test_module_sample_off_is_none_and_uncounted(self):
        before = _counters().get("memscope/memscope.samples", 0)
        assert ms.sample(step=1) is None     # _MS is None: one predicate
        assert ms.watermark_summary() is None
        assert _counters().get("memscope/memscope.samples", 0) == before

    def test_module_sample_armed_counts_and_respects_ring_knob(
            self, monkeypatch):
        monkeypatch.setenv("MXTPU_MEMSCOPE_RING", "3")
        before = _counters().get("memscope/memscope.samples", 0)
        ms.enable()
        for i in range(5):
            ms.sample(step=i, workload="train")
        s = ms.watermark_summary()
        assert s["ring_limit"] == 3 and s["ring"] == 3
        assert s["samples"] == 5
        assert _counters()["memscope/memscope.samples"] == before + 5

    def test_host_rss_bytes_positive_here(self):
        v = host_rss_bytes()
        assert v is not None and v > 0


# ---------------------------------------------------------------------------
# capacity + headroom
# ---------------------------------------------------------------------------

class TestHeadroom:
    def test_target_default_override_and_sanitation(self, monkeypatch):
        assert ms.headroom_target() == ms.DEFAULT_HEADROOM
        monkeypatch.setenv("MXTPU_MEMSCOPE_HEADROOM", "0.5")
        assert ms.headroom_target() == 0.5
        monkeypatch.setenv("MXTPU_MEMSCOPE_HEADROOM", "1.7")
        assert ms.headroom_target() == ms.DEFAULT_HEADROOM

    def test_capacity_env_override_beats_probing(self, monkeypatch):
        monkeypatch.setenv("MXTPU_MEMSCOPE_CAPACITY", str(8 * GiB))
        assert ms.device_capacity() == {"bytes": 8 * GiB,
                                        "source": "env"}

    def test_capacity_on_cpu_is_host_ram(self):
        cap = ms.device_capacity()
        # no allocator limits on XLA:CPU: host RAM is the honest bound
        assert cap["source"] == "host_ram"
        assert cap["bytes"] > 0

    def test_headroom_ok_under_roomy_capacity(self, monkeypatch):
        monkeypatch.setenv("MXTPU_MEMSCOPE_CAPACITY", str(1 << 45))
        hs = ms.headroom_state()
        assert hs["verdict"] == "ok"
        assert hs["in_use_source"] == "host_rss"   # like-with-like
        assert 0.0 < hs["headroom_fraction"] <= 1.0
        assert hs["in_use_bytes"] > 0
        assert hs["capacity_source"] == "env"

    def test_headroom_tight_when_capacity_tiny(self, monkeypatch):
        monkeypatch.setenv("MXTPU_MEMSCOPE_CAPACITY", "1024")
        hs = ms.headroom_state()
        assert hs["verdict"] == "tight"
        assert hs["headroom_fraction"] == 0.0     # clamped, never < 0

    def test_headroom_unknown_without_capacity(self, monkeypatch):
        monkeypatch.setattr(ms, "device_capacity",
                            lambda: {"bytes": None, "source": "unknown"})
        hs = ms.headroom_state()
        assert hs["verdict"] == "unknown"
        assert hs["headroom_fraction"] is None


# ---------------------------------------------------------------------------
# analytic-vs-measured reconciliation
# ---------------------------------------------------------------------------

class _FakeRing:
    """A ring whose device column reports — CPU can't produce one."""

    def __init__(self, peak):
        self._peak = peak

    def summary(self):
        return {"device": {"p50": self._peak, "p95": self._peak,
                           "peak": self._peak, "latest": self._peak}}

    def latest(self):
        return None

    def reset(self):
        pass


class TestReconciliation:
    def test_analytic_registers_and_reports(self, monkeypatch):
        # quiet the measured side: the ledger census would otherwise
        # report whatever live arrays earlier tests left behind
        from incubator_mxnet_tpu.diagnostics import memory as dmem
        monkeypatch.setattr(dmem, "reconcile", lambda: {})
        ms.register_analytic({"param_bytes_per_device": 1000,
                              "state_bytes_per_device": 2000,
                              "reduction": "3.3x"})
        rec = ms.reconciliation()
        assert rec["analytic"]["total_per_device"] == 3000
        assert rec["analytic"]["reduction"] == "3.3x"
        assert rec["drift_warning"] is False

    def test_malformed_analytic_dropped(self):
        ms.register_analytic("not a dict")
        assert ms.reconciliation()["analytic"] is None
        ms.register_analytic({"state_bytes_per_device": 5})  # no params
        assert ms.reconciliation()["analytic"] is None

    def test_drift_beyond_threshold_warns_and_counts(self):
        ms.enable()
        ms._MS.ring = _FakeRing(10 * GiB)     # measured says 10 GiB
        ms.register_analytic({"param_bytes_per_device": 1 * GiB,
                              "state_bytes_per_device": 0})
        before = _counters().get("memscope/memscope.drift_warnings", 0)
        with pytest.warns(UserWarning, match="gone stale"):
            rec = ms.reconciliation()
        assert rec["drift_warning"] is True
        assert rec["drift"]["per_device_bytes"] == 9.0
        assert rec["measured"]["source"] == "memory_stats"
        assert _counters()["memscope/memscope.drift_warnings"] \
            == before + 1

    def test_drift_within_threshold_is_quiet(self):
        ms.enable()
        ms._MS.ring = _FakeRing(int(1.1 * GiB))
        ms.register_analytic({"param_bytes_per_device": GiB,
                              "state_bytes_per_device": 0})
        rec = ms.reconciliation()
        assert rec["drift_warning"] is False
        assert rec["drift"]["per_device_bytes"] == pytest.approx(
            0.1, abs=1e-6)


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

class TestForensics:
    @pytest.mark.parametrize("exc,want", [
        (MemoryError(), True),
        (RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying "
                      "to allocate 17179869184 bytes."), True),
        (RuntimeError("Resource exhausted: ran out of HBM"), True),
        (RuntimeError("failed to allocate request for 2.0GiB"), True),
        (RuntimeError("std::bad_alloc"), True),
        (ValueError("shapes (3,4) and (5,6) not aligned"), False),
        (RuntimeError("INVALID_ARGUMENT: mesh mismatch"), False),
    ])
    def test_is_oom_error_taxonomy(self, exc, want):
        assert forens.is_oom_error(exc) is want

    def test_non_oom_error_records_nothing(self):
        before = _counters().get("memscope/memscope.oom_events", 0)
        assert ms.record_oom(ValueError("nope"), program="p") is None
        assert ms.last_post_mortem() is None
        assert _counters().get("memscope/memscope.oom_events", 0) \
            == before

    def test_post_mortem_from_synthesized_resource_exhausted(self):
        ms.enable(ring_limit=8)
        fp.capture("fused_step_b64", lowered=_lowered(16))
        for i in range(12):
            ms.sample(step=i, workload="train")
        err = RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory while trying to "
            "allocate 17179869184 bytes.")
        before = _counters().get("memscope/memscope.oom_events", 0)
        pm = ms.record_oom(err, program="fused_step_b64", step=11)
        assert pm is not None
        assert pm["schema"] == forens.OOM_SCHEMA
        assert pm["error_type"] == "RuntimeError"
        assert "RESOURCE_EXHAUSTED" in pm["error"]
        assert pm["program"] == "fused_step_b64" and pm["step"] == 11
        # the offending program's static footprint rides along
        assert pm["footprint"]["peak_bytes"] > 0
        # the watermark tail: what memory did in the steps before death
        assert 0 < len(pm["watermark_tail"]) <= 8
        assert pm["watermark_tail"][-1]["step"] == 11
        # the resolved knob config that produced the shape
        assert isinstance(pm["knobs"], dict) and "batch" in pm["knobs"]
        assert pm["capacity"]["source"] == "host_ram"
        assert _counters()["memscope/memscope.oom_events"] == before + 1
        # the last post-mortem is what extra.memscope.oom publishes
        assert ms.last_post_mortem() is pm
        assert ms.bench_extra()["oom"] is pm

    def test_forensics_never_masks_the_error(self):
        class _Hostile:
            def __str__(self):
                raise RuntimeError("even str() is broken")
        # is_oom_error and record_oom both swallow: the caller's
        # re-raise of the ORIGINAL error is never replaced
        assert forens.is_oom_error(_Hostile()) is False
        assert ms.record_oom(_Hostile()) is None


# ---------------------------------------------------------------------------
# bench payload + trace_check schema (satellite)
# ---------------------------------------------------------------------------

def _armed_extra():
    ms.enable(ring_limit=8)
    fp.capture("fused_step_b64", lowered=_lowered(16))
    for i in range(10):
        ms.sample(step=i)
    return ms.bench_extra()


class TestBenchExtraSchema:
    def test_real_payload_validates(self):
        tc = _load_tool("trace_check")
        extra = _armed_extra()
        extra = json.loads(json.dumps(extra))   # the BENCH round-trip
        assert tc.check_memscope_extra(extra) == []

    def test_absent_section_is_fine(self):
        tc = _load_tool("trace_check")
        assert tc.check_memscope_extra(None) == []

    def test_violations_flagged(self):
        tc = _load_tool("trace_check")
        base = json.loads(json.dumps(_armed_extra()))

        bad = json.loads(json.dumps(base))
        bad["programs"][0]["provenance"] = "guessed"
        assert any("provenance" in e
                   for e in tc.check_memscope_extra(bad))

        bad = json.loads(json.dumps(base))
        bad["watermarks"]["ring"] = bad["watermarks"]["ring_limit"] + 1
        assert any("unbounded ring" in e
                   for e in tc.check_memscope_extra(bad))

        bad = json.loads(json.dumps(base))
        bad["programs"][0].update(available=False,
                                  provenance="unavailable")
        # unavailable record must NOT keep its bytes
        assert any("unavailable record carries" in e
                   for e in tc.check_memscope_extra(bad))

        bad = json.loads(json.dumps(base))
        bad["headroom"]["verdict"] = "plenty"
        assert any("verdict" in e for e in tc.check_memscope_extra(bad))

        bad = json.loads(json.dumps(base))
        bad["capacity"] = {"bytes": None, "source": "host_ram"}
        assert any("bytes is null" in e
                   for e in tc.check_memscope_extra(bad))

        bad = json.loads(json.dumps(base))
        bad["oom"] = {"schema": "wrong/0", "error": "boom"}
        assert any("oom.schema" in e
                   for e in tc.check_memscope_extra(bad))

    def test_families_registered(self):
        tc = _load_tool("trace_check")
        fam = tc.MEMSCOPE_FAMILIES
        assert "memscope/memscope.programs_captured" in fam
        assert "memscope/memscope.oom_events" in fam
        assert "memscope/memscope.headroom_fraction" in fam


# ---------------------------------------------------------------------------
# perf_regress peak-memory gate (satellite)
# ---------------------------------------------------------------------------

def _artifact(tmp_path, name, peak=None, sect="host_rss", static=None,
              value=100.0):
    extra = {}
    if peak is not None:
        extra["memscope"] = {
            "programs": [],
            "watermarks": {"samples": 10, "ring": 8, "ring_limit": 8,
                           "available": sect == "device",
                           sect: {"p50": peak, "p95": peak,
                                  "peak": peak, "latest": peak}},
        }
    elif static is not None:
        extra["memscope"] = {
            "programs": [{"name": "fused", "peak_bytes": static}]}
    doc = {"metric": "images_sec", "value": value, "unit": "img/s",
           "extra": extra}
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


class TestPerfRegressPeakGate:
    def test_loader_extracts_peak_and_instrument(self, tmp_path):
        pr = _load_tool("perf_regress")
        rec, skip = pr.load_artifact(
            _artifact(tmp_path, "a.json", peak=GiB))
        assert skip is None
        assert rec["peak_bytes"] == GiB
        assert rec["peak_source"] == "watermark host_rss"
        rec2, _ = pr.load_artifact(
            _artifact(tmp_path, "b.json", static=GiB))
        assert rec2["peak_source"] == "static footprint"

    def test_growth_beyond_threshold_flags(self, tmp_path):
        pr = _load_tool("perf_regress")
        b, _ = pr.load_artifact(_artifact(tmp_path, "b.json", peak=GiB))
        c, _ = pr.load_artifact(
            _artifact(tmp_path, "c.json", peak=int(GiB * 1.3)))
        regs, _notes = pr.compare(b, c)
        assert any("peak memory" in r for r in regs), regs
        # within threshold: quiet
        c2, _ = pr.load_artifact(
            _artifact(tmp_path, "d.json", peak=int(GiB * 1.05)))
        regs2, _ = pr.compare(b, c2)
        assert not any("peak memory" in r for r in regs2), regs2

    def test_one_sided_is_a_note_not_a_gate(self, tmp_path):
        pr = _load_tool("perf_regress")
        b, _ = pr.load_artifact(_artifact(tmp_path, "b.json", peak=GiB))
        c, _ = pr.load_artifact(_artifact(tmp_path, "c.json"))
        regs, notes = pr.compare(b, c)
        assert not any("peak memory" in r for r in regs)
        assert any("peak" in n for n in notes), notes

    def test_instrument_mismatch_skips(self, tmp_path):
        pr = _load_tool("perf_regress")
        b, _ = pr.load_artifact(
            _artifact(tmp_path, "b.json", peak=GiB, sect="device"))
        c, _ = pr.load_artifact(
            _artifact(tmp_path, "c.json", peak=3 * GiB,
                      sect="host_rss"))
        regs, notes = pr.compare(b, c)
        # a host-RSS number is not comparable to a device watermark
        assert not any("peak memory" in r for r in regs)
        assert any("instrument" in n for n in notes), notes


# ---------------------------------------------------------------------------
# mxdiag mem renderer (satellite)
# ---------------------------------------------------------------------------

class TestMxdiagMem:
    def test_renders_real_payload(self, capsys):
        md = _load_tool("mxdiag")
        extra = json.loads(json.dumps(_armed_extra()))
        md.print_mem({"metric": "images_sec", "value": 100.0,
                      "extra": {"memscope": extra}})
        out = capsys.readouterr().out
        assert "fused_step_b64" in out
        assert "headroom" in out
        assert "no OOM recorded" in out

    def test_renders_oom_post_mortem(self, capsys):
        md = _load_tool("mxdiag")
        ms.enable(ring_limit=8)
        fp.capture("fused_step_b64", lowered=_lowered(16))
        for i in range(6):
            ms.sample(step=i)
        ms.record_oom(RuntimeError("RESOURCE_EXHAUSTED: boom"),
                      program="fused_step_b64", step=5)
        extra = json.loads(json.dumps(ms.bench_extra()))
        md.print_mem({"extra": {"memscope": extra}})
        out = capsys.readouterr().out
        assert "RESOURCE_EXHAUSTED" in out
        assert "fused_step_b64" in out

    def test_handles_missing_section(self, capsys):
        md = _load_tool("mxdiag")
        md.print_mem({"extra": {}})
        assert "memscope" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# feasibility math + the tuner's pre-trial pruner
# ---------------------------------------------------------------------------

class TestFeasibility:
    def test_linear_batch_prediction(self):
        p, basis = feas.predict_candidate_peak(
            "batch", 128, {"peak_bytes": 1000, "batch": 64})
        assert (p, basis) == (2000.0, "linear_batch")

    def test_missing_baseline_disables(self):
        assert feas.predict_candidate_peak(
            "batch", 128, {"batch": 64}) == (None, "no_baseline_peak")
        assert feas.predict_candidate_peak(
            "batch", 128, {"peak_bytes": 1000}) \
            == (None, "no_baseline_batch")
        assert feas.predict_candidate_peak(
            "batch", 128, None) == (None, "no_baseline_peak")

    def test_remat_floor(self):
        base = {"peak_bytes": 1000, "batch": 64, "remat": True}
        p, basis = feas.predict_candidate_peak("remat_policy", None, base)
        assert (p, basis) == (1000.0, "remat_floor")
        # a non-rematerializing baseline predicts nothing
        p, basis = feas.predict_candidate_peak(
            "remat_policy", None, {"peak_bytes": 1000, "batch": 64})
        assert p is None

    def test_non_memory_knob_runs_normally(self):
        p, basis = feas.predict_candidate_peak(
            "loop_chunk", 8, {"peak_bytes": 1000, "batch": 64})
        assert (p, basis) == (None, "not_memory_knob")

    def test_check_feasible_and_infeasible(self):
        base = {"peak_bytes": GiB, "batch": 64}
        ok = feas.feasibility_check("batch", 128, base,
                                    capacity_bytes=8 * GiB, target=0.9)
        assert ok["feasible"] is True and ok["reason"] is None
        before = _counters().get(
            "memscope/memscope.infeasible_candidates", 0)
        bad = feas.feasibility_check("batch", 1024, base,
                                     capacity_bytes=8 * GiB, target=0.5)
        assert bad["feasible"] is False
        assert bad["reason"].startswith("memory:")
        assert bad["predicted_peak_bytes"] == 16 * GiB
        assert bad["limit_bytes"] == 4 * GiB
        assert _counters()["memscope/memscope.infeasible_candidates"] \
            == before + 1

    def test_fails_open(self):
        v = feas.feasibility_check("batch", 128, "garbage")
        assert v["feasible"] is True


GAPS_DISPATCH = {"input_starved_ms": 0.2, "dispatch_serialized_ms": 3.0,
                 "host_gap_ms": 2.0}


def _mem_runner(calls=None):
    """A deterministic fake trial whose baseline measurement carries
    the measured memscope peak the pruner scales over: 2 GiB RSS at
    batch 64."""
    def run(cfg, knob=None, value=None):
        if calls is not None:
            calls.append((knob, value, cfg))
        m = {"busy_fraction": 0.5, "step_ms": 10.0, "mfu": 0.1,
             "value": 100.0, "gaps": dict(GAPS_DISPATCH),
             "mfu_if_removed": None, "provenance": "measured(profile)",
             "memscope": {"peak_bytes": 2 * GiB,
                          "peak_source": "watermark_host_rss",
                          "batch": 64, "capacity": None}}
        return TrialResult(cfg, "ok", measurement=m, knob=knob,
                           value=value)
    return run


class TestTunerMemoryPruner:
    def test_infeasible_batch_rejected_pre_trial(self, tmp_path,
                                                 monkeypatch):
        monkeypatch.setenv("MXTPU_MEMSCOPE_CAPACITY", str(8 * GiB))
        calls = []
        before = prof.counters().get(
            "autotune/autotune.trials_pruned", 0)
        r = search(model="lenet", batch=64, runner=_mem_runner(calls),
                   cache_dir=str(tmp_path), use_cache=False, budget=12,
                   batch_candidates=(65536,))
        # the verdict: filed beside the knob-family prunes
        reason = r.pruned.get("batch=65536")
        assert isinstance(reason, str) and reason.startswith("memory:"),\
            r.pruned
        assert "linear_batch" in reason
        # zero subprocess spent: the runner never saw the candidate
        assert all(v != 65536 for _k, v, _c in calls)
        # counter == payload contract across BOTH prune kinds
        extra = r.to_extra()
        assert extra["pruned"]["batch=65536"] == reason
        delta = prof.counters()["autotune/autotune.trials_pruned"] \
            - before
        assert delta == extra["trials_pruned"] >= 1

    def test_feasible_batch_candidate_is_tried(self, tmp_path,
                                               monkeypatch):
        monkeypatch.setenv("MXTPU_MEMSCOPE_CAPACITY", str(8 * GiB))
        calls = []
        r = search(model="lenet", batch=64, runner=_mem_runner(calls),
                   cache_dir=str(tmp_path), use_cache=False, budget=20,
                   batch_candidates=(128,))
        # 2 GiB x 2 = 4 GiB < 8 GiB x 0.9: feasible, so it runs
        assert "batch=128" not in r.pruned
        assert any(v == 128 for _k, v, _c in calls)

    def test_no_memscope_baseline_disables_gate(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv("MXTPU_MEMSCOPE_CAPACITY", str(8 * GiB))

        def run(cfg, knob=None, value=None):
            m = {"busy_fraction": 0.5, "step_ms": 10.0, "mfu": 0.1,
                 "value": 100.0, "gaps": dict(GAPS_DISPATCH),
                 "mfu_if_removed": None,
                 "provenance": "measured(profile)",
                 "memscope": {"peak_bytes": None, "peak_source": None,
                              "batch": None, "capacity": None}}
            return TrialResult(cfg, "ok", measurement=m, knob=knob,
                               value=value)
        r = search(model="lenet", batch=64, runner=run,
                   cache_dir=str(tmp_path), use_cache=False, budget=20,
                   batch_candidates=(65536,))
        # the pruner only rejects what it can defend: no baseline peak,
        # no verdict — the candidate runs like any other
        assert "batch=65536" not in r.pruned


# ---------------------------------------------------------------------------
# deep /healthz headroom embed (serving)
# ---------------------------------------------------------------------------

def _tiny_frozen():
    from incubator_mxnet_tpu.serving import FrozenModel
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4, in_units=6))
    net.initialize(init=mx.init.Xavier())
    return FrozenModel(net, input_shape=(6,), batch_buckets=(1, 2))


class TestHealthzHeadroom:
    def test_armed_server_embeds_live_headroom(self, monkeypatch):
        from incubator_mxnet_tpu.serving import ModelServer
        monkeypatch.setenv("MXTPU_MEMSCOPE_CAPACITY", str(1 << 45))
        ms.enable()
        srv = ModelServer(_tiny_frozen(), max_delay_ms=2)
        srv.start()
        try:
            code, body = srv.health()
            assert code == 200
            blk = body["checks"]["memscope"]
            assert blk["verdict"] == "ok"
            assert 0.0 < blk["headroom_fraction"] <= 1.0
            assert blk["capacity_bytes"] == 1 << 45
            assert blk["in_use_bytes"] > 0
            assert blk["oom_events"] == prof.counters().get(
                "memscope/memscope.oom_events", 0)
        finally:
            srv.stop()

    def test_unarmed_server_reports_no_memscope_block(self):
        from incubator_mxnet_tpu.serving import ModelServer
        srv = ModelServer(_tiny_frozen(), max_delay_ms=2)
        srv.start()
        try:
            code, body = srv.health()
            assert code == 200
            assert "memscope" not in body["checks"]
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# profiler.device_memory_stats normalization (satellite bugfix)
# ---------------------------------------------------------------------------

class TestDeviceMemoryStats:
    def test_cpu_device_degrades_counted(self):
        before = _counters().get(
            "memscope/memscope.stats_unavailable", 0)
        st = prof.device_memory_stats(jax.local_devices()[0])
        # XLA:CPU returns None from memory_stats(): the helper must
        # hand back the one-flag shape, not None, not a raise
        assert st == {"available": False}
        assert _counters()["memscope/memscope.stats_unavailable"] \
            == before + 1

    def test_reporting_device_normalized(self):
        class _Dev:
            def memory_stats(self):
                return {"bytes_in_use": 5, "peak_bytes_in_use": 7,
                        "bytes_limit": 10}
        st = prof.device_memory_stats(_Dev())
        assert st["available"] is True
        assert (st["bytes_in_use"], st["peak_bytes_in_use"],
                st["bytes_limit"]) == (5, 7, 10)

    def test_hostile_device_degrades(self):
        class _Dev:
            def memory_stats(self):
                raise RuntimeError("backend says no")
        assert prof.device_memory_stats(_Dev()) == {"available": False}
