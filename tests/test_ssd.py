"""Box ops + SSD tests (mirrors reference tests/python/unittest/
test_contrib_operator.py multibox cases + example/ssd smoke)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd, ops
from incubator_mxnet_tpu.models.ssd import SSD, SSDLoss, ssd_300_resnet18_v1


# ---------------------------------------------------------------------------
# box_iou / box_nms
# ---------------------------------------------------------------------------

def test_box_iou_known_values():
    a = nd.array([[0.0, 0.0, 1.0, 1.0], [0.0, 0.0, 0.5, 0.5]])
    b = nd.array([[0.0, 0.0, 1.0, 1.0], [0.5, 0.5, 1.0, 1.0]])
    iou = ops.box_iou(a, b).asnumpy()
    np.testing.assert_allclose(iou[0, 0], 1.0, atol=1e-6)
    np.testing.assert_allclose(iou[0, 1], 0.25, atol=1e-6)
    np.testing.assert_allclose(iou[1, 0], 0.25, atol=1e-6)
    np.testing.assert_allclose(iou[1, 1], 0.0, atol=1e-6)


def test_box_nms_suppresses_overlaps():
    # rows: [id, score, x0, y0, x1, y1]
    data = nd.array([
        [0, 0.9, 0.0, 0.0, 1.0, 1.0],
        [0, 0.8, 0.01, 0.01, 1.0, 1.0],   # heavy overlap with row 0 -> out
        [0, 0.7, 0.5, 0.5, 0.9, 0.9],     # small overlap -> kept
        [1, 0.6, 0.02, 0.0, 1.0, 1.0],    # other class -> kept
    ])
    out = ops.box_nms(data, overlap_thresh=0.5, coord_start=2,
                      score_index=1, id_index=0).asnumpy()
    scores = out[:, 1]
    assert (scores > 0).sum() == 3
    assert 0.8 not in scores[scores > 0]


def test_box_nms_force_suppress_ignores_class():
    data = nd.array([
        [0, 0.9, 0.0, 0.0, 1.0, 1.0],
        [1, 0.6, 0.02, 0.0, 1.0, 1.0],
    ])
    out = ops.box_nms(data, overlap_thresh=0.5, coord_start=2, score_index=1,
                      id_index=0, force_suppress=True).asnumpy()
    assert (out[:, 1] > 0).sum() == 1


def test_nd_contrib_namespace():
    assert nd.contrib.box_nms is ops.box_nms
    assert nd.contrib.MultiBoxPrior is ops.MultiBoxPrior


# ---------------------------------------------------------------------------
# MultiBoxPrior / Target / Detection
# ---------------------------------------------------------------------------

def test_multibox_prior_count_and_centers():
    x = nd.zeros((1, 4, 4, 8))  # NHWC
    anchors = ops.MultiBoxPrior(x, sizes=[0.5, 0.25], ratios=[1, 2],
                                layout="NHWC")
    a = anchors.asnumpy()
    assert a.shape == (1, 4 * 4 * 3, 4)
    # first pixel center = (0.5/4, 0.5/4); first anchor size 0.5 ratio 1
    np.testing.assert_allclose(a[0, 0], [0.125 - 0.25, 0.125 - 0.25,
                                         0.125 + 0.25, 0.125 + 0.25],
                               atol=1e-6)


def test_multibox_prior_nonsquare_map_pixel_square():
    """Reference kernel scales anchor width by in_h/in_w: on a non-square
    feature map, a ratio-1 anchor must stay square in PIXEL space."""
    h, w = 2, 4
    x = nd.zeros((1, 8, h, w))  # NCHW
    a = ops.MultiBoxPrior(x, sizes=[0.5], ratios=[1.0]).asnumpy()[0]
    # first pixel center = (0.5/w, 0.5/h); w_norm = 0.5*h/w, h_norm = 0.5
    np.testing.assert_allclose(a[0], [0.125 - 0.125, 0.25 - 0.25,
                                      0.125 + 0.125, 0.25 + 0.25], atol=1e-6)
    w_norm = a[:, 2] - a[:, 0]
    h_norm = a[:, 3] - a[:, 1]
    np.testing.assert_allclose(w_norm * w, h_norm * h, atol=1e-6)


def test_multibox_detection_default_topk_all():
    """Op-level default nms_topk=-1 considers every candidate (reference
    default); anchors beyond any fixed top-k still come through."""
    import inspect
    assert inspect.signature(ops.MultiBoxDetection).parameters[
        "nms_topk"].default == -1


def test_multibox_target_matches_gt():
    # one anchor dead-on a GT box, one far away
    anchor = nd.array(np.array([[[0.1, 0.1, 0.4, 0.4],
                                 [0.6, 0.6, 0.9, 0.9]]]))
    label = nd.array(np.array([[[1.0, 0.1, 0.1, 0.4, 0.4]]]))  # class 1
    cls_pred = nd.zeros((1, 3, 2))
    bt, bm, ct = ops.MultiBoxTarget(anchor, label, cls_pred,
                                    negative_mining_ratio=-1)
    ct = ct.asnumpy()
    assert ct[0, 0] == 2          # class 1 -> target 2 (0 is background)
    assert ct[0, 1] == 0          # unmatched -> background
    bm = bm.asnumpy().reshape(1, 2, 4)
    assert bm[0, 0].sum() == 4 and bm[0, 1].sum() == 0
    bt = bt.asnumpy().reshape(1, 2, 4)
    np.testing.assert_allclose(bt[0, 0], 0.0, atol=1e-5)  # perfect match


def test_multibox_target_hard_negative_mining():
    rng = np.random.RandomState(0)
    anchor = nd.array(rng.uniform(0, 0.4, (1, 20, 2)).repeat(2, axis=-1)
                      + np.array([0, 0, 0.3, 0.3]))
    label = nd.array(np.array([[[0.0, 0.05, 0.05, 0.35, 0.35]]]))
    cls_pred = nd.array(rng.randn(1, 4, 20))
    bt, bm, ct = ops.MultiBoxTarget(anchor, label, cls_pred,
                                    negative_mining_ratio=2,
                                    negative_mining_thresh=0.5)
    ct = ct.asnumpy()[0]
    n_pos = (ct > 0).sum()
    n_neg = (ct == 0).sum()
    n_ign = (ct == -1).sum()
    assert n_pos >= 1
    assert n_neg <= 2 * n_pos     # mining ratio respected
    assert n_ign > 0              # some anchors ignored


def test_multibox_target_padded_labels_keep_bipartite_match():
    """Padding rows (cls=-1) must not steal the forced bipartite match at
    anchor 0 (regression: padded gts all argmax to anchor 0)."""
    # gt's best anchor IS anchor 0 but with IoU below threshold
    anchor = nd.array(np.array([[[0.0, 0.0, 0.3, 0.3],
                                 [0.7, 0.7, 1.0, 1.0]]]))
    label = nd.array(np.array([[[2.0, 0.2, 0.2, 0.6, 0.6],
                                [-1.0, 0.0, 0.0, 0.0, 0.0],
                                [-1.0, 0.0, 0.0, 0.0, 0.0]]]))
    cls_pred = nd.zeros((1, 4, 2))
    bt, bm, ct = ops.MultiBoxTarget(anchor, label, cls_pred,
                                    overlap_threshold=0.5,
                                    negative_mining_ratio=-1)
    ct = ct.asnumpy()
    assert ct[0, 0] == 3          # class 2 -> target 3, forced bipartite
    assert bm.asnumpy().reshape(1, 2, 4)[0, 0].sum() == 4


def test_multibox_target_two_gts_get_distinct_anchors():
    """Two GTs sharing a best anchor must claim different anchors
    (exclusive sequential bipartite, reference matcher semantics)."""
    anchor = nd.array(np.array([[[0.0, 0.0, 0.4, 0.4],
                                 [0.05, 0.05, 0.45, 0.45],
                                 [0.7, 0.7, 1.0, 1.0]]]))
    # both GTs closest to anchor 0; below the 0.9 threshold so only the
    # bipartite stage can make positives
    label = nd.array(np.array([[[0.0, 0.0, 0.0, 0.38, 0.38],
                                [1.0, 0.02, 0.02, 0.40, 0.40]]]))
    cls_pred = nd.zeros((1, 3, 3))
    bt, bm, ct = ops.MultiBoxTarget(anchor, label, cls_pred,
                                    overlap_threshold=0.9,
                                    negative_mining_ratio=-1)
    ct = ct.asnumpy()[0]
    assert (ct > 0).sum() == 2            # both GTs matched
    assert ct[0] != ct[1] or (ct[0] > 0 and ct[1] > 0)
    assert set(ct[:2]) == {1.0, 2.0}      # distinct anchors, distinct classes


def test_box_nms_center_format():
    # centered boxes: both rows are the same box in center format
    data = nd.array([[0, 0.9, 0.5, 0.5, 1.0, 1.0],
                     [0, 0.8, 0.5, 0.5, 1.0, 1.0]])
    out = ops.box_nms(data, overlap_thresh=0.5, coord_start=2, score_index=1,
                      id_index=0, in_format="center",
                      out_format="center").asnumpy()
    assert (out[:, 1] > 0).sum() == 1


def test_multibox_detection_roundtrip():
    # perfect loc_pred (zeros) on an anchor == the anchor itself
    anchor = nd.array(np.array([[[0.1, 0.1, 0.4, 0.4],
                                 [0.6, 0.6, 0.9, 0.9]]]))
    cls_prob = nd.array(np.array([[[0.1, 0.8],    # background prob
                                   [0.9, 0.1],    # class 0
                                   [0.0, 0.1]]]))  # class 1
    loc_pred = nd.zeros((1, 8))
    out = ops.MultiBoxDetection(cls_prob, loc_pred, anchor,
                                threshold=0.2).asnumpy()
    kept = out[0][out[0, :, 0] >= 0]
    assert kept.shape[0] == 1
    assert kept[0, 0] == 0                       # class 0
    np.testing.assert_allclose(kept[0, 1], 0.9, atol=1e-6)
    np.testing.assert_allclose(kept[0, 2:], [0.1, 0.1, 0.4, 0.4], atol=1e-5)


# ---------------------------------------------------------------------------
# SSD network
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ssd_net():
    mx.random.seed(0)
    np.random.seed(0)
    net = ssd_300_resnet18_v1(classes=4)
    net.initialize()
    return net


def test_ssd_forward_shapes(ssd_net):
    x = nd.ones((2, 128, 128, 3))
    anchor, cls_pred, box_pred = ssd_net(x)
    A = anchor.shape[1]
    assert anchor.shape == (1, A, 4)
    assert cls_pred.shape == (2, A, 5)
    assert box_pred.shape == (2, A * 4)


@pytest.mark.slow
def test_ssd_train_step_decreases_loss(ssd_net):
    net = ssd_net
    L = SSDLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    x = nd.array(np.random.randn(2, 128, 128, 3).astype(np.float32))
    label = nd.array(np.array([
        [[1.0, 0.1, 0.1, 0.45, 0.45]],
        [[3.0, 0.5, 0.5, 0.95, 0.95]]]))
    losses = []
    for _ in range(6):
        with autograd.record():
            anchor, cls_pred, box_pred = net(x)
            with autograd.pause():
                bt, bm, ct = net.targets(anchor, cls_pred, label)
            loss = L(cls_pred, box_pred, ct, bt, bm)
        loss.backward()
        trainer.step(2)
        losses.append(float(loss.asnumpy().mean()))
    assert losses[-1] < losses[0], losses


def test_ssd_detect(ssd_net):
    x = nd.ones((1, 128, 128, 3))
    det = ssd_net.detect(x).asnumpy()
    assert det.shape[-1] == 6
    # scores of kept rows are sorted desc
    kept = det[0][det[0, :, 0] >= 0]
    if kept.shape[0] > 1:
        assert (np.diff(kept[:, 1]) <= 1e-6).all()


# ---------------------------------------------------------------------------
# ROIPooling / im2col / SliceChannel (SURVEY §2.5 vision extras)
# ---------------------------------------------------------------------------

def test_roi_pooling_known_values():
    # 1x1x4x4 image with values 0..15; roi covering the whole image,
    # pooled 2x2 -> max of each quadrant
    img = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    rois = nd.array(np.array([[0, 0, 0, 3, 3]], np.float32))
    out = ops.ROIPooling(img, rois, pooled_size=(2, 2), spatial_scale=1.0)
    np.testing.assert_allclose(out.asnumpy()[0, 0],
                               [[5.0, 7.0], [13.0, 15.0]])


def test_roi_pooling_batch_index_and_scale():
    imgs = nd.array(np.stack([np.zeros((1, 4, 4), np.float32),
                              np.full((1, 4, 4), 9.0, np.float32)]))
    rois = nd.array(np.array([[1, 0, 0, 6, 6]], np.float32))
    out = ops.ROIPooling(imgs, rois, pooled_size=(1, 1), spatial_scale=0.5)
    assert float(out.asnumpy()[0, 0, 0, 0]) == 9.0


def test_im2col_matches_torch_unfold():
    import torch
    x = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
    got = ops.im2col(nd.array(x), kernel=(3, 3), stride=(2, 2),
                     pad=(1, 1)).asnumpy()
    ref = torch.nn.functional.unfold(torch.from_numpy(x), (3, 3),
                                     padding=1, stride=2).numpy()
    np.testing.assert_allclose(got, ref, atol=1e-6)


def test_slice_channel():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(2, 6))
    parts = ops.SliceChannel(x, 3, axis=1)
    assert len(parts) == 3
    np.testing.assert_array_equal(parts[0].asnumpy(), [[0, 1], [6, 7]])


def test_box_nms_out_format_conversion():
    """out_format != in_format converts surviving rows corner<->center;
    suppressed all-(-1) rows stay -1 (reference box_nms semantics)."""
    boxes = np.array([[0, 0.9, 0.0, 0.0, 0.4, 0.4],
                      [0, 0.8, 0.0, 0.0, 0.38, 0.42],   # suppressed
                      [1, 0.7, 0.5, 0.5, 0.9, 0.9]], np.float32)
    out = mx.nd.contrib.box_nms(nd.array(boxes), overlap_thresh=0.5,
                                force_suppress=True,
                                in_format="corner",
                                out_format="center").asnumpy()
    # top row: corner (0,0,.4,.4) -> center (.2,.2,.4,.4)
    np.testing.assert_allclose(out[0, 2:6], [0.2, 0.2, 0.4, 0.4],
                               atol=1e-6)
    assert (out[1] == -1).all()          # suppressed row stays all -1
    np.testing.assert_allclose(out[2, 2:6], [0.7, 0.7, 0.4, 0.4],
                               atol=1e-6)
    with pytest.raises(ValueError):
        mx.nd.contrib.box_nms(nd.array(boxes), out_format="diag")
    # symbol surface validates and converts identically
    from incubator_mxnet_tpu import symbol as sym
    with pytest.raises(ValueError):
        sym.contrib.box_nms(sym.Variable("d"), out_format="diag")
    s = sym.contrib.box_nms(sym.Variable("d"), overlap_thresh=0.5,
                            force_suppress=True, out_format="center")
    r = s.bind(args={"d": boxes}, grad_req="null").forward()[0].asnumpy()
    np.testing.assert_allclose(r, out)
