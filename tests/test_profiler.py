"""mxtpu.profiler subsystem tests (ISSUE 1): Chrome-trace validity,
exact aggregate counts, scope nesting, zero-overhead disabled mode,
multi-layer coverage of a real gluon train loop, engine.bulk scopes,
Monitor-through-counters, and the trace_check schema validator."""
import importlib.util
import json
import os
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, engine, gluon, nd, profiler


@pytest.fixture(autouse=True)
def _clean_profiler():
    profiler.stop()
    profiler.reset()
    profiler.reset_counters()
    yield
    profiler.stop()
    profiler.reset()
    profiler.reset_counters()
    profiler.set_config(filename="profile.json", profile_imperative=True,
                        profile_all=False)


def _load_trace_check():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "trace_check.py")
    spec = importlib.util.spec_from_file_location("trace_check", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -------------------------------------------------------------------------
# Chrome trace validity
# -------------------------------------------------------------------------

def test_start_stop_dump_valid_chrome_trace(tmp_path):
    path = str(tmp_path / "trace.json")
    profiler.set_config(filename=path)
    profiler.start()
    a = nd.ones((4, 4))
    ((a * 2) + 1).sum().wait_to_read()
    profiler.stop()
    written = profiler.dump()
    assert written == path
    with open(path) as f:
        doc = json.loads(f.read())
    events = doc["traceEvents"]
    assert isinstance(events, list) and len(events) >= 3
    x_events = [e for e in events if e.get("ph") == "X"]
    assert x_events, "no complete events recorded"
    for e in x_events:
        assert isinstance(e["name"], str) and e["name"]
        assert e["ph"] == "X"
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
    # the validator agrees
    assert _load_trace_check().check_trace(path) == []


def test_api_parity_surface():
    """mx.profiler parity: every reference entry point exists and the
    legacy utils.profiler path is the SAME module (one state)."""
    for name in ("set_config", "set_state", "start", "stop", "pause",
                 "resume", "dump", "dumps", "Scope", "record_function"):
        assert callable(getattr(profiler, name)), name
    from incubator_mxnet_tpu.utils import profiler as legacy
    assert legacy is profiler
    assert mx.profiler is profiler
    # unknown reference kwargs are accepted and ignored
    profiler.set_config(profile_process="worker", nonsense=1)


# -------------------------------------------------------------------------
# Aggregate stats
# -------------------------------------------------------------------------

def test_aggregate_counts_known_sequence_exactly():
    a = nd.ones((3, 3))
    b = nd.ones((3, 3))
    profiler.start()
    for _ in range(3):
        (a + b).wait_to_read()      # 3x add
    for _ in range(2):
        (a * b).wait_to_read()      # 2x mul
    (a + b).sum().wait_to_read()    # 1x add, 1x sum
    profiler.stop()
    stats = profiler.aggregate_stats()
    assert stats["add"]["count"] == 4
    assert stats["mul"]["count"] == 2
    assert stats["sum"]["count"] == 1
    for ent in stats.values():
        assert ent["min_us"] <= ent["avg_us"] <= ent["max_us"]
        assert ent["total_us"] == pytest.approx(
            ent["avg_us"] * ent["count"])
    table = profiler.dumps()
    assert "Calls" in table and "add" in table and "Min(us)" in table
    profiler.reset()
    assert profiler.dumps().count("\n") == 0


# -------------------------------------------------------------------------
# Scope nesting
# -------------------------------------------------------------------------

def test_nested_scopes_nest(tmp_path):
    path = str(tmp_path / "nested.json")
    profiler.set_config(filename=path)
    profiler.start()
    with profiler.Scope("outer"):
        nd.ones((2, 2)).wait_to_read()
        with profiler.record_function("inner"):
            (nd.ones((2, 2)) * 3).wait_to_read()
    profiler.stop()
    doc = json.load(open(profiler.dump()))
    by_name = {e["name"]: e for e in doc["traceEvents"]
               if e.get("ph") == "X"}
    outer, inner = by_name["outer"], by_name["inner"]
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"]["depth"] == 0
    assert inner["args"]["depth"] == 1


# -------------------------------------------------------------------------
# Disabled mode: bit-identical results, <5% overhead
# -------------------------------------------------------------------------

def test_disabled_mode_bit_identical():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))

    def work(v):
        return (((v * 1.5) + 2.0).sum() * 0.25).asnumpy()

    ref = work(x)
    profiler.start()              # enable...
    profiler.stop()               # ...and disable again
    out = work(x)
    assert ref.tobytes() == out.tobytes()


def test_disabled_mode_overhead_under_5_percent():
    """1k-op microloop: the disabled-profiler build (hooks compiled in,
    predicate False) must be within 5% of the same loop before the
    profiler was ever touched. min-of-N damps scheduler noise."""
    a = nd.ones((4,))

    def loop():
        v = a
        for _ in range(1000):
            v = v + 1.0
        v.wait_to_read()

    def best(n=3):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            loop()
            ts.append(time.perf_counter() - t0)
        return min(ts)

    loop()                        # warm caches
    baseline = best()
    profiler.start()
    profiler.stop()               # leave hooks armed-then-disarmed
    disabled = best()
    if disabled > baseline * 1.05 + 0.010:
        # re-measure-once (the test_overhead_bounded deflake, PR 5): on
        # this 1-core box a single scheduler burp during the sub-100ms
        # microloop dwarfs the effect under test when the full suite
        # runs alongside — a REAL predicate regression reproduces on
        # the immediate re-measure, noise doesn't. Only `disabled` is
        # re-measured: the pristine PRE-ARM baseline is the very thing
        # the comparison exists to preserve (re-measuring both sides
        # in the armed-then-disarmed state would erase the difference
        # under test)
        disabled = best()
    # 5% relative, with a 10ms absolute floor against timer jitter
    assert disabled <= baseline * 1.05 + 0.010, (
        f"disabled-profiler overhead too high: {disabled:.4f}s vs "
        f"baseline {baseline:.4f}s")


def test_off_path_is_single_predicate():
    """The documented zero-overhead contract: profiling off means the
    ndarray funnel hook is literally None and the layer predicate False."""
    from incubator_mxnet_tpu import ndarray as nd_mod
    profiler.stop()
    assert nd_mod._op_hook is None
    assert profiler._ACTIVE is False
    profiler.start()
    assert nd_mod._op_hook is not None
    assert profiler._ACTIVE is True
    profiler.pause()
    assert nd_mod._op_hook is None and profiler._ACTIVE is False
    profiler.resume()
    assert nd_mod._op_hook is not None and profiler._ACTIVE is True
    profiler.stop()
    assert nd_mod._op_hook is None


# -------------------------------------------------------------------------
# Acceptance: 2 gluon train steps cover >= 4 distinct layers
# -------------------------------------------------------------------------

def test_train_loop_covers_four_layers(tmp_path):
    path = str(tmp_path / "train.json")
    net = gluon.nn.Dense(4)
    net.initialize()
    net.hybridize()
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="device")
    kv = trainer._kvstore
    x = nd.ones((2, 3))
    y = nd.zeros((2, 4))

    profiler.set_config(profile_all=True, filename=path)
    profiler.start()
    for _ in range(2):
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(2)
        kv.pushpull("loss_sync", loss, out=loss)   # metric allreduce
    profiler.stop()
    doc = json.load(open(profiler.dump()))

    cats = {e.get("cat") for e in doc["traceEvents"] if e.get("cat")}
    # >= 4 distinct layers: ndarray op, trainer phase, kvstore collective,
    # jit compile-cache event (+ autograd tape for good measure)
    assert {"operator", "trainer", "kvstore", "jit", "autograd"} <= cats
    names = {e["name"] for e in doc["traceEvents"]}
    assert "trainer.allreduce_grads" in names
    assert "trainer.optimizer_update" in names
    assert "kvstore.pushpull" in names
    assert any(n.startswith("jit.compile:") for n in names)
    # compile-cache counters: step 1 missed, step 2 hit
    ctr = profiler.counters()
    assert ctr["gluon/jit.cache_miss"] == 1
    assert ctr["gluon/jit.cache_hit"] == 1
    assert ctr["mxtpu/trainer.steps"] == 2
    assert _load_trace_check().check_trace(path) == []


# -------------------------------------------------------------------------
# engine.bulk scope (satellite)
# -------------------------------------------------------------------------

def test_engine_bulk_records_scope_when_profiling():
    profiler.start()
    with engine.bulk(8) as b:
        assert b.size == 8
        nd.ones((2,)).wait_to_read()
    profiler.stop()
    stats = profiler.aggregate_stats()
    assert stats["bulk(8)"]["count"] == 1


def test_engine_bulk_noop_when_off():
    with engine.bulk(4) as b:
        assert b.size == 4
        assert b._scope is None
    assert profiler.aggregate_stats() == {}
    # exceptions propagate (exit returns False)
    with pytest.raises(ValueError):
        with engine.bulk():
            raise ValueError("boom")


def test_engine_push_wait_all_scopes():
    profiler.start()
    hit = []
    engine.push(lambda: hit.append(1))
    engine.wait_all()
    profiler.stop()
    stats = profiler.aggregate_stats()
    assert hit == [1]
    assert stats["engine.push"]["count"] == 1
    assert stats["engine.wait_all"]["count"] == 1


# -------------------------------------------------------------------------
# Counters registry
# -------------------------------------------------------------------------

def test_counters_registry_and_trace_counter_events(tmp_path):
    c = profiler.counter("requests", domain="serving")
    c.increment()
    c.increment(2)
    c.decrement()
    assert profiler.counters()["serving/requests"] == 2
    profiler.set_gauge("step_ms", 12.5, domain="bench")
    assert profiler.counters()["bench/step_ms"] == 12.5
    # same name returns the same counter (registry, not a new object)
    assert profiler.counter("requests", domain="serving") is c
    path = str(tmp_path / "ctr.json")
    profiler.dump(filename=path)
    doc = json.load(open(path))
    c_events = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    assert {"serving/requests", "bench/step_ms"} <= {e["name"]
                                                     for e in c_events}
    assert _load_trace_check().check_trace(path) == []


# -------------------------------------------------------------------------
# Monitor through the counters registry (satellite)
# -------------------------------------------------------------------------

class _FakeExec:
    """Executor double with dicts but NO outputs attribute."""

    def __init__(self):
        self.arg_dict = {"w": nd.ones((2, 2))}
        self.aux_dict = {}
        self.grad_dict = {"w": nd.full((2, 2), 3.0)}


def test_monitor_tolerates_executor_without_outputs():
    mon = mx.Monitor(1, pattern=".*")
    mon.install(_FakeExec())
    mon.tic()
    rows = mon.toc()                      # must not raise
    tags = {r[1] for r in rows}
    assert tags == {"w", "w_grad"}


def test_monitor_non_numeric_stat_func_still_works():
    """Custom stat funcs may return strings (formatted for toc_print);
    those stay rows-only and must not crash gauge publishing."""
    mon = mx.Monitor(1, stat_func=lambda x: f"{x.mean():.2f}")
    mon.install(_FakeExec())
    mon.tic()
    rows = mon.toc()                      # must not raise
    assert {r[1] for r in rows} == {"w", "w_grad"}
    assert "monitor/w" not in profiler.counters()


def test_monitor_stats_flow_through_counters():
    mon = mx.Monitor(1, stat_func=lambda x: float(np.abs(x).mean()))
    mon.install(_FakeExec())
    mon.tic()
    mon.toc()
    ctr = profiler.counters()
    assert ctr["monitor/w"] == 1.0
    assert ctr["monitor/w_grad"] == 3.0


# -------------------------------------------------------------------------
# trace_check validator (satellite: CI/tooling)
# -------------------------------------------------------------------------

def test_trace_check_accepts_valid_and_rejects_malformed(tmp_path):
    tc = _load_trace_check()
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"traceEvents": [
        {"name": "op", "ph": "X", "ts": 0, "dur": 5, "pid": 0, "tid": 0},
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": "mxtpu"}},
    ]}))
    assert tc.check_trace(str(good)) == []

    bad_json = tmp_path / "bad.json"
    bad_json.write_text("{not json")
    assert tc.check_trace(str(bad_json))

    missing_ph = tmp_path / "noph.json"
    missing_ph.write_text(json.dumps([{"name": "op", "ts": 0}]))
    assert any("ph" in e for e in tc.check_trace(str(missing_ph)))

    bad_dur = tmp_path / "dur.json"
    bad_dur.write_text(json.dumps(
        [{"name": "op", "ph": "X", "ts": 1, "dur": "oops"}]))
    assert any("dur" in e for e in tc.check_trace(str(bad_dur)))

    not_list = tmp_path / "scalar.json"
    not_list.write_text("42")
    assert tc.check_trace(str(not_list))

    # CLI contract: nonzero exit on malformed input
    assert tc.main([str(bad_dur)]) == 1
    assert tc.main([str(good)]) == 0


# -------------------------------------------------------------------------
# Smoke (tier-1 fast path): one start/op/stop/dump round-trip
# -------------------------------------------------------------------------

def test_profiler_smoke(tmp_path):
    path = str(tmp_path / "smoke.json")
    profiler.set_config(filename=path)
    profiler.start()
    (nd.ones((2,)) + 1).wait_to_read()
    profiler.stop()
    profiler.dump()
    assert json.load(open(path))["traceEvents"]
