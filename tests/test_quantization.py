"""Int8 inference quantization (parity: src/operator/quantization/*,
python/mxnet/contrib/quantization.py): quantize/dequantize ops, int8
Dense/Conv2D, naive min-max calibration, quantize_net on LeNet within 1%
top-1 agreement of fp32."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.contrib import quantization as q


def test_quantize_dequantize_roundtrip_int8():
    x = nd.array(np.linspace(-3, 5, 64).astype(np.float32))
    qd, mn, mx_ = mx.nd.contrib.quantize(x, -3.0, 5.0, out_type="int8")
    assert qd.asnumpy().dtype == np.int8
    back = mx.nd.contrib.dequantize(qd, mn, mx_).asnumpy()
    # symmetric int8: worst-case error is half a step of |5|/127
    np.testing.assert_allclose(back, x.asnumpy(), atol=5.0 / 127)


def test_quantize_v2_auto_range_and_uint8():
    x = nd.array(np.random.RandomState(0).randn(32).astype(np.float32))
    qd, mn, mx_ = mx.nd.contrib.quantize_v2(x)
    back = mx.nd.contrib.dequantize(qd, mn, mx_).asnumpy()
    amax = float(np.abs(x.asnumpy()).max())
    np.testing.assert_allclose(back, x.asnumpy(), atol=amax / 127 + 1e-6)

    xu = nd.array(np.random.RandomState(1).rand(32).astype(np.float32))
    qu, mn2, mx2 = mx.nd.contrib.quantize_v2(xu, out_type="uint8")
    assert qu.asnumpy().dtype == np.uint8
    backu = mx.nd.contrib.dequantize(qu, mn2, mx2).asnumpy()
    np.testing.assert_allclose(backu, xu.asnumpy(), atol=1.0 / 255 + 1e-6)

    with pytest.raises(ValueError):
        mx.nd.contrib.quantize(x, -1.0, 1.0, out_type="int4")


def test_quantized_dense_matches_fp32():
    rng = np.random.RandomState(0)
    dense = gluon.nn.Dense(16, in_units=32, activation="relu")
    dense.initialize(init=mx.init.Xavier())
    x = nd.array(rng.randn(8, 32).astype(np.float32))
    ref = dense(x).asnumpy()
    qd = q.QuantizedDense(dense)
    out = qd(x).asnumpy()
    scale = max(np.abs(ref).max(), 1.0)
    assert np.abs(out - ref).max() / scale < 0.05


def test_quantized_conv2d_matches_fp32():
    rng = np.random.RandomState(1)
    conv = gluon.nn.Conv2D(8, 3, padding=1, in_channels=4)
    conv.initialize(init=mx.init.Xavier())
    x = nd.array(rng.randn(2, 4, 8, 8).astype(np.float32))
    ref = conv(x).asnumpy()
    out = q.QuantizedConv2D(conv)(x).asnumpy()
    scale = max(np.abs(ref).max(), 1.0)
    assert np.abs(out - ref).max() / scale < 0.05


def _lenet():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(6, 5, in_channels=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(16, 5, in_channels=6, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(120, activation="relu"),
            gluon.nn.Dense(84, activation="relu"),
            gluon.nn.Dense(10))
    return net


def test_quantize_net_lenet_top1_within_1pct():
    """The verdict's acceptance bar: quantized LeNet inference agrees with
    fp32 top-1 on >=99% of samples (synthetic MNIST-shaped data), with a
    naive-calibrated net. The net is briefly trained first so logits are
    separated the way a deployed model's are (an untrained net's near-tie
    argmax is noise, not a quantization property)."""
    mx.random.seed(0)
    np.random.seed(0)
    net = _lenet()
    net.initialize(init=mx.init.Xavier())
    rng = np.random.RandomState(2)
    data = rng.rand(256, 1, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, 256)
    net(nd.array(data[:1]))                      # complete deferred init
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    for i in range(0, 256, 64):
        with mx.autograd.record():
            loss = L(net(nd.array(data[i:i + 64])),
                     nd.array(labels[i:i + 64]))
        loss.backward()
        trainer.step(64)
    fp32_pred = net(nd.array(data)).asnumpy().argmax(1)

    calib = [nd.array(data[i:i + 64]) for i in range(0, 128, 64)]
    qnet = q.quantize_net(net, calib_data=calib)
    # every Dense/Conv2D replaced
    kinds = [type(c).__name__ for c in qnet._children.values()]
    assert "Conv2D" not in kinds and "Dense" not in kinds
    assert any(k == "QuantizedConv2D" for k in kinds)
    # calibration baked static scales
    for c in qnet._children.values():
        if isinstance(c, (q.QuantizedDense, q.QuantizedConv2D)):
            assert c.calib_max is not None and c.calib_max > 0
    int8_pred = qnet(nd.array(data)).asnumpy().argmax(1)
    agreement = (int8_pred == fp32_pred).mean()
    assert agreement >= 0.99, f"top-1 agreement {agreement:.3f} < 0.99"


def test_quantize_net_dynamic_mode_and_exclude():
    net = _lenet()
    net.initialize(init=mx.init.Xavier())
    x = nd.array(np.random.RandomState(3).rand(4, 1, 28, 28)
                 .astype(np.float32))
    net(x)
    last_dense = list(net._children.values())[-1]
    qnet = q.quantize_net(net, exclude=(last_dense,))
    kinds = [type(c).__name__ for c in qnet._children.values()]
    assert kinds[-1] == "Dense"                  # excluded stays fp32
    out = qnet(x).asnumpy()
    assert out.shape == (4, 10) and np.isfinite(out).all()
    # dynamic mode: no calibration baked
    qd = [c for c in qnet._children.values()
          if isinstance(c, (q.QuantizedDense, q.QuantizedConv2D))]
    assert qd and all(c.calib_max is None for c in qd)


def test_quantize_net_no_targets_raises():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Activation("relu"))
    with pytest.raises(ValueError):
        q.quantize_net(net)


def test_quantize_constant_tensor_no_nan():
    z = nd.zeros((8,))
    qd, mn, mx_ = mx.nd.contrib.quantize_v2(z)
    np.testing.assert_array_equal(qd.asnumpy(), 0)
    back = mx.nd.contrib.dequantize(qd, mn, mx_).asnumpy()
    np.testing.assert_array_equal(back, 0.0)
    qu, mn2, mx2 = mx.nd.contrib.quantize_v2(nd.ones((8,)) * 3,
                                             out_type="uint8")
    assert np.isfinite(
        mx.nd.contrib.dequantize(qu, mn2, mx2).asnumpy()).all()


def test_quantize_net_hybridized():
    """Hybridized nets: stale fp32 traces are dropped, calibration runs
    eagerly, and the quantized net retraces onto the int8 graph."""
    net = _lenet()
    net.initialize(init=mx.init.Xavier())
    x = nd.array(np.random.RandomState(4).rand(4, 1, 28, 28)
                 .astype(np.float32))
    net.hybridize()
    ref_fp32 = net(x).asnumpy()               # builds the fp32 cache
    qnet = q.quantize_net(net, calib_data=[x])
    out = qnet(x).asnumpy()
    assert out.shape == ref_fp32.shape and np.isfinite(out).all()
    # the cache really was dropped: int8 output differs from fp32 trace
    assert not np.array_equal(out, ref_fp32)
    scale = max(np.abs(ref_fp32).max(), 1.0)
    assert np.abs(out - ref_fp32).max() / scale < 0.2


def test_quantize_net_deferred_init_raises_clearly():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(10))               # no in_units, never run
    net.initialize()
    with pytest.raises(ValueError, match="deferred"):
        q.quantize_net(net)


def test_quantize_net_idempotent_reentry():
    net = _lenet()
    net.initialize(init=mx.init.Xavier())
    x = nd.array(np.random.RandomState(5).rand(2, 1, 28, 28)
                 .astype(np.float32))
    net(x)
    q.quantize_net(net)
    with pytest.raises(ValueError, match="no quantizable"):
        q.quantize_net(net)                   # all layers already int8


def test_uncalibrated_layer_falls_back_to_dynamic(caplog):
    """A layer the calib batches never reach keeps dynamic ranges (with a
    warning) instead of baking a garbage scale."""
    import logging
    net = _lenet()
    net.initialize(init=mx.init.Xavier())
    x = nd.array(np.random.RandomState(6).rand(2, 1, 28, 28)
                 .astype(np.float32))
    net(x)
    # "calibrate" with an empty batch list: no layer sees data
    with caplog.at_level(logging.WARNING):
        qnet = q.quantize_net(net, calib_data=[])
    qd = [c for c in qnet._children.values()
          if isinstance(c, (q.QuantizedDense, q.QuantizedConv2D))]
    assert all(c.calib_max is None for c in qd)
    assert any("no calibration data" in r.message for r in caplog.records)
    out = qnet(x).asnumpy()
    assert np.isfinite(out).all() and np.abs(out).max() > 0


def test_quantize_model_rejects_reference_arg_params():
    net = _lenet()
    net.initialize(init=mx.init.Xavier())
    with pytest.raises(TypeError, match="MIGRATION"):
        q.quantize_model(net, {"conv0_weight": None})


def test_entropy_threshold_clips_outliers():
    """KL-optimal threshold lands well below a lone outlier but above the
    bulk of the distribution."""
    rng = np.random.RandomState(0)
    samples = np.concatenate([rng.randn(20000) * 0.5, [50.0]])
    t = q._entropy_threshold(np.abs(samples))
    assert 1.0 < t < 10.0, t        # bulk |x| <~ 2.5; outlier at 50


def test_quantize_net_entropy_calibration():
    mx.random.seed(0)
    np.random.seed(0)
    net = _lenet()
    net.initialize(init=mx.init.Xavier())
    rng = np.random.RandomState(7)
    data = rng.rand(256, 1, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, 256)
    net(nd.array(data[:1]))
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3})
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(2):
        for i in range(0, 256, 64):
            with mx.autograd.record():
                loss = L(net(nd.array(data[i:i + 64])),
                         nd.array(labels[i:i + 64]))
            loss.backward()
            tr.step(64)
    fp32_pred = net(nd.array(data)).asnumpy().argmax(1)
    qnet = q.quantize_net(net, calib_data=[nd.array(data[:128])],
                          calib_mode="entropy")
    for c in qnet._children.values():
        if isinstance(c, (q.QuantizedDense, q.QuantizedConv2D)):
            assert c.calib_max is not None and c.calib_max > 0
    int8_pred = qnet(nd.array(data)).asnumpy().argmax(1)
    # entropy mode trades outlier fidelity for in-range resolution — its
    # win case is outlier-heavy activations; on a toy net with smooth
    # activations it clips real tail mass, so the bar is looser than
    # naive's 0.99 (same trade the reference documents)
    assert (int8_pred == fp32_pred).mean() >= 0.90
    with pytest.raises(ValueError, match="calib_mode"):
        q.quantize_net(_lenet(), calib_mode="kl2")


def test_quantize_transformer_lm_generation_agrees():
    """int8 quantization generalizes beyond CNNs: a trained-ish causal LM
    with every Dense (QKV/proj/FFN) quantized must keep greedy generation
    consistent with fp32 on a strongly-peaked distribution."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd
    from incubator_mxnet_tpu.contrib.quantization import (quantize_net,
                                                          QuantizedDense)
    from incubator_mxnet_tpu.models import TransformerLM, lm_loss

    vocab, period = 10, 4
    mx.random.seed(0)
    np.random.seed(0)
    m = TransformerLM(vocab, num_layers=2, units=64, hidden_size=128,
                      num_heads=4, max_length=24)
    m.initialize(init=mx.init.Xavier())
    tr = gluon.Trainer(m.collect_params(), "adam", {"learning_rate": 3e-3})
    seq = np.tile(np.arange(period), 6)[None, :20].astype(np.float32)
    x = nd.array(np.repeat(seq, 4, axis=0))
    for _ in range(120):
        with mx.autograd.record():
            loss = lm_loss(m(x), x)
        loss.backward()
        tr.step(4)

    ref = m.generate(seq[:, :5], 6).asnumpy()
    quantize_net(m, calib_data=[x], calib_mode="naive")
    assert any(isinstance(c, QuantizedDense)
               for c in m.layers[0].attention._children.values())
    got = m.generate(seq[:, :5], 6).asnumpy()
    np.testing.assert_array_equal(got, ref)
