"""mx.contrib.text: vocabulary + embeddings (reference
tests/python/unittest/test_contrib_text.py)."""
import collections

import numpy as np
import pytest

from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.contrib import text
from incubator_mxnet_tpu.contrib.text import embedding as emb


def test_count_tokens_from_str():
    c = text.count_tokens_from_str("Life is great!\nlife is good.\n")
    assert c["is"] == 2 and c["Life"] == 1 and c["life"] == 1
    c2 = text.count_tokens_from_str("Life is great!\nlife is good.\n",
                                    to_lower=True)
    assert c2["life"] == 2
    base = collections.Counter({"is": 10})
    c3 = text.count_tokens_from_str("is it", counter_to_update=base)
    assert c3["is"] == 11 and c3["it"] == 1


def test_vocabulary_ordering_and_limits():
    counter = collections.Counter(
        {"c": 5, "b": 5, "a": 3, "rare": 1, "x": 2})
    v = text.Vocabulary(counter, most_freq_count=3, min_freq=2,
                        reserved_tokens=["<pad>"])
    # 0=<unk>, 1=<pad>, then by (-freq, token): b, c, a
    assert v.idx_to_token == ["<unk>", "<pad>", "b", "c", "a"]
    assert len(v) == 5
    assert v.to_indices("b") == 2
    assert v.to_indices(["zzz", "a"]) == [0, 4]
    assert v.to_tokens([0, 3]) == ["<unk>", "c"]
    with pytest.raises(ValueError):
        v.to_tokens(99)
    assert v.unknown_token == "<unk>" and v.reserved_tokens == ["<pad>"]


def test_vocabulary_validation():
    with pytest.raises(ValueError):
        text.Vocabulary(min_freq=0)
    with pytest.raises(ValueError):
        text.Vocabulary(reserved_tokens=["<unk>"])
    with pytest.raises(ValueError):
        text.Vocabulary(reserved_tokens=["<pad>", "<pad>"])


def _vec_file(tmp_path, name="vecs.txt", header=False):
    lines = []
    if header:
        lines.append("3 4")
    lines += ["hello 1 2 3 4",
              "world 5 6 7 8",
              "tpu 9 10 11 12"]
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_custom_embedding_loads_and_queries(tmp_path):
    e = emb.CustomEmbedding(_vec_file(tmp_path))
    assert len(e) == 4 and e.vec_len == 4  # + <unk> row 0
    v = e.get_vecs_by_tokens("world")
    np.testing.assert_allclose(v.asnumpy(), [5, 6, 7, 8])
    both = e.get_vecs_by_tokens(["tpu", "nope"])
    np.testing.assert_allclose(both.asnumpy()[0], [9, 10, 11, 12])
    np.testing.assert_allclose(both.asnumpy()[1], np.zeros(4))
    assert e.to_indices("hello") == 1
    assert e.to_tokens(2) == "world"
    # lower_case_backup
    v2 = e.get_vecs_by_tokens("HELLO", lower_case_backup=True)
    np.testing.assert_allclose(v2.asnumpy(), [1, 2, 3, 4])


def test_fasttext_header_line_skipped(tmp_path):
    e = emb.CustomEmbedding(_vec_file(tmp_path, header=True))
    assert len(e) == 4 and e.vec_len == 4


def test_embedding_malformed_lines_skipped(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("good 1 2 3\nshort 1\nnotfloat a b c\ngood 9 9 9\n"
                 "fine 4 5 6\n")
    e = emb.CustomEmbedding(str(p))
    # good (first), fine; duplicate + malformed skipped
    assert sorted(e.token_to_idx) == ["<unk>", "fine", "good"]
    np.testing.assert_allclose(
        e.get_vecs_by_tokens("good").asnumpy(), [1, 2, 3])


def test_update_token_vectors(tmp_path):
    e = emb.CustomEmbedding(_vec_file(tmp_path))
    e.update_token_vectors("hello", nd.array(np.full((1, 4), 7.0)))
    np.testing.assert_allclose(
        e.get_vecs_by_tokens("hello").asnumpy(), np.full(4, 7.0))
    with pytest.raises(ValueError):
        e.update_token_vectors("absent", nd.array(np.zeros((1, 4))))


def test_embedding_with_vocabulary_reindex(tmp_path):
    counter = collections.Counter({"world": 3, "unseen": 2})
    v = text.Vocabulary(counter)
    e = emb.CustomEmbedding(_vec_file(tmp_path), vocabulary=v)
    assert e.idx_to_token == v.idx_to_token
    np.testing.assert_allclose(
        e.get_vecs_by_tokens("world").asnumpy(), [5, 6, 7, 8])
    # in-vocab but not in the file -> unknown vector
    np.testing.assert_allclose(
        e.get_vecs_by_tokens("unseen").asnumpy(), np.zeros(4))


def test_composite_embedding(tmp_path):
    e1 = emb.CustomEmbedding(_vec_file(tmp_path, "a.txt"))
    p = tmp_path / "b.txt"
    p.write_text("world 100 200\nhello 300 400\n")
    e2 = emb.CustomEmbedding(str(p))
    v = text.Vocabulary(collections.Counter({"hello": 2, "world": 1}))
    comp = emb.CompositeEmbedding(v, [e1, e2])
    assert comp.vec_len == 6
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("world").asnumpy(),
        [5, 6, 7, 8, 100, 200])


def test_registry_and_pretrained_errors(tmp_path):
    names = emb.get_pretrained_file_names()
    assert "glove" in names and "fasttext" in names
    assert "glove.6B.50d.txt" in emb.get_pretrained_file_names("glove")
    with pytest.raises(KeyError):
        emb.get_pretrained_file_names("word2vec")
    # no-egress: no local file -> documented OSError
    with pytest.raises(OSError, match="egress"):
        emb.create("glove", pretrained_file_name="glove.6B.50d.txt")
    # but a local file works through the registry
    e = emb.create("glove", pretrained_file_path=_vec_file(tmp_path))
    assert e.vec_len == 4
    with pytest.raises(OSError, match="not found"):
        emb.CustomEmbedding(str(tmp_path / "missing.txt"))


def test_embedding_feeds_gluon_embedding_layer(tmp_path):
    from incubator_mxnet_tpu import gluon
    e = emb.CustomEmbedding(_vec_file(tmp_path))
    layer = gluon.nn.Embedding(len(e), e.vec_len)
    layer.initialize()
    layer(nd.array(np.array([0.0])))  # materialize
    layer.weight.set_data(e.idx_to_vec)
    out = layer(nd.array(np.array([e.to_indices("tpu")], np.float32)))
    np.testing.assert_allclose(out.asnumpy()[0], [9, 10, 11, 12])


def test_malformed_first_line_does_not_poison_dim(tmp_path):
    p = tmp_path / "poison.txt"
    p.write_text("word a b c\nhello 1 2 3 4\nworld 5 6 7 8\n")
    e = emb.CustomEmbedding(str(p))
    # the bad 3-elem line must not define dim; the 4-d vectors load
    assert e.vec_len == 4 and len(e) == 3
    np.testing.assert_allclose(
        e.get_vecs_by_tokens("hello").asnumpy(), [1, 2, 3, 4])


def test_count_tokens_regex_metachar_delims():
    c = text.count_tokens_from_str("a.b c", token_delim=".")
    assert c == collections.Counter({"a": 1, "b c": 1})
    c2 = text.count_tokens_from_str("x|y|x", token_delim="|")
    assert c2["x"] == 2 and c2["y"] == 1


def test_registered_custom_embedding_listed():
    try:
        @emb.register
        class MyEmb(emb.CustomEmbedding):
            pretrained_file_names = ("my.vec",)

        names = emb.get_pretrained_file_names()
        assert names.get("myemb") == ["my.vec"]
    finally:
        emb._REG._map.pop("myemb", None)  # keep the registry test-order-safe


def test_blank_first_line_does_not_poison_dim(tmp_path):
    p = tmp_path / "blank.txt"
    p.write_text("\nhello 1 2 3 4\nworld 5 6 7 8\n")
    e = emb.CustomEmbedding(str(p))
    assert e.vec_len == 4 and len(e) == 3
    np.testing.assert_allclose(
        e.get_vecs_by_tokens("world").asnumpy(), [5, 6, 7, 8])
