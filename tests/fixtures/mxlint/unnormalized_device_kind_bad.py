"""BAD fixture: raw device-kind strings compared against literals —
jax reports 'TPU v4', the tables store 'tpu v4': a silent never-match."""


def lookup(entry, device):
    if entry["stored_device_kind"] == "tpu v4":          # raw == literal
        return True
    if device.device_kind in ("tpu v4", "tpu v5e"):      # raw in tuple
        return True
    return False
