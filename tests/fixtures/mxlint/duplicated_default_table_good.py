"""GOOD fixture: small dicts, computed dicts, and function-local
tables are not default-table duplicates."""

SMALL = {"a": 1, "b": 2}                   # below the size floor

COMPUTED = {
    "resnet50": 2 * 128,
    "bert": int("32"),
    "lenet": 512,
    "transformer": 8,
}


def scratch():
    local_table = {
        "resnet50": 256,
        "bert": 32,
        "lenet": 512,
        "transformer": 8,
    }
    return local_table
