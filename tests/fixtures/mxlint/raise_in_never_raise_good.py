"""GOOD fixture: internal raises are fine when a catching handler
guarantees the module boundary stays never-raise."""


def parse(doc):
    try:
        if not isinstance(doc, dict):
            raise ValueError("bad artifact")    # caught two lines down
        return doc["events"]
    except Exception:  # noqa: BLE001 — never-raise contract
        return []


def helper_inside_guard(doc):
    try:
        def _require(cond):
            if not cond:
                raise KeyError("missing")       # still inside the try
        _require("events" in doc)
        return doc["events"]
    except Exception:  # noqa: BLE001
        return []
