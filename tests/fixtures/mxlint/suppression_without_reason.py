"""Fixture: a reasonless directive suppresses NOTHING and is itself a
finding."""
import os

# mxlint: disable=raw-env-read
a = os.environ.get("MXTPU_NOT_WAIVED_KNOB", "1")
