"""BAD fixture: metrics emitted into governed families without a
families.py registration (or with the wrong kind)."""
from incubator_mxnet_tpu.profiler.counters import (counter, histogram,
                                                   observe, set_gauge)

counter("healthmon.not_a_real_metric", "healthmon").increment()
histogram("autotune.invented_histogram", "autotune")
observe("perfscope.mfu", 0.5, "perfscope")       # mfu is a gauge
set_gauge("resilience.rollbacks", 1, "resilience")   # a counter
