"""GOOD fixture: knob reads routed through the knobs home, allowlisted
arming knobs, non-knob env reads, and env WRITES (config, not reads)."""
import os


def resolved():
    from incubator_mxnet_tpu.autotune.knobs import env_int, env_str
    return env_int("MXTPU_SOME_KNOB", 1), env_str("BENCH_SOME_KNOB")


def non_knob():
    # not a MXTPU_*/BENCH_* name: out of the rule's jurisdiction
    return os.environ.get("JAX_PLATFORMS", "")


def write_is_config():
    os.environ["MXTPU_SOME_KNOB"] = "1"          # a write, not a read
