"""GOOD fixture: the write happens under the module lock (or is
suppressed with a reason proving single-threadedness)."""
import threading

_STATE = None
_COUNT = 0
_lock = threading.Lock()


def worker_update(value):
    global _STATE, _COUNT
    with _lock:
        _STATE = value
        _COUNT += 1


def arm(value):
    global _STATE
    # mxlint: disable=thread-shared-mutation -- written before the
    # worker thread starts
    _STATE = value


def local_only(value):
    state = value           # plain local: no global declaration
    return state
