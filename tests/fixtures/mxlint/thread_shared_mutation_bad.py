"""BAD fixture: module-global rebinding without the lock in a threaded
module (linted as if at incubator_mxnet_tpu/serving/batcher.py)."""
import threading

_STATE = None
_COUNT = 0
_lock = threading.Lock()


def worker_update(value):
    global _STATE, _COUNT
    _STATE = value          # racy rebind
    _COUNT += 1             # racy read-modify-write
