"""BAD fixture (pair half A): the canonical home of a default table."""

DEFAULT_BATCH = {
    "resnet50": 256,
    "bert": 32,
    "lenet": 512,
    "transformer": 8,
}
