"""GOOD fixture: registered metrics with the right kinds, ungoverned
domains, and dynamic names (the runtime validator's job, not ast's)."""
from incubator_mxnet_tpu.profiler.counters import (counter, histogram,
                                                   observe, set_gauge)

counter("healthmon.nan_alerts", "healthmon").increment()
set_gauge("perfscope.mfu", 0.5, "perfscope")
histogram("servescope.e2e_ms", "servescope")
observe("resilience.save_ms", 12.5, "resilience")
counter("my.private.metric", "bulk")                 # ungoverned domain


def dynamic(verdict):
    counter(f"perfscope.{verdict}", "perfscope").increment()
