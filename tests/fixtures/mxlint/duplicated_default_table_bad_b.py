"""BAD fixture (pair half B): a structurally equal copy in a second
module — the PR 13 perf_sweep/bench drift, re-enacted."""

MY_BATCH_TABLE = {
    "lenet": 512,
    "bert": 32,
    "transformer": 8,
    "resnet50": 256,
}
