"""GOOD fixture: device-kind comparisons through the canonical
normalizer (or an explicit lowering pipeline)."""
from incubator_mxnet_tpu.autotune.cache import normalize_device_kind


def lookup(entry, device):
    if normalize_device_kind(entry["device_kind"]) == "tpu v4":
        return True
    if device.device_kind.lower() in ("tpu v4", "tpu v5e"):
        return True
    # comparing two raw kinds against each other is symmetric-safe
    return entry["device_kind"] == entry["other_device_kind"]
