"""Fixture: a reasoned suppression silences the finding."""
import os

# mxlint: disable=raw-env-read -- fixture proving the waiver grammar
a = os.environ.get("MXTPU_WAIVED_KNOB", "1")

b = os.environ.get("MXTPU_SAME_LINE", "1")  # mxlint: disable=raw-env-read -- same-line form
