"""BAD fixture: every raw-read spelling of a MXTPU_*/BENCH_* knob the
rule must catch (linted as if at incubator_mxnet_tpu/somemod.py)."""
import os
from os import getenv

a = os.environ.get("MXTPU_SOME_KNOB", "1")          # .get
b = os.getenv("BENCH_SOME_KNOB")                    # os.getenv
c = getenv("MXTPU_OTHER_KNOB")                      # bare getenv
d = os.environ["MXTPU_SUBSCRIPT_KNOB"]              # subscript read
e = "MXTPU_MEMBERSHIP_KNOB" in os.environ           # membership read


def helper(name):
    # dynamic-name wrapper: the drift vector the rule exists for
    return os.environ.get(name, "")
