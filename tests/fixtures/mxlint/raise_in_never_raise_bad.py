"""BAD fixture: raises leaking out of a module documented never-raise
(linted as if at incubator_mxnet_tpu/devicescope/ingest.py)."""


def parse(doc):
    if not isinstance(doc, dict):
        raise ValueError("bad artifact")        # leaks to the caller
    return doc


def rethrower(doc):
    try:
        return doc["events"]
    except KeyError:
        raise RuntimeError("torn file")         # handler re-raises out
