"""Sequence ops, spatial transformer family, Correlation, scatter_nd /
batch_take / reverse (parity: src/operator/sequence_*.cc,
grid_generator.cc, bilinear_sampler.cc, spatial_transformer.cc,
correlation.cc, tensor/indexing_op.cc)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def test_sequence_mask():
    data = np.arange(2 * 3 * 2, dtype=np.float32).reshape(2, 3, 2)  # (T,N,D)
    out = mx.nd.SequenceMask(nd.array(data), nd.array(np.array([1, 2, 0])),
                             use_sequence_length=True, value=-1.0).asnumpy()
    assert (out[0, 0] == data[0, 0]).all()       # t=0 < len=1
    assert (out[1, 0] == -1.0).all()             # t=1 >= len=1
    assert (out[1, 1] == data[1, 1]).all()       # t=1 < len=2
    assert (out[0, 2] == -1.0).all()             # len=0: all masked


def test_sequence_last():
    data = np.arange(3 * 2 * 2, dtype=np.float32).reshape(3, 2, 2)
    out = mx.nd.SequenceLast(nd.array(data), nd.array(np.array([2, 3])),
                             use_sequence_length=True).asnumpy()
    np.testing.assert_array_equal(out[0], data[1, 0])   # len 2 -> t=1
    np.testing.assert_array_equal(out[1], data[2, 1])   # len 3 -> t=2
    full = mx.nd.SequenceLast(nd.array(data)).asnumpy()
    np.testing.assert_array_equal(full, data[-1])


def test_sequence_reverse():
    data = np.arange(4 * 2, dtype=np.float32).reshape(4, 2, 1)
    out = mx.nd.SequenceReverse(nd.array(data), nd.array(np.array([3, 4])),
                                use_sequence_length=True).asnumpy()
    # seq 0 (len 3): steps 0..2 reversed, step 3 untouched
    np.testing.assert_array_equal(out[:, 0, 0], [4, 2, 0, 6])
    # seq 1 (len 4): fully reversed
    np.testing.assert_array_equal(out[:, 1, 0], [7, 5, 3, 1])


def test_grid_generator_identity_affine():
    theta = nd.array(np.array([[1.0, 0, 0, 0, 1.0, 0]], np.float32))
    grid = mx.nd.GridGenerator(theta, "affine", target_shape=(3, 5)).asnumpy()
    assert grid.shape == (1, 2, 3, 5)
    np.testing.assert_allclose(grid[0, 0, 0], np.linspace(-1, 1, 5),
                               atol=1e-6)
    np.testing.assert_allclose(grid[0, 1, :, 0], np.linspace(-1, 1, 3),
                               atol=1e-6)


def test_bilinear_sampler_identity():
    x = np.random.RandomState(0).randn(2, 3, 4, 6).astype(np.float32)
    theta = nd.array(np.tile(np.array([[1.0, 0, 0, 0, 1.0, 0]], np.float32),
                             (2, 1)))
    grid = mx.nd.GridGenerator(theta, "affine", target_shape=(4, 6))
    out = mx.nd.BilinearSampler(nd.array(x), grid).asnumpy()
    np.testing.assert_allclose(out, x, atol=1e-5)


def test_spatial_transformer_shift():
    """Translation by a full normalized unit in x shifts the image."""
    x = np.zeros((1, 1, 3, 3), np.float32)
    x[0, 0, 1, 1] = 1.0
    # affine with tx shifting sample positions right by one pixel
    theta = nd.array(np.array([[1.0, 0, 1.0, 0, 1.0, 0]], np.float32))
    out = mx.nd.SpatialTransformer(nd.array(x), theta,
                                   target_shape=(3, 3)).asnumpy()
    # sampling coords shifted +1 in x -> output shifts content left
    assert out[0, 0, 1, 0] == 1.0
    assert out[0, 0, 1, 1] == 0.0


def test_spatial_transformer_grad_flows():
    x = nd.array(np.random.RandomState(1).randn(1, 2, 4, 4)
                 .astype(np.float32))
    theta = nd.array(np.array([[1.0, 0, 0.1, 0, 1.0, -0.1]], np.float32))
    theta.attach_grad()
    with mx.autograd.record():
        y = mx.nd.SpatialTransformer(x, theta, target_shape=(4, 4))
        loss = (y * y).sum()
    loss.backward()
    g = theta._grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_correlation_self_peak():
    """Correlation of a map with itself peaks at zero displacement; output
    is cropped by border = max_displacement (reference shape semantics)."""
    x = np.random.RandomState(2).randn(1, 4, 6, 6).astype(np.float32)
    out = mx.nd.Correlation(nd.array(x), nd.array(x),
                            max_displacement=1).asnumpy()
    assert out.shape == (1, 9, 4, 4)
    center = out[0, 4]          # (dy,dx)=(0,0) of the 3x3 window
    for k in range(9):
        if k == 4:
            continue
        assert center.mean() >= out[0, k].mean()


def test_correlation_kernel_and_pad():
    """kernel_size patch-sums (normalized by k*k*C) and pad_size restores
    output size: with k=3, d=1, pad=2 on a 6x6 map, border=2 and the
    output is 6x6 again; constant inputs give exactly 1.0 everywhere in
    the interior (partial patches at the crop edge see padding zeros)."""
    x = np.ones((1, 2, 6, 6), np.float32)
    out = mx.nd.Correlation(nd.array(x), nd.array(x), kernel_size=3,
                            max_displacement=1, pad_size=2).asnumpy()
    assert out.shape == (1, 9, 6, 6)
    np.testing.assert_allclose(out[0, 4, 2:-2, 2:-2], 1.0, atol=1e-6)


def test_scatter_nd_roundtrip():
    # reference layout: indices (M, N) — one COLUMN per point
    idx = np.array([[0, 2], [2, 0]])            # points (0,2) and (2,0)
    vals = np.array([5.0, 7.0], np.float32)
    out = mx.nd.scatter_nd(nd.array(vals), nd.array(idx),
                           shape=(3, 4)).asnumpy()
    expected = np.zeros((3, 4), np.float32)
    expected[0, 2] = 5.0
    expected[2, 0] = 7.0
    np.testing.assert_array_equal(out, expected)
    back = mx.nd.gather_nd(nd.array(out), nd.array(idx)).asnumpy()
    np.testing.assert_array_equal(back, vals)


def test_batch_take_and_reverse():
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = mx.nd.batch_take(nd.array(a), nd.array(np.array([1, 3, 0])))
    np.testing.assert_array_equal(out.asnumpy(), [1.0, 7.0, 8.0])
    rev = mx.nd.reverse(nd.array(a), axis=1).asnumpy()
    np.testing.assert_array_equal(rev, a[:, ::-1])


def test_small_op_gap_fills():
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    gx, gy = mx.nd.meshgrid(nd.array([1.0, 2.0]), nd.array([3.0, 4.0, 5.0]))
    assert gx.shape == (3, 2) and gy.shape == (3, 2)
    np.testing.assert_array_equal(mx.nd.shape_array(nd.array(a)).asnumpy(),
                                  [2, 3])
    assert int(mx.nd.size_array(nd.array(a)).asnumpy()[0]) == 6
    np.testing.assert_allclose(mx.nd.gamma(nd.array(np.array([4.0]))).asnumpy(),
                               [6.0], rtol=1e-5)
    hs = mx.nd.hard_sigmoid(nd.array(np.array([-10.0, 0.0, 10.0])))
    np.testing.assert_allclose(hs.asnumpy(), [0.0, 0.5, 1.0])
    nn = mx.nd.nan_to_num(nd.array(np.array([np.nan, 1.0])))
    np.testing.assert_array_equal(nn.asnumpy(), [0.0, 1.0])


def test_depth_space_roundtrip():
    x = np.random.RandomState(0).randn(2, 8, 3, 4).astype(np.float32)
    d = mx.nd.depth_to_space(nd.array(x), 2)
    assert d.shape == (2, 2, 6, 8)
    back = mx.nd.space_to_depth(d, 2)
    np.testing.assert_allclose(back.asnumpy(), x)


def test_ravel_unravel_roundtrip():
    pts = np.array([[0, 1, 2], [2, 0, 3]])    # (M=2, N=3) in shape (3, 4)
    flat = mx.nd.ravel_multi_index(nd.array(pts), shape=(3, 4))
    np.testing.assert_array_equal(flat.asnumpy(), [2, 4, 11])
    back = mx.nd.unravel_index(flat, shape=(3, 4))
    np.testing.assert_array_equal(back.asnumpy(), pts)


def test_degrees_radians_nanprod_argmax_channel():
    x = nd.array(np.array([np.pi, np.pi / 2], np.float32))
    np.testing.assert_allclose(mx.nd.degrees(x).asnumpy(), [180.0, 90.0],
                               rtol=1e-6)
    np.testing.assert_allclose(
        mx.nd.radians(mx.nd.degrees(x)).asnumpy(), x.asnumpy(), rtol=1e-6)
    y = nd.array(np.array([[2.0, np.nan], [3.0, 4.0]], np.float32))
    np.testing.assert_allclose(mx.nd.nanprod(y).asnumpy(), 24.0)
    np.testing.assert_allclose(mx.nd.nanprod(y, axis=1).asnumpy(), [2.0, 12.0])
    z = nd.array(np.array([[[1.0, 9.0], [5.0, 2.0]]], np.float32))  # (1,2,2)
    np.testing.assert_array_equal(mx.nd.argmax_channel(z).asnumpy(),
                                  [[1.0, 0.0]])


def test_custom_metric_and_np_wrapper():
    def mse(label, pred):
        return float(((label - pred) ** 2).mean())

    m = mx.metric.CustomMetric(mse)
    m.update(nd.array([1.0, 2.0]), nd.array([1.5, 2.0]))
    name, val = m.get()
    assert "mse" in name and abs(val - 0.125) < 1e-6
    m2 = mx.metric.np(mse)
    m2.update(nd.array([0.0]), nd.array([2.0]))
    assert abs(m2.get()[1] - 4.0) < 1e-6
    m3 = mx.metric.create("custom", feval=mse)
    assert isinstance(m3, mx.metric.CustomMetric)


def test_reflection_pad2d():
    from incubator_mxnet_tpu import gluon
    pad = gluon.nn.ReflectionPad2D(padding=(1, 1, 2, 0))
    x = nd.array(np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3))
    out = pad(x)
    ref = np.pad(x.asnumpy(), ((0, 0), (0, 0), (2, 0), (1, 1)),
                 mode="reflect")
    np.testing.assert_array_equal(out.asnumpy(), ref)


def test_reflection_pad2d_reference_8tuple():
    """The reference's NCHW pad_width form (0,0,0,0,t,b,l,r) maps onto the
    same padding as the 4-tuple extension."""
    import pytest
    from incubator_mxnet_tpu import gluon
    pad8 = gluon.nn.ReflectionPad2D(padding=(0, 0, 0, 0, 2, 0, 1, 1))
    x = nd.array(np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3))
    ref = np.pad(x.asnumpy(), ((0, 0), (0, 0), (2, 0), (1, 1)),
                 mode="reflect")
    np.testing.assert_array_equal(pad8(x).asnumpy(), ref)
    with pytest.raises(ValueError):
        gluon.nn.ReflectionPad2D(padding=(1, 0, 0, 0, 2, 0, 1, 1))


def test_parity_sweep_round3_ops():
    """Round-3 parity batch: add_n/ElementWiseSum, reshape_like,
    multi_sum_sq, khatri_rao, digamma, sym.arange, contrib
    arange_like/fft/ifft, BatchNormReLU, engine.bulk."""
    from incubator_mxnet_tpu import gluon
    a = nd.array(np.array([[1., 2.], [3., 4.]], np.float32))
    b = nd.array(np.array([[10., 20.], [30., 40.]], np.float32))
    np.testing.assert_allclose(mx.nd.add_n(a, b).asnumpy(),
                               [[11, 22], [33, 44]])
    np.testing.assert_allclose(mx.nd.ElementWiseSum([a, b]).asnumpy(),
                               [[11, 22], [33, 44]])
    assert mx.nd.reshape_like(
        nd.array(np.arange(4, dtype=np.float32)), a).shape == (2, 2)
    ss = mx.nd.multi_sum_sq(a, b, num_arrays=2)
    assert ss.shape == (2,)                     # one 1-D NDArray, like ref
    np.testing.assert_allclose(ss.asnumpy(), [30.0, 3000.0])
    kr = mx.nd.khatri_rao(
        nd.array(np.array([[1., 2.], [3., 4.]], np.float32)),
        nd.array(np.array([[1., 1.], [2., 2.]], np.float32)))
    assert kr.shape == (4, 2)
    np.testing.assert_allclose(kr.asnumpy()[:, 0], [1, 2, 3, 6])
    np.testing.assert_allclose(
        mx.nd.digamma(nd.array(np.array([1.0], np.float32))).asnumpy(),
        [-0.5772157], rtol=1e-5)

    np.testing.assert_allclose(
        mx.sym.arange(5).bind(args={}, grad_req="null")
        .forward()[0].asnumpy(), [0, 1, 2, 3, 4])
    np.testing.assert_allclose(
        mx.sym.arange(2, 6, step=2).bind(args={}, grad_req="null")
        .forward()[0].asnumpy(), [2, 4])
    np.testing.assert_allclose(
        mx.nd.contrib.arange_like(a, start=1.0).asnumpy(),
        [[1, 2], [3, 4]])
    np.testing.assert_allclose(
        mx.nd.contrib.arange_like(a, step=0.1).asnumpy(),
        [[0, 0.1], [0.2, 0.3]], atol=1e-6)      # exact length w/ float step
    np.testing.assert_allclose(
        mx.nd.contrib.arange_like(a, repeat=2).asnumpy(),
        [[0, 0], [1, 1]])                       # repeat keeps data's shape
    np.testing.assert_allclose(
        mx.sym.digamma(mx.sym.Variable("x")).bind(
            args={"x": np.array([1.0], np.float32)},
            grad_req="null").forward()[0].asnumpy(),
        [-0.5772157], rtol=1e-5)

    x = nd.array(np.random.RandomState(0).randn(2, 8).astype(np.float32))
    fx = mx.nd.contrib.fft(x)
    assert fx.shape == (2, 16)
    # reference ifft is unnormalized: ifft(fft(x)) == d * x
    np.testing.assert_allclose(mx.nd.contrib.ifft(fx).asnumpy(),
                               8 * x.asnumpy(), rtol=1e-4, atol=1e-4)

    bnr = gluon.nn.BatchNormReLU(axis=-1, in_channels=3)
    bnr.initialize()
    y = bnr(nd.array(np.random.RandomState(1).randn(4, 3)
                     .astype(np.float32)))
    assert (y.asnumpy() >= 0).all() and (y.asnumpy() > 0).any()

    with mx.engine.bulk(30):
        np.testing.assert_allclose((a + b).asnumpy(), [[11, 22], [33, 44]])


# ---------------------------------------------------------------------------
# round-4 op-surface completions: moments/softmin/crop + symbol mirror
# long-tail (reference: mx.nd.moments src/operator/nn/moments.cc, softmin,
# legacy crop, and the nd-mirror rule "every nd op has a sym mirror")
# ---------------------------------------------------------------------------

def test_moments_matches_numpy():
    x = nd.array(np.random.RandomState(0).randn(3, 4, 5).astype(np.float32))
    m, v = nd.moments(x, axes=(1, 2))
    np.testing.assert_allclose(m.asnumpy(), x.asnumpy().mean((1, 2)),
                               rtol=1e-5)
    np.testing.assert_allclose(v.asnumpy(), x.asnumpy().var((1, 2)),
                               rtol=1e-4, atol=1e-6)
    mk, vk = nd.moments(x, axes=1, keepdims=True)
    assert mk.shape == (3, 1, 5) and vk.shape == (3, 1, 5)


def test_softmin_is_softmax_of_negation():
    x = nd.array(np.random.RandomState(1).randn(2, 6).astype(np.float32))
    np.testing.assert_allclose(nd.softmin(x, axis=1).asnumpy(),
                               nd.softmax(-x, axis=1).asnumpy(), rtol=1e-6)


def test_crop_aliases_slice():
    x = nd.array(np.arange(24, dtype=np.float32).reshape(4, 6))
    np.testing.assert_array_equal(
        nd.crop(x, begin=(1, 2), end=(3, 5)).asnumpy(),
        x.asnumpy()[1:3, 2:5])


def test_symbol_mirror_long_tail():
    import incubator_mxnet_tpu.symbol as S
    x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    d, e = S.Variable("d"), S.Variable("e")
    cases = [
        (S.broadcast_to(d, shape=(3, 4)),
         {"d": nd.array(np.ones((1, 4), np.float32))},
         np.ones((3, 4), np.float32)),
        (S.cumsum(d, axis=1), {"d": x}, np.cumsum(x.asnumpy(), axis=1)),
        (S.maximum(d, e),
         {"d": x, "e": nd.array(np.full((3, 4), 5.0, np.float32))},
         np.maximum(x.asnumpy(), 5.0)),
        (S.mod(d, e),
         {"d": x, "e": nd.array(np.full((3, 4), 3.0, np.float32))},
         np.mod(x.asnumpy(), 3.0)),
        (S.slice_like(d, e),
         {"d": x, "e": nd.array(np.ones((2, 2), np.float32))},
         x.asnumpy()[:2, :2]),
        (S.linspace(start=0.0, stop=1.0, num=5), {},
         np.linspace(0, 1, 5, dtype=np.float32)),
        (S.full(shape=(2, 3), val=7.0), {},
         np.full((2, 3), 7.0, np.float32)),
        (S.softmin(d, axis=1), {"d": x},
         np.exp(-x.asnumpy()) / np.exp(-x.asnumpy()).sum(1, keepdims=True)),
        (S.crop(d, begin=(0, 1), end=(2, 3)), {"d": x},
         x.asnumpy()[0:2, 1:3]),
    ]
    for sym, args, expect in cases:
        out = sym.bind(args=args).forward(is_train=False)[0].asnumpy()
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)
    # moments mirror: two outputs
    ms = S.moments(d, axes=1)
    ex = ms.bind(args={"d": x})
    mo, vo = [o.asnumpy() for o in ex.forward(is_train=False)]
    np.testing.assert_allclose(mo, x.asnumpy().mean(1), rtol=1e-5)
    np.testing.assert_allclose(vo, x.asnumpy().var(1), rtol=1e-5)


# ---------------------------------------------------------------------------
# contrib vision ops (reference src/operator/contrib/: roi_align.cc,
# bilinear_resize.cc, adaptive_avg_pooling.cc)
# ---------------------------------------------------------------------------

def test_bilinear_resize_2d():
    # exact on a linear ramp (bilinear reproduces linear functions)
    h, w = 4, 6
    ramp = (np.arange(h)[:, None] * 2.0
            + np.arange(w)[None, :]).astype(np.float32)
    x = nd.array(ramp[None, None])
    out = nd.contrib.BilinearResize2D(x, height=7, width=11).asnumpy()[0, 0]
    yy = np.linspace(0, h - 1, 7)
    xx = np.linspace(0, w - 1, 11)
    expect = yy[:, None] * 2.0 + xx[None, :]
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)
    # identity when the size is unchanged
    same = nd.contrib.BilinearResize2D(x, height=h, width=w).asnumpy()[0, 0]
    np.testing.assert_allclose(same, ramp, atol=1e-6)


def test_adaptive_avg_pooling_2d():
    rng = np.random.RandomState(0)
    x_np = rng.randn(2, 3, 7, 5).astype(np.float32)
    x = nd.array(x_np)
    out = nd.contrib.AdaptiveAvgPooling2D(x, output_size=(2, 2)).asnumpy()
    assert out.shape == (2, 3, 2, 2)
    # torch-style bins: rows [0,4) and [3,7), cols [0,3) and [2,5)
    for i, (rs, re) in enumerate([(0, 4), (3, 7)]):
        for j, (cs, ce) in enumerate([(0, 3), (2, 5)]):
            np.testing.assert_allclose(
                out[:, :, i, j], x_np[:, :, rs:re, cs:ce].mean((2, 3)),
                rtol=1e-5)
    # output_size=1 == global average pooling
    g = nd.contrib.AdaptiveAvgPooling2D(x, output_size=1).asnumpy()
    np.testing.assert_allclose(g[:, :, 0, 0], x_np.mean((2, 3)), rtol=1e-5)


def test_roi_align_constant_and_ramp():
    # constant image: every pooled cell must be that constant, regardless
    # of sub-pixel sampling
    x = nd.array(np.full((1, 2, 8, 8), 3.5, np.float32))
    rois = nd.array(np.array([[0, 1.0, 1.0, 6.0, 6.0]], np.float32))
    out = nd.contrib.ROIAlign(x, rois, pooled_size=(3, 3),
                              spatial_scale=1.0).asnumpy()
    assert out.shape == (1, 2, 3, 3)
    np.testing.assert_allclose(out, 3.5, rtol=1e-6)
    # ramp image: bilinear sampling reproduces linear functions exactly,
    # so each cell equals the ramp at the cell's center
    ramp = (np.arange(8)[:, None] + 0.0 * np.arange(8)[None, :]
            ).astype(np.float32)
    xr = nd.array(ramp[None, None])
    roi = np.array([[0, 0.0, 2.0, 8.0, 6.0]], np.float32)  # y in [2,6)
    o = nd.contrib.ROIAlign(xr, nd.array(roi), pooled_size=(2, 2),
                            spatial_scale=1.0).asnumpy()[0, 0]
    # bin height 2: centers at y = 2+1 and 2+3 -> values 3 and 5
    np.testing.assert_allclose(o[:, 0], [3.0, 5.0], rtol=1e-5)
    np.testing.assert_allclose(o[:, 1], [3.0, 5.0], rtol=1e-5)


def test_contrib_vision_symbol_mirrors():
    import incubator_mxnet_tpu.symbol as S
    x = nd.array(np.random.RandomState(0).rand(1, 2, 6, 6)
                 .astype(np.float32))
    rois = nd.array(np.array([[0, 0.0, 0.0, 5.0, 5.0]], np.float32))
    d, r = S.Variable("d"), S.Variable("r")
    s1 = S.contrib.BilinearResize2D(d, height=3, width=3)
    np.testing.assert_allclose(
        s1.bind(args={"d": x}).forward()[0].asnumpy(),
        nd.contrib.BilinearResize2D(x, height=3, width=3).asnumpy(),
        rtol=1e-6)
    s2 = S.contrib.AdaptiveAvgPooling2D(d, output_size=2)
    np.testing.assert_allclose(
        s2.bind(args={"d": x}).forward()[0].asnumpy(),
        nd.contrib.AdaptiveAvgPooling2D(x, output_size=2).asnumpy(),
        rtol=1e-6)
    s3 = S.contrib.ROIAlign(d, r, pooled_size=(2, 2))
    np.testing.assert_allclose(
        s3.bind(args={"d": x, "r": rois}).forward()[0].asnumpy(),
        nd.contrib.ROIAlign(x, rois, pooled_size=(2, 2)).asnumpy(),
        rtol=1e-6)


def test_roi_align_border_zeroing():
    # samples more than one pixel outside the image contribute zero
    # (reference roi_align.cc border rule), not edge-replicated values
    x = nd.array(np.full((1, 1, 4, 4), 2.0, np.float32))
    far_out = nd.array(np.array([[0, -20.0, -20.0, -12.0, -12.0]],
                                np.float32))
    o = nd.contrib.ROIAlign(x, far_out, pooled_size=(2, 2)).asnumpy()
    np.testing.assert_allclose(o, 0.0, atol=1e-7)
    # interior ROI on the same constant image stays the constant
    inside = nd.array(np.array([[0, 0.5, 0.5, 3.5, 3.5]], np.float32))
    o2 = nd.contrib.ROIAlign(x, inside, pooled_size=(2, 2)).asnumpy()
    np.testing.assert_allclose(o2, 2.0, rtol=1e-6)


def test_bilinear_resize_requires_sizes():
    x = nd.array(np.ones((1, 1, 4, 4), np.float32))
    with pytest.raises(ValueError, match="height"):
        nd.contrib.BilinearResize2D(x)

def test_bilinear_resize_accepts_numpy_int_sizes():
    # sizes from shape arithmetic are numpy integer scalars, not python
    # ints; the op must accept them (and still reject bool/float/None)
    x = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    h = np.int64(7)
    w = np.ceil(4 * 2.75).astype(np.int32)
    out = nd.contrib.BilinearResize2D(x, height=h, width=w)
    assert out.shape == (1, 1, 7, 11)
    ref = nd.contrib.BilinearResize2D(x, height=7, width=11)
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy())
    for bad_h, bad_w in ((True, 3), (3.5, 3), (-2, 3), (3, 0)):
        with pytest.raises(ValueError):
            nd.contrib.BilinearResize2D(x, height=bad_h, width=bad_w)

def test_vision_ops_integer_dtypes():
    # uint8 images must resize/pool to sensible values, not truncate the
    # fractional interpolation weights to zero
    img = np.arange(64, dtype=np.uint8).reshape(1, 1, 8, 8) * 3
    x = nd.array(img)
    assert x.dtype == np.uint8
    out = nd.contrib.BilinearResize2D(x, height=4, width=4)
    assert out.dtype == np.uint8
    ref = nd.contrib.BilinearResize2D(x.astype("float32"), height=4,
                                      width=4).asnumpy()
    np.testing.assert_allclose(out.asnumpy().astype(np.float32), np.round(ref),
                               atol=1)
    assert out.asnumpy().max() > 0
    pool = nd.contrib.AdaptiveAvgPooling2D(x, output_size=2)
    assert pool.dtype == np.uint8 and pool.asnumpy().max() > 0
    refp = nd.contrib.AdaptiveAvgPooling2D(x.astype("float32"),
                                           output_size=2).asnumpy()
    np.testing.assert_allclose(pool.asnumpy().astype(np.float32),
                               np.round(refp), atol=1)


def test_symbol_bilinear_resize_validates_sizes():
    from incubator_mxnet_tpu import symbol as S
    with pytest.raises(ValueError, match="height"):
        S.contrib.BilinearResize2D(S.Variable("d"))
    with pytest.raises(ValueError, match="height"):
        S.contrib.BilinearResize2D(S.Variable("d"), height=0, width=3)
    # numpy ints fine on the symbol path too
    s = S.contrib.BilinearResize2D(S.Variable("d"), height=np.int64(3),
                                   width=np.int32(3))
    assert s is not None
