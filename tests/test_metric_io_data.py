"""Metrics, io iterators, gluon.data (SURVEY.md §2.15, §2.17)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import io, metric, nd
from incubator_mxnet_tpu.gluon import data as gdata
from incubator_mxnet_tpu.gluon.data import vision


def test_accuracy():
    m = metric.create("acc")
    m.update(nd.array([0, 1, 1]), nd.array([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3]]))
    assert abs(m.get()[1] - 2 / 3) < 1e-6
    m.reset()
    assert np.isnan(m.get()[1])


def test_topk():
    m = metric.create("top_k_accuracy", top_k=2)
    m.update(nd.array([2, 0]), nd.array([[0.3, 0.4, 0.35], [0.1, 0.5, 0.4]]))
    assert abs(m.get()[1] - 0.5) < 1e-6


def test_mae_mse_rmse():
    lab = nd.array([1.0, 2.0])
    pred = nd.array([2.0, 4.0])
    m = metric.create("mae"); m.update(lab, pred)
    assert abs(m.get()[1] - 1.5) < 1e-6
    m = metric.create("mse"); m.update(lab, pred)
    assert abs(m.get()[1] - 2.5) < 1e-6
    m = metric.create("rmse"); m.update(lab, pred)
    assert abs(m.get()[1] - np.sqrt(2.5)) < 1e-6


def test_f1_perplexity_composite():
    f1 = metric.create("f1")
    f1.update(nd.array([1, 0, 1, 1]), nd.array([[0.1, 0.9], [0.8, 0.2],
                                                [0.2, 0.8], [0.9, 0.1]]))
    assert 0 < f1.get()[1] <= 1
    c = metric.create(["acc", "ce"])
    c.update(nd.array([1]), nd.array([[0.2, 0.8]]))
    names, vals = c.get()
    assert len(names) == 2
    p = metric.create("perplexity", ignore_label=None)
    p.update(nd.array([0]), nd.array([[1.0, 0.0]]))
    assert abs(p.get()[1] - 1.0) < 1e-6


def test_pearson():
    m = metric.create("pearsonr")
    m.update(nd.array([1.0, 2.0, 3.0]), nd.array([2.0, 4.0, 6.0]))
    assert abs(m.get()[1] - 1.0) < 1e-6


def test_ndarray_iter_pad_and_shuffle():
    X = np.arange(20, dtype=np.float32).reshape(10, 2)
    it = io.NDArrayIter(X, np.arange(10), batch_size=4, shuffle=False)
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 3
    desc = it.provide_data[0]
    assert desc.shape == (4, 2)


def test_mnist_iter_synthetic():
    it = io.MNISTIter(batch_size=32, num_examples=100)
    b = next(iter(it))
    assert b.data[0].shape == (32, 1, 28, 28)


def test_prefetching_iter():
    X = np.random.randn(16, 2).astype(np.float32)
    base = io.NDArrayIter(X, np.arange(16), batch_size=4)
    pf = io.PrefetchingIter(base)
    assert len(list(pf)) == 4
    pf.reset()
    assert len(list(pf)) == 4


def test_array_dataset_and_loader():
    X = np.random.randn(10, 3).astype(np.float32)
    y = np.arange(10).astype(np.float32)
    ds = gdata.ArrayDataset(X, y)
    assert len(ds) == 10
    xb, yb = ds[3]
    loader = gdata.DataLoader(ds, batch_size=4, shuffle=True, last_batch="discard")
    batches = list(loader)
    assert len(batches) == 2
    assert batches[0][0].shape == (4, 3)


def test_loader_workers_match_serial():
    X = np.arange(24, dtype=np.float32).reshape(12, 2)
    ds = gdata.ArrayDataset(X)
    serial = [b.asnumpy() for b in gdata.DataLoader(ds, batch_size=4)]
    threaded = [b.asnumpy() for b in gdata.DataLoader(ds, batch_size=4, num_workers=3)]
    for a, b in zip(serial, threaded):
        np.testing.assert_array_equal(a, b)


def test_dataset_transform_shard_take():
    ds = gdata.SimpleDataset(list(range(10)))
    t = ds.transform(lambda x: x * 2)
    assert t[3] == 6
    s = ds.shard(3, 1)
    assert list(s[i] for i in range(len(s))) == [1, 4, 7]
    assert len(ds.take(4)) == 4


def test_vision_mnist_and_transforms():
    ds = vision.MNIST(train=False)
    img, label = ds[0]
    assert img.shape == (28, 28, 1)
    t = vision.transforms.Compose([
        vision.transforms.Resize(32),
        vision.transforms.CenterCrop(28),
        vision.transforms.ToTensor(),
        vision.transforms.Normalize(0.5, 0.5),
    ])
    out = t(img)
    assert out.shape == (1, 28, 28)


def test_cifar_synthetic():
    ds = vision.CIFAR10(train=False)
    img, label = ds[0]
    assert img.shape == (32, 32, 3)
    assert 0 <= int(label) < 10


def test_batch_sampler_rollover():
    s = gdata.BatchSampler(gdata.SequentialSampler(5), 2, "rollover")
    first = list(s)
    assert len(first) == 2
    second = list(s)
    assert second[0][0] == 4  # rolled-over sample leads


def test_ndarrayiter_roll_over():
    import numpy as np
    from incubator_mxnet_tpu.io import NDArrayIter
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = NDArrayIter(x, None, batch_size=4, last_batch_handle="roll_over")
    seen1 = [b.data[0].shape[0] for b in it]
    assert seen1 == [4, 4]          # 2 leftover roll to next epoch
    it.reset()
    seen2 = sum(b.data[0].shape[0] for b in it)
    assert seen2 == 12              # 2 rolled + 10 fresh

def test_prefetching_iter_exhaustion_no_hang():
    import numpy as np, pytest
    from incubator_mxnet_tpu.io import NDArrayIter, PrefetchingIter
    x = np.zeros((8, 2), np.float32)
    it = PrefetchingIter(NDArrayIter(x, None, batch_size=4))
    assert len(list(it)) == 2
    with pytest.raises(StopIteration):
        it.next()   # must raise again, not hang
    it.reset()
    assert len(list(it)) == 2


def test_color_transforms():
    from incubator_mxnet_tpu.gluon.data.vision import transforms as T
    import incubator_mxnet_tpu as mx
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(8, 8, 3).astype(np.float32))
    for t in (T.RandomSaturation(0.3), T.RandomHue(0.2),
              T.RandomColorJitter(0.2, 0.2, 0.2, 0.1),
              T.RandomLighting(0.1)):
        y = t(x)
        assert y.shape == x.shape
        assert np.isfinite(y.asnumpy()).all()
    g = T.RandomGray(p=1.0)(x).asnumpy()
    assert np.allclose(g[..., 0], g[..., 1]) and np.allclose(g[..., 1],
                                                             g[..., 2])
    # saturation=identity factor 0 keeps the image
    y0 = T.RandomSaturation(0.0)(x)
    np.testing.assert_allclose(y0.asnumpy(), x.asnumpy(), rtol=1e-6)
