"""Subprocess worker for commscope's CPU-mesh matrix (tests/test_commscope.py).

Trains a small fixed-seed MLP through FusedTrainStep under one layout on
4 FAKE host devices (--xla_force_host_platform_device_count=4 — set
HERE, before jax import) with commscope armed, and prints one JSON line
with the captured collective inventory for the `fused_step` program:
per-kind counts, per-axis attribution, payload bytes, the resharding
verdict, and a real StepBudget settle so the collective component's
provenance is asserted against a REAL mesh (the in-process tests can
only stub one).

Layouts:
    single        no mesh — the no-collectives baseline
    dp4           pure data parallel: all-reduce-only signature
    dp2mp2        2x2 (dp, mp), Dense kernels on mp: model-axis
                  collectives must appear
    fsdp4         zero-style: all-gather + reduce-scatter (XLA:CPU
                  spells the latter all-to-all + local reduce)
    misannotated  dp4 with a Dense weight deliberately pinned onto the
                  dp axis — the "accidental all-gather" fixture that
                  must trip the resharding detector

Usage: python commscope_matrix_worker.py <layout>
"""
import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
# isolate from the suite's persistent compile cache (the PR 4 lesson)
os.environ.setdefault("JAX_ENABLE_COMPILATION_CACHE", "false")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import commscope, gluon, nd, perfscope  # noqa: E402
from incubator_mxnet_tpu.gluon import nn  # noqa: E402
from incubator_mxnet_tpu.parallel import (FusedTrainStep, make_mesh,  # noqa: E402
                                          set_mesh)

STEPS = 4
BATCH = 16


def _net():
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"),
            nn.Dense(16, activation="relu"),
            nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    return net


def _data(seed):
    rng = np.random.RandomState(seed)
    return (nd.array(rng.randn(BATCH, 8).astype(np.float32)),
            nd.array(rng.randint(0, 4, BATCH)))


def main():
    layout = sys.argv[1]
    commscope.enable()           # arms perfscope too (capture hooks)
    mode = None
    net = _net()
    if layout == "single":
        pass
    elif layout == "dp4":
        set_mesh(make_mesh({"dp": 4}))
        mode = "dp"
    elif layout == "dp2mp2":
        set_mesh(make_mesh({"dp": 2, "mp": 2}))
        mode = "auto"
    elif layout == "fsdp4":
        set_mesh(make_mesh({"dp": -1}))
        mode = "fsdp"
    elif layout == "misannotated":
        set_mesh(make_mesh({"dp": 4}))
        mode = "dp"
        # the deliberate mistake: a Dense kernel pinned onto the DATA
        # axis in a data-parallel program — the computation needs it
        # replicated, so GSPMD inserts the accidental all-gather
        net[0].shard(weight=P("dp", None))
    else:
        raise SystemExit(f"unknown layout {layout!r}")

    import warnings
    step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mx.optimizer.create("sgd", learning_rate=0.1),
                          sharding=mode)
    budget = perfscope.StepBudget().begin()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        t0 = time.perf_counter()
        losses = []
        for i in range(STEPS):
            x, y = _data(100 + i)
            losses.append(float(step(x, y)))
        dt = time.perf_counter() - t0
    budget.end(steps=STEPS, steady_s=dt)
    decomp = budget.finish()

    progs = {p["name"]: p for p in commscope.programs()}
    rec = progs.get("fused_step") or {}
    kinds = {}
    axes = set()
    for c in rec.get("collectives") or []:
        kinds[c["kind"]] = kinds.get(c["kind"], 0) + c["count"]
        if c.get("axis"):
            axes.add(c["axis"])
    from incubator_mxnet_tpu import profiler as prof
    counters = {k: v for k, v in prof.counters().items()
                if k.startswith("commscope/")}
    print(json.dumps({
        "layout": layout,
        "devices": len(jax.devices()),
        "losses": losses,
        "program": {k: rec.get(k) for k in
                    ("name", "mode", "mesh", "totals",
                     "resharding_collectives", "resharding",
                     "hlo_available", "collectives")},
        "kinds": kinds,
        "axes": sorted(axes),
        "step_estimate": commscope.step_estimate(),
        "collective_source": decomp.get("collective_source"),
        "collective_ms": decomp.get("collective_ms"),
        "counters": counters,
        "resharding_warned": any("commscope" in str(w.message)
                                 for w in caught),
    }), flush=True)


if __name__ == "__main__":
    main()
