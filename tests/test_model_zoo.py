"""Vision model zoo shape/param-count tests (mirrors reference
tests/python/unittest/test_gluon_model_zoo.py).

Fast suite: small inputs (32-64 px) + the cheap family representatives —
enough to exercise every constructor path that matters per family.
Full-size forwards are marked `slow` (--run-slow / RUN_SLOW=1)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.models import get_model


def _params(net):
    return sum(int(np.prod(p.shape)) for p in net.collect_params().values()
               if p.grad_req != "null")


@pytest.mark.parametrize("name,size,classes", [
    ("mobilenet0_25", 32, 10),
    ("mobilenet_v2_0_5", 32, 10),
    ("squeezenet1_1", 32, 10),
    ("vgg11", 32, 10),
])
def test_zoo_forward_shapes(name, size, classes):
    mx.random.seed(0)
    net = get_model(name, classes=classes)
    net.initialize()
    out = net(nd.ones((2, size, size, 3)))
    assert out.shape == (2, classes)
    assert np.isfinite(out.asnumpy()).all()


@pytest.mark.slow
@pytest.mark.parametrize("name,size,classes", [
    ("alexnet", 224, 10),
    ("vgg13_bn", 64, 10),
    ("mobilenet1_0", 64, 10),
    ("mobilenet_v2_1_0", 64, 10),
    ("squeezenet1_0", 64, 10),
    ("densenet121", 64, 10),
])
def test_zoo_forward_shapes_full(name, size, classes):
    mx.random.seed(0)
    net = get_model(name, classes=classes)
    net.initialize()
    out = net(nd.ones((2, size, size, 3)))
    assert out.shape == (2, classes)
    assert np.isfinite(out.asnumpy()).all()


@pytest.mark.slow
def test_inception_v3_forward():
    net = get_model("inception_v3", classes=10)
    net.initialize()
    out = net(nd.ones((1, 299, 299, 3)))
    assert out.shape == (1, 10)


def test_mobilenet_v2_param_count():
    net = get_model("mobilenet_v2_1_0", classes=1000)
    net.initialize()
    net(nd.ones((1, 32, 32, 3)))   # global pool → count is size-independent
    n = _params(net)
    assert 3.3e6 < n < 3.7e6, n    # reference ~3.5M

def test_vgg16_param_count():
    net = get_model("vgg16", classes=1000)
    net.initialize()
    net(nd.ones((1, 32, 32, 3)))
    # conv params exact; dense depends on input size — check conv total
    conv = sum(int(np.prod(p.shape))
               for k, p in net.collect_params().items()
               if "conv" in k and p.grad_req != "null")
    assert 14.7e6 < conv < 14.8e6, conv  # VGG16 convs = 14.71M


def test_densenet121_param_count():
    net = get_model("densenet121", classes=1000)
    net.initialize()
    net(nd.ones((1, 32, 32, 3)))   # global pool → count is size-independent
    n = _params(net)
    assert 7.7e6 < n < 8.3e6, n    # reference ~7.98M


def test_zoo_hybridize_parity():
    mx.random.seed(0)
    net = get_model("mobilenet_v2_0_25", classes=5)
    net.initialize()
    x = nd.array(np.random.RandomState(0).randn(2, 32, 32, 3)
                 .astype(np.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    jitted = net(x).asnumpy()
    np.testing.assert_allclose(eager, jitted, rtol=1e-4, atol=1e-4)


def test_zoo_registry_complete():
    """Every reference family is registered (constructor-level check, no
    forward — keeps the fast suite honest about breadth)."""
    from incubator_mxnet_tpu.models import _MODELS
    expected = [
        "lenet", "alexnet",
        "vgg11", "vgg13", "vgg16", "vgg19",
        "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn",
        "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
        "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
        "resnet101_v2", "resnet152_v2",
        "mobilenet1_0", "mobilenet0_75", "mobilenet0_5", "mobilenet0_25",
        "mobilenet_v2_1_0", "mobilenet_v2_0_75", "mobilenet_v2_0_5",
        "mobilenet_v2_0_25",
        "squeezenet1_0", "squeezenet1_1",
        "densenet121", "densenet161", "densenet169", "densenet201",
        "inception_v3",
    ]
    missing = [n for n in expected if n not in _MODELS]
    assert not missing, f"unregistered models: {missing}"


class TestSpaceToDepthStem:
    def test_exact_parity_with_conv_stem(self):
        """S2D stem == 7x7/s2 conv stem bit-for-bit (fwd and weight
        grad) from the SAME (7,7,3,O) parameter."""
        from incubator_mxnet_tpu.models.resnet import SpaceToDepthStem
        rng = np.random.RandomState(0)
        x = nd.array(rng.randn(2, 32, 32, 3).astype(np.float32))
        w = rng.randn(7, 7, 3, 16).astype(np.float32) * 0.1
        cot = nd.array(rng.randn(2, 16, 16, 16).astype(np.float32))

        conv = gluon.nn.Conv2D(16, 7, strides=2, padding=3, use_bias=False,
                               layout="NHWC", in_channels=3)
        conv.initialize(); conv(x); conv.weight.set_data(nd.array(w))
        s2d = SpaceToDepthStem(16)
        s2d.initialize(); s2d(x); s2d.weight.set_data(nd.array(w))

        np.testing.assert_allclose(s2d(x).asnumpy(), conv(x).asnumpy(),
                                   rtol=1e-5, atol=1e-5)
        grads = []
        for blk in (conv, s2d):
            with mx.autograd.record():
                loss = (blk(x) * cot).sum()
            loss.backward()
            grads.append(blk.weight.grad().asnumpy())
        np.testing.assert_allclose(grads[1], grads[0], rtol=1e-4,
                                   atol=1e-4)

    def test_checkpoint_interchange_with_standard_stem(self, tmp_path):
        """A standard-stem checkpoint loads into a stem_s2d model (same
        parameter structure) and predicts identically."""
        from incubator_mxnet_tpu.models import get_model
        mx.random.seed(0)
        rng = np.random.RandomState(1)
        x = nd.array(rng.rand(2, 32, 32, 3).astype(np.float32))
        net = get_model("resnet18_v1", classes=10)
        net.initialize(init=mx.init.Xavier())
        ref = net(x).asnumpy()
        p = str(tmp_path / "std.params")
        net.save_parameters(p)

        net2 = get_model("resnet18_v1", classes=10, stem_s2d=True)
        net2.initialize()
        net2(x * 0)                            # shape-complete then load
        net2.load_parameters(p)
        np.testing.assert_allclose(net2(x).asnumpy(), ref, rtol=2e-5,
                                   atol=2e-5)
